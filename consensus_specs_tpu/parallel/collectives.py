"""Mesh collectives for curve-group values (SURVEY §2.3 "G1/G2 reduction
collectives" row).

G1 point addition is a group law, not a ring sum, so GSPMD's automatic
`psum` insertion cannot reduce it; the collective is spelled out with
shard_map: each device tree-reduces its local shard of points (all VPU
work, no communication), ONE `all_gather` moves the n_devices partial sums
over ICI (~100 bytes/device — the only wire traffic regardless of input
size), and every device finishes the log2(n_devices) tail reduce
replicated. This is the scale-out path for registry-wide pubkey
aggregation (sync-committee aggregate keys, deposit-sweep key checks):
single-chip `ops/bls12_jax.g1_sum_reduce` handles one device's worth, this
composes it across the mesh.
"""
from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import bls12_jax as K
from .mesh import DATA_AXIS


from functools import lru_cache


def _shard_map(f, *, mesh, in_specs, out_specs):
    """Compat shim: jax >= 0.6 exposes `jax.shard_map` with the `check_vma`
    flag; older builds (<= 0.4.x) ship `jax.experimental.shard_map` where
    the same replication checker is called `check_rep`. Both are disabled —
    every per-device tail here recomputes an identical replicated reduce
    from gathered partials, which the checker can't prove."""
    try:
        from jax import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


@lru_cache(maxsize=8)
def _mesh_reduce_fn(mesh):
    """One compiled reducer per mesh (jit then caches per input shape);
    rebuilding the shard_map closure per call would recompile every time."""

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )
    def reduce_shards(X, Y, Z):
        px, py, pz = K.g1_sum_reduce((X, Y, Z))
        gx = jax.lax.all_gather(px[None], DATA_AXIS, axis=0, tiled=True)
        gy = jax.lax.all_gather(py[None], DATA_AXIS, axis=0, tiled=True)
        gz = jax.lax.all_gather(pz[None], DATA_AXIS, axis=0, tiled=True)
        return K.g1_sum_reduce((gx, gy, gz))

    return jax.jit(reduce_shards)


def g1_mesh_sum(pts, mesh):
    """Sum a mesh-sharded batch of Jacobian G1 points.

    `pts`: (X, Y, Z) arrays of shape (N, limbs), N divisible by the mesh
    size; sharded (or shardable) on the leading axis. Returns the single
    Jacobian sum, replicated on every device."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    pts = tuple(jax.device_put(a, split) for a in pts)
    return _mesh_reduce_fn(mesh)(*pts)


def g1_small_multiples(n: int):
    """(X, Y, Z) Jacobian Montgomery arrays of [1]G .. [n]G plus their
    affine int pairs — the shared fixture for collective checks (the
    dryrun and tests/test_mesh_collectives.py must agree on encoding)."""
    import jax.numpy as jnp

    from ..crypto import bls12_381 as oracle

    enc = K.F.ints_to_mont_batch
    affs, acc = [], oracle.G1_GEN
    for _ in range(n):
        affs.append(oracle.pt_to_affine(oracle.FP_FIELD, acc))
        acc = oracle.pt_add(oracle.FP_FIELD, acc, oracle.G1_GEN)
    X = jnp.asarray(enc([a[0] for a in affs]))
    Y = jnp.asarray(enc([a[1] for a in affs]))
    Z = jnp.broadcast_to(jnp.asarray(K.F.ONE_MONT), X.shape)
    return (X, Y, Z), affs


@lru_cache(maxsize=8)
def _mesh_rlc_fn(mesh, p2_is_neg_g1: bool):
    """Mesh-sharded `pairing_check_rlc`: the flagship kernel's scale-out.

    Signature sets are sharded on the data axis; every device runs the
    z-scalar ladders and its shard's Miller loops, tree-folding local Fp12
    values (pure compute, no wire traffic). With `p2_is_neg_g1` the second
    pairing set collapses by bilinearity exactly as in the single-device
    kernel (ops/bls12_jax.py): each shard ladders and locally sums
    [z_i]·sig_i on G2, the per-device partial POINTS (~600 B each) ride
    the same all_gather round as the Fp12 partials, and the one extra
    Miller loop for e(−G1, Σ z_i·sig_i) runs replicated. Communication
    volume stays independent of batch size; the final exponentiation is
    paid once, not per shard.
    """
    import jax.numpy as jnp

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=tuple([P(DATA_AXIS)] * 9),
        out_specs=P(),
    )
    def rlc_shards(qx, qy, px, py, q2x, q2y, p2x, p2y, zbits):
        a1x, a1y = K.rlc_randomize_g1(px, py, zbits)
        m1 = K.miller_loop_batch(qx, qy, a1x, a1y)
        if p2_is_neg_g1:
            one = jnp.broadcast_to(
                jnp.asarray(K.F.ONE_MONT), q2x[0].shape).astype(q2x[0].dtype)
            one2 = (one, jnp.zeros_like(one))
            zsig = K.g2_scalar_mul_batch((q2x, q2y, one2), zbits)
            local_pt = K.g2_sum_reduce(zsig)  # shard's Σ [z_i]·sig_i

            def gather_f2(c):
                return (
                    jax.lax.all_gather(c[0][None], DATA_AXIS, axis=0, tiled=True),
                    jax.lax.all_gather(c[1][None], DATA_AXIS, axis=0, tiled=True),
                )

            total_pt = K.g2_sum_reduce(tuple(gather_f2(c) for c in local_pt))
            aqx, aqy = K.g2_jacobian_to_affine(total_pt)
            ngx, ngy = K._neg_g1_affine_mont()
            m2_single = K.miller_loop_batch(aqx, aqy, ngx, ngy)
            local = K.f12_prod_reduce(m1)  # leading dim 1
            gathered = jax.tree.map(
                lambda c: jax.lax.all_gather(c, DATA_AXIS, axis=0, tiled=True), local)
            return K.rlc_tail(gathered, m2_single)
        one = jnp.broadcast_to(jnp.asarray(K.F.ONE_MONT), px.shape).astype(px.dtype)
        z2 = K.g1_scalar_mul_batch((p2x, p2y, one), zbits)
        a2x, a2y = K._g1_jacobian_to_affine_batch(z2)
        m2 = K.miller_loop_batch(q2x, q2y, a2x, a2y)
        local = K.f12_prod_reduce(K.f12_mul(m1, m2))  # leading dim 1
        gathered = jax.tree.map(
            lambda c: jax.lax.all_gather(c, DATA_AXIS, axis=0, tiled=True), local)
        prod = K.f12_prod_reduce(gathered)
        single = tuple((c[0][0], c[1][0]) for c in prod)
        return K.f12_is_one(K.final_exponentiation_batch(single))

    return jax.jit(rlc_shards)


def pairing_check_rlc_mesh(mesh, qx, qy, px, py, q2x, q2y, p2x, p2y, zbits,
                           p2_is_neg_g1: bool = False):
    """Randomized batch signature check sharded across `mesh`.

    Same contract as `ops.bls12_jax.pairing_check_rlc` (scalar bool,
    2^-64 soundness, caller supplies nonzero zbits); batch size must be
    divisible by the mesh's device count. Bit-equal to the single-device
    kernel: tests/test_mesh_collectives.py asserts agreement, and the
    driver's `dryrun_multichip` runs it over the hierarchical layout."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    args = tuple(
        jax.device_put(a, split)
        for a in (qx, qy, px, py, q2x, q2y, p2x, p2y, zbits)
    )
    return _mesh_rlc_fn(mesh, p2_is_neg_g1)(*args)


@lru_cache(maxsize=8)
def _mesh_rlc_grouped_fn(mesh):
    """Mesh-sharded SEGMENTED `pairing_check_rlc`: the distinct-message
    collapse scaled across chips. Two axes ride the same mesh axis:

    - ITEMS (N): each device runs the [z_i]·pk_i and [z_i]·sig_i 64-bit
      ladders for its shard, then ONE all_gather moves the N randomized
      Jacobian G1 points (~600 B/item) so every device can segment-sum any
      group — membership is arbitrary, a group's items may live anywhere.
    - GROUPS (D): the D distinct-message Miller loops partition across
      devices; device k segment-sums and Miller-loops groups
      [k·D/n_dev, (k+1)·D/n_dev) only. This is where the wall-clock lives
      (the Fp12 squaring chain), so throughput scales with chip count.

    The tail is one psum-style Fp12 PRODUCT collective (all_gather of
    per-device Fp12 partials + replicated tree product — a group law, so
    GSPMD's additive psum cannot express it, same stance as g1_mesh_sum),
    the sig-side partial G2 points ride the gather round, and the single
    final exponentiation runs replicated. Exact equality with the
    single-device kernel: all reductions are modular group/field ops, so
    association order cannot change the value."""
    import jax.numpy as jnp

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=tuple([P(DATA_AXIS)] * 7) + (P(),),
        out_specs=P(),
    )
    def grouped_shards(qx, qy, px, py, q2x, q2y, zbits, seg_ids):
        d_local = qx[0].shape[0]  # D / n_devices distinct messages per device
        base = jax.lax.axis_index(DATA_AXIS) * d_local
        one = jnp.broadcast_to(jnp.asarray(K.F.ONE_MONT), px.shape).astype(px.dtype)
        z1_local = K.g1_scalar_mul_batch((px, py, one), zbits)
        z1 = tuple(
            jax.lax.all_gather(c, DATA_AXIS, axis=0, tiled=True) for c in z1_local)
        segsum = K.g1_segment_sum(z1, seg_ids, d_local, first_segment=base)
        a1x, a1y = K._g1_jacobian_to_affine_batch(segsum)
        m1_local = K.miller_loop_batch(qx, qy, a1x, a1y)

        # sig-side bilinearity collapse, sharded: local ladders + local sum,
        # per-device partial G2 points gathered and folded replicated
        oneq = jnp.broadcast_to(
            jnp.asarray(K.F.ONE_MONT), q2x[0].shape).astype(q2x[0].dtype)
        one2 = (oneq, jnp.zeros_like(oneq))
        zsig = K.g2_scalar_mul_batch((q2x, q2y, one2), zbits)
        local_pt = K.g2_sum_reduce(zsig)

        def gather_f2(c):
            return (
                jax.lax.all_gather(c[0][None], DATA_AXIS, axis=0, tiled=True),
                jax.lax.all_gather(c[1][None], DATA_AXIS, axis=0, tiled=True),
            )

        total_pt = K.g2_sum_reduce(tuple(gather_f2(c) for c in local_pt))
        aqx, aqy = K.g2_jacobian_to_affine(total_pt)
        ngx, ngy = K._neg_g1_affine_mont()
        m2_single = K.miller_loop_batch(aqx, aqy, ngx, ngy)

        local = K.f12_prod_reduce(m1_local)  # leading dim 1
        gathered = jax.tree.map(
            lambda c: jax.lax.all_gather(c, DATA_AXIS, axis=0, tiled=True), local)
        return K.rlc_tail(gathered, m2_single)

    return jax.jit(grouped_shards)


def pairing_check_rlc_grouped_mesh(mesh, qx, qy, px, py, q2x, q2y, zbits,
                                   seg_ids):
    """Segmented randomized batch check sharded across `mesh`.

    Same contract as the single-device grouped fast path
    (`ops.bls12_jax.pairing_check_rlc(..., seg_ids=...)`): qx/qy carry the
    D distinct H(m) points, seg_ids (N,) maps items to groups, every group
    must be non-empty, and both N and D must divide by the mesh's device
    count. seg_ids stays replicated (it is the only global index table);
    item arrays shard on N, message arrays on D."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    args = tuple(
        jax.device_put(a, split) for a in (qx, qy, px, py, q2x, q2y, zbits))
    seg = jax.device_put(seg_ids, repl)
    return _mesh_rlc_grouped_fn(mesh)(*args, seg)
