"""Mesh collectives for curve-group values (SURVEY §2.3 "G1/G2 reduction
collectives" row).

G1 point addition is a group law, not a ring sum, so GSPMD's automatic
`psum` insertion cannot reduce it; the collective is spelled out with
shard_map: each device tree-reduces its local shard of points (all VPU
work, no communication), ONE `all_gather` moves the n_devices partial sums
over ICI (~100 bytes/device — the only wire traffic regardless of input
size), and every device finishes the log2(n_devices) tail reduce
replicated. This is the scale-out path for registry-wide pubkey
aggregation (sync-committee aggregate keys, deposit-sweep key checks):
single-chip `ops/bls12_jax.g1_sum_reduce` handles one device's worth, this
composes it across the mesh.
"""
from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import bls12_jax as K
from .mesh import DATA_AXIS


from functools import lru_cache


@lru_cache(maxsize=8)
def _mesh_reduce_fn(mesh):
    """One compiled reducer per mesh (jit then caches per input shape);
    rebuilding the shard_map closure per call would recompile every time."""
    from jax import shard_map

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
        # every device computes the identical tail reduce from the gathered
        # partials; the varying-manual-axes checker can't prove that
        check_vma=False,
    )
    def reduce_shards(X, Y, Z):
        px, py, pz = K.g1_sum_reduce((X, Y, Z))
        gx = jax.lax.all_gather(px[None], DATA_AXIS, axis=0, tiled=True)
        gy = jax.lax.all_gather(py[None], DATA_AXIS, axis=0, tiled=True)
        gz = jax.lax.all_gather(pz[None], DATA_AXIS, axis=0, tiled=True)
        return K.g1_sum_reduce((gx, gy, gz))

    return jax.jit(reduce_shards)


def g1_mesh_sum(pts, mesh):
    """Sum a mesh-sharded batch of Jacobian G1 points.

    `pts`: (X, Y, Z) arrays of shape (N, limbs), N divisible by the mesh
    size; sharded (or shardable) on the leading axis. Returns the single
    Jacobian sum, replicated on every device."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    pts = tuple(jax.device_put(a, split) for a in pts)
    return _mesh_reduce_fn(mesh)(*pts)


def g1_small_multiples(n: int):
    """(X, Y, Z) Jacobian Montgomery arrays of [1]G .. [n]G plus their
    affine int pairs — the shared fixture for collective checks (the
    dryrun and tests/test_mesh_collectives.py must agree on encoding)."""
    import jax.numpy as jnp

    from ..crypto import bls12_381 as oracle

    enc = K.F.ints_to_mont_batch
    affs, acc = [], oracle.G1_GEN
    for _ in range(n):
        affs.append(oracle.pt_to_affine(oracle.FP_FIELD, acc))
        acc = oracle.pt_add(oracle.FP_FIELD, acc, oracle.G1_GEN)
    X = jnp.asarray(enc([a[0] for a in affs]))
    Y = jnp.asarray(enc([a[1] for a in affs]))
    Z = jnp.broadcast_to(jnp.asarray(K.F.ONE_MONT), X.shape)
    return (X, Y, Z), affs
