"""Multi-host "gossip" load driver — the DCN side of the distributed story.

The reference specifies its network layer as prose and never executes it
(SURVEY.md §5: "distributed communication backend: none implemented"). This
framework keeps the vectors-as-test-bus stance for conformance but ships the
piece the reference leaves to clients: a host-side driver that plays the
gossip layer's role for multi-host load runs. Each node is a separate OS
process (one per host/slice in a real deployment) that:

  1. produces its share of signed attestation messages for the slot,
  2. floods them to every peer over TCP (localhost stands in for DCN),
     framed exactly like the wire contract in specs/phase0/p2p-interface.md:
     snappy BLOCK compression and the 20-byte
     SHA256(MESSAGE_DOMAIN_VALID_SNAPPY ‖ ssz) message-id for dedup,
  3. collects the slot's messages from peers, deduplicates by message-id,
  4. verifies the whole collected batch in ONE deferred-BLS flush
     (crypto/bls.deferred_verification — the same bulk path
     state_transition uses, which on device is one pairing_check_batch).

The intra-host/ICI half of the distributed design lives in parallel/mesh.py
(sharded epoch engine + GSPMD collectives); this driver is the inter-host
half. Convergence invariant checked by the tests: after each slot barrier,
every node holds the identical message set.
"""
from __future__ import annotations

import hashlib
import socket
import struct
import threading
from dataclasses import dataclass, field

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
_LEN = struct.Struct("<I")


def message_id(ssz_bytes: bytes) -> bytes:
    """20-byte gossip message-id (p2p-interface.md gossip domain)."""
    return hashlib.sha256(MESSAGE_DOMAIN_VALID_SNAPPY + ssz_bytes).digest()[:20]


def encode_message(ssz_bytes: bytes) -> bytes:
    from ..native.snappy import compress

    return compress(ssz_bytes)


def decode_message(wire: bytes) -> bytes:
    from ..native.snappy import decompress

    return decompress(wire)


# --- framing over a stream socket -------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes | None:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# --- node -------------------------------------------------------------------


@dataclass
class NodeStats:
    produced: int = 0
    received: int = 0
    duplicates: int = 0
    verified_batches: int = 0
    message_ids: set = field(default_factory=set)


class GossipNode:
    """One gossip participant: a listener plus dial-out links to peers."""

    def __init__(self, node_id: int, listen_port: int, peer_ports: list[int]):
        self.node_id = node_id
        self.listen_port = listen_port
        self.peer_ports = peer_ports
        self.stats = NodeStats()
        self.inbox: list[bytes] = []  # decompressed ssz payloads
        self._lock = threading.Lock()
        self._server = socket.create_server(("127.0.0.1", listen_port))
        self._server.settimeout(10.0)
        self._accepted: list[socket.socket] = []
        self._links: list[socket.socket] = []
        self._rx_threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- wiring ---------------------------------------------------------------

    def accept_peers(self, count: int) -> None:
        for _ in range(count):
            conn, _ = self._server.accept()
            self._accepted.append(conn)
            t = threading.Thread(target=self._rx_loop, args=(conn,), daemon=True)
            t.start()
            self._rx_threads.append(t)

    def dial_peers(self) -> None:
        for port in self.peer_ports:
            s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
            self._links.append(s)

    def _rx_loop(self, conn: socket.socket) -> None:
        conn.settimeout(30.0)
        while not self._stop.is_set():
            try:
                wire = recv_frame(conn)
            except (TimeoutError, OSError):
                break
            if wire is None:
                break
            ssz = decode_message(wire)
            mid = message_id(ssz)
            with self._lock:
                if mid in self.stats.message_ids:
                    self.stats.duplicates += 1
                    continue
                self.stats.message_ids.add(mid)
                self.stats.received += 1
                self.inbox.append(ssz)

    # -- slot actions ---------------------------------------------------------

    def publish(self, ssz_payloads: list[bytes]) -> None:
        """Flood locally produced messages to every peer."""
        with self._lock:
            for ssz in ssz_payloads:
                mid = message_id(ssz)
                if mid not in self.stats.message_ids:
                    self.stats.message_ids.add(mid)
                    self.inbox.append(ssz)
                    self.stats.produced += 1
        for ssz in ssz_payloads:
            wire = encode_message(ssz)
            for link in self._links:
                send_frame(link, wire)

    def drain_and_verify(self, verify_fn) -> int:
        """Verify everything collected so far in one deferred-BLS flush."""
        from ..crypto import bls

        with self._lock:
            batch = list(self.inbox)
            self.inbox.clear()
        if batch:
            with bls.deferred_verification():
                for ssz in batch:
                    verify_fn(ssz)
            self.stats.verified_batches += 1
        return len(batch)

    def close(self) -> None:
        self._stop.set()
        for s in self._links + self._accepted:
            try:
                s.close()
            except OSError:
                pass
        self._server.close()


# --- full-mesh topology helper ----------------------------------------------


def connect_full_mesh(nodes: list[GossipNode]) -> None:
    """Dial every node to every other; each accepts n-1 inbound links."""
    n = len(nodes)
    acceptors = [
        threading.Thread(target=node.accept_peers, args=(n - 1,)) for node in nodes
    ]
    for t in acceptors:
        t.start()
    for node in nodes:
        node.dial_peers()
    for t in acceptors:
        t.join(timeout=15.0)
