"""Multi-host "gossip" load driver — the DCN side of the distributed story.

The reference specifies its network layer as prose and never executes it
(SURVEY.md §5: "distributed communication backend: none implemented"). This
framework keeps the vectors-as-test-bus stance for conformance but ships the
piece the reference leaves to clients: a host-side driver that plays the
gossip layer's role for multi-host load runs. Each node owns a TCP listener
socket and may run as a thread (how the in-repo tests drive it, all nodes in
one process) or as its own OS process via `run_node_process`/`spawn_cluster`
(one per host/slice in a real deployment; exercised by the
`test_gossip_driver` process-cluster test). A node:

  1. produces its share of signed attestation messages for the slot,
  2. floods them to every peer over TCP (localhost stands in for DCN),
     framed exactly like the wire contract in specs/phase0/p2p-interface.md:
     snappy BLOCK compression and the 20-byte
     SHA256(MESSAGE_DOMAIN_VALID_SNAPPY ‖ ssz) message-id for dedup,
  3. collects the slot's messages from peers, deduplicates by message-id,
  4. verifies the whole collected batch in ONE deferred-BLS flush
     (crypto/bls.deferred_verification — the same bulk path
     state_transition uses, which on device is one pairing_check_batch).

The intra-host/ICI half of the distributed design lives in parallel/mesh.py
(sharded epoch engine + GSPMD collectives); this driver is the inter-host
half. Convergence invariant checked by the tests: after each slot barrier,
every node holds the identical message set.
"""
from __future__ import annotations

import hashlib
import socket
import struct
import threading
from dataclasses import dataclass, field

from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from ..robustness import faults as rfaults

MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"
MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
_LEN = struct.Struct("<I")
# GOSSIP_MAX_SIZE (specs/phase0/p2p-interface.md): the largest uncompressed
# payload a gossip message may declare — passed to snappy.decompress so a
# crafted preamble is rejected at the protocol bound, not the 1 GiB backstop.
MAX_MESSAGE_SIZE = 1 << 20
# Wire-frame bound: a frame carries one snappy-compressed message, and snappy
# BLOCK compression expands incompressible input by at most ~1/6 + constant,
# so any frame larger than this cannot decompress to <= MAX_MESSAGE_SIZE. A
# bigger declared length is a framing attack or a desynced stream — without
# the bound, one crafted 4-byte header makes _recv_exact buffer (up to) 4 GiB
# from a hostile peer before decode even runs.
MAX_WIRE_FRAME = MAX_MESSAGE_SIZE + MAX_MESSAGE_SIZE // 6 + 64
# rx socket timeout: a peer that stops sending mid-frame cannot pin the rx
# thread (and whatever waits on its stats) forever.
RECV_TIMEOUT = 30.0


class FrameError(ValueError):
    """Framing-level violation (oversized declared length). Once the length
    prefix cannot be trusted there is no way to find the next frame boundary
    — the connection must be dropped, not resynced."""


def message_id(ssz_bytes: bytes) -> bytes:
    """20-byte phase0 gossip message-id (specs/phase0/p2p-interface.md):
    domain ‖ decompressed data, no topic binding."""
    return hashlib.sha256(MESSAGE_DOMAIN_VALID_SNAPPY + ssz_bytes).digest()[:20]


def message_id_v2(topic: bytes, data: bytes) -> bytes:
    """Topic-aware altair message-id (specs/altair/p2p-interface.md):
    the topic (length-prefixed, little-endian uint64) is mixed into the
    hash, so identical payloads on two topics get distinct ids — the
    cross-topic seen-cache poisoning phase0's derivation admits is closed.
    `data` is the raw wire payload; the VALID domain + decompressed bytes
    are hashed when it is valid snappy, the INVALID domain + raw bytes
    otherwise."""
    from ..native.snappy import decompress

    prefix = len(topic).to_bytes(8, "little") + topic
    try:
        payload = decompress(data, max_len=MAX_MESSAGE_SIZE)
        domain = MESSAGE_DOMAIN_VALID_SNAPPY
    except (ValueError, IndexError):
        # The wire-format failures snappy.decompress raises (ValueError from
        # the native path, IndexError from the pure-Python fallback on
        # truncated input); anything else (MemoryError, a broken native
        # import) must propagate — it is an environment fault, not an
        # invalid message.
        payload = data
        domain = MESSAGE_DOMAIN_INVALID_SNAPPY
    return hashlib.sha256(domain + prefix + payload).digest()[:20]


def encode_message(ssz_bytes: bytes) -> bytes:
    from ..native.snappy import compress

    return compress(ssz_bytes)


def decode_message(wire: bytes) -> bytes:
    from ..native.snappy import decompress

    return decompress(wire, max_len=MAX_MESSAGE_SIZE)


# --- framing over a stream socket -------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket,
               max_frame: int = MAX_WIRE_FRAME) -> bytes | None:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    if n > max_frame:
        raise FrameError(
            f"declared frame length {n} exceeds the {max_frame}-byte wire "
            "bound")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# --- node -------------------------------------------------------------------


@dataclass
class NodeStats:
    """Per-node gossip accounting. Every increment goes through `count`,
    which mirrors the tick into the process-wide metrics registry
    (`gossip_<field>_total{node=...}`) — the registry snapshot is the
    cross-node view, this dataclass stays the cheap per-node one."""

    node_id: int = -1
    produced: int = 0
    received: int = 0
    duplicates: int = 0
    verified_batches: int = 0
    partial_drains: int = 0  # drain_ready() calls that returned messages
    malformed: int = 0  # frames/messages quarantined instead of delivered
    message_ids: set = field(default_factory=set)
    # (reason, payload head) of recent malformed frames — enough to
    # attribute a misbehaving peer in a postmortem, bounded memory.
    quarantined: list = field(default_factory=list)

    def count(self, stat: str, n: int = 1) -> None:
        setattr(self, stat, getattr(self, stat) + n)
        _obs_metrics.REGISTRY.counter(
            f"gossip_{stat}_total", node=self.node_id).inc(n)


class GossipNode:
    """One gossip participant: a listener plus dial-out links to peers."""

    def __init__(self, node_id: int, listen_port: int, peer_ports: list[int]):
        self.node_id = node_id
        self.listen_port = listen_port
        self.peer_ports = peer_ports
        self.stats = NodeStats(node_id=node_id)
        self.inbox: list[bytes] = []  # decompressed ssz payloads
        self._lock = threading.Lock()
        self._server = socket.create_server(("127.0.0.1", listen_port))
        self._server.settimeout(10.0)
        self._accepted: list[socket.socket] = []
        self._links: list[socket.socket] = []
        self._rx_threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # -- wiring ---------------------------------------------------------------

    def accept_peers(self, count: int) -> None:
        for _ in range(count):
            conn, _ = self._server.accept()
            self._accepted.append(conn)
            t = threading.Thread(target=self._rx_loop, args=(conn,), daemon=True)
            t.start()
            self._rx_threads.append(t)

    def dial_peers(self) -> None:
        for port in self.peer_ports:
            s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
            self._links.append(s)

    def _quarantine(self, reason: str, wire: bytes) -> None:
        """Count + quarantine a malformed frame instead of letting it raise
        out of the rx loop (one bad peer must not kill message collection
        for every well-behaved one)."""
        with self._lock:
            self.stats.count("malformed")
            self.stats.quarantined.append((reason, bytes(wire[:64])))
            del self.stats.quarantined[:-32]  # keep the most recent 32

    def _rx_loop(self, conn: socket.socket) -> None:
        conn.settimeout(RECV_TIMEOUT)
        while not self._stop.is_set():
            try:
                wire = recv_frame(conn)
            except FrameError as exc:
                # length prefix can't be trusted -> the stream has no
                # recoverable frame boundary: quarantine and drop the link
                self._quarantine(f"frame: {exc}", b"")
                break
            except (TimeoutError, OSError):
                break
            if wire is None:
                break
            with _obs_trace.span("gossip.rx", node=self.node_id,
                                 wire_bytes=len(wire)):
                wire = rfaults.mangle_bytes("gossip.recv_frame", wire)
                try:
                    with _obs_trace.span("gossip.decode", node=self.node_id):
                        ssz = decode_message(wire)
                except (ValueError, IndexError) as exc:
                    # truncated/garbled snappy payload: the FRAME was still
                    # length-delimited, so the stream is in sync — quarantine
                    # the message, keep the connection
                    self._quarantine(
                        f"decode: {type(exc).__name__}: {exc}", wire)
                    continue
                mid = message_id(ssz)
                with self._lock:
                    if mid in self.stats.message_ids:
                        self.stats.count("duplicates")
                        continue
                    self.stats.message_ids.add(mid)
                    self.stats.count("received")
                    self.inbox.append(ssz)

    # -- slot actions ---------------------------------------------------------

    def publish(self, ssz_payloads: list[bytes]) -> None:
        """Flood locally produced messages to every peer."""
        with self._lock:
            for ssz in ssz_payloads:
                mid = message_id(ssz)
                if mid not in self.stats.message_ids:
                    self.stats.message_ids.add(mid)
                    self.inbox.append(ssz)
                    self.stats.count("produced")
        for ssz in ssz_payloads:
            wire = encode_message(ssz)
            for link in self._links:
                send_frame(link, wire)

    def drain_ready(self, max_messages: int | None = None) -> list[bytes]:
        """Non-blocking partial drain for streaming consumers (the
        attestation firehose): pop up to `max_messages` verified-candidate
        payloads that already cleared framing, decode, and message-id
        dedup — WITHOUT waiting for the slot barrier and without
        verifying. Interleaves freely with `drain_and_verify`, which keeps
        its exact batch semantics over whatever remains buffered: every
        message is returned by exactly one drain call, whichever kind
        claims it first."""
        with self._lock:
            if max_messages is None:
                batch, self.inbox = self.inbox, []
            else:
                batch = self.inbox[:max_messages]
                del self.inbox[:max_messages]
            if batch:
                self.stats.count("partial_drains")
        return batch

    def drain_and_verify(self, verify_fn) -> int:
        """Verify everything collected so far in one deferred-BLS flush."""
        from ..crypto import bls

        with self._lock:
            batch = list(self.inbox)
            self.inbox.clear()
        if batch:
            with _obs_trace.span("gossip.drain_and_verify",
                                 node=self.node_id, batch=len(batch)):
                with bls.deferred_verification():
                    for ssz in batch:
                        verify_fn(ssz)
            self.stats.count("verified_batches")
        return len(batch)

    def close(self) -> None:
        self._stop.set()
        for s in self._links + self._accepted:
            try:
                s.close()
            except OSError:
                pass
        self._server.close()


# --- full-mesh topology helper ----------------------------------------------


def connect_full_mesh(nodes: list[GossipNode]) -> None:
    """Dial every node to every other; each accepts n-1 inbound links."""
    n = len(nodes)
    acceptors = [
        threading.Thread(target=node.accept_peers, args=(n - 1,)) for node in nodes
    ]
    for t in acceptors:
        t.start()
    for node in nodes:
        node.dial_peers()
    for t in acceptors:
        t.join(timeout=15.0)


# --- one-OS-process-per-node cluster -----------------------------------------


def run_node_process(node_id: int, ports: list[int], messages_per_node: int,
                     barrier, out_queue) -> None:
    """Entry point for one cluster member running in its OWN OS process.

    Wires into the full mesh (two barrier phases: listeners up, mesh dialed),
    floods its share of deterministic payloads, waits for convergence, and
    reports (node_id, message_count, duplicates, sha256-of-sorted-ids) so the
    parent can assert every process converged to the identical message set."""
    import time
    import traceback

    try:
        n = len(ports)
        peers = [p for i, p in enumerate(ports) if i != node_id]
        node = GossipNode(node_id, ports[node_id], peers)
        barrier.wait(timeout=30.0)  # every process has a listening socket
        acceptor = threading.Thread(target=node.accept_peers, args=(n - 1,), daemon=True)
        acceptor.start()
        node.dial_peers()
        acceptor.join(timeout=15.0)
        barrier.wait(timeout=30.0)  # full mesh wired
        payloads = [
            b"node %03d attestation %06d " % (node_id, j) + b"." * 40
            for j in range(messages_per_node)
        ]
        node.publish(payloads)
        want = n * messages_per_node
        deadline = time.time() + 30.0
        while time.time() < deadline:
            with node._lock:
                have = len(node.stats.message_ids)
            if have >= want:
                break
            time.sleep(0.02)
        with node._lock:
            ids = sorted(node.stats.message_ids)
            dups = node.stats.duplicates
        digest = hashlib.sha256(b"".join(ids)).hexdigest()
        out_queue.put((node_id, len(ids), dups, digest))
        node.close()
    except BaseException:  # always report: a silent child hangs the parent
        out_queue.put((node_id, -1, -1, traceback.format_exc()))
        raise


def spawn_cluster(n_nodes: int, messages_per_node: int = 8,
                  base_port: int | None = None) -> list[tuple]:
    """Run one gossip round with one OS process per node (localhost TCP
    standing in for DCN). Returns the per-node reports sorted by node id;
    convergence holds iff every report carries the same count and digest."""
    import multiprocessing as mp
    import os

    if base_port is None:
        base_port = 20000 + (os.getpid() * 7) % 20000
    ports = [base_port + i for i in range(n_nodes)]
    ctx = mp.get_context("spawn")
    barrier = ctx.Barrier(n_nodes)
    out_queue = ctx.Queue()
    procs = [
        ctx.Process(target=run_node_process,
                    args=(i, ports, messages_per_node, barrier, out_queue))
        for i in range(n_nodes)
    ]
    for p in procs:
        p.start()
    try:
        reports = [out_queue.get(timeout=120.0) for _ in range(n_nodes)]
    finally:
        for p in procs:
            p.join(timeout=30.0)
            if p.is_alive():  # report collected or failed; never leak children
                p.terminate()
    failed = [r for r in reports if r[1] < 0]
    if failed:
        raise RuntimeError(
            f"gossip cluster: {len(failed)} node(s) crashed:\n" +
            "\n".join(r[3] for r in failed))
    return sorted(reports)
