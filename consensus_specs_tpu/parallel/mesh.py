"""Mesh + sharding layout for the epoch engine.

The protocol's scale axis is the validator registry (SURVEY.md §2.3): every
epoch sub-transition is an elementwise or reduce-shaped sweep over (N,)
arrays, so the natural layout is pure data parallelism — shard the validator
axis across the mesh, replicate the small per-epoch vectors (slashings,
randao mixes, block roots, checkpoints). GSPMD then turns `jnp.sum` over
sharded axes into psums over ICI and keeps everything else local.

The registry sort inside process_registry_updates (activation-queue ordering)
is the only op that needs cross-device data movement beyond reductions; XLA
lowers it to a distributed sort.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.state import EpochState

DATA_AXIS = "data"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def epoch_state_shardings(mesh: Mesh) -> EpochState:
    """An EpochState-shaped pytree of NamedShardings: validator axis split
    over the mesh, everything else replicated."""
    split = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    return EpochState(
        slot=repl,
        balances=split,
        effective_balance=split,
        activation_eligibility_epoch=split,
        activation_epoch=split,
        exit_epoch=split,
        withdrawable_epoch=split,
        slashed=split,
        prev_participation=split,
        curr_participation=split,
        inactivity_scores=split,
        slashings=repl,
        randao_mixes=repl,
        block_roots=repl,
        state_roots=repl,
        justification_bits=repl,
        prev_justified_epoch=repl,
        prev_justified_root=repl,
        curr_justified_epoch=repl,
        curr_justified_root=repl,
        finalized_epoch=repl,
        finalized_root=repl,
    )


def shard_epoch_state(state: EpochState, mesh: Mesh) -> EpochState:
    """Place an EpochState onto the mesh with the standard layout."""
    return jax.device_put(state, epoch_state_shardings(mesh))
