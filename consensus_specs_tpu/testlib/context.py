"""Decorator context engine: one test body, a (fork x preset x BLS) matrix,
two execution modes.

Reference parity: tests/core/pyspec/eth2spec/test/context.py (spec_targets
:67-78, with_custom_state + genesis LRU cache :96-116, spec_test :249,
spec_state_test :259, never_bls/always_bls/bls_switch :285-325, with_phases
:422, with_presets :450, with_config_overrides :493-525) and
test/utils/utils.py vector_test (:6-73) — the central dual-mode design: a
test body is a generator yielding named parts; under pytest the parts are
drained and assertions do the testing; under generator mode the identical run
is serialized into client-consumable vectors.

Usage:

    @with_all_phases
    @spec_state_test
    def test_something(spec, state):
        yield "pre", state
        ... mutate ...
        yield "post", state

Outermost wrapper signature (what pytest and the vector generator both call):

    test_something(preset=None, fork=None, generator_mode=False, bls_active=None)

Under pytest (no args) it runs every selected fork on the default preset.
Under generator mode the runner pins one (fork, preset) and collects the
typed parts list.
"""
from __future__ import annotations

import functools
from random import Random

from ..compiler import get_spec
from ..crypto import bls
from ..ssz import SSZType, serialize
from .genesis import create_genesis_state

# Fork / preset universe (mirrors compiler FORK_ORDER; sharding-era forks are
# spec'd but not compiled, same as the reference's build targets).
PHASE0 = "phase0"
ALTAIR = "altair"
BELLATRIX = "bellatrix"
SHARDING = "sharding"
DAS = "das"
CUSTODY_GAME = "custody_game"
# ALL_PHASES stays the stable fork set (the reference's with_all_phases
# universe); sharding-era forks compile here (unlike the reference) but opt
# in per-test via with_phases([SHARDING]) etc.
ALL_PHASES = (PHASE0, ALTAIR, BELLATRIX)
MINIMAL = "minimal"
MAINNET = "mainnet"
DEFAULT_TEST_PRESET = MINIMAL
# pytest --fork sets this to pin the decorator matrix to one fork
FORK_RESTRICTION: str | None = None


# --- part collection (vector_test dual-mode) --------------------------------

def _normalize_part(item):
    """yielded item -> (name, kind, value); kinds: meta | data | ssz."""
    if len(item) == 3:
        name, kind, value = item
        return name, kind, value
    name, value = item
    if isinstance(value, SSZType):
        return name, "ssz", value
    return name, "data", value


def vector_test(fn):
    """Make a yielding test body dual-mode (reference vector_test)."""

    @functools.wraps(fn)
    def wrapper(*args, generator_mode=False, **kwargs):
        out = fn(*args, **kwargs)
        if out is None:
            return None
        parts = []
        for item in out:
            if item is None:
                continue
            parts.append(_normalize_part(item))
        return parts if generator_mode else None

    return wrapper


# --- genesis-state cache ----------------------------------------------------

_state_cache: dict = {}


def _default_validator_count(spec) -> int:
    """Test-world registry size: SLOTS_PER_EPOCH * 8, the reference's
    default_balances sizing (helpers — 64 at minimal, 256 at mainnet).
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT coincides at minimal (64) but is
    16384 at mainnet — far past the 512-key deterministic test pool, which
    is exactly why the reference sizes its test worlds by epoch length."""
    return int(spec.SLOTS_PER_EPOCH) * 8


def default_balances(spec):
    n = _default_validator_count(spec)
    return [int(spec.MAX_EFFECTIVE_BALANCE)] * n


def low_balances(spec):
    n = _default_validator_count(spec)
    return [int(spec.config.EJECTION_BALANCE)] * n


def misc_balances(spec):
    n = _default_validator_count(spec)
    mx = int(spec.MAX_EFFECTIVE_BALANCE)
    balances = [mx * 2 * i // n for i in range(n)]
    Random(3141).shuffle(balances)
    return balances


def _cached_genesis(spec, balances_fn, threshold_fn):
    # keyed by the MODULE, not (fork, preset): a with_config_overrides spec
    # is a fresh module with its own SSZ classes, and a state built from
    # another module's classes fails coercion/equality inside it (the
    # get_spec singletons hit the cache as before; per-override modules
    # build genesis fresh, which is also what correctness requires —
    # overridden config can change genesis content)
    key = (spec, balances_fn.__name__, threshold_fn.__name__)
    if key not in _state_cache:
        balances = balances_fn(spec)
        threshold = threshold_fn(spec)
        _state_cache[key] = create_genesis_state(spec, balances, threshold)
    return _state_cache[key].copy()


def _default_threshold(spec):
    return spec.MAX_EFFECTIVE_BALANCE


def _low_threshold(spec):
    return spec.config.EJECTION_BALANCE


# --- core decorators --------------------------------------------------------

def spec_test(fn):
    """Innermost: dual-mode part collection (no state fixture)."""
    return vector_test(fn)


def with_custom_state(balances_fn, threshold_fn):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec, **kwargs):
            state = _cached_genesis(spec, balances_fn, threshold_fn)
            return fn(*args, spec=spec, state=state, **kwargs)

        return wrapper

    return deco


def spec_state_test(fn):
    """spec_test + default genesis state fixture."""
    return spec_test(with_custom_state(default_balances, _default_threshold)(_kwargs_body(fn)))


def _kwargs_body(fn):
    """Adapt positional body(spec, state) to keyword calling convention."""

    @functools.wraps(fn)
    def wrapper(*, spec, state=None, **kwargs):
        if state is None:
            return fn(spec, **kwargs)
        return fn(spec, state, **kwargs)

    return wrapper


def spec_configured_state_test(balances_fn=default_balances, threshold_fn=_default_threshold):
    def deco(fn):
        return spec_test(with_custom_state(balances_fn, threshold_fn)(_kwargs_body(fn)))

    return deco


# --- BLS switches -----------------------------------------------------------

def _with_bls(fn, active, meta_tag):
    @functools.wraps(fn)
    def wrapper(*args, bls_active=None, generator_mode=False, **kwargs):
        want = active if active is not None else (
            bls_active if bls_active is not None else bls.bls_active
        )
        prev = bls.bls_active
        bls.bls_active = want
        try:
            parts = fn(*args, generator_mode=generator_mode, **kwargs)
        finally:
            bls.bls_active = prev
        if generator_mode and parts is not None and meta_tag is not None:
            parts = [("bls_setting", "meta", meta_tag)] + parts
        return parts

    return wrapper


def always_bls(fn):
    """Test is meaningless without real signature checks (meta bls_setting=1)."""
    return _with_bls(fn, True, 1)


def never_bls(fn):
    """Test must run with BLS off (meta bls_setting=2)."""
    return _with_bls(fn, False, 2)


def bls_switch(fn):
    """Honor the caller's bls_active flag (pytest default: off, for speed)."""
    return _with_bls(fn, None, None)


# --- fork / preset matrix ---------------------------------------------------

def with_phases(phases, other_phases=None):
    """Outermost: expand over forks; pytest runs all, generator pins one."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(preset=None, fork=None, generator_mode=False, bls_active=None, **kwargs):
            preset = preset or DEFAULT_TEST_PRESET
            if fork is None and FORK_RESTRICTION is not None:
                if FORK_RESTRICTION not in phases:
                    import pytest as _pytest

                    _pytest.skip(f"test does not cover fork {FORK_RESTRICTION}")
                run_forks = [FORK_RESTRICTION]
            else:
                run_forks = [fork] if fork else list(phases)
            results = {}
            prev_bls = bls.bls_active
            if bls_active is not None:
                # ambient default; an inner always_bls/never_bls still overrides
                bls.bls_active = bls_active
            try:
                for f in run_forks:
                    if f not in phases and (other_phases is None or f not in other_phases):
                        continue
                    spec = get_spec(f, preset)
                    extra = {}
                    if other_phases:
                        extra["phases"] = {
                            g: get_spec(g, preset) for g in (*phases, *other_phases)
                        }
                    results[f] = fn(
                        spec=spec, generator_mode=generator_mode, **extra, **kwargs
                    )
            finally:
                bls.bls_active = prev_bls
            # pytest (no pinned fork) must see None; the generator pins a fork
            # and receives that fork's typed parts
            return results[fork] if fork else None

        wrapper.run_phases = tuple(phases)
        wrapper.all_phases = tuple(phases) + tuple(other_phases or ())
        # pytest resolves fixtures from the *original* body signature via
        # __wrapped__; hide it so the zero-arg wrapper is what gets collected
        del wrapper.__wrapped__
        return wrapper

    return deco


def with_all_phases(fn):
    return with_phases(ALL_PHASES)(fn)


def with_all_phases_except(excluded):
    return with_phases([p for p in ALL_PHASES if p not in excluded])


def with_presets(presets, reason=None):
    """Restrict a test to given presets (e.g. minimal-only scenario sizes).

    Must sit ABOVE (outside) with_phases/with_all_phases: with_phases
    consumes the `preset` kwarg, so the gate has to see it first — and it
    only re-injects the kwarg when it actually received one, because the
    inner chain does not accept `preset` otherwise."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(preset=None, **kwargs):
            effective = preset or DEFAULT_TEST_PRESET
            if effective not in presets:
                return None  # skipped
            if preset is None:
                return fn(**kwargs)
            return fn(preset=preset, **kwargs)

        wrapper.allowed_presets = tuple(presets)
        return wrapper

    return deco


def with_config_overrides(overrides: dict):
    """Run with a modified runtime config (fresh spec module per overrides).

    Generator mode also emits the overrides as a per-case `config.yaml`
    part (reference context.py:493-525 does the same) — without it a
    replay runs the vector against the DEFAULT config and the case is
    unreproducible (caught by the round-5 fork_choice replay)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, spec, **kwargs):
            from ..compiler.spec_compiler import get_spec_with_overrides

            patched = get_spec_with_overrides(spec.fork, spec.preset_name, overrides)
            parts = fn(*args, spec=patched, **kwargs)
            if kwargs.get("generator_mode") and parts is not None:
                serializable = {
                    k: ("0x" + v.hex()) if isinstance(v, (bytes, bytearray)) else v
                    for k, v in overrides.items()
                }
                parts = [("config", "data", serializable)] + list(parts)
            return parts

        return wrapper

    return deco


# --- misc helpers -----------------------------------------------------------

def expect_assertion_error(fn):
    """Run fn expecting the spec to reject (AssertionError or IndexError —
    reference counts out-of-range accesses as failed asserts, context.py
    :271-282)."""
    try:
        fn()
    except (AssertionError, IndexError):
        return
    raise AssertionError("expected the spec to reject, but it accepted")


def serialize_part(value):
    return serialize(value)
