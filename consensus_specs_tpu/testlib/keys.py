"""Deterministic test keypairs.

Reference parity: eth2spec test helpers' key fixtures
(tests/core/pyspec/eth2spec/test/helpers/keys.py:4-6) — privkeys are small
consecutive integers, pubkeys derived once and cached. Small scalars keep the
pure-Python G1 multiplications cheap (bit-length-bounded double-and-add).
"""
from __future__ import annotations

from ..crypto import bls_sig

NUM_KEYS = 512  # enough for minimal-preset test worlds (64..256 validators)

privkeys = [i + 1 for i in range(NUM_KEYS)]

_pubkey_cache: list[bytes] | None = None
_pubkey_to_privkey: dict[bytes, int] | None = None


def get_pubkeys() -> list[bytes]:
    global _pubkey_cache
    if _pubkey_cache is None:
        _pubkey_cache = [bls_sig.SkToPk(k) for k in privkeys]
    return _pubkey_cache


def pubkey_to_privkey(pubkey: bytes) -> int:
    global _pubkey_to_privkey
    if _pubkey_to_privkey is None:
        _pubkey_to_privkey = {pk: sk for pk, sk in zip(get_pubkeys(), privkeys)}
    return _pubkey_to_privkey[bytes(pubkey)]


class _LazyPubkeys:
    def __getitem__(self, i):
        return get_pubkeys()[i]

    def __len__(self):
        return NUM_KEYS

    def __iter__(self):
        return iter(get_pubkeys())


pubkeys = _LazyPubkeys()
