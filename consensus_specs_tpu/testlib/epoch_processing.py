"""Epoch-processing test harness helpers.

Reference parity: test/helpers/epoch_processing.py (run_epoch_processing_to
:36-55): advance the state to the final slot of the epoch, then run the
epoch sub-transitions *in spec order* up to — but not including — the target,
so a test can exercise exactly one sub-transition against a realistic
pre-state.
"""
from __future__ import annotations


def get_process_calls(spec) -> list[str]:
    """Sub-transition order of the fork's process_epoch. Fork-aware by name
    (the overlay namespace keeps superseded phase0 functions importable, so
    hasattr alone would leak process_participation_record_updates into
    altair's order)."""
    if spec.fork == "phase0":
        return [
            "process_justification_and_finalization",
            "process_rewards_and_penalties",
            "process_registry_updates",
            "process_slashings",
            "process_eth1_data_reset",
            "process_effective_balance_updates",
            "process_slashings_reset",
            "process_randao_mixes_reset",
            "process_historical_roots_update",
            "process_participation_record_updates",
        ]
    return [
        "process_justification_and_finalization",
        "process_inactivity_updates",
        "process_rewards_and_penalties",
        "process_registry_updates",
        "process_slashings",
        "process_eth1_data_reset",
        "process_effective_balance_updates",
        "process_slashings_reset",
        "process_randao_mixes_reset",
        "process_historical_roots_update",
        "process_participation_flag_updates",
        "process_sync_committee_updates",
    ]


def run_epoch_processing_to(spec, state, process_name: str) -> None:
    """Process slots to the epoch boundary, then sub-transitions before
    `process_name`."""
    slot = state.slot + (spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH) - 1
    if state.slot < slot:
        spec.process_slots(state, slot)
    for name in get_process_calls(spec):
        if name == process_name:
            break
        getattr(spec, name)(state)


def run_epoch_processing_with(spec, state, process_name: str):
    """Dual-mode runner: yields pre, runs the sub-transition, yields post.

    The sub-transition name rides meta.yaml so replay harnesses know which
    process_* to apply (the reference encodes it in the handler directory;
    our generator groups by module — meta carries the same information)."""
    run_epoch_processing_to(spec, state, process_name)
    yield "sub_transition", "meta", process_name.removeprefix("process_")
    yield "pre", state.copy()
    getattr(spec, process_name)(state)
    yield "post", state.copy()
