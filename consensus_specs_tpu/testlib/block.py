"""Block-construction helpers (reference parity: test/helpers/block.py)."""
from __future__ import annotations

from .keys import pubkey_to_privkey
from ..crypto import bls


def get_proposer_privkey(spec, state, proposer_index=None):
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state)
    return pubkey_to_privkey(state.validators[proposer_index].pubkey)


def apply_randao_reveal(spec, state, block):
    assert state.slot <= block.slot
    proposer_state = state
    if state.slot < block.slot:
        proposer_state = state.copy()
        spec.process_slots(proposer_state, block.slot)
    privkey = get_proposer_privkey(spec, proposer_state, block.proposer_index)
    epoch = spec.get_current_epoch(proposer_state)
    domain = spec.get_domain(proposer_state, spec.DOMAIN_RANDAO, epoch)
    signing_root = spec.compute_signing_root(spec.Epoch(epoch), domain)
    block.body.randao_reveal = bls.Sign(privkey, signing_root)


def build_empty_block(spec, state, slot=None):
    if slot is None:
        slot = state.slot
    if slot < state.slot:
        raise ValueError("cannot build a block for a past slot")
    if state.slot < slot:
        state = state.copy()
        spec.process_slots(state, slot)

    block = spec.BeaconBlock()
    block.slot = slot
    block.proposer_index = spec.get_beacon_proposer_index(state)
    block.parent_root = spec.hash_tree_root(state.latest_block_header)
    block.body.eth1_data.deposit_count = state.eth1_deposit_index
    if spec.fork != "phase0":
        # Empty-participation sync aggregate: valid with the infinity signature
        block.body.sync_aggregate.sync_committee_signature = spec.G2_POINT_AT_INFINITY
    if spec.fork in ("bellatrix", "sharding", "custody_game"):
        if spec.is_merge_transition_complete(state):
            block.body.execution_payload = build_empty_execution_payload(spec, state)
        else:
            block.body.execution_payload = spec.ExecutionPayload()
    apply_randao_reveal(spec, state, block)
    return block


def build_empty_execution_payload(spec, state):
    """A payload that passes process_execution_payload's consistency asserts
    for the post-merge `state` (reference parity: helpers/execution_payload.py
    build_empty_execution_payload)."""
    latest = state.latest_execution_payload_header
    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        state_root=latest.state_root,
        receipt_root=b"\x2a" * 32,
        logs_bloom=spec.ByteVector[spec.BYTES_PER_LOGS_BLOOM](),
        random=spec.get_randao_mix(state, spec.get_current_epoch(state)),
        block_number=latest.block_number + 1,
        gas_limit=latest.gas_limit,
        gas_used=0,
        timestamp=spec.compute_timestamp_at_slot(state, state.slot),
        base_fee_per_gas=latest.base_fee_per_gas,
    )
    payload.block_hash = spec.Hash32(spec.hash(spec.hash_tree_root(payload) + b"FAKE RLP HASH"))
    return payload


def build_empty_block_for_next_slot(spec, state):
    return build_empty_block(spec, state, state.slot + 1)


def sign_block(spec, state, block, proposer_index=None):
    if proposer_index is None:
        proposer_index = block.proposer_index
    privkey = pubkey_to_privkey(state.validators[proposer_index].pubkey)
    domain = spec.get_domain(
        state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(block.slot)
    )
    signing_root = spec.compute_signing_root(block, domain)
    return spec.SignedBeaconBlock(message=block, signature=bls.Sign(privkey, signing_root))


def transition_unsigned_block(spec, state, block):
    assert state.slot < block.slot
    spec.process_slots(state, block.slot)
    spec.process_block(state, block)


def state_transition_and_sign_block(spec, state, block, expect_fail=False):
    """Advance `state` through `block`, fill in the resulting state root, and
    return the signed block (the standard valid-block test flow)."""
    pre_state = state.copy()
    transition_unsigned_block(spec, state, block)
    block.state_root = spec.hash_tree_root(state)
    signed_block = sign_block(spec, pre_state, block)
    # The full transition (with signature checks) must agree.
    check_state = pre_state
    spec.state_transition(check_state, signed_block, validate_result=True)
    assert spec.hash_tree_root(check_state) == spec.hash_tree_root(state)
    return signed_block


def apply_empty_block(spec, state, slot=None):
    if slot is None:
        slot = state.slot + 1
    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)


def next_epoch_via_block(spec, state):
    return apply_empty_block(
        spec, state, state.slot + spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH
    )
