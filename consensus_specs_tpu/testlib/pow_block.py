"""Synthetic PoW-chain mocking for merge-transition tests.

Reference parity: test/helpers/pow_block.py + the get_pow_block stub the
reference injects at build time (setup.py:513-514) — tests patch the spec
module's `get_pow_block` to serve from an in-memory chain dict.
"""
from contextlib import contextmanager


def prepare_terminal_pow_chain(spec):
    """(parent, terminal) PoW pair straddling TERMINAL_TOTAL_DIFFICULTY."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    parent = spec.PowBlock(
        block_hash=spec.Hash32(b"\x01" * 32),
        parent_hash=spec.Hash32(b"\x00" * 32),
        total_difficulty=spec.uint256(ttd - 1),
    )
    terminal = spec.PowBlock(
        block_hash=spec.Hash32(b"\x02" * 32),
        parent_hash=parent.block_hash,
        total_difficulty=spec.uint256(ttd),
    )
    return parent, terminal


@contextmanager
def pow_chain(spec, blocks):
    """Patch spec.get_pow_block to serve from `blocks` for the duration."""
    table = {bytes(b.block_hash): b for b in blocks}
    prev = spec.get_pow_block
    spec.get_pow_block = lambda block_hash: table.get(bytes(block_hash))
    try:
        yield table
    finally:
        spec.get_pow_block = prev
