"""Rewards-delta harness: per-component isolation with invariant checks.

The `run_deltas` role of the reference (test/helpers/rewards.py:19-100):
compute every reward/penalty component in isolation from one pre-state,
validate each against independently-derived participation sets, and emit
the `Deltas` vector parts. On top of the reference's per-component checks
this harness closes the loop with a TOTAL-consistency oracle: the summed
component deltas must equal the balance changes an actual
`process_rewards_and_penalties` run produces on a copy of the state.

Works across both fork families: phase0 (pending-attestation derived) and
altair+ (participation-flag derived).

NOTE: no `from __future__ import annotations` here — Container fields are
resolved from the class annotations as real type objects.
"""
import random

from ..ssz.types import Container, List, uint64
from .state import next_epoch

REGISTRY_LIMIT = 2**40


class Deltas(Container):
    rewards: List[uint64, REGISTRY_LIMIT]
    penalties: List[uint64, REGISTRY_LIMIT]


def make_deltas(pair) -> Deltas:
    rewards, penalties = pair
    return Deltas(
        rewards=List[uint64, REGISTRY_LIMIT](*[int(x) for x in rewards]),
        penalties=List[uint64, REGISTRY_LIMIT](*[int(x) for x in penalties]),
    )


def is_post_altair(state) -> bool:
    return hasattr(state, "previous_epoch_participation")


# --- participation scenario setters -----------------------------------------


def set_participation_fraction(spec, state, fraction: float) -> None:
    """Leave the first `fraction` of the registry fully participating in the
    previous epoch, the rest idle."""
    n = len(state.validators)
    cut = int(n * fraction)
    if is_post_altair(state):
        full = spec.ParticipationFlags(0b111)
        for i in range(n):
            state.previous_epoch_participation[i] = (
                full if i < cut else spec.ParticipationFlags(0))
    else:
        _filter_pending_attestation_bits(spec, state, lambda i: i < cut)


def set_random_participation(spec, state, rng: random.Random) -> None:
    if is_post_altair(state):
        for i in range(len(state.validators)):
            flags = 0
            for flag_index in range(3):
                if rng.random() < 0.55:
                    flags |= 1 << flag_index
            # target participation implies source in real attestation flows;
            # random flags are fine for delta math (components read flags
            # independently) but keep them plausible: head implies target
            if flags & 0b100:
                flags |= 0b010
            if flags & 0b010:
                flags |= 0b001
            state.previous_epoch_participation[i] = spec.ParticipationFlags(flags)
    else:
        _filter_pending_attestation_bits(spec, state, lambda i: rng.random() < 0.55)


def set_flag_only(spec, state, flag_index: int) -> None:
    """Altair family: every validator participates in exactly one duty flag
    (plus implied lower flags for target/head plausibility is NOT applied —
    the point is component isolation)."""
    flags = spec.ParticipationFlags(1 << flag_index)
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = flags


def _filter_pending_attestation_bits(spec, state, keep_fn) -> None:
    """phase0: clear aggregation bits of previous-epoch pending attestations
    for validators where keep_fn(validator_index) is false."""
    for att in state.previous_epoch_attestations:
        committee = spec.get_beacon_committee(
            state, att.data.slot, att.data.index)
        for pos, vidx in enumerate(committee):
            if att.aggregation_bits[pos] and not keep_fn(int(vidx)):
                att.aggregation_bits[pos] = False


def slash_fraction(spec, state, fraction: float) -> None:
    """Mark a prefix of the registry slashed (still withdrawable in the
    future, so they remain delta-eligible)."""
    current = spec.get_current_epoch(state)
    for i in range(int(len(state.validators) * fraction)):
        v = state.validators[i]
        # participation flags/pending bits stay as-is: the spec's
        # unslashed-set filtering is what must exclude these validators
        v.slashed = True
        v.withdrawable_epoch = current + spec.EPOCHS_PER_SLASHINGS_VECTOR


def exit_fraction(spec, state, fraction: float) -> None:
    """Exit a prefix of the registry as of two epochs ago (inactive AND not
    slashed => ineligible for deltas)."""
    current = spec.get_current_epoch(state)
    for i in range(int(len(state.validators) * fraction)):
        v = state.validators[i]
        v.exit_epoch = max(spec.GENESIS_EPOCH, current - 2)
        v.withdrawable_epoch = v.exit_epoch + spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY


def put_in_leak(spec, state, extra_epochs: int = 0) -> None:
    """Advance far enough past the (never-updated) finalized checkpoint that
    is_in_inactivity_leak flips on."""
    target = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 1 + extra_epochs
    while spec.get_previous_epoch(state) - state.finalized_checkpoint.epoch <= target:
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    if is_post_altair(state):
        # leaked epochs accrue inactivity scores; model a plausible spread
        for i in range(len(state.validators)):
            state.inactivity_scores[i] = uint64(
                (i % 5) * int(spec.config.INACTIVITY_SCORE_BIAS))


# --- participation sets (independent of the delta functions) -----------------


def eligible_indices(spec, state) -> set:
    return set(int(i) for i in spec.get_eligible_validator_indices(state))


def duty_participants(spec, state, duty: str) -> set:
    """Unslashed previous-epoch participants for duty in
    {source, target, head}, derived from raw state data."""
    prev = spec.get_previous_epoch(state)
    if is_post_altair(state):
        flag_index = {
            "source": spec.TIMELY_SOURCE_FLAG_INDEX,
            "target": spec.TIMELY_TARGET_FLAG_INDEX,
            "head": spec.TIMELY_HEAD_FLAG_INDEX,
        }[duty]
        return set(
            int(i) for i in spec.get_unslashed_participating_indices(state, flag_index, prev))
    atts = {
        "source": spec.get_matching_source_attestations,
        "target": spec.get_matching_target_attestations,
        "head": spec.get_matching_head_attestations,
    }[duty](state, prev)
    return set(int(i) for i in spec.get_unslashed_attesting_indices(state, atts))


# --- component invariant validation ------------------------------------------


def validate_attestation_component(spec, state, duty: str, deltas: Deltas) -> None:
    """source/target/head: participants are never penalized; non-participating
    eligible validators earn nothing and are penalized; the ineligible get
    zero/zero. Under a leak, even participants earn no attestation rewards
    (altair semantics; phase0 pays a leak-reduced amount through different
    arithmetic — the zero-reward claim is altair-only)."""
    n = len(state.validators)
    assert len(deltas.rewards) == n and len(deltas.penalties) == n
    eligible = eligible_indices(spec, state)
    participants = duty_participants(spec, state, duty)
    leaking = spec.is_in_inactivity_leak(state)
    post_altair = is_post_altair(state)
    # altair exempts the head flag from penalties (head timeliness is hard
    # to control for honest validators); phase0 penalizes all three duties
    penalizes = not (post_altair and duty == "head")
    for i in range(n):
        r, p = int(deltas.rewards[i]), int(deltas.penalties[i])
        if i not in eligible:
            assert r == 0 and p == 0, f"{duty}: ineligible {i} has deltas"
        elif i in participants:
            assert p == 0, f"{duty}: participant {i} penalized"
            if leaking and post_altair:
                assert r == 0, f"{duty}: leak paid attestation reward to {i}"
        else:
            assert r == 0, f"{duty}: non-participant {i} rewarded"
            if penalizes:
                assert p > 0, f"{duty}: non-participant {i} not penalized"
            else:
                assert p == 0, f"{duty}: altair head flag must not penalize {i}"
    # liveness of the component itself: outside a leak (where altair zeroes
    # attestation rewards), a non-empty participant set must actually earn —
    # otherwise a regression zeroing the reward arithmetic passes silently
    if participants and not leaking:
        total = sum(int(deltas.rewards[i]) for i in participants)
        assert total > 0, f"{duty}: participants earned nothing outside a leak"


def validate_inclusion_delay_component(spec, state, deltas: Deltas) -> None:
    """phase0 only: nobody is penalized; source-credited attesters earn."""
    n = len(state.validators)
    participants = duty_participants(spec, state, "source")
    for i in range(n):
        assert int(deltas.penalties[i]) == 0, f"inclusion_delay penalized {i}"
        if int(deltas.rewards[i]) > 0:
            # rewards go to attesters and to their including proposers —
            # proposers may be outside the attester set, so only the converse
            # direction is checkable per-index:
            pass
    for i in participants:
        assert int(deltas.rewards[i]) > 0, f"attester {i} got no inclusion reward"


def validate_inactivity_component(spec, state, deltas: Deltas) -> None:
    """Inactivity: never rewards anyone. Penalties hit eligible validators
    missing target participation — always in altair (score-scaled), only
    under leak in phase0."""
    n = len(state.validators)
    eligible = eligible_indices(spec, state)
    target_participants = duty_participants(spec, state, "target")
    leaking = spec.is_in_inactivity_leak(state)
    post_altair = is_post_altair(state)
    for i in range(n):
        r, p = int(deltas.rewards[i]), int(deltas.penalties[i])
        assert r == 0, f"inactivity rewarded {i}"
        if i not in eligible:
            assert p == 0, f"inactivity penalized ineligible {i}"
            continue
        if post_altair:
            score = int(state.inactivity_scores[i])
            if i in target_participants or score == 0:
                assert p == 0, f"inactivity penalized participant/zero-score {i}"
            elif score > 0:
                assert p > 0, f"score {score} but no inactivity penalty for {i}"
        else:
            if not leaking:
                assert p == 0, f"phase0 inactivity penalty outside leak for {i}"
            else:
                # phase0 leak: EVERY eligible validator pays the flat
                # base-reward component; non-target-participants additionally
                # pay the quadratic finality-delay term
                assert p > 0, f"phase0 leak: eligible {i} unpenalized"


# --- the harness -------------------------------------------------------------


def component_deltas(spec, state):
    """(name, Deltas) per fork-appropriate component."""
    if is_post_altair(state):
        for name, idx in (
            ("source_deltas", spec.TIMELY_SOURCE_FLAG_INDEX),
            ("target_deltas", spec.TIMELY_TARGET_FLAG_INDEX),
            ("head_deltas", spec.TIMELY_HEAD_FLAG_INDEX),
        ):
            yield name, make_deltas(spec.get_flag_index_deltas(state, idx))
    else:
        yield "source_deltas", make_deltas(spec.get_source_deltas(state))
        yield "target_deltas", make_deltas(spec.get_target_deltas(state))
        yield "head_deltas", make_deltas(spec.get_head_deltas(state))
        yield "inclusion_delay_deltas", make_deltas(spec.get_inclusion_delay_deltas(state))
    yield "inactivity_penalty_deltas", make_deltas(spec.get_inactivity_penalty_deltas(state))


def validate_component(spec, state, name: str, deltas: Deltas) -> None:
    if name in ("source_deltas", "target_deltas", "head_deltas"):
        validate_attestation_component(spec, state, name.split("_")[0], deltas)
    elif name == "inclusion_delay_deltas":
        validate_inclusion_delay_component(spec, state, deltas)
    else:
        validate_inactivity_component(spec, state, deltas)


def check_total_consistency(spec, state, components: dict) -> None:
    """Sum of per-component deltas == balance movement of the real
    process_rewards_and_penalties sweep (run on a copy). This pins the
    isolation decomposition to the actual epoch transition."""
    probe = state.copy()
    spec.process_rewards_and_penalties(probe)
    n = len(state.validators)
    for i in range(n):
        total = sum(int(d.rewards[i]) for d in components.values()) - sum(
            int(d.penalties[i]) for d in components.values())
        expected = int(probe.balances[i]) - int(state.balances[i])
        # balances floor at zero: a penalty overshoot saturates
        if expected == -int(state.balances[i]) and total < expected:
            continue
        assert total == expected, (
            f"component sum {total} != epoch-processing movement {expected} "
            f"for validator {i}")


def run_deltas(spec, state):
    """Vector-part generator: pre + every component (validated), plus the
    total-consistency check. Use from @spec_state_test bodies."""
    yield "pre", state.copy()
    components = {}
    for name, deltas in component_deltas(spec, state):
        validate_component(spec, state, name, deltas)
        components[name] = deltas
        yield name, deltas
    check_total_consistency(spec, state, components)
