"""State-advancement helpers (reference parity: test/helpers/state.py)."""
from __future__ import annotations


def next_slot(spec, state):
    spec.process_slots(state, state.slot + 1)


def next_slots(spec, state, slots):
    if slots > 0:
        spec.process_slots(state, state.slot + slots)


def next_epoch(spec, state):
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def transition_to(spec, state, slot):
    assert state.slot <= slot
    if state.slot < slot:
        spec.process_slots(state, slot)


def transition_to_slot_via_block(spec, state, slot):
    from .block import apply_empty_block
    assert state.slot < slot
    apply_empty_block(spec, state, slot)


def get_balance(state, index):
    return state.balances[index]


def set_full_participation_previous_epoch(spec, state):
    """Make every active validator appear to have attested correctly for the
    previous epoch — phase0: synthetic PendingAttestations; altair family:
    all three timely flags on the previous-epoch participation column."""
    if hasattr(state, "previous_epoch_participation"):
        full = spec.ParticipationFlags(0)
        for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, flag_index)
        prev = spec.get_previous_epoch(state)
        for index in spec.get_active_validator_indices(state, prev):
            state.previous_epoch_participation[index] = full
    else:
        from .attestations import add_attestations_for_epoch
        add_attestations_for_epoch(spec, state, spec.get_previous_epoch(state))
