"""State-advancement helpers (reference parity: test/helpers/state.py)."""
from __future__ import annotations


def next_slot(spec, state):
    spec.process_slots(state, state.slot + 1)


def next_slots(spec, state, slots):
    if slots > 0:
        spec.process_slots(state, state.slot + slots)


def next_epoch(spec, state):
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, slot)


def transition_to(spec, state, slot):
    assert state.slot <= slot
    if state.slot < slot:
        spec.process_slots(state, slot)


def transition_to_slot_via_block(spec, state, slot):
    from .block import apply_empty_block
    assert state.slot < slot
    apply_empty_block(spec, state, slot)


def get_balance(state, index):
    return state.balances[index]


def prepared_epoch_state(spec, start_epoch: int, seed: int):
    """A randomized state parked at the LAST slot of `start_epoch` (where
    process_epoch runs), with per-validator balances/participation/
    inactivity scrambled and a justifiable checkpoint pair — the shared
    setup of the engine differential suites (test_resident_engine,
    test_robustness, test_chaos_epoch). start_epoch=6 on minimal puts
    eth1 reset, historical append, and sync rotation boundaries within a
    9-epoch run."""
    import random

    from .genesis import create_valid_beacon_state

    state = create_valid_beacon_state(spec)
    transition_to(spec, state, start_epoch * spec.SLOTS_PER_EPOCH)
    state.slot = spec.Slot((start_epoch + 1) * spec.SLOTS_PER_EPOCH - 1)
    rng = random.Random(seed)
    for i in range(len(state.validators)):
        state.balances[i] = spec.Gwei(rng.randrange(16_000_000_000, 40_000_000_000))
        state.previous_epoch_participation[i] = spec.ParticipationFlags(rng.randrange(0, 8))
        state.current_epoch_participation[i] = spec.ParticipationFlags(rng.randrange(0, 8))
        state.inactivity_scores[i] = spec.uint64(rng.randrange(0, 100))
    cur = spec.get_current_epoch(state)
    state.finalized_checkpoint = spec.Checkpoint(
        epoch=spec.Epoch(max(0, int(cur) - 2)), root=state.finalized_checkpoint.root)
    state.current_justified_checkpoint = spec.Checkpoint(
        epoch=spec.Epoch(max(0, int(cur) - 1)), root=state.current_justified_checkpoint.root)
    return state


def set_full_participation_previous_epoch(spec, state):
    """Make every active validator appear to have attested correctly for the
    previous epoch — phase0: synthetic PendingAttestations; altair family:
    all three timely flags on the previous-epoch participation column."""
    if hasattr(state, "previous_epoch_participation"):
        full = spec.ParticipationFlags(0)
        for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
            full = spec.add_flag(full, flag_index)
        prev = spec.get_previous_epoch(state)
        for index in spec.get_active_validator_indices(state, prev):
            state.previous_epoch_participation[index] = full
    else:
        from .attestations import add_attestations_for_epoch
        add_attestations_for_epoch(spec, state, spec.get_previous_epoch(state))
