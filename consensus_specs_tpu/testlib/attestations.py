"""Attestation scenario builders (reference parity: test/helpers/attestations.py)."""
from __future__ import annotations

from .block import build_empty_block_for_next_slot, state_transition_and_sign_block
from .keys import pubkey_to_privkey
from ..crypto import bls


def build_attestation_data(spec, state, slot, index):
    assert state.slot >= slot

    if slot == state.slot:
        block_root = build_empty_block_for_next_slot(spec, state).parent_root
    else:
        block_root = spec.get_block_root_at_slot(state, slot)

    current_epoch_start_slot = spec.compute_start_slot_at_epoch(spec.get_current_epoch(state))
    if slot < current_epoch_start_slot:
        epoch_boundary_root = spec.get_block_root(state, spec.get_previous_epoch(state))
    elif slot == current_epoch_start_slot:
        epoch_boundary_root = block_root
    else:
        epoch_boundary_root = spec.get_block_root(state, spec.get_current_epoch(state))

    # COPY the checkpoint: aliasing the state's own object would let a test
    # that edits attestation.data.source silently mutate the state and
    # vacuously pass equality asserts
    if slot < current_epoch_start_slot:
        source = state.previous_justified_checkpoint.copy()
    else:
        source = state.current_justified_checkpoint.copy()

    return spec.AttestationData(
        slot=slot,
        index=index,
        beacon_block_root=block_root,
        source=source,
        target=spec.Checkpoint(epoch=spec.compute_epoch_at_slot(slot), root=epoch_boundary_root),
    )


def get_attestation_signature(spec, state, attestation_data, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER, attestation_data.target.epoch)
    signing_root = spec.compute_signing_root(attestation_data, domain)
    return bls.Sign(privkey, signing_root)


def sign_aggregate_attestation(spec, state, attestation_data, participants):
    signatures = [
        get_attestation_signature(
            spec, state, attestation_data,
            pubkey_to_privkey(state.validators[participant].pubkey),
        )
        for participant in participants
    ]
    if not bls.bls_active:
        return bls.STUB_SIGNATURE
    return bls.Aggregate(signatures)


def sign_attestation(spec, state, attestation):
    participants = spec.get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits)
    attestation.signature = sign_aggregate_attestation(
        spec, state, attestation.data, sorted(participants))


def get_valid_attestation(spec, state, slot=None, index=None,
                          filter_participant_set=None, signed=False):
    """A valid (optionally signed) full-committee attestation for `slot`."""
    if slot is None:
        slot = state.slot
    if index is None:
        index = 0
    slot = spec.Slot(slot)
    index = spec.CommitteeIndex(index)

    attestation_data = build_attestation_data(spec, state, slot=slot, index=index)
    committee = spec.get_beacon_committee(state, attestation_data.slot, attestation_data.index)
    committee_size = len(committee)
    participants = set(committee)
    if filter_participant_set is not None:
        participants = filter_participant_set(participants)

    aggregation_bits = spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](
        *([0b0] * committee_size))
    for i, validator_index in enumerate(committee):
        if validator_index in participants:
            aggregation_bits[i] = True

    attestation = spec.Attestation(
        aggregation_bits=aggregation_bits,
        data=attestation_data,
    )
    if signed and participants:
        sign_attestation(spec, state, attestation)
    return attestation


def get_valid_attestations_at_slot(spec, state, slot, participation_fn=None, signed=False):
    """One attestation per committee at `slot`."""
    committees_per_slot = spec.get_committee_count_per_slot(
        state, spec.compute_epoch_at_slot(slot))
    return [
        get_valid_attestation(
            spec, state, slot=slot, index=index,
            filter_participant_set=participation_fn, signed=signed,
        )
        for index in range(committees_per_slot)
    ]


def state_transition_with_full_block(spec, state, fill_cur_epoch, fill_prev_epoch,
                                     participation_fn=None, signed=None):
    """Build, apply, and return a signed block carrying the attestations the
    caller asked for (reference parity: attestations.py's same-named helper).

    signed=None follows the ambient BLS switch: when real signature checks
    are on (generator mode), unsigned attestations would fail
    is_valid_indexed_attestation inside process_attestation."""
    if signed is None:
        signed = bls.bls_active
    block = build_empty_block_for_next_slot(spec, state)
    if fill_cur_epoch and state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot_to_attest = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
        if slot_to_attest >= spec.compute_start_slot_at_epoch(spec.get_current_epoch(state)):
            for attestation in get_valid_attestations_at_slot(
                    spec, state, slot_to_attest, participation_fn, signed=signed):
                block.body.attestations.append(attestation)
    if fill_prev_epoch and state.slot >= spec.SLOTS_PER_EPOCH:
        slot_to_attest = state.slot - spec.SLOTS_PER_EPOCH + 1
        for attestation in get_valid_attestations_at_slot(
                spec, state, slot_to_attest, participation_fn, signed=signed):
            block.body.attestations.append(attestation)
    return state_transition_and_sign_block(spec, state, block)


def next_epoch_with_attestations(spec, state, fill_cur_epoch, fill_prev_epoch,
                                 participation_fn=None):
    """Advance one epoch via blocks full of attestations.
    Returns (pre_state, signed_blocks, post_state)."""
    assert state.slot % spec.SLOTS_PER_EPOCH == 0
    pre_state = state.copy()
    signed_blocks = []
    for _ in range(int(spec.SLOTS_PER_EPOCH)):
        signed_blocks.append(state_transition_with_full_block(
            spec, state, fill_cur_epoch, fill_prev_epoch, participation_fn))
    return pre_state, signed_blocks, state


def add_attestations_for_epoch(spec, state, epoch):
    """Synthesize full-participation PendingAttestations for every committee
    of `epoch` directly into the state (fast path for epoch-processing tests)."""
    start_slot = spec.compute_start_slot_at_epoch(epoch)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    is_current = epoch == spec.get_current_epoch(state)
    target_list = state.current_epoch_attestations if is_current else state.previous_epoch_attestations
    source = (state.current_justified_checkpoint if is_current
              else state.previous_justified_checkpoint)
    for slot in range(int(start_slot), min(int(start_slot) + int(spec.SLOTS_PER_EPOCH), int(state.slot))):
        for index in range(int(committees_per_slot)):
            committee = spec.get_beacon_committee(
                state, spec.Slot(slot), spec.CommitteeIndex(index))
            data = spec.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=spec.get_block_root_at_slot(state, spec.Slot(slot)),
                source=source,
                target=spec.Checkpoint(epoch=epoch, root=spec.get_block_root(state, epoch)),
            )
            target_list.append(spec.PendingAttestation(
                aggregation_bits=[True] * len(committee),
                data=data,
                inclusion_delay=1,
                proposer_index=spec.get_beacon_proposer_index(state),
            ))


def sign_indexed_attestation(spec, state, indexed_attestation):
    """Re-sign an IndexedAttestation after its data/indices were edited."""
    participants = [int(i) for i in indexed_attestation.attesting_indices]
    indexed_attestation.signature = sign_aggregate_attestation(
        spec, state, indexed_attestation.data, participants)
