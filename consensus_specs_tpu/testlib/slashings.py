"""Slashing scenario builders.

Reference parity: test/helpers/proposer_slashings.py and
attester_slashings.py — equivocating header pairs and double-vote indexed
attestation pairs, signed with the deterministic test keys.
"""
from ..crypto import bls
from .attestations import get_valid_attestation, sign_attestation
from .keys import privkeys


def sign_block_header(spec, state, header, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(header.slot))
    signing_root = spec.compute_signing_root(header, domain)
    return spec.SignedBeaconBlockHeader(message=header, signature=bls.Sign(privkey, signing_root))


def build_proposer_slashing(spec, state, proposer_index=None, signed=True):
    """Two distinct headers for the same (slot, proposer) — equivocation."""
    if proposer_index is None:
        proposer_index = spec.get_beacon_proposer_index(state)
    header_1 = spec.BeaconBlockHeader(
        slot=state.slot,
        proposer_index=proposer_index,
        parent_root=spec.Root(b"\x33" * 32),
        state_root=spec.Root(b"\x44" * 32),
        body_root=spec.Root(b"\x55" * 32),
    )
    header_2 = header_1.copy()
    header_2.parent_root = spec.Root(b"\x99" * 32)
    privkey = privkeys[int(proposer_index)]
    if signed:
        signed_1 = sign_block_header(spec, state, header_1, privkey)
        signed_2 = sign_block_header(spec, state, header_2, privkey)
    else:
        signed_1 = spec.SignedBeaconBlockHeader(message=header_1)
        signed_2 = spec.SignedBeaconBlockHeader(message=header_2)
    return spec.ProposerSlashing(signed_header_1=signed_1, signed_header_2=signed_2)


def build_attester_slashing(spec, state, slot=None, signed=True):
    """Two attestations by the same committee for the same target epoch with
    different data — a double vote (is_slashable_attestation_data rule 1)."""
    att_1 = get_valid_attestation(spec, state, slot=slot, signed=signed)
    att_2 = att_1.copy()
    att_2.data.beacon_block_root = spec.Root(b"\x66" * 32)
    if signed:
        sign_attestation(spec, state, att_2)
    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, att_1),
        attestation_2=spec.get_indexed_attestation(state, att_2),
    )
