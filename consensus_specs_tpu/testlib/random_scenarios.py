"""Randomized-scenario building blocks.

Reference parity: test/utils/randomized_block_tests.py (randomize_state :52,
transition_to_leaking, random_block_*) — the vocabulary the random-test
codegen (generators/random/generate.py) composes into checked-in test files.
Deterministic per (seed): every randomness source is an explicit
random.Random so generated vectors are reproducible.
"""
from random import Random

from .attestations import get_valid_attestation
from .block import build_empty_block_for_next_slot, state_transition_and_sign_block
from .state import next_epoch, next_slots


def randomize_balances(spec, state, rng: Random):
    for i in range(len(state.balances)):
        roll = rng.random()
        if roll < 0.1:
            state.balances[i] = spec.Gwei(0)
        elif roll < 0.3:
            state.balances[i] = spec.Gwei(int(spec.config.EJECTION_BALANCE))
        elif roll < 0.5:
            state.balances[i] = spec.Gwei(rng.randrange(int(spec.MAX_EFFECTIVE_BALANCE)))


def randomize_validator_flags(spec, state, rng: Random):
    current = int(spec.get_current_epoch(state))
    for v in state.validators:
        roll = rng.random()
        if roll < 0.1:
            v.slashed = True
        elif roll < 0.2 and current > 0:
            v.exit_epoch = spec.Epoch(current + rng.randrange(1, 8))


def randomize_state(spec, state, rng: Random):
    randomize_balances(spec, state, rng)
    randomize_validator_flags(spec, state, rng)
    spec.process_effective_balance_updates(state)


def transition_to_leaking(spec, state):
    """Advance past MIN_EPOCHS_TO_INACTIVITY_PENALTY without participation."""
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)


def random_slot_skips(spec, state, rng: Random):
    next_slots(spec, state, rng.randrange(1, int(spec.SLOTS_PER_EPOCH)))


def _advance_to_unslashed_proposer(spec, state):
    """Randomization may slash upcoming proposers; a slashed proposer makes
    every block at that slot invalid (process_block_header `assert not
    proposer.slashed`), so hop slots until a buildable one (bounded)."""
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        probe = state.copy()
        spec.process_slots(probe, probe.slot + 1)
        if not probe.validators[spec.get_beacon_proposer_index(probe)].slashed:
            return
        next_slots(spec, state, 1)
    raise AssertionError("no unslashed proposer found in two epochs")


def random_block(spec, state, rng: Random):
    """An empty-ish block with a random sprinkle of valid attestations."""
    _advance_to_unslashed_proposer(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    if int(state.slot) > int(spec.MIN_ATTESTATION_INCLUSION_DELAY):
        target = int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        for _ in range(rng.randrange(0, 2)):
            try:
                att = get_valid_attestation(spec, state, slot=spec.Slot(target), signed=True)
                block.body.attestations.append(att)
            except Exception:
                break
    return block


def run_random_scenario(spec, state, *, seed, leak=False, skips=True, blocks=2,
                        epoch_boundary=False):
    """One composed scenario; yields the sanity-blocks vector parts.

    epoch_boundary: hop to the last slot of the epoch before the final block
    so it crosses process_epoch with the randomized registry."""
    rng = Random(seed)
    randomize_state(spec, state, rng)
    if leak:
        transition_to_leaking(spec, state)
    if skips:
        random_slot_skips(spec, state, rng)
    yield "pre", state.copy()
    signed = []
    for i in range(blocks):
        if epoch_boundary and i == blocks - 1:
            per_epoch = int(spec.SLOTS_PER_EPOCH)
            to_boundary = per_epoch - 1 - (int(state.slot) % per_epoch)
            if to_boundary:
                next_slots(spec, state, to_boundary)
        block = random_block(spec, state, rng)
        signed.append(state_transition_and_sign_block(spec, state, block))
    yield "meta", "meta", {"blocks_count": len(signed)}
    for i, s in enumerate(signed):
        yield f"blocks_{i}", s
    yield "post", state.copy()
