"""Randomized-scenario building blocks.

Reference parity: test/utils/randomized_block_tests.py (randomize_state :52,
transition_to_leaking, random_block_*) — the vocabulary the random-test
codegen (generators/random/generate.py) composes into checked-in test files.
Deterministic per (seed): every randomness source is an explicit
random.Random so generated vectors are reproducible.
"""
from random import Random

from .attestations import get_valid_attestation
from .block import build_empty_block_for_next_slot, state_transition_and_sign_block
from .state import next_epoch, next_slots


def randomize_balances(spec, state, rng: Random):
    for i in range(len(state.balances)):
        roll = rng.random()
        if roll < 0.1:
            state.balances[i] = spec.Gwei(0)
        elif roll < 0.3:
            state.balances[i] = spec.Gwei(int(spec.config.EJECTION_BALANCE))
        elif roll < 0.5:
            state.balances[i] = spec.Gwei(rng.randrange(int(spec.MAX_EFFECTIVE_BALANCE)))


def randomize_validator_flags(spec, state, rng: Random):
    current = int(spec.get_current_epoch(state))
    for v in state.validators:
        roll = rng.random()
        if roll < 0.1:
            v.slashed = True
        elif roll < 0.2 and current > 0:
            v.exit_epoch = spec.Epoch(current + rng.randrange(1, 8))


def randomize_state(spec, state, rng: Random):
    randomize_balances(spec, state, rng)
    randomize_validator_flags(spec, state, rng)
    spec.process_effective_balance_updates(state)


def transition_to_leaking(spec, state):
    """Advance past MIN_EPOCHS_TO_INACTIVITY_PENALTY without participation."""
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)


def random_slot_skips(spec, state, rng: Random):
    next_slots(spec, state, rng.randrange(1, int(spec.SLOTS_PER_EPOCH)))


def _advance_to_unslashed_proposer(spec, state):
    """Randomization may slash upcoming proposers; a slashed proposer makes
    every block at that slot invalid (process_block_header `assert not
    proposer.slashed`), so hop slots until a buildable one (bounded)."""
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        probe = state.copy()
        spec.process_slots(probe, probe.slot + 1)
        if not probe.validators[spec.get_beacon_proposer_index(probe)].slashed:
            return probe  # state advanced to the block's slot — reusable
        next_slots(spec, state, 1)
    raise AssertionError("no unslashed proposer found in two epochs")


def random_block(spec, state, rng: Random, with_ops: bool = False, deposit=None):
    """An empty-ish block with a random sprinkle of valid attestations and
    (with_ops) a random subset of other operations: deposits, proposer/
    attester slashings, and randomized sync-aggregate participation — the
    reference's randomized_block_tests block vocabulary
    (random_block_altair :180-220).

    `deposit` must be PRE-PLANNED by the scenario before its pre-state
    snapshot: building one installs a new eth1_data root/count on the
    state, an out-of-band mutation a vector replay cannot reproduce from
    blocks alone (caught by the conformance round-trip, r4)."""
    probe = _advance_to_unslashed_proposer(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    if deposit is not None:
        block.body.deposits.append(deposit)
    if int(state.slot) > int(spec.MIN_ATTESTATION_INCLUSION_DELAY):
        target = int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        for _ in range(rng.randrange(0, 2)):
            try:
                att = get_valid_attestation(spec, state, slot=spec.Slot(target), signed=True)
                block.body.attestations.append(att)
            except Exception:
                break
    if with_ops:
        slashed_in_block: set = set()
        if rng.random() < 0.4:
            from .slashings import build_proposer_slashing

            try:
                target_idx = _random_slashable_index(spec, state, rng)
                if target_idx is not None:
                    block.body.proposer_slashings.append(
                        build_proposer_slashing(spec, state, proposer_index=target_idx))
                    slashed_in_block.add(int(target_idx))
            except Exception:
                pass
        if rng.random() < 0.3:
            from .slashings import build_attester_slashing

            try:
                slashing = build_attester_slashing(spec, state)
                # viable only if someone remains slashABLE after the earlier
                # proposer slashing of this same block is applied
                # (process_operations handles proposer slashings first)
                if any(not state.validators[i].slashed
                       and int(i) not in slashed_in_block
                       for i in slashing.attestation_1.attesting_indices):
                    block.body.attester_slashings.append(slashing)
            except Exception:
                pass
        if hasattr(block.body, "sync_aggregate") and rng.random() < 0.6:
            from .sync_committee import build_sync_aggregate

            bits = [rng.random() < 0.8 for _ in range(int(spec.SYNC_COMMITTEE_SIZE))]
            try:
                # `probe` is already advanced to block.slot (proposer hunt)
                block.body.sync_aggregate = build_sync_aggregate(spec, probe, bits)
            except Exception:
                pass
    return block


def _random_slashable_index(spec, state, rng: Random):
    """A random index that is currently slashable (active, not slashed)."""
    epoch = spec.get_current_epoch(state)
    candidates = [
        i for i, v in enumerate(state.validators)
        if spec.is_slashable_validator(v, epoch)
    ]
    return rng.choice(candidates) if candidates else None


def run_random_scenario(spec, state, *, seed, leak=False, skips=True, blocks=2,
                        epoch_boundary=False, ops=False, heavy=False):
    """One composed scenario; yields the sanity-blocks vector parts.

    epoch_boundary: hop to the last slot of the epoch before the final block
    so it crosses process_epoch with the randomized registry.
    ops: blocks carry random deposits/slashings/sync participation too.
    heavy: additionally randomize participation flags/inactivity scores."""
    rng = Random(seed)
    randomize_state(spec, state, rng)
    if leak:
        transition_to_leaking(spec, state)
    if heavy:
        # AFTER the leak transition: each epoch rotation zeroes the
        # participation lists, so randomizing first would be inert
        randomize_participation(spec, state, rng)
    if skips:
        random_slot_skips(spec, state, rng)
    # Deposit planning BEFORE the pre snapshot: building a deposit installs
    # the new eth1_data root/count on the state, and a replay can only see
    # mutations that live in `pre` or are produced by the blocks themselves
    # — process_operations then REQUIRES the first block to carry it
    # (expected deposit count = eth1 count - deposit index).
    pending_deposit = None
    if ops and rng.random() < 0.5:
        from .deposits import build_deposit_for_index

        idx = rng.randrange(len(state.validators))
        amount = spec.Gwei(rng.randrange(1, int(spec.MAX_EFFECTIVE_BALANCE)))
        pending_deposit = build_deposit_for_index(spec, state, idx, amount=amount)
    yield "pre", state.copy()
    signed = []
    for i in range(blocks):
        if epoch_boundary and i == blocks - 1:
            per_epoch = int(spec.SLOTS_PER_EPOCH)
            to_boundary = per_epoch - 1 - (int(state.slot) % per_epoch)
            if to_boundary:
                next_slots(spec, state, to_boundary)
        block = random_block(
            spec, state, rng, with_ops=ops,
            deposit=pending_deposit if i == 0 else None)
        signed.append(state_transition_and_sign_block(spec, state, block))
    yield "meta", "meta", {"blocks_count": len(signed)}
    for i, s in enumerate(signed):
        yield f"blocks_{i}", s
    yield "post", state.copy()


def randomize_participation(spec, state, rng: Random):
    """Heavy-mode extra: randomized epoch-participation flags and inactivity
    scores (altair+ registries; phase0 keeps its attestation lists — the
    epoch engine's differential tests own that shape)."""
    if not hasattr(state, "previous_epoch_participation"):
        return
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = spec.ParticipationFlags(rng.randrange(0, 8))
        state.current_epoch_participation[i] = spec.ParticipationFlags(rng.randrange(0, 8))
        state.inactivity_scores[i] = spec.uint64(rng.randrange(0, 50))
