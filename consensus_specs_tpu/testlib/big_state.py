"""Registry-scale synthetic BeaconState builder.

Builds a structurally valid spec `BeaconState` with `n` validators fast
enough to benchmark at 1M (BASELINE.md configs 3/4: registry-scale epoch
processing and state-root hashing). Keys are deterministic fakes — state
hashing and epoch math don't verify them; scenarios needing real signatures
(testlib/attestations.py) sign per-committee with the shared test keypairs
instead.

Reference analog: the reference builds big states only through genesis
helpers (test/helpers/genesis.py), which is deposit-by-deposit and far too
slow past ~100k validators; this builder fills the state columns directly.
"""
from __future__ import annotations


def fake_pubkey(i: int) -> bytes:
    return b"\xaa" + i.to_bytes(8, "little") + b"\x00" * 39


def synthetic_beacon_state(spec, n: int, slot: int = 3200):
    """A `spec.BeaconState` with `n` active max-balance validators, filled
    historical vectors, and (post-phase0) participation/sync fields."""
    far_future = spec.FAR_FUTURE_EPOCH
    epoch = slot // spec.SLOTS_PER_EPOCH
    V = spec.Validator
    validators = [
        V(
            pubkey=fake_pubkey(i),
            withdrawal_credentials=bytes(spec.BLS_WITHDRAWAL_PREFIX) + i.to_bytes(31, "little"),
            effective_balance=spec.MAX_EFFECTIVE_BALANCE,
            activation_eligibility_epoch=0,
            activation_epoch=0,
            exit_epoch=far_future,
            withdrawable_epoch=far_future,
        )
        for i in range(n)
    ]
    state = spec.BeaconState(
        genesis_time=1_600_000_000,
        slot=slot,
        fork=spec.Fork(current_version=spec.config.GENESIS_FORK_VERSION),
        latest_block_header=spec.BeaconBlockHeader(slot=slot - 1),
        validators=validators,
        eth1_deposit_index=n,
        previous_justified_checkpoint=spec.Checkpoint(epoch=epoch - 2),
        current_justified_checkpoint=spec.Checkpoint(epoch=epoch - 1),
        finalized_checkpoint=spec.Checkpoint(epoch=epoch - 2),
    )
    state.balances = type(state.balances).from_values(
        [int(spec.MAX_EFFECTIVE_BALANCE)] * n)
    for i in range(len(state.block_roots)):
        state.block_roots[i] = spec.Root((i % 251 + 1).to_bytes(32, "little"))
        state.state_roots[i] = spec.Root((i % 241 + 1).to_bytes(32, "big"))
    for i in range(len(state.randao_mixes)):
        state.randao_mixes[i] = spec.Bytes32((i % 253 + 1).to_bytes(32, "little"))
    fields = spec.BeaconState.fields()
    if "previous_epoch_participation" in fields:  # altair+
        part_t = type(state.previous_epoch_participation)
        state.previous_epoch_participation = part_t.from_values([7] * n)
        state.current_epoch_participation = part_t.from_values([3] * n)
        state.inactivity_scores = type(state.inactivity_scores).from_values([0] * n)
        committee = spec.SyncCommittee(
            pubkeys=[fake_pubkey(i % n) for i in range(spec.SYNC_COMMITTEE_SIZE)],
            aggregate_pubkey=fake_pubkey(0),
        )
        state.current_sync_committee = committee
        state.next_sync_committee = committee.copy()
    if "previous_epoch_attestations" in fields:  # phase0
        pass  # left empty: pending attestations accumulate per block
    return state
