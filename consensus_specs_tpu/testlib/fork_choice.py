"""Step-yielding fork-choice scenario helpers.

Reference parity: test/helpers/fork_choice.py (:26-48 tick_and_add_block) —
drive one Store through scripted ticks/blocks/attestations while emitting
the steps.yaml entries + ssz parts the fork_choice vector format requires
(tests/formats/fork_choice: anchor_state, anchor_block, steps, per-object
block_<root>/attestation_<root> files, `checks` steps with head/time/
justified state).
"""


def get_genesis_forkchoice_store_and_block(spec, state):
    assert state.slot == spec.GENESIS_SLOT
    genesis_block = spec.BeaconBlock(state_root=spec.hash_tree_root(state))
    return spec.get_forkchoice_store(state, genesis_block), genesis_block


def initialize_steps(spec, state):
    """(store, anchor parts list, steps list) for a fresh scenario."""
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)
    parts = [("anchor_state", state.copy()), ("anchor_block", anchor_block)]
    return store, parts, []


def on_tick_step(spec, store, steps, time):
    spec.on_tick(store, int(time))
    steps.append({"tick": int(time)})


def tick_to_slot_step(spec, store, steps, slot):
    on_tick_step(spec, store, steps, store.genesis_time + int(slot) * int(spec.config.SECONDS_PER_SLOT))


def add_block_step(spec, store, parts, steps, signed_block, valid=True):
    root = spec.hash_tree_root(signed_block.message)
    name = f"block_{bytes(root).hex()[:16]}"
    parts.append((name, signed_block))
    step = {"block": name}
    if not valid:
        step["valid"] = False
        try:
            spec.on_block(store, signed_block)
        except AssertionError:
            steps.append(step)
            return None
        raise AssertionError("expected on_block to reject")
    spec.on_block(store, signed_block)
    # the reference's add_block also routes the block's attestations into the
    # fork choice (helpers/fork_choice.py:143) — this is what materializes
    # checkpoint states for targets justified purely via blocks. Routing is
    # best-effort, also per the reference: a block may legitimately carry
    # attestations the STORE rejects (e.g. targets behind a fresh store's
    # anchor after a fork handoff) while the state transition accepts them.
    for attestation in signed_block.message.body.attestations:
        try:
            spec.on_attestation(store, attestation, is_from_block=True)
        except AssertionError:
            pass
    steps.append(step)
    return root


def add_attestation_step(spec, store, parts, steps, attestation, valid=True):
    root = spec.hash_tree_root(attestation)
    name = f"attestation_{bytes(root).hex()[:16]}"
    parts.append((name, attestation))
    step = {"attestation": name}
    if not valid:
        step["valid"] = False
        try:
            spec.on_attestation(store, attestation)
        except AssertionError:
            steps.append(step)
            return
        raise AssertionError("expected on_attestation to reject")
    spec.on_attestation(store, attestation)
    steps.append(step)


def checks_snapshot(spec, store):
    """(head_root, checks dict) for the store's current observable state —
    the fork_choice vector format's `checks` payload. Shared by the step
    helpers below and the scenario lanes (scenarios/lanes.py), which
    assert THIS dict bit-identical across replay paths."""
    head = spec.get_head(store)
    return head, {
        "time": int(store.time),
        "head": {
            "slot": int(store.blocks[head].slot),
            "root": "0x" + bytes(head).hex(),
        },
        "justified_checkpoint": {
            "epoch": int(store.justified_checkpoint.epoch),
            "root": "0x" + bytes(store.justified_checkpoint.root).hex(),
        },
        "finalized_checkpoint": {
            "epoch": int(store.finalized_checkpoint.epoch),
            "root": "0x" + bytes(store.finalized_checkpoint.root).hex(),
        },
        "proposer_boost_root": "0x" + bytes(store.proposer_boost_root).hex(),
    }


def add_checks_step(spec, store, steps):
    head, checks = checks_snapshot(spec, store)
    steps.append({"checks": checks})
    return head


def add_pow_block_step(parts, steps, pow_block):
    """Install a synthetic PoW block into the scenario (reference
    tests/formats/fork_choice `on_pow_block` step: consumers feed it to
    their get_pow_block view before the dependent beacon block arrives)."""
    name = f"pow_block_{bytes(pow_block.block_hash).hex()[:16]}"
    parts.append((name, pow_block))
    steps.append({"pow_block": name})


def finalize_steps(parts, steps):
    """Order: anchor parts, object parts, then steps.yaml last."""
    return parts + [("steps", "data", steps)]


# --- pure store-update helpers ---------------------------------------------
# The spec's on_attestation filtering and ancestor walk, extracted as pure
# functions over plain mappings so the fork-choice lane (forkchoice/) can
# reuse the exact reference semantics instead of copy-pasting them. The
# step helpers above still drive the compiled spec directly, so vector
# output is untouched.


def latest_message_updates(latest_messages, attesting_indices, target_epoch):
    """Pure twin of the spec's `update_latest_messages` admission filter
    (phase0/fork-choice.md): of `attesting_indices`, the indices whose
    latest message a new vote at `target_epoch` replaces — unseen
    validators, or ones whose recorded message is from a strictly earlier
    epoch. `latest_messages` maps index -> object with an `.epoch`
    attribute (the spec's LatestMessage, or any namedtuple twin)."""
    target_epoch = int(target_epoch)
    return [i for i in attesting_indices
            if i not in latest_messages
            or target_epoch > int(latest_messages[i].epoch)]


def ancestor_at_slot(blocks, root, slot):
    """Pure twin of the spec's `get_ancestor` over any {root: block-like}
    mapping (block-like = has `.slot` and `.parent_root`): walk parent
    pointers while the block sits above `slot`; at or below it, the
    current root is its own ancestor. Iterative where the spec recurses —
    thousand-slot scenario chains would overflow Python's stack — and a
    parent outside the mapping (or a self-parented anchor) terminates at
    the current root where the spec would KeyError, which is what the
    anchored/padded fork-choice mirrors rely on."""
    slot = int(slot)
    block = blocks[root]
    while int(block.slot) > slot:
        parent = block.parent_root
        if parent == root or parent not in blocks:
            return root
        root = parent
        block = blocks[root]
    return root
