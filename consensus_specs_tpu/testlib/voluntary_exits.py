"""Voluntary-exit scenario builders (reference parity: test/helpers/
voluntary_exits.py)."""
from __future__ import annotations

from ..crypto import bls
from .keys import privkeys


def build_voluntary_exit(spec, state, index, epoch=None):
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state) if epoch is None else epoch,
        validator_index=index,
    )
    domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch)
    signing_root = spec.compute_signing_root(exit_msg, domain)
    return spec.SignedVoluntaryExit(
        message=exit_msg, signature=bls.Sign(privkeys[index], signing_root)
    )


_aged_cache: dict = {}


def age_state_past_shard_committee_period(spec, state):
    """Advance so validators satisfy the exit-eligibility age gate.

    The SHARD_COMMITTEE_PERIOD-epoch advance is deterministic per starting
    state, so it runs once per (fork, preset, pre-root) and later callers
    get the cached result copied in — every voluntary-exit test was paying
    ~10s of identical epoch transitions (VERDICT r2 item 7)."""
    from ..ssz import hash_tree_root

    # config must join the key: with_config_overrides builds specs sharing
    # fork/preset whose SHARD_COMMITTEE_PERIOD (and thus aging depth) differs
    # while the pre-state root is identical
    key = (spec.fork, spec.preset_name,
           int(spec.config.SHARD_COMMITTEE_PERIOD), bytes(hash_tree_root(state)))
    aged = _aged_cache.get(key)
    if aged is None:
        epochs = int(spec.config.SHARD_COMMITTEE_PERIOD)
        spec.process_slots(state, state.slot + epochs * spec.SLOTS_PER_EPOCH)
        _aged_cache[key] = state.copy()
        return
    fresh = aged.copy()
    for name in state.fields():
        setattr(state, name, getattr(fresh, name))
