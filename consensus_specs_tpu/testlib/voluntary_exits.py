"""Voluntary-exit scenario builders (reference parity: test/helpers/
voluntary_exits.py)."""
from __future__ import annotations

from ..crypto import bls
from .keys import privkeys


def build_voluntary_exit(spec, state, index, epoch=None):
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state) if epoch is None else epoch,
        validator_index=index,
    )
    domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch)
    signing_root = spec.compute_signing_root(exit_msg, domain)
    return spec.SignedVoluntaryExit(
        message=exit_msg, signature=bls.Sign(privkeys[index], signing_root)
    )


def age_state_past_shard_committee_period(spec, state):
    """Advance so validators satisfy the exit-eligibility age gate."""
    epochs = int(spec.config.SHARD_COMMITTEE_PERIOD)
    spec.process_slots(state, state.slot + epochs * spec.SLOTS_PER_EPOCH)
