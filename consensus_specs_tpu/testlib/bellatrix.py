"""Bellatrix (merge) scenario helpers.

Reference parity: test/helpers/execution_payload.py + the merge-transition
setup the reference's bellatrix suites do inline."""
from __future__ import annotations



def complete_merge_transition(spec, state):
    """Put `state` past the merge: install a non-empty latest execution
    payload header so is_merge_transition_complete(state) is True."""
    header = spec.ExecutionPayloadHeader(
        block_hash=spec.Hash32(b"\x61" * 32),
        parent_hash=spec.Hash32(b"\x60" * 32),
        block_number=1,
        gas_limit=30_000_000,
        timestamp=spec.compute_timestamp_at_slot(state, state.slot),
        random=spec.get_randao_mix(state, spec.get_current_epoch(state)),
        base_fee_per_gas=spec.uint256(7),
    )
    state.latest_execution_payload_header = header
    assert spec.is_merge_transition_complete(state)
    return header
