"""Custody-game scenario builders.

Reference parity: the role test/helpers/custody.py plays for the reference's
custody_game suite (key reveals, early derived secret reveals, chunk
challenge/response payloads, custody slashings), rebuilt against this
framework's executable custody overlay (specs/custody_game/beacon-chain.md),
whose challenges link to `ShardBlobHeader`s instead of the reference's
retired `ShardTransition`.
"""
from __future__ import annotations

from ..crypto import bls
from ..ssz import hash_tree_root
from ..ssz.merkle import merkleize_chunks, mix_in_length
from .keys import pubkey_to_privkey


def custody_reveal_signature(spec, state, revealer_index, period=None):
    """A validator's key reveal for `period` (default: the one currently owed)."""
    revealer = state.validators[revealer_index]
    if period is None:
        period = revealer.next_custody_secret_to_reveal
    epoch_to_sign = spec.get_randao_epoch_for_custody_period(period, revealer_index)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch_to_sign)
    signing_root = spec.compute_signing_root(spec.Epoch(epoch_to_sign), domain)
    return bls.Sign(pubkey_to_privkey(bytes(revealer.pubkey)), signing_root)


def get_valid_custody_key_reveal(spec, state, revealer_index=0, period=None):
    return spec.CustodyKeyReveal(
        revealer_index=revealer_index,
        reveal=custody_reveal_signature(spec, state, revealer_index, period),
    )


def get_valid_early_derived_secret_reveal(spec, state, revealed_index=0,
                                          masker_index=None, epoch=None):
    """Masked early reveal: aggregate of (revealed validator's signature over
    the epoch, masker's signature over the mask)."""
    if masker_index is None:
        masker_index = (revealed_index + 1) % len(state.validators)
    current_epoch = spec.get_current_epoch(state)
    if epoch is None:
        epoch = spec.Epoch(current_epoch + spec.CUSTODY_PERIOD_TO_RANDAO_PADDING)
    mask = spec.hash(spec.uint_to_bytes(spec.Epoch(epoch)))
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    reveal_root = spec.compute_signing_root(spec.Epoch(epoch), domain)
    mask_root = spec.compute_signing_root(spec.Bytes32(mask), domain)
    if bls.bls_active:
        signature = bls.Aggregate([
            bls.Sign(pubkey_to_privkey(bytes(state.validators[revealed_index].pubkey)), reveal_root),
            bls.Sign(pubkey_to_privkey(bytes(state.validators[masker_index].pubkey)), mask_root),
        ])
    else:
        signature = bls.STUB_SIGNATURE
    return spec.EarlyDerivedSecretReveal(
        revealed_index=revealed_index,
        epoch=epoch,
        reveal=signature,
        masker_index=masker_index,
        mask=mask,
    )


def data_chunk_bytes(spec, points, chunk_index):
    """The `chunk_index`-th BYTES_PER_CUSTODY_CHUNK window of the blob's
    serialized points (zero-padded past the data end)."""
    raw = b"".join(int(p).to_bytes(32, "little") for p in points)
    start = chunk_index * spec.BYTES_PER_CUSTODY_CHUNK
    window = raw[start:start + spec.BYTES_PER_CUSTODY_CHUNK]
    return window + b"\x00" * (spec.BYTES_PER_CUSTODY_CHUNK - len(window))


def build_chunk_branch(spec, points, chunk_index):
    """Merkle branch from the chunk's subtree root to the data list's root
    (CUSTODY_RESPONSE_DEPTH siblings + the length mix-in chunk)."""
    limit_points = spec.POINTS_PER_SAMPLE * spec.MAX_SAMPLES_PER_BLOB
    per_chunk = spec.POINTS_PER_CUSTODY_CHUNK
    n_chunk_slots = limit_points // per_chunk
    # subtree root per custody chunk across the whole (padded) limit
    chunk_roots = []
    for j in range(n_chunk_slots):
        window = points[j * per_chunk:(j + 1) * per_chunk]
        leaves = [int(p).to_bytes(32, "little") for p in window]
        leaves += [b"\x00" * 32] * (per_chunk - len(leaves))
        chunk_roots.append(merkleize_chunks(leaves))
    # branch within the chunk-root tree
    branch = []
    nodes = chunk_roots
    idx = chunk_index
    for _ in range(spec.CUSTODY_RESPONSE_DEPTH):
        branch.append(nodes[idx ^ 1])
        nodes = [spec.hash(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
        idx //= 2
    # length mix-in sibling
    branch.append(len(points).to_bytes(32, "little"))
    root = mix_in_length(nodes[0], len(points))
    assert root == hash_tree_root(
        spec.List[spec.BLSPoint, limit_points](points)), "branch construction out of sync"
    return branch


def get_valid_chunk_challenge(spec, state, attestation, header, responder_index=None,
                              chunk_index=0):
    if responder_index is None:
        attesters = spec.get_attesting_indices(
            state, attestation.data, attestation.aggregation_bits)
        responder_index = min(attesters)
    return spec.CustodyChunkChallenge(
        responder_index=responder_index,
        attestation=attestation,
        header=header,
        chunk_index=chunk_index,
    )


def get_valid_chunk_response(spec, state, challenge_record, points, chunk_index=None):
    if chunk_index is None:
        chunk_index = int(challenge_record.chunk_index)
    return spec.CustodyChunkResponse(
        challenge_index=challenge_record.challenge_index,
        chunk_index=chunk_index,
        chunk=data_chunk_bytes(spec, points, chunk_index),
        branch=build_chunk_branch(spec, points, chunk_index),
    )


def get_custody_slashing(spec, state, attestation, header, points, malefactor_index,
                         whistleblower_index, malefactor_secret=None):
    if malefactor_secret is None:
        # the malefactor's custody key for the attestation's period
        period = spec.get_custody_period_for_validator(
            malefactor_index, attestation.data.target.epoch)
        malefactor_secret = custody_reveal_signature(spec, state, malefactor_index, period)
    slashing = spec.CustodySlashing(
        malefactor_index=malefactor_index,
        malefactor_secret=malefactor_secret,
        whistleblower_index=whistleblower_index,
        attestation=attestation,
        header=header,
        data=points,
    )
    domain = spec.get_domain(state, spec.DOMAIN_CUSTODY_BIT_SLASHING, spec.get_current_epoch(state))
    signing_root = spec.compute_signing_root(slashing, domain)
    signature = bls.Sign(
        pubkey_to_privkey(bytes(state.validators[whistleblower_index].pubkey)), signing_root)
    return spec.SignedCustodySlashing(message=slashing, signature=signature)
