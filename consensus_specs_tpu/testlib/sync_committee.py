"""Sync-committee scenario builders (reference parity: test/helpers/sync_committee.py)."""
from __future__ import annotations

from .keys import pubkey_to_privkey
from ..crypto import bls


def compute_sync_committee_signature(spec, state, slot, privkey, block_root=None):
    domain = spec.get_domain(state, spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(slot))
    if block_root is None:
        if slot == state.slot:
            block_root = spec.hash_tree_root(state.latest_block_header)
        else:
            block_root = spec.get_block_root_at_slot(state, slot)
    signing_root = spec.compute_signing_root(spec.Root(block_root), domain)
    return bls.Sign(privkey, signing_root)


def compute_aggregate_sync_committee_signature(spec, state, slot, participants, block_root=None):
    if len(participants) == 0:
        return spec.G2_POINT_AT_INFINITY
    signatures = [
        compute_sync_committee_signature(
            spec, state, slot,
            pubkey_to_privkey(state.validators[participant].pubkey),
            block_root=block_root,
        )
        for participant in participants
    ]
    if not bls.bls_active:
        return bls.STUB_SIGNATURE
    return bls.Aggregate(signatures)


def get_committee_indices(spec, state):
    """Validator indices of the current sync committee, in committee order."""
    all_pubkeys = [v.pubkey for v in state.validators]
    return [
        spec.ValidatorIndex(all_pubkeys.index(pubkey))
        for pubkey in state.current_sync_committee.pubkeys
    ]


def build_sync_aggregate(spec, state, participation=None, slot=None):
    """SyncAggregate over the previous slot's block root with the given
    per-member participation bools (default: full participation)."""
    if participation is None:
        participation = [True] * int(spec.SYNC_COMMITTEE_SIZE)
    if slot is None:
        slot = state.slot
    committee_indices = get_committee_indices(spec, state)
    participants = [idx for idx, bit in zip(committee_indices, participation) if bit]
    previous_slot = max(int(slot), 1) - 1
    signature = compute_aggregate_sync_committee_signature(
        spec, state, spec.Slot(previous_slot), participants)
    return spec.SyncAggregate(
        sync_committee_bits=participation,
        sync_committee_signature=signature,
    )
