"""Deposit scenario builders.

Reference parity: test/helpers/deposits.py — construct signed DepositData,
accumulate leaves in the incremental contract tree
(utils/deposit_tree.DepositTree), and emit (Deposit, root) pairs whose
depth-33 proofs satisfy process_deposit / initialize_beacon_state_from_eth1.
"""
from ..crypto import bls
from ..utils.deposit_tree import DepositTree
from .keys import get_pubkeys, privkeys


def build_deposit_data(spec, pubkey, privkey, amount, withdrawal_credentials, signed=True):
    data = spec.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=amount,
    )
    if signed:
        msg = spec.DepositMessage(
            pubkey=pubkey, withdrawal_credentials=withdrawal_credentials, amount=amount
        )
        domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
        signing_root = spec.compute_signing_root(msg, domain)
        data.signature = bls.Sign(privkey, bytes(signing_root))
    return data


def default_withdrawal_credentials(spec, validator_index: int) -> bytes:
    return bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(get_pubkeys()[validator_index])[1:]


def prepare_genesis_deposits(spec, count, amount=None, signed=True):
    """count signed deposits with *progressive* proofs: deposit i's branch
    verifies against the tree holding leaves 0..i — the root sequence
    initialize_beacon_state_from_eth1 recomputes per deposit
    (specs/phase0/beacon-chain.md genesis loop)."""
    amount = amount if amount is not None else spec.MAX_EFFECTIVE_BALANCE
    tree = DepositTree()
    deposits = []
    for i in range(count):
        data = build_deposit_data(
            spec,
            get_pubkeys()[i],
            privkeys[i],
            amount,
            default_withdrawal_credentials(spec, i),
            signed=signed,
        )
        tree.push(bytes(spec.hash_tree_root(data)))
        deposits.append(
            spec.Deposit(proof=[spec.Bytes32(b) for b in tree.proof(i)], data=data)
        )
    return deposits, spec.Root(tree.root())


def build_deposit_for_index(spec, state, validator_index, amount=None, signed=True,
                            withdrawal_credentials=None):
    """One post-genesis deposit appended to a tree seeded with the state's
    existing deposit count (top-up when validator_index exists)."""
    amount = amount if amount is not None else spec.MAX_EFFECTIVE_BALANCE
    tree = DepositTree()
    # replay placeholder leaves for already-consumed deposits so the index
    # and proof line up with state.eth1_deposit_index
    for i in range(int(state.eth1_deposit_index)):
        tree.push(bytes(spec.hash_tree_root(spec.DepositData())))
    if withdrawal_credentials is None:
        withdrawal_credentials = default_withdrawal_credentials(spec, validator_index)
    data = build_deposit_data(
        spec,
        get_pubkeys()[validator_index],
        privkeys[validator_index],
        amount,
        withdrawal_credentials,
        signed=signed,
    )
    index = tree.deposit_count
    tree.push(bytes(spec.hash_tree_root(data)))
    deposit = spec.Deposit(proof=[spec.Bytes32(b) for b in tree.proof(index)], data=data)
    state.eth1_data.deposit_root = spec.Root(tree.root())
    state.eth1_data.deposit_count = tree.deposit_count
    return deposit
