"""Genesis-state factory for tests.

Reference parity: helpers/genesis.py create_genesis_state (:42) — builds a
valid post-genesis BeaconState directly (without replaying deposit proofs),
with deterministic keypairs and full effective balances activated at genesis.
"""
from __future__ import annotations

from .keys import get_pubkeys


def build_mock_validator(spec, i: int, balance: int):
    pubkey = get_pubkeys()[i]
    withdrawal_credentials = (
        bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pubkey)[1:]
    )
    validator = spec.Validator(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=min(
            balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
            spec.MAX_EFFECTIVE_BALANCE,
        ),
    )
    if hasattr(spec, "get_custody_period_for_validator"):
        # custody_game fork: genesis validators owe from period 0 and have
        # revealed nothing (custody_game/beacon-chain.md deposit init).
        validator.next_custody_secret_to_reveal = spec.get_custody_period_for_validator(
            spec.ValidatorIndex(i), spec.Epoch(0))
        validator.all_custody_secrets_revealed_epoch = spec.FAR_FUTURE_EPOCH
    return validator


def create_genesis_state(spec, validator_balances, activation_threshold=None):
    if activation_threshold is None:
        activation_threshold = spec.MAX_EFFECTIVE_BALANCE
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    # the state's Fork must carry the real per-fork versions: every signing
    # domain derives from it (reference helpers/genesis.py:26-41 sets the
    # same pairs; a zeroed Fork self-verifies but diverges from reference
    # genesis states and breaks cross-fork upgrade invariants)
    from ..compiler.spec_compiler import PREVIOUS_FORK

    def fork_version(fork_name):
        # convention: <FORK>_FORK_VERSION config key; phase0 = GENESIS
        if fork_name is None or fork_name == "phase0":
            return spec.config.GENESIS_FORK_VERSION
        return getattr(spec.config, f"{fork_name.upper()}_FORK_VERSION", None)

    current = fork_version(spec.fork)
    previous = fork_version(PREVIOUS_FORK.get(spec.fork))
    if current is None:
        # fork without a configured version (sharding-era R&D): keep the
        # pair COHERENT by walking back to the newest configured ancestor
        walk = spec.fork
        while current is None and walk is not None:
            walk = PREVIOUS_FORK.get(walk)
            current = fork_version(walk)
        previous = fork_version(PREVIOUS_FORK.get(walk)) or current
    elif previous is None:
        previous = current
    state = spec.BeaconState(
        genesis_time=spec.config.MIN_GENESIS_TIME,
        fork=spec.Fork(
            previous_version=previous,
            current_version=current,
            epoch=spec.GENESIS_EPOCH,
        ),
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())
        ),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )

    for i, balance in enumerate(validator_balances):
        validator = build_mock_validator(spec, i, balance)
        state.validators.append(validator)
        state.balances.append(balance)
        if validator.effective_balance >= activation_threshold:
            validator.activation_eligibility_epoch = spec.GENESIS_EPOCH
            validator.activation_epoch = spec.GENESIS_EPOCH

    state.genesis_validators_root = spec.hash_tree_root(state.validators)

    if spec.fork != "phase0":
        # Altair+: fill participation/inactivity and the first sync committees.
        state.previous_epoch_participation = [
            spec.ParticipationFlags(0) for _ in validator_balances
        ]
        state.current_epoch_participation = [
            spec.ParticipationFlags(0) for _ in validator_balances
        ]
        state.inactivity_scores = [spec.uint64(0) for _ in validator_balances]
        state.current_sync_committee = spec.get_next_sync_committee(state)
        state.next_sync_committee = spec.get_next_sync_committee(state)

    if spec.fork in ("bellatrix", "sharding", "custody_game"):
        state.latest_execution_payload_header = spec.ExecutionPayloadHeader()

    if hasattr(spec, "MIN_SAMPLE_PRICE"):
        # Sharding-era: the fee controller floors at MIN_SAMPLE_PRICE.
        state.shard_sample_price = spec.MIN_SAMPLE_PRICE

    return state


def create_valid_beacon_state(spec, num_validators=None):
    n = num_validators or spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    balances = [spec.MAX_EFFECTIVE_BALANCE] * n
    return create_genesis_state(spec, balances)
