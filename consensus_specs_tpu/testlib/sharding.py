"""Shard-blob scenario builders for the sharding fork overlay.

Reference parity: the role test/helpers/shard_block.py plays for the
reference's sharding tests — builder registration, signed blob headers, and
ring-buffer arming — rebuilt against this framework's executable sharding
spec (specs/sharding/beacon-chain.md).
"""
from __future__ import annotations

from ..crypto import bls, kzg_shim
from ..ssz import hash_tree_root
from .keys import NUM_KEYS, get_pubkeys, privkeys, pubkey_to_privkey


def builder_privkey(builder_slot: int) -> int:
    """Builders take keys from the top of the fixture range, clear of the
    validator registry (minimal worlds use 64..256 validators)."""
    return privkeys[NUM_KEYS - 1 - builder_slot]


def register_builder(spec, state, balance=None, key_slot=None):
    """Append a blob builder (+balance) to the registry; returns its index."""
    index = len(state.blob_builders)
    pubkey = get_pubkeys()[NUM_KEYS - 1 - (key_slot if key_slot is not None else index)]
    state.blob_builders.append(spec.Builder(pubkey=pubkey))
    state.blob_builder_balances.append(
        spec.Gwei(balance if balance is not None else spec.MAX_EFFECTIVE_BALANCE))
    return spec.BuilderIndex(index)


def make_blob_points(spec, samples_count: int, seed: int = 1):
    """Deterministic in-field scalar points for a blob of `samples_count`."""
    n = samples_count * spec.POINTS_PER_SAMPLE
    return [(seed * 0x9E3779B97F4A7C15 + i) % spec.MODULUS for i in range(n)]


def build_blob_body(spec, points, max_priority_fee_per_sample=0, max_fee_per_sample=None):
    """ShardBlobBody with a real (or stub-mode) commitment + degree proof."""
    samples_count = len(points) // spec.POINTS_PER_SAMPLE
    if max_fee_per_sample is None:
        max_fee_per_sample = spec.MIN_SAMPLE_PRICE
    commitment_point = kzg_shim.commit_to_data(points)
    degree_proof = kzg_shim.prove_degree_bound_bytes(points, len(points))
    return spec.ShardBlobBody(
        commitment=spec.DataCommitment(point=commitment_point, samples_count=samples_count),
        degree_proof=degree_proof,
        data=points,
        max_priority_fee_per_sample=max_priority_fee_per_sample,
        max_fee_per_sample=max_fee_per_sample,
    )


def body_to_summary(spec, body):
    return spec.ShardBlobBodySummary(
        commitment=body.commitment,
        degree_proof=body.degree_proof,
        data_root=hash_tree_root(body.data),
        max_priority_fee_per_sample=body.max_priority_fee_per_sample,
        max_fee_per_sample=body.max_fee_per_sample,
    )


def sign_shard_blob_header(spec, state, header, builder_index=None):
    """Joint builder+proposer signature (one FastAggregateVerify target)."""
    if not bls.bls_active:
        return bls.STUB_SIGNATURE
    signing_root = spec.compute_signing_root(
        header, spec.get_domain(state, spec.DOMAIN_SHARD_BLOB))
    builder_pk = state.blob_builders[header.builder_index].pubkey
    proposer_pk = state.validators[header.proposer_index].pubkey
    sigs = [
        bls.Sign(pubkey_to_privkey(bytes(builder_pk)), signing_root),
        bls.Sign(pubkey_to_privkey(bytes(proposer_pk)), signing_root),
    ]
    return bls.Aggregate(sigs)


def build_signed_shard_blob_header(spec, state, slot=None, shard=0, builder_index=0,
                                   samples_count=1, points=None,
                                   max_priority_fee_per_sample=0, max_fee_per_sample=None,
                                   valid_signature=True):
    """A SignedShardBlobHeader ready for process_shard_header at `state.slot`."""
    if slot is None:
        slot = state.slot
    if points is None:
        points = make_blob_points(spec, samples_count)
    body = build_blob_body(spec, points,
                           max_priority_fee_per_sample=max_priority_fee_per_sample,
                           max_fee_per_sample=max_fee_per_sample)
    header = spec.ShardBlobHeader(
        slot=slot,
        shard=shard,
        builder_index=builder_index,
        proposer_index=spec.get_shard_proposer_index(state, slot, shard),
        body_summary=body_to_summary(spec, body),
    )
    signature = sign_shard_blob_header(spec, state, header) if valid_signature \
        else spec.BLSSignature(b"\x42" * 96)
    return spec.SignedShardBlobHeader(message=header, signature=signature), body


def arm_shard_cells(spec, state, epoch=None):
    """Arm the ring-buffer cells for `epoch` (default: current) the way
    reset_pending_shard_work arms the next epoch — needed at genesis, where
    no epoch transition has run yet."""
    if epoch is None:
        epoch = spec.get_current_epoch(state)
    start_slot = spec.compute_start_slot_at_epoch(epoch)
    committees_per_slot = spec.get_committee_count_per_slot(state, epoch)
    active_shards = spec.get_active_shard_count(state, epoch)
    for slot in range(start_slot, start_slot + spec.SLOTS_PER_EPOCH):
        buffer_index = slot % spec.SHARD_STATE_MEMORY_SLOTS
        state.shard_buffer[buffer_index] = [spec.ShardWork() for _ in range(active_shards)]
        start_shard = spec.get_start_shard(state, slot)
        for committee_index in range(committees_per_slot):
            shard = (int(start_shard) + committee_index) % int(active_shards)
            committee_length = len(spec.get_beacon_committee(
                state, slot, spec.CommitteeIndex(committee_index)))
            pending_type = spec.List[spec.PendingShardHeader, spec.MAX_SHARD_HEADERS_PER_SHARD]
            state.shard_buffer[buffer_index][shard].status.change(
                selector=spec.SHARD_WORK_PENDING,
                value=pending_type(
                    spec.PendingShardHeader(
                        attested=spec.AttestedDataCommitment(),
                        votes=spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE]([0] * committee_length),
                        weight=0,
                        update_slot=slot,
                    )
                ),
            )


def committee_index_for_shard(spec, state, slot, shard):
    return spec.compute_committee_index_from_shard(state, slot, spec.Shard(shard))


def shard_for_committee_index(spec, state, slot, index=0):
    return spec.compute_shard_from_committee_index(state, slot, spec.CommitteeIndex(index))
