"""Host-side (pure-Python, jax-free) BLS12-381 scalar-field helpers.

Split out of ops/fr_jax.py so the crypto py-branch (crypto/kzg.py,
crypto/kzg_shim.py, crypto/das.py) can reach the Fr constants, root-of-unity
derivation and the O(n^2) oracle DFT without importing jax — the same
deferred-import discipline PR 3 applied to crypto/bls.py (a pure-Python
oracle process must be able to run the whole non-device path with jax
unimportable; tpulint's import-layering pass enforces this statically).

ops/fr_jax.py re-exports everything here, so `fr_jax.R_MODULUS`,
`fr_jax.root_of_unity`, `fr_jax.host_ntt` remain the established device-side
spellings.
"""
from __future__ import annotations

# Curve order of BLS12-381 (the "inner" / scalar modulus, reference
# specs/sharding/beacon-chain.md:107) and its primitive root 7 (:104).
R_MODULUS = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
PRIMITIVE_ROOT = 7
TWO_ADICITY = 32
assert (R_MODULUS - 1) % (1 << TWO_ADICITY) == 0


def root_of_unity(order: int) -> int:
    """Primitive `order`-th root of unity in Fr (order a power of two ≤ 2^32).

    Matches the reference's ROOT_OF_UNITY derivation
    (specs/sharding/beacon-chain.md:174): 7^((r-1)/order) mod r."""
    assert order & (order - 1) == 0 and order <= (1 << TWO_ADICITY)
    return pow(PRIMITIVE_ROOT, (R_MODULUS - 1) // order, R_MODULUS)


def domain(n: int) -> list[int]:
    """[w^0, w^1, ..., w^(n-1)] for the n-th root w (host ints)."""
    w = root_of_unity(n)
    out, acc = [], 1
    for _ in range(n):
        out.append(acc)
        acc = acc * w % R_MODULUS
    return out


def host_ntt(values: list[int], inverse: bool = False) -> list[int]:
    """O(n^2) reference DFT over Fr (host ints) for differential tests and
    the jax-free sampling path."""
    n = len(values)
    w = root_of_unity(n)
    if inverse:
        w = pow(w, R_MODULUS - 2, R_MODULUS)
    out = []
    for i in range(n):
        acc = 0
        for j, v in enumerate(values):
            acc = (acc + v * pow(w, i * j, R_MODULUS)) % R_MODULUS
        if inverse:
            acc = acc * pow(n, R_MODULUS - 2, R_MODULUS) % R_MODULUS
        out.append(acc)
    return out
