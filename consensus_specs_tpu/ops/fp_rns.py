"""BLS12-381 base-field arithmetic in a Residue Number System — the MXU path.

Why RNS: the positional-limb Montgomery core (ops/limb_mont.py) is inherently
sequential (per-limb carry/reduction fori_loops over uint64 lanes, which TPUs
emulate in 32-bit halves); measured ~59 aggregate-verifies/s — ~1,700x off the
BASELINE.md north star. In an RNS the field element is a vector of small
residues, multiplication is carry-free and fully lane-parallel int32 work, and
the one cross-channel step (base extension) is a matrix product against a
CONSTANT matrix — exactly the op the MXU exists for. This is the
representation change flagged in limb_mont.py's perf notes.

Representation
  element: (..., 64) int32 — residues modulo 64 fixed 15-bit primes, the
  first 32 forming base A (M_A = prod a_i), the last 32 base B (M_B).
  Montgomery domain with R = M_A: x is stored as residues of x_hat, where
  x_hat ≡ x·M_A (mod p). All primes sit in (2^15 - 2^10, 2^15 - 128) so that
  (a) residues split into two int8 halves for MXU matmuls and (b) reduction
  mod m after an int32 op is a few shift/mul/add folds (2^15 ≡ delta, delta
  < 2^10).

Redundancy (the contract with the tower code in ops/bls12_jax.py)
  A value's integer magnitude may exceed p, and may be NEGATIVE — ops only
  keep per-channel residues reduced, and every channel consistently
  represents the same (possibly negative) integer, so fp_sub is a plain
  per-channel subtraction with no normalization. mont_mul tolerates signed
  inputs (the canonical-q base extension and the wrap-aware second extension
  both remain exact) and outputs a value in (-p/2^9, 3p). With M_A ≈ 2^479
  the Montgomery condition |x·y| < M_A·p holds for operand magnitudes up to
  ~2^49·p, so no realistic add/sub chain between multiplies can overflow and
  no bound tracking is needed. Equality/zero tests are therefore NOT residue
  comparisons: fp_is_zero/fp_is_one_mont first "shrink" (Montgomery-multiply
  by one) into (-p/2^9, 3p), then compare against the residue vectors of
  {0, p, 2p} / {R, R+p, R+2p} — RNS representations are unique there.

Montgomery multiplication (Bajard/Kawamura, float-assisted base extension)
  t = x·y per channel; q = -t·p^{-1} in base A; q is extended to base B via
  sigma_i = q_i·(M_A/a_i)^{-1} mod a_i and the constant matrix
  C[i][j] = (M_A/a_i) mod b_j, with alpha = floor(sum sigma_i/a_i) estimated
  in f32 (offset -1/4: may underestimate by 1, never overestimate → q_hat <
  2·M_A, harmless: it only adds p to the result). r = (t + q_hat·p)/M_A in
  base B, then extended back to A the same way — that second extension is
  EXACT because |r| < 3p << M_B parks the fractional sum far from the floor
  boundary (offset +1/4 >> f32 sum error ~2^-14 >> r/M_B ~ 2^-95). Each
  extension's inner product runs as four int8 x int8 -> int32 matmuls
  (balanced-digit split of both factors).

Differentially tested channel-for-channel against Python bigints
(tests/test_fp_rns.py) and end-to-end through the pairing against the
crypto/bls12_381.py oracle. Reference framing: the reference's fast backend
is the milagro C wheel behind utils/bls.py (SURVEY.md §2.2); this module is
that role, built for the MXU/VPU instead of scalar CPUs.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

K_PER_BASE = 32
NLIMBS = 2 * K_PER_BASE  # interface name: trailing dim of an element
LIMB_BITS = 15
TWO15 = 1 << 15


def _gen_primes(lo: int, hi: int, count: int) -> list[int]:
    """largest `count` primes in (lo, hi), descending."""
    sieve = np.ones(hi, dtype=bool)
    sieve[:2] = False
    for i in range(2, int(hi**0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = False
    primes = np.nonzero(sieve)[0]
    primes = primes[(primes > lo) & (primes < hi)][::-1][:count]
    assert len(primes) == count, f"only {len(primes)} primes in ({lo}, {hi})"
    return [int(q) for q in primes]


# Keep residues <= 32511 so the balanced int8 split (hi = (v+128)>>8 <= 127)
# never overflows; keep delta = 2^15 - m < 2^10 so reduction folds converge.
_PRIMES = _gen_primes(TWO15 - (1 << 10), TWO15 - 128, NLIMBS)
A_PRIMES = _PRIMES[:K_PER_BASE]
B_PRIMES = _PRIMES[K_PER_BASE:]

M_A = 1
for _q in A_PRIMES:
    M_A *= _q
M_B = 1
for _q in B_PRIMES:
    M_B *= _q

# Montgomery condition headroom: t = x*y < M_A*p for operand bounds c*p
# requires c^2*p < M_A. SUB_K-sized chains stay far below this.
_HEADROOM = int((M_A // P) ** 0.5)
assert _HEADROOM > 2**40, hex(_HEADROOM)
assert M_B > 1 << 400

_M_ALL = np.asarray(_PRIMES, dtype=np.int32)  # (64,)
_DELTA = (TWO15 - _M_ALL).astype(np.int32)  # 2^15 mod m
_MA = np.asarray(A_PRIMES, dtype=np.int32)
_MB = np.asarray(B_PRIMES, dtype=np.int32)


def _residues(x: int, moduli) -> np.ndarray:
    return np.asarray([x % int(m) for m in moduli], dtype=np.int32)


def _split8(mat: np.ndarray, moduli) -> tuple[np.ndarray, np.ndarray]:
    """int matrix (entries < 2^15) -> balanced int8 (hi, lo): v = hi*256+lo."""
    hi = (mat + 128) >> 8
    lo = mat - (hi << 8)
    assert hi.max() <= 127 and lo.min() >= -128 and lo.max() <= 127
    return hi.astype(np.int8), lo.astype(np.int8)


class _Ext:
    """Constants for one direction of base extension (src base -> dst base)."""

    def __init__(self, src_primes, dst_primes, m_src_prod):
        k = len(src_primes)
        # sigma_i = q_i * (M/m_i)^{-1} mod m_i
        self.w_inv = np.asarray(
            [pow(m_src_prod // m, -1, m) for m in src_primes], dtype=np.int32
        )
        # C[i][j] = (M/m_i) mod dst_j
        C = np.asarray(
            [[(m_src_prod // mi) % mj for mj in dst_primes] for mi in src_primes],
            dtype=np.int64,
        )
        self.C_hi, self.C_lo = _split8(C, dst_primes)
        self.m_src_prod_mod_dst = _residues(m_src_prod, dst_primes)
        self.inv_src_f32 = (1.0 / np.asarray(src_primes)).astype(np.float32)
        self.dst_m = np.asarray(dst_primes, dtype=np.int32)
        self.dst_delta = (TWO15 - self.dst_m).astype(np.int32)


_EXT_AB = _Ext(A_PRIMES, B_PRIMES, M_A)
_EXT_BA = _Ext(B_PRIMES, A_PRIMES, M_B)

_NEG_PINV_A = np.asarray([(-pow(P, -1, m)) % m for m in A_PRIMES], dtype=np.int32)
_P_MOD_B = _residues(P, B_PRIMES)
_MAINV_MOD_B = np.asarray([pow(M_A % m, -1, m) for m in B_PRIMES], dtype=np.int32)

R_MOD_P = M_A % P
ONE_MONT = _residues(R_MOD_P, _PRIMES)  # to_mont(1)
ZERO = np.zeros(NLIMBS, dtype=np.int32)

# shrink(x) = mont_mul(x, ONE_MONT) has integer value < 3p; mod-p equality
# classes below 3p are {v, v+p, v+2p}
_ZERO_CLASSES = np.stack([_residues(i * P, _PRIMES) for i in range(3)])
_ONE_CLASSES = np.stack([_residues(R_MOD_P + i * P, _PRIMES) for i in range(3)])


# --- host codecs -------------------------------------------------------------


def to_mont(x: int) -> np.ndarray:
    return _residues((x % P) * M_A % P, _PRIMES)


def int_to_limbs(x: int) -> np.ndarray:
    """Plain (non-Montgomery) residues; interface parity with fp_jax."""
    return _residues(x, _PRIMES)


def limbs_to_int(limbs) -> int:
    """CRT reconstruction from the base-A half (exact for values < M_A)."""
    res = np.asarray(limbs, dtype=np.int64).reshape(-1)[:K_PER_BASE]
    acc = 0
    for i, m in enumerate(A_PRIMES):
        w = M_A // m
        acc += int(res[i]) * pow(w, -1, m) % m * w
    return acc % M_A


def from_mont_int(limbs) -> int:
    v = limbs_to_int(limbs)
    if v > M_A // 2:  # signed representation: interpret the top half as < 0
        v -= M_A
    return v * pow(M_A, -1, P) % P


def ints_to_mont_batch(xs) -> np.ndarray:
    xs = list(xs)
    if not xs:
        return np.zeros((0, NLIMBS), np.int32)
    return np.stack([to_mont(int(x)) for x in xs])


def mont_batch_to_ints(arr) -> list:
    a = np.asarray(arr)
    return [from_mont_int(a[i]) for i in range(a.shape[0])]


# --- per-channel reduction ---------------------------------------------------


def _fold(x, m, delta):
    """one step of x mod m via 2^15 = delta: x -> (x>>15)*delta + (x&32767)."""
    return (x >> LIMB_BITS) * delta + (x & (TWO15 - 1))


def _cond_sub(x, m):
    return jnp.where(x >= m, x - m, x)


def _red_full(x, m, delta):
    """x in [0, 2^31) -> x mod m. 4 folds + 1 conditional subtract.

    (3 folds + 3 conditional subtracts also lands < m but costs the same op
    count with more selects — measured as a wash; keep the fold form.)"""
    x = _fold(x, m, delta)
    x = _fold(x, m, delta)
    x = _fold(x, m, delta)
    x = _fold(x, m, delta)
    return _cond_sub(x, m)


def _red_small(x, m, delta):
    """x in [0, ~2^18) -> x mod m. 2 folds + 1 conditional subtract."""
    x = _fold(x, m, delta)
    x = _fold(x, m, delta)
    return _cond_sub(x, m)


def _c(arr):
    """host constant -> jnp int32 (embedded per-trace; numpy in globals)."""
    return jnp.asarray(arr, dtype=jnp.int32)


# --- field ops (all jitted at the call-site graph level) ---------------------


def _add(a, b):
    m = _c(_M_ALL)
    return _cond_sub(a + b, m)


def _sub(a, b):
    # represents the signed integer a_int - b_int (every channel consistent)
    m = _c(_M_ALL)
    return _cond_sub(a + (m - b), m)


def _neg(a):
    m = _c(_M_ALL)
    return _cond_sub(m - a, m)  # a == 0 -> m - 0 == m -> 0


def _extend(sigma, ext: _Ext, plus_alpha_offset: float):
    """sum_i sigma_i*(M/m_i) - alpha*M in the destination base.

    sigma: (..., k) int32 residues of the source base. Returns (..., k) int32
    in [0, ~2^27) == q_hat mod dst_j + (2^11)*dst_j positivity offset, NOT yet
    reduced (caller folds it into its next reduction)."""
    m = _c(ext.dst_m)
    delta = _c(ext.dst_delta)
    hi = (sigma + 128) >> 8
    lo = sigma - (hi << 8)
    dot = partial(jax.lax.dot_general, dimension_numbers=(((sigma.ndim - 1,), (0,)), ((), ())),
                  preferred_element_type=jnp.int32)
    hh = dot(hi.astype(jnp.int8), _c(ext.C_hi).astype(jnp.int8))
    hl = dot(hi.astype(jnp.int8), _c(ext.C_lo).astype(jnp.int8))
    lh = dot(lo.astype(jnp.int8), _c(ext.C_hi).astype(jnp.int8))
    ll = dot(lo.astype(jnp.int8), _c(ext.C_lo).astype(jnp.int8))
    # recombine mod m: v = hh*2^16 + (hl+lh)*2^8 + ll, term-wise reduced.
    # |hl+lh| <= 2*32*127*128 < 2^21; +64m (> 2^21) keeps terms nonnegative.
    off64 = m << 6
    s_hh = _red_small(hh, m, delta)  # hi, C_hi >= 0: already nonnegative
    s_mid = _red_small(hl + lh + off64, m, delta)
    s_ll = _red_small(ll + off64, m, delta)
    two16 = _c(2 * ext.dst_delta)  # 2^16 mod m (delta < 2^10 so 2delta < m)
    v = _red_full(s_hh * two16, m, delta) + (s_mid << 8) + s_ll  # < m + 2^23 + m
    # alpha estimate (Kawamura): fractional sums in f32
    frac = jnp.sum(sigma.astype(jnp.float32) * _c_f32(ext.inv_src_f32), axis=-1)
    alpha = jnp.floor(frac + plus_alpha_offset).astype(jnp.int32)
    v = v + (m << 11) - alpha[..., None] * _c(ext.m_src_prod_mod_dst)
    return _red_full(v, m, delta)


def _c_f32(arr):
    return jnp.asarray(arr, dtype=jnp.float32)


def _mul_wide(x, y):
    """Per-channel product, channel-reduced but NOT Montgomery-reduced: the
    result represents the integer x_int*y_int (double Montgomery scale).
    Wide values add/sub/sum with the ordinary ops; _mont_reduce brings them
    back to single scale. This is the tower's lazy-reduction primitive: an
    Fp12 multiply accumulates its products wide and pays one reduction per
    output coefficient instead of one per product."""
    return _red_full(x * y, _c(_M_ALL), _c(_DELTA))


def _mont_reduce(t):
    """t -> t*M_A^{-1} (mod p), |result| < 3p; t any channel-reduced value."""
    tA = t[..., :K_PER_BASE]
    tB = t[..., K_PER_BASE:]
    mA = _c(_MA)
    dA = _c(_DELTA[:K_PER_BASE])
    mB = _c(_MB)
    dB = _c(_DELTA[K_PER_BASE:])
    q = _red_full(tA * _c(_NEG_PINV_A), mA, dA)
    sigma = _red_full(q * _c(_EXT_AB.w_inv), mA, dA)
    # alpha may underestimate by 1 (offset -1/4): q_hat in [0, 2*M_A)
    q_hat = _extend(sigma, _EXT_AB, -0.25)
    u = _red_full(tB + _red_full(q_hat * _c(_P_MOD_B), mB, dB), mB, dB)
    rB = _red_full(u * _c(_MAINV_MOD_B), mB, dB)
    # exact extension back: |r| < 3p << M_B so floor(frac + 1/4) is alpha
    sigma2 = _red_full(rB * _c(_EXT_BA.w_inv), mB, dB)
    rA = _extend(sigma2, _EXT_BA, 0.25)
    return jnp.concatenate([rA, rB], axis=-1)


def _mont_mul(x, y):
    """x*y*M_A^{-1} (mod p); (..., 64) reduced residues; output in (-p/2^9, 3p)."""
    return _mont_reduce(_mul_wide(x, y))


def _pow_const(a, exponent: int):
    bits = jnp.asarray(np.array([int(c) for c in bin(exponent)[2:]], dtype=np.int32))
    one = jnp.broadcast_to(_c(ONE_MONT), a.shape)

    def body(i, acc):
        acc = _mont_mul(acc, acc)
        mul = _mont_mul(acc, a)
        return jnp.where(bits[i] == 1, mul, acc)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(bits.shape[0]), body, one)


fp_add = jax.jit(_add)
fp_sub = jax.jit(_sub)
fp_neg = jax.jit(_neg)
fp_mont_mul = jax.jit(_mont_mul)
fp_mont_sqr = jax.jit(lambda a: _mont_mul(a, a))
fp_mul_wide = jax.jit(_mul_wide)
fp_mont_reduce = jax.jit(_mont_reduce)
fp_pow_const = partial(jax.jit, static_argnums=(1,))(_pow_const)
SUPPORTS_WIDE = True


def fp_inv(a):
    """Batched Fermat inversion a^(p-2); zero maps to zero."""
    return fp_pow_const(a, P - 2)


def fp_sum_stack(arr, axis: int = 0):
    """Sum <= 8 reduced (..., 64) residue vectors along `axis`."""
    assert arr.shape[axis] <= 8
    m = _c(_M_ALL)
    # dtype pinned: jnp reductions promote int32 -> int64 under x64
    return _red_small(arr.sum(axis=axis, dtype=jnp.int32), m, _c(_DELTA))


def fp_sqrt_candidate(a):
    """a^((p+1)/4) — square root when a is a QR (p ≡ 3 mod 4)."""
    return fp_pow_const(a, (P + 1) // 4)


# --- mod-p equality (shrink + class compare) --------------------------------


def _shrink(a):
    """same class mod p, integer value in (-p/2^9, 3p)."""
    return _mont_mul(a, jnp.broadcast_to(_c(ONE_MONT), a.shape))


def _in_classes(small, classes):
    """small: (..., 64) residues of a value < 3p; classes: (3, 64) host."""
    cls = _c(classes)
    eq = jnp.all(small[..., None, :] == cls, axis=-1)  # (..., 3)
    return jnp.any(eq, axis=-1)


def fp_is_zero(a):
    """(...) bool: a ≡ 0 (mod p). Accepts any reduced-residue element."""
    return _in_classes(_shrink(a), _ZERO_CLASSES)


def fp_is_one_mont(a):
    """(...) bool: a is the Montgomery-domain 1 (i.e. value ≡ R mod p)."""
    return _in_classes(_shrink(a), _ONE_CLASSES)


# --- import-time self-check (host-side, no jax backend touched) -------------

assert from_mont_int(to_mont(12345)) == 12345
assert from_mont_int(ONE_MONT) == 1
_xchk = 0xDEADBEEF_CAFEBABE_0123456789ABCDEF % P
assert limbs_to_int(int_to_limbs(_xchk)) == _xchk


DTYPE = jnp.int32
