"""Batched LMD-GHOST head selection as a JAX kernel (the fork-choice lane).

Device twin of the spec's `get_head` (phase0/fork-choice.md: greedy
child-walk from the justified root maximizing
`(get_latest_attesting_balance, root)`), over a store mirrored in gather
form: the block tree as parent-pointer indices, per-validator latest
messages as a `(V,)` vote-index vector, per-block FFG checkpoints as
interned root ids + epochs.

Three gather-form stages, no scatter anywhere:

  1. **Ancestor matrix by pointer doubling.** `anc[i, j]` = "j is an
     ancestor-or-self of i", grown from the identity in `log2(B)` steps of
     `anc |= anc[jump]; jump = jump[jump]` — the multiproof kernel's
     level-walk idiom lifted to whole-tree reachability. Because slots
     strictly increase parent -> child, `get_ancestor(store, vote_root,
     candidate.slot) == candidate` is exactly "candidate is
     ancestor-or-self of vote_root", so no slot data is needed on device.
  2. **Masked segment-sum vote weights** — the `g1_segment_sum` tree idiom
     on int64 Gwei: a `(V_chunk, B)` equality mask against the block-index
     lane, summed per chunk inside an int32-pinned `fori_loop` (vote -1 =
     "no message" never matches). Subtree weights are then one masked
     reduction over the ancestor matrix; proposer boost is a single row
     gather.
  3. **Viability + head walk.** `filter_block_tree`'s leaf rule (store
     justified/finalized agreement, with the GENESIS_EPOCH escapes) is a
     per-block predicate; a node is viable iff some agreeing leaf sits in
     its ancestor column. The head walk is an int32-pinned `fori_loop` of
     B greedy steps, each an argmax over `(weight, root)` realized as a
     lexicographic mask refinement: weight first, then the 8 big-endian
     root words most-significant first — bit-identical to the spec's
     bytes-wise `max(children, key=...)` tie-break.

One XLA compile per pow2 (blocks, validators) bucket; the engine entry
(`engine/fork_choice.py`) owns the padding (pad blocks parent-self-looped
and unreal, pad validators vote -1 / balance 0).

x64 mode is required: effective balances sum in exact int64 Gwei.
"""
from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

# Validator-lane chunk for the masked segment-sum: bounds the live
# (V_chunk, B) mask so a 1M-validator registry never materializes a
# (V, B) intermediate. Must divide every validator bucket >= itself.
V_CHUNK = 4096


def _ghost_head_impl(parent: jax.Array, root_words: jax.Array,
                     ck_epochs: jax.Array, ck_rids: jax.Array,
                     is_real: jax.Array, votes: jax.Array,
                     balances: jax.Array, idx_scalars: jax.Array,
                     ep_scalars: jax.Array) -> jax.Array:
    """One store snapshot -> head block index (int32 scalar).

    `parent` (B,) int32 parent indices (anchor and pads self-looped);
    `root_words` (B, 8) uint32 big-endian root words; `ck_epochs` (B, 2)
    int64 / `ck_rids` (B, 2) int32 per-block (justified, finalized)
    checkpoint epochs + interned root ids; `is_real` (B,) bool;
    `votes` (V,) int32 latest-message block index (-1 = none);
    `balances` (V,) int64 effective Gwei; `idx_scalars` (4,) int32 =
    [justified_idx, boost_idx (-1 = off), store_justified_rid,
    store_finalized_rid]; `ep_scalars` (4,) int64 = [store_justified_epoch,
    store_finalized_epoch, GENESIS_EPOCH, boost_weight]."""
    b = parent.shape[0]
    v = votes.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)

    justified_idx = idx_scalars[0]
    boost_idx = idx_scalars[1]
    store_just_rid = idx_scalars[2]
    store_fin_rid = idx_scalars[3]
    store_just_ep = ep_scalars[0]
    store_fin_ep = ep_scalars[1]
    genesis_ep = ep_scalars[2]
    boost_weight = ep_scalars[3]

    # 1. ancestor-or-self matrix by pointer doubling: after k steps anc
    # covers all ancestors within distance 2^k, so log2(B) steps saturate
    # any chain that fits the bucket (self-looped roots are fixpoints).
    levels = (b - 1).bit_length() if b > 1 else 0

    def double(_i, carry):
        anc, jump = carry
        anc = anc | jnp.take(anc, jump, axis=0)
        return anc, jnp.take(jump, jump, axis=0)

    anc, _ = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(levels), double,
        (jnp.eye(b, dtype=jnp.bool_), parent))

    # 2a. direct vote weight per block: chunked masked segment-sum
    chunk = v if v < V_CHUNK else V_CHUNK

    def seg_sum(k, acc):
        off = k * jnp.int32(chunk)
        vs = jax.lax.dynamic_slice(votes, (off,), (chunk,))
        bs = jax.lax.dynamic_slice(balances, (off,), (chunk,))
        mask = vs[:, None] == idx[None, :]  # (chunk, B); vote -1 never hits
        return acc + jnp.sum(
            jnp.where(mask, bs[:, None], jnp.int64(0)), axis=0)

    direct = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(v // chunk), seg_sum,
        jnp.zeros((b,), dtype=jnp.int64))

    # 2b. subtree weight: W[c] = sum of direct votes over descendants-or-self
    weight = jnp.sum(jnp.where(anc, direct[:, None], jnp.int64(0)), axis=0)

    # 2c. proposer boost: every ancestor-or-self of the boost root gains
    # the committee-fraction weight (one row gather; -1 disables)
    boost_row = jnp.take(anc, jnp.maximum(boost_idx, jnp.int32(0)), axis=0)
    weight = weight + jnp.where((boost_idx >= jnp.int32(0)) & boost_row,
                                boost_weight, jnp.int64(0))

    # 3a. filter_block_tree: a leaf is viable iff its head-state FFG
    # checkpoints agree with the store's (GENESIS_EPOCH short-circuits,
    # matching the spec's `== GENESIS_EPOCH or ==` disjunctions); an
    # interior node is viable iff an agreeing leaf sits in its subtree.
    child_of = ((parent[:, None] == idx[None, :])
                & is_real[:, None] & (parent != idx)[:, None])
    is_leaf = ~jnp.any(child_of, axis=0)
    ok_just = ((store_just_ep == genesis_ep)
               | ((ck_epochs[:, 0] == store_just_ep)
                  & (ck_rids[:, 0] == store_just_rid)))
    ok_fin = ((store_fin_ep == genesis_ep)
              | ((ck_epochs[:, 1] == store_fin_ep)
                 & (ck_rids[:, 1] == store_fin_rid)))
    leaf_ok = is_leaf & is_real & ok_just & ok_fin
    viable = jnp.any(anc & leaf_ok[:, None], axis=0)
    filtered = (viable & is_real
                & jnp.take(anc, justified_idx, axis=1))

    # 3b. greedy head walk: from the justified root, step to the filtered
    # child maximizing (weight, root) until childless. The lexicographic
    # argmax refines a candidate mask — weight, then each big-endian root
    # word — so ties break bytes-wise exactly like the spec's Root max.
    def step(_i, head):
        kids = (parent == head) & (idx != head) & filtered
        has = jnp.any(kids)
        m = kids & (weight == jnp.max(
            jnp.where(kids, weight, jnp.int64(-1))))
        for t in range(8):
            wt = root_words[:, t]
            m = m & (wt == jnp.max(jnp.where(m, wt, jnp.uint32(0))))
        return jnp.where(has, jnp.argmax(m).astype(jnp.int32), head)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(b), step,
                             justified_idx.astype(jnp.int32))


# (Q, ...) batched entry: one compile per (Q, B, V) pow2 bucket.
ghost_head_bucket = jax.jit(jax.vmap(_ghost_head_impl))
