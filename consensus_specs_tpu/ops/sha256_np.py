"""Vectorized sha256 over numpy uint32 lanes.

The Merkleization hot path (hash_tree_root of the beacon state, merkle trees of
roots) hashes *levels* of independent 64-byte parent nodes — embarrassingly
parallel. The reference does this one node at a time through hashlib
(eth2spec/utils/merkle_minimal.py, remerkleable); here a whole level is one
vectorized compression over N lanes. The JAX twin (ops/sha256_jax.py) runs the
same schedule on TPU.

All functions operate on big-endian byte semantics (standard sha256).
"""
from __future__ import annotations

import numpy as np

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _schedule(w16: np.ndarray) -> np.ndarray:
    """Expand 16 message words -> 64. w16: (16, ...) uint32 -> (64, ...)."""
    w = list(w16)
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append((w[t - 16] + s0 + w[t - 7] + s1).astype(np.uint32))
    return np.stack(w)


def _compress(state: np.ndarray, w: np.ndarray) -> np.ndarray:
    """One compression. state: (8, ...) uint32; w: (64, ...) expanded schedule."""
    a, b, c, d, e, f, g, h = state
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + _K[t] + w[t]).astype(np.uint32)
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj).astype(np.uint32)
        h, g, f = g, f, e
        e = (d + t1).astype(np.uint32)
        d, c, b = c, b, a
        a = (t1 + t2).astype(np.uint32)
    return (state + np.stack([a, b, c, d, e, f, g, h])).astype(np.uint32)


# The padding block for a 64-byte message is constant: 0x80, zeros, bitlen=512.
_PAD64_W16 = np.zeros(16, dtype=np.uint32)
_PAD64_W16[0] = 0x80000000
_PAD64_W16[15] = 512
_PAD64_SCHED = _schedule(_PAD64_W16.reshape(16, 1))[:, 0]  # (64,)


def _bytes_to_words(data: np.ndarray) -> np.ndarray:
    """(..., 4k) uint8 big-endian -> (..., k) uint32."""
    be = data.reshape(*data.shape[:-1], data.shape[-1] // 4, 4).astype(np.uint32)
    return (be[..., 0] << 24) | (be[..., 1] << 16) | (be[..., 2] << 8) | be[..., 3]


def _words_to_bytes(words: np.ndarray) -> np.ndarray:
    """(..., k) uint32 -> (..., 4k) uint8 big-endian."""
    out = np.empty(words.shape + (4,), dtype=np.uint8)
    out[..., 0] = words >> 24
    out[..., 1] = (words >> 16) & 0xFF
    out[..., 2] = (words >> 8) & 0xFF
    out[..., 3] = words & 0xFF
    return out.reshape(*words.shape[:-1], words.shape[-1] * 4)


def sha256_64B(data: np.ndarray) -> np.ndarray:
    """Batched sha256 of N independent 64-byte messages.

    data: (N, 64) uint8 -> (N, 32) uint8. This is the Merkle parent-node hash:
    data[i] = left_child_root || right_child_root.
    """
    n = data.shape[0]
    w16 = _bytes_to_words(data).T  # (16, N)
    state = np.repeat(_H0.reshape(8, 1), n, axis=1)
    state = _compress(state, _schedule(w16))
    state = _compress(state, np.broadcast_to(_PAD64_SCHED.reshape(64, 1), (64, n)))
    return _words_to_bytes(state.T)  # (N, 32)


def sha256_batch(data: np.ndarray) -> np.ndarray:
    """Batched sha256 of N equal-length messages. data: (N, L) uint8 -> (N, 32)."""
    n, length = data.shape
    padded_len = ((length + 9 + 63) // 64) * 64
    padded = np.zeros((n, padded_len), dtype=np.uint8)
    padded[:, :length] = data
    padded[:, length] = 0x80
    bitlen = length * 8
    for i in range(8):
        padded[:, padded_len - 1 - i] = (bitlen >> (8 * i)) & 0xFF
    state = np.repeat(_H0.reshape(8, 1), n, axis=1)
    words = _bytes_to_words(padded)  # (N, padded_len/4)
    for blk in range(padded_len // 64):
        w16 = words[:, blk * 16:(blk + 1) * 16].T
        state = _compress(state, _schedule(w16))
    return _words_to_bytes(state.T)
