"""Batched swap-or-not shuffle as a JAX/XLA kernel.

Device twin of the spec's `compute_shuffled_index` (phase0/beacon-chain.md:
swap-or-not, SHUFFLE_ROUND_COUNT sha256-driven conditional swaps per index;
reference: specs/phase0/beacon-chain.md `compute_shuffled_index`, memoized at
reference setup.py:377-380 because the scalar form is the #1 hot loop).

The scalar algorithm is index-parallel per round: every index sees the same
round pivot and the same per-256-index-bucket source hash. So the whole
permutation is computed at once:

  - `rounds` pivot hashes   — one (rounds, 16)-word sha256 batch
  - `rounds x ceil(n/256)` source hashes — one batched sha256 call
  - `rounds` fori_loop steps of elementwise flip/select over the (n,) index
    vector (gathers into the per-round source digests)

For mainnet scale (n = 1M, 90 rounds) this is ~368k hashes + 90 vectorized
sweeps instead of 90M scalar hash calls.

uint64 (x64) mode is required: the round pivot is a 64-bit LE integer mod n.
"""
from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from functools import partial

from .sha256_jax import sha256_1block


def _bswap32(x: jax.Array) -> jax.Array:
    """Reverse the byte order of each uint32 lane."""
    x = x.astype(jnp.uint32)
    return (
        ((x & jnp.uint32(0x000000FF)) << 24)
        | ((x & jnp.uint32(0x0000FF00)) << 8)
        | ((x & jnp.uint32(0x00FF0000)) >> 8)
        | ((x & jnp.uint32(0xFF000000)) >> 24)
    )


def seed_to_words(seed: bytes) -> np.ndarray:
    """32-byte shuffle seed -> (8,) uint32 big-endian message words."""
    assert len(seed) == 32
    from .sha256_jax import bytes_to_words

    return bytes_to_words(seed)


def _round_pivots(seed_words: jax.Array, n: int, rounds: int) -> jax.Array:
    """Per-round pivots: u64_le(sha256(seed || u8(round))[0:8]) % n.

    Returns (rounds,) uint32 (n < 2^32).
    """
    r = jnp.arange(rounds, dtype=jnp.uint32)
    msg = jnp.zeros((rounds, 16), dtype=jnp.uint32)
    msg = msg.at[:, :8].set(jnp.broadcast_to(seed_words, (rounds, 8)))
    # byte 32 = round, byte 33 = 0x80 terminator; bit length 33*8 = 264
    msg = msg.at[:, 8].set((r << 24) | jnp.uint32(0x80 << 16))
    msg = msg.at[:, 15].set(jnp.uint32(264))
    digest = sha256_1block(msg)  # (rounds, 8)
    lo = _bswap32(digest[:, 0]).astype(jnp.uint64)
    hi = _bswap32(digest[:, 1]).astype(jnp.uint64)
    pivot = lo | (hi << jnp.uint64(32))
    return (pivot % jnp.uint64(n)).astype(jnp.uint32)


def _round_sources(seed_words: jax.Array, rounds: int, buckets: int) -> jax.Array:
    """Source digests for every (round, position-bucket) pair.

    message = seed || u8(round) || u32_le(bucket), 37 bytes, one sha256 block.
    Returns (rounds, buckets, 8) uint32 digest words.
    """
    r = jnp.arange(rounds, dtype=jnp.uint32)[:, None]
    k = jnp.arange(buckets, dtype=jnp.uint32)[None, :]
    msg = jnp.zeros((rounds, buckets, 16), dtype=jnp.uint32)
    msg = msg.at[:, :, :8].set(jnp.broadcast_to(seed_words, (rounds, buckets, 8)))
    # bytes 32..35: round, bucket_le[0..2]; bytes 36: bucket_le[3], then 0x80
    w8 = (
        (r << 24)
        | ((k & 0xFF) << 16)
        | (((k >> 8) & 0xFF) << 8)
        | ((k >> 16) & 0xFF)
    )
    w9 = (((k >> 24) & 0xFF) << 24) | jnp.uint32(0x80 << 16)
    msg = msg.at[:, :, 8].set(jnp.broadcast_to(w8, (rounds, buckets)))
    msg = msg.at[:, :, 9].set(jnp.broadcast_to(w9, (rounds, buckets)))
    msg = msg.at[:, :, 15].set(jnp.uint32(296))  # 37*8
    return sha256_1block(msg)


@partial(jax.jit, static_argnums=(0, 2))
def shuffled_index_map(n: int, seed_words: jax.Array, rounds: int) -> jax.Array:
    """Vector of spec `compute_shuffled_index(i, n, seed)` for all i in [0, n).

    n and rounds are static (XLA shapes); seed_words is a traced (8,) uint32
    array so the kernel jits once per (n, rounds) and is reusable across seeds
    (e.g. inside the jitted epoch engine where the seed is data).
    """
    assert 1 <= n < 2**31  # uint32 index math needs pivot + n - idx < 2^32
    buckets = (n + 255) // 256
    pivots = _round_pivots(seed_words, n, rounds)
    sources = _round_sources(seed_words, rounds, buckets)  # (rounds, buckets, 8)
    idx = jnp.arange(n, dtype=jnp.uint32)
    un = jnp.uint32(n)

    def body(rnd, idx):
        pivot = pivots[rnd]
        flip = (pivot + un - idx) % un
        position = jnp.maximum(idx, flip)
        src = sources[rnd]  # (buckets, 8)
        word = src[position >> 8, (position >> 5) & 7]
        # byte j of the big-endian digest stream, j = (position % 256) // 8
        byte_in_word = (position >> 3) & 3
        byte = (word >> (jnp.uint32(24) - 8 * byte_in_word)) & jnp.uint32(0xFF)
        bit = (byte >> (position & 7)) & jnp.uint32(1)
        return jnp.where(bit == 1, flip, idx)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(rounds), body, idx)


def compute_shuffled_indices(n: int, seed: bytes, rounds: int) -> np.ndarray:
    """Host wrapper: full shuffled-index map as numpy uint32."""
    if n == 0:
        return np.zeros(0, dtype=np.uint32)
    words = jnp.asarray(seed_to_words(seed))
    return np.asarray(shuffled_index_map(n, words, rounds))


def compute_shuffled_indices_np(n: int, seed: bytes, rounds: int) -> np.ndarray:
    """Pure-host numpy twin of `shuffled_index_map` — zero XLA involvement.

    The device kernel compiles once per (n, rounds) static shape; that is
    right for the epoch engine (one registry size per process) and wrong
    for the vector-generator lane, which sweeps dozens of small counts and
    would pay a full XLA compile per count (VERDICT r3 weak #7: 352 cases,
    zero emitted in 240s). Same round structure: per-round pivot hash and
    per-256-bucket source digests, then vectorized flip/select over the
    whole index vector. Bit-identical to the kernel and to the scalar spec
    loop (tests/test_shuffle.py).
    """
    import hashlib

    if n == 0:
        return np.zeros(0, dtype=np.uint32)
    assert 1 <= n < 2**31
    idx = np.arange(n, dtype=np.uint64)
    un = np.uint64(n)
    buckets = (n + 255) // 256
    for rnd in range(rounds):
        rb = bytes([rnd])
        pivot = np.uint64(
            int.from_bytes(hashlib.sha256(seed + rb).digest()[:8], "little") % n)
        src = np.frombuffer(
            b"".join(
                hashlib.sha256(seed + rb + k.to_bytes(4, "little")).digest()
                for k in range(buckets)
            ),
            dtype=np.uint8,
        )
        flip = (pivot + un - idx) % un
        position = np.maximum(idx, flip)
        byte = src[(position >> 8) * 32 + ((position & 0xFF) >> 3)]
        bit = (byte >> (position & 0x7).astype(np.uint8)) & 1
        idx = np.where(bit == 1, flip, idx)
    return idx.astype(np.uint32)
