"""Batched BLS12-381 base-field (Fp, 381-bit) arithmetic as JAX kernels.

The reference delegates all BLS math to C wheels (milagro) or py_ecc scalars
(SURVEY.md §2.2); here the field layer is data-parallel from the start: a
batch of field elements is a uint32 array of shape (..., 24) — 24 limbs of
16 bits, little-endian — and every operation is elementwise over the leading
batch axes, so `Verify`-style workloads become one XLA program over the whole
signature set instead of per-signature C calls.

Representation:
  - limbs: (..., 24) uint32, each < 2^16 (canonical), little-endian base 2^16
  - Montgomery domain for multiplication: a is stored as a·R mod p, R = 2^384
  - products/accumulators use uint64 lanes (x64 mode); per-limb loops are
    lax.fori_loop with dynamic slices, keeping the HLO graph small (see
    ops/sha256_jax.py for why unrolling is fatal to compile times here)

The Montgomery SOS core (deferred carries in uint64 columns, per-limb carry
folded upward each round; magnitudes < ~2^41, far from the uint64 ceiling)
lives in ops/limb_mont.py, shared with the scalar field Fr (ops/fr_jax.py).
This module binds the 24-limb Fp specialization plus Fp-specific extras
(sqrt candidate for point decompression, lazy-reduction stack summation for
point-add chains).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .limb_mont import MontgomeryField

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

NLIMBS = 24
LIMB_BITS = 16
MASK = (1 << LIMB_BITS) - 1
BASE = np.uint64(1 << LIMB_BITS)  # host scalar: no backend init at import
R = 1 << (NLIMBS * LIMB_BITS)  # 2^384
R_MOD_P = R % P
R2_MOD_P = (R * R) % P

FIELD = MontgomeryField(P, NLIMBS)
N0 = FIELD.n0  # -p^-1 mod 2^16 (Montgomery n')

# Established public surface (bound to the shared factory instance).
int_to_limbs = FIELD.int_to_limbs
limbs_to_int = FIELD.limbs_to_int
to_mont = FIELD.to_mont
from_mont_int = FIELD.from_mont_int

P_LIMBS = FIELD.mod_limbs
_P64 = P_LIMBS.astype(np.uint64)
ZERO = FIELD.zero
ONE_MONT = FIELD.one_mont

fp_add = FIELD.add
fp_sub = FIELD.sub
fp_neg = FIELD.neg
fp_mont_mul = FIELD.mont_mul
fp_mont_sqr = FIELD.mont_sqr
fp_pow_const = FIELD.pow_const
fp_inv = FIELD.inv

# shared primitives reused by the Fp-specific extras below
_carry_pass = FIELD.carry_pass
_sub_limbs = FIELD.sub_limbs
_geq_vec = FIELD.geq_vec


def fp_sqrt_candidate(a: jax.Array) -> jax.Array:
    """a^((p+1)/4) — square root when a is a QR (p ≡ 3 mod 4)."""
    return fp_pow_const(a, (P + 1) // 4)


# --- small-multiple reduction (lazy-sum support) ----------------------------

# p·2^j limb vectors for conditional subtraction of accumulated sums (< 8p;
# 8p < 2^384 so intermediates stay canonical in 24 limbs — 16p would not)
_P_MULTIPLES = [int_to_limbs((P << j)).astype(np.uint64) for j in range(3)]


def fp_sum_stack(arr, axis: int = 0) -> jax.Array:
    """Sum ≤ 8 canonical (..., 24) u32 values along `axis`, reduced mod p.

    Lazy reduction: one u64 column sum + carry pass + conditional subtraction
    of 4p/2p/p (binary descent), instead of per-addition reductions."""
    k = arr.shape[axis]
    assert k <= 8
    t = _carry_pass(arr.astype(jnp.uint64).sum(axis=axis))
    for j in range(2, -1, -1):
        if (1 << j) < k or j == 0:
            vec = _P_MULTIPLES[j]
            sub = _sub_limbs(t, vec)
            t = jnp.where(_geq_vec(t, vec)[..., None], sub, t)
    return t.astype(jnp.uint32)


# --- host codecs ------------------------------------------------------------

ints_to_mont_batch = FIELD.ints_to_mont_batch
mont_batch_to_ints = FIELD.mont_batch_to_ints

# --- mod-p equality (canonical representation: direct limb compare) ---------


def fp_is_zero(a) -> jax.Array:
    """(...) bool: a == 0 (elements are canonical, so limb equality)."""
    return jnp.all(a == 0, axis=-1)


def fp_is_one_mont(a) -> jax.Array:
    """(...) bool: a is the Montgomery-domain 1."""
    return jnp.all(a == jnp.asarray(ONE_MONT), axis=-1)


DTYPE = jnp.uint32


# --- lazy-reduction interface parity (no-op in the positional-limb form:
# fp_mont_mul is already fully reduced, so "wide" == ordinary) --------------

fp_mul_wide = fp_mont_mul


def fp_mont_reduce(t):
    return t


SUPPORTS_WIDE = False
