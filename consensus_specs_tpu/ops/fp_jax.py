"""Batched BLS12-381 base-field (Fp, 381-bit) arithmetic as JAX kernels.

The reference delegates all BLS math to C wheels (milagro) or py_ecc scalars
(SURVEY.md §2.2); here the field layer is data-parallel from the start: a
batch of field elements is a uint32 array of shape (..., 24) — 24 limbs of
16 bits, little-endian — and every operation is elementwise over the leading
batch axes, so `Verify`-style workloads become one XLA program over the whole
signature set instead of per-signature C calls.

Representation:
  - limbs: (..., 24) uint32, each < 2^16 (canonical), little-endian base 2^16
  - Montgomery domain for multiplication: a is stored as a·R mod p, R = 2^384
  - products/accumulators use uint64 lanes (x64 mode); per-limb loops are
    lax.fori_loop with dynamic slices, keeping the HLO graph small (see
    ops/sha256_jax.py for why unrolling is fatal to compile times here)

Montgomery reduction is SOS (separated operand scanning): deferred carries in
uint64 columns with the per-limb carry folded upward each round; column
magnitudes stay below ~2^41, far from the uint64 ceiling.
"""
from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from functools import partial

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

NLIMBS = 24
LIMB_BITS = 16
MASK = (1 << LIMB_BITS) - 1
BASE = jnp.uint64(1 << LIMB_BITS)
R = 1 << (NLIMBS * LIMB_BITS)  # 2^384
R_MOD_P = R % P
R2_MOD_P = (R * R) % P
# -p^-1 mod 2^16 (Montgomery n')
N0 = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    assert 0 <= x < (1 << 384)
    return np.array([(x >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.uint32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.uint64).reshape(-1)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(arr))


P_LIMBS = int_to_limbs(P)
_P64 = jnp.asarray(P_LIMBS.astype(np.uint64))
ZERO = np.zeros(NLIMBS, dtype=np.uint32)
ONE_MONT = int_to_limbs(R_MOD_P)  # 1 in Montgomery form


def to_mont(x: int) -> np.ndarray:
    """Host: integer -> Montgomery-form limb vector."""
    return int_to_limbs((x * R) % P)


def from_mont_int(limbs) -> int:
    """Host: Montgomery-form limbs -> integer."""
    return (limbs_to_int(limbs) * pow(R, -1, P)) % P


# --- carry / borrow primitives ----------------------------------------------


def _carry_pass(t):
    """(..., N) u64 deferred-carry columns -> per-limb < 2^16 except possibly
    the last (which receives the final carry)."""
    n = t.shape[-1]

    def body(i, t):
        v = jax.lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        t = jax.lax.dynamic_update_index_in_dim(t, v & jnp.uint64(MASK), i, axis=-1)
        nxt = jax.lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            t, nxt + (v >> LIMB_BITS), i + 1, axis=-1
        )

    return jax.lax.fori_loop(0, n - 1, body, t)


def _sub_limbs(x, y):
    """x - y over canonical (..., 24) u64 limb vectors, assuming x >= y."""
    out = jnp.zeros(jnp.broadcast_shapes(x.shape, y.shape), dtype=jnp.uint64)
    borrow0 = jnp.zeros(out.shape[:-1], dtype=jnp.uint64)
    xb = jnp.broadcast_to(x, out.shape)
    yb = jnp.broadcast_to(y, out.shape)

    def body(i, st):
        borrow, out = st
        xi = jax.lax.dynamic_index_in_dim(xb, i, axis=-1, keepdims=False)
        yi = jax.lax.dynamic_index_in_dim(yb, i, axis=-1, keepdims=False)
        d = xi + BASE - yi - borrow
        out = jax.lax.dynamic_update_index_in_dim(out, d & jnp.uint64(MASK), i, axis=-1)
        borrow = jnp.uint64(1) - (d >> LIMB_BITS)
        return borrow, out

    _, res = jax.lax.fori_loop(0, NLIMBS, body, (borrow0, out))
    return res


def _geq_p(a64):
    """canonical (..., 24) u64 >= p ? (lexicographic from the top limb)."""
    gt = jnp.zeros(a64.shape[:-1], dtype=bool)
    lt = jnp.zeros(a64.shape[:-1], dtype=bool)
    for i in range(NLIMBS - 1, -1, -1):
        ai = a64[..., i]
        pi = _P64[i]
        gt = gt | (~lt & (ai > pi))
        lt = lt | (~gt & (ai < pi))
    return ~lt


def _cond_sub_p(a64):
    """Subtract p where a >= p (a canonical, a < 2p)."""
    sub = _sub_limbs(a64, _P64)
    return jnp.where(_geq_p(a64)[..., None], sub, a64)


# --- field ops ---------------------------------------------------------------


@jax.jit
def fp_add(a: jax.Array, b: jax.Array) -> jax.Array:
    """(..., 24) u32 canonical -> canonical (a + b) mod p."""
    t = _carry_pass(a.astype(jnp.uint64) + b.astype(jnp.uint64))
    return _cond_sub_p(t).astype(jnp.uint32)


@jax.jit
def fp_sub(a: jax.Array, b: jax.Array) -> jax.Array:
    """(..., 24) u32 canonical -> canonical (a - b) mod p."""
    p_minus_b = _sub_limbs(_P64, b.astype(jnp.uint64))
    t = _carry_pass(a.astype(jnp.uint64) + p_minus_b)
    return _cond_sub_p(t).astype(jnp.uint32)


@jax.jit
def fp_neg(a: jax.Array) -> jax.Array:
    """(p - a) mod p; zero stays zero."""
    z = jnp.all(a == 0, axis=-1, keepdims=True)
    res = _sub_limbs(_P64, a.astype(jnp.uint64))
    return jnp.where(z, jnp.zeros_like(res), res).astype(jnp.uint32)


def _poly_mul_acc(a64, b64):
    """Schoolbook product columns: (..., 24) x (..., 24) -> (..., 48) u64."""
    shape = jnp.broadcast_shapes(a64.shape[:-1], b64.shape[:-1])
    t = jnp.zeros(shape + (2 * NLIMBS,), dtype=jnp.uint64)
    a64 = jnp.broadcast_to(a64, shape + (NLIMBS,))
    b64 = jnp.broadcast_to(b64, shape + (NLIMBS,))

    def body(i, t):
        ai = jax.lax.dynamic_index_in_dim(a64, i, axis=-1, keepdims=True)
        window = jax.lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        return jax.lax.dynamic_update_slice_in_dim(t, window + ai * b64, i, axis=-1)

    return jax.lax.fori_loop(0, NLIMBS, body, t)


@jax.jit
def fp_mont_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Montgomery product: (a·b·R^-1) mod p over (..., 24) u32 limbs.

    Column magnitude bound: products accumulate ≤ 24·(2^16-1)^2 ≈ 2^36.6 per
    column; each reduction round adds m·p (≤ 2^32 per column) and a folded
    carry (≤ 2^21) — all far below 2^64.
    """
    t = _poly_mul_acc(a.astype(jnp.uint64), b.astype(jnp.uint64))
    t = jnp.concatenate([t, jnp.zeros(t.shape[:-1] + (1,), jnp.uint64)], axis=-1)  # (..., 49)
    n0 = jnp.uint64(N0)

    def body(i, t):
        ti = jax.lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        m = ((ti & jnp.uint64(MASK)) * n0) & jnp.uint64(MASK)
        window = jax.lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        window = window + m[..., None] * _P64
        # t[i] is now ≡ 0 mod 2^16; move its whole value up as carry
        carry = window[..., 0] >> LIMB_BITS
        window = window.at[..., 0].set(jnp.uint64(0))
        window = window.at[..., 1].add(carry)
        return jax.lax.dynamic_update_slice_in_dim(t, window, i, axis=-1)

    t = jax.lax.fori_loop(0, NLIMBS, body, t)
    hi = _carry_pass(t[..., NLIMBS:])  # 25 columns; result < 2p fits 24
    return _cond_sub_p(hi[..., :NLIMBS]).astype(jnp.uint32)


@jax.jit
def fp_mont_sqr(a: jax.Array) -> jax.Array:
    return fp_mont_mul(a, a)


@partial(jax.jit, static_argnums=(1,))
def fp_pow_const(a: jax.Array, exponent: int) -> jax.Array:
    """a^exponent via square-and-multiply over the constant's bits (MSB-first).

    a in Montgomery form; exponent is a static Python int (e.g. p-2 for
    inversion, (p+1)/4 for sqrt). a == 0 yields 0 for exponent >= 1."""
    bits = jnp.asarray(np.array([int(c) for c in bin(exponent)[2:]], dtype=np.int32))
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape).astype(jnp.uint32)

    def body(i, acc):
        acc = fp_mont_mul(acc, acc)
        mul = fp_mont_mul(acc, a)
        return jnp.where(bits[i] == 1, mul, acc)

    return jax.lax.fori_loop(0, bits.shape[0], body, one)


def fp_inv(a: jax.Array) -> jax.Array:
    """Batched inversion (Fermat): a^(p-2). Zero maps to zero."""
    return fp_pow_const(a, P - 2)


def fp_sqrt_candidate(a: jax.Array) -> jax.Array:
    """a^((p+1)/4) — square root when a is a QR (p ≡ 3 mod 4)."""
    return fp_pow_const(a, (P + 1) // 4)


# --- small-multiple reduction (lazy-sum support) ----------------------------

# p·2^j limb vectors for conditional subtraction of accumulated sums (< 8p;
# 8p < 2^384 so intermediates stay canonical in 24 limbs — 16p would not)
_P_MULTIPLES = [jnp.asarray(int_to_limbs((P << j))).astype(jnp.uint64) for j in range(3)]


def _geq_vec(a64, vec):
    gt = jnp.zeros(a64.shape[:-1], dtype=bool)
    lt = jnp.zeros(a64.shape[:-1], dtype=bool)
    for i in range(NLIMBS - 1, -1, -1):
        ai = a64[..., i]
        vi = vec[i]
        gt = gt | (~lt & (ai > vi))
        lt = lt | (~gt & (ai < vi))
    return ~lt


def fp_sum_stack(arr, axis: int = 0) -> jax.Array:
    """Sum ≤ 8 canonical (..., 24) u32 values along `axis`, reduced mod p.

    Lazy reduction: one u64 column sum + carry pass + conditional subtraction
    of 4p/2p/p (binary descent), instead of per-addition reductions."""
    k = arr.shape[axis]
    assert k <= 8
    t = _carry_pass(arr.astype(jnp.uint64).sum(axis=axis))
    for j in range(2, -1, -1):
        if (1 << j) < k or j == 0:
            vec = _P_MULTIPLES[j]
            sub = _sub_limbs(t, vec)
            t = jnp.where(_geq_vec(t, vec)[..., None], sub, t)
    return t.astype(jnp.uint32)


# --- host codecs ------------------------------------------------------------


def ints_to_mont_batch(xs) -> np.ndarray:
    """Host: iterable of ints -> (N, 24) u32 Montgomery batch."""
    xs = list(xs)
    if not xs:
        return np.zeros((0, NLIMBS), np.uint32)
    return np.stack([to_mont(int(x) % P) for x in xs])


def mont_batch_to_ints(arr) -> list[int]:
    a = np.asarray(arr, dtype=np.uint32)
    return [from_mont_int(a[i]) for i in range(a.shape[0])]
