"""Batched Merkle multiproof extraction as a JAX kernel (the read lane).

For a pow2-bucketed batch of (tree, gindex) queries over equal-shape chunk
trees, ONE jitted program hashes every interior level once — the same flat
adjacent-pair fold as `engine/state_root.tree_root_batch`, so queries that
hit the same subtree share its interior-node hashing by construction — and
then gathers each query's sibling rows with a gather-form level walk: no
scatter, int32-pinned `fori_loop` bounds (the tpulint dtype-pin rule:
under x64 an unpinned induction var is s64 while GSPMD emits s32 offset
math for the dynamic slices, failing HLO verification on sharded
programs).

Layout: the level stack concatenates into a per-tree binary heap addressed
by generalized index (heap[:, 1] = root, heap[:, C:2C] = the leaf chunks;
heap[:, 0] is a zero row, so a query shallower than the batch depth
gathers zeros past its own branch, which the host slices off). Sibling row
i of a query is the sibling of its node at distance i above it — exactly
`ssz/proofs.build_proof` order (deepest first), so host and device
branches compare byte-for-byte.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .sha256_jax import merkle_parent_level


def _sibling_rows_impl(chunks: jax.Array, tree_ids: jax.Array,
                       gindices: jax.Array):
    """(K, C, 8) uint32 chunk words (C a power of two), (Q,) int32 tree
    slots, (Q,) int32 in-tree generalized indices ->
    (siblings (Q, D, 8), nodes (Q, 8), roots (K, 8)) with D = max(depth, 1).

    A depth-d query fills siblings[:d]; rows beyond gather the zero heap
    row. `nodes` is each query's own node (leaf chunk or subtree root), so
    callers can verify branches without re-deriving the leaf."""
    k, c, _ = chunks.shape
    assert c & (c - 1) == 0, "per-tree chunk count must be a power of two"
    depth = (c - 1).bit_length() if c > 1 else 0
    q = gindices.shape[0]

    levels = [chunks.reshape(k * c, 8)]
    for _ in range(depth):
        levels.append(merkle_parent_level(levels[-1]))
    roots = levels[-1].reshape(k, 8)

    # per-tree heap addressed by generalized index: row 0 zero, row 1 the
    # root, rows [C, 2C) the leaves — pure concatenation, no scatter
    zero_row = jnp.zeros((k, 1, 8), dtype=chunks.dtype)
    heap = jnp.concatenate(
        [zero_row] + [lvl.reshape(k, -1, 8) for lvl in reversed(levels)],
        axis=1)
    flat = heap.reshape(k * 2 * c, 8)

    base = tree_ids * jnp.int32(2 * c)
    nodes = jnp.take(flat, base + gindices, axis=0)
    out0 = jnp.zeros((q, max(depth, 1), 8), dtype=chunks.dtype)

    def step(i, carry):
        g, out = carry
        rows = jnp.take(flat, base + (g ^ jnp.int32(1)), axis=0)
        out = jax.lax.dynamic_update_index_in_dim(out, rows, i, axis=1)
        # clamp at the root: a finished (shallower) query's next sibling is
        # root ^ 1 = the zero row, never a wrapped heap read
        return jnp.maximum(g >> jnp.int32(1), jnp.int32(1)), out

    # int32 loop bounds: the dtype-pin rule (see ops/sha256_jax._compress)
    _, siblings = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(depth), step, (gindices, out0))
    return siblings, nodes, roots


sibling_rows_batch = jax.jit(_sibling_rows_impl)
