"""Batched BLS12-381 scalar-field (Fr, 255-bit) arithmetic + NTT as JAX kernels.

The sharding/DAS layer of the reference (specs/sharding/beacon-chain.md:104-174,
specs/das/das-core.md:90-129) does polynomial commitments and data-availability
erasure coding over the curve's *scalar* field Fr (MODULUS = curve order r).
The reference leaves this math to research-prototype Python; here it is a
first-class TPU kernel family:

  - field elements: (..., 16) uint32 limb vectors, 16 bits per limb,
    little-endian, Montgomery domain (R = 2^256) — the shared deferred-carry
    SOS core in ops/limb_mont.py, specialized to the scalar modulus (the base
    field Fp in ops/fp_jax.py specializes the same factory at 24 limbs);
  - the NTT (number-theoretic transform over the 2-adic roots of unity of Fr,
    2-adicity 32) is an iterative radix-2 Cooley-Tukey with static shapes:
    log2(n) stages, each one vectorized butterfly pass over the whole batch —
    XLA sees a flat chain of ~log2(n) fused elementwise stages, no dynamic
    control flow;
  - polynomial-eval extension (the DAS "extend by 2x" primitive) and coset
    evaluation build on the NTT.

Differential oracle: plain Python pow/mult mod r (host_* helpers below).
"""
from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

# Re-exported host surface: established import site for callers.
from .fr_host import (  # noqa: F401
    PRIMITIVE_ROOT,
    R_MODULUS,
    TWO_ADICITY,
    domain,
    host_ntt,
    root_of_unity,
)
from .limb_mont import MontgomeryField

NLIMBS = 16
FIELD = MontgomeryField(R_MODULUS, NLIMBS)

# Established public surface (bound to the shared factory instance).
int_to_limbs = FIELD.int_to_limbs
limbs_to_int = FIELD.limbs_to_int
to_mont = FIELD.to_mont
from_mont_int = FIELD.from_mont_int
ints_to_mont_batch = FIELD.ints_to_mont_batch
mont_batch_to_ints = FIELD.mont_batch_to_ints
ONE_MONT = FIELD.one_mont
MOD_LIMBS = FIELD.mod_limbs

fr_add = FIELD.add
fr_sub = FIELD.sub
fr_mul = FIELD.mont_mul
fr_pow_const = FIELD.pow_const
fr_inv = FIELD.inv


# --- roots of unity / domains (host math in ops/fr_host.py) ------------------


def _twiddle_tables(n: int, inverse: bool) -> list[np.ndarray]:
    """Per-stage Montgomery twiddle tables for the DIT NTT below.

    Stage s (s = 1..log2 n) works on blocks of size 2^s and needs the first
    2^(s-1) powers of the 2^s-th root (or its inverse)."""
    tables = []
    m = 2
    while m <= n:
        w = root_of_unity(m)
        if inverse:
            w = pow(w, R_MODULUS - 2, R_MODULUS)
        tables.append(ints_to_mont_batch([pow(w, k, R_MODULUS) for k in range(m // 2)]))
        m *= 2
    return tables


def _bit_reverse_perm(n: int) -> np.ndarray:
    bits = n.bit_length() - 1
    idx = np.arange(n)  # tpulint: disable=jit-purity -- trace-time table on the static NTT size
    rev = np.zeros(n, dtype=np.int64)  # tpulint: disable=jit-purity -- trace-time table on the static NTT size
    for b in range(bits):
        rev |= ((idx >> b) & 1) << (bits - 1 - b)
    return rev


def _ntt_impl(values: jax.Array, tables) -> jax.Array:
    """Iterative radix-2 DIT over (..., n, 16) Montgomery limbs."""
    n = values.shape[-2]
    x = values[..., jnp.asarray(_bit_reverse_perm(n)), :]
    for s, table in enumerate(tables):
        half = 1 << s
        blocks = n // (2 * half)
        xb = x.reshape(x.shape[:-2] + (blocks, 2, half, NLIMBS))
        lo = xb[..., 0, :, :]
        hi = fr_mul(xb[..., 1, :, :], jnp.asarray(table))
        out = jnp.stack([fr_add(lo, hi), fr_sub(lo, hi)], axis=-3)
        x = out.reshape(x.shape)
    return x


@lru_cache(maxsize=None)
def make_ntt(n: int, inverse: bool = False):
    """Build a jitted NTT (or inverse NTT) of static size n over (..., n, 16)
    Montgomery-limb arrays. Inverse includes the 1/n scaling.

    Cached per (n, inverse): callers (das extension/recovery hit five domains
    per blob) must share one jitted closure per domain or XLA recompiles the
    butterfly chain every call."""
    tables = _twiddle_tables(n, inverse)
    n_inv_mont = jnp.asarray(to_mont(pow(n, R_MODULUS - 2, R_MODULUS)))

    @jax.jit
    def ntt(values: jax.Array) -> jax.Array:
        out = _ntt_impl(values, tables)
        if inverse:
            out = fr_mul(out, n_inv_mont)
        return out

    return ntt


# --- host oracle: fr_host.host_ntt (re-exported above) -----------------------
