"""Shared Montgomery limb-field kernel factory (Fp and Fr specialize this).

Both BLS12-381 fields used by the framework — the 381-bit base field
(ops/fp_jax.py, 24×16-bit limbs) and the 255-bit scalar field
(ops/fr_jax.py, 16×16-bit limbs) — need the same deferred-carry SOS
Montgomery core: 16-bit little-endian limbs in uint32 lanes, uint64
accumulation columns, per-limb fori_loops (unrolling is fatal to XLA compile
times at this op count). One parameterized implementation generates both so
a carry-scheme or bound fix lands in exactly one place.

Magnitude analysis (worst case, nlimbs = 24): schoolbook columns accumulate
≤ 24·(2^16-1)^2 ≈ 2^36.6; each Montgomery round adds m·p (≤ 2^32 per
column) plus a folded carry (≤ 2^21) — far below the uint64 ceiling.

Perf notes (measured, TPU v5e, pairing_check_batch):
- this fori/dynamic-slice form: ~27ms/verify, compile ~750s (batch 64);
  throughput flat in batch size (59/s at 2048) => VPU-compute-bound.
- a fully parallel rewrite (broadcast poly-mul + pad-stack-sum columns,
  full-word Montgomery reduction, bounded magnitude passes +
  associative-scan carry-lookahead) was built and differentially validated:
  TPU runtime equivalent (32/s), compile ~20%% faster, but CPU (test-suite)
  10x SLOWER — XLA/CPU lowers the fori form to tight loops. Reverted.
- the real path to the 100k/s target is a representation change that puts
  limb products on the MXU (int8 limbs with int32 matmul accumulation, or
  RNS), likely as a Pallas kernel with explicit VMEM tiling — tracked for
  the next round.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)


class MontgomeryField:
    """Batched modular arithmetic over (..., nlimbs) u32 limb vectors.

    Elements are stored in the Montgomery domain (R = 2^(16·nlimbs)).
    Attributes `add`, `sub`, `neg`, `mont_mul`, `mont_sqr` are jitted; use
    `pow_const(x, e)` for static-exponent chains (inversion, sqrt)."""

    def __init__(self, modulus: int, nlimbs: int, limb_bits: int = 16):
        assert modulus < 1 << (nlimbs * limb_bits)
        self.modulus = modulus
        self.nlimbs = nlimbs
        self.limb_bits = limb_bits
        self.mask = (1 << limb_bits) - 1
        # host numpy, NOT jnp: creating device arrays here would initialize
        # the default (possibly remote-TPU) backend at import time, hanging
        # every pure-host consumer (spec compiler via kzg -> fr_jax) when the
        # tunnel is down. Under jit these trace to constants either way.
        self.base = np.uint64(1 << limb_bits)
        self.R = 1 << (nlimbs * limb_bits)
        self.R_mod = self.R % modulus
        self.n0 = (-pow(modulus, -1, 1 << limb_bits)) % (1 << limb_bits)
        self.mod_limbs = self.int_to_limbs(modulus)
        self._mod64 = self.mod_limbs.astype(np.uint64)
        self.one_mont = self.int_to_limbs(self.R_mod)
        self.zero = np.zeros(nlimbs, dtype=np.uint32)

        self.add = jax.jit(self._add)
        self.sub = jax.jit(self._sub)
        self.neg = jax.jit(self._neg)
        self.mont_mul = jax.jit(self._mont_mul)
        self.mont_sqr = jax.jit(lambda a: self._mont_mul(a, a))
        self.pow_const = partial(jax.jit, static_argnums=(1,))(self._pow_const)

    # --- host codecs --------------------------------------------------------

    def int_to_limbs(self, x: int) -> np.ndarray:
        assert 0 <= x < self.R
        lb, m = self.limb_bits, self.mask
        return np.array([(x >> (lb * i)) & m for i in range(self.nlimbs)], dtype=np.uint32)

    def limbs_to_int(self, limbs) -> int:
        arr = np.asarray(limbs, dtype=np.uint64).reshape(-1)
        return sum(int(v) << (self.limb_bits * i) for i, v in enumerate(arr))

    def to_mont(self, x: int) -> np.ndarray:
        return self.int_to_limbs((x % self.modulus) * self.R % self.modulus)

    def from_mont_int(self, limbs) -> int:
        return (self.limbs_to_int(limbs) * pow(self.R, -1, self.modulus)) % self.modulus

    def ints_to_mont_batch(self, xs) -> np.ndarray:
        xs = list(xs)
        if not xs:
            return np.zeros((0, self.nlimbs), np.uint32)
        return np.stack([self.to_mont(int(x)) for x in xs])

    def mont_batch_to_ints(self, arr) -> list[int]:
        a = np.asarray(arr, dtype=np.uint32)
        return [self.from_mont_int(a[i]) for i in range(a.shape[0])]

    # --- carry / borrow / compare primitives --------------------------------

    def carry_pass(self, t):
        """(..., N) u64 deferred-carry columns -> per-limb < 2^16 except the
        last (which receives the final carry)."""
        n = t.shape[-1]
        mask64 = jnp.uint64(self.mask)
        lb = self.limb_bits

        def body(i, t):
            v = jax.lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
            t = jax.lax.dynamic_update_index_in_dim(t, v & mask64, i, axis=-1)
            nxt = jax.lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(t, nxt + (v >> lb), i + 1, axis=-1)

        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(n - 1), body, t)

    def sub_limbs(self, x, y):
        """x - y over canonical u64 limb vectors, assuming x >= y."""
        out = jnp.zeros(jnp.broadcast_shapes(x.shape, y.shape), dtype=jnp.uint64)
        borrow0 = jnp.zeros(out.shape[:-1], dtype=jnp.uint64)
        xb = jnp.broadcast_to(x, out.shape)
        yb = jnp.broadcast_to(y, out.shape)
        mask64 = jnp.uint64(self.mask)
        lb = self.limb_bits

        def body(i, st):
            borrow, out = st
            xi = jax.lax.dynamic_index_in_dim(xb, i, axis=-1, keepdims=False)
            yi = jax.lax.dynamic_index_in_dim(yb, i, axis=-1, keepdims=False)
            d = xi + self.base - yi - borrow
            out = jax.lax.dynamic_update_index_in_dim(out, d & mask64, i, axis=-1)
            borrow = jnp.uint64(1) - (d >> lb)
            return borrow, out

        _, res = jax.lax.fori_loop(jnp.int32(0), jnp.int32(self.nlimbs), body, (borrow0, out))
        return res

    def geq_vec(self, a64, vec):
        """Lexicographic a >= vec over canonical u64 limbs (vec a (nlimbs,) array)."""
        gt = jnp.zeros(a64.shape[:-1], dtype=bool)
        lt = jnp.zeros(a64.shape[:-1], dtype=bool)
        for i in range(self.nlimbs - 1, -1, -1):
            ai = a64[..., i]
            vi = vec[i]
            gt = gt | (~lt & (ai > vi))
            lt = lt | (~gt & (ai < vi))
        return ~lt

    def cond_sub_mod(self, a64):
        """Subtract the modulus where a >= modulus (a canonical, a < 2·mod)."""
        sub = self.sub_limbs(a64, self._mod64)
        return jnp.where(self.geq_vec(a64, self._mod64)[..., None], sub, a64)

    # --- field ops ----------------------------------------------------------

    def _add(self, a, b):
        t = self.carry_pass(a.astype(jnp.uint64) + b.astype(jnp.uint64))
        return self.cond_sub_mod(t).astype(jnp.uint32)

    def _sub(self, a, b):
        mod_minus_b = self.sub_limbs(self._mod64, b.astype(jnp.uint64))
        # b == 0 -> mod_minus_b == modulus; cond_sub_mod renormalizes.
        t = self.carry_pass(a.astype(jnp.uint64) + mod_minus_b)
        return self.cond_sub_mod(t).astype(jnp.uint32)

    def _neg(self, a):
        z = jnp.all(a == 0, axis=-1, keepdims=True)
        res = self.sub_limbs(self._mod64, a.astype(jnp.uint64))
        return jnp.where(z, jnp.zeros_like(res), res).astype(jnp.uint32)

    def poly_mul_acc(self, a64, b64):
        """Schoolbook product columns: (..., n) x (..., n) -> (..., 2n) u64."""
        shape = jnp.broadcast_shapes(a64.shape[:-1], b64.shape[:-1])
        t = jnp.zeros(shape + (2 * self.nlimbs,), dtype=jnp.uint64)
        a64 = jnp.broadcast_to(a64, shape + (self.nlimbs,))
        b64 = jnp.broadcast_to(b64, shape + (self.nlimbs,))

        def body(i, t):
            ai = jax.lax.dynamic_index_in_dim(a64, i, axis=-1, keepdims=True)
            window = jax.lax.dynamic_slice_in_dim(t, i, self.nlimbs, axis=-1)
            return jax.lax.dynamic_update_slice_in_dim(t, window + ai * b64, i, axis=-1)

        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(self.nlimbs), body, t)

    def _mont_mul(self, a, b):
        """Montgomery product (a·b·R^-1 mod modulus); SOS with deferred carries."""
        t = self.poly_mul_acc(a.astype(jnp.uint64), b.astype(jnp.uint64))
        t = jnp.concatenate([t, jnp.zeros(t.shape[:-1] + (1,), jnp.uint64)], axis=-1)
        n0 = jnp.uint64(self.n0)
        mask64 = jnp.uint64(self.mask)
        lb = self.limb_bits

        def body(i, t):
            ti = jax.lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
            m = ((ti & mask64) * n0) & mask64
            window = jax.lax.dynamic_slice_in_dim(t, i, self.nlimbs, axis=-1)
            window = window + m[..., None] * self._mod64
            # t[i] is now ≡ 0 mod 2^16; move its whole value up as carry
            carry = window[..., 0] >> lb
            window = window.at[..., 0].set(jnp.uint64(0))
            window = window.at[..., 1].add(carry)
            return jax.lax.dynamic_update_slice_in_dim(t, window, i, axis=-1)

        t = jax.lax.fori_loop(jnp.int32(0), jnp.int32(self.nlimbs), body, t)
        hi = self.carry_pass(t[..., self.nlimbs :])
        return self.cond_sub_mod(hi[..., : self.nlimbs]).astype(jnp.uint32)

    def _pow_const(self, a, exponent: int):
        """a^exponent, square-and-multiply over the static exponent bits."""
        bits = jnp.asarray(np.array([int(c) for c in bin(exponent)[2:]], dtype=np.int32))
        one = jnp.broadcast_to(jnp.asarray(self.one_mont), a.shape).astype(jnp.uint32)

        def body(i, acc):
            acc = self._mont_mul(acc, acc)
            mul = self._mont_mul(acc, a)
            return jnp.where(bits[i] == 1, mul, acc)

        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(bits.shape[0]), body, one)

    def inv(self, a):
        """Batched Fermat inversion a^(mod-2); zero maps to zero."""
        return self.pow_const(a, self.modulus - 2)
