"""sha256 as a batched JAX/XLA kernel (TPU twin of ops/sha256_np.py).

Operates on uint32 *word lanes* so the whole Merkle level / shuffle round is a
single fused XLA computation: shape (N, 16) message-word blocks in, (N, 8)
digest words out. The 64 rounds run as a `lax.fori_loop` (constant trip
count, no data-dependent control flow): the rounds are inherently serial, the
parallelism is across lanes, and a rolled loop keeps the HLO graph ~64x
smaller than full unrolling — programs that instantiate many compressions
(Merkle level stacks, the epoch engine) would otherwise take minutes to
XLA-compile.

Used by: ssz device Merkleization, the swap-or-not shuffle kernel
(ops/shuffle.py), and randao/seed derivation inside the jitted epoch engine.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .sha256_np import _H0, _K


def _rotr(x, n):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress(state, w16):
    """state: tuple of 8 (...,) uint32; w16: (..., 16) uint32 block words."""
    k = jnp.asarray(_K)

    # message schedule: (..., 64) built in-place from the 16 block words
    w = jnp.concatenate(
        [w16, jnp.zeros(w16.shape[:-1] + (48,), dtype=jnp.uint32)], axis=-1
    )

    def sched(t, w):
        w15 = jax.lax.dynamic_index_in_dim(w, t - 15, axis=-1, keepdims=False)
        w2 = jax.lax.dynamic_index_in_dim(w, t - 2, axis=-1, keepdims=False)
        w16_ = jax.lax.dynamic_index_in_dim(w, t - 16, axis=-1, keepdims=False)
        w7 = jax.lax.dynamic_index_in_dim(w, t - 7, axis=-1, keepdims=False)
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
        return jax.lax.dynamic_update_index_in_dim(w, w16_ + s0 + w7 + s1, t, axis=-1)

    # int32 loop bounds: under x64 the induction var would be s64, and the
    # GSPMD partitioner emits s32 offset math for the dynamic slices — the
    # mixed-width compare fails HLO verification on sharded programs.
    w = jax.lax.fori_loop(jnp.int32(16), jnp.int32(64), sched, w)

    def round_fn(t, vars8):
        a, b, c, d, e, f, g, h = vars8
        wt = jax.lax.dynamic_index_in_dim(w, t, axis=-1, keepdims=False)
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(jnp.int32(0), jnp.int32(64), round_fn, tuple(state))
    return tuple(s + v for s, v in zip(state, out))


def _init_state(shape):
    return tuple(jnp.full(shape, int(_H0[i]), dtype=jnp.uint32) for i in range(8))


def sha256_1block(w16: jax.Array) -> jax.Array:
    """sha256 of messages that fit one padded block. w16: (..., 16) pre-padded
    message words (caller sets the 0x80... terminator and bit length).
    Returns (..., 8) digest words."""
    state = _compress(_init_state(w16.shape[:-1]), w16)
    return jnp.stack(state, axis=-1)


# Constant padding block for 64-byte messages: 0x80 then bitlen 512.
_PAD64 = np.zeros(16, dtype=np.uint32)
_PAD64[0] = 0x80000000
_PAD64[15] = 512


def sha256_64B_words(w16: jax.Array) -> jax.Array:
    """Batched sha256 of 64-byte messages given as (..., 16) uint32 words
    (Merkle parent hash: left_root_words || right_root_words). -> (..., 8).

    GSPMD caveat: when the batch dim is SHARDED and smaller than the mesh
    (the top levels of a sharded Merkle fold), the partitioned while-loop
    schedule updates miscompile on the CPU backend (jax 0.4.37 logs
    "Involuntary full rematerialization" around the loop's dynamic slices
    and the values diverge). Keep sharded callers' batch dims either
    >= the mesh size or replicated — tests/test_mesh_epoch.py gathers the
    scan output before the cross-layout state-root comparison for this
    reason."""
    state = _compress(_init_state(w16.shape[:-1]), w16)
    pad = jnp.broadcast_to(jnp.asarray(_PAD64), w16.shape[:-1] + (16,))
    state = _compress(state, pad)
    return jnp.stack(state, axis=-1)


def merkle_parent_level(nodes: jax.Array) -> jax.Array:
    """One Merkle level: (2N, 8) digest-word nodes -> (N, 8) parents."""
    pairs = nodes.reshape(-1, 16)
    return sha256_64B_words(pairs)


def bytes_to_words(data: bytes) -> np.ndarray:
    """Host helper: big-endian bytes -> uint32 word array (len % 4 == 0)."""
    from .sha256_np import _bytes_to_words

    return _bytes_to_words(np.frombuffer(data, dtype=np.uint8))


def words_to_bytes(words: np.ndarray) -> bytes:
    from .sha256_np import _words_to_bytes

    return _words_to_bytes(np.asarray(words, dtype=np.uint32).reshape(-1)).tobytes()
