"""Batched BLS12-381 towers, curves, and optimal-ate pairing as JAX kernels.

Device twin of the pure-Python oracle (crypto/bls12_381.py) — every function
here is differentially tested against it. Representation is a pytree of limb
arrays (ops/fp_jax.py): Fp2 = (re, im), Fp12 = 6 Fp2 coefficients of w^i
(w^6 = xi = 1+u), points = coordinate tuples. Batch axes lead.

Performance/compile structure — the two rules that shape this file:

1. STACK independent Fp multiplies. A naive Fp12 multiply would instantiate
   108 separate Montgomery-multiply subgraphs; instead operands are stacked
   on a leading axis and multiplied in ONE fp_mont_mul call (wider vector op,
   ~50x smaller HLO). This is what makes the Miller loop compile in seconds
   on a 1-core host and saturate VPU lanes on TPU.
2. LAZY-REDUCE sums. Coefficient sums accumulate in uint64 columns and
   reduce once (fp_sum_stack), not per addition.

Algorithmic notes (correctness-critical):
- Twist/untwist follows the oracle: Q=(x', y') on E'(Fp2) maps to
  (x'·xi^-1·w^4, y'·xi^-1·w^3) on E(Fp12).
- Miller loop runs in Jacobian coordinates on the twist — no inversions.
  Line values may be scaled by any nonzero Fp2 factor (killed by the final
  exponentiation since |Fp2*| divides p^6-1); with scale 2YZ^3·xi (double) /
  HZ·xi (add) the line is polynomial:
    double T=(X,Y,Z):  l = [2YZ^3·xi·yp]_w0 + [3X^3 - 2Y^2]_w3 + [-3X^2Z^2·xp]_w5
    add T+(xq,yq):     l = [HZ·xi·yp]_w0 + [r·xq - HZ·yq]_w3 + [-r·xp]_w5
  with H = xq·Z^2 - X, r = yq·Z^3 - Y.
- Final exponentiation: easy part via conj/inv/frobenius; hard part via
    (x-1)^2 (x+p) (x^2+p^2-1) + 3  ==  3 · (p^4 - p^2 + 1)/r
  (asserted at import). This yields the CUBE of the canonical reduced
  pairing — gcd(3, r) = 1 makes cubing a bijection on G_T, so every ==1 /
  equality-of-pairings check is unaffected, while needing only four 64-bit
  x-exponentiations instead of a 1500-bit pow. x < 0 is handled by
  conjugation (valid in the cyclotomic subgroup).
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bls12_381 as oracle
from . import fp_jax, fp_rns

# Swappable field backend: every field op goes through `F.<op>` resolved at
# call time, so one tower/pairing implementation runs on either the
# positional-limb kernels (fp_jax: canonical 24x16-bit, CPU-friendly) or the
# RNS kernels (fp_rns: 64-channel residues, the TPU/MXU path). The two
# representations differ in trailing dim (24 vs 64), so jit caches never
# collide across a switch.
F = fp_rns

FIELD_BACKENDS = {"limb": fp_jax, "rns": fp_rns}


def set_field_backend(name: str) -> None:
    global F
    F = FIELD_BACKENDS[name]


def field_backend() -> str:
    return next(k for k, v in FIELD_BACKENDS.items() if v is F)


P = fp_jax.P
assert fp_rns.P == P

X_PARAM = oracle.X_PARAM
ABS_X = abs(X_PARAM)
R_ORDER = oracle.R

assert ((X_PARAM - 1) ** 2 * (X_PARAM + P) * (X_PARAM**2 + P**2 - 1) + 3) == 3 * (
    (P**4 - P**2 + 1) // R_ORDER
)

# --- Fp2 = Fp[u]/(u^2+1) ----------------------------------------------------
# element: tuple (a, b) of (..., 24) u32 Montgomery limb arrays


def f2_zero_like(x):
    z = jnp.zeros_like(x[0])
    return (z, z)


def f2_one_like(x):
    one = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), x[0].shape).astype(x[0].dtype)
    return (one, jnp.zeros_like(one))


def f2_add(x, y):
    return (F.fp_add(x[0], y[0]), F.fp_add(x[1], y[1]))


def f2_sub(x, y):
    return (F.fp_sub(x[0], y[0]), F.fp_sub(x[1], y[1]))


def f2_neg(x):
    return (F.fp_neg(x[0]), F.fp_neg(x[1]))


def f2_conj(x):
    return (x[0], F.fp_neg(x[1]))


def _bcast2(x, y):
    a, b = jnp.broadcast_arrays(x[0], y[0])
    c, d = jnp.broadcast_arrays(x[1], y[1])
    return (a, c), (b, d)


def f2_mul_wide(x, y):
    """Karatsuba, 3 stacked Fp products, WIDE result (lazy reduction): the
    output components are unreduced double-Montgomery-scale values that may
    be summed/xi-folded before one fp_mont_reduce per final coefficient.
    Under the positional-limb backend wide == reduced and this is the plain
    Fp2 multiply."""
    x, y = _bcast2(x, y)
    a, b = x
    c, d = y
    A = jnp.stack([a, b, F.fp_add(a, b)])
    B = jnp.stack([c, d, F.fp_add(c, d)])
    M = F.fp_mul_wide(A, B)
    ac, bd, t = M[0], M[1], M[2]
    return (F.fp_sub(ac, bd), F.fp_sub(F.fp_sub(t, ac), bd))


def f2_reduce(x):
    return (F.fp_mont_reduce(x[0]), F.fp_mont_reduce(x[1]))


def f2_mul(x, y):
    return f2_reduce(f2_mul_wide(x, y))


def f2_sqr(x):
    a, b = x
    A = jnp.stack([F.fp_add(a, b), F.fp_add(a, a)])
    B = jnp.stack([F.fp_sub(a, b), b])
    M = F.fp_mont_reduce(F.fp_mul_wide(A, B))
    return (M[0], M[1])


def f2_mul_fp(x, s):
    S = jnp.stack(jnp.broadcast_arrays(*((s,) * 2)))
    M = F.fp_mont_mul(jnp.stack(jnp.broadcast_arrays(x[0], x[1])), S)
    return (M[0], M[1])


def f2_mul_xi(x):
    """multiply by xi = 1 + u: (a+bu)(1+u) = (a-b) + (a+b)u."""
    a, b = x
    return (F.fp_sub(a, b), F.fp_add(a, b))


def f2_inv(x):
    a, b = x
    norm = F.fp_add(F.fp_mont_sqr(a), F.fp_mont_sqr(b))
    ninv = F.fp_inv(norm)
    M = F.fp_mont_mul(jnp.stack(jnp.broadcast_arrays(a, b)), ninv)
    return (M[0], F.fp_neg(M[1]))


def f2_stack(elems):
    """list of Fp2 -> stacked Fp2 with leading axis len(elems)."""
    res = [jnp.broadcast_arrays(e[0], e[1]) for e in elems]
    shapes = jnp.broadcast_shapes(*[r[0].shape for r in res])
    return (
        jnp.stack([jnp.broadcast_to(r[0], shapes) for r in res]),
        jnp.stack([jnp.broadcast_to(r[1], shapes) for r in res]),
    )


def f2_unstack(x, n):
    return [(x[0][i], x[1][i]) for i in range(n)]


# --- Fp12 as 6 Fp2 coefficients of w^i, w^6 = xi ---------------------------


def f12_one_like(c):
    one = f2_one_like(c)
    z = f2_zero_like(c)
    return (one, z, z, z, z, z)


def f12_conj(x):
    """f^(p^6): negate odd-w coefficients."""
    return tuple(c if i % 2 == 0 else f2_neg(c) for i, c in enumerate(x))


def _combine_tables(pairs):
    """index tables mapping a product list (degrees i+j) to 6 coefficients.

    Returns (lo_idx, hi_idx) padded gather matrices; pad slot = len(pairs)
    (a zero row appended to the product stack)."""
    lo = [[] for _ in range(6)]
    hi = [[] for _ in range(6)]
    for idx, (i, j) in enumerate(pairs):
        d = i + j
        (lo[d] if d < 6 else hi[d - 6]).append(idx)
    pad = len(pairs)
    lo_w = max(max(len(g) for g in lo), 1)
    hi_w = max(max(len(g) for g in hi), 1)
    lo_m = np.full((6, lo_w), pad, dtype=np.int32)
    hi_m = np.full((6, hi_w), pad, dtype=np.int32)
    for k in range(6):
        lo_m[k, : len(lo[k])] = lo[k]
        hi_m[k, : len(hi[k])] = hi[k]
    return jnp.asarray(lo_m), jnp.asarray(hi_m)


def _combine_products(prod, lo_m, hi_m):
    """prod: stacked Fp2 products (m, ..., 24); combine into 6 coefficients
    with w^6 = xi folding: out[k] = sum(lo) + xi·sum(hi)."""
    Pre, Pim = prod
    zero = jnp.zeros_like(Pre[:1])
    PreE = jnp.concatenate([Pre, zero])
    PimE = jnp.concatenate([Pim, zero])
    lo_re = F.fp_sum_stack(PreE[lo_m], axis=1)  # (6, ..., NLIMBS)
    lo_im = F.fp_sum_stack(PimE[lo_m], axis=1)
    hi_re = F.fp_sum_stack(PreE[hi_m], axis=1)
    hi_im = F.fp_sum_stack(PimE[hi_m], axis=1)
    xi_re, xi_im = F.fp_sub(hi_re, hi_im), F.fp_add(hi_re, hi_im)
    # products arrive WIDE; one Montgomery reduction per output coefficient
    # (12 total), batched into a single kernel call
    out = F.fp_mont_reduce(jnp.stack([F.fp_add(lo_re, xi_re), F.fp_add(lo_im, xi_im)]))
    out_re, out_im = out[0], out[1]
    return tuple((out_re[k], out_im[k]) for k in range(6))


_FULL_PAIRS = [(i, j) for i in range(6) for j in range(6)]
# host numpy (device arrays at import would init the default backend)
_FULL_I = np.array([i for i, _ in _FULL_PAIRS])
_FULL_J = np.array([j for _, j in _FULL_PAIRS])
_FULL_LO, _FULL_HI = _combine_tables(_FULL_PAIRS)


def f12_mul(x, y):
    X = f2_stack(list(x))
    Y = f2_stack(list(y))
    A = (X[0][_FULL_I], X[1][_FULL_I])
    B = (Y[0][_FULL_J], Y[1][_FULL_J])
    prod = f2_mul_wide(A, B)  # (36, ..., NLIMBS) wide
    return _combine_products(prod, _FULL_LO, _FULL_HI)


def f12_sqr(x):
    """Squaring via the Fp4 tower view (Chung-Hasan SQR3 shape): with
    s = w^3 (s^2 = xi) and f = A + B·w + C·w^2, A,B,C in Fp4 = Fp2[s],

        f^2 = (A^2 + 2BC·s) + (2AB + C^2·s)·w + (B^2 + 2AC)·w^2

    3 Fp4 squarings + 3 Fp4 products = 54 Fp products vs the generic
    f12_mul(x, x)'s 108, with the same one-reduction-per-coefficient
    discipline (12 reductions). Differentially covered by every pairing
    test plus test_f12_mul_sqr_inv_conj."""
    c0, c1, c2, c3, c4, c5 = x
    A = (c0, c3)
    B = (c1, c4)
    C = (c2, c5)

    def fp4_mul_wide(u, v):
        # (a + b·s)(c + d·s) = (ac + xi·bd) + (ad + bc)·s  — Karatsuba over
        # Fp2, products kept WIDE
        a, b = u
        c, d = v
        X = f2_stack([a, b, f2_add(a, b)])
        Y = f2_stack([c, d, f2_add(c, d)])
        M = f2_mul_wide(X, Y)
        ac = (M[0][0], M[1][0])
        bd = (M[0][1], M[1][1])
        t = (M[0][2], M[1][2])
        re = f2_add(ac, f2_mul_xi(bd))
        im = f2_sub(f2_sub(t, ac), bd)
        return (re, im)

    def fp4_dbl(u):
        return (f2_add(u[0], u[0]), f2_add(u[1], u[1]))

    def fp4_mul_s(u):
        # s·(a + b·s) = xi·b + a·s  (on wide values: xi fold is add/sub)
        return (f2_mul_xi(u[1]), u[0])

    A2 = fp4_mul_wide(A, A)
    B2 = fp4_mul_wide(B, B)
    C2 = fp4_mul_wide(C, C)
    AB = fp4_mul_wide(A, B)
    AC = fp4_mul_wide(A, C)
    BC = fp4_mul_wide(B, C)

    out0 = tuple(f2_add(p_, q_) for p_, q_ in zip(A2, fp4_mul_s(fp4_dbl(BC))))
    out1 = tuple(f2_add(p_, q_) for p_, q_ in zip(fp4_dbl(AB), fp4_mul_s(C2)))
    out2 = tuple(f2_add(p_, q_) for p_, q_ in zip(B2, fp4_dbl(AC)))

    # one batched reduction for all 12 Fp coefficients
    re = jnp.stack([out0[0][0], out1[0][0], out2[0][0], out0[1][0], out1[1][0], out2[1][0]])
    im = jnp.stack([out0[0][1], out1[0][1], out2[0][1], out0[1][1], out1[1][1], out2[1][1]])
    red = F.fp_mont_reduce(jnp.stack([re, im]))
    rre, rim = red[0], red[1]
    return tuple((rre[k], rim[k]) for k in range(6))


_SPARSE_J = (0, 3, 5)
_SPARSE_PAIRS = [(i, j) for j in _SPARSE_J for i in range(6)]
_SPARSE_I = np.array([i for i, _ in _SPARSE_PAIRS])
_SPARSE_LO, _SPARSE_HI = _combine_tables(_SPARSE_PAIRS)


def f12_mul_sparse035(f, l0, l3, l5):
    """f * (l0·w^0 + l3·w^3 + l5·w^5) with li in Fp2 — 18 stacked products."""
    Fs = f2_stack(list(f))
    A = (Fs[0][_SPARSE_I], Fs[1][_SPARSE_I])
    L = f2_stack([l0] * 6 + [l3] * 6 + [l5] * 6)
    prod = f2_mul_wide(A, L)
    return _combine_products(prod, _SPARSE_LO, _SPARSE_HI)


# Fp6 view (v = w^2, Fp6 = Fp2[v]/(v^3 - xi)) used only for inversion.


def _f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))))
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)), f2_mul_xi(t2)
    )
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(
        f2_mul(a0, c0),
        f2_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2))),
    )
    tinv = f2_inv(t)
    return (f2_mul(c0, tinv), f2_mul(c1, tinv), f2_mul(c2, tinv))


def _f12_to_f6_pair(x):
    """w-basis -> (c0, c1) with x = c0(v) + c1(v)·w, v = w^2."""
    return (x[0], x[2], x[4]), (x[1], x[3], x[5])


def _f6_pair_to_f12(c0, c1):
    return (c0[0], c1[0], c0[1], c1[1], c0[2], c1[2])


def _f6_mul_by_v(a):
    return (f2_mul_xi(a[2]), a[0], a[1])


def f12_inv(x):
    c0, c1 = _f12_to_f6_pair(x)
    # (c0 + c1 w)^-1 = (c0 - c1 w) / (c0^2 - c1^2 v)
    c1sq_v = _f6_mul_by_v(_f6_mul(c1, c1))
    denom = tuple(f2_sub(a, b) for a, b in zip(_f6_mul(c0, c0), c1sq_v))
    dinv = _f6_inv(denom)
    num0 = _f6_mul(c0, dinv)
    num1 = tuple(f2_neg(c) for c in _f6_mul(c1, dinv))
    return _f6_pair_to_f12(num0, num1)


# --- Frobenius constants (computed on host with the oracle's Fp2 math) ------


def _host_f2_pow(base, e):
    r = (1, 0)
    b = base
    while e:
        if e & 1:
            r = oracle.f2_mul(r, b)
        b = oracle.f2_sqr(b)
        e >>= 1
    return r


_GAMMA1 = [_host_f2_pow(oracle.XI, i * (P - 1) // 6) for i in range(6)]
_GAMMA2 = [
    oracle.f2_mul((g[0], (-g[1]) % P), g) for g in _GAMMA1  # γ^(p+1): conj(γ)·γ
]


def _const_f2_stack(gammas):
    # numpy (NOT jnp): these are cached in module globals, and the first
    # pairing call may happen inside a jit trace — a cached jnp constant
    # created there would be a DynamicJaxprTracer leaking into later traces
    # (UnexpectedTracerError on the second jitted pairing). numpy constants
    # are trace-safe and embed per-trace.
    re = np.stack([F.to_mont(g[0]) for g in gammas])
    im = np.stack([F.to_mont(g[1]) for g in gammas])
    return re, im


_GAMMA_CACHE: dict = {}


def _gamma_arrays():
    # deferred so importing this module does not touch a jax backend;
    # keyed per field backend (the representations differ)
    key = field_backend()
    if key not in _GAMMA_CACHE:
        _GAMMA_CACHE[key] = (_const_f2_stack(_GAMMA1), _const_f2_stack(_GAMMA2))
    return _GAMMA_CACHE[key]


def _gamma_shaped(g, like):
    """(6, 24) constant stack -> (6, 1...1, 24) broadcastable against like."""
    return g.reshape((6,) + (1,) * (like.ndim - 1) + (F.NLIMBS,))


def f12_frobenius(x):
    """f^p in the w-basis: conj each Fp2 coefficient, times γ1^i (stacked)."""
    (g_re, g_im), _ = _gamma_arrays()
    Xs = f2_stack([f2_conj(c) for c in x])
    prod = f2_mul(Xs, (_gamma_shaped(g_re, x[0][0]), _gamma_shaped(g_im, x[0][0])))
    return tuple(f2_unstack(prod, 6))


def f12_frobenius2(x):
    """f^(p^2): coefficient i times γ2^i (γ2 real)."""
    _, (g_re, g_im) = _gamma_arrays()
    Xs = f2_stack(list(x))
    prod = f2_mul(Xs, (_gamma_shaped(g_re, x[0][0]), _gamma_shaped(g_im, x[0][0])))
    return tuple(f2_unstack(prod, 6))


# --- pairing ----------------------------------------------------------------


def _dbl_step(T, xp, yp):
    """One Miller doubling: T=(X,Y,Z) Jacobian on E'(Fp2); line coefficients
    per module docstring. Independent multiplies grouped into stacked calls."""
    X, Y, Z = T
    sq = f2_sqr(f2_stack([X, Y, Z]))
    A, B, Zsq = f2_unstack(sq, 3)
    E = f2_add(f2_add(A, A), A)  # 3X^2
    m1 = f2_mul(
        f2_stack([X, Y, Z, E, E]),
        f2_stack([B, Z, Zsq, X, Zsq]),
    )
    D0, YZ, Zcu, EX, EZsq = f2_unstack(m1, 5)
    D = f2_add(D0, D0)
    D = f2_add(D, D)  # 4XY^2
    sq2 = f2_sqr(f2_stack([E, B]))
    Fq, C = f2_unstack(sq2, 2)
    X3 = f2_sub(Fq, f2_add(D, D))
    C8 = f2_add(C, C)
    C8 = f2_add(C8, C8)
    C8 = f2_add(C8, C8)
    m2 = f2_mul(f2_stack([E, Y]), f2_stack([f2_sub(D, X3), Zcu]))
    Y3a, YZcu = f2_unstack(m2, 2)
    Y3 = f2_sub(Y3a, C8)
    Z3 = f2_add(YZ, YZ)
    # lines: l0 = 2YZ^3·xi·yp ; l3 = 3X^3 - 2Y^2 ; l5 = -3X^2 Z^2·xp
    xi0 = f2_mul_xi(f2_add(YZcu, YZcu))
    lm = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(xi0[0], xi0[1], EZsq[0], EZsq[1])),
        jnp.stack(jnp.broadcast_arrays(yp, yp, xp, xp)),
    )
    l0 = (lm[0], lm[1])
    l5 = f2_neg((lm[2], lm[3]))
    l3 = f2_sub(EX, f2_add(B, B))
    return (X3, Y3, Z3), (l0, l3, l5)


def _add_step(T, Q, xp, yp):
    """Mixed addition T + Q (Q affine on E'(Fp2)); returns (T3, line)."""
    X, Y, Z = T
    xq, yq = Q
    Zsq = f2_sqr(Z)
    m1 = f2_mul(f2_stack([xq, Z]), f2_stack([Zsq, Zsq]))
    U, Zcu = f2_unstack(m1, 2)
    S = f2_mul(yq, Zcu)
    H = f2_sub(U, X)
    r = f2_sub(S, Y)
    sq = f2_sqr(f2_stack([H, r]))
    Hsq, rsq = f2_unstack(sq, 2)
    m2 = f2_mul(f2_stack([H, X, H]), f2_stack([Hsq, Hsq, Z]))
    Hcu, V, HZ = f2_unstack(m2, 3)
    X3 = f2_sub(f2_sub(rsq, Hcu), f2_add(V, V))
    m3 = f2_mul(
        f2_stack([r, Y, r, HZ]),
        f2_stack([f2_sub(V, X3), Hcu, xq, yq]),
    )
    Y3a, YHcu, rxq, HZyq = f2_unstack(m3, 4)
    Y3 = f2_sub(Y3a, YHcu)
    Z3 = f2_mul(Z, H)
    xiHZ = f2_mul_xi(HZ)
    lm = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(xiHZ[0], xiHZ[1], r[0], r[1])),
        jnp.stack(jnp.broadcast_arrays(yp, yp, xp, xp)),
    )
    l0 = (lm[0], lm[1])
    l5 = f2_neg((lm[2], lm[3]))
    l3 = f2_sub(rxq, HZyq)
    return (X3, Y3, Z3), (l0, l3, l5)


_X_BITS = [int(c) for c in bin(ABS_X)[3:]]  # MSB dropped


def miller_loop_batch(Qx, Qy, xp, yp):
    """f_{|x|,Q}(P) for batches: Qx,Qy Fp2 pairs ((...,24),(...,24));
    xp,yp Fp arrays. Returns Fp12 (tuple of 6 Fp2).

    Rolled as a fori_loop over the 63 loop bits; the sparse addition step
    runs under lax.cond (|x| has hamming weight 6)."""
    bits = jnp.asarray(np.array(_X_BITS, dtype=bool))
    f = f12_one_like(Qx)
    T = (Qx, Qy, f2_one_like(Qx))

    def add_branch(carry):
        f, T = carry
        T, (l0, l3, l5) = _add_step(T, (Qx, Qy), xp, yp)
        return f12_mul_sparse035(f, l0, l3, l5), T

    def body(i, carry):
        f, T = carry
        T, (l0, l3, l5) = _dbl_step(T, xp, yp)
        f = f12_mul_sparse035(f12_sqr(f), l0, l3, l5)
        return jax.lax.cond(bits[i], add_branch, lambda c: c, (f, T))

    f, T = jax.lax.fori_loop(jnp.int32(0), jnp.int32(len(_X_BITS)), body, (f, T))
    return f12_conj(f)  # x < 0


def f12_cyclotomic_sqr(f):
    """Granger-Scott squaring for UNITARY f (the cyclotomic subgroup — i.e.
    anything after the final exponentiation's easy part): in the
    Fp4 = Fp2[s]/(s^2 - xi) view with s = w^3, f = A + B·w + C·w^2 and

        f^2 = (3·A² - 2·Ā) + (3·xi·C² + 2·B̄)·w + (3·B² - 2·C̄)·w²

    (bars are the Fp4 conjugation s -> -s). 3 Fp4 squarings ≈ half the
    products and reductions of a generic f12_sqr; differentially tested
    against f12_sqr on easy-part outputs."""
    c0, c1, c2, c3, c4, c5 = f
    A = (c0, c3)
    B = (c1, c4)
    C = (c2, c5)

    def fp4_sqr(x):
        a, b = x
        # (a + b·s)^2 = (a^2 + xi·b^2) + (2ab)·s, via 2 squares + 1 product,
        # all three stacked into one wide multiply
        X = f2_stack([a, b, a])
        Y = f2_stack([a, b, b])
        M = f2_mul_wide(X, Y)
        a2 = (M[0][0], M[1][0])
        b2 = (M[0][1], M[1][1])
        ab = (M[0][2], M[1][2])
        re = f2_add(a2, f2_mul_xi(b2))
        im = f2_add(ab, ab)
        red = f2_reduce(f2_stack([re, im]))
        return ((red[0][0], red[1][0]), (red[0][1], red[1][1]))

    def triple(x):
        return f2_add(f2_add(x, x), x)

    def fp4_conj(x):
        return (x[0], f2_neg(x[1]))

    def mul_s(x):
        # s·(a + b·s) = xi·b + a·s
        return (f2_mul_xi(x[1]), x[0])

    A2 = fp4_sqr(A)
    B2 = fp4_sqr(B)
    C2 = fp4_sqr(C)
    cA = fp4_conj(A)
    cB = fp4_conj(B)
    cC = fp4_conj(C)
    sC2 = mul_s(C2)
    Ao = tuple(f2_sub(triple(t), f2_add(c, c)) for t, c in zip(A2, cA))
    Bo = tuple(f2_add(triple(t), f2_add(c, c)) for t, c in zip(sC2, cB))
    Co = tuple(f2_sub(triple(t), f2_add(c, c)) for t, c in zip(B2, cC))
    return (Ao[0], Bo[0], Co[0], Ao[1], Bo[1], Co[1])


def _f12_pow_abs_x(f):
    """f^|x| by square-and-multiply over the fixed 64-bit loop constant.

    f must be unitary (all final-exp hard-part inputs are): the squaring
    chain uses the cyclotomic formulas."""
    bits = jnp.asarray(np.array(_X_BITS, dtype=bool))

    def body(i, r):
        r = f12_cyclotomic_sqr(r)
        return jax.lax.cond(bits[i], lambda r: f12_mul(r, f), lambda r: r, r)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(len(_X_BITS)), body, f)


def _f12_pow_x(f):
    """f^x with x < 0: conj of f^|x| (cyclotomic subgroup)."""
    return f12_conj(_f12_pow_abs_x(f))


def final_exponentiation_batch(f):
    # easy part: f^((p^6-1)(p^2+1))
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frobenius2(f), f)
    # hard part: (x-1)^2 (x+p) (x^2+p^2-1) + 3
    fx = _f12_pow_x(f)
    a = f12_mul(fx, f12_conj(f))  # f^(x-1)
    ax = _f12_pow_x(a)
    a = f12_mul(ax, f12_conj(a))  # f^((x-1)^2)
    b = f12_mul(_f12_pow_x(a), f12_frobenius(a))  # ^(x+p)
    c = f12_mul(
        f12_mul(_f12_pow_x(_f12_pow_x(b)), f12_frobenius2(b)), f12_conj(b)
    )  # ^(x^2+p^2-1)
    f3 = f12_mul(f12_sqr(f), f)
    return f12_mul(c, f3)


def f12_is_one(f):
    """(...) bool: f == 1 (Montgomery domain; representation-aware)."""
    ok = F.fp_is_one_mont(f[0][0])
    zero_parts = [f[0][1]]
    for c in f[1:]:
        zero_parts.extend([c[0], c[1]])
    z = F.fp_is_zero(jnp.stack(jnp.broadcast_arrays(*zero_parts)))
    return ok & jnp.all(z, axis=0)


# --- G1 (over Fp) Jacobian ops for aggregation ------------------------------


def g1_double(pt):
    X, Y, Z = pt
    sq = F.fp_mont_mul(jnp.stack([X, Y, Z]), jnp.stack([X, Y, Z]))
    A, B, _ = sq[0], sq[1], sq[2]
    m1 = F.fp_mont_mul(jnp.stack([X, Y]), jnp.stack([B, Z]))
    D0, YZ = m1[0], m1[1]
    C = F.fp_mont_sqr(B)
    D = F.fp_add(D0, D0)
    D = F.fp_add(D, D)
    E = F.fp_add(F.fp_add(A, A), A)
    Fv = F.fp_mont_sqr(E)
    X3 = F.fp_sub(Fv, F.fp_add(D, D))
    C8 = F.fp_add(C, C)
    C8 = F.fp_add(C8, C8)
    C8 = F.fp_add(C8, C8)
    Y3 = F.fp_sub(F.fp_mont_mul(E, F.fp_sub(D, X3)), C8)
    Z3 = F.fp_add(YZ, YZ)
    return (X3, Y3, Z3)


def g1_add(p1, p2):
    """Complete-ish Jacobian addition with branchless special cases
    (inf inputs, equal points -> double, opposite points -> inf)."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    inf1 = F.fp_is_zero(Z1)
    inf2 = F.fp_is_zero(Z2)
    Z1sq = F.fp_mont_sqr(Z1)
    Z2sq = F.fp_mont_sqr(Z2)
    m1 = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(X1, X2, Z2, Z1)),
        jnp.stack(jnp.broadcast_arrays(Z2sq, Z1sq, Z2sq, Z1sq)),
    )
    U1, U2, Z2cu, Z1cu = m1[0], m1[1], m1[2], m1[3]
    m2 = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(Y1, Y2)),
        jnp.stack(jnp.broadcast_arrays(Z2cu, Z1cu)),
    )
    S1, S2 = m2[0], m2[1]
    H = F.fp_sub(U2, U1)
    r = F.fp_sub(S2, S1)
    same_x = F.fp_is_zero(H)
    same_y = F.fp_is_zero(r)
    Hsq = F.fp_mont_sqr(H)
    m3 = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(H, U1, Z1)),
        jnp.stack(jnp.broadcast_arrays(Hsq, Hsq, Z2)),
    )
    Hcu, V, Z1Z2 = m3[0], m3[1], m3[2]
    rsq = F.fp_mont_sqr(r)
    X3 = F.fp_sub(F.fp_sub(rsq, Hcu), F.fp_add(V, V))
    m4 = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(r, S1, Z1Z2)),
        jnp.stack(jnp.broadcast_arrays(F.fp_sub(V, X3), Hcu, H)),
    )
    Y3 = F.fp_sub(m4[0], m4[1])
    Z3 = m4[2]
    dX, dY, dZ = g1_double(p1)
    is_dbl = same_x & same_y & ~inf1 & ~inf2
    is_inf_out = same_x & ~same_y & ~inf1 & ~inf2

    def sel(c, a, b):
        return jnp.where(c[..., None], a, b)

    X3 = sel(is_dbl, dX, X3)
    Y3 = sel(is_dbl, dY, Y3)
    Z3 = sel(is_dbl, dZ, Z3)
    Z3 = jnp.where(is_inf_out[..., None], jnp.zeros_like(Z3), Z3)
    X3 = sel(inf1, X2, sel(inf2, X1, X3))
    Y3 = sel(inf1, Y2, sel(inf2, Y1, Y3))
    Z3 = sel(inf1, Z2, sel(inf2, Z1, Z3))
    return (X3, Y3, Z3)


def g1_sum_reduce(pts):
    """Tree-reduce a (N, ...) batch of Jacobian points to a single point."""
    X, Y, Z = pts
    n = X.shape[0]
    while n > 1:
        half = n // 2
        even = (X[: 2 * half : 2], Y[: 2 * half : 2], Z[: 2 * half : 2])
        odd = (X[1 : 2 * half : 2], Y[1 : 2 * half : 2], Z[1 : 2 * half : 2])
        sX, sY, sZ = g1_add(even, odd)
        if n % 2:
            sX = jnp.concatenate([sX, X[-1:]])
            sY = jnp.concatenate([sY, Y[-1:]])
            sZ = jnp.concatenate([sZ, Z[-1:]])
        X, Y, Z = sX, sY, sZ
        n = X.shape[0]
    return X[0], Y[0], Z[0]


def g1_to_affine(pt):
    X, Y, Z = pt
    zinv = F.fp_inv(Z)
    zinv2 = F.fp_mont_sqr(zinv)
    return F.fp_mont_mul(X, zinv2), F.fp_mont_mul(Y, F.fp_mont_mul(zinv, zinv2))


# --- host bridging ----------------------------------------------------------


def fp_to_device(x: int) -> jnp.ndarray:
    return jnp.asarray(F.to_mont(x % P))


def f2_to_device(x) -> tuple:
    return (fp_to_device(x[0]), fp_to_device(x[1]))


def f12_from_device(f) -> tuple:
    """Device Fp12 -> oracle-format tuple of Fp2 int pairs."""
    out = []
    for c in f:
        re = F.from_mont_int(np.asarray(c[0]).reshape(-1, F.NLIMBS)[0])
        im = F.from_mont_int(np.asarray(c[1]).reshape(-1, F.NLIMBS)[0])
        out.append((re, im))
    return tuple(out)


@jax.jit
def pairing_cube_batch(qx, qy, px, py):
    """e(P, Q)^3 (the device-canonical reduced pairing; see module docstring)."""
    return final_exponentiation_batch(miller_loop_batch(qx, qy, px, py))


@jax.jit
def pairing_check_batch(qx, qy, px, py, q2x, q2y, p2x, p2y):
    """Batched check e(P1, Q1)·e(P2, Q2) == 1.

    Inputs: Q* = ((...,24),(...,24)) Fp2 pairs (G2 affine, twist coords);
    P* = (...,24) Fp arrays (G1 affine). Returns (...) bool.
    """
    m1 = miller_loop_batch(qx, qy, px, py)
    m2 = miller_loop_batch(q2x, q2y, p2x, p2y)
    return f12_is_one(final_exponentiation_batch(f12_mul(m1, m2)))


# --- randomized batch check: ONE final exponentiation for the whole batch ---


def g1_scalar_mul_batch(pt, bits):
    """[z]P per item over `bits` ((..., nbits) bool, LSB first), Jacobian
    in/out. 2-bit fixed windows, same structure (and same
    compile-size-vs-op-count tradeoff) as g2_scalar_mul_batch: per-item
    table [0,P,2P,3P], then nbits/2 windows of 2 doubles + one
    table-gathered complete add — vs the plain conditional ladder's
    64 doubles + 64 adds, half of which its select discards. Odd bit
    counts (the 255-bit KZG MSM scalars) zero-pad to the next even width
    (a zero MSB window gathers the identity — harmless)."""
    nbits = bits.shape[-1]
    if nbits % 2:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (1,), dtype=bits.dtype)], axis=-1)
        nbits += 1
    n_windows = nbits // 2

    X, Y, Z = pt
    inf = (jnp.zeros_like(X), jnp.zeros_like(Y), jnp.zeros_like(Z))
    p2 = g1_double(pt)
    table = [inf, pt, p2, g1_add(p2, pt)]
    tab = tuple(jnp.stack([t[i] for t in table]) for i in range(3))

    weights = jnp.asarray(np.array([1, 2], dtype=np.int32))
    digits = jnp.sum(
        bits.reshape(bits.shape[:-1] + (n_windows, 2)).astype(jnp.int32) * weights,
        axis=-1)

    def gather(w):
        d = jnp.take(digits, w, axis=-1)[None, ..., None]
        return tuple(jnp.take_along_axis(c, d, axis=0)[0] for c in tab)

    def body(i, acc):
        w = n_windows - 2 - i
        acc = g1_double(g1_double(acc))
        return g1_add(acc, gather(w))

    acc = gather(n_windows - 1)
    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_windows - 1), body, acc)


@lru_cache(maxsize=1)
def _neg_g1_window_tables():
    """8-bit window tables for the constant base −G1: tables[w][k] =
    [k·2^(8w)]·(−G1), affine with a Z flag (index 0 is the Jacobian zero,
    which the complete g1_add absorbs). Host-computed once per process
    (~2k oracle point-adds), returned as device-ready Montgomery arrays.

    Motivation: pairing_check_rlc multiplies −G1 by every item's random
    64-bit scalar; a fixed base turns the 64-step double-and-add ladder
    (64 adds + 64 doubles batch-wide) into 8 table gathers + 7 adds."""
    gx, gy = oracle.G1_GEN_AFF
    base_pt = oracle.pt_from_affine(oracle.FP_FIELD, (gx, (-gy) % oracle.P))
    enc = F.ints_to_mont_batch
    tabs = []
    for w in range(8):
        step = oracle.pt_mul(oracle.FP_FIELD, base_pt, 1 << (8 * w))
        xs, ys, zs = [0], [0], [0]
        acc = None
        for _ in range(255):
            acc = step if acc is None else oracle.pt_add(oracle.FP_FIELD, acc, step)
            ax, ay = oracle.pt_to_affine(oracle.FP_FIELD, acc)
            xs.append(ax)
            ys.append(ay)
            zs.append(1)
        tabs.append((enc(xs), enc(ys), enc(zs)))
    return (
        np.stack([t[0] for t in tabs]),
        np.stack([t[1] for t in tabs]),
        np.stack([t[2] for t in tabs]),
    )


def g1_fixed_mul_neg_g1(zbits):
    """[z]·(−G1) per item via the window tables; zbits (N, 64) bool, LSB
    first. Jacobian out (Z ∈ {0, 1} per window entry)."""
    tx, ty, tz = (jnp.asarray(t) for t in _neg_g1_window_tables())
    n = zbits.shape[0]
    weights = jnp.asarray(np.array([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.int32))
    idx = jnp.sum(zbits.reshape(n, 8, 8).astype(jnp.int32) * weights, axis=-1)
    acc = None
    for w in range(8):
        pt = (tx[w][idx[:, w]], ty[w][idx[:, w]], tz[w][idx[:, w]])
        acc = pt if acc is None else g1_add(acc, pt)
    return acc


def _g1_jacobian_to_affine_batch(pt):
    X, Y, Z = pt
    zinv = F.fp_inv(Z)
    zinv2 = F.fp_mont_sqr(zinv)
    M = F.fp_mont_mul(
        jnp.stack(jnp.broadcast_arrays(X, Y)),
        jnp.stack(jnp.broadcast_arrays(zinv2, F.fp_mont_mul(zinv, zinv2))),
    )
    return M[0], M[1]


# --- G2 (sextic twist, over Fp2) Jacobian ops -------------------------------
# Point arithmetic on the twist in its native Fp2 coordinates: BLS12-381 and
# its twist both have a = 0, and the curve's b never appears in Jacobian
# add/double, so the G1 formulas lift verbatim to Fp2. Untwisting is linear,
# so sums and scalar multiples computed here ARE the twist coordinates of the
# true G2 results — exactly what miller_loop_batch consumes. These exist for
# the bilinearity collapse in pairing_check_rlc below (VERDICT r4 item 2).


def f2_is_zero(x):
    return F.fp_is_zero(x[0]) & F.fp_is_zero(x[1])


def g2_double(pt):
    X, Y, Z = pt
    A = f2_sqr(X)
    B = f2_sqr(Y)
    C = f2_sqr(B)
    D0 = f2_mul(X, B)
    YZ = f2_mul(Y, Z)
    D = f2_add(D0, D0)
    D = f2_add(D, D)
    E = f2_add(f2_add(A, A), A)
    Fv = f2_sqr(E)
    X3 = f2_sub(Fv, f2_add(D, D))
    C8 = f2_add(C, C)
    C8 = f2_add(C8, C8)
    C8 = f2_add(C8, C8)
    Y3 = f2_sub(f2_mul(E, f2_sub(D, X3)), C8)
    Z3 = f2_add(YZ, YZ)
    return (X3, Y3, Z3)


def g2_add(p1, p2):
    """Complete-ish Jacobian addition over Fp2 (mirror of g1_add):
    branchless special cases for infinity inputs, doubling, opposites."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    inf1 = f2_is_zero(Z1)
    inf2 = f2_is_zero(Z2)
    Z1sq = f2_sqr(Z1)
    Z2sq = f2_sqr(Z2)
    U1 = f2_mul(X1, Z2sq)
    U2 = f2_mul(X2, Z1sq)
    Z2cu = f2_mul(Z2, Z2sq)
    Z1cu = f2_mul(Z1, Z1sq)
    S1 = f2_mul(Y1, Z2cu)
    S2 = f2_mul(Y2, Z1cu)
    H = f2_sub(U2, U1)
    r = f2_sub(S2, S1)
    same_x = f2_is_zero(H)
    same_y = f2_is_zero(r)
    Hsq = f2_sqr(H)
    Hcu = f2_mul(H, Hsq)
    V = f2_mul(U1, Hsq)
    rsq = f2_sqr(r)
    X3 = f2_sub(f2_sub(rsq, Hcu), f2_add(V, V))
    Y3 = f2_sub(f2_mul(r, f2_sub(V, X3)), f2_mul(S1, Hcu))
    Z3 = f2_mul(f2_mul(Z1, Z2), H)
    dX, dY, dZ = g2_double(p1)
    is_dbl = same_x & same_y & ~inf1 & ~inf2
    is_inf_out = same_x & ~same_y & ~inf1 & ~inf2

    def sel2(c, a, b):
        return (jnp.where(c[..., None], a[0], b[0]),
                jnp.where(c[..., None], a[1], b[1]))

    X3 = sel2(is_dbl, dX, X3)
    Y3 = sel2(is_dbl, dY, Y3)
    Z3 = sel2(is_dbl, dZ, Z3)
    zero = (jnp.zeros_like(Z3[0]), jnp.zeros_like(Z3[1]))
    Z3 = sel2(is_inf_out, zero, Z3)
    X3 = sel2(inf1, X2, sel2(inf2, X1, X3))
    Y3 = sel2(inf1, Y2, sel2(inf2, Y1, Y3))
    Z3 = sel2(inf1, Z2, sel2(inf2, Z1, Z3))
    return (X3, Y3, Z3)


def g2_scalar_mul_batch(pt, bits):
    """[z]Q per item over `bits` ((..., nbits) bool, LSB first), Jacobian
    in/out. 2-bit fixed windows: per-item table [0,Q,2Q,3Q] (one double +
    one add), then nbits/2 windows of 2 doubles + one table-gathered add —
    ~130 point-op units vs the plain conditional ladder's ~190 (its
    unconditional add-then-select wastes half its adds). Window width 2 is
    deliberate: a 4-bit table wins ~15% more ops but its 14 unrolled
    table ops compile-explode under the RNS backend (the same reason the
    Miller loop is a fori_loop). Entry 0 is the Jacobian zero, absorbed by
    the complete g2_add. Odd bit counts zero-pad to the next even width
    (a zero MSB window gathers the identity — harmless)."""
    nbits = bits.shape[-1]
    if nbits % 2:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (1,), dtype=bits.dtype)], axis=-1)
        nbits += 1
    n_windows = nbits // 2

    def zero_like(c):
        return (jnp.zeros_like(c[0]), jnp.zeros_like(c[1]))

    X, Y, Z = pt
    inf = (zero_like(X), zero_like(Y), zero_like(Z))
    q2 = g2_double(pt)
    table = [inf, pt, q2, g2_add(q2, pt)]

    # (4, ..., 24) per coordinate component
    def stack_component(i, j):
        return jnp.stack([t[i][j] for t in table])

    tab = tuple((stack_component(i, 0), stack_component(i, 1)) for i in range(3))

    weights = jnp.asarray(np.array([1, 2], dtype=np.int32))
    # (..., n_windows) digit per window, LSB-first windows
    digits = jnp.sum(
        bits.reshape(bits.shape[:-1] + (n_windows, 2)).astype(jnp.int32) * weights,
        axis=-1)

    def gather(w):
        # w may be a traced index: dynamic take along the window axis
        d = jnp.take(digits, w, axis=-1)[None, ..., None]

        def g(c):
            return (jnp.take_along_axis(c[0], d, axis=0)[0],
                    jnp.take_along_axis(c[1], d, axis=0)[0])

        return (g(tab[0]), g(tab[1]), g(tab[2]))

    def body(i, acc):
        w = n_windows - 2 - i
        acc = g2_double(g2_double(acc))
        return g2_add(acc, gather(w))

    acc = gather(n_windows - 1)
    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_windows - 1), body, acc)


def g2_sum_reduce(pts):
    """Tree-reduce a (N, ...) batch of Jacobian G2 points to one point."""
    X, Y, Z = pts

    def take(c, sl):
        return (c[0][sl], c[1][sl])

    n = X[0].shape[0]
    while n > 1:
        half = n // 2
        ev = slice(None, 2 * half, 2)
        od = slice(1, 2 * half, 2)
        sX, sY, sZ = g2_add(
            (take(X, ev), take(Y, ev), take(Z, ev)),
            (take(X, od), take(Y, od), take(Z, od)),
        )
        if n % 2:
            sX = (jnp.concatenate([sX[0], X[0][-1:]]), jnp.concatenate([sX[1], X[1][-1:]]))
            sY = (jnp.concatenate([sY[0], Y[0][-1:]]), jnp.concatenate([sY[1], Y[1][-1:]]))
            sZ = (jnp.concatenate([sZ[0], Z[0][-1:]]), jnp.concatenate([sZ[1], Z[1][-1:]]))
        X, Y, Z = sX, sY, sZ
        n = X[0].shape[0]

    def first(c):
        return (c[0][0], c[1][0])

    return first(X), first(Y), first(Z)


def g2_jacobian_to_affine(pt):
    X, Y, Z = pt
    zinv = f2_inv(Z)
    zinv2 = f2_sqr(zinv)
    ax = f2_mul(X, zinv2)
    ay = f2_mul(Y, f2_mul(zinv, zinv2))
    return ax, ay


@lru_cache(maxsize=1)
def _neg_g1_affine_mont():
    # NUMPY, not jnp: the first call can happen inside a jit trace, and a
    # cached traced constant would leak out of that trace (same stance as
    # _neg_g1_window_tables)
    gx, gy = oracle.G1_GEN_AFF
    return (np.asarray(F.to_mont(gx)), np.asarray(F.to_mont((-gy) % P)))


def f12_prod_reduce(f):
    """Tree-product of a batch of Fp12 values over the leading axis."""
    n = f[0][0].shape[0]
    while n > 1:
        half = n // 2
        even = tuple((c[0][: 2 * half : 2], c[1][: 2 * half : 2]) for c in f)
        odd = tuple((c[0][1 : 2 * half : 2], c[1][1 : 2 * half : 2]) for c in f)
        prod = f12_mul(even, odd)
        if n % 2:
            prod = tuple(
                (jnp.concatenate([c[0], f[k][0][-1:]]), jnp.concatenate([c[1], f[k][1][-1:]]))
                for k, c in enumerate(prod)
            )
        f = prod
        n = f[0][0].shape[0]
    return f


@partial(jax.jit, static_argnames=("p2_is_neg_g1",))
def pairing_check_rlc(qx, qy, px, py, q2x, q2y, p2x, p2y, zbits,
                      p2_is_neg_g1: bool = False, seg_ids=None):
    """Randomized batch verification with a SHARED final exponentiation:

        prod_i [ e(z_i·P1_i, Q1_i) · e(z_i·P2_i, Q2_i) ] == 1

    `zbits`: (N, 64) bool — independent uniform random scalars supplied by
    the HOST per flush (z=0 is excluded by the caller). If every per-item
    check holds the product is 1; a cheating batch passes with probability
    2^-64 over the choice of z (standard Schwartz-Zippel batching, the same
    scheme native BLS libraries use for aggregate verification). Returns a
    scalar bool — callers needing attribution re-check per item.

    vs pairing_check_batch: trades N final exponentiations (~1/3 of total
    cost) for 2N 64-bit G1 scalar multiplications (~1/8), net faster at
    large N.

    `p2_is_neg_g1=True` (what the BLS shim's verification shape always
    satisfies: every second pairing is e(−G1, sig_i)) additionally
    collapses the whole second pairing SET by bilinearity:

        prod_i e(z_i·(−G1), sig_i) = e(−G1, Σ_i z_i·sig_i)

    so N of the 2N Miller loops become N 64-bit G2 ladders (no Fp12 work
    at all), one G2 tree reduce, and ONE extra Miller loop — the Fp12
    squaring/sparse-multiply chain that dominates a Miller loop's cost is
    paid N+1 times instead of 2N (VERDICT r4 item 2). If Σ z_i·sig_i
    lands on the point at infinity the affine conversion degenerates and
    the check simply fails — unreachable for honest batches (probability
    ~2^-64 over z), and an adversary gains nothing (failing closed).

    `seg_ids` (requires p2_is_neg_g1) applies the SAME bilinearity trick
    to the first pairing set, grouped by distinct message: Q1 carries only
    the D distinct H(m) points (leading dim D), `seg_ids` (N,) int32 maps
    item i to its message group, and

        prod_i e(z_i·pk_i, H(m_{g(i)})) = prod_g e(Σ_{i∈g} z_i·pk_i, H(m_g))

    so the flush pays D+1 Miller loops instead of N+1 — for an epoch's
    attestations every committee of a slot signs the same root, D ≪ N.
    Soundness is unchanged: each item keeps its own independent z_i, so the
    product is still prod_i [check_i]^{z_i} and the Schwartz-Zippel bound
    stays 2^-64 per flush. The caller must give every segment in [0, D) at
    least one member (an empty segment sums to infinity, degenerates the
    affine conversion, and fails the batch closed — same stance as the G2
    collapse note above)."""
    if seg_ids is not None:
        assert p2_is_neg_g1, "grouped RLC requires the collapsed -G1 sig side"
        num_segments = qx[0].shape[0]
        a1x, a1y = rlc_collapse_g1_by_message(px, py, zbits, seg_ids, num_segments)
        m1 = miller_loop_batch(qx, qy, a1x, a1y)
        aqx, aqy = rlc_collapse_g2(q2x, q2y, zbits)
        ngx, ngy = _neg_g1_affine_mont()
        m2 = miller_loop_batch(aqx, aqy, ngx, ngy)
        return rlc_tail(m1, m2)
    a1x, a1y = rlc_randomize_g1(px, py, zbits)
    m1 = miller_loop_batch(qx, qy, a1x, a1y)
    if p2_is_neg_g1:
        aqx, aqy = rlc_collapse_g2(q2x, q2y, zbits)
        ngx, ngy = _neg_g1_affine_mont()
        m2 = miller_loop_batch(aqx, aqy, ngx, ngy)
        return rlc_tail(m1, m2)
    one = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), px.shape).astype(px.dtype)
    z2 = g1_scalar_mul_batch((p2x, p2y, one), zbits)
    a2x, a2y = _g1_jacobian_to_affine_batch(z2)
    m2 = miller_loop_batch(q2x, q2y, a2x, a2y)
    prod = f12_prod_reduce(f12_mul(m1, m2))
    single = tuple((c[0][0], c[1][0]) for c in prod)
    return f12_is_one(final_exponentiation_batch(single))


# Named stage boundaries of the fast path — the kernel above and the bench's
# stage profiler (benches/bls_verify_bench.py rlc_stage_breakdown) call these
# SAME helpers, so the published per-stage numbers always decompose the
# shipped kernel.


def rlc_randomize_g1(px, py, zbits):
    """Stage 1: per-item [z_i]·P1_i, affine out."""
    one = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), px.shape).astype(px.dtype)
    z1 = g1_scalar_mul_batch((px, py, one), zbits)
    return _g1_jacobian_to_affine_batch(z1)


def rlc_collapse_g2(q2x, q2y, zbits):
    """Stage 2: the bilinearity collapse — Σ_i [z_i]·sig_i, affine out."""
    one = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), q2x[0].shape).astype(q2x[0].dtype)
    one2 = (one, jnp.zeros_like(one))
    zsig = g2_scalar_mul_batch((q2x, q2y, one2), zbits)
    return g2_jacobian_to_affine(g2_sum_reduce(zsig))


def g1_segment_sum(pts, seg_ids, num_segments, first_segment=0):
    """Segmented Jacobian G1 sum: out[d] = Σ_{i: seg_ids[i] == first_segment+d}.

    `pts`: (N, limbs) coordinate arrays; `seg_ids`: (N,) int32;
    `num_segments` static; `first_segment` may be traced (the mesh variant
    passes axis_index·D_local so each device reduces only its segment
    range). Non-members enter the tree reduce as the Jacobian zero (Z = 0),
    which the complete g1_add absorbs — one masked (N, D) tree reduce, no
    gather/scatter, shape-stable under jit. An empty segment returns
    infinity; callers must not create one (the affine conversion downstream
    degenerates and the batch check fails closed)."""
    X, Y, Z = pts
    n = X.shape[0]
    segs = jnp.arange(num_segments, dtype=seg_ids.dtype) + first_segment
    mask = seg_ids[:, None] == segs[None, :]  # (N, D)
    shape = (n, num_segments) + X.shape[1:]
    Xb = jnp.broadcast_to(X[:, None], shape)
    Yb = jnp.broadcast_to(Y[:, None], shape)
    Zb = jnp.where(mask[..., None], jnp.broadcast_to(Z[:, None], shape),
                   jnp.zeros_like(Z[:, None]))
    return g1_sum_reduce((Xb, Yb, Zb))


def rlc_collapse_g1_by_message(px, py, zbits, seg_ids, num_segments,
                               first_segment=0):
    """Stage 1 (grouped): per-item [z_i]·pk_i via the 64-bit windowed G1
    ladder, then a segmented sum per distinct message — (D,) affine points,
    one Miller-loop operand per distinct H(m)."""
    one = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), px.shape).astype(px.dtype)
    z1 = g1_scalar_mul_batch((px, py, one), zbits)
    seg = g1_segment_sum(z1, seg_ids, num_segments, first_segment)
    return _g1_jacobian_to_affine_batch(seg)


def rlc_miller_loop_count(*millers) -> int:
    """Miller-loop evaluations a set of stage outputs represents: the
    leading batch dim of each Fp12 (1 when unbatched). Shape-only — works
    on jax.eval_shape results, so the D+1 claim is assertable without
    compiling; the grouped fast path costs exactly
    rlc_miller_loop_count(m1, m2) == D + 1 loops."""
    total = 0
    for f in millers:
        c = f[0][0]
        total += int(c.shape[0]) if len(c.shape) > 1 else 1
    return total


def rlc_tail(m1, m2_single):
    """Stage 3: Fp12 tree product of the batched Miller outputs, times the
    collapsed single Miller output, one shared final exponentiation."""
    prod = f12_prod_reduce(m1)
    single = tuple((c[0][0], c[1][0]) for c in prod)
    return f12_is_one(final_exponentiation_batch(f12_mul(single, m2_single)))


# --- Pippenger bucket-MSM ---------------------------------------------------
#
# One multi-scalar multiplication Σ_i [s_i]·P_i for every G1 hot path that
# used to pay a per-item double-and-add ladder: the KZG batch verifier's
# 255-bit coefficient fold (crypto/kzg_batch), committee pubkey aggregation
# (crypto/bls_jax via the sched "msm" work class), and standalone MSM
# requests. Scalars split into w-bit windows; each (item, window) digit d
# selects the bucket multiple [d]·P_i out of a per-item table; the window
# sums reduce with the SAME masked tree machinery as g1_segment_sum (no
# scatter — the tpulint rule that shaped PR 3's grouped RLC); windows
# combine Horner-style with w doublings per step.
#
# Why the gather form: textbook Pippenger scatters points into 2^w-1
# buckets then folds them with a running sum, Σ_k k·B_k. On a scatter-free
# backend the bucket accumulation would need one masked tree lane per
# bucket per window ((N-1)·(2^w-1)·W adds) — strictly MORE work than the
# ladder it replaces. Exchanging the summation order,
#     Σ_k k·(Σ_{i: d_i=k} P_i)  ==  Σ_i [d_i]·P_i,
# turns the scatter into a digit-indexed GATHER from the per-item bucket
# table, so the tree pays one lane per (item, window) instead: N·(2^w-2)
# table ops + (N-1)·W tree adds + (W-1)·(w+1) Horner ops, vs the 2-bit
# ladder's N·(3·ceil(b/2) - 1). At the KZG shape (N=128, b=255, w=4) that
# is ~10.2k point ops vs ~49k — the O(b·n/w) claim with the constant
# actually below the ladder's, which the masked-bucket literal form never
# achieves (see g1_msm_point_ops / g1_ladder_point_ops, pinned by
# tests/test_msm.py the same way tests/test_rlc_grouped.py pins D+1).

MSM_WINDOW = 4  # default window width; 2^w per-item bucket-table entries


def msm_window_digits(bits, window: int = MSM_WINDOW):
    """(..., nbits) LSB-first scalar bits -> (..., W) int32 window digits,
    W = ceil(nbits/window). nbits zero-pads up to a multiple of `window`
    (a zero MSB digit gathers the bucket-0 identity — harmless, same
    stance as g1_scalar_mul_batch's odd-width pad). Shape-only callers
    (the eval_shape loop-count pin) read W off the result shape."""
    nbits = bits.shape[-1]
    rem = (-nbits) % window
    if rem:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (rem,), dtype=bits.dtype)],
            axis=-1)
        nbits += rem
    n_windows = nbits // window
    weights = jnp.asarray([1 << i for i in range(window)], dtype=jnp.int32)
    return jnp.sum(
        bits.reshape(bits.shape[:-1] + (n_windows, window)).astype(jnp.int32)
        * weights, axis=-1)


def _g1_bucket_tables(pt, window: int):
    """Per-item bucket-multiple tables: tab[k] = [k]·P_i for k < 2^window,
    stacked on a leading bucket axis — (2^w, N, limbs) per coordinate.
    Entry 0 is the Jacobian zero (absorbed by the complete g1_add); even
    entries double tab[k/2], odd entries add P once — 2^(w-1)-1 batched
    doubles + 2^(w-1)-1 batched adds total."""
    X, Y, Z = pt
    table = [(jnp.zeros_like(X), jnp.zeros_like(Y), jnp.zeros_like(Z)), pt]
    for k in range(2, 1 << window):
        table.append(g1_double(table[k // 2]) if k % 2 == 0
                     else g1_add(table[k - 1], pt))
    return tuple(jnp.stack([t[i] for t in table]) for i in range(3))


def g1_msm_pippenger(pt, bits, window: int = MSM_WINDOW):
    """Σ_i [s_i]·P_i — windowed bucket MSM, one Jacobian point out.

    `pt`: (N, limbs) Jacobian coordinate triple (Z = 0 entries contribute
    the identity, so infinity pads and zero scalars are both safe);
    `bits`: (N, nbits) bool, LSB first; `window` static.

    Stages (all shape-stable under jit):
      1. digits (N, W) via msm_window_digits;
      2. per-item bucket tables (2^w, N, limbs) via _g1_bucket_tables;
      3. bucket-multiple gather: take_along_axis picks [d_ij]·P_i per
         (item, window) — the scatter-free dual of bucket accumulation;
      4. window sums: ONE masked tree reduce over the item axis with W
         lanes (the g1_segment_sum tree, mask folded into the digit-0
         identity rows);
      5. Horner combine, MSB window first: w doublings + one gathered add
         per fori_loop step (W-1 steps — strictly fewer than the 2-bit
         ladder's ceil(b/2)-1; bounds pinned int32 per the PR-1 s64/s32
         dtype rule)."""
    digits = msm_window_digits(bits, window)            # (N, W)
    n_windows = digits.shape[-1]
    tab = _g1_bucket_tables(pt, window)                 # (2^w, N, L)
    gathered = tuple(
        jnp.take_along_axis(jnp.moveaxis(c, 0, 1), digits[..., None], axis=1)
        for c in tab)                                   # (N, W, L)
    Sx, Sy, Sz = g1_sum_reduce(gathered)                # (W, L)

    def body(i, acc):
        w = n_windows - 2 - i
        for _ in range(window):
            acc = g1_double(acc)
        nxt = (jnp.take(Sx, w, axis=0), jnp.take(Sy, w, axis=0),
               jnp.take(Sz, w, axis=0))
        return g1_add(acc, nxt)

    acc = (Sx[n_windows - 1], Sy[n_windows - 1], Sz[n_windows - 1])
    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_windows - 1), body, acc)


@partial(jax.jit, static_argnames=("window",))
def _g1_msm_program(X, Y, Z, bits, window: int = MSM_WINDOW):
    """Jitted MSM entry: one XLA program per (n-bucket, nbits, window) —
    callers pad the item count to a pow2 bucket so the jit cache stays
    bounded (CompileTracker-pinned in tests/test_msm.py)."""
    return g1_msm_pippenger((X, Y, Z), bits, window)


@jax.jit
def _g1_aggregate_program(X, Y, Z):
    """All-ones-scalar MSM degenerate: Σ_i P_i via the bucketed tree sum
    (no digits, no tables — every item lands in bucket 1 of a single
    window). The committee-pubkey fast path."""
    return g1_sum_reduce((X, Y, Z))


@jax.jit
def _g1_subgroup_program(X, Y, Z, bits):
    """[r]·P_i == inf per item (r broadcast as fixed 255-bit scalar bits):
    batched r-subgroup membership through the shared windowed ladder, so
    cold pubkey validation leaves the host along with the aggregation."""
    return F.fp_is_zero(g1_scalar_mul_batch((X, Y, Z), bits)[2])


@lru_cache(maxsize=1)
def _r_order_bits():
    # NUMPY, not jnp: cached module constant, same trace-leak stance as
    # _neg_g1_window_tables
    return np.array([(R_ORDER >> i) & 1 for i in range(255)], dtype=bool)


def _msm_pow2_pad(n: int, min_bucket: int = 8) -> int:
    b = min_bucket
    while b < n:
        b *= 2
    return b


def _scalar_bits_lsb(scalars, nbits: int) -> np.ndarray:
    out = np.zeros((len(scalars), nbits), dtype=bool)
    for i, s in enumerate(scalars):
        for b in range(nbits):
            out[i, b] = (s >> b) & 1
    return out


def g1_msm_device(points_aff, scalars, nbits: int,
                  window: int = MSM_WINDOW):
    """Host-callable MSM: affine int pairs + int scalars in, affine int
    pair out (None for the identity). Pads the item count to a pow2
    bucket with (G1 generator, scalar 0) so the jit cache holds one
    program per (bucket, nbits, window), then runs _g1_msm_program; the
    affine unprojection is one host modular inverse on the single
    reduced point."""
    b = _msm_pow2_pad(len(points_aff))
    pad = b - len(points_aff)
    points_aff = list(points_aff) + [oracle.G1_GEN_AFF] * pad
    scalars = list(scalars) + [0] * pad
    enc = F.ints_to_mont_batch
    X = jnp.asarray(enc([p[0] for p in points_aff]))
    Y = jnp.asarray(enc([p[1] for p in points_aff]))
    Z = jnp.broadcast_to(jnp.asarray(F.ONE_MONT), X.shape).astype(X.dtype)
    bits = jnp.asarray(_scalar_bits_lsb(scalars, nbits))
    sx, sy, sz = jax.device_get(_g1_msm_program(X, Y, Z, bits, window))  # tpulint: disable=recompile-risk -- nbits is a caller config constant (64 RLC / 255 full-width), not data-dependent; the item axis is pow2-bucketed above
    unmont = lambda v: F.from_mont_int(np.asarray(v).reshape(-1, F.NLIMBS)[0])
    xj, yj, zj = unmont(sx), unmont(sy), unmont(sz)
    if zj == 0:
        return None
    zinv = pow(zj, P - 2, P)
    return (xj * zinv * zinv % P, yj * zinv * zinv * zinv % P)


def g1_aggregate_device(points_aff):
    """Σ_i P_i (all-ones MSM fast path): affine int pairs in, affine pair
    out (None for an infinity sum). Pads to the pow2 bucket with Jacobian
    zeros — the complete add absorbs them, so padding never perturbs the
    sum."""
    b = _msm_pow2_pad(len(points_aff))
    pad = b - len(points_aff)
    enc = F.ints_to_mont_batch
    X = jnp.asarray(enc([p[0] for p in points_aff] + [0] * pad))
    Y = jnp.asarray(enc([p[1] for p in points_aff] + [0] * pad))
    ones = np.zeros(b, dtype=bool)
    ones[: len(points_aff)] = True
    Z = jnp.where(jnp.asarray(ones)[:, None],
                  jnp.broadcast_to(jnp.asarray(F.ONE_MONT), X.shape),
                  jnp.zeros_like(X)).astype(X.dtype)
    sx, sy, sz = jax.device_get(_g1_aggregate_program(X, Y, Z))
    unmont = lambda v: F.from_mont_int(np.asarray(v).reshape(-1, F.NLIMBS)[0])
    xj, yj, zj = unmont(sx), unmont(sy), unmont(sz)
    if zj == 0:
        return None
    zinv = pow(zj, P - 2, P)
    return (xj * zinv * zinv % P, yj * zinv * zinv * zinv % P)


def g1_subgroup_check_device(points_aff) -> np.ndarray:
    """r-subgroup membership per affine point, batched: (n,) bool. The
    255-bit fixed scalar r is broadcast across the bucket-padded batch
    (pads are Jacobian zeros — [r]·inf == inf reports True and is
    discarded)."""
    n = len(points_aff)
    b = _msm_pow2_pad(n)
    pad = b - n
    enc = F.ints_to_mont_batch
    X = jnp.asarray(enc([p[0] for p in points_aff] + [0] * pad))
    Y = jnp.asarray(enc([p[1] for p in points_aff] + [0] * pad))
    live = np.zeros(b, dtype=bool)
    live[:n] = True
    Z = jnp.where(jnp.asarray(live)[:, None],
                  jnp.broadcast_to(jnp.asarray(F.ONE_MONT), X.shape),
                  jnp.zeros_like(X)).astype(X.dtype)
    bits = jnp.broadcast_to(jnp.asarray(_r_order_bits())[None, :], (b, 255))
    ok = jax.device_get(_g1_subgroup_program(X, Y, Z, bits))
    return np.asarray(ok)[:n]


# Shape-only cost accounting for the eval_shape pins (tests/test_msm.py),
# the BASELINE.md stage table, and benches/msm_bench.py — derived purely
# from (n, nbits, window), never from compiled programs, so the claims are
# assertable without tracing (same stance as rlc_miller_loop_count).


def g1_ladder_loop_count(bits) -> int:
    """Sequential fori_loop trip count of the 2-bit per-item ladder
    (g1_scalar_mul_batch) for a (..., nbits) bits operand — works on
    jax.eval_shape results."""
    nbits = bits.shape[-1]
    return (nbits + 1) // 2 - 1


def msm_loop_count(digits) -> int:
    """Sequential fori_loop trip count of the Pippenger Horner combine for
    a (..., W) digits operand (msm_window_digits output) — works on
    jax.eval_shape results."""
    return digits.shape[-1] - 1


def g1_ladder_op_counts(n: int, nbits: int) -> dict:
    """Batched G1 point ops (one per lane) the per-item ladder pays for an
    (n, nbits) MSM: per item, a 4-entry table (1 double + 1 add) then
    ceil(nbits/2)-1 window steps of 2 doubles + 1 gathered add."""
    nw = (nbits + 1) // 2
    return {"doubles": n * (1 + 2 * (nw - 1)), "adds": n * nw}


def g1_msm_op_counts(n: int, nbits: int, window: int = MSM_WINDOW) -> dict:
    """Batched G1 point ops the Pippenger path pays for an (n, nbits, w)
    MSM: bucket tables + masked window tree + Horner combine."""
    n_windows = -(-nbits // window)
    half = (1 << (window - 1)) - 1
    return {
        "doubles": n * half + window * (n_windows - 1),
        "adds": n * half + (n - 1) * n_windows + (n_windows - 1),
    }


def g1_ladder_point_ops(n: int, nbits: int) -> int:
    c = g1_ladder_op_counts(n, nbits)
    return c["doubles"] + c["adds"]


def g1_msm_point_ops(n: int, nbits: int, window: int = MSM_WINDOW) -> int:
    c = g1_msm_op_counts(n, nbits, window)
    return c["doubles"] + c["adds"]
