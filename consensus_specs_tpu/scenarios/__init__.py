"""Long-horizon scenario engine (ROADMAP item 4 — the L6/L7 closure).

A scenario is a seeded, randomized multi-epoch adversarial history —
reorg storms, fork ladders (proposer equivocation), slashing waves,
empty-slot droughts, sync-committee rotation across a fork boundary —
materialized ONCE (`history.build_history`) as spec-valid SSZ objects plus
a replayable step script, then replayed through three lanes
(`lanes.oracle_lane` / `engine_lane` / `firehose_lane`) that must agree
bit-identically on every checkpoint (fork-choice head + head state root +
justified/finalized checkpoints).

The L7 loop closes in `emit`/`diff`: scenario segments are written from
the TPU lane into the reference `<preset>/<fork>/<runner>/<handler>`
vector tree via gen/, replayed back through conformance.runner, and
diffed field-by-field against reference-shaped (oracle-emitted) vectors —
conformance in BOTH directions.

jax-free at module level (analysis/layering.py pins this): every device
dependency (engine bridge, sched dispatch) is a deferred import inside
the lane that needs it, so scripting/diffing scenarios never drags in a
TPU runtime.
"""
from .script import EpochPlan, ScenarioScript, build_script  # noqa: F401
from .history import ScenarioHistory, Segment, build_history  # noqa: F401
from .lanes import (  # noqa: F401
    LaneResult,
    assert_converged,
    device_head_checker,
    engine_lane,
    firehose_lane,
    oracle_lane,
    replay_history,
)
from .emit import emit_history, scenario_test_cases  # noqa: F401
from .diff import diff_checkpoints, diff_vector_trees  # noqa: F401
