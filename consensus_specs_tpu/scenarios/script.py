"""Seeded scenario scripts: WHAT happens each epoch, decided up front.

The script layer is pure planning — stdlib `random.Random` seeded with
`f"scenario:{seed}"` (the robustness/faults.py per-site stream idiom), no
spec objects, no jax. `build_history` materializes a script into SSZ
objects; keeping the planner separate means the seed→plan mapping is
stable even as the materializer grows new mechanics, which is the
seed/replay contract the vector emitter depends on (same seed, same
tree, byte-identical — tests/test_scenarios.py double-render check).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random

# Epoch event kinds, in escalation order. `calm` epochs carry full-committee
# in-block attestations (justification/finality keeps advancing); everything
# else trades some liveness for adversarial structure.
CALM = "calm"
DROUGHT = "drought"                # empty-slot stretches, gossip-only votes
REORG_STORM = "reorg_storm"        # private branch released late, head flips
EQUIVOCATION = "equivocation_ladder"  # double proposals + proposer slashings
SLASHING_WAVE = "slashing_wave"    # attester double-vote, committee slashed

EVENT_KINDS = (CALM, DROUGHT, REORG_STORM, EQUIVOCATION, SLASHING_WAVE)


@dataclass
class EpochPlan:
    """One epoch's event assignment."""

    epoch: int
    kind: str
    params: dict = field(default_factory=dict)


@dataclass
class ScenarioScript:
    """The full seeded plan for one scenario run."""

    seed: int
    preset: str
    forks: tuple            # ("phase0", "altair") — pre fork, post fork
    fork_epoch: int         # epoch at which forks[1] activates
    epochs: int             # total scenario length in epochs
    plans: list             # [EpochPlan] * epochs

    @property
    def name(self) -> str:
        return f"seed_{self.seed}_epochs_{self.epochs}_fork_{self.fork_epoch}"

    def plan_for(self, epoch: int) -> EpochPlan:
        return self.plans[epoch]


def build_script(seed: int, *, epochs: int = 8, preset: str = "minimal",
                 forks: tuple = ("phase0", "altair"), fork_epoch: int = 2,
                 max_slashing_waves: int = 2,
                 max_equivocation_epochs: int = 4) -> ScenarioScript:
    """Compose a seeded epoch-by-epoch plan.

    Guard rails the materializer relies on:
      * epoch 0 and the epochs around the fork boundary are calm (the
        store needs an attested base before a storm can flip heads, and
        the fork handoff anchors a fresh store from the canonical chain);
      * the two epochs AFTER the post-fork anchor are also calm:
        get_forkchoice_store pins the fresh store's justified/finalized
        checkpoints to (anchor_epoch, anchor_root), and filter_block_tree
        compares descendant STATES against those by equality (the only
        escape is GENESIS_EPOCH, which a mid-history anchor forfeits) —
        in-state finality needs two consecutive justified epochs to
        realize (anchor_epoch, anchor_root) and unstick the head walk;
      * slashing waves are budgeted — each wave burns a whole committee
        (~1/16 of the default 64-validator world), and an over-slashed
        set starves proposer selection;
      * storm depth (private-branch length) and release split are chosen
        so the late branch strictly outweighs the public one under
        LMD-GHOST's one-sticky-vote-per-epoch rule (history._storm_epoch).
    """
    if epochs < 2:
        raise ValueError("a scenario needs at least 2 epochs")
    if not (0 < fork_epoch < epochs):
        raise ValueError("fork_epoch must fall inside the scenario")
    rng = Random(f"scenario:{seed}")
    slashing_budget = max_slashing_waves
    equivocation_budget = max_equivocation_epochs
    plans = []
    for epoch in range(epochs):
        boundary = epoch in (
            0, fork_epoch - 1, fork_epoch, fork_epoch + 1, fork_epoch + 2)
        if boundary:
            plans.append(EpochPlan(epoch, CALM))
            continue
        kind = rng.choices(
            EVENT_KINDS, weights=(0.34, 0.16, 0.25, 0.15, 0.10))[0]
        if kind == SLASHING_WAVE and slashing_budget <= 0:
            kind = CALM
        if kind == EQUIVOCATION and equivocation_budget <= 0:
            kind = DROUGHT
        params: dict = {}
        if kind == DROUGHT:
            # which in-epoch slots go blockless (never all: the epoch must
            # keep a spine so attestation targets stay resolvable)
            params["skip_every"] = rng.choice((2, 3))
        elif kind == REORG_STORM:
            # public branch runs `public` blocks, private branch `private`
            # blocks; private > 2*public guarantees the weight flip
            public = rng.choice((1, 2))
            params["public"] = public
            params["private"] = public * 2 + rng.choice((1, 2))
        elif kind == EQUIVOCATION:
            params["rungs"] = rng.choice((1, 2))
            equivocation_budget -= 1
        elif kind == SLASHING_WAVE:
            params["attester"] = True
            slashing_budget -= 1
        plans.append(EpochPlan(epoch, kind, params))
    return ScenarioScript(
        seed=seed, preset=preset, forks=tuple(forks),
        fork_epoch=fork_epoch, epochs=epochs, plans=plans)
