"""L7 closure, inbound: load reference-shaped vector trees back and diff
them field-by-field.

`diff_vector_trees(a, b)` walks two `<preset>/<fork>/<runner>/<handler>/
<suite>/<case>` trees (either a repo root containing `tests/` or the tests
dir itself) and returns a list of human-readable difference strings —
empty means byte-identical trees. Byte equality is the primary check (the
emission contract is deterministic down to the snappy framing); when a
file's bytes DO differ, the payload is decoded — ssz_snappy through the
spec types, yaml through safe_load — and the first divergent fields are
named (`state.balances[3]: 100 != 101`), because "vector differs" without
a field path is undebuggable at scenario scale.

This is the inbound half of bidirectional conformance: vectors emitted
from the TPU lane are diffed against reference-shaped (oracle-emitted)
vectors, while conformance.runner.replay_case independently replays both.

jax-free by charter: spec modules load through the compiler's host path.
"""
from __future__ import annotations

from pathlib import Path

import yaml

from ..native import snappy

MAX_DIFFS_PER_FILE = 12


def _tests_root(tree) -> Path:
    root = Path(tree)
    return root / "tests" if (root / "tests").is_dir() else root


def _files(root: Path) -> dict:
    return {str(p.relative_to(root)): p
            for p in sorted(root.rglob("*"))
            if p.is_file() and p.name != "testgen_error_log.txt"}


def _spec_for(rel: str, case_dir: Path):
    """Resolve the case's spec module from its tree position (+ config.yaml
    overrides, mirroring conformance.runner.replay_case)."""
    from ..compiler import get_spec, get_spec_with_overrides

    parts = Path(rel).parts
    preset, fork = parts[0], parts[1]
    cfg_path = case_dir / "config.yaml"
    if cfg_path.exists():
        with open(cfg_path) as f:
            overrides = yaml.safe_load(f) or {}
        converted = {
            k: bytes.fromhex(v[2:])
            if isinstance(v, str) and v.startswith("0x") else v
            for k, v in overrides.items()
        }
        return get_spec_with_overrides(fork, preset, converted)
    return get_spec(fork, preset)


def _ssz_type(spec, stem: str):
    if stem in ("anchor_state", "pre", "post", "state", "genesis"):
        return spec.BeaconState
    if stem == "anchor_block":
        return spec.BeaconBlock
    if stem.startswith(("block_", "blocks_")):
        return spec.SignedBeaconBlock
    if stem.startswith("attestation"):
        return spec.Attestation
    if stem.startswith("pow_block") and hasattr(spec, "PowBlock"):
        return spec.PowBlock
    return None


def _deep_diff(a, b, path: str, out: list) -> None:
    if len(out) >= MAX_DIFFS_PER_FILE:
        return
    if isinstance(a, dict) and isinstance(b, dict):
        for key in sorted(set(a) | set(b)):
            if key not in a:
                out.append(f"{path}.{key}: missing on left")
            elif key not in b:
                out.append(f"{path}.{key}: missing on right")
            else:
                _deep_diff(a[key], b[key], f"{path}.{key}", out)
            if len(out) >= MAX_DIFFS_PER_FILE:
                return
    elif isinstance(a, list) and isinstance(b, list):
        if len(a) != len(b):
            out.append(f"{path}: length {len(a)} != {len(b)}")
        for i, (x, y) in enumerate(zip(a, b)):
            _deep_diff(x, y, f"{path}[{i}]", out)
            if len(out) >= MAX_DIFFS_PER_FILE:
                return
    elif a != b:
        out.append(f"{path}: {a!r} != {b!r}")


def _field_diff(rel: str, path_a: Path, path_b: Path,
                raw_a: bytes, raw_b: bytes) -> list:
    name = Path(rel).name
    out: list = []
    if name.endswith(".yaml"):
        _deep_diff(yaml.safe_load(raw_a.decode()),
                   yaml.safe_load(raw_b.decode()),
                   Path(name).stem, out)
    elif name.endswith(".ssz_snappy"):
        from ..debug.encode import encode

        stem = name.removesuffix(".ssz_snappy")
        spec = _spec_for(rel, path_a.parent)
        typ = _ssz_type(spec, stem)
        if typ is None:
            return [f"binary mismatch ({len(raw_a)} vs {len(raw_b)} bytes, "
                    f"no decoder for {stem!r})"]
        try:
            val_a = encode(typ.decode_bytes(snappy.decompress(raw_a)))
            val_b = encode(typ.decode_bytes(snappy.decompress(raw_b)))
        except Exception as exc:
            return [f"binary mismatch (decode failed: "
                    f"{type(exc).__name__}: {exc})"]
        _deep_diff(val_a, val_b, stem, out)
        if not out:
            out.append("ssz bodies decode equal but serialized bytes "
                       "differ (framing/compression drift)")
    else:
        out.append(f"binary mismatch ({len(raw_a)} vs {len(raw_b)} bytes)")
    return out


def _checkpoint_heads(cp: dict) -> dict:
    """Every head claim a checkpoint carries: the reference `get_head`
    root plus, when the lane ran with head_check, the device lane's."""
    heads = {}
    checks = cp.get("checks") or {}
    head = checks.get("head") or {}
    if "root" in head:
        heads["reference"] = head["root"]
    if "device_head" in cp:
        heads["device"] = cp["device_head"]
    return heads


def diff_checkpoints(a: list, b: list) -> dict:
    """Structured diff of two lane checkpoint transcripts.

    Returns {"count": (len_a, len_b), "mismatches": [...], and — the
    fork-choice lane's incident payload — "head_divergence": [...]}.
    `mismatches` names the first divergent fields per checkpoint index
    (the `_deep_diff` walk). `head_divergence` isolates disagreeing head
    roots: across the two transcripts at the same index, and *within* a
    single checkpoint when its `device_head` contradicts its own
    reference head — so a wrong device head is attributed even when both
    lanes mirror the same wrong value."""
    mismatches: list = []
    head_divergence: list = []
    for i in range(max(len(a), len(b))):
        ca = a[i] if i < len(a) else None
        cb = b[i] if i < len(b) else None
        if ca is None or cb is None:
            mismatches.append(
                {"index": i, "fields":
                 [f"checkpoint[{i}]: missing on "
                  f"{'left' if ca is None else 'right'}"]})
            continue
        heads = {}
        for side, cp in (("a", ca), ("b", cb)):
            for kind, root in _checkpoint_heads(cp).items():
                heads[f"{side}.{kind}"] = root
        if len(set(heads.values())) > 1:
            head_divergence.append({
                "index": i,
                "epoch": ca.get("epoch", cb.get("epoch")),
                "heads": heads,
            })
        if ca != cb:
            fields: list = []
            _deep_diff(ca, cb, f"checkpoint[{i}]", fields)
            mismatches.append({"index": i, "fields": fields})
    return {"count": (len(a), len(b)), "mismatches": mismatches,
            "head_divergence": head_divergence}


def diff_vector_trees(tree_a, tree_b) -> list:
    """Field-by-field diff of two vector trees; [] means identical."""
    root_a, root_b = _tests_root(tree_a), _tests_root(tree_b)
    files_a, files_b = _files(root_a), _files(root_b)
    diffs: list = []
    for rel in sorted(set(files_a) | set(files_b)):
        if rel not in files_a:
            diffs.append(f"{rel}: only in {root_b}")
            continue
        if rel not in files_b:
            diffs.append(f"{rel}: only in {root_a}")
            continue
        raw_a = files_a[rel].read_bytes()
        raw_b = files_b[rel].read_bytes()
        if raw_a == raw_b:
            continue
        for detail in _field_diff(rel, files_a[rel], files_b[rel],
                                  raw_a, raw_b):
            diffs.append(f"{rel}: {detail}")
    return diffs
