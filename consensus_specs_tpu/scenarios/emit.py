"""L7 closure, outbound: scenario segments → reference-shaped vector trees.

`scenario_test_cases` turns one materialized history into gen/ TestCases
for two runner/handler pairs, and `emit_history` writes them through the
standard `gen_runner.run_generator` machinery (same snappy/yaml dumpers,
same `<preset>/<fork>/<runner>/<handler>/<suite>/<case>` layout, same
INCOMPLETE sentinel), so scenario vectors are indistinguishable from any
other generator's output and replay through conformance.runner unchanged:

  fork_choice/scenario   anchor_state + anchor_block + every block/
                         attestation object + steps.yaml whose `checks`
                         payloads come from the SUPPLIED lane's replay
                         (pass the engine lane's LaneResult and the
                         vectors assert what the TPU implementation
                         computed — the outbound half of bidirectional
                         conformance).
  sanity/blocks          pre / blocks_i (the canonical chain) / post per
                         segment — the same history cross-checked through
                         the state-transition runner instead of the store.

Determinism contract (satellite: double-render test): emitting the same
history twice yields byte-identical trees — no wall clock, no unseeded
iteration order anywhere in the part lists.
"""
from __future__ import annotations

from ..gen import TestCase, TestProvider, run_generator
from .history import ScenarioHistory
from .lanes import LaneResult

SUITE = "pyspec_tests"


def _segment_checks(history: ScenarioHistory, lane_result: LaneResult) -> list:
    """Per-segment slices of the lane's checkpoint `checks` payloads, in
    step order (each segment consumes as many as it has checkpoint steps)."""
    per_segment = []
    cursor = 0
    for seg in history.segments:
        n = sum(1 for step in seg.steps if "checkpoint" in step)
        chunk = lane_result.checkpoints[cursor:cursor + n]
        assert len(chunk) == n, (
            f"lane '{lane_result.name}' recorded {len(lane_result.checkpoints)} "
            f"checkpoints; segment needs {n} more at offset {cursor}")
        per_segment.append([cp["checks"] for cp in chunk])
        cursor += n
    return per_segment


def _fork_choice_case_fn(history, seg, checks):
    def case_fn():
        steps = []
        it = iter(checks)
        for step in seg.steps:
            if "tick" in step or "block" in step or "attestation" in step:
                steps.append(dict(step))
            elif "checkpoint" in step:
                steps.append({"checks": next(it)})
            # probe steps are a lane-internal sampling aid, not part of the
            # reference step vocabulary — dropped on emission
        parts = [
            ("anchor_state", "ssz", seg.anchor_state),
            ("anchor_block", "ssz", seg.anchor_block),
        ]
        for name, obj in seg.objects.items():
            parts.append((name, "ssz", obj))
        parts.append(("config", "data", dict(seg.config_overrides)))
        parts.append(("steps", "data", steps))
        parts.append(("meta", "meta", {
            "bls_setting": 2,  # stub-signed traffic: must replay unverified
            "scenario_seed": history.script.seed,
        }))
        return parts

    return case_fn


def _sanity_blocks_case_fn(history, seg):
    def case_fn():
        from ..compiler import get_spec_with_overrides
        from ..crypto import bls

        spec = get_spec_with_overrides(
            seg.fork, history.script.preset, seg.config_overrides)
        anchor_slot = int(seg.anchor_state.slot)
        blocks = [seg.objects[name] for name in seg.canonical
                  if int(seg.objects[name].message.slot) > anchor_slot]
        post = seg.anchor_state.copy()
        prev = bls.bls_active
        bls.bls_active = False
        try:
            for signed in blocks:
                spec.state_transition(post, signed, validate_result=True)
        finally:
            bls.bls_active = prev
        parts = [("pre", "ssz", seg.anchor_state)]
        for i, signed in enumerate(blocks):
            parts.append((f"blocks_{i}", "ssz", signed))
        parts.append(("post", "ssz", post))
        parts.append(("config", "data", dict(seg.config_overrides)))
        parts.append(("meta", "meta", {
            "bls_setting": 2,
            "blocks_count": len(blocks),
            "scenario_seed": history.script.seed,
        }))
        return parts

    return case_fn


def scenario_test_cases(history: ScenarioHistory,
                        lane_result: LaneResult | None = None) -> list:
    """gen/ TestCases for one history: fork_choice/scenario + sanity/blocks
    per segment. `lane_result` supplies the checks payloads (default: a
    fresh oracle replay; pass the engine lane's result to emit what the
    TPU implementation computed)."""
    if lane_result is None:
        from .lanes import oracle_lane

        lane_result = oracle_lane(history)
    checks = _segment_checks(history, lane_result)
    script = history.script
    cases = []
    for i, seg in enumerate(history.segments):
        case_name = f"{script.name}_seg{i}"
        cases.append(TestCase(
            fork_name=seg.fork, preset_name=script.preset,
            runner_name="fork_choice", handler_name="scenario",
            suite_name=SUITE, case_name=case_name,
            case_fn=_fork_choice_case_fn(history, seg, checks[i])))
        cases.append(TestCase(
            fork_name=seg.fork, preset_name=script.preset,
            runner_name="sanity", handler_name="blocks",
            suite_name=SUITE, case_name=case_name,
            case_fn=_sanity_blocks_case_fn(history, seg)))
    return cases


def emit_history(history: ScenarioHistory, output_dir, *,
                 lane_result: LaneResult | None = None,
                 force: bool = True, smoke: int | None = None) -> list:
    """Write the history's vector cases under `<output_dir>/tests/...` via
    the standard generator runtime. Returns the emitted case paths.
    `smoke=N` stops the run after N cases (the generator health probe)."""
    cases = scenario_test_cases(history, lane_result=lane_result)
    if smoke is not None:
        cases = cases[:smoke]
    providers = [TestProvider(make_cases=lambda: list(cases))]
    argv = ["-o", str(output_dir)] + (["-f"] if force else [])
    if smoke is not None:
        argv += ["--smoke", str(smoke)]
    rc = run_generator("scenarios", providers, argv)
    if rc != 0:
        raise RuntimeError(
            f"scenario vector emission failed (rc {rc}); see "
            f"{output_dir}/testgen_error_log.txt")
    return [case.path for case in cases]
