"""Three replay lanes over one materialized history, asserted bit-identical.

A ScenarioHistory is a pure data script (ticks, blocks, attestations,
checkpoints, probes). Each lane replays it through a fresh fork-choice
store per segment and records the SAME observables at every checkpoint —
`testlib.fork_choice.checks_snapshot` (head, justified/finalized,
proposer boost) plus the head state's hash_tree_root — so convergence is
a plain dict comparison (`assert_converged`):

  oracle    pure-Python spec execution, no device, no faults — the truth.
  engine    epoch transitions routed through the resident device bridge
            (`bridge.apply_epoch_via_engine`) with the PR-5 chaos seams
            live (robustness/schedules.long_horizon_plan "engine"): every
            injected dispatch raise / torn aux readout must be absorbed by
            retry → breaker → degrade without moving a single bit.
  firehose  gossip attestations are admitted through a real
            AttestationFirehose (ingest → dedup → sched flush) before the
            store sees them, interleaved with adversarial traffic
            (malformed payloads, duplicate offers) that must quarantine
            without perturbing a verdict.

Reorg accounting: `probe` and `checkpoint` steps sample get_head; a new
head that does not descend from the previous sample is a reorg of depth
(old head slot − common ancestor slot). The storm builder brackets each
release with probes, so every lane measures the same flips.

jax-free at module level by charter (analysis/layering.py): the engine
bridge and scheduler are deferred imports inside the lanes that use them.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from random import Random

from ..obs import flight as _flight
from ..obs import metrics as _obs_metrics
from ..testlib.fork_choice import checks_snapshot
from .history import ScenarioHistory


@dataclass
class LaneResult:
    """One lane's replay transcript — everything assert_converged compares."""

    name: str
    checkpoints: list           # [{"epoch", "fork", "head_state_root", "checks"}]
    reorgs: int = 0
    max_reorg_depth: int = 0
    slots: int = 0
    elapsed_s: float = 0.0
    extra: dict = field(default_factory=dict)


def _reorg_depth(store, old_head, new_head) -> int:
    """Depth of the head flip old→new: 0 when new descends from old, else
    old head slot − common ancestor slot (parent walks over store.blocks)."""
    if old_head == new_head:
        return 0
    ancestors = set()
    root = new_head
    while root in store.blocks:
        ancestors.add(root)
        parent = store.blocks[root].parent_root
        if parent == root:
            break
        root = parent
    if old_head in ancestors:
        return 0
    root = old_head
    while root in store.blocks and root not in ancestors:
        parent = store.blocks[root].parent_root
        if parent == root:
            break
        root = parent
    if root in ancestors:
        return int(store.blocks[old_head].slot) - int(store.blocks[root].slot)
    # disjoint trees (cannot happen for one store; belt for partial stores)
    return int(store.blocks[old_head].slot) + 1


@contextmanager
def _null_router():
    yield


def device_head_checker(spec, seg, *, registry=None):
    """Per-segment device head checker: a ForkChoiceService over its own
    sched "forkchoice" lane (breaker/retry isolated from the replay's
    other scheduling), mirror synced incrementally per checkpoint. The
    returned callable maps the segment's live store to the device head
    root — the thing replay_history asserts equals `spec.get_head`."""
    from ..forkchoice import ForkChoiceService
    from ..sched import ForkChoiceWorkClass, Scheduler

    service = ForkChoiceService(
        scheduler=Scheduler(classes=[ForkChoiceWorkClass()],
                            registry=registry),
        registry=registry)
    attached = []

    def check(store) -> bytes:
        if not attached:
            service.attach(spec, store)
            attached.append(True)
        return service.head()

    return check


def replay_history(history: ScenarioHistory, *, name: str = "oracle",
                   epoch_router=None, attestation_gate=None,
                   registry=None, head_check=False) -> LaneResult:
    """Replay every segment's steps through a fresh store; one LaneResult.

    `epoch_router(spec)` — optional context-manager factory entered per
    segment (the engine lane patches spec.process_epoch inside it).
    `attestation_gate(spec, seg)` — optional per-segment factory returning
    `gate(name, attestation)`, called before each gossip on_attestation
    (the firehose lane verifies through the pipeline here); it must raise
    to veto, and its verdict must agree with the oracle by construction.
    `head_check` — truthy enables the per-checkpoint device fork-choice
    assertion: every checkpoint also computes the head through the
    forkchoice/ lane, records it as the checkpoint's `device_head`, and
    a mismatch against the reference `get_head` dumps a flight-recorder
    black box and fails the lane. Pass a `factory(spec, seg) ->
    callable(store) -> bytes` to customize (True = device_head_checker).
    Lanes compared by assert_converged must agree on this setting —
    `device_head` participates in the bit-identical checkpoint dict.
    """
    from ..compiler import get_spec_with_overrides
    from ..crypto import bls

    reg = registry if registry is not None else _obs_metrics.REGISTRY
    script = history.script
    result = LaneResult(name=name, checkpoints=[])
    prev_bls = bls.bls_active
    bls.bls_active = False  # scenario traffic is stub-signed (history.py)
    t0 = time.monotonic()
    try:
        for seg in history.segments:
            spec = get_spec_with_overrides(
                seg.fork, script.preset, seg.config_overrides)
            store = spec.get_forkchoice_store(
                seg.anchor_state.copy(), seg.anchor_block)
            gate = (attestation_gate(spec, seg)
                    if attestation_gate is not None else None)
            checker = None
            if head_check:
                factory = (device_head_checker if head_check is True
                           else head_check)
                checker = factory(spec, seg, registry=reg)
            router = (epoch_router(spec) if epoch_router is not None
                      else _null_router())
            with router:
                sampled_head = None
                for step in seg.steps:
                    if "tick" in step:
                        spec.on_tick(store, int(step["tick"]))
                        result.slots += 1
                    elif "block" in step:
                        signed = seg.objects[step["block"]]
                        spec.on_block(store, signed)
                        # the reference's add_block contract: in-block
                        # attestations feed the fork choice too, best-effort
                        # (a fresh post-fork store rejects anchor-older
                        # targets the state transition accepts)
                        for att in signed.message.body.attestations:
                            try:
                                spec.on_attestation(store, att,
                                                    is_from_block=True)
                            except AssertionError:
                                pass
                        reg.counter("scenario_blocks_total", lane=name).inc()
                    elif "attestation" in step:
                        att = seg.objects[step["attestation"]]
                        if gate is not None:
                            gate(step["attestation"], att)
                        spec.on_attestation(store, att)
                        reg.counter(
                            "scenario_attestations_total", lane=name).inc()
                    else:  # probe / checkpoint: head samples
                        head = spec.get_head(store)
                        if sampled_head is not None:
                            depth = _reorg_depth(store, sampled_head, head)
                            if depth > 0:
                                result.reorgs += 1
                                result.max_reorg_depth = max(
                                    result.max_reorg_depth, depth)
                                reg.counter(
                                    "scenario_reorgs_total", lane=name).inc()
                                reg.gauge("scenario_reorg_depth_max",
                                          lane=name).set(
                                    result.max_reorg_depth)
                        sampled_head = head
                        if "checkpoint" in step:
                            head, checks = checks_snapshot(spec, store)
                            state_root = spec.hash_tree_root(
                                store.block_states[head])
                            cp = {
                                "epoch": int(step["checkpoint"]),
                                "fork": seg.fork,
                                "head_state_root":
                                    "0x" + bytes(state_root).hex(),
                                "checks": checks,
                            }
                            if checker is not None:
                                device = "0x" + checker(store).hex()
                                cp["device_head"] = device
                                if device != checks["head"]["root"]:
                                    _flight.record(
                                        "head_divergence", lane=name,
                                        epoch=int(step["checkpoint"]),
                                        reference=checks["head"]["root"],
                                        device=device)
                                    _flight.dump("head_divergence",
                                                 meta={"lane": name})
                                    raise AssertionError(
                                        f"{name}: device head {device} != "
                                        f"reference {checks['head']['root']}"
                                        f" at epoch {step['checkpoint']}")
                            result.checkpoints.append(cp)
                            reg.counter("scenario_checkpoints_total",
                                        lane=name).inc()
            if gate is not None and hasattr(gate, "finish"):
                gate.finish(result)
        result.elapsed_s = max(time.monotonic() - t0, 1e-9)
        reg.histogram("scenario_slots_per_s", lane=name).observe(
            result.slots / result.elapsed_s)
        return result
    finally:
        bls.bls_active = prev_bls


# -- lane: oracle -----------------------------------------------------------

def oracle_lane(history: ScenarioHistory, *, registry=None,
                head_check=False) -> LaneResult:
    """Pure-Python spec replay: the ground truth the others must match."""
    return replay_history(history, name="oracle", registry=registry,
                          head_check=head_check)


# -- lane: engine (chaos on) -------------------------------------------------

@contextmanager
def _engine_epoch_router(spec):
    """Route epoch transitions through the resident device bridge.

    The bridge's degrade path calls `spec.process_epoch` itself (bridge.py
    pre-commit failure handling), so the patch is removed AROUND each
    bridge call — a degraded epoch runs the original, never recurses.
    Phase0 states (no participation flags) stay on the pure path: the
    engine's column layout is altair+.
    """
    from ..engine import bridge

    original = spec.process_epoch

    def routed(state):
        if not hasattr(state, "previous_epoch_participation"):
            return original(state)
        spec.process_epoch = original
        try:
            bridge.apply_epoch_via_engine(spec, state)
        finally:
            spec.process_epoch = routed

    spec.process_epoch = routed
    try:
        yield
    finally:
        spec.process_epoch = original


def engine_lane(history: ScenarioHistory, *, registry=None,
                fault_seed=None, fault_profile: str = "engine",
                head_check=False) -> LaneResult:
    """Resident-engine replay with the long-horizon chaos drizzle live."""
    from ..engine import bridge
    from ..robustness.schedules import long_horizon_plan

    seed = history.script.seed if fault_seed is None else fault_seed
    plan = long_horizon_plan(seed, profile=fault_profile)
    bridge.reset_device_breaker()
    try:
        with plan.active():
            result = replay_history(
                history, name="engine", epoch_router=_engine_epoch_router,
                registry=registry, head_check=head_check)
    finally:
        bridge.reset_device_breaker()
    result.extra["faults_fired"] = {
        site: plan.fires(site) for site in sorted(plan.fired_sites())}
    return result


# -- lane: firehose -----------------------------------------------------------

class _SwitchableBls:
    """BlsWorkClass variant whose device path routes through crypto.bls's
    switchable frontend — stub-signed scenario traffic then verifies
    exactly as the oracle's on_attestation does (bls off → True), while a
    real-signature run still checks for real. Collapse stays enabled, but
    scenario committees sign distinct roots, so requests queue 1:1."""

    def __new__(cls):
        from ..sched import BlsWorkClass

        class _Impl(BlsWorkClass):
            def execute(self, requests):
                return self.execute_degraded(requests)

            def execute_degraded(self, requests):
                import numpy as np

                from ..crypto import bls
                dispatch = {
                    "verify": bls.Verify,
                    "fast_aggregate": bls.FastAggregateVerify,
                    "aggregate_verify": bls.AggregateVerify,
                }
                return np.asarray(
                    [bool(dispatch[r.kind](*r.payload)) for r in requests],
                    dtype=bool)

        return _Impl(collapse_same_message=True)


class _FirehoseGate:
    """Admission gate: every gossip attestation passes through a real
    firehose (classify → dedup → sched flush → verdict) before the store's
    on_attestation. Classification is a pure lookup against the history's
    att_keys table (the builder recorded pubkeys/signing-root per vote).
    Adversarial extras — malformed payloads and duplicate offers, drawn
    from a lane-local seeded stream — ride along in the offered traffic
    only; they must quarantine/dedup without touching any verdict."""

    def __init__(self, spec, seg, *, registry, seed, adversarial=True):
        from ..firehose.ingest import AttestationItem, ClassifyError
        from ..firehose.pipeline import AttestationFirehose, FirehoseConfig
        from ..parallel.gossip_driver import message_id
        from ..sched import Scheduler
        from ..ssz import serialize

        self._rng = Random(f"scenario:{seed}:firehose")
        self._adversarial = adversarial
        self._message_id = message_id
        self.offered = self.malformed = self.duplicates = 0

        self._raw: dict = {}
        table: dict = {}
        for att_name, keys in seg.att_keys.items():
            att = seg.objects[att_name]
            raw = bytes(serialize(att))
            data = att.data
            self._raw[att_name] = raw
            table[raw] = AttestationItem(
                msg_id=message_id(raw),
                key=(int(data.slot), int(data.index),
                     bytes(data.beacon_block_root)),
                pubkeys=tuple(keys["pubkeys"]),
                message=keys["message"],
                signature=keys["signature"],
                ssz=raw)

        def classify(ssz_bytes: bytes):
            item = table.get(bytes(ssz_bytes))
            if item is None:
                raise ClassifyError("payload is not a scenario attestation")
            return item

        # batch_attestations=1: every offer seals + flushes inline
        # (threaded=False), so verdicts resolve deterministically in step
        # order — the scenario contract replay depends on.
        self._hose = AttestationFirehose(
            classify,
            config=FirehoseConfig(batch_attestations=1, max_pending=64,
                                  flush_deadline_s=0.0),
            scheduler=Scheduler(classes=[_SwitchableBls()],
                                max_depth=1 << 30, registry=registry),
            registry=registry, threaded=False)

    def __call__(self, att_name, attestation):
        raw = self._raw[att_name]
        if self._adversarial and self._rng.random() < 0.05:
            # malformed gossip frame: must quarantine, not verify
            junk = self._rng.randbytes(self._rng.randrange(1, 64))
            assert not self._hose.offer(junk)
            self.malformed += 1
        if self._adversarial and self._rng.random() < 0.05:
            # duplicate offer ahead of the real one: dedup admits only one
            self._hose.offer(raw)
            self.duplicates += 1
        self._hose.offer(raw)
        self.offered += 1
        self._hose.drain(timeout_s=30.0)
        verdict = self._hose.results().get(self._message_id(raw))
        assert verdict is True, (
            f"firehose rejected scenario attestation {att_name}")

    def finish(self, result: LaneResult) -> None:
        self._hose.drain(timeout_s=30.0)
        stats = result.extra.setdefault(
            "firehose", {"offered": 0, "malformed": 0, "duplicates": 0})
        stats["offered"] += self.offered
        stats["malformed"] += self.malformed
        stats["duplicates"] += self.duplicates


def firehose_lane(history: ScenarioHistory, *, registry=None,
                  adversarial: bool = True, fault_seed=None,
                  chaos: bool = False, head_check=False) -> LaneResult:
    """Streaming replay: gossip votes verified through the firehose/sched
    path before admission. `chaos=True` additionally drizzles transient
    faults over the ingest/flush seams (retried inside the pipeline)."""
    from ..robustness.schedules import long_horizon_plan

    reg = registry if registry is not None else _obs_metrics.REGISTRY
    script = history.script

    def gate_factory(spec, seg):
        return _FirehoseGate(spec, seg, registry=reg, seed=script.seed,
                             adversarial=adversarial)

    if chaos:
        seed = script.seed if fault_seed is None else fault_seed
        with long_horizon_plan(seed, profile="firehose").active():
            return replay_history(history, name="firehose",
                                  attestation_gate=gate_factory,
                                  registry=reg, head_check=head_check)
    return replay_history(history, name="firehose",
                          attestation_gate=gate_factory, registry=reg,
                          head_check=head_check)


# -- convergence --------------------------------------------------------------

def assert_converged(results: list) -> None:
    """Every lane must agree bit-identically on every checkpoint — state
    roots, heads, justified/finalized checkpoints, boost — and on the
    reorg transcript (count + max depth). A divergence is an incident:
    the flight recorder dumps its black box before the assertion
    propagates, so the post-mortem has the event history without
    re-running the scenario."""
    try:
        _check_converged(results)
    except AssertionError as exc:
        from .diff import diff_checkpoints

        lanes = [getattr(r, "name", "?") for r in results]
        base = results[0].checkpoints if results else []
        head_div = []
        for other in results[1:]:
            d = diff_checkpoints(base, other.checkpoints)
            head_div.extend(d["head_divergence"])
        _flight.record("divergence", lanes=lanes, error=str(exc)[:500],
                       head_divergence=head_div[:16])
        _flight.dump("scenario_divergence",
                     meta={"lanes": lanes, "head_divergence": head_div[:16]})
        raise


def _check_converged(results: list) -> None:
    assert results, "no lanes to compare"
    base = results[0]
    for other in results[1:]:
        assert len(other.checkpoints) == len(base.checkpoints), (
            f"{other.name}: {len(other.checkpoints)} checkpoints vs "
            f"{base.name}: {len(base.checkpoints)}")
        for i, (a, b) in enumerate(zip(base.checkpoints, other.checkpoints)):
            assert a == b, (
                f"checkpoint {i} diverged: {base.name}={a!r} "
                f"{other.name}={b!r}")
        assert other.reorgs == base.reorgs, (
            f"reorg count diverged: {base.name}={base.reorgs} "
            f"{other.name}={other.reorgs}")
        assert other.max_reorg_depth == base.max_reorg_depth, (
            f"reorg depth diverged: {base.name}={base.max_reorg_depth} "
            f"{other.name}={other.max_reorg_depth}")
