"""Materialize a ScenarioScript into spec-valid SSZ objects + a step script.

The history is built ONCE per (seed, shape) and replayed by every lane
(lanes.py) and by the vector emitter (emit.py), so bit-identity questions
reduce to "did the lanes process the same steps the same way" — never
"did two builders roll the same dice".

Mechanics (all under LMD-GHOST's one-sticky-vote-per-validator-per-epoch
rule — on_attestation only supersedes an earlier vote from a PRIOR epoch):

* calm epochs: one block per slot carrying full-committee attestations for
  the previous slot (justification/finality advances), plus the same votes
  gossiped as standalone attestation steps (fork-choice weight).
* droughts: every `skip_every`-th slot is tick-only; gossip votes continue,
  re-attesting the stale head across the gap.
* reorg storms: the public branch runs `public` blocks and collects that
  many slots of sticky votes; a private branch of `private > 2*public`
  blocks (equivocating with the public proposers on the shared slots) is
  released late together with the still-unspent committee votes of the
  silent slots — the private branch strictly outweighs the public one and
  the head flips. `probe` steps bracket the release so lanes measure the
  reorg depth identically.
* equivocation ladders: a proposer signs two sibling blocks in one slot
  (both enter the store); the pair's headers become a proposer slashing
  included two slots later.
* slashing waves: an attester double-vote slashes a whole committee via a
  block-included attester slashing.
* fork boundary: the canonical chain upgrades (upgrade_to_<post>) at the
  scripted epoch; the first post-fork block anchors a FRESH fork-choice
  store (its state_root seals the anchor contract get_forkchoice_store
  asserts), matching the reference's per-fork store scoping.

Deferred spec imports only — this module stays importable from the
jax-free layer (analysis/layering.py pins `scenarios/`).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random

from ..obs import metrics as _obs_metrics
from .script import (
    CALM,
    DROUGHT,
    EQUIVOCATION,
    REORG_STORM,
    SLASHING_WAVE,
    ScenarioScript,
    build_script,
)


@dataclass
class Segment:
    """One fork's worth of scenario: a store anchor plus replayable steps.

    steps entries (replayed in order by every lane):
      {"tick": <time>}            — spec.on_tick
      {"block": <name>}           — spec.on_block + in-block attestation routing
      {"attestation": <name>}     — spec.on_attestation (gossip path)
      {"checkpoint": <epoch>}     — lanes snapshot checks + head state root
      {"probe": <label>}          — lanes sample get_head (reorg detection)
    """

    fork: str
    config_overrides: dict
    anchor_state: object
    anchor_block: object
    steps: list = field(default_factory=list)
    objects: dict = field(default_factory=dict)
    # name -> {"pubkeys": [bytes], "message": bytes, "signature": bytes}
    # (the firehose lane's classification table — scenario gossip carries
    # stub signatures, so classification is a pure lookup, not re-derivation)
    att_keys: dict = field(default_factory=dict)
    canonical: list = field(default_factory=list)  # block names, chain order
    start_slot: int = 0
    end_slot: int = 0


@dataclass
class ScenarioHistory:
    script: ScenarioScript
    segments: list
    stats: dict


def build_history(script_or_seed, **script_kwargs) -> ScenarioHistory:
    """Materialize a script (or build one from a seed) into a history."""
    from ..compiler import get_spec_with_overrides
    from ..crypto import bls
    from ..testlib.context import _cached_genesis, default_balances

    script = (script_or_seed if isinstance(script_or_seed, ScenarioScript)
              else build_script(script_or_seed, **script_kwargs))
    pre_fork, post_fork = script.forks
    overrides = {f"{post_fork.upper()}_FORK_EPOCH": script.fork_epoch}
    # memoized spec modules: the lanes replay with the SAME module objects
    # the builder used, so SSZ class identity and per-module caches line up
    pre_spec = get_spec_with_overrides(pre_fork, script.preset, overrides)
    post_spec = get_spec_with_overrides(post_fork, script.preset, overrides)

    prev_bls = bls.bls_active
    bls.bls_active = False  # stub signatures: the scenario contract (README)
    try:
        genesis = _cached_genesis(
            pre_spec, default_balances, lambda s: s.MAX_EFFECTIVE_BALANCE)
        builder = _HistoryBuilder(script)
        fork_slot = script.fork_epoch * int(pre_spec.SLOTS_PER_EPOCH)

        # --- pre-fork segment: genesis-anchored store -------------------
        anchor_block = pre_spec.BeaconBlock(
            state_root=pre_spec.hash_tree_root(genesis))
        builder.open_segment(
            pre_spec, pre_fork, dict(overrides), genesis.copy(), anchor_block,
            start_slot=0)
        for epoch in range(script.fork_epoch):
            builder.run_epoch(epoch)
        builder.close_segment(fork_slot, checkpoint_epoch=script.fork_epoch)

        # --- fork transition: the epoch transition INTO the fork epoch runs
        # under the pre spec (reference transition-test semantics), then the
        # state upgrades and the first post-fork block anchors a fresh store.
        # That block sits at the NEXT epoch start (the fork epoch stays
        # blockless): get_forkchoice_store pins finalized = (anchor_epoch,
        # anchor_root), and on_block's finalized-ancestor walk targets the
        # anchor epoch's start slot — an off-boundary anchor would make the
        # walk recurse past the anchor into pre-fork roots the store lacks.
        state = builder.state
        pre_spec.process_slots(state, fork_slot)
        upgraded = getattr(post_spec, f"upgrade_to_{post_fork}")(state)
        anchor_slot = fork_slot + int(post_spec.SLOTS_PER_EPOCH)
        first_block = _build_signed_block(post_spec, upgraded, anchor_slot)
        builder.open_segment(
            post_spec, post_fork, dict(overrides), upgraded.copy(),
            first_block.message, start_slot=anchor_slot, state=upgraded,
            canonical_head=first_block)
        builder.queue_votes(anchor_slot)
        for epoch in range(script.fork_epoch + 1, script.epochs):
            builder.run_epoch(epoch)
        builder.close_segment(
            script.epochs * int(post_spec.SLOTS_PER_EPOCH),
            checkpoint_epoch=script.epochs)
        return ScenarioHistory(
            script=script, segments=builder.segments, stats=builder.stats)
    finally:
        bls.bls_active = prev_bls


def _build_signed_block(spec, state, slot, *, graffiti=None, atts=(),
                        proposer_slashings=(), attester_slashings=()):
    """Build + apply one block AT `slot`, mutating `state` to its post-state."""
    from ..testlib.block import build_empty_block, state_transition_and_sign_block

    assert state.slot < slot, (int(state.slot), int(slot))
    block = build_empty_block(spec, state, slot=slot)
    if graffiti is not None:
        block.body.graffiti = spec.Bytes32(graffiti.ljust(32, b"\x00"))
    for slashing in proposer_slashings:
        block.body.proposer_slashings.append(slashing)
    for slashing in attester_slashings:
        block.body.attester_slashings.append(slashing)
    for att in atts:
        block.body.attestations.append(att)
    return state_transition_and_sign_block(spec, state, block)


def _header_of(spec, signed_block):
    """SignedBeaconBlockHeader equivalent of a signed block: the header's
    hash_tree_root equals the block's (body_root substitution), so the block
    signature verifies over the header too — equivocating blocks ARE
    proposer-slashing evidence without re-signing."""
    b = signed_block.message
    return spec.SignedBeaconBlockHeader(
        message=spec.BeaconBlockHeader(
            slot=b.slot, proposer_index=b.proposer_index,
            parent_root=b.parent_root, state_root=b.state_root,
            body_root=spec.hash_tree_root(b.body)),
        signature=signed_block.signature)


class _HistoryBuilder:
    """Stateful walk over the script, one epoch routine per event kind."""

    def __init__(self, script: ScenarioScript):
        self.script = script
        self.rng = Random(f"scenario:{script.seed}:materialize")
        self.segments: list = []
        self.stats = {
            "blocks": 0, "attestations": 0, "equivocations": 0,
            "proposer_slashings": 0, "attester_slashings": 0,
            "storms": 0, "droughts": 0, "skipped_proposals": 0,
            "suppressed_votes": 0, "planned_reorg_depth_max": 0,
        }
        self.slashed: set = set()
        self.known_roots: set = set()  # block roots the segment's store holds
        self.spec = None
        self.seg: Segment | None = None
        self.state = None           # canonical post-state at the built head
        self.chain: list = []       # canonical block names, genesis->head
        self.pending_atts: list = []   # gossip votes awaiting the next tick
        self.pending_proposer_slashings: list = []
        self.pending_attester_slashings: list = []
        self._registry = _obs_metrics.REGISTRY

    # -- segment plumbing ---------------------------------------------------

    def open_segment(self, spec, fork, overrides, anchor_state, anchor_block,
                     *, start_slot, state=None, canonical_head=None) -> Segment:
        self.spec = spec
        self.seg = Segment(
            fork=fork, config_overrides=overrides, anchor_state=anchor_state,
            anchor_block=anchor_block, start_slot=start_slot,
            end_slot=start_slot)
        if state is not None:
            self.state = state
        elif self.state is None:
            self.state = anchor_state.copy()
        self.chain = []
        self.pending_atts = []
        self.known_roots = {bytes(spec.hash_tree_root(anchor_block))}
        if canonical_head is not None:
            # the anchor block doubles as the first canonical chain entry
            name = self._register_block(canonical_head)
            self.chain.append(name)
        self.segments.append(self.seg)
        return self.seg

    def close_segment(self, final_slot, *, checkpoint_epoch):
        self.tick(final_slot)
        self.flush_votes()
        self.seg.steps.append({"checkpoint": int(checkpoint_epoch)})
        self.seg.end_slot = final_slot
        self.seg.canonical = list(self.chain)

    def tick(self, slot):
        spec, seg = self.spec, self.seg
        time = (int(seg.anchor_state.genesis_time)
                + int(slot) * int(spec.config.SECONDS_PER_SLOT))
        seg.steps.append({"tick": time})
        self._registry.counter("scenario_build_slots_total").inc()

    def flush_votes(self):
        for name in self.pending_atts:
            self.seg.steps.append({"attestation": name})
        self.pending_atts = []

    def start_slot_steps(self, slot, epoch):
        """tick → flush queued gossip votes → epoch-boundary checkpoint."""
        self.tick(slot)
        self.flush_votes()
        if slot % int(self.spec.SLOTS_PER_EPOCH) == 0:
            self.seg.steps.append({"checkpoint": int(epoch)})

    # -- object registration ------------------------------------------------

    def _register_block(self, signed_block) -> str:
        spec, seg = self.spec, self.seg
        root = spec.hash_tree_root(signed_block.message)
        name = f"block_{bytes(root).hex()[:16]}"
        seg.objects[name] = signed_block
        self.known_roots.add(bytes(root))
        return name

    def _vote_admissible(self, att) -> bool:
        """A gossip vote is only scripted when the segment's store can
        accept it: validate_on_attestation requires both the voted head and
        the target root to be in store.blocks, and a fresh post-fork store
        does not hold pre-anchor blocks — first-epoch-after-fork votes
        (target = the boundary root) are suppressed, not emitted-and-
        expected-to-fail, so emitted vectors replay clean."""
        if (bytes(att.data.beacon_block_root) in self.known_roots
                and bytes(att.data.target.root) in self.known_roots):
            return True
        self.stats["suppressed_votes"] += 1
        return False

    def _register_att(self, att, state) -> str:
        spec, seg = self.spec, self.seg
        root = spec.hash_tree_root(att)
        name = f"attestation_{bytes(root).hex()[:16]}"
        if name not in seg.objects:
            seg.objects[name] = att
            participants = sorted(spec.get_attesting_indices(
                state, att.data, att.aggregation_bits))
            domain = spec.get_domain(
                state, spec.DOMAIN_BEACON_ATTESTER, att.data.target.epoch)
            message = spec.compute_signing_root(att.data, domain)
            seg.att_keys[name] = {
                "pubkeys": [bytes(state.validators[i].pubkey)
                            for i in participants],
                "message": bytes(message),
                "signature": bytes(att.signature),
            }
        return name

    # -- building blocks ----------------------------------------------------

    def _slot_proposer_slashed(self, state, slot) -> bool:
        """Probe whether `slot`'s proposer (from `state`'s fork of history)
        is already slashed — such a slot must go blockless on that branch,
        since process_block_header rejects slashed proposers."""
        if not self.slashed:
            return False
        spec = self.spec
        probe = state.copy()
        if probe.slot < slot:
            spec.process_slots(probe, slot)
        proposer = spec.get_beacon_proposer_index(probe)
        return bool(probe.validators[proposer].slashed)

    def _proposer_blocked(self, slot) -> bool:
        if self._slot_proposer_slashed(self.state, slot):
            self.stats["skipped_proposals"] += 1
            return True
        return False

    def _take_pending_ops(self):
        spec = self.spec
        pro = self.pending_proposer_slashings[
            :int(spec.MAX_PROPOSER_SLASHINGS)]
        att = self.pending_attester_slashings[
            :int(spec.MAX_ATTESTER_SLASHINGS)]
        self.pending_proposer_slashings = self.pending_proposer_slashings[len(pro):]
        self.pending_attester_slashings = self.pending_attester_slashings[len(att):]
        return pro, att

    def canonical_block(self, slot, *, atts=(), graffiti=None) -> str | None:
        """Build + emit one canonical block step; None if the proposer is
        slashed (tick-only slot)."""
        if self._proposer_blocked(slot):
            if self.state.slot < slot:
                self.spec.process_slots(self.state, slot)
            return None
        pro, att_sl = self._take_pending_ops()
        signed = _build_signed_block(
            self.spec, self.state, slot, graffiti=graffiti, atts=atts,
            proposer_slashings=pro, attester_slashings=att_sl)
        name = self._register_block(signed)
        self.seg.steps.append({"block": name})
        self.chain.append(name)
        self.stats["blocks"] += 1
        self.stats["proposer_slashings"] += len(pro)
        self.stats["attester_slashings"] += len(att_sl)
        self._registry.counter("scenario_build_blocks_total").inc()
        return name

    def queue_votes(self, slot, *, state=None):
        """Full-committee gossip votes for `slot`, emitted at the next tick
        (on_attestation requires attestation.data.slot + 1 <= wall slot)."""
        from ..testlib.attestations import get_valid_attestations_at_slot

        spec = self.spec
        state = state if state is not None else self.state
        assert state.slot == slot, (state.slot, slot)
        for att in get_valid_attestations_at_slot(spec, state, slot):
            if not self._vote_admissible(att):
                continue
            self.pending_atts.append(self._register_att(att, state))
            self.stats["attestations"] += 1
            self._registry.counter("scenario_build_attestations_total").inc()

    def prev_slot_block_atts(self, slot):
        """Attestations for slot-1 to include IN the block at `slot` (the
        justification driver: in-state participation only advances through
        block-included attestations)."""
        from ..testlib.attestations import get_valid_attestations_at_slot

        return get_valid_attestations_at_slot(self.spec, self.state, slot - 1)

    # -- epoch routines -----------------------------------------------------

    def run_epoch(self, epoch: int):
        plan = self.script.plan_for(epoch)
        spec = self.spec
        per_epoch = int(spec.SLOTS_PER_EPOCH)
        first = epoch * per_epoch
        # the genesis slot carries no block, and a segment-opening slot is
        # already consumed by the anchor block
        slots = [s for s in range(first, first + per_epoch)
                 if s > self.seg.start_slot]
        if not slots:
            return
        routine = {
            CALM: self._calm_epoch,
            DROUGHT: self._drought_epoch,
            REORG_STORM: self._storm_epoch,
            EQUIVOCATION: self._equivocation_epoch,
            SLASHING_WAVE: self._slashing_wave_epoch,
        }[plan.kind]
        routine(epoch, slots, plan.params)
        self.seg.end_slot = slots[-1]
        self.seg.canonical = list(self.chain)

    def _calm_epoch(self, epoch, slots, params, *, graffiti=None):
        for slot in slots:
            self.start_slot_steps(slot, epoch)
            atts = self.prev_slot_block_atts(slot)
            self.canonical_block(slot, atts=atts, graffiti=graffiti)
            self.queue_votes(slot)

    def _drought_epoch(self, epoch, slots, params):
        self.stats["droughts"] += 1
        skip_every = int(params.get("skip_every", 2))
        for i, slot in enumerate(slots):
            self.start_slot_steps(slot, epoch)
            if i % skip_every == 0:
                # tick-only slot: advance the canonical state so gossip
                # votes for the empty slot still resolve their committee
                if self.state.slot < slot:
                    self.spec.process_slots(self.state, slot)
            else:
                self.canonical_block(slot)
            self.queue_votes(slot)

    def _equivocation_epoch(self, epoch, slots, params):
        spec = self.spec
        rung_offsets = (1, 4)[:int(params.get("rungs", 1))]
        rung_slots = {slots[0] + off for off in rung_offsets
                      if slots[0] + off <= slots[-1]}
        for slot in slots:
            self.start_slot_steps(slot, epoch)
            if slot in rung_slots and not self._proposer_blocked(slot):
                pre = self.state.copy()
                name = self.canonical_block(slot, graffiti=b"rung-a")
                if name is not None:
                    rival_state = pre
                    rival = _build_signed_block(
                        spec, rival_state, slot, graffiti=b"rung-b")
                    rival_name = self._register_block(rival)
                    # canonical sibling first: it takes the proposer boost
                    self.seg.steps.append({"block": rival_name})
                    self.stats["equivocations"] += 1
                    self._registry.counter(
                        "scenario_build_equivocations_total").inc()
                    proposer = int(rival.message.proposer_index)
                    if proposer not in self.slashed:
                        canonical = self.seg.objects[name]
                        self.pending_proposer_slashings.append(
                            spec.ProposerSlashing(
                                signed_header_1=_header_of(spec, canonical),
                                signed_header_2=_header_of(spec, rival)))
                        self.slashed.add(proposer)
            else:
                self.canonical_block(slot)
            self.queue_votes(slot)

    def _slashing_wave_epoch(self, epoch, slots, params):
        from ..testlib.slashings import build_attester_slashing

        spec = self.spec
        armed = bool(params.get("attester", True))
        for i, slot in enumerate(slots):
            self.start_slot_steps(slot, epoch)
            if i == 1 and armed:
                slashing = build_attester_slashing(spec, self.state)
                self.pending_attester_slashings.append(slashing)
                self.slashed |= set(
                    map(int, slashing.attestation_1.attesting_indices))
                self._registry.counter(
                    "scenario_build_slashing_waves_total").inc()
            self.canonical_block(slot)
            self.queue_votes(slot)

    def _storm_epoch(self, epoch, slots, params):
        spec, seg = self.spec, self.seg
        self.stats["storms"] += 1
        public = min(int(params.get("public", 1)), max(1, len(slots) - 3))
        private = min(int(params.get("private", public * 2 + 1)), len(slots) - 1)
        if private <= 2 * public:  # weight-flip invariant (script guards too)
            private = min(2 * public + 1, len(slots) - 1)
        fork_state = self.state.copy()
        fork_chain_len = len(self.chain)
        public_head_slot = None

        # public branch: `public` blocks, each slot's committees vote for it
        for slot in slots[:public]:
            self.start_slot_steps(slot, epoch)
            if self.canonical_block(slot, graffiti=b"public") is not None:
                public_head_slot = slot
            self.queue_votes(slot)

        # private branch, built silently off the pre-storm head: the shared
        # slots equivocate with the public proposers (same proposer, other
        # graffiti); votes are only collected for the slots whose committees
        # have NOT already voted public (sticky one-vote-per-epoch rule)
        private_blocks, private_atts = [], []
        private_state = fork_state
        for slot in slots[:private]:
            if self._slot_proposer_slashed(private_state, slot):
                # slashed proposer holes the private branch too (the next
                # built slot's process_slots absorbs the gap); its committees
                # sit out — an empty slot offers no new head to vote for
                self.stats["skipped_proposals"] += 1
                continue
            signed = _build_signed_block(
                spec, private_state, slot, graffiti=b"storm")
            private_blocks.append(self._register_block(signed))
            if slot >= slots[0] + public:
                from ..testlib.attestations import get_valid_attestations_at_slot
                for att in get_valid_attestations_at_slot(
                        spec, private_state, slot):
                    if not self._vote_admissible(att):
                        continue
                    private_atts.append(self._register_att(att, private_state))
                    self.stats["attestations"] += 1
            if slot == slots[0] and public >= 1:
                self.stats["equivocations"] += 1

        # silent slots: ticks only — no public blocks, no public votes
        for slot in slots[public:private]:
            self.start_slot_steps(slot, epoch)

        # release slot: the private branch + its banked votes land at once
        release_slot = slots[private]
        self.start_slot_steps(release_slot, epoch)
        seg.steps.append({"probe": "storm_pre"})
        for name in private_blocks:
            seg.steps.append({"block": name})
        for name in private_atts:
            seg.steps.append({"attestation": name})
        seg.steps.append({"probe": "storm_post"})
        self._registry.counter("scenario_build_storms_total").inc()

        # the reorg: private branch becomes canonical
        self.state = private_state
        self.chain = self.chain[:fork_chain_len] + private_blocks
        if public_head_slot is not None:
            depth = public_head_slot - (slots[0] - 1)
            self.stats["planned_reorg_depth_max"] = max(
                self.stats["planned_reorg_depth_max"], depth)

        # re-converge: canonical blocks on the private branch to epoch end
        self.canonical_block(release_slot)
        self.queue_votes(release_slot)
        for slot in slots[private + 1:]:
            self.start_slot_steps(slot, epoch)
            self.canonical_block(slot)
            self.queue_votes(slot)
