"""Fork registry: the single place that knows the fork lineage and how to
cross boundaries.

Reference parity: the role of `spec_builders`/`combine_spec_objects` fork
bookkeeping in the reference's setup.py (:446,492,551-554) plus the
`with_fork_metas` transition vocabulary (context.py:564). The compiler owns
document overlays (compiler/spec_compiler.py FORK_ORDER); this package owns
the runtime questions: what comes after X, how a state upgrades at a
boundary, and which forks are stable vs R&D.
"""
from __future__ import annotations

from ..compiler.spec_compiler import FORK_ORDER, PREVIOUS_FORK, get_spec

STABLE_FORKS = ("phase0", "altair", "bellatrix")
RND_FORKS = ("sharding", "das", "custody_game")

UPGRADE_FN = {
    "altair": "upgrade_to_altair",
    "bellatrix": "upgrade_to_bellatrix",
}


def previous_fork(fork: str) -> str | None:
    return PREVIOUS_FORK[fork]


def next_fork(fork: str) -> str | None:
    i = FORK_ORDER.index(fork)
    return FORK_ORDER[i + 1] if i + 1 < len(FORK_ORDER) else None


def is_post(fork: str, milestone: str) -> bool:
    """True when `fork` is `milestone` or any later fork."""
    return FORK_ORDER.index(fork) >= FORK_ORDER.index(milestone)


def upgrade_state(pre_state, to_fork: str, preset: str):
    """Upgrade a pre-fork state across the `to_fork` boundary using the
    post-fork spec's upgrade function (specs/<fork>/fork.md)."""
    fn_name = UPGRADE_FN.get(to_fork)
    if fn_name is None:
        raise ValueError(f"no upgrade function for fork {to_fork!r}")
    post_spec = get_spec(to_fork, preset)
    return getattr(post_spec, fn_name)(pre_state)


def fork_lineage(fork: str) -> list[str]:
    """The overlay chain phase0..fork, oldest first."""
    return FORK_ORDER[: FORK_ORDER.index(fork) + 1]
