"""consensus_specs_tpu — a TPU-native executable Ethereum PoS consensus-spec framework.

Built from scratch with the capabilities of the reference executable spec
(eth2spec, see /root/reference): SSZ type system + Merkleization, BLS12-381
signature stack, per-fork executable beacon-chain specs (phase0/altair/bellatrix),
fork choice, a conformance-test framework, and test-vector generators — with the
hot path (batched signature verification, shuffling, epoch registry math,
Merkleization) designed as JAX/XLA kernels over TPU meshes rather than scalar
C-library calls.

Layout:
  ssz/       SSZ type zoo, flat serialization, batched Merkleization, proofs
  crypto/    BLS12-381 fields/curves/pairing (pure-Python oracle) + shim
  ops/       batched device kernels (sha256, shuffle, field limb arithmetic)
  parallel/  mesh / sharding helpers (pjit / shard_map over jax.sharding.Mesh)
  forks/     executable spec modules per fork x preset
  config/    preset + runtime-config loading
  utils/     host-side utilities (hash, caches)
"""

__version__ = "0.1.0"

# Exact uint64 semantics in device code require x64 mode. Enabled lazily by the
# modules that trace jax code (ops/, parallel/) so that pure-host users do not
# pay the jax import cost.
