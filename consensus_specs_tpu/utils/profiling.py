"""Tracing/profiling hooks (SURVEY.md §5: the reference has only wall-clock
prints — real tracing is new surface this framework adds).

Thin, dependency-tolerant wrappers over the JAX profiler:

- `trace(logdir)`: context manager capturing a device trace viewable in
  TensorBoard/XProf/Perfetto (`jax.profiler.trace`).
- `annotate(name)`: labels a host-side region so it shows up inside the
  trace timeline (`jax.profiler.TraceAnnotation`).
- `annotate_fn(name)`: decorator form of the same.
- `timed(name)`: lightweight wall-clock section timing that accumulates into
  a process-global registry (`timings()`/`reset_timings()`), for the many
  places a full device trace is overkill — e.g. per-stage numbers in
  bench.py (`BENCH_PROFILE_DIR=/path`), generator hot-case forensics.

Everything degrades to a no-op if the profiler is unavailable (e.g. a
stripped CPU-only CI), so call sites never need to guard.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from functools import wraps

_TIMINGS: dict[str, list[float]] = defaultdict(list)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a JAX device trace under `logdir` for the enclosed region."""
    import jax

    # only the profiler START is guarded: a body exception must propagate
    # unchanged (a second yield under `except` would corrupt the generator)
    try:
        ctx = jax.profiler.trace(str(logdir))
        ctx.__enter__()
    except Exception:  # profiler backend unavailable: degrade to no-op
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            with contextlib.suppress(Exception):
                ctx.__exit__(None, None, None)


@contextlib.contextmanager
def annotate(name: str):
    """Label the enclosed host region in the active device trace."""
    import jax

    try:
        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:
        ctx = contextlib.nullcontext()
    with ctx:
        yield


def annotate_fn(name: str | None = None):
    def deco(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with annotate(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def timed(name: str):
    """Accumulate wall-clock time for `name` into the process registry."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _TIMINGS[name].append(time.perf_counter() - t0)


def timings() -> dict[str, dict[str, float]]:
    """{name: {count, total_s, mean_s, max_s}} snapshot."""
    out = {}
    for name, samples in _TIMINGS.items():
        out[name] = {
            "count": len(samples),
            "total_s": round(sum(samples), 6),
            "mean_s": round(sum(samples) / len(samples), 6),
            "max_s": round(max(samples), 6),
        }
    return out


def reset_timings() -> None:
    _TIMINGS.clear()
