from .hash import hash_eth2  # noqa: F401
