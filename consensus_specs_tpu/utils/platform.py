"""JAX platform selection guard for host-side tools.

The deployment environment pins JAX_PLATFORMS to a remote-TPU plugin that is
only registered when its site hook ran at interpreter start. Generator CLIs
and other host tools must work in both worlds: use the pinned platform when
it is actually available, otherwise fall back to CPU instead of dying with
"Backend 'axon' is not in the list of known backends".
"""
from __future__ import annotations


def ensure_usable_jax_backend() -> str:
    """Returns the selected backend name, downgrading to cpu if the pinned
    platform is unavailable in this process."""
    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
    return jax.default_backend()
