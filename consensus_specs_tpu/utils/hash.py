"""Scalar host-side hashing.

Reference parity: eth2spec's ``hash`` helper (tests/core/pyspec/eth2spec/utils/
hash_function.py:8) — sha256 returning 32 bytes. The batched device/vectorized
paths live in ops/sha256_np.py and ops/sha256_jax.py; this module is the plain
one-at-a-time boundary used by host-side control flow.
"""
from hashlib import sha256 as _sha256


def hash_eth2(data: bytes) -> bytes:
    """sha256(data) -> 32 bytes."""
    return _sha256(data).digest()
