"""Statement-for-statement Python twin of solidity_deposit_contract/
deposit_contract.sol.

This image ships no solc/EVM, so the contract's algorithm is validated by
keeping this twin in lockstep with the Solidity source (same storage layout,
same loops, same byte concatenations) and differentially testing it against
(a) the independent `utils/deposit_tree.DepositTree` and (b) the compiled
spec's `hash_tree_root(DepositData)` + `process_deposit` Merkle check
(tests/test_deposit_contract_twin.py). A change to the .sol file must be
mirrored here or the tests lose their meaning — keep the structures parallel.
"""
from __future__ import annotations

from hashlib import sha256 as _sha256

DEPOSIT_CONTRACT_TREE_DEPTH = 32
MAX_DEPOSIT_COUNT = 2**DEPOSIT_CONTRACT_TREE_DEPTH - 1
GWEI = 10**9
ETHER = 10**18


class DepositRevert(AssertionError):
    """A require() failure, carrying the contract's exact revert reason.

    Subclasses AssertionError so callers treating the twin's checks as
    assertions keep working, but raises even under `python -O` (a bare
    `assert` would vanish) and lets the differential suite
    (evm/differential.py) compare reasons string-for-string with the
    Error(string) payload the EVM bytecode reverts with.
    """

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _require(condition: bool, reason: str) -> None:
    if not condition:
        raise DepositRevert(reason)


def sha256(b: bytes) -> bytes:
    return _sha256(b).digest()


def to_little_endian_64(value: int) -> bytes:
    return value.to_bytes(8, "little")


class DepositContractTwin:
    def __init__(self):
        self.branch = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        self.deposit_count = 0
        self.zero_hashes = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH - 1):
            self.zero_hashes[height + 1] = sha256(
                self.zero_hashes[height] + self.zero_hashes[height]
            )
        self.events: list[dict] = []

    def get_deposit_root(self) -> bytes:
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1 == 1:
                node = sha256(self.branch[height] + node)
            else:
                node = sha256(node + self.zero_hashes[height])
            size //= 2
        return sha256(node + to_little_endian_64(self.deposit_count) + b"\x00" * 24)

    def get_deposit_count(self) -> bytes:
        return to_little_endian_64(self.deposit_count)

    def deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                signature: bytes, deposit_data_root: bytes, msg_value: int) -> None:
        # reasons are byte-identical to the .sol require() strings so the
        # twin<->EVM differential suite can assert revert-for-revert equality
        _require(len(pubkey) == 48, "DepositContract: invalid pubkey length")
        _require(len(withdrawal_credentials) == 32,
                 "DepositContract: invalid withdrawal_credentials length")
        _require(len(signature) == 96, "DepositContract: invalid signature length")

        _require(msg_value >= 1 * ETHER, "DepositContract: deposit value too low")
        _require(msg_value % GWEI == 0,
                 "DepositContract: deposit value not multiple of gwei")
        deposit_amount = msg_value // GWEI
        _require(deposit_amount <= 2**64 - 1, "DepositContract: deposit value too high")

        # (the .sol emits the event here; Python has no revert, so the emit
        # moves after the asserts to preserve the EVM's rollback atomicity)
        pubkey_root = sha256(pubkey + b"\x00" * 16)
        signature_root = sha256(
            sha256(signature[:64]) + sha256(signature[64:] + b"\x00" * 32)
        )
        node = sha256(
            sha256(pubkey_root + withdrawal_credentials)
            + sha256(to_little_endian_64(deposit_amount) + b"\x00" * 24 + signature_root)
        )
        _require(node == deposit_data_root,
                 "DepositContract: reconstructed DepositData does not match "
                 "supplied deposit_data_root")

        _require(self.deposit_count < MAX_DEPOSIT_COUNT,
                 "DepositContract: merkle tree full")
        self.events.append({
            "pubkey": pubkey,
            "withdrawal_credentials": withdrawal_credentials,
            "amount": to_little_endian_64(deposit_amount),
            "signature": signature,
            "index": to_little_endian_64(self.deposit_count),
        })
        self.deposit_count += 1

        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1 == 1:
                self.branch[height] = node
                return
            node = sha256(self.branch[height] + node)
            size //= 2
        raise AssertionError("unreachable")
