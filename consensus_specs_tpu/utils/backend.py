"""Backend pinning: force the host CPU platform before jax touches a device.

One copy of the accelerator-avoidance dance used by every TPU-free entry
point (tests/conftest.py, __graft_entry__.dryrun_multichip, bench.py's
debug lane, `make graft_check`). The environment pins JAX_PLATFORMS=axon (a
remote TPU tunnel) and its sitecustomize imports jax at interpreter start,
so three things are needed, in order: override the env var (for child
processes), drop the accelerator PJRT plugin factories (jax initializes
every registered plugin even when not selected, and the tunnel blocks when
another process holds the single TPU), and update jax_platforms (the env
var was already frozen into jax.config at import).
"""
from __future__ import annotations

ACCELERATOR_PLUGINS = ("axon", "tpu", "cuda", "rocm")


def enable_compile_cache(path: str | None = None):
    """Point JAX at a persistent on-disk compilation cache.

    The pairing/epoch kernels compile for minutes; caching the serialized
    XLA executables means only the first run on a given machine+code state
    pays. Works for both the CPU mesh and the TPU backend (entries are
    keyed by platform + HLO hash, so they never collide). Safe to delete
    the directory at any time. Returns the jax module."""
    import os

    import jax

    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def force_cpu(n_devices: int | None = None):
    """Pin this process to the CPU backend; with `n_devices`, provision a
    virtual multi-device CPU mesh (tearing down any already-initialized
    backend — three caches must all clear or the old backend keeps being
    served: _backends, get_backend's lru, and the plugin factory table).

    Safe to call before OR after a backend exists; never probes an
    accelerator. Returns the jax module."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    from jax._src import xla_bridge as xb

    for plugin in ACCELERATOR_PLUGINS:
        xb._backend_factories.pop(plugin, None)
    jax.config.update("jax_platforms", "cpu")
    if n_devices is not None:
        if getattr(xb, "_backends", None):
            xb._clear_backends()
            xb.get_backend.cache_clear()
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except AttributeError:
            # jax builds without the jax_num_cpu_devices config option
            # (<= 0.4.x): the XLA flag is the portable spelling. It is read
            # at backend init, which the _clear_backends above guarantees
            # is still ahead of us.
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={n_devices}"
                ).strip()
    return jax
