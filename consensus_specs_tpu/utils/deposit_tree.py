"""Deposit-contract incremental Merkle tree (host tooling).

Reference parity: the on-chain contract's algorithm
(solidity_deposit_contract/deposit_contract.sol — `deposit()` :101 updates
one branch node per insertion; `get_deposit_root()` :80 folds the branch
against the zero-hash ladder and mixes in the little-endian deposit count)
and its spec `specs/phase0/deposit-contract.md`. The EVM artifact itself is
external to this framework; this module re-implements the data structure for
genesis tooling and deposit-proof construction, matching
`process_deposit`'s `is_valid_merkle_branch(leaf, proof,
DEPOSIT_CONTRACT_TREE_DEPTH + 1, index, deposit_root)` check
(specs/phase0/beacon-chain.md:1851) bit-for-bit.

O(1) storage per insertion (the `branch` array holds one node per level —
the root of the largest complete subtree left of the insertion frontier at
that height), O(log n) per root read. Proof generation for arbitrary
indices keeps the full leaf list (tooling only; the contract never needs
proofs — clients build them from the log).
"""
from __future__ import annotations

from .hash import hash_eth2 as sha256

DEPOSIT_CONTRACT_TREE_DEPTH = 32


class TreeFullError(AssertionError):
    """Insert past 2**depth - 1 leaves (the contract's "merkle tree full"
    revert — one slot stays free so the count mix-in can never collide with
    a full bottom layer).  Subclasses AssertionError for existing callers,
    but survives `python -O`."""


def _zero_hashes(depth: int = DEPOSIT_CONTRACT_TREE_DEPTH) -> list[bytes]:
    zh = [b"\x00" * 32]
    for _ in range(depth - 1):
        zh.append(sha256(zh[-1] + zh[-1]))
    return zh


ZERO_HASHES = _zero_hashes()


class DepositTree:
    """Incremental depth-32 Merkle accumulator with count mix-in.

    `depth` parameterizes the accumulator so the tree-full boundary (2**32-1
    inserts on the real contract — unreachable in a test) can be exercised at
    a small depth; production callers never pass it.
    """

    def __init__(self, depth: int = DEPOSIT_CONTRACT_TREE_DEPTH) -> None:
        assert 1 <= depth <= DEPOSIT_CONTRACT_TREE_DEPTH
        self.depth = depth
        self.branch: list[bytes] = [b"\x00" * 32] * depth
        self.leaves: list[bytes] = []  # retained for proof tooling

    @property
    def deposit_count(self) -> int:
        return len(self.leaves)

    def push(self, leaf: bytes) -> None:
        """Insert hash_tree_root(DepositData); one branch node changes."""
        assert len(leaf) == 32
        if self.deposit_count >= 2**self.depth - 1:
            # the contract's `require(deposit_count < MAX_DEPOSIT_COUNT,
            # "DepositContract: merkle tree full")` — same boundary, and a
            # real exception so host tooling cannot overfill under -O
            raise TreeFullError("merkle tree full")
        self.leaves.append(leaf)
        size = self.deposit_count
        node = leaf
        for h in range(self.depth):
            if size & 1:
                self.branch[h] = node
                return
            node = sha256(self.branch[h] + node)
            size >>= 1
        raise AssertionError("unreachable: size bound checked above")

    def root(self) -> bytes:
        """`get_deposit_root()`: branch fold + little-endian count mix-in."""
        node = b"\x00" * 32
        size = self.deposit_count
        for h in range(self.depth):
            if size & 1:
                node = sha256(self.branch[h] + node)
            else:
                node = sha256(node + ZERO_HASHES[h])
            size >>= 1
        return sha256(node + self.deposit_count.to_bytes(8, "little") + b"\x00" * 24)

    def proof(self, index: int) -> list[bytes]:
        """(depth+1)-element branch for leaf `index` against the CURRENT
        root: depth sibling hashes plus the count mix-in node — at the
        default depth, the exact 33-node shape `process_deposit` verifies at
        DEPOSIT_CONTRACT_TREE_DEPTH + 1."""
        assert 0 <= index < self.deposit_count
        # level 0 = padded leaves; level h nodes pair into level h+1
        level = list(self.leaves)
        proof: list[bytes] = []
        idx = index
        for h in range(self.depth):
            sibling = idx ^ 1
            proof.append(level[sibling] if sibling < len(level) else ZERO_HASHES[h])
            nxt = []
            for i in range(0, len(level), 2):
                left = level[i]
                right = level[i + 1] if i + 1 < len(level) else ZERO_HASHES[h]
                nxt.append(sha256(left + right))
            level = nxt or [ZERO_HASHES[h]]
            idx >>= 1
        proof.append(self.deposit_count.to_bytes(8, "little") + b"\x00" * 24)
        return proof


def is_valid_deposit_proof(leaf: bytes, proof: list[bytes], index: int, root: bytes) -> bool:
    """Standalone `is_valid_merkle_branch` at depth 33 (for tests/tooling;
    the compiled specs carry their own copy)."""
    value = leaf
    for i, node in enumerate(proof):
        if (index >> i) & 1:
            value = sha256(node + value)
        else:
            value = sha256(value + node)
    return value == root
