"""Random SSZ object fuzzer for the ssz_static vector generator.

Reference parity: tests/core/pyspec/eth2spec/debug/random_value.py — six
randomization modes plus a chaos switch:

  random     fully random values, random list/bytelist lengths
  zero       all-zero values, empty lists
  max        all-max values (0xff bytes, max uints), empty lists
  nil        lists empty, everything else random
  one        lists of length 1, everything else random
  lengthy    lists at their max sampled length, everything else random

chaos=True re-rolls the mode per sub-object, producing mixed shapes.
"""
from __future__ import annotations

from enum import Enum
from random import Random

from ..ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)


class RandomizationMode(Enum):
    mode_random = 0
    mode_zero = 1
    mode_max = 2
    mode_nil_count = 3
    mode_one_count = 4
    mode_max_count = 5

    def is_changing(self) -> bool:
        """Modes that vary element values (not the all-zero / all-max fills)."""
        return self in (
            RandomizationMode.mode_random,
            RandomizationMode.mode_nil_count,
            RandomizationMode.mode_one_count,
            RandomizationMode.mode_max_count,
        )


def get_random_ssz_object(
    rng: Random,
    typ,
    max_bytes_length: int,
    max_list_length: int,
    mode: RandomizationMode,
    chaos: bool = False,
):
    if chaos:
        mode = rng.choice(list(RandomizationMode))

    if issubclass(typ, boolean):
        if mode == RandomizationMode.mode_zero:
            return typ(False)
        if mode == RandomizationMode.mode_max:
            return typ(True)
        return typ(rng.choice((True, False)))

    if issubclass(typ, uint):
        if mode == RandomizationMode.mode_zero:
            return typ(0)
        if mode == RandomizationMode.mode_max:
            return typ(2 ** (typ.BYTE_LEN * 8) - 1)
        return typ(rng.randint(0, 2 ** (typ.BYTE_LEN * 8) - 1))

    if issubclass(typ, ByteVector):
        if mode == RandomizationMode.mode_zero:
            return typ(b"\x00" * typ.LENGTH)
        if mode == RandomizationMode.mode_max:
            return typ(b"\xff" * typ.LENGTH)
        return typ(rng.randbytes(typ.LENGTH))

    if issubclass(typ, ByteList):
        length = min(typ.LIMIT, max_bytes_length)
        if mode in (RandomizationMode.mode_zero, RandomizationMode.mode_max, RandomizationMode.mode_nil_count):
            n = 0
        elif mode == RandomizationMode.mode_one_count:
            n = min(1, length)
        elif mode == RandomizationMode.mode_max_count:
            n = length
        else:
            n = rng.randint(0, length)
        fill = b"\x00" if mode == RandomizationMode.mode_zero else b"\xff"
        if mode in (RandomizationMode.mode_zero, RandomizationMode.mode_max):
            return typ(fill * n)
        return typ(rng.randbytes(n))

    if issubclass(typ, Bitvector):
        if mode == RandomizationMode.mode_zero:
            return typ([False] * typ.LENGTH)
        if mode == RandomizationMode.mode_max:
            return typ([True] * typ.LENGTH)
        return typ([rng.choice((True, False)) for _ in range(typ.LENGTH)])

    if issubclass(typ, Bitlist):
        length = min(typ.LIMIT, max_list_length)
        if mode in (RandomizationMode.mode_zero, RandomizationMode.mode_max, RandomizationMode.mode_nil_count):
            n = 0
        elif mode == RandomizationMode.mode_one_count:
            n = min(1, length)
        elif mode == RandomizationMode.mode_max_count:
            n = length
        else:
            n = rng.randint(0, length)
        if mode == RandomizationMode.mode_max:
            return typ([True] * n)
        return typ([rng.choice((True, False)) for _ in range(n)])

    if issubclass(typ, Vector):
        return typ(
            *[
                get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length, max_list_length, mode, chaos)
                for _ in range(typ.LENGTH)
            ]
        )

    if issubclass(typ, List):
        length = min(typ.LIMIT, max_list_length)
        if mode in (RandomizationMode.mode_zero, RandomizationMode.mode_nil_count):
            n = 0
        elif mode == RandomizationMode.mode_one_count:
            n = min(1, length)
        elif mode in (RandomizationMode.mode_max, RandomizationMode.mode_max_count):
            n = length
        else:
            n = rng.randint(0, length)
        return typ(
            *[
                get_random_ssz_object(rng, typ.ELEM_TYPE, max_bytes_length, max_list_length, mode, chaos)
                for _ in range(n)
            ]
        )

    if issubclass(typ, Container):
        return typ(
            **{
                name: get_random_ssz_object(rng, ftyp, max_bytes_length, max_list_length, mode, chaos)
                for name, ftyp in typ.fields().items()
            }
        )

    if issubclass(typ, Union):
        if mode == RandomizationMode.mode_zero:
            selector = 0
        elif mode == RandomizationMode.mode_max:
            selector = len(typ.OPTIONS) - 1
        else:
            selector = rng.randrange(len(typ.OPTIONS))
        opt = typ.OPTIONS[selector]
        value = (
            None
            if opt is None
            else get_random_ssz_object(rng, opt, max_bytes_length, max_list_length, mode, chaos)
        )
        return typ(selector, value)

    raise TypeError(f"cannot generate random {typ.__name__}")
