"""Debug / introspection codecs (reference layer L8).

SSZ objects <-> plain YAML-safe python structures (for test vectors), plus a
random SSZ object fuzzer used by the ssz_static vector generator.

Reference parity: tests/core/pyspec/eth2spec/debug/{encode.py,decode.py,
random_value.py}.
"""
from .encode import encode
from .decode import decode
from .random_value import RandomizationMode, get_random_ssz_object

__all__ = ["encode", "decode", "RandomizationMode", "get_random_ssz_object"]
