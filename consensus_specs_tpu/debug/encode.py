"""SSZ object -> YAML-safe python structure.

Format compatibility with the reference vector corpus is a conformance
requirement (tests/core/pyspec/eth2spec/debug/encode.py): uints wider than
32 bits become decimal strings (YAML 1.1 int readers lose precision beyond
2^53), byte blobs and packed bitfields become 0x-hex strings, containers
become dicts keyed by field name.
"""
from __future__ import annotations

from ..ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)


def encode(value):
    if isinstance(value, boolean):
        return bool(value)
    if isinstance(value, uint):
        return int(value) if value.BYTE_LEN <= 4 else str(int(value))
    if isinstance(value, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (Bitvector, Bitlist)):
        return "0x" + value.encode_bytes().hex()
    if isinstance(value, (Vector, List)):
        return [encode(e) for e in value]
    if isinstance(value, Container):
        return {name: encode(getattr(value, name)) for name in value.fields()}
    if isinstance(value, Union):
        return {"selector": value.selector, "value": None if value.value is None else encode(value.value)}
    raise TypeError(f"cannot encode {type(value).__name__}")
