"""YAML-safe python structure -> SSZ object (inverse of debug/encode.py).

Reference parity: tests/core/pyspec/eth2spec/debug/decode.py.
"""
from __future__ import annotations

from ..ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)


def decode(data, typ):
    if issubclass(typ, boolean):
        return typ(data)
    if issubclass(typ, uint):
        return typ(int(data))
    if issubclass(typ, (ByteVector, ByteList)):
        return typ(bytes.fromhex(data[2:]))
    if issubclass(typ, (Bitvector, Bitlist)):
        return typ.decode_bytes(bytes.fromhex(data[2:]))
    if issubclass(typ, (Vector, List)):
        return typ(*[decode(e, typ.ELEM_TYPE) for e in data])
    if issubclass(typ, Container):
        return typ(**{name: decode(data[name], ft) for name, ft in typ.fields().items()})
    if issubclass(typ, Union):
        sel = int(data["selector"])
        opt = typ.OPTIONS[sel]
        val = None if opt is None else decode(data["value"], opt)
        return typ(selector=sel, value=val)
    raise TypeError(f"cannot decode into {typ.__name__}")
