"""Test-vector generator runtime (reference layer L7).

Reference parity: tests/core/pyspec/eth2spec/gen_helpers/ — gen_base
(run_generator, TestCase/TestProvider) and gen_from_tests (reflection bridge
from dual-mode test modules to vector output).
"""
from .gen_typing import TestCase, TestProvider
from .gen_runner import run_generator
from .gen_from_tests import generate_from_tests, run_state_test_generators

__all__ = [
    "TestCase",
    "TestProvider",
    "run_generator",
    "generate_from_tests",
    "run_state_test_generators",
]
