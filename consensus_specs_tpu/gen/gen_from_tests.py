"""Reflection bridge: dual-mode test modules -> vector TestCases.

Reference parity: gen_helpers/gen_from_tests/gen.py (generate_from_tests
:13-56, run_state_test_generators :96-111, combine_mods :114-132): discover
`test_*` functions in a module, re-run each with generator_mode=True pinned
to one (fork, preset), and map module names to runner/handler names. BLS is
forced on for vector generation (reference :75-77) except where a test is
tagged never_bls.
"""
from __future__ import annotations

import importlib
import inspect
from typing import Iterable

from ..crypto import bls
from .gen_typing import TestCase, TestProvider


def generate_from_tests(
    runner_name: str,
    handler_name: str,
    src,
    fork_name: str,
    preset_name: str,
    suite_name: str = "pyspec_tests",
    bls_active: bool = True,
    name_prefix: str = "",
) -> Iterable[TestCase]:
    """name_prefix filters to tests named test_<prefix>* — lets one module
    back multiple handlers (e.g. genesis initialization vs validity)."""
    for name, fn in inspect.getmembers(src, inspect.isfunction):
        if not name.startswith("test_" + name_prefix):
            continue
        run_phases = getattr(fn, "run_phases", None)
        if run_phases is not None and fork_name not in run_phases:
            continue
        allowed = getattr(fn, "allowed_presets", None)
        if allowed is not None and preset_name not in allowed:
            continue
        case_name = name[len("test_") :]

        def case_fn(fn=fn):
            return fn(
                fork=fork_name,
                preset=preset_name,
                generator_mode=True,
                bls_active=bls_active,
            )

        yield TestCase(
            fork_name=fork_name,
            preset_name=preset_name,
            runner_name=runner_name,
            handler_name=handler_name,
            suite_name=suite_name,
            case_name=case_name,
            case_fn=case_fn,
        )


def combine_mods(dict_1: dict, dict_2: dict) -> dict:
    """Merge {handler: [module,...]} maps (fork inheritance of test modules)."""
    out = {k: list(v if isinstance(v, list) else [v]) for k, v in dict_1.items()}
    for k, v in dict_2.items():
        out.setdefault(k, [])
        out[k] += v if isinstance(v, list) else [v]
    return out


def run_state_test_generators(
    runner_name: str,
    all_mods: dict[str, dict[str, object]],
    presets: tuple = ("minimal", "mainnet"),
) -> None:
    """all_mods: {fork: {handler: module-or-dotted-name-or-list}}."""
    from .gen_runner import run_generator

    def make_cases():
        for fork_name, handlers in all_mods.items():
            for handler_name, mods in handlers.items():
                for mod in mods if isinstance(mods, list) else [mods]:
                    prefix = ""
                    if isinstance(mod, tuple):
                        mod, prefix = mod
                    if isinstance(mod, str):
                        mod = importlib.import_module(mod)
                    for preset_name in presets:
                        yield from generate_from_tests(
                            runner_name, handler_name, mod, fork_name, preset_name,
                            name_prefix=prefix,
                        )

    def prepare():
        bls.bls_active = True
        # CONSENSUS_TPU_GEN_BLS=jax: verify through the batched XLA pairing
        # backend instead of the pure-Python oracle — the reference's
        # generators make the same move (milagro on CI, gen.py:75-77),
        # because host-oracle pairings at ~1.5 s each make block-rich
        # suites (sanity, finality) generation-bound. With the persistent
        # compile cache the bucketed flush shapes compile once per machine.
        import os

        if os.environ.get("CONSENSUS_TPU_GEN_BLS") == "jax":
            # force_cpu, not JAX_PLATFORMS: an accelerator sitecustomize
            # freezes jax_platforms before env vars are consulted, and a
            # dead tunnel makes the first devices() call hang — the
            # plugin-factory drop in force_cpu is the only reliable pin.
            from ..utils.backend import enable_compile_cache, force_cpu

            force_cpu()
            enable_compile_cache()
            bls.use_jax()

    raise SystemExit(
        run_generator(runner_name, [TestProvider(make_cases=make_cases, prepare=prepare)])
    )
