"""Vector-generator CLI runtime.

Reference parity: gen_helpers/gen_base/gen_runner.py (run_generator :41-218,
dump_yaml_fn :221, dump_ssz_fn :229): walks TestProviders, writes each case
under <preset>/<fork>/<runner>/<handler>/<suite>/<case>/, YAML for data/meta
parts, snappy-compressed SSZ for binary parts, an INCOMPLETE sentinel during
writing for crash forensics, an error log, skip-existing incremental mode,
and a slow-case timing print.

Output tree and file conventions match the consensus-spec-tests format
(reference tests/formats/README.md) so external clients can consume vectors
from either framework interchangeably.
"""
from __future__ import annotations

import argparse
import shutil
import sys
import time
import traceback
from pathlib import Path

import yaml

from ..native import snappy
from ..ssz import SSZType, serialize
from .gen_typing import TestCase, TestProvider

TIME_THRESHOLD_TO_PRINT = 1.0  # seconds


def _dump_yaml(path: Path, name: str, data) -> None:
    with open(path / f"{name}.yaml", "w") as f:
        yaml.safe_dump(data, f, default_flow_style=None)


def _dump_ssz(path: Path, name: str, value) -> None:
    raw = serialize(value) if isinstance(value, SSZType) else bytes(value)
    with open(path / f"{name}.ssz_snappy", "wb") as f:
        f.write(snappy.compress(raw))


def _write_case(case: TestCase, case_dir: Path, log: list[str]) -> bool:
    """Returns True if the case produced output (False => skipped/empty)."""
    parts = case.case_fn()
    if not parts:  # None or [] — a body that declined (preset guard etc.)
        return False
    case_dir.mkdir(parents=True, exist_ok=True)
    incomplete = case_dir / "INCOMPLETE"
    incomplete.touch()
    meta: dict = {}
    for name, kind, value in parts:
        if kind == "meta":
            # a dict yielded under the literal name "meta" merges flat —
            # meta.yaml is a flat mapping in the reference vector format
            if name == "meta" and isinstance(value, dict):
                meta.update(value)
            else:
                meta[name] = value
        elif kind == "ssz":
            _dump_ssz(case_dir, name, value)
        elif kind == "data":
            _dump_yaml(case_dir, name, value)
        else:
            raise ValueError(f"unknown part kind {kind!r} for part {name!r}")
    if meta:
        _dump_yaml(case_dir, "meta", meta)
    incomplete.unlink()
    return True


def run_generator(generator_name: str, providers: list[TestProvider], args=None) -> int:
    parser = argparse.ArgumentParser(prog=f"gen-{generator_name}")
    parser.add_argument("-o", "--output-dir", required=True)
    parser.add_argument("-f", "--force", action="store_true", help="regenerate existing cases")
    parser.add_argument("--preset-list", nargs="*", default=None)
    parser.add_argument("--fork-list", nargs="*", default=None)
    parser.add_argument(
        "--smoke", type=int, default=None, metavar="N",
        help="stop after N cases have been generated or failed — the "
             "default-lane health probe (tests/test_generator_smoke.py) "
             "that bounds every generator's wall-clock",
    )
    ns = parser.parse_args(args)

    output_dir = Path(ns.output_dir)
    log: list[str] = []
    generated = skipped = failed = 0

    for provider in providers:
        provider.prepare()
        for case in provider.make_cases():
            if ns.preset_list and case.preset_name not in ns.preset_list:
                continue
            if ns.fork_list and case.fork_name not in ns.fork_list:
                continue
            case_dir = output_dir / "tests" / case.path
            if case_dir.exists():
                if not ns.force and not (case_dir / "INCOMPLETE").exists():
                    skipped += 1
                    continue
                shutil.rmtree(case_dir)
            t0 = time.time()
            try:
                if _write_case(case, case_dir, log):
                    generated += 1
                else:
                    skipped += 1
            except Exception:
                failed += 1
                err = f"[ERROR] {case.path}:\n{traceback.format_exc()}"
                log.append(err)
                print(err, file=sys.stderr)
            elapsed = time.time() - t0
            if elapsed > TIME_THRESHOLD_TO_PRINT:
                print(f"[slow] {case.path}: {elapsed:.1f}s")
            if ns.smoke is not None and generated + failed >= ns.smoke:
                break
        if ns.smoke is not None and generated + failed >= ns.smoke:
            break

    if log:
        output_dir.mkdir(parents=True, exist_ok=True)
        with open(output_dir / "testgen_error_log.txt", "a") as f:
            f.write("\n".join(log) + "\n")
    print(
        f"{generator_name}: generated {generated}, skipped {skipped}, failed {failed}"
    )
    return 1 if failed else 0


def detect_incomplete(output_dir: str) -> list[str]:
    """Paths of cases whose INCOMPLETE sentinel survived (crash forensics)."""
    return [str(p.parent) for p in Path(output_dir).rglob("INCOMPLETE")]
