"""Typed shapes of the vector-generator pipeline.

Reference parity: gen_helpers/gen_base/gen_typing.py (TestCase :20,
TestProvider :31). A case's `case_fn` returns the typed parts list produced
by the dual-mode context engine: [(name, kind, value)] with kind in
{"meta", "data", "ssz"}.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional, Tuple


@dataclass
class TestCase:
    fork_name: str
    preset_name: str
    runner_name: str
    handler_name: str
    suite_name: str
    case_name: str
    case_fn: Callable[[], Optional[List[Tuple[str, str, object]]]]
    dir_meta: dict = field(default_factory=dict)

    @property
    def path(self) -> str:
        return "/".join(
            (
                self.preset_name,
                self.fork_name,
                self.runner_name,
                self.handler_name,
                self.suite_name,
                self.case_name,
            )
        )


@dataclass
class TestProvider:
    """prepare() runs once (e.g. switch BLS backend); make_cases yields cases."""

    make_cases: Callable[[], Iterable[TestCase]]
    prepare: Callable[[], None] = lambda: None
