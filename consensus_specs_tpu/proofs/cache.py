"""Epoch-versioned Merkle proof cache keyed by the dirty-column diff.

The PR-1 epoch programs report exactly which registry columns a
transition touched (`engine/state.EpochAux.dirty_cols`; the resident
engine OR-accumulates them across a segment). A branch proven inside a
column's chunk tree stays valid as long as that column's values do, so
the cache invalidates per COLUMN, not per epoch: clean columns keep their
sibling rows across epoch advances, only dirty columns drop.

Hit/miss/invalidation counters plus the hit-ratio and resident-entry
gauges land in obs (`proof_cache_*`), so the read lane's cache behaviour
is part of every snapshot. jax-free at module level by charter.
"""
from __future__ import annotations

import threading

from ..obs import metrics as obs_metrics


class ProofCache:
    """(column, gindex) -> deepest-first sibling-branch tuple, dropped per
    dirty column at each epoch advance."""

    def __init__(self, registry: obs_metrics.MetricsRegistry | None = None):
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self._lock = threading.Lock()
        self._entries: dict[str, dict[int, tuple]] = {}
        self._hits = 0
        self._misses = 0
        self.epoch = 0

    def lookup(self, column: str, gindex: int):
        """Cached branch or None; counts the hit/miss and refreshes the
        hit-ratio gauge either way."""
        with self._lock:
            branch = self._entries.get(column, {}).get(int(gindex))
            if branch is None:
                self._misses += 1
                self.registry.counter(
                    "proof_cache_misses_total", column=column).inc()
            else:
                self._hits += 1
                self.registry.counter(
                    "proof_cache_hits_total", column=column).inc()
            self._refresh_gauges_locked()
            return branch

    def store(self, column: str, gindex: int, branch) -> None:
        with self._lock:
            self._entries.setdefault(column, {})[int(gindex)] = tuple(
                bytes(b) for b in branch)
            self._refresh_gauges_locked()

    def advance_epoch(self, dirty_columns) -> int:
        """Advance one epoch, invalidating exactly the dirty columns'
        entries; returns how many branches dropped. `dirty_columns` is an
        iterable of column names (a mapping counts its truthy-valued
        keys — the `resident.dirty_columns()` shape)."""
        if hasattr(dirty_columns, "items"):
            dirty_columns = [k for k, v in dirty_columns.items() if v]
        with self._lock:
            self.epoch += 1
            dropped = 0
            for col in dirty_columns:
                n = len(self._entries.pop(col, ()))
                if n:
                    self.registry.counter(
                        "proof_cache_invalidated_total", column=col).inc(n)
                dropped += n
            self._refresh_gauges_locked()
            return dropped

    def entries(self, column: str) -> dict:
        """Snapshot of one column's cached {gindex: branch} (tests and
        introspection; mutating the copy does not touch the cache)."""
        with self._lock:
            return dict(self._entries.get(column, ()))

    def size(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._entries.values())

    def _refresh_gauges_locked(self) -> None:
        total = self._hits + self._misses
        self.registry.gauge("proof_cache_hit_ratio").set(
            self._hits / total if total else 0.0)
        self.registry.gauge("proof_cache_entries").set(
            sum(len(v) for v in self._entries.values()))
