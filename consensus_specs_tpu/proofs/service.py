"""Read-lane front end: cache lookups backed by sched multiproof batches.

A ProofService owns column providers (name -> callable returning the
column's CURRENT 32-byte chunk list), a ProofCache, and a scheduler.
`prove_many` answers every query it can from cache and batches the misses
into "multiproof" submits on the merkle work class: one flush serves all
misses, same-column queries share one provider read and one device tree
slot, and each device branch is stored back so the next epoch's clean
columns answer from cache. `note_epoch` wires the PR-1 dirty-column diff
into the cache's invalidation.

The lane's own latency histogram (`proof_request_latency_seconds`) is
where the bench's p99 comes from: each query in a batch observes the full
batch latency — what a beacon-API caller of that batch actually waited.
jax-free at module level by charter.
"""
from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..sched.api import Request
from .cache import ProofCache


def u64_column_chunks(column) -> list[bytes]:
    """SSZ-pack a uint64 column into 32-byte chunks (4 values per chunk,
    little-endian, zero-padded) — the registry-column leaf layout the
    multiproof kernel serves."""
    a = np.asarray(column).astype("<u8", copy=False).reshape(-1)
    pad = (-a.shape[0]) % 4
    if pad:
        a = np.concatenate([a, np.zeros(pad, dtype="<u8")])
    raw = a.tobytes()
    return [raw[i:i + 32] for i in range(0, len(raw), 32)]


def leaf_gindex(chunk_index: int, chunk_count: int) -> int:
    """Generalized index of chunk `chunk_index` within a chunk tree padded
    to the next power of two — the "multiproof" kind's leaf addressing."""
    from ..ssz.merkle import next_power_of_two

    c_full = next_power_of_two(max(1, int(chunk_count)))
    if not 0 <= int(chunk_index) < c_full:
        raise ValueError(
            f"chunk index {chunk_index} outside the {c_full}-leaf tree")
    return c_full + int(chunk_index)


class ProofService:
    """Serve (column, gindex) branch queries: cache first, batched device
    multiproofs for the misses, dirty-column invalidation per epoch."""

    def __init__(self, scheduler=None, cache: ProofCache | None = None,
                 registry: obs_metrics.MetricsRegistry | None = None):
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self.cache = (cache if cache is not None
                      else ProofCache(registry=self.registry))
        self._scheduler = scheduler
        self._providers: dict = {}
        self._latency = self.registry.histogram(
            "proof_request_latency_seconds")
        self._requests = self.registry.counter("proof_requests_total")

    def _sched(self):
        if self._scheduler is None:
            from ..sched.scheduler import default_scheduler

            self._scheduler = default_scheduler()
        return self._scheduler

    def register_column(self, name: str, chunks_provider) -> None:
        """`chunks_provider()` must return the column's CURRENT 32-byte
        chunk list; it is consulted at most once per prove_many flush."""
        self._providers[name] = chunks_provider

    def note_epoch(self, dirty) -> int:
        """Advance the cache one epoch given the dirty-column diff
        (mapping name -> moved, or an iterable of dirty names); returns
        the number of invalidated branches."""
        return self.cache.advance_epoch(dirty)

    def prove(self, column: str, gindex: int) -> tuple:
        return self.prove_many([(column, gindex)])[0]

    def prove_host(self, column: str, gindex: int) -> tuple:
        """Degraded read: serve the branch from the host `build_chunk_proof`
        oracle, bypassing cache and scheduler entirely. This is the shed
        ladder's light-client fallback (frontdoor): when the device lanes
        are saturated, a caller that opted into degraded reads still gets a
        bit-identical branch — build_chunk_proof is the same oracle the
        multiproof kernel is pinned against — it just pays host latency and
        never warms the cache."""
        if column not in self._providers:
            raise KeyError(f"unregistered proof column {column!r}")
        from ..ssz.proofs import build_chunk_proof

        chunks = [bytes(c) for c in self._providers[column]()]
        branch = tuple(build_chunk_proof(chunks, int(gindex)))
        self.registry.counter("proof_degraded_reads_total").inc()
        return branch

    def prove_many(self, queries) -> list:
        """One branch (deepest-first tuple of 32-byte siblings) per
        (column, gindex) query, in input order; cache hits answer
        immediately, misses batch into one scheduler flush."""
        t0 = time.perf_counter()
        queries = list(queries)
        results: list = [None] * len(queries)
        misses = []
        for qi, (column, gindex) in enumerate(queries):
            if column not in self._providers:
                raise KeyError(f"unregistered proof column {column!r}")
            branch = self.cache.lookup(column, gindex)
            if branch is None:
                misses.append(qi)
            else:
                results[qi] = branch
        if misses:
            sched = self._sched()
            chunks_by_column: dict = {}
            handles = []
            for qi in misses:
                column, gindex = queries[qi]
                chunks = chunks_by_column.get(column)
                if chunks is None:
                    chunks = tuple(
                        bytes(c) for c in self._providers[column]())
                    chunks_by_column[column] = chunks
                handles.append(sched.submit(Request(
                    work_class="merkle", kind="multiproof",
                    payload=(chunks, int(gindex)))))
            sched.flush("merkle")
            for qi, h in zip(misses, handles):
                column, gindex = queries[qi]
                branch = tuple(h.result())
                self.cache.store(column, gindex, branch)
                results[qi] = branch
        dt = time.perf_counter() - t0
        self._requests.inc(len(queries))
        for _ in queries:
            self._latency.observe(dt)
        return results
