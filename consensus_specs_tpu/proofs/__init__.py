"""Light-client read lane: epoch-versioned proof cache + serving front end.

jax-free at module level by charter (tpulint import-layering): device work
reaches the multiproof kernel only through sched "multiproof" submits, so
shims and tools can import the cache without dragging the device stack in.
"""
from .cache import ProofCache
from .service import ProofService, leaf_gindex, u64_column_chunks

__all__ = ["ProofCache", "ProofService", "leaf_gindex", "u64_column_chunks"]
