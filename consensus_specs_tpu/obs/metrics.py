"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

One registry (`REGISTRY`) for the whole process, mirroring how Prometheus
client libraries model it: every hot-path seam increments named instruments
here, and the exporters (obs/export.py) read one coherent snapshot instead
of scraping module-global dicts (`LAST_FLUSH`), dataclasses (`NodeStats`)
and ad-hoc event lists (the breaker log) that cannot see each other.

Design constraints, in priority order:

  1. CHEAP — an increment is a dict lookup plus an int add under a lock the
     hot paths never contend (tier-1 is single-threaded; the gossip rx
     threads touch disjoint label sets). Instrument handles are stable
     objects, so call sites may cache them and skip even the lookup.
  2. jax-free at module level (tpulint import-layering: `obs/` is consumed
     by the jax-free branches — crypto/bls.py, robustness/ — so it inherits
     their constraint; device hooks live behind obs/recompile.install()).
  3. CANONICAL — `snapshot()` returns a plain dict whose keys are the
     Prometheus series identities (`name{k="v"}`, labels sorted), so two
     snapshots of equal registry state serialize byte-identically and the
     JSON and Prometheus exporters agree on the value set by construction.

Histograms use FIXED buckets (log-spaced seconds by default): quantile
readout (p50/p99) is bucket interpolation, never a sample sort, so memory
per histogram is O(buckets) no matter how long the soak runs — the same
reason the breaker event log is now a bounded ring.
"""
from __future__ import annotations

import threading
from bisect import bisect_left

# Log-spaced latency buckets (seconds): 1us .. 60s. Device dispatches sit in
# the 1ms-1s decades, host epilogues in 10us-10ms, pairing flushes can reach
# tens of seconds on the cpu-debug lane — one shared ladder keeps every
# span/seam comparable in the exported snapshot.
DEFAULT_BUCKETS = (
    1e-6, 1e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n")


def series_key(name: str, labels: dict | None = None) -> str:
    """Prometheus series identity: `name` or `name{k="v",...}`, labels
    sorted by key — THE canonical key for snapshots and exporters."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic integer counter."""

    __slots__ = ("key", "_value", "_lock")

    def __init__(self, key: str, lock: threading.Lock):
        self.key = key
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        self._value = 0


class Gauge:
    """Last-write-wins numeric gauge."""

    __slots__ = ("key", "_value", "_lock")

    def __init__(self, key: str, lock: threading.Lock):
        self.key = key
        self._value = 0.0
        self._lock = lock

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def add(self, v) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self):
        return self._value

    def _reset(self) -> None:
        self._value = 0.0


class Histogram:
    """Fixed-bucket histogram with sum/count/min/max and quantile readout.

    Buckets are upper-bound edges (non-cumulative counts internally; the
    snapshot exports CUMULATIVE counts plus the +Inf bucket, matching the
    Prometheus text format so the two exporters share one value set)."""

    __slots__ = ("key", "buckets", "_counts", "_sum", "_count", "_min",
                 "_max", "_exemplars", "_lock")

    def __init__(self, key: str, lock: threading.Lock,
                 buckets: tuple = DEFAULT_BUCKETS):
        self.key = key
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(self.buckets), "bucket edges must ascend"
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._exemplars: dict[int, str] = {}  # bucket ix -> last trace id
        self._lock = lock

    def observe(self, v: float, exemplar: str | None = None) -> None:
        """Record one observation. `exemplar` (optional) is a trace id
        retained per bucket — LAST writer wins — so a fat p99 bucket links
        to a replayable trace. Exemplars surface in the JSON snapshot
        only, never in the Prometheus text, and a histogram that never
        receives one snapshots byte-identically to the pre-exemplar
        format."""
        v = float(v)
        ix = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[ix] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars[ix] = str(exemplar)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile in [0, 1]; 0.0 when empty. Values in
        the +Inf bucket resolve to the observed max (the honest upper bound
        a fixed ladder can give)."""
        if self._count == 0:
            return 0.0
        rank = q * self._count
        cum = 0
        for ix, c in enumerate(self._counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if ix >= len(self.buckets):  # +Inf bucket
                    return float(self._max)
                lo = self.buckets[ix - 1] if ix else 0.0
                hi = self.buckets[ix]
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return float(self._max)

    def p50(self) -> float:
        return self.quantile(0.50)

    def p99(self) -> float:
        return self.quantile(0.99)

    def cumulative_buckets(self) -> list:
        """[(le, cumulative_count)] including ("+Inf", count)."""
        out = []
        cum = 0
        for edge, c in zip(self.buckets, self._counts):
            cum += c
            out.append((edge, cum))
        out.append(("+Inf", self._count))
        return out

    def exemplars(self) -> dict:
        """{bucket le label: trace id} for buckets holding an exemplar
        (the +Inf bucket labels as "+Inf"); empty when none recorded."""
        out = {}
        for ix, trace_id in self._exemplars.items():
            le = ("+Inf" if ix >= len(self.buckets)
                  else repr(float(self.buckets[ix])))
            out[le] = trace_id
        return out

    def _reset(self) -> None:
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._exemplars = {}


class MetricsRegistry:
    """Homogeneous home for every instrument; instrument identity is the
    canonical series key, so asking twice returns the same object (call
    sites may cache handles — the hot paths do)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        key = series_key(name, labels)
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(key, self._lock))
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = series_key(name, labels)
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(key, self._lock))
        return g

    def histogram(self, name: str, buckets: tuple = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        key = series_key(name, labels)
        h = self._histograms.get(key)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    key, Histogram(key, self._lock, buckets))
        return h

    def counter_value(self, name: str, **labels) -> int:
        """Read-only: 0 when the series was never created (reads must not
        materialize series, or snapshots would differ run to run)."""
        with self._lock:
            c = self._counters.get(series_key(name, labels))
        return c.value if c is not None else 0

    def gauge_value(self, name: str, **labels):
        with self._lock:
            g = self._gauges.get(series_key(name, labels))
        return g.value if g is not None else 0.0

    def counters_matching(self, name: str) -> dict[str, int]:
        """{series key: value} for every series of `name` (any label set).

        The lock is not optional here: iterating `_counters` while the
        flush worker registers a new series raises `RuntimeError: dict
        changed size during iteration`."""
        prefix = name + "{"
        with self._lock:
            items = sorted(self._counters.items())
        return {k: c.value for k, c in items
                if k == name or k.startswith(prefix)}

    def reset(self) -> None:
        """Zero every instrument IN PLACE: cached handles stay wired, so a
        test may reset between phases without re-plumbing call sites."""
        with self._lock:
            for c in self._counters.values():
                c._reset()
            for g in self._gauges.values():
                g._reset()
            for h in self._histograms.values():
                h._reset()

    def clear(self) -> None:
        """Drop every series entirely (fresh-process equivalence; snapshot
        of a cleared registry is empty). Cached handles become orphans —
        only test teardown should use this."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    def snapshot(self) -> dict:
        """Canonical plain-dict state: sorted series keys, cumulative
        histogram buckets, derived p50/p99 included for human consumers.
        Two calls against equal registry state return equal dicts, and
        json.dumps(..., sort_keys=True) of them is byte-identical."""
        with self._lock:
            counters = {k: c._value for k, c in sorted(self._counters.items())}
            gauges = {k: g._value for k, g in sorted(self._gauges.items())}
            hists = {}
            for k, h in sorted(self._histograms.items()):
                hists[k] = {
                    "buckets": [[le if le == "+Inf" else float(le), int(n)]
                                for le, n in h.cumulative_buckets()],
                    "count": h._count,
                    "sum": h._sum,
                    "min": h._min,
                    "max": h._max,
                    "p50": h.quantile(0.50),
                    "p99": h.quantile(0.99),
                }
                # exemplars are JSON-snapshot-only (the Prometheus text and
                # both exporter value sets never see them) and the key is
                # OMITTED when none were recorded, so exemplar-free
                # registries snapshot byte-identically to the v1 format
                if h._exemplars:
                    hists[k]["exemplars"] = h.exemplars()
        return {
            "version": 1,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }


# The process-wide registry: every instrumented seam records here unless a
# caller explicitly threads its own registry (tests isolating a phase).
REGISTRY = MetricsRegistry()
