"""Recompile / compile-cache-pressure tracker.

ROADMAP item 5 names the failure mode: as scenario diversity multiplies
shapes, every new (kernel, shape) pair silently costs a fresh XLA
compilation. This tracker counts DISTINCT jitted-shape compilations per
kernel so a test (or a soak) can pin "this loop compiles once" the same way
tests/test_rlc_grouped.py pins Miller-loop counts via eval_shape.

Two attachment points inside jax, both observational:

  * the lowering log record "Compiling <fun_name> with global shapes and
    types <args>." (jax._src.interpreters.pxla) carries the kernel NAME and
    the abstract shapes — a logging.Handler parses it into per-kernel
    counters (`compile_total{kernel=...}`) and a distinct-shape set;
  * `jax.monitoring`'s BACKEND_COMPILE_EVENT duration stream feeds a
    `compile_seconds` histogram (no kernel attribution, but it is the
    wall-clock the cache pressure actually costs).

jax is imported ONLY inside install(): off-device (or with jax absent) the
module stays importable and install() degrades to a no-op tracker, the same
contract the obs package promises tpulint's import-layering rule.

jax.monitoring has no single-listener unregister, so a module-level
trampoline registers ONCE and routes through the installed tracker global;
uninstall() just clears the global.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from .metrics import REGISTRY, MetricsRegistry

# The duration event dispatch.py records around every backend compile.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_COMPILE_MSG_PREFIX = "Compiling %s"


class _CompileLogHandler(logging.Handler):
    """Parses jax's per-compilation log records; attached to the pxla
    logger by install(). Never raises into jax's logging path."""

    def __init__(self, tracker: "CompileTracker"):
        super().__init__(level=logging.DEBUG)
        self._tracker = tracker

    def emit(self, record: logging.LogRecord) -> None:
        try:
            if not record.msg.startswith(_COMPILE_MSG_PREFIX) or not record.args:
                return
            kernel = str(record.args[0])
            shapes = str(record.args[1]) if len(record.args) > 1 else ""
            self._tracker._on_compile(kernel, shapes)
        except Exception:
            pass


def _monitoring_trampoline(event: str, duration: float, **kwargs) -> None:
    tracker = _TRACKER
    if tracker is None or event != BACKEND_COMPILE_EVENT:
        return
    tracker._on_backend_compile(duration)


_TRAMPOLINE_REGISTERED = False
_TRACKER: Optional["CompileTracker"] = None


class CompileTracker:
    """Counts per-kernel compilations and distinct (kernel, shape) pairs.

    install() wires the jax hooks (idempotent; returns self either way);
    uninstall() detaches the log handler and silences the trampoline.
    When jax cannot be imported, install() leaves the tracker enabled as a
    pure sink — counts stay zero, nothing raises."""

    def __init__(self, registry: MetricsRegistry = REGISTRY):
        self.registry = registry
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._shapes: dict[str, set] = {}
        self._handler: Optional[_CompileLogHandler] = None
        self._logger: Optional[logging.Logger] = None
        self._prev_level: Optional[int] = None

    # -- jax-side callbacks ----------------------------------------------------

    def _on_compile(self, kernel: str, shapes: str) -> None:
        with self._lock:
            self._counts[kernel] = self._counts.get(kernel, 0) + 1
            self._shapes.setdefault(kernel, set()).add(shapes)
            distinct = len(self._shapes[kernel])
        self.registry.counter("compile_total", kernel=kernel).inc()
        self.registry.gauge("compile_distinct_shapes", kernel=kernel).set(distinct)

    def _on_backend_compile(self, duration: float) -> None:
        self.registry.histogram("compile_seconds").observe(duration)

    # -- readout ---------------------------------------------------------------

    def compiles(self, kernel: str) -> int:
        return self._counts.get(kernel, 0)

    def distinct_shapes(self, kernel: str) -> int:
        return len(self._shapes.get(kernel, ()))

    def kernels(self) -> dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))

    # -- lifecycle -------------------------------------------------------------

    def install(self) -> "CompileTracker":
        global _TRACKER, _TRAMPOLINE_REGISTERED
        _TRACKER = self
        try:
            import jax.monitoring  # deferred: obs/ is jax-free at module level
            from jax._src.interpreters import pxla
        except Exception:
            return self  # no-op degrade: importable and callable without jax
        if not _TRAMPOLINE_REGISTERED:
            jax.monitoring.register_event_duration_secs_listener(
                _monitoring_trampoline)
            _TRAMPOLINE_REGISTERED = True
        if self._handler is None:
            logger = logging.getLogger(pxla.__name__)
            self._handler = _CompileLogHandler(self)
            self._logger = logger
            self._prev_level = logger.level
            # The compile log is DEBUG unless jax_log_compiles; the logger
            # must be opened up for the handler to see it. Propagation is
            # left on — ancestor handlers keep their own level filters.
            if logger.getEffectiveLevel() > logging.DEBUG:
                logger.setLevel(logging.DEBUG)
            logger.addHandler(self._handler)
        return self

    def uninstall(self) -> None:
        global _TRACKER
        if _TRACKER is self:
            _TRACKER = None
        if self._handler is not None and self._logger is not None:
            self._logger.removeHandler(self._handler)
            if self._prev_level is not None:
                self._logger.setLevel(self._prev_level)
            self._handler = None
            self._logger = None
            self._prev_level = None


def current_tracker() -> Optional[CompileTracker]:
    return _TRACKER


def uninstall() -> None:
    """Detach whatever tracker is installed (test-teardown safety net)."""
    t = _TRACKER
    if t is not None:
        t.uninstall()
