"""Trace-context propagation: the causal identity of one request.

A `TraceContext` is the minimal W3C-traceparent analog this stack needs:
a trace id naming one request's whole journey (minted once, at firehose /
gossip ingest) plus the span id of the context's creation point, so a span
opened WITH a context knows both which request it serves and which span
caused it. Contexts are carried as plain fields on the host-side carriers
that already cross thread boundaries — `AttestationItem`, sched
`Request`/`Handle` — never through thread-locals, because the producer
thread that mints a context is not the flusher thread that resolves it.

Fan-in/fan-out is expressed with *span links* (obs/trace.py): a collapsed
flush batch's `sched.dispatch` span links to every member's context (N
requests → one device check), and a failed collapse's `sched.reverify`
span links to the exact member set it re-verifies (one failure → N
attributions). The timeline exporter (obs/timeline.py) follows a trace id
through ctx-carrying spans AND links, which is what makes a verdict
attributable to its full ingest→admit→seal→dispatch→resolve path.

Id allocation is a process-wide counter, not a RNG: ids only need to be
unique within one process lifetime (the artifact formats carry them as
opaque strings), and a counter keeps minting cheap and replay-friendly.
Minting is gated by the caller on an installed tracer — with tracing
disabled nothing mints, so the PR-6 disabled-overhead contract holds.

jax-free at module level by charter (tpulint import-layering).
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Optional

_lock = threading.Lock()
_ids = itertools.count(1)


def _next_id(prefix: str) -> str:
    with _lock:
        return f"{prefix}{next(_ids):08x}"


def reset_ids() -> None:
    """Restart the id counter (test determinism only — production never
    resets, uniqueness within the process is the contract)."""
    global _ids
    with _lock:
        _ids = itertools.count(1)


@dataclass(frozen=True)
class TraceContext:
    """One request's causal identity: (trace id, span id of the minting /
    forking point, optional parent span id)."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None

    def child(self) -> "TraceContext":
        """A new context in the SAME trace, parented on this one — the
        shape a stage hands downstream when it starts sub-work."""
        return TraceContext(self.trace_id, _next_id("s"), self.span_id)

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    @staticmethod
    def from_dict(d: dict) -> "TraceContext":
        return TraceContext(d["trace_id"], d["span_id"],
                            d.get("parent_span_id"))


def mint_trace() -> TraceContext:
    """A fresh root context: new trace id, new span id, no parent. Callers
    gate on `trace.current_tracer() is not None` so disabled mode never
    pays the counter."""
    return TraceContext(_next_id("t"), _next_id("s"), None)
