"""Span tracer for the resident pipeline's hot-path seams.

`span("engine.dispatch", epoch=3)` is a context manager that times the
enclosed work on the monotonic clock, tracks nesting (a dispatch inside an
epoch inside a run), carries structured attributes, and feeds the metrics
registry (`<name>_seconds` histogram + `span_total{span=...}` counter) so
p50/p99 per seam fall out of the same snapshot as every counter.

Disabled-by-default, mirroring robustness.faults.FaultPlan: a module global
`_TRACER` starts as None and `span(...)` then returns one shared immutable
`_NullSpan` — the disabled cost is a module-global read, a tuple lookup and
a no-op __enter__/__exit__ pair (measured in benches/obs_overhead_bench.py,
not asserted). Production code therefore instruments unconditionally; only
installing a `Tracer` (chaos lane, benches, obs_dump) turns the lights on.

Thread model: the active-span stack is thread-local (gossip rx threads each
get their own nesting chain); the finished-span ring and the registry are
shared and locked. The ring is FIXED SIZE with a drop counter — same
bounded-memory rule as the breaker event log and the metrics histograms.

Causality (PR 13): a span may carry a `TraceContext` (obs/context.py) —
the request identity minted at ingest — and *links* to other contexts,
expressing fan-in (N collapsed requests → one dispatch span) and fan-out
(one failed collapse → N reverify attributions). Finished spans also
record their thread name/id and monotonic start time, which is what the
timeline exporter (obs/timeline.py) renders into per-thread lanes with
flow events following a request across them. All of it rides the same
disabled-mode contract: no tracer ⇒ `span(...)` still returns the shared
no-op singleton and nothing mints, links, or records.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from . import flight as _flight
from .metrics import REGISTRY, MetricsRegistry


class _NullSpan:
    """The disabled-mode span: every operation is a no-op. One shared
    instance — `span()` must not allocate when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self

    def link(self, ctx):
        return self

    @property
    def attrs(self):
        return {}


NULL_SPAN = _NullSpan()


class Span:
    """One live (or finished) span. Created only by an installed Tracer."""

    __slots__ = ("name", "attrs", "depth", "parent", "t_start", "duration",
                 "status", "ctx", "links", "thread", "thread_id", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict,
                 depth: int, parent: Optional[str],
                 ctx=None, links=None):
        self.name = name
        self.attrs = attrs
        self.depth = depth
        self.parent = parent
        self.t_start = 0.0
        self.duration = 0.0
        self.status = "ok"
        self.ctx = ctx
        self.links = list(links) if links else []
        self.thread = ""
        self.thread_id = 0
        self._tracer = tracer

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def link(self, ctx) -> "Span":
        """Add a span link to another request's context — fan-in/fan-out
        causality the parent/child nesting cannot express."""
        if ctx is not None:
            self.links.append(ctx)
        return self

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        th = threading.current_thread()
        self.thread = th.name
        self.thread_id = th.ident or 0
        self.t_start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.monotonic() - self.t_start
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("exc", exc_type.__name__)
        self._tracer._pop(self)
        return False

    def to_dict(self) -> dict:
        ctx = self.ctx
        return {
            "name": self.name,
            "depth": self.depth,
            "parent": self.parent,
            "t_start": self.t_start,
            "duration": self.duration,
            "status": self.status,
            "thread": self.thread,
            "thread_id": self.thread_id,
            "trace_id": ctx.trace_id if ctx is not None else None,
            "span_id": ctx.span_id if ctx is not None else None,
            "parent_span_id": (ctx.parent_span_id
                               if ctx is not None else None),
            "links": [{"trace_id": c.trace_id, "span_id": c.span_id}
                      for c in self.links],
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Collects finished spans into a bounded ring and mirrors timings into
    the metrics registry.

    max_spans bounds the ring; older spans are dropped oldest-first and
    counted in `spans_dropped_total` (visible in the snapshot, so a soak
    that overflows the ring says so instead of silently forgetting)."""

    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 max_spans: int = 4096):
        self.registry = registry
        self.max_spans = int(max_spans)
        self.finished: list[dict] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- stack ----------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def _push(self, sp: Span) -> None:
        self._stack().append(sp)

    def _pop(self, sp: Span) -> None:
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        self._record(sp)

    # -- recording ------------------------------------------------------------

    def _record(self, sp: Span) -> None:
        with self._lock:
            self.finished.append(sp.to_dict())
            if len(self.finished) > self.max_spans:
                drop = len(self.finished) - self.max_spans
                del self.finished[:drop]
                self.dropped += drop
                self.registry.counter("spans_dropped_total").inc(drop)
        self.registry.counter("span_total", span=sp.name).inc()
        if sp.status == "error":
            self.registry.counter("span_errors_total", span=sp.name).inc()
        self.registry.histogram("span_seconds", span=sp.name).observe(
            sp.duration,
            exemplar=(sp.ctx.trace_id if sp.ctx is not None else None))
        # black box: span completions are flight-recorder events, so a dump
        # shows what the pipeline was DOING just before the trigger
        _flight.record("span", name=sp.name, status=sp.status,
                       duration=round(sp.duration, 6),
                       trace_id=(sp.ctx.trace_id
                                 if sp.ctx is not None else None))

    def span(self, name: str, ctx=None, links=None, **attrs) -> Span:
        cur = self.current()
        return Span(self, name, attrs,
                    depth=(cur.depth + 1 if cur is not None else 0),
                    parent=(cur.name if cur is not None else None),
                    ctx=ctx, links=links)

    def spans(self, name: Optional[str] = None) -> list[dict]:
        """Finished spans (optionally filtered by name), oldest first."""
        with self._lock:
            out = list(self.finished)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        return out

    def install(self) -> "Tracer":
        global _TRACER
        _TRACER = self
        return self

    def uninstall(self) -> None:
        global _TRACER
        if _TRACER is self:
            _TRACER = None


_TRACER: Optional[Tracer] = None


def current_tracer() -> Optional[Tracer]:
    return _TRACER


def uninstall() -> None:
    """Remove whatever tracer is installed (test-teardown safety net)."""
    global _TRACER
    _TRACER = None


def span(name: str, ctx=None, links=None, **attrs):
    """THE hot-path entry point. Disabled: one global read + shared no-op
    object (ctx/links ignored — callers gate minting on `current_tracer()`
    so nothing is even built). Enabled: a real nested span carrying the
    request context and any fan-in/fan-out links."""
    tracer = _TRACER
    if tracer is None:
        return NULL_SPAN
    return tracer.span(name, ctx=ctx, links=links, **attrs)


def annotate(**attrs) -> None:
    """Attach attributes to the innermost active span of the calling thread
    (no-op when tracing is disabled or no span is open). This is how deep
    seams — fault injection, retry classification — mark the enclosing
    dispatch span without threading a span object through every call."""
    tracer = _TRACER
    if tracer is None:
        return
    cur = tracer.current()
    if cur is None:
        return
    for k, v in attrs.items():
        if k in ("fault_sites", "retried_errors"):
            cur.attrs.setdefault(k, [])
            cur.attrs[k].append(v)
        else:
            cur.attrs[k] = v
