"""Exporters: canonical JSON snapshot and Prometheus text exposition.

Canonical means byte-identical across two dumps of equal registry state:
sorted keys, fixed separators, no timestamps — if a consumer wants a
timestamp it goes in the caller-supplied `meta` block, never injected here.
The CI artifact diff and tools/bench_probe.py rely on this.

The two formats expose ONE value set. `snapshot_value_set` derives
{series: float} from the JSON snapshot; `prometheus_value_set` parses the
same out of the text exposition — tests/test_obs.py holds them equal so the
exporters cannot drift apart.
"""
from __future__ import annotations

import json
from typing import Optional

from .metrics import REGISTRY, MetricsRegistry

SNAPSHOT_VERSION = 1


# --- JSON --------------------------------------------------------------------


def snapshot_dict(registry: MetricsRegistry = REGISTRY,
                  meta: Optional[dict] = None) -> dict:
    snap = registry.snapshot()
    if meta:
        snap["meta"] = dict(meta)
    return snap


def canonical_json(obj: dict) -> str:
    """THE canonical serialization (sorted keys, fixed separators, trailing
    newline). Anything claiming to be an obs snapshot must round-trip
    through this byte-identically."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      allow_nan=False) + "\n"


def json_snapshot(registry: MetricsRegistry = REGISTRY,
                  meta: Optional[dict] = None) -> str:
    return canonical_json(snapshot_dict(registry, meta))


def write_snapshot(path, registry: MetricsRegistry = REGISTRY,
                   meta: Optional[dict] = None) -> str:
    text = json_snapshot(registry, meta)
    with open(path, "w") as f:
        f.write(text)
    return text


def validate_snapshot_text(text: str):
    """(ok, reason) for an on-disk snapshot: parseable, right version,
    canonical (re-serializing reproduces the exact bytes)."""
    try:
        obj = json.loads(text)
    except ValueError as e:
        return False, f"not JSON: {e}"
    if not isinstance(obj, dict):
        return False, "snapshot is not an object"
    if obj.get("version") != SNAPSHOT_VERSION:
        return False, f"version {obj.get('version')!r} != {SNAPSHOT_VERSION}"
    for section in ("counters", "gauges", "histograms"):
        if not isinstance(obj.get(section), dict):
            return False, f"missing section {section!r}"
    if canonical_json(obj) != text:
        return False, "not canonical (re-serialization differs)"
    return True, "ok"


# --- Prometheus text exposition ----------------------------------------------


def _split_series(key: str):
    """`name{a="b"}` -> ("name", 'a="b"'); bare `name` -> ("name", "")."""
    if key.endswith("}") and "{" in key:
        name, _, inner = key.partition("{")
        return name, inner[:-1]
    return key, ""


def _with_label(inner: str, extra: str) -> str:
    return f"{inner},{extra}" if inner else extra


def _fmt(v) -> str:
    """Value formatting shared by exporter and value-set derivation; floats
    via repr so float(text) round-trips exactly."""
    if isinstance(v, bool):
        return repr(int(v))
    if isinstance(v, int):
        return repr(v)
    return repr(float(v))


def _fmt_le(edge) -> str:
    return "+Inf" if edge == "+Inf" else repr(float(edge))


def prometheus_text(snapshot: dict) -> str:
    """Text exposition of a snapshot dict (counters, gauges, histogram
    bucket/sum/count; derived p50/p99/min/max stay JSON-only — Prometheus
    computes quantiles server-side from the buckets)."""
    lines = []
    typed: set[str] = set()

    def head(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for key, v in snapshot.get("counters", {}).items():
        name, inner = _split_series(key)
        head(name, "counter")
        lines.append(f"{key} {_fmt(v)}")
    for key, v in snapshot.get("gauges", {}).items():
        name, inner = _split_series(key)
        head(name, "gauge")
        lines.append(f"{key} {_fmt(v)}")
    for key, h in snapshot.get("histograms", {}).items():
        name, inner = _split_series(key)
        head(name, "histogram")
        for le, n in h["buckets"]:
            labels = _with_label(inner, f'le="{_fmt_le(le)}"')
            lines.append(f"{name}_bucket{{{labels}}} {_fmt(n)}")
        suffix = f"{{{inner}}}" if inner else ""
        lines.append(f"{name}_sum{suffix} {_fmt(h['sum'])}")
        lines.append(f"{name}_count{suffix} {_fmt(h['count'])}")
    return "\n".join(lines) + "\n"


def snapshot_value_set(snapshot: dict) -> dict:
    """{series: float} — the ground truth both exporters must agree on."""
    out: dict[str, float] = {}
    for key, v in snapshot.get("counters", {}).items():
        out[key] = float(v)
    for key, v in snapshot.get("gauges", {}).items():
        out[key] = float(v)
    for key, h in snapshot.get("histograms", {}).items():
        name, inner = _split_series(key)
        for le, n in h["buckets"]:
            labels = _with_label(inner, f'le="{_fmt_le(le)}"')
            out[f"{name}_bucket{{{labels}}}"] = float(n)
        suffix = f"{{{inner}}}" if inner else ""
        out[f"{name}_sum{suffix}"] = float(h["sum"])
        out[f"{name}_count{suffix}"] = float(h["count"])
    return out


def prometheus_value_set(text: str) -> dict:
    """Parse a text exposition back into {series: float}."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        out[series] = float(value)
    return out
