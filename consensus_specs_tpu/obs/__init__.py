"""Unified observability for the resident pipeline.

Seven cooperating pieces, all jax-free at module level (device hooks are
deferred behind install calls — the tpulint import-layering rule enforces
this):

  obs.metrics    process-wide registry: counters, gauges, fixed-bucket
                 histograms with p50/p99 readout + per-bucket trace-id
                 exemplars (`REGISTRY`).
  obs.trace      span tracer (`span("engine.dispatch")`), disabled unless a
                 Tracer is installed — the FaultPlan pattern. Spans carry
                 TraceContexts and fan-in/fan-out span links.
  obs.context    TraceContext minting/propagation: one trace id per
                 ingested request, carried on AttestationItem and sched
                 Request across threads.
  obs.flight     always-on flight recorder: bounded structured-event ring
                 dumped as a canonical-JSON black box on incident
                 triggers (breaker open, FirehoseKilled, self-check,
                 scenario divergence).
  obs.recompile  per-kernel compile counter via jax's lowering log +
                 jax.monitoring durations; no-op off-device.
  obs.export     canonical JSON snapshot + Prometheus text, one value set.
  obs.timeline   Perfetto/Chrome-trace export: spans in per-thread lanes,
                 flow events following a request across them.
  obs.slo        declarative SLO gate over snapshots + BENCH_LOCAL.json
                 (tools/slo_check.py is the CLI).

See README "Observability" for the four-layer map and BASELINE.md for
what each metric/SLO watches.
"""
from .metrics import REGISTRY, MetricsRegistry, DEFAULT_BUCKETS, series_key
from .trace import (
    NULL_SPAN,
    Tracer,
    annotate,
    current_tracer,
    span,
)
from .context import TraceContext, mint_trace
from .flight import FlightRecorder, current_recorder
from .recompile import BACKEND_COMPILE_EVENT, CompileTracker, current_tracker
from .export import (
    canonical_json,
    json_snapshot,
    prometheus_text,
    prometheus_value_set,
    snapshot_dict,
    snapshot_value_set,
    validate_snapshot_text,
    write_snapshot,
)

__all__ = [
    "REGISTRY",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "series_key",
    "NULL_SPAN",
    "Tracer",
    "annotate",
    "current_tracer",
    "span",
    "TraceContext",
    "mint_trace",
    "FlightRecorder",
    "current_recorder",
    "BACKEND_COMPILE_EVENT",
    "CompileTracker",
    "current_tracker",
    "canonical_json",
    "json_snapshot",
    "prometheus_text",
    "prometheus_value_set",
    "snapshot_dict",
    "snapshot_value_set",
    "validate_snapshot_text",
    "write_snapshot",
]
