"""Unified observability for the resident pipeline.

Three cooperating pieces, all jax-free at module level (device hooks are
deferred behind install calls — the tpulint import-layering rule enforces
this):

  obs.metrics    process-wide registry: counters, gauges, fixed-bucket
                 histograms with p50/p99 readout (`REGISTRY`).
  obs.trace      span tracer (`span("engine.dispatch")`), disabled unless a
                 Tracer is installed — the FaultPlan pattern.
  obs.recompile  per-kernel compile counter via jax's lowering log +
                 jax.monitoring durations; no-op off-device.
  obs.export     canonical JSON snapshot + Prometheus text, one value set.

See README "Observability" for the span map and BASELINE.md for what each
metric watches.
"""
from .metrics import REGISTRY, MetricsRegistry, DEFAULT_BUCKETS, series_key
from .trace import (
    NULL_SPAN,
    Tracer,
    annotate,
    current_tracer,
    span,
)
from .recompile import BACKEND_COMPILE_EVENT, CompileTracker, current_tracker
from .export import (
    canonical_json,
    json_snapshot,
    prometheus_text,
    prometheus_value_set,
    snapshot_dict,
    snapshot_value_set,
    validate_snapshot_text,
    write_snapshot,
)

__all__ = [
    "REGISTRY",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "series_key",
    "NULL_SPAN",
    "Tracer",
    "annotate",
    "current_tracer",
    "span",
    "BACKEND_COMPILE_EVENT",
    "CompileTracker",
    "current_tracker",
    "canonical_json",
    "json_snapshot",
    "prometheus_text",
    "prometheus_value_set",
    "snapshot_dict",
    "snapshot_value_set",
    "validate_snapshot_text",
    "write_snapshot",
]
