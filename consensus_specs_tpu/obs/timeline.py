"""Perfetto/Chrome-trace timeline export of the finished-span ring.

Finished spans (obs/trace.py — each carries monotonic start, duration,
thread name/id, optional TraceContext and span links) render into the
Chrome trace event format (the JSON Perfetto and chrome://tracing both
load): one "X" complete event per span, laned by THREAD, so the
producer/flusher overlap of the firehose's double-buffered flush is
visible as two parallel tracks instead of an interleaved log.

Requests are followed ACROSS lanes with flow events ("s"/"t"/"f" with a
shared id): every span that carries a trace id — in its own context or in
a span link — joins that request's flow, so clicking one sampled
attestation's arrow chain walks ingest (producer lane) → aggregate →
flush → sched.dispatch (flusher lane) → resolve. That chain is the
acceptance artifact: one timeline export reconstructs a verdict's full
path across threads.

Two on-disk forms:
  * span dump — `{"version": 1, "kind": "spans", "spans": [...]}` in the
    canonical-JSON serialization (obs/export.py), the raw material tests
    and benches persist;
  * chrome trace — `{"traceEvents": [...]}`, what
    `tools/obs_dump.py trace` emits from a span dump.

jax-free at module level by charter (tpulint import-layering).
"""
from __future__ import annotations

import json
from typing import Optional

from . import export as _export

SPAN_DUMP_VERSION = 1

_US = 1e6  # chrome trace timestamps are microseconds


def span_dump_dict(spans: list, meta: Optional[dict] = None) -> dict:
    """The persistable span-dump artifact for a list of finished-span
    dicts (Tracer.spans())."""
    return {"version": SPAN_DUMP_VERSION, "kind": "spans",
            "spans": [dict(s) for s in spans], "meta": dict(meta or {})}


def write_span_dump(path, spans: list, meta: Optional[dict] = None) -> str:
    text = _export.canonical_json(span_dump_dict(spans, meta))
    with open(path, "w") as f:
        f.write(text)
    return text


def load_span_dump(text: str) -> list:
    """Parse + validate a span dump; returns the span dicts. Raises
    ValueError on anything that is not a canonical span dump."""
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as exc:
        raise ValueError(f"not JSON: {exc}") from exc
    if not isinstance(obj, dict) or obj.get("kind") != "spans":
        raise ValueError('not a span dump (kind != "spans")')
    if obj.get("version") != SPAN_DUMP_VERSION:
        raise ValueError(
            f"span dump version {obj.get('version')!r} != {SPAN_DUMP_VERSION}")
    spans = obj.get("spans")
    if not isinstance(spans, list):
        raise ValueError("missing spans list")
    return spans


def _span_trace_ids(span: dict) -> list:
    """Every trace id a span participates in: its own context plus every
    span link (fan-in/fan-out membership)."""
    ids = []
    if span.get("trace_id"):
        ids.append(span["trace_id"])
    for link in span.get("links") or []:
        tid = link.get("trace_id")
        if tid and tid not in ids:
            ids.append(tid)
    return ids


def chrome_trace(spans: list, *, flows: bool = True) -> dict:
    """Render finished-span dicts into a Chrome trace event object.

    Lanes: one tid per (thread name, thread id) pair, assigned in sorted
    order so equal inputs render identically; thread_name metadata events
    label them. Flows: one flow chain per trace id across every span that
    carries it (context or link), emitted only when the trace touches >= 2
    spans — a single-span request has no cross-lane arrow to draw."""
    spans = [s for s in spans if s.get("t_start") is not None]
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(s["t_start"] for s in spans)
    threads = sorted({(s.get("thread") or "main", s.get("thread_id") or 0)
                      for s in spans})
    tid_of = {th: i + 1 for i, th in enumerate(threads)}
    events: list[dict] = []
    for (name, ident), tid in sorted(tid_of.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": name or f"tid-{ident}"}})

    def _tid(s: dict) -> int:
        return tid_of[(s.get("thread") or "main", s.get("thread_id") or 0)]

    by_trace: dict[str, list] = {}
    for s in spans:
        ts = round((s["t_start"] - t0) * _US, 3)
        dur = round(max(s.get("duration") or 0.0, 0.0) * _US, 3)
        args = dict(s.get("attrs") or {})
        for k in ("trace_id", "span_id", "parent_span_id", "status"):
            if s.get(k) is not None:
                args[k] = s[k]
        if s.get("links"):
            args["links"] = [link.get("trace_id") for link in s["links"]]
        events.append({"name": s["name"], "ph": "X", "ts": ts, "dur": dur,
                       "pid": 1, "tid": _tid(s), "cat": "span",
                       "args": args})
        for trace_id in _span_trace_ids(s):
            by_trace.setdefault(trace_id, []).append((ts, dur, _tid(s)))
    if flows:
        for trace_id, hits in sorted(by_trace.items()):
            if len(hits) < 2:
                continue
            hits.sort()
            for i, (ts, dur, tid) in enumerate(hits):
                ph = "s" if i == 0 else ("f" if i == len(hits) - 1 else "t")
                ev = {"name": "request", "ph": ph, "id": trace_id,
                      "cat": "request", "ts": ts, "pid": 1, "tid": tid}
                if ph == "f":
                    ev["bp"] = "e"  # bind the finish to the enclosing slice
                events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, spans: list) -> str:
    text = _export.canonical_json(chrome_trace(spans))
    with open(path, "w") as f:
        f.write(text)
    return text
