"""Flight recorder: an always-on black box for the resident pipeline.

A bounded ring of recent structured events — span completions, fault
fires, breaker transitions, queue-depth/occupancy samples, self-check
failures — that costs one locked list append per event and nothing else.
Unlike the tracer (opt-in, per-run) the recorder is ALWAYS armed: the
events that feed it come from seams that are rare (breaker transitions,
faults) or already behind an installed tracer (span completions), so the
disabled-observability hot path never touches it.

On a trigger — breaker open, `FirehoseKilled`, `SchedSelfCheckError`,
scenario-lane divergence — `dump(trigger)` freezes the ring into a
canonical-JSON artifact (obs/export.py serialization rules): the last N
events before the incident, post-mortem without re-running. Dumps are
kept in-process (`dumps`, bounded) for tests, counted in
`flight_dumps_total{trigger=...}`, and — when the `OBS_FLIGHT_DIR`
environment variable names a directory (the CI lanes point it at
test-results/) — written to `flight_<trigger>_<seq>.json` so the
artifact-upload step that already ships obs snapshots ships the black
box too.

Same bounded-memory rule as the breaker event log and the span ring:
overflow drops oldest-first and is counted, never silent.

jax-free at module level by charter (tpulint import-layering).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from . import export as _export
from .metrics import REGISTRY, MetricsRegistry

# Default ring capacity: at one event per span/fault/flush, a few thousand
# events is minutes of steady-state history — plenty of pre-incident
# context without unbounded growth.
DEFAULT_CAPACITY = 2048

# In-process dump retention: incidents are rare; keep the last few so a
# multi-fault chaos schedule can still inspect each one.
KEEP_DUMPS = 8

DUMP_VERSION = 1


def _jsonable(v):
    """Clamp event field values to the canonical-JSON type set; anything
    exotic degrades to repr() instead of poisoning a later dump."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


class FlightRecorder:
    """Bounded structured-event ring + triggered canonical-JSON dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: MetricsRegistry = REGISTRY,
                 keep_dumps: int = KEEP_DUMPS):
        self.capacity = int(capacity)
        self.registry = registry
        self.keep_dumps = int(keep_dumps)
        self.dropped = 0
        self.dumps: list[dict] = []
        self._ring: list[dict] = []
        self._seq = 0
        self._dump_seq = 0
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        ev = {"kind": kind, "t": round(time.monotonic(), 6),
              "thread": threading.current_thread().name}
        for k, v in fields.items():
            ev[k] = _jsonable(v)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            overflow = len(self._ring) - self.capacity
            if overflow > 0:
                del self._ring[:overflow]
                self.dropped += overflow

    def events(self, kind: Optional[str] = None) -> list[dict]:
        """Ring contents (optionally filtered by kind), oldest first."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out

    # -- triggered dump ----------------------------------------------------

    def dump(self, trigger: str, meta: Optional[dict] = None) -> dict:
        """Freeze the ring into a black-box artifact. Returns the artifact
        dict; also retains it in `dumps`, ticks the trigger counter, and
        writes `OBS_FLIGHT_DIR/flight_<trigger>_<seq>.json` when that env
        var names a directory."""
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
            artifact = {
                "version": DUMP_VERSION,
                "trigger": trigger,
                "dump_seq": seq,
                "events": [dict(e) for e in self._ring],
                "events_dropped": self.dropped,
                "meta": _jsonable(meta or {}),
            }
            self.dumps.append(artifact)
            if len(self.dumps) > self.keep_dumps:
                del self.dumps[:len(self.dumps) - self.keep_dumps]
        self.registry.counter("flight_dumps_total", trigger=trigger).inc()
        out_dir = os.environ.get("OBS_FLIGHT_DIR")
        if out_dir:
            try:
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(
                    out_dir, f"flight_{trigger}_{seq:04d}.json")
                with open(path, "w") as f:
                    f.write(_export.canonical_json(artifact))
            except OSError:
                # the black box must never turn an incident into a second
                # incident; the in-process copy and the counter survive
                pass
        return artifact

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dumps.clear()
            self.dropped = 0

    # -- install (tests swap in an isolated instance) ----------------------

    def install(self) -> "FlightRecorder":
        global _RECORDER
        _RECORDER = self
        return self

    def uninstall(self) -> None:
        global _RECORDER
        if _RECORDER is self:
            _RECORDER = _DEFAULT


# The always-on process recorder. Tests that need isolation install their
# own instance and uninstall back to this default.
_DEFAULT = FlightRecorder()
_RECORDER = _DEFAULT


def current_recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **fields) -> None:
    _RECORDER.record(kind, **fields)


def dump(trigger: str, meta: Optional[dict] = None) -> dict:
    return _RECORDER.dump(trigger, meta)
