"""Declarative SLO engine: the bench trajectory as a machine-checked gate.

BASELINE.md records what the stack measured; this module makes the floor
beneath those numbers executable. An SLO spec (slo.json at the repo root)
is a list of declarative objects evaluated against two evidence sources:

  source "obs"    a canonical obs snapshot (BENCH_OBS.json, the
                  test-results/obs_<lane>.json artifacts): counter value,
                  gauge value, a histogram stat (p50/p99/count/max), or
                  the compile-per-shape reconciliation — for every
                  `compile_total{kernel=K}` counter the matching
                  `compile_distinct_shapes{kernel=K}` gauge must equal it
                  (one XLA compile per (class, bucket), the PR-8 pin).
  source "bench"  BENCH_LOCAL.json history: a dotted path into the MOST
                  RECENT record that resolves it (records are
                  heterogeneous — full bench runs carry sched extras,
                  probe runs only firehose extras).
  source "overhead"  measured in-process: ns per disabled-mode span()
                  call with ctx/links propagation compiled in — the PR-6
                  contract as a gate instead of prose.

Each spec may scope itself to snapshot lanes (`"lanes": ["bench"]`): the
zero-drops SLO must hold on a clean bench run but NOT on chaos-lane
snapshots, where backpressure drops are injected deliberately. Missing
evidence is per-spec policy (`"missing": "pass" | "fail"`): lane
artifacts legitimately lack other lanes' series, while a bench metric
that vanishes from history should fail loudly.

tools/slo_check.py is the CLI (rc != 0 names the violated SLO);
bench.py evaluates the same spec after every run and embeds the verdict
in the persisted record.

jax-free at module level by charter (tpulint import-layering).
"""
from __future__ import annotations

import json
import timeit
from dataclasses import dataclass, field
from typing import Optional

SPEC_VERSION = 1

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
}


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective. `kind` only applies to source "obs"."""

    name: str
    source: str                 # "obs" | "bench" | "overhead"
    op: str                     # key into _OPS
    value: float
    kind: str = "counter"       # counter | gauge | histogram | compile_per_shape
    series: Optional[str] = None
    stat: str = "p99"           # histogram stat: p50 | p99 | count | max | sum
    path: Optional[str] = None  # bench dotted path, e.g. "extra.sched_occupancy_min"
    lanes: tuple = ()           # () = any snapshot; else meta.lane must match
    missing: str = "fail"       # verdict when no evidence resolves
    note: str = ""


@dataclass
class SloResult:
    name: str
    ok: bool
    measured: Optional[float]
    detail: str
    spec: SloSpec = field(repr=False, default=None)


def load_spec(obj: dict) -> list[SloSpec]:
    if not isinstance(obj, dict) or obj.get("version") != SPEC_VERSION:
        raise ValueError(
            f"SLO spec version {obj.get('version')!r} != {SPEC_VERSION}")
    specs = []
    for raw in obj.get("slos", []):
        d = dict(raw)
        d["lanes"] = tuple(d.get("lanes", ()))
        spec = SloSpec(**d)
        if spec.op not in _OPS:
            raise ValueError(f"SLO {spec.name!r}: unknown op {spec.op!r}")
        if spec.source not in ("obs", "bench", "overhead"):
            raise ValueError(
                f"SLO {spec.name!r}: unknown source {spec.source!r}")
        if spec.missing not in ("pass", "fail"):
            raise ValueError(
                f"SLO {spec.name!r}: missing policy {spec.missing!r}")
        specs.append(spec)
    return specs


def load_spec_file(path) -> list[SloSpec]:
    with open(path) as f:
        return load_spec(json.load(f))


# -- evidence extraction ------------------------------------------------------


def _lane_of(snap: dict) -> str:
    meta = snap.get("meta")
    return meta.get("lane", "") if isinstance(meta, dict) else ""


def _snaps_for(spec: SloSpec, snapshots: list) -> list:
    if not spec.lanes:
        return snapshots
    return [s for s in snapshots if _lane_of(s) in spec.lanes]


def _hist_stat(h: dict, stat: str) -> float:
    if stat in ("p50", "p99", "count", "sum", "max", "min"):
        v = h.get(stat)
        return float(v) if v is not None else 0.0
    raise ValueError(f"unknown histogram stat {stat!r}")


def _obs_measurements(spec: SloSpec, snapshots: list) -> list:
    """[(value, where)] across every in-scope snapshot holding evidence."""
    out = []
    for i, snap in enumerate(_snaps_for(spec, snapshots)):
        where = _lane_of(snap) or f"snapshot[{i}]"
        if spec.kind == "counter":
            if spec.series in snap.get("counters", {}):
                out.append((float(snap["counters"][spec.series]), where))
        elif spec.kind == "gauge":
            if spec.series in snap.get("gauges", {}):
                out.append((float(snap["gauges"][spec.series]), where))
        elif spec.kind == "histogram":
            h = snap.get("histograms", {}).get(spec.series)
            if h is not None:
                out.append((_hist_stat(h, spec.stat), where))
        elif spec.kind == "compile_per_shape":
            # measured value: total EXCESS compiles beyond one per distinct
            # shape, summed over every compile_total{kernel=...} series
            counters = snap.get("counters", {})
            gauges = snap.get("gauges", {})
            kernels = [k for k in counters if k.startswith("compile_total{")]
            if kernels:
                excess = 0.0
                for k in kernels:
                    shapes_key = k.replace(
                        "compile_total{", "compile_distinct_shapes{", 1)
                    excess += float(counters[k]) - float(
                        gauges.get(shapes_key, 0.0))
                out.append((excess, where))
        else:
            raise ValueError(f"unknown obs kind {spec.kind!r}")
    return out


def _bench_measurement(spec: SloSpec, records: list):
    """Latest record (scanning backwards) where the dotted path resolves
    to a number; None when nothing in history carries it."""
    parts = (spec.path or "").split(".")
    for rec in reversed(records):
        node = rec
        for p in parts:
            if isinstance(node, dict) and p in node:
                node = node[p]
            else:
                node = None
                break
        if isinstance(node, (int, float)) and not isinstance(node, bool):
            return float(node), rec.get("timestamp", "?")
    return None


def measure_disabled_span_ns(number: int = 20_000) -> float:
    """ns per disabled-mode span() with ctx/links propagation compiled in
    — the A side of the obs_overhead_bench A/B, sized to run in
    milliseconds so the SLO gate can afford it inline."""
    from . import trace as _trace

    if _trace.current_tracer() is not None:
        raise RuntimeError("a tracer is installed; disabled-mode overhead "
                           "cannot be measured")
    t = timeit.timeit(
        "span('slo.probe', ctx=None, links=None)",
        globals={"span": _trace.span}, number=number)
    return t / number * 1e9


# -- evaluation ---------------------------------------------------------------


def evaluate(specs: list, snapshots: list, bench_records: list,
             *, overhead_ns: Optional[float] = None) -> list:
    """One SloResult per spec. `overhead_ns` may be pre-measured (bench.py
    measures before installing its tracer); otherwise overhead specs
    measure inline, or skip-pass when a tracer is installed."""
    from . import trace as _trace

    results = []
    for spec in specs:
        cmp_op = _OPS[spec.op]
        if spec.source == "bench":
            got = _bench_measurement(spec, bench_records)
            if got is None:
                ok = spec.missing == "pass"
                results.append(SloResult(
                    spec.name, ok, None,
                    f"no bench record resolves {spec.path!r} "
                    f"(missing={spec.missing})", spec))
                continue
            measured, where = got
            ok = bool(cmp_op(measured, spec.value))
            results.append(SloResult(
                spec.name, ok, measured,
                f"{spec.path}={measured:g} {spec.op} {spec.value:g} "
                f"(record {where})", spec))
        elif spec.source == "obs":
            hits = _obs_measurements(spec, snapshots)
            if not hits:
                ok = spec.missing == "pass"
                results.append(SloResult(
                    spec.name, ok, None,
                    f"no snapshot in lanes {list(spec.lanes) or 'any'} "
                    f"carries {spec.series or spec.kind!r} "
                    f"(missing={spec.missing})", spec))
                continue
            # every in-scope snapshot must satisfy the objective; report
            # the worst offender as the measured value
            failing = [(v, w) for v, w in hits if not cmp_op(v, spec.value)]
            if failing:
                measured, where = failing[0]
                results.append(SloResult(
                    spec.name, False, measured,
                    f"{spec.series or spec.kind}={measured:g} violates "
                    f"{spec.op} {spec.value:g} (lane {where})", spec))
            else:
                measured, where = hits[0]
                results.append(SloResult(
                    spec.name, True, measured,
                    f"{spec.series or spec.kind}={measured:g} {spec.op} "
                    f"{spec.value:g} ({len(hits)} snapshot(s))", spec))
        else:  # overhead
            if overhead_ns is not None:
                measured = float(overhead_ns)
            elif _trace.current_tracer() is not None:
                results.append(SloResult(
                    spec.name, True, None,
                    "tracer installed; disabled-mode overhead not "
                    "measurable in-process (skipped)", spec))
                continue
            else:
                measured = measure_disabled_span_ns()
            ok = bool(cmp_op(measured, spec.value))
            results.append(SloResult(
                spec.name, ok, measured,
                f"disabled span() = {measured:.0f} ns {spec.op} "
                f"{spec.value:g} ns", spec))
    return results


def summarize(results: list) -> dict:
    """Compact verdict for embedding in a bench record."""
    violations = [r.name for r in results if not r.ok]
    return {"pass": sum(r.ok for r in results),
            "fail": len(violations),
            "violations": violations}
