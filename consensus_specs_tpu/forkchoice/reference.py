"""Host oracle for the fork-choice lane: LMD-GHOST over a StoreSnapshot.

`host_head` is the pure-Python twin the sched "forkchoice" class runs as
`execute_degraded` when the breaker opens, and the per-query baseline the
bench races the batched kernel against. It follows the spec shape —
`filter_block_tree`'s leaf rule, the greedy `(weight, root)` child walk,
and the proposer-boost ancestor test routed through testlib's
`ancestor_at_slot` (the extracted spec walk, not a copy) — with one
documented departure: per-candidate LMD weights come from a single exact
int64 direct-vote accumulation plus one reverse subtree sweep instead of
O(B·V) ancestor walks. That is the same sum: slots strictly increase
parent -> child, so `get_ancestor(store, vote_root, candidate.slot) ==
candidate` holds exactly when the candidate is an ancestor-or-self of the
vote root, i.e. when the vote's block sits in the candidate's subtree.

jax-free by charter; must stay importable (and fast enough to answer)
with the device wedged — that is its whole job.
"""
from __future__ import annotations

import numpy as np

from ..testlib.fork_choice import ancestor_at_slot
from .mirror import StoreSnapshot


class _BlockView:
    """Minimal block-like (slot, parent_root-as-index) for the spec walk."""

    __slots__ = ("slot", "parent_root")

    def __init__(self, slot: int, parent_root: int):
        self.slot = slot
        self.parent_root = parent_root


def subtree_weights(snap: StoreSnapshot) -> np.ndarray:
    """(B,) exact int64 LMD weight per candidate: direct latest-message
    balances accumulated up the tree (parent-before-child order makes one
    reverse sweep sufficient), plus the spec proposer-boost score on every
    ancestor-or-self of the boost root."""
    b = snap.n_blocks
    direct = np.zeros(b, dtype=np.int64)
    live = snap.votes >= 0
    np.add.at(direct, snap.votes[live], snap.balances[live])
    weight = direct
    parent = snap.parent
    for i in range(b - 1, -1, -1):
        p = int(parent[i])
        if p != i:
            weight[p] += weight[i]
    if snap.boost_idx >= 0:
        views = {i: _BlockView(int(snap.slots[i]), int(parent[i]))
                 for i in range(b)}
        for c in range(b):
            if ancestor_at_slot(views, snap.boost_idx,
                                snap.slots[c]) == c:
                weight[c] += snap.boost_weight
    return weight


def filtered_mask(snap: StoreSnapshot) -> np.ndarray:
    """(B,) bool: `get_filtered_block_tree` membership — descendants-or-self
    of the justified root owning at least one leaf whose state checkpoints
    agree with the store's (GENESIS_EPOCH short-circuits per spec)."""
    b = snap.n_blocks
    parent = snap.parent
    just_epoch, just_rid = snap.store_justified
    fin_epoch, fin_rid = snap.store_finalized
    genesis = snap.genesis_epoch
    has_child = np.zeros(b, dtype=bool)
    for i in range(b):
        if int(parent[i]) != i:
            has_child[int(parent[i])] = True
    viable = np.zeros(b, dtype=bool)
    for i in range(b):
        if has_child[i]:
            continue
        ok_just = (just_epoch == genesis
                   or (int(snap.ck_epochs[i, 0]) == just_epoch
                       and int(snap.ck_rids[i, 0]) == just_rid))
        ok_fin = (fin_epoch == genesis
                  or (int(snap.ck_epochs[i, 1]) == fin_epoch
                      and int(snap.ck_rids[i, 1]) == fin_rid))
        viable[i] = ok_just and ok_fin
    for i in range(b - 1, -1, -1):
        if viable[i] and int(parent[i]) != i:
            viable[int(parent[i])] = True
    under = np.zeros(b, dtype=bool)
    for i in range(b):
        under[i] = (i == snap.justified_idx
                    or (int(parent[i]) != i and under[int(parent[i])]))
    return viable & under


def host_head(snap: StoreSnapshot) -> int:
    """Head block index for one snapshot — the spec's greedy `get_head`
    walk over the filtered tree, ties broken by highest root bytes."""
    weight = subtree_weights(snap)
    keep = filtered_mask(snap)
    b = snap.n_blocks
    children: list = [[] for _ in range(b)]
    parent = snap.parent
    for i in range(b):
        if int(parent[i]) != i and keep[i]:
            children[int(parent[i])].append(i)
    head = int(snap.justified_idx)
    while children[head]:
        head = max(children[head],
                   key=lambda c: (int(weight[c]), snap.root_bytes(c)))
    return head
