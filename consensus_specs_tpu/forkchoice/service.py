"""ForkChoiceService: the resident head tracker over the sched lane.

The write lane's missing consumer: a service that mirrors a Store (or a
directly-driven vote feed), submits "forkchoice"/"head" work, and keeps
the current head fresh as verified attestations land. It subscribes to
the firehose's verified-batch output — the same consumer seam
ProofService uses for dirty columns — recomputing the head once per
sealed flush and observing `forkchoice_head_lag_seconds` per verified
attestation: the wall-clock from "verified" to "a head reflecting it",
the series the head-lag SLO gates.

Every head query crosses sched.dispatch, so it inherits the breaker /
retry / span envelope for free: transient device faults retry, hard-down
degrades to the spec-shaped host oracle (`reference.host_head`) with
bit-identical answers.

jax-free by charter — the device never appears above the work class.
"""
from __future__ import annotations

import threading
import time
from collections import namedtuple

from ..obs import metrics as obs_metrics
from ..sched.api import Request
from ..testlib.fork_choice import latest_message_updates
from .mirror import StoreMirror

LatestMessage = namedtuple("LatestMessage", ("epoch", "root"))


class ForkChoiceService:
    """Track the LMD-GHOST head of a mirrored store via the sched lane."""

    def __init__(self, scheduler=None,
                 registry: obs_metrics.MetricsRegistry | None = None):
        self.registry = (registry if registry is not None
                         else obs_metrics.REGISTRY)
        self.mirror = StoreMirror()
        self._scheduler = scheduler
        self._spec = None
        self._store = None
        self._latest: dict = {}   # direct-drive latest messages
        self._lock = threading.Lock()
        # (root, t_monotonic) of the most recent computed head; published
        # with a single GIL-atomic store from head() — note_verified calls
        # head() while holding _lock, so the cache cannot take it — and
        # read the same way by last_head() (the init-publication /
        # publish-store idiom the concurrency lint sanctions).
        self._head_cache: tuple | None = None
        self._head_lag = self.registry.histogram(
            "forkchoice_head_lag_seconds")
        self._heads = self.registry.counter("forkchoice_heads_total")
        self._blocks = self.registry.gauge("forkchoice_mirror_blocks")

    def _sched(self):
        if self._scheduler is None:
            from ..sched.scheduler import default_scheduler

            self._scheduler = default_scheduler()
        return self._scheduler

    # --- store mirroring ---------------------------------------------------

    def attach(self, spec, store) -> None:
        """Bind a Store; every head query re-syncs the mirror first."""
        self._spec, self._store = spec, store
        self.sync()

    def sync(self) -> None:
        if self._store is not None:
            self.mirror.sync(self._spec, self._store)
        self._blocks.set(len(self.mirror))

    # --- direct vote drive (no Store: firehose feeds, bench, tests) --------

    def apply_votes(self, attesting_indices, target_epoch,
                    beacon_block_root) -> list:
        """Admit one verified attestation's votes through the spec's
        `update_latest_messages` filter (testlib's extracted helper) and
        fold the admitted ones into the mirror's vote lane. Returns the
        validator indices actually updated."""
        root = bytes(beacon_block_root)
        updated = latest_message_updates(
            self._latest, attesting_indices, target_epoch)
        for i in updated:
            self._latest[i] = LatestMessage(int(target_epoch), root)
            self.mirror.set_vote(int(i), root)
        return updated

    # --- head queries ------------------------------------------------------

    def head_index(self) -> int:
        """Current head as an index into the mirror's block table."""
        self.sync()
        snap = self.mirror.snapshot()
        sched = self._sched()
        handle = sched.submit(Request(
            work_class="forkchoice", kind="head", payload=(snap,)))
        sched.flush("forkchoice")
        index = int(handle.result())
        self._heads.inc()
        return index

    def head(self) -> bytes:
        """Current head root (32 bytes)."""
        root = self.mirror.root_at(self.head_index())
        self._head_cache = (root, time.monotonic())
        return root

    def last_head(self) -> bytes | None:
        """STALE read: the most recently computed head, without touching
        the device lane — the shed ladder's head-query fallback
        (frontdoor). None until the first head() lands; staleness is the
        caller's bargain (age is available via last_head_age_s)."""
        cached = self._head_cache
        return cached[0] if cached is not None else None

    def last_head_age_s(self) -> float | None:
        """Seconds since the cached head was computed (None: no head yet)."""
        cached = self._head_cache
        return (time.monotonic() - cached[1]) if cached is not None else None

    # --- firehose consumer seam --------------------------------------------

    def subscribe(self, firehose) -> None:
        """Attach to a firehose's verified-batch seam: every sealed flush
        triggers one incremental head recompute."""
        firehose.subscribe_verified(self.note_verified)

    def note_verified(self, records) -> bytes | None:
        """Verified-batch callback: records are (msg_id, key, ok,
        t_verified) tuples from the firehose collector. Recomputes the
        head once for the whole batch and observes per-record head lag;
        returns the new head root (None when nothing verified)."""
        verified = [r for r in records if r[2]]
        if not verified:
            return None
        with self._lock:
            head = self.head()
            now = time.monotonic()
            for _msg_id, _key, _ok, t_verified in verified:
                self._head_lag.observe(max(0.0, now - float(t_verified)))
        return head
