"""Host-side store mirror: the gather-form arrays behind the head kernel.

A StoreMirror incrementally tracks a spec Store as flat arrays — the
block tree as parent-pointer indices (parents always precede children,
anchor self-looped), per-validator latest messages as one int32 vote
lane, per-block FFG checkpoints as interned root ids + epochs — and
emits immutable StoreSnapshots: the payload of the sched "forkchoice"
work class, consumed identically by the device kernel
(engine/fork_choice.ghost_head_batch) and the host oracle
(forkchoice/reference.host_head).

Sync is incremental along every axis the Store itself grows
incrementally: blocks are an append-only suffix scan (dict insertion
order), latest messages a diff against a per-validator cache, and the
justified-state balance/boost-weight rebuild fires only when the store's
justified checkpoint actually moves. The mirror can also be driven
directly (add_block / set_vote / set_registry) for synthetic trees —
the bench and the kernel unit tests build contested histories without a
Store.

jax-free by charter: numpy arrays only, importable from the service
layer and the degraded host-oracle path.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

ZERO_ROOT = b"\x00" * 32


@dataclass(frozen=True)
class StoreSnapshot:
    """One immutable gather-form view of a Store.

    Invariant: `parent[i] <= i` (insertion order is parent-before-child;
    the anchor — and any engine-side pad row — is self-looped), which is
    what lets the host oracle accumulate subtree weights in one reverse
    sweep and the kernel saturate ancestry in log2(B) doubling steps."""

    parent: np.ndarray      # (B,) int32 parent index, anchor self-looped
    slots: np.ndarray       # (B,) int64 block slots
    root_words: np.ndarray  # (B, 8) uint32 big-endian root words
    ck_epochs: np.ndarray   # (B, 2) int64 per-block (justified, finalized)
    ck_rids: np.ndarray     # (B, 2) int32 interned checkpoint-root ids
    votes: np.ndarray       # (V,) int32 latest-message block index, -1 none
    balances: np.ndarray    # (V,) int64 effective Gwei at justified state
    justified_idx: int      # index of store.justified_checkpoint.root
    boost_idx: int          # proposer-boost block index, -1 = boost off
    boost_weight: int       # spec committee-fraction score, exact Gwei
    store_justified: tuple  # (epoch, rid) of store.justified_checkpoint
    store_finalized: tuple  # (epoch, rid) of store.finalized_checkpoint
    genesis_epoch: int

    @property
    def n_blocks(self) -> int:
        return int(self.parent.shape[0])

    @property
    def n_validators(self) -> int:
        return int(self.votes.shape[0])

    def root_bytes(self, index: int) -> bytes:
        return self.root_words[index].astype(">u4").tobytes()


class StoreMirror:
    """Incrementally mirror a Store (or a hand-built tree) in gather form."""

    def __init__(self):
        # One reentrant lock over every public entry point: the mirror is
        # mutated by whichever thread delivers the verified-batch callback
        # (the firehose flush worker) and read by callers on the main
        # thread (`head`, bench drivers). RLock, not Lock — `sync` re-enters
        # `add_block` and `snapshot` is called under `head`'s sync.
        self._lock = threading.RLock()
        self._block_index: dict = {}   # root bytes -> block index
        self._roots: list = []         # block index -> root bytes
        self._parent: list = []
        self._slots: list = []
        self._root_words: list = []    # (8,) uint32 rows
        self._ck_epochs: list = []     # (justified, finalized) epochs
        self._ck_rids: list = []       # (justified, finalized) root ids
        self._rids: dict = {}          # checkpoint root bytes -> interned id
        self._lm_cache: dict = {}      # validator -> (epoch, root bytes)
        self._votes = np.empty(0, dtype=np.int32)
        self._balances = np.empty(0, dtype=np.int64)
        self._justified_key = None     # (epoch, root) of last balance build
        self._justified_idx = 0
        self._boost_idx = -1
        self._boost_weight = 0
        self._store_justified = (0, 0)
        self._store_finalized = (0, 0)
        self._genesis_epoch = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._roots)

    @property
    def n_validators(self) -> int:
        with self._lock:
            return int(self._votes.shape[0])

    def root_at(self, index: int) -> bytes:
        with self._lock:
            return self._roots[index]

    def index_of(self, root) -> int:
        with self._lock:
            return self._block_index[bytes(root)]

    def _rid(self, root: bytes) -> int:
        rid = self._rids.get(root)
        if rid is None:
            rid = len(self._rids)
            self._rids[root] = rid
        return rid

    def _grow_validators(self, n: int) -> None:
        cur = self._votes.shape[0]
        if n <= cur:
            return
        votes = np.full(n, -1, dtype=np.int32)
        votes[:cur] = self._votes
        balances = np.zeros(n, dtype=np.int64)
        balances[:cur] = self._balances
        self._votes, self._balances = votes, balances

    # --- direct drive (synthetic trees: bench, kernel unit tests) ---------

    def add_block(self, root, parent_root, slot, *,
                  justified=(0, ZERO_ROOT), finalized=(0, ZERO_ROOT)) -> int:
        """Append one block; the parent must already be present (or equal
        the block's own root for the anchor). `justified`/`finalized` are
        the block state's (epoch, checkpoint-root) pairs."""
        with self._lock:
            rb = bytes(root)
            pb = bytes(parent_root)
            if rb in self._block_index:
                return self._block_index[rb]
            index = len(self._roots)
            self._block_index[rb] = index
            self._roots.append(rb)
            self._parent.append(self._block_index.get(pb, index))
            self._slots.append(int(slot))
            self._root_words.append(
                np.frombuffer(rb, dtype=">u4").astype(np.uint32))
            self._ck_epochs.append((int(justified[0]), int(finalized[0])))
            self._ck_rids.append((self._rid(bytes(justified[1])),
                                  self._rid(bytes(finalized[1]))))
            return index

    def set_registry(self, balances) -> None:
        """Replace the effective-balance lane (grows the vote lane)."""
        balances = np.asarray(balances, dtype=np.int64)
        with self._lock:
            self._grow_validators(balances.shape[0])
            self._balances[:balances.shape[0]] = balances
            self._balances[balances.shape[0]:] = 0

    def set_vote(self, index: int, root) -> None:
        """Record validator `index`'s latest message as a block root (or
        None to clear). Admission filtering is the caller's job — the
        service routes through testlib's `latest_message_updates`."""
        with self._lock:
            self._grow_validators(int(index) + 1)
            self._votes[int(index)] = (
                -1 if root is None else self._block_index[bytes(root)])

    def set_checkpoints(self, justified, finalized, *,
                        genesis_epoch: int = 0) -> None:
        """Set the store-level (epoch, root) checkpoint pair; the
        justified root must be a known block."""
        with self._lock:
            self._justified_idx = self._block_index[bytes(justified[1])]
            self._store_justified = (int(justified[0]),
                                     self._rid(bytes(justified[1])))
            self._store_finalized = (int(finalized[0]),
                                     self._rid(bytes(finalized[1])))
            self._genesis_epoch = int(genesis_epoch)

    def set_boost(self, root, weight: int = 0) -> None:
        with self._lock:
            self._boost_idx = (-1 if root is None
                               else self._block_index.get(bytes(root), -1))
            self._boost_weight = int(weight)

    # --- incremental Store sync -------------------------------------------

    def sync(self, spec, store) -> None:
        """Fold the Store's growth since the last sync into the mirror."""
        with self._lock:
            blocks = store.blocks
            if len(blocks) > len(self._roots):
                for root, block in list(blocks.items())[len(self._roots):]:
                    state = store.block_states[root]
                    cj = state.current_justified_checkpoint
                    cf = state.finalized_checkpoint
                    self.add_block(
                        root, block.parent_root, block.slot,
                        justified=(int(cj.epoch), bytes(cj.root)),
                        finalized=(int(cf.epoch), bytes(cf.root)))

            jc = store.justified_checkpoint
            jkey = (int(jc.epoch), bytes(jc.root))
            if jkey != self._justified_key:
                state = store.checkpoint_states[jc]
                active = spec.get_active_validator_indices(
                    state, spec.get_current_epoch(state))
                self._grow_validators(len(state.validators))
                self._balances[:] = 0
                validators = state.validators
                for i in active:
                    self._balances[int(i)] = int(
                        validators[int(i)].effective_balance)
                num = len(active)
                if num:
                    # spec get_latest_attesting_balance proposer_score:
                    # (num_active/SLOTS_PER_EPOCH) * avg_balance * BOOST // 100
                    avg = int(spec.get_total_active_balance(state)) // num
                    committee_size = num // int(spec.SLOTS_PER_EPOCH)
                    self._boost_weight = (
                        committee_size * avg
                        * int(spec.config.PROPOSER_SCORE_BOOST)) // 100
                else:
                    self._boost_weight = 0
                self._justified_key = jkey

            for i, lm in store.latest_messages.items():
                index = int(i)
                entry = (int(lm.epoch), bytes(lm.root))
                if self._lm_cache.get(index) != entry:
                    self._lm_cache[index] = entry
                    self._grow_validators(index + 1)
                    self._votes[index] = self._block_index.get(entry[1], -1)

            fc = store.finalized_checkpoint
            self._justified_idx = self._block_index[bytes(jc.root)]
            self._store_justified = (int(jc.epoch), self._rid(bytes(jc.root)))
            self._store_finalized = (int(fc.epoch), self._rid(bytes(fc.root)))
            self._genesis_epoch = int(spec.GENESIS_EPOCH)
            pb = bytes(store.proposer_boost_root)
            self._boost_idx = (self._block_index.get(pb, -1)
                               if pb != ZERO_ROOT else -1)

    def snapshot(self) -> StoreSnapshot:
        """Freeze the current mirror state (arrays copied: snapshots cross
        the scheduler's thread boundary and must not alias live lanes)."""
        with self._lock:
            b = len(self._roots)
            if b == 0:
                raise ValueError("empty mirror: no anchor block synced")
            return StoreSnapshot(
                parent=np.asarray(self._parent, dtype=np.int32),
                slots=np.asarray(self._slots, dtype=np.int64),
                root_words=np.vstack(self._root_words).astype(np.uint32),
                ck_epochs=np.asarray(self._ck_epochs, dtype=np.int64),
                ck_rids=np.asarray(self._ck_rids, dtype=np.int32),
                votes=self._votes.copy(),
                balances=self._balances.copy(),
                justified_idx=int(self._justified_idx),
                boost_idx=int(self._boost_idx),
                boost_weight=int(self._boost_weight),
                store_justified=self._store_justified,
                store_finalized=self._store_finalized,
                genesis_epoch=int(self._genesis_epoch))
