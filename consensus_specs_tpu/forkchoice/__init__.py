"""Device-resident fork choice: LMD-GHOST head tracking as a batched lane.

The store mirrored in gather form (mirror.py), a spec-shaped host oracle
(reference.py), and a service front end (service.py) over the sched
"forkchoice" work class — the kernel itself lives in
ops/forkchoice_jax.py behind engine/fork_choice.py, keeping this package
jax-free by charter.
"""
from .mirror import StoreMirror, StoreSnapshot, ZERO_ROOT
from .reference import filtered_mask, host_head, subtree_weights
from .service import ForkChoiceService, LatestMessage

__all__ = [
    "ForkChoiceService",
    "LatestMessage",
    "StoreMirror",
    "StoreSnapshot",
    "ZERO_ROOT",
    "filtered_mask",
    "host_head",
    "subtree_weights",
]
