"""Typed request/result surface of the verification scheduler.

A `Request` names a work class (registered with the Scheduler) and a kind
within it; the payload is the class-specific argument tuple, opaque to the
scheduler. `submit` returns a `Handle` — a single-assignment future whose
`result()` lazily flushes the owning class, so callers that submit-then-read
synchronously (the BLS deferral flush, `kzg_batch.batch_verify_samples`)
never deadlock on an idle queue.

jax-free by charter: handles are resolved with host values (bool verdicts,
root bytes) after the dispatch loop has read the device result back.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Hashable, Optional

_PENDING = object()


@dataclass
class Request:
    """One unit of verification work.

    work_class: registered class name ("bls", "kzg", "merkle", ...).
    kind: class-specific operation ("verify", "verify_samples", ...).
    payload: positional arguments for the class executor, already
        host-side (bytes / ints / tuples) — never device arrays.
    group_key: admission-collapse key; requests sharing a truthy key may
        be merged into one device check when the class opts in (the
        Wonderboom same-message FastAggregateVerify collapse).
    """

    work_class: str
    kind: str
    payload: tuple
    group_key: Optional[Hashable] = None
    # deadline: absolute time (in the owning scheduler's clock space —
    # time.monotonic unless the scheduler was built with an injected clock)
    # by which the submitter wants a verdict. The scheduler never rejects
    # on it; it only feeds the seal policy's EDF ordering (scheduler.py),
    # so a deadline-free request behaves exactly as before.
    deadline: Optional[float] = None
    # trace: the submitter's TraceContext (obs/context.py), when tracing is
    # on. The scheduler never reads it for scheduling decisions — it only
    # links the dispatch/reverify spans back to every member request, and
    # stamps latency-histogram exemplars, so a verdict stays attributable
    # through admission collapse. Handles reach it via `handle.request`.
    trace: Optional[Any] = None


@dataclass
class Handle:
    """Single-assignment future for one submitted Request."""

    request: Request
    _scheduler: Any = field(repr=False, default=None)
    _value: Any = field(repr=False, default=_PENDING)
    _error: Optional[BaseException] = field(repr=False, default=None)
    _submitted_at: float = 0.0

    def done(self) -> bool:
        return self._value is not _PENDING or self._error is not None

    def result(self):
        """The verification result, flushing the owning class if needed."""
        if not self.done() and self._scheduler is not None:
            self._scheduler.flush(self.request.work_class)
        if self._error is not None:
            raise self._error
        if self._value is _PENDING:
            raise RuntimeError(
                f"handle for {self.request.work_class}/{self.request.kind} "
                "still pending after flush")
        return self._value

    def _resolve(self, value) -> None:
        self._value = value

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
