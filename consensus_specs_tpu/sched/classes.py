"""Work classes served by the verification scheduler.

A work class owns everything lane-specific the scheduler itself must not
know: how a batch of requests executes on device (`execute`), the
pure-Python degrade path the circuit breaker falls back to
(`execute_degraded`), how a result row converts to the caller-facing value
(`to_result`), the live/padded unit accounting behind the occupancy and
pad-waste metrics (`load`), and — for classes that opt in — the admission
collapse hooks (`collapse_key` / `merge`).

Executors return a numpy array with one row per request (bool verdicts for
BLS/KZG, 32-byte roots for Merkle). The scheduler validates shape and
dtype after the `sched.dispatch` fault seam, so corrupt-kind chaos faults
are caught and retried instead of resolving handles with garbage.

jax-free at module level by charter: jax, the device kernels, and the
heavyweight crypto modules are imported inside the execute bodies only
(the crypto/bls.py deferral pattern), so jax-free shims can import the
scheduler without dragging the device stack in.
"""
from __future__ import annotations

import os

import numpy as np

from . import bucketing
from .api import Request


class WorkClass:
    """Base class: one verification lane behind the shared dispatch seam."""

    name = "work"
    kinds: tuple = ()
    # per-class queue-depth flush trigger; None defers to the scheduler's
    # default admission policy
    max_depth: int | None = None
    min_bucket = bucketing.MIN_BUCKET

    def execute(self, requests: list) -> np.ndarray:
        """Device path: one row per request."""
        raise NotImplementedError

    def execute_degraded(self, requests: list) -> np.ndarray:
        """Pure-host fallback the breaker degrades to; must agree with
        `execute` bit-for-bit on every valid input."""
        raise NotImplementedError

    def to_result(self, row):
        return bool(row)

    def load(self, requests: list) -> tuple:
        """(live_units, padded_units) for the dispatched batch — feeds the
        sched_batch_occupancy / sched_pad_waste series."""
        n = len(requests)
        return n, bucketing.pow2_bucket(n, self.min_bucket)

    # -- admission collapse (off unless a class overrides) -----------------

    def collapse_key(self, request: Request):
        """Truthy key = this request may merge with queued requests sharing
        the key into ONE device check. None = never collapse."""
        return None

    def merge(self, merged: Request, request: Request) -> Request:
        """Fold `request` into the synthetic collapsed request `merged`;
        raising aborts the collapse (the request queues individually)."""
        raise NotImplementedError

    # Optional batched collapse hook used by Scheduler.submit_many:
    # merge_group(merged, requests) folds a whole same-key group in one
    # aggregation pass. None = the scheduler chains pairwise merge() calls.
    merge_group = None

    # Optional post-dispatch value check: verify_results(requests, results)
    # runs after the scheduler's shape/dtype validation and raises a
    # retryable IntegrityError when a structurally valid batch fails a
    # semantic self-check (the msm class's 2G2T outsourcing equation).
    # None = no check.
    verify_results = None


class BlsWorkClass(WorkClass):
    """BLS signature checks: the deferral queue's device lane.

    Kinds mirror crypto/bls.py's queue entries: "verify" and
    "fast_aggregate" become QueuedChecks for the batched RLC flush;
    "aggregate_verify" (distinct messages per signer) stays on the host
    oracle exactly as the pre-scheduler flush routed it.

    `collapse_same_message=True` enables the Wonderboom admission policy:
    same-message fast_aggregate requests merge into one check over the
    concatenated pubkeys and the aggregated signature (the product of the
    individual verification equations). The collapsed equation is NOT
    sound against adversarially chosen signatures without per-request
    randomization — a forged pair can cancel — so the collapse is opt-in,
    and a failing collapsed check is re-verified per member for sound
    attribution before any handle resolves False.
    """

    name = "bls"
    kinds = ("verify", "fast_aggregate", "aggregate_verify")

    def __init__(self, collapse_same_message: bool = False):
        self.collapse_same_message = collapse_same_message

    def execute(self, requests: list) -> np.ndarray:
        from ..crypto import bls_jax
        from ..crypto import bls_sig

        checks = []
        host: dict = {}
        for i, r in enumerate(requests):
            if r.kind == "verify":
                checks.append(bls_jax.make_verify_check(*r.payload))
            elif r.kind == "fast_aggregate":
                checks.append(bls_jax.make_fast_aggregate_check(*r.payload))
            else:  # aggregate_verify: distinct message per signer, host path
                checks.append(None)
                host[i] = bool(bls_sig.AggregateVerify(*r.payload))
        dev = bls_jax.run_checks(checks)
        return np.asarray(
            [host[i] if i in host else bool(dev[i])
             for i in range(len(requests))], dtype=bool)

    def execute_degraded(self, requests: list) -> np.ndarray:
        from ..crypto import bls_sig

        dispatch = {
            "verify": bls_sig.Verify,
            "fast_aggregate": bls_sig.FastAggregateVerify,
            "aggregate_verify": bls_sig.AggregateVerify,
        }
        return np.asarray(
            [bool(dispatch[r.kind](*r.payload)) for r in requests],
            dtype=bool)

    def load(self, requests: list) -> tuple:
        n = len(requests)
        msgs = [bytes(r.payload[1]) for r in requests
                if r.kind in ("verify", "fast_aggregate")]
        if len(set(msgs)) < len(msgs):
            # grouped RLC routing: the item bucket covers pad-group seeds
            plan = bucketing.grouped_plan(msgs, self.min_bucket)
            return n, n - plan.n + plan.b_n
        return n, bucketing.pow2_bucket(n, self.min_bucket)

    def collapse_key(self, request: Request):
        if not self.collapse_same_message:
            return None
        if request.kind != "fast_aggregate":
            return None
        return ("fast_aggregate", bytes(request.payload[1]))

    def merge(self, merged: Request, request: Request) -> Request:
        from ..crypto import bls_sig

        pks_a, msg, sig_a = merged.payload
        pks_b, _, sig_b = request.payload
        # Aggregate raises on malformed signature bytes -> the scheduler
        # aborts the collapse and queues the request individually, keeping
        # admission non-raising for garbage inputs.
        agg_sig = bls_sig.Aggregate([bytes(sig_a), bytes(sig_b)])
        return Request(
            work_class=merged.work_class, kind="fast_aggregate",
            payload=(list(pks_a) + list(pks_b), msg, agg_sig),
            group_key=merged.group_key)

    def merge_group(self, merged: Request, requests: list) -> Request:
        """Batched collapse for submit_many: aggregate a committee's worth
        of same-message signatures in ONE Aggregate pass (one point
        decompression per signature) instead of a chain of pairwise merges
        that re-decompresses the running aggregate at every step — the
        admission cost that dominates a streaming attestation workload.
        Raising (malformed bytes anywhere in the group) makes the scheduler
        fall back to pairwise merges, isolating the bad payload."""
        from ..crypto import bls_sig

        pks, msg, sig = merged.payload
        all_pks = list(pks)
        sigs = [bytes(sig)]
        for r in requests:
            pks_r, _, sig_r = r.payload
            all_pks.extend(pks_r)
            sigs.append(bytes(sig_r))
        return Request(
            work_class=merged.work_class, kind="fast_aggregate",
            payload=(all_pks, msg, bls_sig.Aggregate(sigs)),
            group_key=merged.group_key)


class KzgWorkClass(WorkClass):
    """KZG batch lanes: one request = one strict randomized batch check
    (`crypto/kzg_batch` semantics preserved exactly — the request-level
    granularity keeps the all-or-nothing soundness contract intact)."""

    name = "kzg"
    kinds = ("verify_samples", "verify_degree_proofs")

    def execute(self, requests: list) -> np.ndarray:
        from ..crypto import kzg_batch

        out = []
        for r in requests:
            if r.kind == "verify_samples":
                setup, items, use_device = r.payload
                out.append(kzg_batch._verify_samples_impl(
                    setup, items, use_device))
            else:
                setup, items, points_count, use_device = r.payload
                out.append(kzg_batch._verify_degree_proofs_impl(
                    setup, items, points_count, use_device))
        return np.asarray(out, dtype=bool)

    def execute_degraded(self, requests: list) -> np.ndarray:
        from ..crypto import kzg_batch

        out = []
        for r in requests:
            if r.kind == "verify_samples":
                setup, items, _ = r.payload
                out.append(kzg_batch._verify_samples_impl(
                    setup, items, False))
            else:
                setup, items, points_count, _ = r.payload
                out.append(kzg_batch._verify_degree_proofs_impl(
                    setup, items, points_count, False))
        return np.asarray(out, dtype=bool)

    def load(self, requests: list) -> tuple:
        # units are blob/proof items: each request's MSM pads its own item
        # count to a pow2 bucket inside _device_msm
        live = padded = 0
        for r in requests:
            n = len(r.payload[1])
            live += n
            padded += bucketing.pow2_bucket(n, self.min_bucket)
        return live, padded


class MerkleWorkClass(WorkClass):
    """Batched SSZ chunk-tree lanes. Two kinds, both over 32-byte leaves:

    - "tree_root": payload = (chunks,). Trees sharing a leaf count fold in
      one `engine/state_root.tree_root_batch` launch, padded to the pow2
      tree bucket with zero trees (results discarded); host fallback is
      the ssz merkleize oracle.
    - "multiproof": payload = (chunks, gindex) with gindex a generalized
      index over the pow2-padded chunk tree (1 = root, C..2C-1 = leaves).
      Queries sharing a leaf-count bucket fold in one
      `engine/state_root.multiproof_batch` launch; identical trees within
      the batch share ONE device slot (interior hashing paid once), the
      tree axis pads with zero trees and the query axis with root queries
      against tree 0 (both discarded). The result row is the deepest-first
      sibling branch as a tuple of 32-byte values; host fallback is the
      `ssz/proofs.build_chunk_proof` oracle, bit-identical by
      construction.

    A pure tree_root batch keeps the legacy (n, 32) uint8 result array;
    any batch containing a multiproof returns object dtype — branch tuples
    alongside (32,) uint8 root rows (the msm marker-tuple precedent, which
    the scheduler's row validation accepts)."""

    name = "merkle"
    kinds = ("tree_root", "multiproof")

    def execute(self, requests: list) -> np.ndarray:
        if all(r.kind == "tree_root" for r in requests):
            return self._tree_roots_device(requests)
        out = np.empty(len(requests), dtype=object)
        root_idxs = [i for i, r in enumerate(requests)
                     if r.kind == "tree_root"]
        if root_idxs:
            rows = self._tree_roots_device([requests[i] for i in root_idxs])
            for row, i in zip(rows, root_idxs):
                out[i] = row
        self._multiproofs_device(
            requests,
            [i for i, r in enumerate(requests) if r.kind == "multiproof"],
            out)
        return out

    def _tree_roots_device(self, requests: list) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from ..engine import state_root as SR
        from ..ops.sha256_jax import words_to_bytes

        out = [None] * len(requests)
        by_shape: dict = {}
        for i, r in enumerate(requests):
            chunks = r.payload[0]
            c_full = bucketing.pow2_bucket(max(1, len(chunks)), 1)
            by_shape.setdefault(c_full, []).append(i)
        for c_full, idxs in sorted(by_shape.items()):
            k = len(idxs)
            b_k = bucketing.pow2_bucket(k, 1)
            words = np.zeros((b_k, c_full, 8), dtype=np.uint32)
            for row, i in enumerate(idxs):
                for j, leaf in enumerate(requests[i].payload[0]):
                    words[row, j] = np.frombuffer(
                        bytes(leaf), dtype=">u4").astype(np.uint32)
            roots = np.asarray(jax.device_get(
                SR.tree_root_batch(jnp.asarray(words))))
            for row, i in enumerate(idxs):
                out[i] = np.frombuffer(
                    words_to_bytes(roots[row]), dtype=np.uint8)
        return np.asarray(out, dtype=np.uint8)

    def _multiproofs_device(self, requests: list, idxs: list,
                            out: np.ndarray) -> None:
        """Fill out[i] (a branch tuple) for every multiproof index."""
        from ..engine import state_root as SR
        from ..ops.sha256_jax import words_to_bytes

        by_shape: dict = {}
        for i in idxs:
            chunks, gindex = requests[i].payload
            c_full = bucketing.pow2_bucket(max(1, len(chunks)), 1)
            depth = (c_full - 1).bit_length() if c_full > 1 else 0
            g = int(gindex)
            if g < 1 or g.bit_length() - 1 > depth:
                raise ValueError(
                    f"multiproof gindex {g} outside the depth-{depth} "
                    f"padded chunk tree")
            by_shape.setdefault(c_full, []).append((i, g))
        # content keys memoized by payload identity: a proof-service flush
        # reuses ONE chunks tuple for a whole column's queries, so the
        # O(leaf-count) key build must run once per distinct tuple, not
        # once per request (the payloads stay alive in `requests`, so ids
        # cannot be recycled underneath the memo)
        content_keys: dict = {}

        def key_for(chunks) -> tuple:
            key = content_keys.get(id(chunks))
            if key is None:
                key = content_keys[id(chunks)] = tuple(
                    bytes(c) for c in chunks)
            return key

        for c_full, members in sorted(by_shape.items()):
            slots: dict = {}
            queries = []
            for i, g in members:
                key = key_for(requests[i].payload[0])
                slot = slots.get(key)
                if slot is None:
                    slot = slots[key] = len(slots)
                queries.append((i, slot, g))
            b_k = bucketing.pow2_bucket(len(slots), 1)
            b_q = bucketing.pow2_bucket(len(queries), 1)
            words = np.zeros((b_k, c_full, 8), dtype=np.uint32)
            for key, slot in slots.items():
                for j, leaf in enumerate(key):
                    words[slot, j] = np.frombuffer(
                        leaf, dtype=">u4").astype(np.uint32)
            tree_ids = np.zeros(b_q, dtype=np.int32)
            gidx = np.ones(b_q, dtype=np.int32)  # pad: root query on tree 0
            for row, (i, slot, g) in enumerate(queries):
                tree_ids[row] = slot
                gidx[row] = g
            sib, _nodes, _roots = SR.multiproof_batch(words, tree_ids, gidx)
            for row, (i, slot, g) in enumerate(queries):
                d = g.bit_length() - 1
                out[i] = tuple(
                    words_to_bytes(sib[row, lvl]) for lvl in range(d))

    def execute_degraded(self, requests: list) -> np.ndarray:
        from ..ssz.merkle import merkleize_chunks

        if all(r.kind == "tree_root" for r in requests):
            return np.asarray(
                [np.frombuffer(
                    merkleize_chunks([bytes(c) for c in r.payload[0]]),
                    dtype=np.uint8)
                 for r in requests], dtype=np.uint8)
        from ..ssz.proofs import build_chunk_proof

        out = np.empty(len(requests), dtype=object)
        for i, r in enumerate(requests):
            if r.kind == "tree_root":
                out[i] = np.frombuffer(
                    merkleize_chunks([bytes(c) for c in r.payload[0]]),
                    dtype=np.uint8)
            else:
                chunks, gindex = r.payload
                out[i] = tuple(build_chunk_proof(
                    [bytes(c) for c in chunks], int(gindex)))
        return out

    def to_result(self, row):
        if isinstance(row, tuple):
            return row  # multiproof branch: deepest-first 32-byte siblings
        return np.asarray(row, dtype=np.uint8).tobytes()

    def load(self, requests: list) -> tuple:
        # units are whole trees (tree_root) / queries (multiproof); each
        # (kind, leaf-count) bucket pads independently
        by_shape: dict = {}
        for r in requests:
            c_full = bucketing.pow2_bucket(max(1, len(r.payload[0])), 1)
            key = (r.kind, c_full)
            by_shape[key] = by_shape.get(key, 0) + 1
        live = len(requests)
        padded = sum(bucketing.pow2_bucket(k, 1) for k in by_shape.values())
        return live, padded


class MsmWorkClass(WorkClass):
    """G1 multi-scalar multiplication lanes over the Pippenger kernel
    (ops/bls12_jax.g1_msm_pippenger). Two kinds:

    - "msm": payload = (points, scalars, nbits), points affine int pairs;
      one Σ scalar_i·P_i per request via g1_msm_device.
    - "aggregate": payload = tuple of compressed pubkey bytes — the
      all-ones-scalar degenerate case, routed through crypto/bls_jax's
      batched device subgroup check + g1_aggregate_device reduction tree
      (the firehose cold-lane path).

    Result rows are marker tuples in an object-dtype array — ("point", x,
    y) | ("inf",) | ("inf_member",) | ("bad_encoding", msg) — so hostile
    inputs travel as data instead of exceptions across the dispatch seam.
    Every marker is truthy, which keeps the scheduler's failing-collapse
    re-verify inert (this class never collapses). The bucketer bounds
    compile diversity exactly as for the other lanes: one XLA program per
    (pow2 item bucket, nbits, window).

    With `self_check=True` (or env CONSENSUS_TPU_MSM_SELF_CHECK=1) each
    "msm" row is verified post-dispatch with the 2G2T-style constant-size
    outsourcing equation — see `verify_results` below.
    """

    name = "msm"
    kinds = ("msm", "aggregate")
    min_bucket = 8

    def __init__(self, self_check: bool | None = None):
        if self_check is None:
            self_check = os.environ.get(
                "CONSENSUS_TPU_MSM_SELF_CHECK", "") not in ("", "0")
        self.self_check = bool(self_check)

    def execute(self, requests: list) -> np.ndarray:
        from ..crypto import bls_jax
        from ..ops import bls12_jax as K

        out = np.empty(len(requests), dtype=object)
        for i, r in enumerate(requests):
            if r.kind == "aggregate":
                out[i] = bls_jax._aggregate_pubkeys_device_impl(
                    list(r.payload))
            else:
                points, scalars, nbits = r.payload
                total = K.g1_msm_device(
                    list(points), list(scalars), int(nbits))
                out[i] = (("inf",) if total is None
                          else ("point", total[0], total[1]))
        return out

    def execute_degraded(self, requests: list) -> np.ndarray:
        from ..crypto import kzg_batch

        out = np.empty(len(requests), dtype=object)
        for i, r in enumerate(requests):
            if r.kind == "aggregate":
                out[i] = self._host_aggregate(list(r.payload))
            else:
                points, scalars, _nbits = r.payload
                total = kzg_batch._host_msm(list(points), list(scalars))
                out[i] = (("inf",) if total is None
                          else ("point", total[0], total[1]))
        return out

    @staticmethod
    def _host_aggregate(pubkeys_bytes: list):
        """Host-oracle twin of bls_jax._aggregate_pubkeys_device_impl:
        same marker protocol, validated g1_from_bytes + pt_add loop."""
        from ..crypto import bls12_381 as oracle

        acc = None
        try:
            for pk in pubkeys_bytes:
                aff = oracle.g1_from_bytes(bytes(pk))
                if aff is None:
                    return ("inf_member",)
                pt = oracle.pt_from_affine(oracle.FP_FIELD, aff)
                acc = (pt if acc is None
                       else oracle.pt_add(oracle.FP_FIELD, acc, pt))
        except ValueError as e:
            return ("bad_encoding", str(e))
        aff = oracle.pt_to_affine(oracle.FP_FIELD, acc)
        return ("inf",) if aff is None else ("point", aff[0], aff[1])

    def to_result(self, row):
        return row

    def load(self, requests: list) -> tuple:
        # units are MSM terms: each request pads its own item count to the
        # pow2 bucket inside g1_msm_device / g1_aggregate_device
        live = padded = 0
        for r in requests:
            n = (len(r.payload) if r.kind == "aggregate"
                 else len(r.payload[0]))
            live += n
            padded += bucketing.pow2_bucket(max(1, n), self.min_bucket)
        return live, padded

    def verify_results(self, requests: list, results) -> None:
        """2G2T-style outsourcing check on "msm" rows: draw a random
        64-bit c and require host [c]·R_claimed == device MSM over the
        rerandomized scalars c·s_i mod r — two independent evaluations of
        the same sum bound by a random scalar, so a corrupt-but-well-formed
        row is caught BEFORE any handle resolves (the failure mode the
        scheduler's shape/dtype validation cannot see). This catches
        faults, not an adversarial kernel: a deterministic corruption of
        both evaluations could still agree. "aggregate" rows skip the
        check — a wrong committee aggregate fails the downstream pairing
        check, which already re-attributes per member."""
        if not self.self_check:
            return
        import secrets

        from ..crypto import bls12_381 as oracle
        from ..ops import bls12_jax as K

        for r, row in zip(requests, results):
            if r.kind != "msm":
                continue
            tag = row[0]
            if tag == "point":
                claimed = (int(row[1]), int(row[2]))
            elif tag == "inf":
                claimed = None
            else:
                continue
            points, scalars, _nbits = r.payload
            c = secrets.randbelow(2**64 - 1) + 1
            expect = (None if claimed is None else oracle.pt_to_affine(
                oracle.FP_FIELD,
                oracle.pt_mul(
                    oracle.FP_FIELD,
                    oracle.pt_from_affine(oracle.FP_FIELD, claimed), c)))
            redo = K.g1_msm_device(
                list(points), [c * s % oracle.R for s in scalars], 255)
            if redo != expect:
                from .scheduler import SchedSelfCheckError

                raise SchedSelfCheckError(
                    f"sched.dispatch[{self.name}]: 2G2T self-check "
                    f"mismatch on a {len(scalars)}-term MSM")


class ForkChoiceWorkClass(WorkClass):
    """Batched LMD-GHOST head selection: the fork-choice lane.

    One kind, "head": payload = (StoreSnapshot,) — the gather-form store
    view from forkchoice/mirror. The device path groups snapshots by
    their pow2 (blocks, validators) bucket and answers each group in one
    `engine/fork_choice.ghost_head_batch` launch; the degraded path is
    the spec-shaped host oracle (`forkchoice/reference.host_head`),
    bit-identical per the documented ancestor-equivalence. The result
    row is the head's block index into the snapshot's own table (int32 —
    note index 0, the anchor, is a legitimate falsy head: this class
    never collapses, so the resolver's falsy-collapse reverify path
    cannot misread it)."""

    name = "forkchoice"
    kinds = ("head",)
    min_bucket = 1

    def execute(self, requests: list) -> np.ndarray:
        from ..engine.fork_choice import ghost_head_batch

        return ghost_head_batch([r.payload[0] for r in requests])

    def execute_degraded(self, requests: list) -> np.ndarray:
        from ..forkchoice.reference import host_head

        return np.asarray([host_head(r.payload[0]) for r in requests],
                          dtype=np.int32)

    def to_result(self, row):
        return int(row)

    def load(self, requests: list) -> tuple:
        # units are head queries; each (blocks, validators) bucket pads
        # its query axis independently (engine/fork_choice grouping)
        by_bucket: dict = {}
        for r in requests:
            snap = r.payload[0]
            key = (bucketing.pow2_bucket(max(1, snap.n_blocks), 8),
                   bucketing.pow2_bucket(max(1, snap.n_validators), 64))
            by_bucket[key] = by_bucket.get(key, 0) + 1
        live = len(requests)
        padded = sum(bucketing.pow2_bucket(k, 1) for k in by_bucket.values())
        return live, padded


def default_classes() -> list:
    return [BlsWorkClass(), KzgWorkClass(), MerkleWorkClass(),
            MsmWorkClass(), ForkChoiceWorkClass()]
