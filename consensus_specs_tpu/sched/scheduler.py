"""Unified verification scheduler: one shape-bucketed device queue.

Every verification lane used to own its batching path — the BLS deferral
queue (crypto/bls.py), the KZG batch lane (crypto/kzg_batch.py), the
hashtree folds in engine/ — each with its own pow2 bucketing, its own
(or no) retry/breaker wiring, and its own metrics vocabulary. This module
multiplexes them behind one dispatch seam:

  * admission: `submit(Request) -> Handle` appends to a bounded per-class
    queue. Depth at/over the class bound flushes immediately (backpressure
    stays bounded without a background thread); an optional deadline
    flushes any class whose oldest entry has waited too long, checked at
    every admission. Classes may opt into same-key collapse at admission
    (the Wonderboom FastAggregateVerify merge — see classes.BlsWorkClass).
    Installing a `SealPolicy` replaces both built-in triggers: the policy
    alone decides which classes seal after each admission (`EdfSealPolicy`
    is earliest-deadline-first over `Request.deadline` — the front door's
    sealing discipline), and `class_priority` orders multi-class
    flush/drain passes so the proposal lane dispatches before reads.
  * dispatch: one batch per class per flush, executed behind the
    `sched.dispatch` fault seam with the PR-5 retry policy; results are
    validated (row count + dtype) so corrupt-kind chaos faults retry
    instead of resolving handles with garbage. Retries always re-enter
    from intact host payloads — requests carry host bytes, never donated
    device buffers, so the pre-donation retry invariant holds by
    construction.
  * degrade: a dispatch that exhausts retries on a device failure trips
    the per-class circuit breaker and falls back to the class's
    pure-Python path. One poisoned lane degrades alone; the other classes
    keep their device queues.
  * observability: per-class queue depth, batch occupancy, pad-waste
    ratio, and submit->result latency histograms (p50/p99 via the
    registry), plus dispatch/degrade/collapse counters.

jax-free at module level by charter: device work happens inside the work
classes' execute bodies, behind deferred imports.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..obs import flight as _flight
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from ..robustness import breaker as _breaker
from ..robustness import faults as _faults
from ..robustness import retry as _retry
from .api import Handle, Request
from .classes import default_classes

# Matches crypto/bls.py's FLUSH_RETRY_POLICY: the seam absorbs the same
# transient budget the deferral flush always had.
DISPATCH_RETRY_POLICY = _retry.RetryPolicy(
    max_attempts=3, base_delay=0.02, max_delay=0.2)

# Admission bound: far above any single epoch's check count, so the depth
# trigger is backpressure against unbounded producers, not a batch splitter
# for normal workloads (splitting a flush changes grouped-RLC routing).
DEFAULT_MAX_DEPTH = 8192


class SchedResultIntegrityError(_faults.IntegrityError):
    """Executor returned a result batch that fails shape/dtype validation
    (the corrupt-fault detection point). Retryable: request payloads are
    host-side and intact, so re-execution is safe."""


class SchedSelfCheckError(_faults.IntegrityError):
    """A work class's post-dispatch `verify_results` hook rejected a
    structurally VALID result batch — the seam where the msm class's
    2G2T-style outsourcing equation catches well-formed-but-wrong values
    that shape/dtype validation cannot. Retryable for the same reason as
    SchedResultIntegrityError."""


class _Entry:
    """One queue slot: the requests collapsed into it and their handles."""

    __slots__ = ("members", "handles", "collapsed", "t_submit", "deadline")

    def __init__(self, request: Request, handle: Handle, now: float):
        self.members = [request]
        self.handles = [handle]
        self.collapsed = request  # the request dispatch actually executes
        self.t_submit = now
        self.deadline = request.deadline

    def note_deadline(self, request: Request) -> None:
        """Fold a merged member's deadline in: the entry owes its verdict
        by the EARLIEST member deadline (a collapse must not let a tight
        request inherit a lax neighbour's slack)."""
        d = request.deadline
        if d is not None and (self.deadline is None or d < self.deadline):
            self.deadline = d


class SealPolicy:
    """Seam deciding WHICH queued classes to seal after an admission.

    Installed on a Scheduler via `seal_policy=`, `select(scheduler, now)`
    runs after every submit/submit_many admission (outside the queue lock)
    and returns the class names to flush, in flush order. It REPLACES the
    built-in depth/deadline triggers — a policy that wants depth
    backpressure must implement it (EdfSealPolicy does)."""

    def select(self, scheduler: "Scheduler", now: float) -> list:
        raise NotImplementedError


class EdfSealPolicy(SealPolicy):
    """Earliest-deadline-first sealing: seal the batch whose earliest
    deadline is closest to expiry.

    A class becomes due when its earliest queued deadline is within
    `slack_s` of `now` (the slack covers dispatch time so the verdict — not
    just the flush — lands inside the deadline), when its depth reaches the
    depth limit (backpressure, same bound the built-in trigger used), or —
    for deadline-free entries — when its oldest entry has waited
    `max_wait_s`. Due classes flush earliest-deadline-first; deadline-free
    overflow follows, oldest-first."""

    def __init__(self, slack_s: float = 0.0, *,
                 max_wait_s: float | None = None,
                 depth_limit: int | None = None):
        self.slack_s = slack_s
        self.max_wait_s = max_wait_s
        self.depth_limit = depth_limit

    def select(self, scheduler: "Scheduler", now: float) -> list:
        due = []
        for name, wc in scheduler.classes.items():
            depth, oldest, earliest = scheduler.queue_meta(name)
            if not depth:
                continue
            limit = self.depth_limit
            if limit is None:
                limit = (wc.max_depth if wc.max_depth is not None
                         else scheduler.max_depth)
            if earliest is not None and earliest - now <= self.slack_s:
                due.append((earliest, name))
            elif depth >= limit:
                due.append((now, name))
            elif (self.max_wait_s is not None and oldest is not None
                  and now - oldest >= self.max_wait_s):
                due.append((oldest + self.max_wait_s, name))
        due.sort()
        return [name for _, name in due]


class Scheduler:
    """Shape-bucketed multiplexer for heterogeneous verification work."""

    def __init__(self, classes=None, *, retry_policy=None,
                 failure_threshold: int = 3,
                 max_depth: int = DEFAULT_MAX_DEPTH,
                 flush_deadline_s: float | None = None,
                 seal_policy: SealPolicy | None = None,
                 class_priority: dict | None = None,
                 clock=time.monotonic,
                 registry=None):
        self.classes = {wc.name: wc for wc in
                        (default_classes() if classes is None else classes)}
        self.retry_policy = retry_policy or DISPATCH_RETRY_POLICY
        self.max_depth = max_depth
        self.flush_deadline_s = flush_deadline_s
        # seal_policy: when set, it owns the "when do we flush" decision
        # entirely (depth/deadline triggers are bypassed). class_priority
        # maps name -> rank (lower flushes first) and orders multi-class
        # flush()/drain() passes; unranked classes keep admission order
        # after every ranked one. clock is injectable so deadline math is
        # deterministic under a virtual clock (frontdoor traffic replay).
        self.seal_policy = seal_policy
        self.class_priority = class_priority
        self.clock = clock
        self.registry = registry if registry is not None else _obs_metrics.REGISTRY
        self._breakers = {
            name: _breaker.CircuitBreaker(
                failure_threshold=failure_threshold, name=f"sched-{name}")
            for name in self.classes}
        self._queues: dict = {name: [] for name in self.classes}
        self._collapse_index: dict = {name: {} for name in self.classes}
        self._lock = threading.RLock()

    # -- admission ---------------------------------------------------------

    def breaker(self, work_class: str) -> _breaker.CircuitBreaker:
        return self._breakers[work_class]

    def queue_depth(self, work_class: str) -> int:
        with self._lock:
            return len(self._queues[work_class])

    def queue_meta(self, work_class: str) -> tuple:
        """(depth, oldest_t_submit, earliest_deadline) for one class queue
        — the seal policy's decision inputs. Empty queue: (0, None, None);
        a queue whose entries carry no deadline reports earliest None."""
        with self._lock:
            queue = self._queues[work_class]
            if not queue:
                return 0, None, None
            deadlines = [e.deadline for e in queue if e.deadline is not None]
            return (len(queue), queue[0].t_submit,
                    min(deadlines) if deadlines else None)

    def _ordered(self, names) -> list:
        """Flush order for a multi-class pass: class_priority rank when
        installed (stable within a rank), registration order otherwise."""
        names = list(names)
        if self.class_priority is None:
            return names
        rank = self.class_priority
        return sorted(names, key=lambda n: rank.get(n, len(rank)))

    def queue_load(self, work_class: str) -> tuple:
        """(entries, members) currently queued: distinct device checks vs
        the requests collapsed into them. members/entries is the live
        collapse ratio a streaming consumer (the attestation firehose)
        reports before it seals a batch."""
        with self._lock:
            queue = self._queues[work_class]
            return len(queue), sum(len(e.members) for e in queue)

    def submit(self, request: Request) -> Handle:
        wc = self.classes.get(request.work_class)
        if wc is None:
            raise ValueError(f"unknown work class {request.work_class!r} "
                             f"(registered: {sorted(self.classes)})")
        if request.kind not in wc.kinds:
            raise ValueError(f"unknown kind {request.kind!r} for work class "
                             f"{wc.name!r} (kinds: {wc.kinds})")
        now = self.clock()
        handle = Handle(request, self, _submitted_at=now)
        reg = self.registry
        with self._lock:
            depth = self._admit(wc, request, handle, now)
        reg.counter("sched_submitted_total",
                    work_class=wc.name, kind=request.kind).inc()
        reg.gauge("sched_queue_depth", work_class=wc.name).set(depth)
        if self.seal_policy is not None:
            self._run_seal_policy(now)
            return handle
        limit = wc.max_depth if wc.max_depth is not None else self.max_depth
        if depth >= limit:
            self._flush_class(wc.name, trigger="depth")
        elif self.flush_deadline_s is not None:
            self._flush_overdue(now)
        return handle

    def submit_many(self, requests: list) -> list:
        """Admit a batch of requests under ONE lock acquisition.

        Semantics match a submit() loop (same collapse behaviour, counters,
        and depth/deadline triggers evaluated after admission), with one
        batch-level improvement: same-collapse-key groups fold through the
        class's `merge_group` hook when it defines one, so a committee's
        worth of same-message signatures aggregates in a single pass
        instead of a chain of pairwise merges. The depth trigger fires at
        most once per class AFTER the whole batch is admitted — a batched
        producer wants one sealed flush, not a flush per boundary crossing.
        """
        if not requests:
            return []
        now = self.clock()
        handles: list[Handle] = []
        per_class: dict = {}
        for request in requests:
            wc = self.classes.get(request.work_class)
            if wc is None:
                raise ValueError(f"unknown work class {request.work_class!r} "
                                 f"(registered: {sorted(self.classes)})")
            if request.kind not in wc.kinds:
                raise ValueError(f"unknown kind {request.kind!r} for work "
                                 f"class {wc.name!r} (kinds: {wc.kinds})")
            handle = Handle(request, self, _submitted_at=now)
            handles.append(handle)
            per_class.setdefault(wc.name, []).append((request, handle))
        reg = self.registry
        depths: dict = {}
        with self._lock:
            for name, pairs in per_class.items():
                depths[name] = self._admit_batch(
                    self.classes[name], pairs, now)
        for name, pairs in per_class.items():
            for request, _ in pairs:
                reg.counter("sched_submitted_total",
                            work_class=name, kind=request.kind).inc()
            reg.gauge("sched_queue_depth", work_class=name).set(depths[name])
            if self.seal_policy is not None:
                continue
            wc = self.classes[name]
            limit = wc.max_depth if wc.max_depth is not None else self.max_depth
            if depths[name] >= limit:
                self._flush_class(name, trigger="depth")
        if self.seal_policy is not None:
            self._run_seal_policy(self.clock())
        elif self.flush_deadline_s is not None:
            self._flush_overdue(self.clock())
        return handles

    def _run_seal_policy(self, now: float) -> None:
        for name in self.seal_policy.select(self, now):
            self._flush_class(name, trigger="seal")

    def _admit_batch(self, wc, pairs: list, now: float) -> int:
        """Admit (request, handle) pairs for one class under the held lock."""
        groups: dict = {}
        for request, handle in pairs:
            key = wc.collapse_key(request)
            if key is None:
                self._admit(wc, request, handle, now)
            else:
                groups.setdefault(key, []).append((request, handle))
        for key, members in groups.items():
            self._admit_group(wc, key, members, now)
        return len(self._queues[wc.name])

    def _admit_group(self, wc, key, members: list, now: float) -> None:
        """Collapse one same-key group in a single merge_group pass; any
        class without the hook — or a group whose aggregation rejects a
        payload — falls back to the pairwise _admit path, which isolates
        the unmergeable request instead of poisoning the group."""
        merge_group = getattr(wc, "merge_group", None)
        entry = self._collapse_index[wc.name].get(key)
        if merge_group is not None and (entry is not None or len(members) > 1):
            base = entry.collapsed if entry is not None else members[0][0]
            rest = members if entry is not None else members[1:]
            try:
                merged = merge_group(base, [r for r, _ in rest])
            except Exception:
                merged = None  # unmergeable payload somewhere: isolate below
            if merged is not None:
                if entry is None:
                    request, handle = members[0]
                    entry = _Entry(request, handle, now)
                    self._collapse_index[wc.name][key] = entry
                    self._queues[wc.name].append(entry)
                for request, handle in rest:
                    entry.members.append(request)
                    entry.handles.append(handle)
                    entry.note_deadline(request)
                    self.registry.counter(
                        "sched_collapsed_total", work_class=wc.name).inc()
                entry.collapsed = merged
                return
        for request, handle in members:
            self._admit(wc, request, handle, now)

    def _admit(self, wc, request: Request, handle: Handle, now: float) -> int:
        """Append (or collapse) under the lock; returns the queue depth."""
        queue = self._queues[wc.name]
        key = wc.collapse_key(request)
        if key is not None:
            index = self._collapse_index[wc.name]
            entry = index.get(key)
            if entry is not None:
                try:
                    merged = wc.merge(entry.collapsed, request)
                except Exception:
                    merged = None  # unmergeable payload: queue individually
                if merged is not None:
                    entry.members.append(request)
                    entry.handles.append(handle)
                    entry.note_deadline(request)
                    entry.collapsed = merged
                    self.registry.counter(
                        "sched_collapsed_total", work_class=wc.name).inc()
                    return len(queue)
            entry = _Entry(request, handle, now)
            index[key] = entry
            queue.append(entry)
            return len(queue)
        queue.append(_Entry(request, handle, now))
        return len(queue)

    def _flush_overdue(self, now: float) -> None:
        overdue = []
        with self._lock:
            for name, queue in self._queues.items():
                if queue and now - queue[0].t_submit >= self.flush_deadline_s:
                    overdue.append(name)
        for name in overdue:
            self._flush_class(name, trigger="deadline")

    # -- flush / drain -----------------------------------------------------

    def flush(self, work_class: str | None = None, *,
              trigger: str = "explicit") -> None:
        """Dispatch everything queued (for one class, or all of them).
        `trigger` only labels the sched_flush_total series — streaming
        callers (the firehose worker) tag their flushes distinctly."""
        names = ([work_class] if work_class is not None
                 else self._ordered(self.classes))
        for name in names:
            self._flush_class(name, trigger=trigger)

    def drain(self) -> None:
        """Flush until every queue is empty (a flush can enqueue more work
        through degraded re-verification paths, hence the loop)."""
        while True:
            with self._lock:
                pending = [n for n, q in self._queues.items() if q]
            if not pending:
                return
            for name in self._ordered(pending):
                self._flush_class(name, trigger="drain")

    def _flush_class(self, name: str, trigger: str) -> None:
        with self._lock:
            entries = self._queues[name]
            if not entries:
                return
            self._queues[name] = []
            self._collapse_index[name] = {}
        reg = self.registry
        reg.counter("sched_flush_total", work_class=name,
                    trigger=trigger).inc()
        reg.gauge("sched_queue_depth", work_class=name).set(0)
        self._dispatch(self.classes[name], entries)

    # -- dispatch seam -----------------------------------------------------

    def _dispatch(self, wc, entries: list) -> None:
        reg = self.registry
        requests = [e.collapsed for e in entries]
        brk = self._breakers[wc.name]
        # fan-in span links: N member requests (across every admission
        # collapse in the batch) -> ONE dispatch span, so a device verdict
        # is attributable to exactly the traces that rode this batch
        links = None
        if _obs_trace.current_tracer() is not None:
            links = [m.trace for e in entries for m in e.members
                     if m.trace is not None] or None
        with _obs_trace.span("sched.dispatch", work_class=wc.name,
                             batch=len(requests), links=links):
            mode = brk.on_attempt()
            n = len(requests)

            def attempt():
                _faults.fire("sched.dispatch")
                res = np.asarray(wc.execute(requests))
                res = _faults.corrupt_array("sched.dispatch", res)
                res = self._validated(res, n, wc.name)
                # Optional per-class value check (msm's 2G2T equation):
                # raises a retryable error so corrupt-but-well-formed rows
                # re-execute or degrade instead of resolving handles. The
                # degraded path below skips it — the host oracle is the
                # trust anchor the check compares against. A rejection is
                # an incident: the black box freezes its event ring.
                if wc.verify_results is not None:
                    try:
                        wc.verify_results(requests, res)
                    except Exception as exc:
                        _flight.record("self_check", work_class=wc.name,
                                       error=type(exc).__name__,
                                       detail=str(exc)[:200])
                        _flight.dump("sched_self_check",
                                     meta={"work_class": wc.name})
                        raise
                return res

            degraded = False
            try:
                policy = (self.retry_policy if mode == "closed"
                          else _retry.PROBE_POLICY)
                results = _retry.call_with_retry(attempt, policy)
                brk.record_success()
            except Exception as exc:
                if not _retry.is_device_failure(exc):
                    for e in entries:
                        for h in e.handles:
                            h._fail(exc)
                    raise
                brk.record_failure(degraded=True)
                reg.counter("sched_degraded_total", work_class=wc.name).inc()
                _obs_trace.annotate(degraded_class=wc.name)
                results = self._validated(
                    np.asarray(wc.execute_degraded(requests)), n, wc.name)
                degraded = True

            live, padded = wc.load(requests)
            occ = (live / padded) if padded else 1.0
            reg.counter("sched_dispatch_total", work_class=wc.name,
                        path="host" if degraded else "device").inc()
            reg.counter("sched_items_total", work_class=wc.name).inc(live)
            reg.histogram("sched_batch_occupancy",
                          buckets=_OCCUPANCY_BUCKETS,
                          work_class=wc.name).observe(occ)
            reg.gauge("sched_last_batch_occupancy",
                      work_class=wc.name).set(occ)
            reg.gauge("sched_last_pad_waste", work_class=wc.name).set(1 - occ)
            self._resolve(wc, entries, results, degraded)

    def _resolve(self, wc, entries: list, results, degraded: bool) -> None:
        lat = self.registry.histogram(
            "sched_submit_latency_seconds", work_class=wc.name)
        now = self.clock()

        def _ex(h):
            tr = h.request.trace
            return tr.trace_id if tr is not None else None

        for e, row in zip(entries, results):
            if len(e.members) > 1 and not wc.to_result(row):
                # a failing collapsed check proves nothing about members:
                # re-verify each for sound attribution (Wonderboom
                # fallback). Fan-out span links name the EXACT member set
                # the failure decomposes into — the reverse edge of the
                # dispatch span's fan-in.
                self.registry.counter("sched_collapse_reverify_total",
                                      work_class=wc.name).inc()
                runner = wc.execute_degraded if degraded else wc.execute
                mlinks = [m.trace for m in e.members if m.trace is not None]
                with _obs_trace.span("sched.reverify", work_class=wc.name,
                                     members=len(e.members),
                                     links=mlinks or None):
                    member_rows = self._validated(
                        np.asarray(runner(e.members)), len(e.members),
                        wc.name)
                for h, mrow in zip(e.handles, member_rows):
                    lat.observe(max(0.0, now - h._submitted_at),
                                exemplar=_ex(h))
                    h._resolve(wc.to_result(mrow))
                continue
            value = wc.to_result(row)
            for h in e.handles:
                lat.observe(max(0.0, now - h._submitted_at), exemplar=_ex(h))
                h._resolve(value)

    def _validated(self, res: np.ndarray, n: int, name: str) -> np.ndarray:
        arr = np.asarray(res)
        if arr.ndim == 0 or arr.shape[0] != n or arr.dtype.kind == "f":
            raise SchedResultIntegrityError(
                f"sched.dispatch[{name}]: executor returned "
                f"shape={arr.shape} dtype={arr.dtype} for {n} requests")
        return arr


# Occupancy is a ratio in [0, 1]; the default latency-shaped buckets would
# collapse every observation into the top decades.
_OCCUPANCY_BUCKETS = tuple(i / 16 for i in range(1, 17))


# -- process-default instance ---------------------------------------------
#
# The BLS deferral flush and the KZG batch entry points route through one
# shared scheduler so heterogeneous submitters actually share queues (the
# point of the subsystem). Tests that inject faults or trip breakers build
# their own instances, or reset this one to avoid cross-test state.

_DEFAULT: Scheduler | None = None
_DEFAULT_LOCK = threading.Lock()


def default_scheduler() -> Scheduler:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Scheduler()
    return _DEFAULT


def reset_default_scheduler() -> None:
    """Drop the process-default instance (fresh queues and breakers)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
