"""Shape-bucket planning shared by every verification lane.

Every device lane in this repo pads its batch to a power-of-two bucket so
the jit cache holds one entry per bucket instead of one per request count
(crypto/bls_jax.py grew the idiom for the RLC flush; crypto/kzg_batch.py
and the scheduler's Merkle lane repeat it). This module owns the *shape*
math — bucket sizes, pad counts, and the grouped segment/pad-assignment
plan behind `_pack_grouped_args` — so the lanes only own their
class-specific pad VALUES (BLS seeds identity pairs e(G1,Q)·e(−G1,Q)==1,
KZG seeds zero-scalar points, Merkle pads whole zero trees).

jax-free by charter: plans are plain tuples/ints computed on host, cheap
enough to run per flush, and importable from the jax-free shim layer.
"""
from __future__ import annotations

from dataclasses import dataclass

# Smallest item bucket. Matches the historical crypto/bls_jax._MIN_BATCH:
# below 8 items the pad overhead is noise next to kernel fixed costs, and
# a shared floor keeps the (class, bucket) compile-cache product small.
MIN_BUCKET = 8


def pow2_bucket(n: int, min_bucket: int = MIN_BUCKET) -> int:
    """Smallest power-of-two >= n, floored at min_bucket (which must itself
    be a power of two — 1 disables the floor)."""
    b = min_bucket
    while b < n:
        b *= 2
    return b


@dataclass(frozen=True)
class PadPlan:
    """Flat (ungrouped) batch plan: n live items padded to one bucket."""

    n: int
    bucket: int

    @property
    def pad(self) -> int:
        return self.bucket - self.n

    @property
    def occupancy(self) -> float:
        """Live fraction of the padded batch (1.0 = no waste)."""
        return self.n / self.bucket if self.bucket else 1.0

    @property
    def pad_waste(self) -> float:
        return 1.0 - self.occupancy


def pad_plan(n: int, min_bucket: int = MIN_BUCKET) -> PadPlan:
    return PadPlan(n=n, bucket=pow2_bucket(n, min_bucket))


@dataclass(frozen=True)
class GroupedPlan:
    """Segmented batch plan: n items in d groups, both padded to buckets.

    Shape contract (inherited verbatim from the RLC grouped flush, whose
    tests pin it): the group bucket b_d pads d to a power of two with no
    minimum; the item bucket b_n is computed over n + pad_groups so every
    pad GROUP is guaranteed at least one pad ITEM to seed it — an empty
    segment would reduce to the identity-less empty sum and fail closed
    (see ops/bls12_jax.g1_segment_sum). Pad items land at the tail in
    submission order: the first pad_groups pads seed groups d..b_d-1, and
    overflow riders join group d (or group 0 when d was already a power
    of two) — callers rely on this ordering so randomization scalars line
    up between grouped and ungrouped packings of the same batch.
    """

    n: int
    d: int
    b_n: int
    b_d: int
    seg: tuple  # group id per slot, len b_n (live items first, pads at tail)
    rep_index: tuple  # len d: index into the live batch of each group's
    # first-seen member (callers take pad values from it)
    pad_assignments: tuple  # len b_n - n: group id per pad item

    @property
    def pad_groups(self) -> int:
        return self.b_d - self.d

    @property
    def pad_items(self) -> int:
        return self.b_n - self.n

    @property
    def occupancy(self) -> float:
        return self.n / self.b_n if self.b_n else 1.0

    @property
    def pad_waste(self) -> float:
        return 1.0 - self.occupancy


def grouped_plan(keys, min_bucket: int = MIN_BUCKET) -> GroupedPlan:
    """Plan a segmented batch from per-item group keys (first-seen order).

    Keys are compared by VALUE — identity of interned keys is an
    optimization upstream, never a correctness input here.
    """
    keys = list(keys)
    n = len(keys)
    gid: dict = {}
    seg = []
    rep_index = []
    for i, k in enumerate(keys):
        g = gid.get(k)
        if g is None:
            g = gid[k] = len(rep_index)
            rep_index.append(i)
        seg.append(g)
    d = len(rep_index)
    b_d = pow2_bucket(d, 1)
    pad_groups = b_d - d
    b_n = pow2_bucket(n + pad_groups, min_bucket)

    pad_assignments = []
    for j in range(b_n - n):
        if j < pad_groups:
            g = d + j  # seed each pad group with one member
        else:
            g = d if pad_groups else 0  # overflow riders join an existing group
        pad_assignments.append(g)
        seg.append(g)

    return GroupedPlan(
        n=n, d=d, b_n=b_n, b_d=b_d, seg=tuple(seg),
        rep_index=tuple(rep_index), pad_assignments=tuple(pad_assignments))
