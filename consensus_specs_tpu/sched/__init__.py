"""Unified verification scheduler: one shape-bucketed device queue for
BLS pairing checks, KZG blob/proof batches, Merkle root folds, and G1
Pippenger multi-scalar multiplications.

Public surface:
  * `Request` / `Handle` — the typed submit/future API (api.py)
  * `Scheduler`, `default_scheduler`, `reset_default_scheduler` — the
    admission + dispatch engine (scheduler.py)
  * `bucketing` — the shared pow2 bucket / pad-assignment planner the
    RLC flush and the scheduler lanes both pack with (bucketing.py)
  * work classes (classes.py) — the per-lane executors

jax-free at module level: safe to import from the jax-free shim layer
(crypto/bls.py routes its deferral flush through here).
"""
from . import bucketing  # noqa: F401
from .api import Handle, Request  # noqa: F401
from .classes import (  # noqa: F401
    BlsWorkClass,
    ForkChoiceWorkClass,
    KzgWorkClass,
    MerkleWorkClass,
    MsmWorkClass,
    WorkClass,
    default_classes,
)
from .scheduler import (  # noqa: F401
    DISPATCH_RETRY_POLICY,
    EdfSealPolicy,
    SchedResultIntegrityError,
    SchedSelfCheckError,
    Scheduler,
    SealPolicy,
    default_scheduler,
    reset_default_scheduler,
)
