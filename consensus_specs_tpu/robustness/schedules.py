"""Scenario-level fault schedules: long-horizon chaos profiles.

The PR-5 chaos lane (tests/test_chaos_epoch.py) drives exact per-call
schedules — "fire on the 3rd staged column". Scenario runs are thousands
of device calls long, so here the schedules are RATE-based with per-site
fire caps: a sustained drizzle of transient failures over the whole
horizon, every fault still inside the retry/breaker/degrade envelope so
the run must stay bit-identical to the fault-free oracle.

Profiles name the seams one lane actually crosses:
  * "engine"   — the resident-epoch bridge (dispatch raise + torn aux
                 readout); the scenario engine lane installs this.
  * "firehose" — the streaming attestation path (ingest/flush raises).
  * "full"     — both, for soak runs that exercise every lane at once.

Seeds follow the faults.py contract: every site draws from its own
`Random(f"{seed}:{site}")` stream, so one lane's fire pattern never
shifts another's (deterministic replay per seed).
"""
from __future__ import annotations

from .faults import FaultPlan, FaultSpec

# "truncate" (not "nan"): the aux-readout flag vector is boolean — a NaN
# write can't represent there, while a truncated copy trips the structural
# shape check in bridge._read_aux_flags exactly like a torn D2H transfer.
ENGINE_PROFILE = {
    "bridge.dispatch": dict(kind="raise", exc="transient"),
    "bridge.aux_readout": dict(kind="corrupt", corruption="truncate"),
}
FIREHOSE_PROFILE = {
    "firehose.ingest": dict(kind="raise", exc="transient"),
    "firehose.flush": dict(kind="raise", exc="transient"),
}
# sched.dispatch is the seam every work class crosses — the fork-choice
# head lane included — so this drizzle exercises retry convergence on any
# scheduler live during the run (transient: absorbed before the breaker).
FORKCHOICE_PROFILE = {
    "sched.dispatch": dict(kind="raise", exc="transient"),
}
PROFILES = {
    "engine": ENGINE_PROFILE,
    "firehose": FIREHOSE_PROFILE,
    "forkchoice": FORKCHOICE_PROFILE,
    "full": {**ENGINE_PROFILE, **FIREHOSE_PROFILE, **FORKCHOICE_PROFILE},
}


def long_horizon_plan(seed: int, *, profile: str = "engine",
                      rate: float = 0.05,
                      max_fires_per_site: int = 8) -> FaultPlan:
    """A seeded drizzle-of-faults plan for a multi-thousand-slot run.

    `rate` is per-crossing: with the default retry budget (4 attempts) a
    5% transient rate keeps the chance of even one exhausted budget over
    hundreds of epochs negligible, so convergence failures point at real
    divergence, not at fault-schedule bad luck. `max_fires_per_site`
    bounds total injected damage so soak wall-clock stays predictable.
    """
    if profile not in PROFILES:
        raise ValueError(f"unknown profile {profile!r} "
                         f"(have: {sorted(PROFILES)})")
    sites = {
        site: FaultSpec(rate=rate, max_fires=max_fires_per_site, **kw)
        for site, kw in PROFILES[profile].items()
    }
    return FaultPlan(seed=seed, sites=sites)
