"""Bounded retry with exponential backoff + deterministic jitter.

One policy surface for every seam that can fail transiently: the resident
engine's dispatch and aux readout, the bridge's write-back staging, the
deferred-BLS flush, the gossip sockets, and tools/bench_probe.py's TPU
probe loop. Classification is centralized here so "what is worth retrying"
is one decision, not five ad-hoc try/excepts:

  retryable   injected TransientFaults, IntegrityErrors (the device source
              is intact — re-reading is safe), XlaRuntimeError (matched by
              MRO *name* so this module never imports jax), socket/OS
              timeouts, and anything carrying `retryable = True`.
  fatal       everything else — assertion failures, BLSVerificationError,
              host-code bugs, and `FatalFault` (the injected hard crash).

Donation caveat: the jitted epoch programs donate their input pytree, so a
dispatch that fails AFTER consuming its buffers cannot be re-issued — the
second attempt would read deleted memory. The injection seams therefore
fire BEFORE the real call (input intact, retry safe), and a genuine
post-donation failure surfaces as a deleted-buffer XlaRuntimeError whose
retry fails identically and falls through to degradation.

jax-free at module level (tpulint import-layering).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from random import Random
from typing import Callable, Optional

from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from .faults import FaultInjected

# Exception type NAMES that classify as retryable device failures; matching
# by __mro__ name keeps this module importable without jax. JaxRuntimeError
# is jax's alias whose underlying class is named XlaRuntimeError.
_RETRYABLE_TYPE_NAMES = frozenset({"XlaRuntimeError", "JaxRuntimeError"})


def is_retryable(exc: BaseException) -> bool:
    """True when retrying the failed operation can plausibly succeed."""
    marked = getattr(exc, "retryable", None)
    if marked is not None:
        return bool(marked)
    if isinstance(exc, (TimeoutError, ConnectionError, OSError)):
        return True
    return any(t.__name__ in _RETRYABLE_TYPE_NAMES for t in type(exc).__mro__)


def is_device_failure(exc: BaseException) -> bool:
    """Failures eligible for device→host degradation (circuit-breaker
    accounting): anything retryable plus injected fatals — a crashed
    dispatch is a *device* problem, not a host-code bug, even when it is
    not worth re-issuing."""
    return is_retryable(exc) or isinstance(exc, FaultInjected)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with multiplicative jitter.

    max_attempts  total attempts including the first; 0 = unbounded.
    base_delay    delay after the first failure (seconds).
    backoff       delay multiplier per subsequent failure.
    max_delay     backoff ceiling (pre-jitter).
    jitter        fraction of the delay added uniformly at random, from a
                  stream seeded by `seed` — deterministic across runs.
    """

    max_attempts: int = 4
    base_delay: float = 0.02
    backoff: float = 2.0
    max_delay: float = 0.5
    jitter: float = 0.5
    seed: int = 0

    def delay(self, attempt: int, rng: Random) -> float:
        d = min(self.max_delay, self.base_delay * self.backoff ** (attempt - 1))
        if self.jitter:
            d *= 1.0 + self.jitter * rng.random()
        return d


# Shared defaults: device-boundary ops are cheap to re-issue, so short
# delays and a small budget; exhausting it falls through to degradation.
DEVICE_POLICY = RetryPolicy(max_attempts=4, base_delay=0.02, max_delay=0.5)
# The half-open probe gets exactly one attempt (see breaker.py).
PROBE_POLICY = RetryPolicy(max_attempts=1)


def call_with_retry(fn: Callable, policy: Optional[RetryPolicy] = None, *,
                    classify: Callable = is_retryable,
                    sleep: Callable = time.sleep,
                    on_retry: Optional[Callable] = None,
                    deadline: Optional[float] = None,
                    clock: Callable = time.monotonic):
    """Run `fn()` under `policy`; re-raise the final failure unchanged.

    `classify(exc)` decides retry-vs-raise; `on_retry(attempt, exc)` runs
    before each backoff sleep (logging / provenance hooks).

    `deadline` (absolute, in `clock`'s timebase) makes the retry loop
    deadline-aware: once the next backoff sleep would land at or past the
    deadline, the budget cannot fit another attempt and the LAST error is
    raised immediately instead of being burned on doomed backoff — this is
    how front-door deadlines propagate through every retried seam. The
    backoff delay is computed before the check, so the jitter RNG stream
    (and therefore every retried schedule) is identical with or without a
    deadline."""
    policy = policy or DEVICE_POLICY
    rng = Random(policy.seed)
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as exc:
            exhausted = policy.max_attempts and attempt >= policy.max_attempts
            if exhausted or not classify(exc):
                if exhausted and classify(exc):
                    _obs_metrics.REGISTRY.counter(
                        "retries_exhausted_total",
                        error=type(exc).__name__).inc()
                raise
            delay = policy.delay(attempt, rng)
            if deadline is not None and clock() + delay >= deadline:
                _obs_metrics.REGISTRY.counter(
                    "retries_deadline_exhausted_total",
                    error=type(exc).__name__).inc()
                raise
            # One tick per absorbed failure, labeled by exception type: the
            # chaos lane reconciles these against the fault plan's per-site
            # fire counts (each retried fire is caught exactly once here).
            _obs_metrics.REGISTRY.counter(
                "retries_total", error=type(exc).__name__).inc()
            _obs_trace.annotate(retried_errors=type(exc).__name__)
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep(delay)
