"""Circuit breaker for the device epoch path.

`bridge.apply_epoch_via_engine` must complete every epoch even when the
accelerator is gone (tunnel drop, preemption): a failed device attempt
degrades that epoch to the pure-Python spec path (`spec.process_epoch`),
which the differential tests prove bit-identical. The breaker bounds what
the degraded steady state COSTS:

  closed      device path with the full retry budget.
  open        reached after `failure_threshold` consecutive epoch-level
              device failures; the very next epoch transitions to...
  half_open   ...a single-attempt probe of the device path. Success
              re-arms (closed, counter reset); failure re-opens, so a dead
              device costs one cheap probe per epoch instead of a full
              retry budget, while recovery is detected within one epoch.

Every transition and degraded epoch is recorded in `events` — liveness
under partial failure is only worth having if it is observable. The log is
a BOUNDED ring (a week-long soak on a dead device would otherwise grow it
one dict per epoch, forever); overflow is not silent — dropped entries are
counted on the ring and as `breaker_events_dropped_total` in the metrics
registry, and every event also ticks `breaker_events_total{event=...}`
there, so the full history survives in counter form after the ring wraps.

jax-free at module level (tpulint import-layering).
"""
from __future__ import annotations

import threading

from ..obs import flight as _flight
from ..obs import metrics as _obs_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Default event-ring capacity: plenty for any test or incident window
# (an epoch produces at most ~2 events even fully degraded).
EVENT_RING_SIZE = 256


class BoundedEventLog(list):
    """A list that drops its OLDEST entries past `maxlen`, counting them.

    A plain `list` subclass on purpose: existing consumers compare the log
    to list literals (`brk.events == []`) and slice it — a deque would
    break them. Only `append` is bounded; the breaker never inserts any
    other way."""

    def __init__(self, maxlen: int = EVENT_RING_SIZE):
        super().__init__()
        self.maxlen = int(maxlen)
        self.dropped = 0

    def append(self, item) -> None:
        super().append(item)
        overflow = len(self) - self.maxlen
        if overflow > 0:
            del self[:overflow]
            self.dropped += overflow

    def clear(self) -> None:
        super().clear()
        self.dropped = 0


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 3, name: str = "device-epoch",
                 event_ring_size: int = EVENT_RING_SIZE):
        self.failure_threshold = int(failure_threshold)
        self.name = name
        # The breaker is driven from the sched flush path (the firehose's
        # flusher thread) and inspected from the main thread; one lock over
        # every transition keeps the counter/event/state triple coherent.
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.degraded_epochs = 0
        self.events: BoundedEventLog = BoundedEventLog(event_ring_size)

    def on_attempt(self) -> str:
        """Call once per epoch before trying the device path. Returns the
        attempt mode: "closed" (full retry budget) or "probe" (single
        attempt; the breaker is half-open)."""
        with self._lock:
            if self.state == OPEN:
                self.state = HALF_OPEN
                self._log("half_open_probe")
            return "probe" if self.state == HALF_OPEN else "closed"

    def record_success(self) -> None:
        with self._lock:
            if self.state != CLOSED:
                self._log("rearmed")
            self.state = CLOSED
            self.consecutive_failures = 0

    def record_failure(self, degraded: bool = True) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if degraded:
                self.degraded_epochs += 1
                self._log("degraded_to_python")
            if self.state == HALF_OPEN or \
                    self.consecutive_failures >= self.failure_threshold:
                if self.state != OPEN:
                    self._log("opened")
                self.state = OPEN

    def reset(self) -> None:
        with self._lock:
            self.state = CLOSED
            self.consecutive_failures = 0
            self.degraded_epochs = 0
            self.events.clear()

    def _log(self, event: str) -> None:
        before = self.events.dropped
        self.events.append({
            "event": event,
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
        })
        reg = _obs_metrics.REGISTRY
        reg.counter("breaker_events_total",
                    breaker=self.name, event=event).inc()
        if self.events.dropped > before:
            reg.counter("breaker_events_dropped_total",
                        breaker=self.name).inc(self.events.dropped - before)
        # black box: every transition is a flight-recorder event, and an
        # OPEN is an incident — dump the ring exactly once per transition
        # (the state != OPEN guard in record_failure already guarantees
        # one "opened" per open, so this stays one dump per incident)
        _flight.record("breaker", breaker=self.name, event=event,
                       consecutive_failures=self.consecutive_failures)
        if event == "opened":
            _flight.dump("breaker_open", meta={"breaker": self.name})

    def __repr__(self) -> str:  # observability in test failures
        return (f"CircuitBreaker({self.name!r}, state={self.state}, "
                f"failures={self.consecutive_failures}, "
                f"degraded={self.degraded_epochs})")
