"""Epoch-boundary checkpoints of the resident engine, with integrity digest.

A `ResidentEpochEngine` holds state in four places: the device `EpochState`
pytree, the host `BeaconState` mirror (stale except for epilogue-owned
fields), the write-back diff bases (`_pre_cols` / `_pre_mixes`), and the
incremental-root level arrays. A crash loses the device half; a checkpoint
makes the whole thing reconstructible:

  state_ssz   the host BeaconState, SSZ-serialized (canonical encoding).
  dev         every EpochState field as an owning numpy copy.
  pre_cols /  the registry diff bases the write-back maintains — snapshot
  pre_mixes   together with the host state so diffs stay coherent.
  meta        the pending-service bookkeeping: dirty-column accumulator,
              epochs since last sync, owed incremental-root refreshes.
  inc         the incremental-root Merkle stack (level arrays, cached
              columns, light roots), so `state_root()` resumes without a
              full rebuild. Captured when built; restore leaves it lazy
              otherwise.

`capture()` first flushes the engine's deferred epilogue service so the
pending queue is empty by construction — a checkpoint is always a clean
epoch boundary. The digest (sha256 over the canonical flattening) makes a
bit-rotted or tampered snapshot fail loudly at `restore()` instead of
resuming from garbage.

jax-free at module level (tpulint import-layering): everything touching
jax or the engine is deferred into capture()/restore().
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace

FORMAT = "engine-checkpoint-v1"


class CheckpointIntegrityError(Exception):
    """The snapshot's content no longer matches its digest."""


# --- host<->device tree helpers ---------------------------------------------


def _to_host(x):
    """Owning numpy copies of every array leaf (device buffers are donated
    by the next step, so references into them would dangle)."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, tuple):
        return tuple(_to_host(v) for v in x)
    if isinstance(x, list):
        return [_to_host(v) for v in x]
    if isinstance(x, dict):
        return {k: _to_host(v) for k, v in x.items()}
    return np.array(x)


def _to_dev(x, jnp):
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, tuple):
        return tuple(_to_dev(v, jnp) for v in x)
    if isinstance(x, list):
        return [_to_dev(v, jnp) for v in x]
    if isinstance(x, dict):
        return {k: _to_dev(v, jnp) for k, v in x.items()}
    return jnp.array(x)


# --- canonical flattening (digest + disk format share it) --------------------


def _flatten(x, prefix: str, arrays: dict):
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, np.ndarray):
        arrays[prefix] = x
        return {"$nd": prefix}
    if isinstance(x, tuple):
        return {"$tuple": [_flatten(v, f"{prefix}/{i}", arrays)
                           for i, v in enumerate(x)]}
    if isinstance(x, list):
        return {"$list": [_flatten(v, f"{prefix}/{i}", arrays)
                          for i, v in enumerate(x)]}
    if isinstance(x, dict):
        return {"$dict": {k: _flatten(v, f"{prefix}/{k}", arrays)
                          for k, v in sorted(x.items())}}
    raise TypeError(f"unsupported checkpoint leaf at {prefix}: {type(x)!r}")


def _unflatten(skel, arrays: dict):
    if not isinstance(skel, dict):
        return skel
    if "$nd" in skel:
        return arrays[skel["$nd"]]
    if "$tuple" in skel:
        return tuple(_unflatten(v, arrays) for v in skel["$tuple"])
    if "$list" in skel:
        return [_unflatten(v, arrays) for v in skel["$list"]]
    return {k: _unflatten(v, arrays) for k, v in skel["$dict"].items()}


# --- the checkpoint ----------------------------------------------------------


@dataclasses.dataclass
class EngineCheckpoint:
    state_ssz: bytes
    dev: dict
    pre_cols: dict
    pre_mixes: Optional[np.ndarray]
    meta: dict
    inc: Optional[dict]
    digest: str = ""

    # -- digest ---------------------------------------------------------------

    def _payload(self) -> dict:
        return {"dev": self.dev, "pre_cols": self.pre_cols,
                "pre_mixes": self.pre_mixes, "meta": self.meta,
                "inc": self.inc}

    def compute_digest(self) -> str:
        arrays: dict = {}
        skel = _flatten(self._payload(), "", arrays)
        h = hashlib.sha256()
        h.update(FORMAT.encode())
        h.update(len(self.state_ssz).to_bytes(8, "little"))
        h.update(self.state_ssz)
        h.update(json.dumps(skel, sort_keys=True).encode())
        for key in sorted(arrays):
            a = np.ascontiguousarray(arrays[key])
            h.update(f"{key}:{a.dtype.str}:{a.shape}".encode())
            h.update(a.tobytes())
        return h.hexdigest()

    def verify(self) -> None:
        actual = self.compute_digest()
        if actual != self.digest:
            _obs_metrics.REGISTRY.counter(
                "checkpoint_integrity_failures_total").inc()
            raise CheckpointIntegrityError(
                f"checkpoint digest mismatch: recorded {self.digest[:16]}…, "
                f"content hashes to {actual[:16]}… — refusing to restore "
                "from a torn or tampered snapshot")

    # -- capture --------------------------------------------------------------

    @classmethod
    def capture(cls, engine) -> "EngineCheckpoint":
        """Snapshot at a clean epoch boundary (deferred service flushed)."""
        with _obs_trace.span("checkpoint.capture"):
            return cls._capture(engine)

    @classmethod
    def _capture(cls, engine) -> "EngineCheckpoint":
        engine._flush_pending()
        dev = {f.name: np.array(getattr(engine.dev, f.name))
               for f in dataclasses.fields(type(engine.dev))}
        meta = {
            "format": FORMAT,
            "fork": str(getattr(engine.spec, "fork", "")),
            "dirty": [bool(b) for b in engine._dirty],
            "epochs_since_sync": int(engine._epochs_since_sync),
            "pending_epochs": int(engine._pending_epochs),
            "pending_last_epoch": int(engine._pending_last_epoch),
        }
        inc = None
        if engine._inc is not None:
            inc = {k: _to_host(v) for k, v in vars(engine._inc).items()}
        ckpt = cls(
            state_ssz=bytes(engine.state.encode_bytes()),
            dev=dev,
            pre_cols={k: np.array(v) for k, v in engine._pre_cols.items()},
            pre_mixes=(None if engine._pre_mixes is None
                       else np.array(engine._pre_mixes)),
            meta=meta,
            inc=inc,
        )
        ckpt.digest = ckpt.compute_digest()
        _obs_metrics.REGISTRY.counter("checkpoint_total", op="capture").inc()
        return ckpt

    # -- restore --------------------------------------------------------------

    def restore(self, spec):
        """Rebuild a ResidentEpochEngine equivalent to the captured one.

        Verifies the digest first; decodes the host state from SSZ; device
        arrays re-enter through jnp.array (jax-owned copies — the donation
        discipline from bridge.state_to_device_with_columns applies to a
        restore exactly as to a fresh bridge-in)."""
        with _obs_trace.span("checkpoint.restore"):
            return self._restore(spec)

    def _restore(self, spec):
        self.verify()
        _obs_metrics.REGISTRY.counter("checkpoint_total", op="restore").inc()
        fork = str(getattr(spec, "fork", ""))
        if self.meta.get("fork") and fork and self.meta["fork"] != fork:
            raise CheckpointIntegrityError(
                f"checkpoint captured under fork {self.meta['fork']!r}, "
                f"restore attempted with {fork!r}")
        import jax.numpy as jnp

        from ..engine.incremental_root import IncrementalStateRoot
        from ..engine.resident import ResidentEpochEngine, resident_step_fn_for
        from ..engine.state import EpochConfig, EpochState
        from . import retry as _retry

        state = spec.BeaconState.decode_bytes(self.state_ssz)
        eng = object.__new__(ResidentEpochEngine)
        eng.spec = spec
        eng.state = state
        eng.cfg = EpochConfig.from_spec(spec)
        eng.dev = EpochState(**{k: jnp.array(v) for k, v in self.dev.items()})
        eng._pre_cols = {k: np.array(v) for k, v in self.pre_cols.items()}
        eng._pre_mixes = (None if self.pre_mixes is None
                          else np.array(self.pre_mixes))
        eng._step = resident_step_fn_for(eng.cfg)
        eng._dirty = np.array(self.meta["dirty"], dtype=bool)
        eng._epochs_since_sync = int(self.meta["epochs_since_sync"])
        eng._pending_epochs = int(self.meta["pending_epochs"])
        eng._pending_last_epoch = int(self.meta["pending_last_epoch"])
        eng._pending = None
        eng._deferred_epochs = 0
        eng.retry_policy = _retry.DEVICE_POLICY
        eng._inc = None
        if self.inc is not None:
            inc = object.__new__(IncrementalStateRoot)
            inc.__dict__.update(
                {k: _to_dev(v, jnp) for k, v in self.inc.items()})
            eng._inc = inc
        return eng

    # -- disk format ----------------------------------------------------------

    def save(self, path) -> None:
        _obs_metrics.REGISTRY.counter("checkpoint_total", op="save").inc()
        arrays: dict = {}
        skel = _flatten(self._payload(), "", arrays)
        manifest = json.dumps({"format": FORMAT, "digest": self.digest,
                               "skeleton": skel}, sort_keys=True)
        np.savez_compressed(
            path,
            __manifest__=np.frombuffer(manifest.encode(), dtype=np.uint8),
            __state_ssz__=np.frombuffer(self.state_ssz, dtype=np.uint8),
            **{f"a{i}": arrays[k] for i, k in enumerate(sorted(arrays))},
        )

    @classmethod
    def load(cls, path) -> "EngineCheckpoint":
        with np.load(path, allow_pickle=False) as z:
            manifest = json.loads(bytes(z["__manifest__"]).decode())
            if manifest.get("format") != FORMAT:
                raise CheckpointIntegrityError(
                    f"not a {FORMAT} file: {manifest.get('format')!r}")
            state_ssz = bytes(z["__state_ssz__"])
            arrays_by_key: dict = {}
            keys: dict = {}

            def collect(skel):
                if isinstance(skel, dict):
                    if "$nd" in skel:
                        keys[skel["$nd"]] = None
                    else:
                        for v in (skel.get("$tuple") or skel.get("$list")
                                  or list(skel.get("$dict", {}).values())):
                            collect(v)

            collect(manifest["skeleton"])
            for i, k in enumerate(sorted(keys)):
                arrays_by_key[k] = np.array(z[f"a{i}"])
        payload = _unflatten(manifest["skeleton"], arrays_by_key)
        ckpt = cls(state_ssz=state_ssz, digest=manifest["digest"], **payload)
        ckpt.verify()
        _obs_metrics.REGISTRY.counter("checkpoint_total", op="load").inc()
        return ckpt
