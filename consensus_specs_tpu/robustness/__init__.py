"""Fault tolerance around the device boundary.

Four pieces (see each module's docstring):

  faults.py      seeded `FaultPlan` — deterministic injection at the real
                 seams (dispatch, aux readout, write-back staging, gossip
                 frames, deferred-BLS flush).
  retry.py       backoff-with-jitter policies + the retryable-vs-fatal
                 classification every seam shares.
  breaker.py     circuit breaker: device path → pure-Python degradation
                 after N consecutive failures, half-open probe to re-arm.
  checkpoint.py  epoch-boundary engine snapshots with an integrity digest;
                 `restore()` rebuilds the engine, two-phase write-back in
                 bridge._write_back keeps a crash from tearing the registry.

The whole package is jax-free at module level (tpulint import-layering:
`robustness/` is in the jax_free set) so the pure-host consumers —
crypto/bls.py, the gossip driver, tools/bench_probe.py — can import it
without dragging in a device runtime.
"""
from . import breaker, checkpoint, faults, retry  # noqa: F401
from .breaker import CircuitBreaker  # noqa: F401
from .checkpoint import CheckpointIntegrityError, EngineCheckpoint  # noqa: F401
from .faults import (  # noqa: F401
    CorruptAuxError,
    FatalFault,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    IntegrityError,
    TornWriteBackError,
    TransientFault,
)
from .retry import (  # noqa: F401
    DEVICE_POLICY,
    RetryPolicy,
    call_with_retry,
    is_device_failure,
    is_retryable,
)
