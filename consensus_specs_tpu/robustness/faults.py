"""Seeded, deterministic fault injection at the device-boundary seams.

The resident pipeline crosses six trust boundaries where real deployments
fail: the XLA dispatch (tunnel drops, preemptions), the EpochAux host
readout (torn or corrupted D2H copies), the registry write-back (a crash
mid-reconstruction), the gossip wire (truncated frames from a dying
peer), the verification scheduler's dispatch (`sched.dispatch` — the
seam every BLS/KZG/Merkle batch crosses in sched/scheduler.py), and the
attestation firehose's three stages (`firehose.ingest`,
`firehose.aggregate`, `firehose.flush` — the streaming
gossip→aggregate→flush pipeline in firehose/pipeline.py). The admission
plane adds two more (`frontdoor.admit`, `frontdoor.shed` — the QoS
front door in frontdoor/admission.py), so hostile-traffic chaos lanes
can fault the admission decision itself. A
`FaultPlan` injects failures at exactly those seams — the hooks live in
the PRODUCTION code paths (engine/bridge.py, engine/resident.py,
parallel/gossip_driver.py, crypto/bls.py, sched/scheduler.py,
firehose/pipeline.py), not in test mocks, so the chaos suite exercises
the same retry/validate/degrade machinery a live node runs.

Determinism: every site draws from its OWN `random.Random` stream keyed by
(plan seed, site name), so the fire schedule of one site is independent of
how often any other site is called. Two runs of the same workload under the
same plan fire identically; tests/test_chaos_epoch.py leans on this to
assert bit-identical state roots against a fault-free oracle.

jax-free at module level (tpulint import-layering: `robustness/` is in the
jax_free set): constructing a real `XlaRuntimeError` is deferred into the
raising function and falls back to `TransientFault` when jax is absent.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from random import Random
from typing import Optional

import numpy as np

from ..obs import flight as _flight
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace


# --- error taxonomy ----------------------------------------------------------


class FaultInjected(Exception):
    """Base class for injected failures (never raised by real code paths)."""


class TransientFault(FaultInjected):
    """An injected failure the retry layer is expected to absorb."""

    retryable = True


class FatalFault(FaultInjected):
    """An injected failure that must NOT be retried (models a hard crash —
    the kill-mid-write-back scenario)."""

    retryable = False


class IntegrityError(Exception):
    """Validation caught corrupted data crossing the device boundary.

    The device source is intact (corruption happens on the host copy), so
    re-reading is safe — hence retryable."""

    retryable = True


class CorruptAuxError(IntegrityError):
    """EpochAux host copy failed validation (dtype/shape/NaN)."""


class TornWriteBackError(IntegrityError):
    """A staged write-back column failed validation against the device
    array it was copied from."""


# --- plan --------------------------------------------------------------------


@dataclass(frozen=True)
class FaultSpec:
    """What one injection site does when it fires.

    kind        "raise" (fire() sites), "corrupt" (corrupt_array sites),
                "mangle" (mangle_bytes sites). A spec whose kind does not
                match the seam's call type never fires.
    rate        per-call fire probability, drawn from the site's own stream.
    at_calls    1-based call indices that always fire (exact schedules for
                tests like "kill on the 3rd staged column").
    max_fires   cap on total fires for the site (None = unlimited).
    exc         raise kind: "transient" | "fatal" | "xla" (a real
                XlaRuntimeError when jax is importable).
    corruption  "nan" | "truncate" for arrays; "truncate" | "garble" for
                byte payloads.
    """

    kind: str = "raise"
    rate: float = 0.0
    at_calls: tuple = ()
    max_fires: Optional[int] = None
    exc: str = "transient"
    corruption: str = "nan"


@dataclass(frozen=True)
class FaultEvent:
    site: str
    call_index: int
    action: str


class FaultPlan:
    """A seeded schedule of injected failures over named sites.

    Usage:
        plan = FaultPlan(seed=0xC0FFEE, sites={
            "engine.dispatch": FaultSpec(kind="raise", exc="xla", rate=0.3),
            "engine.aux_readout": FaultSpec(kind="corrupt", at_calls=(2,)),
        })
        with plan.active():
            ... run the workload ...
        plan.events  # what actually fired, in order

    Thread-safe: the gossip rx loops call in from their own threads.
    """

    def __init__(self, seed: int, sites: dict):
        self.seed = int(seed)
        self.sites = dict(sites)
        self.events: list[FaultEvent] = []
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {}
        self._fires: dict[str, int] = {}
        self._rngs = {site: Random(f"{self.seed}:{site}") for site in self.sites}

    def calls(self, site: str) -> int:
        return self._calls.get(site, 0)

    def fires(self, site: str) -> int:
        return self._fires.get(site, 0)

    def fired_sites(self) -> set:
        return {e.site for e in self.events}

    def _decide(self, site: str, kind: str):
        """Count the call; return (spec, call_index) when the site fires."""
        spec = self.sites.get(site)
        if spec is None or spec.kind != kind:
            return None, 0
        with self._lock:
            ix = self._calls.get(site, 0) + 1
            self._calls[site] = ix
            hit = ix in spec.at_calls
            if not hit and spec.rate > 0.0:
                # always draw so max_fires never shifts later indices
                draw = self._rngs[site].random() < spec.rate
                hit = draw
            if hit and spec.max_fires is not None \
                    and self._fires.get(site, 0) >= spec.max_fires:
                hit = False
            if hit:
                self._fires[site] = self._fires.get(site, 0) + 1
            return (spec if hit else None), ix

    def _log(self, site: str, ix: int, action: str) -> None:
        with self._lock:
            self.events.append(FaultEvent(site, ix, action))
        # Observability mirror: every fire is a counter tick (reconciled
        # 1:1 against plan.fires(site) by the chaos lane) and an attribute
        # on the innermost active span, so a trace shows WHERE each
        # injected failure landed, not just that one did.
        _obs_metrics.REGISTRY.counter("fault_fires_total", site=site).inc()
        _obs_trace.annotate(fault_sites=site)
        # ...and a flight-recorder event, so a black-box dump shows every
        # injected failure that preceded the trigger — reconciled 1:1
        # against plan.fires(site) by the chaos lane, same as the counter
        _flight.record("fault", site=site, call=ix, action=action)

    def install(self) -> "FaultPlan":
        global _PLAN
        _PLAN = self
        return self

    def uninstall(self) -> None:
        global _PLAN
        if _PLAN is self:
            _PLAN = None

    @contextmanager
    def active(self):
        self.install()
        try:
            yield self
        finally:
            self.uninstall()


_PLAN: Optional[FaultPlan] = None


def current_plan() -> Optional[FaultPlan]:
    return _PLAN


def uninstall() -> None:
    """Remove whatever plan is installed (test-teardown safety net)."""
    global _PLAN
    _PLAN = None


# --- seam entry points -------------------------------------------------------


def fire(site: str) -> None:
    """Raise-type seam: no-op unless the installed plan fires `site`."""
    plan = _PLAN
    if plan is None:
        return
    spec, ix = plan._decide(site, "raise")
    if spec is None:
        return
    plan._log(site, ix, f"raise:{spec.exc}")
    raise _make_exc(spec, site, ix)


def corrupt_array(site: str, arr):
    """Corrupt-type seam: return `arr` unchanged unless the site fires, in
    which case a structurally-broken copy comes back (dtype flipped to NaN
    floats, or the leading axis truncated) — the kind of damage a torn D2H
    copy produces and a structural validator can catch."""
    plan = _PLAN
    if plan is None:
        return arr
    spec, ix = plan._decide(site, "corrupt")
    if spec is None:
        return arr
    plan._log(site, ix, f"corrupt:{spec.corruption}")
    return _corrupt(np.asarray(arr), spec.corruption)


def mangle_bytes(site: str, data: bytes) -> bytes:
    """Byte-payload seam (gossip frames): truncate or garble the payload."""
    plan = _PLAN
    if plan is None:
        return data
    spec, ix = plan._decide(site, "mangle")
    if spec is None:
        return data
    plan._log(site, ix, f"mangle:{spec.corruption}")
    return _mangle(data, spec.corruption)


# --- failure construction ----------------------------------------------------


def _make_exc(spec: FaultSpec, site: str, ix: int) -> Exception:
    msg = f"injected {spec.exc} fault at {site} (call {ix})"
    if spec.exc == "fatal":
        return FatalFault(msg)
    if spec.exc == "xla":
        try:
            # Deferred so this module stays importable without jax; the
            # real type exercises the name-based classification in retry.py.
            from jax.errors import JaxRuntimeError
        except Exception:
            return TransientFault(msg)
        return JaxRuntimeError(f"INTERNAL: {msg}")
    return TransientFault(msg)


def _corrupt(arr: np.ndarray, kind: str):
    if kind == "truncate":
        if arr.ndim == 0 or arr.shape[0] == 0:
            return np.float64(np.nan)
        return np.array(arr[:-1])
    # "nan": same shape, dtype flipped to float64 — detectable structurally
    return np.full(arr.shape if arr.ndim else (), np.nan, dtype=np.float64)


def _mangle(data: bytes, kind: str) -> bytes:
    if not data:
        return data
    if kind == "garble":
        # blow up the snappy length preamble: declared size > MAX_MESSAGE_SIZE
        return bytes([data[0] | 0xF0, 0xFF, 0xFF, 0xFF]) + data[1:]
    return data[: len(data) // 2]
