"""The 3-isogeny E' -> E for BLS12-381 G2 hash-to-curve, derived at import.

RFC 9380's BLS12381G2_XMD:SHA-256_SSWU_RO_ suite maps SSWU outputs on the
auxiliary curve E': y^2 = x^3 + 240i·x + 1012(1+i) through a degree-3 isogeny
onto E: y^2 = x^3 + 4(1+i). The RFC publishes the isogeny's rational-map
coefficients as opaque hex; this module instead DERIVES the map with Velu's
formulas and proves at import that the result is the right one:

  1. The kernel: an order-3 subgroup {O, ±Q} of E' whose x-coordinate x0 lies
     in Fp2 — a root of the 3-division polynomial
     psi_3(x) = 3x^4 + 6A'x^2 + 12B'x - A'^2 (found by gcd with x^(p^2) - x
     and factoring, i.e. plain Cantor-Zassenhaus over Fp2).
  2. Velu (odd-degree, kernel pair counted once): t = 6x0^2 + 2a,
     u = 4(x0^3 + a·x0 + b), w = u + x0·t; codomain y^2 = x^3 + (a-5t)x +
     (b-7w); normalized map
        phi_x = x + t/(x - x0) + u/(x - x0)^2
        phi_y = y · d(phi_x)/dx = y·(1 - t/(x - x0)^2 - 2u/(x - x0)^3).
  3. Which map is THE map: E' is itself the Velu codomain of
     psi: E -> E' with kernel x_psi = the cube root of -4b_E for which the
     codomain coefficients come out as (240i, 1012(1+i)) exactly — that is
     how these constants arise. The published E' -> E map is the DUAL
     psi-hat, pinned uniquely by psi-hat ∘ psi = [3]_E: we build the
     normalized Velu lambda: E' -> E'' from the dual kernel
     (x-coordinate psi_x(0), the image of E[3]'s x=0 subgroup), then find
     the isomorphism iota: E'' -> E ((x,y) -> (u^2 x, u^3 y), u in Fp2)
     such that iota ∘ lambda ∘ psi = [-3] on sample points (the RFC's
     published map composes with psi to MINUS 3 — verified against the RFC
     9380 J.10.1 test vectors; [+3] gives the same x-map with negated y).
     Exactly one of the six u candidates satisfies the identity.
  4. Proof obligations asserted at import: psi codomain == E' exactly;
     dual identity on random points; image points on E; homomorphism;
     kernel annihilation.

Reference parity: the reference gets this map from py_ecc==5.2.0
(setup.py:1014) — vendored constants; here it is a 60-line derivation with
machine-checked correctness.
"""
from __future__ import annotations

from .bls12_381 import (
    F2_ONE, F2_ZERO, FP2_FIELD, P, f2_add, f2_inv, f2_mul, f2_neg, f2_pow,
    f2_sqr, f2_sub, pt_add, pt_from_affine, pt_to_affine,
)

A_ISO = (0, 240)
B_ISO = (1012, 1012)
B_E = (4, 4)  # E: y^2 = x^3 + 4(1+i)


# --- minimal polynomial arithmetic over Fp2 (dense coeff lists, low->high) --


def _pmod(a, m):
    a = list(a)
    dm = len(m) - 1
    inv_lead = f2_inv(m[-1])
    while len(a) - 1 >= dm:
        if a[-1] == F2_ZERO:
            a.pop()
            continue
        c = f2_mul(a[-1], inv_lead)
        shift = len(a) - 1 - dm
        for i, mc in enumerate(m):
            a[shift + i] = f2_sub(a[shift + i], f2_mul(c, mc))
        a.pop()
    return a or [F2_ZERO]


def _pmulmod(a, b, m):
    out = [F2_ZERO] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai == F2_ZERO:
            continue
        for j, bj in enumerate(b):
            out[i + j] = f2_add(out[i + j], f2_mul(ai, bj))
    return _pmod(out, m)


def _ppowmod(a, e, m):
    r = [F2_ONE]
    b = _pmod(a, m)
    while e:
        if e & 1:
            r = _pmulmod(r, b, m)
        b = _pmulmod(b, b, m)
        e >>= 1
    return r


def _trim(a):
    a = list(a)
    while len(a) > 1 and a[-1] == F2_ZERO:
        a.pop()
    return a


def _pgcd(a, b):
    a, b = _trim(a), _trim(b)
    while any(c != F2_ZERO for c in b):
        a = _pmod(a, b)
        a, b = _trim(b), _trim(a)
    # normalize monic
    while len(a) > 1 and a[-1] == F2_ZERO:
        a.pop()
    if a[-1] != F2_ONE:
        inv = f2_inv(a[-1])
        a = [f2_mul(c, inv) for c in a]
    return a


def _fp2_roots(poly) -> list:
    """All Fp2 roots of poly (dense Fp2 coeffs), via x^(p^2)-x gcd + CZ."""
    xq = _ppowmod([F2_ZERO, F2_ONE], P * P, poly)
    xq_minus_x = [f2_sub(a, b) for a, b in zip(
        xq + [F2_ZERO] * (len(poly) - len(xq)),
        [F2_ZERO, F2_ONE] + [F2_ZERO] * (len(poly) - 2))]
    g = _pgcd(poly, xq_minus_x)

    roots = []

    def split(h, salt):
        if len(h) == 1:
            return
        if len(h) == 2:  # x + c -> root -c
            roots.append(f2_neg(h[0]))
            return
        # Cantor-Zassenhaus: gcd((x + s)^((p^2-1)/2) - 1, h)
        s = (salt * 7919 % P, salt * 104729 % P)
        r = _ppowmod([s, F2_ONE], (P * P - 1) // 2, h)
        r = list(r)
        r[0] = f2_sub(r[0], F2_ONE)
        d = _pgcd(h, r)
        if len(d) == 1 or len(d) == len(h):
            split(h, salt + 1)
            return
        split(d, salt + 1)
        q = _poly_div_exact(h, d)
        split(q, salt + 1)

    split(g, 1)
    return roots


def _poly_div_exact(a, d):
    a = list(a)
    out = [F2_ZERO] * (len(a) - len(d) + 1)
    inv_lead = f2_inv(d[-1])
    for k in range(len(out) - 1, -1, -1):
        c = f2_mul(a[k + len(d) - 1], inv_lead)
        out[k] = c
        for i, dc in enumerate(d):
            a[k + i] = f2_sub(a[k + i], f2_mul(c, dc))
    assert all(c == F2_ZERO for c in a[: len(d) - 1] + a[len(d):][len(out):]), "not exact"
    return out


# --- Velu derivation of the 3-isogeny ---------------------------------------


def _g_iso(x):
    return f2_add(f2_add(f2_mul(f2_sqr(x), x), f2_mul(A_ISO, x)), B_ISO)


def _velu3(a_coef, b_coef, x0):
    """(t, u, A2, B2): Velu data for the order-3 kernel at x0 on
    y^2 = x^3 + a x + b."""
    gx0 = f2_add(f2_add(f2_mul(f2_sqr(x0), x0), f2_mul(a_coef, x0)), b_coef)
    t = f2_add(f2_mul((6, 0), f2_sqr(x0)), f2_mul((2, 0), a_coef))
    u = f2_mul((4, 0), gx0)
    w = f2_add(u, f2_mul(x0, t))
    a2 = f2_sub(a_coef, f2_mul((5, 0), t))
    b2 = f2_sub(b_coef, f2_mul((7, 0), w))
    return t, u, a2, b2


def _velu_eval(x0, t, u, aff):
    """Evaluate the normalized Velu map at an affine point (None past kernel)."""
    if aff is None:
        return None
    x, y = aff
    d = f2_sub(x, x0)
    if d == F2_ZERO:
        return None  # kernel
    dinv = f2_inv(d)
    dinv2 = f2_sqr(dinv)
    dinv3 = f2_mul(dinv2, dinv)
    xo = f2_add(x, f2_add(f2_mul(t, dinv), f2_mul(u, dinv2)))
    yo = f2_mul(
        y,
        f2_sub(f2_sub(F2_ONE, f2_mul(t, dinv2)), f2_mul(f2_add(u, u), dinv3)),
    )
    return (xo, yo)


def _cube_roots(w):
    """All cube roots of w in Fp2 (possibly empty)."""
    n = P * P - 1
    v, m = 0, n
    while m % 3 == 0:
        v += 1
        m //= 3
    if f2_pow(w, n // 3) != F2_ONE:
        return []
    # deterministic non-cube to generate the 3-Sylow subgroup
    g = (2, 1)
    while f2_pow(f2_pow(g, m), 3 ** (v - 1)) == F2_ONE:
        g = f2_add(g, F2_ONE)
    h = f2_pow(g, m)
    r0 = f2_pow(w, pow(3, -1, m))
    out = []
    for k in range(3**v):
        cand = f2_mul(r0, f2_pow(h, k))
        if f2_mul(f2_sqr(cand), cand) == w and cand not in out:
            out.append(cand)
    return out


def _sample_point_e(seed=(11, 3)):
    from .bls12_381 import f2_sqrt

    x = seed
    while True:
        y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), B_E))
        if y is not None:
            return (x, y)
        x = f2_add(x, F2_ONE)


def _derive():
    from .bls12_381 import f2_sqrt

    # 1. psi: E -> E' — the kernel is the cube root of -4·B_E whose Velu
    #    codomain is EXACTLY (A_ISO, B_ISO).
    psi_data = None
    for c in _cube_roots(f2_neg(f2_mul((4, 0), B_E))):
        t, u, a2, b2 = _velu3(F2_ZERO, B_E, c)
        if a2 == A_ISO and b2 == B_ISO:
            psi_data = (c, t, u)
    assert psi_data is not None, "no kernel of E maps to the RFC iso curve E'"
    c, t_psi, u_psi = psi_data

    # 2. dual kernel on E': the image of E[3]'s x=0 subgroup under psi
    x0d = _velu_eval(c, t_psi, u_psi, (F2_ZERO, F2_ONE))[0]  # y unused by x-map
    t, u, a2, b2 = _velu3(A_ISO, B_ISO, x0d)
    assert a2 == F2_ZERO, "dual codomain not of j=0 shape"

    # 3. iota: E'' -> E with u6^6 = B_E / b2; pick the u making
    #    iota(lambda(psi(P))) == [3]P
    ratio = f2_mul(B_E, f2_inv(b2))
    sixth = []
    for sq in _cube_roots(ratio):
        r = f2_sqrt(sq)
        if r is not None:
            sixth.extend([r, f2_neg(r)])
    assert sixth, "B_E/B'' is not a sixth power — unexpected"

    F = FP2_FIELD
    sample = _sample_point_e()
    m3 = pt_to_affine(F, pt_mul_small(sample, 3))
    minus_three_p = (m3[0], f2_neg(m3[1]))
    chosen = None
    for u6 in sixth:
        u2 = f2_sqr(u6)
        u3 = f2_mul(u2, u6)
        img = _velu_eval(x0d, t, u, _velu_eval(c, t_psi, u_psi, sample))
        cand = (f2_mul(u2, img[0]), f2_mul(u3, img[1]))
        if cand == minus_three_p:
            assert chosen is None, "two u candidates satisfy the dual identity"
            chosen = (u2, u3)
    assert chosen is not None, "no isomorphism satisfies psi-hat o psi == [-3]"
    return x0d, t, u, chosen[0], chosen[1]


def pt_mul_small(aff, k):
    from .bls12_381 import pt_from_affine as _pfa

    F = FP2_FIELD
    acc = None
    j = _pfa(F, aff)
    for _ in range(k):
        acc = pt_add(F, acc, j)
    return acc


_X0, _T, _U, _U2, _U3 = _derive()


def iso3_map(aff):
    """Evaluate the RFC 3-isogeny E' -> E (the dual of psi: E -> E',
    iota-scaled onto E exactly). None = O; kernel points also map to O."""
    img = _velu_eval(_X0, _T, _U, aff)
    if img is None:
        return None
    return (f2_mul(_U2, img[0]), f2_mul(_U3, img[1]))


ISO3_MAP = iso3_map


# --- import-time proof obligations ------------------------------------------


def _on_e(aff) -> bool:
    x, y = aff
    return f2_sqr(y) == f2_add(f2_mul(f2_sqr(x), x), B_E)


def _self_check():
    from .bls12_381 import f2_sqrt

    # deterministic sample points on E' (try-and-increment)
    pts = []
    x = (3, 1)
    while len(pts) < 4:
        gx = _g_iso(x)
        y = f2_sqrt(gx)
        if y is not None:
            pts.append((x, y))
        x = f2_add(x, F2_ONE)

    for pt in pts:
        img = iso3_map(pt)
        assert img is not None and _on_e(img), "isogeny image off E"

    # homomorphism: phi(P + Q) == phi(P) + phi(Q)
    F = FP2_FIELD
    p_, q_ = pts[0], pts[1]
    lhs = iso3_map(pt_to_affine(F, pt_add(F, pt_from_affine(F, p_), pt_from_affine(F, q_))))
    rhs = pt_to_affine(
        F, pt_add(F, pt_from_affine(F, iso3_map(p_)), pt_from_affine(F, iso3_map(q_)))
    )
    assert lhs == rhs, "isogeny is not a homomorphism"

    # kernel annihilation: (x0, y0) has order 3 and maps to O; also check the
    # kernel x0 really is a 3-torsion x-coordinate on E' (psi3(x0) == 0 was
    # the derivation; verify via the group law when y0 is Fp2-rational)
    y0 = f2_sqrt(_g_iso(_X0))
    if y0 is not None:
        Q = pt_from_affine(F, (_X0, y0))
        dbl = pt_to_affine(F, pt_add(F, Q, Q))
        assert dbl == (_X0, f2_neg(y0)), "kernel point not order 3"
        assert iso3_map((_X0, y0)) is None


_self_check()
