"""Batched KZG verification on the device pairing kernels.

BASELINE config 5's shape: one block carries up to 128 data-blob
commitments (sharding mainnet preset) and each needs its sample/degree
proofs checked. Per-item `verify_coset` (crypto/kzg.py:226) is one
2-pairing check — 256 pairings per block. This module folds N checks into
ONE 2-pairing check plus batched G1 scalar-multiplication ladders, all on
device, via two identities:

1. **Bilinearity moves the vanishing-poly scalar to the G1 side.** The
   per-item equation  e(proof, [s^m − zm]G2) == e(C − I, G2)  (zm =
   shift^m) becomes

       e(proof, [s^m]G2) · e(−zm·proof − C + I, G2) == 1

   — the G2 inputs are now ITEM-INDEPENDENT (setup powers and the
   generator), which is what makes cross-item folding possible without
   any G2 arithmetic.

2. **Schwartz–Zippel random linear combination.** With host-drawn random
   r_i, all N equations hold iff (soundness error 2^-64):

       e(Σ r_i·proof_i, [s^m]G2)
         · e(Σ r_i·(−zm_i·proof_i − C_i) + I*, G2) == 1

   where I* = commit(Σ r_i·i_coeffs_i) folds the N interpolant
   commitments into ONE m-term MSM in coefficient space.

Device work: two Pippenger bucket-MSMs (ops/bls12_jax.g1_msm_pippenger —
64-bit windows for the r_i side, 255-bit for the folded side; digit-
gathered bucket multiples + one masked window tree instead of the
per-item double-and-add ladder this module used through PR 10), one
2-pairing check. Host work per item: an m-point interpolation
(m = POINTS_PER_SAMPLE = 8) and two scalar muls mod r — microseconds.

Degree proofs (`verify_degree_proof`, kzg.py:173) batch the same way:
e(Σ r_i·D_i, G2) · e(Σ r_i·(−C_i), [s^(M+1−k)]G2) == 1 for a shared
points-count k.

Reference parity: the reference's DAS/sharding spec verifies each
commitment with py_ecc one pairing at a time
(/root/reference/specs/sharding/polynomial-commitments.md verify_* over
py_ecc); there is no reference batch path — this is TPU-first capability.
"""
from __future__ import annotations

import secrets

import numpy as np

from . import bls12_381 as oracle
from . import kzg
from .bls12_381 import FP_FIELD, P, pt_to_affine
from .kzg import MODULUS, KZGSetup

_SOUND_BITS = 64


def _rand_scalars(n: int) -> list[int]:
    return [secrets.randbelow(2**_SOUND_BITS - 1) + 1 for _ in range(n)]


def _aff(p):
    """Oracle point (Jacobian or affine) -> affine int pair (or None)."""
    if p is None:
        return None
    if isinstance(p, tuple) and len(p) == 2 and isinstance(p[0], int):
        return p
    return pt_to_affine(FP_FIELD, p)


def _neg(aff):
    return (aff[0], (P - aff[1]) % P)


def _device_msm(points_aff: list, scalars: list[int], nbits: int):
    """Σ scalar_i·P_i on device via the Pippenger bucket-MSM
    (ops/bls12_jax.g1_msm_device): pow2-bucketed item count, w-bit window
    digits gathered from per-item bucket tables, one masked window tree +
    Horner combine. Returns an affine oracle pair, or None for the
    identity. Replaces the PR-4 per-item 255-bit double-and-add ladder —
    ~5x fewer batched point ops at the 128-blob shape (see
    g1_msm_op_counts vs g1_ladder_op_counts)."""
    from ..ops import bls12_jax as K

    return K.g1_msm_device(points_aff, scalars, nbits)


def _host_msm(points_aff: list, scalars: list[int]):
    pts = [oracle.pt_from_affine(FP_FIELD, p) for p in points_aff]
    acc = kzg._msm(FP_FIELD, pts, scalars)
    return None if acc is None else pt_to_affine(FP_FIELD, acc)


def _check_two_pairings(p1, q2_point, p2) -> bool:
    """e(p1, q2_point) · e(p2, G2) == 1 — one device 2-pairing launch
    (falls back to the host oracle when either G1 input degenerated to the
    identity, which the device affine path cannot represent)."""
    if p1 is None or p2 is None:
        return kzg._pairings_equal(
            None if p1 is None else oracle.pt_from_affine(FP_FIELD, p1),
            q2_point,
            None if p2 is None else oracle.pt_from_affine(FP_FIELD, _neg(p2)),
            oracle.G2_GEN,
        )
    import jax

    from ..ops import bls12_jax as K
    from .bls_jax import _pack_pairing_args

    q1 = pt_to_affine(oracle.FP2_FIELD, q2_point) if not _is_aff_g2(q2_point) else q2_point
    _, args = _pack_pairing_args([p1], [q1], [p2], [oracle.G2_GEN_AFF])
    ok = K.pairing_check_batch(*args)
    return bool(np.asarray(jax.device_get(ok))[0])


def _is_aff_g2(p) -> bool:
    return (
        isinstance(p, tuple) and len(p) == 2
        and isinstance(p[0], tuple) and len(p[0]) == 2 and isinstance(p[0][0], int)
    )


def batch_verify_samples(setup: KZGSetup, items, use_device: bool = True) -> bool:
    """ALL of `items` verify, where each item is (commitment, coset_shift,
    ys, proof) exactly as `verify_coset` takes them — commitment/proof as
    oracle points (Jacobian or affine). Single randomized check; callers
    needing per-item attribution fall back to `verify_coset` on failure.

    Rejections mirror verify_coset's hostile-input stance: empty/odd ys,
    m beyond the setup, or an identity/malformed proof point reject the
    batch (never crash).

    Served through the unified verification scheduler (sched/): one
    request = one whole randomized check, so the all-or-nothing soundness
    contract is untouched while the dispatch seam adds the shared retry /
    breaker / metrics wiring and a degraded host-MSM fallback."""
    from .. import sched as _sched

    sch = _sched.default_scheduler()
    h = sch.submit(_sched.Request(
        work_class="kzg", kind="verify_samples",
        payload=(setup, tuple(items), use_device)))
    return bool(h.result())


def _verify_samples_impl(setup: KZGSetup, items, use_device: bool = True) -> bool:
    items = list(items)
    if not items:
        return True
    m = len(items[0][2])
    if m == 0 or m & (m - 1) != 0 or m > setup.max_degree:
        return False
    rs = _rand_scalars(len(items))
    folded = [0] * m
    p1_pts, p1_sc = [], []  # Σ r_i·proof_i            (64-bit scalars)
    p2_pts, p2_sc = [], []  # Σ r_i(−zm_i·proof_i − C_i) + I*   (255-bit)
    for (commitment, shift, ys, proof), r in zip(items, rs):
        if len(ys) != m or any(not 0 <= y < MODULUS for y in ys):
            return False
        c_aff, pr_aff = _aff(commitment), _aff(proof)
        if c_aff is None or pr_aff is None:
            return False
        zm = pow(shift % MODULUS, m, MODULUS)
        if zm == 0:
            return False
        for j, c in enumerate(kzg.interpolate_on_domain(ys, shift=shift)):
            folded[j] = (folded[j] + r * c) % MODULUS
        p1_pts.append(pr_aff)
        p1_sc.append(r)
        p2_pts.append(_neg(pr_aff))
        p2_sc.append(r * zm % MODULUS)
        p2_pts.append(_neg(c_aff))
        p2_sc.append(r)
    for j in range(m):
        if folded[j]:
            p2_pts.append(_aff(setup.g1[j]))
            p2_sc.append(folded[j])
    msm = _device_msm if use_device else (lambda p, s, nbits: _host_msm(p, s))
    a = msm(p1_pts, p1_sc, nbits=_SOUND_BITS)
    b = msm(p2_pts, p2_sc, nbits=255)
    return _check_two_pairings(a, setup.g2[m], b)


def batch_verify_degree_proofs(
    setup: KZGSetup, items, points_count: int, use_device: bool = True
) -> bool:
    """ALL of `items` = (commitment, degree_proof) satisfy the degree bound
    `deg < points_count` (verify_degree_proof, one shared randomized check):

        e(Σ r_i·D_i, G2) · e(Σ r_i·(−C_i), [s^(M+1−k)]G2) == 1

    Served through the unified verification scheduler like
    batch_verify_samples above.
    """
    from .. import sched as _sched

    sch = _sched.default_scheduler()
    h = sch.submit(_sched.Request(
        work_class="kzg", kind="verify_degree_proofs",
        payload=(setup, tuple(items), points_count, use_device)))
    return bool(h.result())


def _verify_degree_proofs_impl(
    setup: KZGSetup, items, points_count: int, use_device: bool = True
) -> bool:
    items = list(items)
    if not items:
        return True
    k = points_count
    if not 0 < k <= setup.max_degree + 1:
        return False
    rs = _rand_scalars(len(items))
    d_pts, c_pts = [], []
    for (commitment, degree_proof), _r in zip(items, rs):
        c_aff, d_aff = _aff(commitment), _aff(degree_proof)
        if c_aff is None or d_aff is None:
            return False
        d_pts.append(d_aff)
        c_pts.append(_neg(c_aff))
    msm = _device_msm if use_device else (lambda p, s, nbits: _host_msm(p, s))
    a = msm(d_pts, rs, nbits=_SOUND_BITS)
    b = msm(c_pts, rs, nbits=_SOUND_BITS)
    # e(A, G2) · e(B, [s^shift]G2) == 1, with the shared-G2 roles swapped
    # into the two-pairing helper's fixed shape: e(B', q2)·e(A', G2)
    return _check_two_pairings(b, setup.g2[setup.max_degree + 1 - k], a)


def verify_samples_attributed(setup: KZGSetup, items, use_device: bool = True):
    """Production entry point: batch first, per-item attribution on failure.

    `batch_verify_samples` is deliberately stricter than N `verify_coset`
    calls — an identity proof (legitimate when deg P < m), coset_shift = 0,
    or mixed sample sizes reject the whole batch. A block importer must not
    drop valid samples over that, so on ANY batch failure this re-checks
    each item with the per-item oracle (`kzg.verify_coset`) and returns the
    authoritative per-item verdicts. Returns (all_ok, verdicts) where
    verdicts is None on the batch fast path (all true by construction).
    """
    items = list(items)
    if batch_verify_samples(setup, items, use_device=use_device):
        return True, None
    verdicts = [
        kzg.verify_coset(setup, commitment, shift, ys, proof)
        for commitment, shift, ys, proof in items
    ]
    return all(verdicts), verdicts
