"""Device-batched BLS verification: the bridge between the BLS shim and the
TPU pairing kernels.

Reference parity: the role milagro plays behind eth2spec/utils/bls.py
(:17-22 use_milagro — the fast backend CI and all vector generation run on).
Here the fast backend is ops/bls12_jax.py's batched pairing over the RNS
field (ops/fp_rns.py), and the unit of work is a BATCH of signature checks:
one `pairing_check_batch` launch verifies every queued (pubkey, message,
signature) triple of a block/epoch at once (SURVEY.md §7 deferred-batch
stance).

Host side (this module): decompression, hash-to-curve, G1 aggregation for
FastAggregateVerify, padding to bucketed batch shapes (so jit caches stay
small), and the bool readout. Device side: two Miller loops + shared final
exponentiation per item.
"""
from __future__ import annotations

import os
from collections.abc import Mapping
from functools import lru_cache

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from ..sched import bucketing as _bucketing
from . import bls12_381 as oracle
from .hash_to_curve import hash_to_curve_g2 as _hash_to_curve_g2_uncached
from .bls12_381 import g2_from_bytes as _g2_from_bytes_uncached


# The flush's per-check host prep is dominated by two pure functions, both
# heavily repeated in real workloads: messages recur across the aggregates
# of a slot/epoch (same signing root per committee target) and benchmarks
# replay identical attestation sets, while signature bytes recur whenever
# the same aggregate is re-verified (gossip + block import). Same caching
# stance as g1_from_bytes below; entries are a few KB -> both caps stay
# in the tens of MB.
@lru_cache(maxsize=1 << 13)
def hash_to_curve_g2(msg: bytes):
    return _hash_to_curve_g2_uncached(msg)


@lru_cache(maxsize=1 << 13)
def g2_from_bytes(data: bytes):
    return _g2_from_bytes_uncached(data)


# Cache sizing: each entry holds the 48 compressed bytes plus an affine
# point (two ~381-bit ints, ~0.5 KB with dict overhead), so a full cache
# is ~0.5 GB at the 2^20 default — sized for a 1M-validator registry where
# every pubkey recurs each epoch. Override for memory-constrained hosts
# via CONSENSUS_TPU_PUBKEY_CACHE (power-of-two entry count); the cache is
# keyed on raw bytes so shrinking it only costs re-decompression.
_PUBKEY_CACHE_SIZE = int(os.environ.get("CONSENSUS_TPU_PUBKEY_CACHE", 1 << 20))


@lru_cache(maxsize=_PUBKEY_CACHE_SIZE)
def g1_from_bytes(data: bytes):
    """Memoized validated G1 decompression. A node sees the same validator
    pubkeys every epoch, and the r-subgroup check (a 255-bit scalar
    multiplication) dominates decompression cost — so cache by the 48
    compressed bytes, exactly as reference clients cache deserialized
    pubkeys behind milagro. Invalid encodings raise and are NOT cached
    (lru_cache does not memoize raising calls): they are attacker-supplied
    and mostly fail cheaply before the subgroup check."""
    return oracle.g1_from_bytes(data)

# known-valid padding item: e(G1, G2) * e(-G1, G2) == 1
_G1 = oracle.G1_GEN_AFF
_NEG_G1 = (_G1[0], (-_G1[1]) % oracle.P)
_G2 = oracle.G2_GEN_AFF

_MIN_BATCH = 8
# batches at least this big use the shared-final-exponentiation randomized
# check first (one final exp for the whole batch); only a failing batch pays
# the per-item pass for attribution
RLC_MIN_BATCH = 16


def _bucket(n: int) -> int:
    return _bucketing.pow2_bucket(n, _MIN_BATCH)


def _device_check(p1s, q1s, p2s, q2s) -> np.ndarray:
    """e(p1_i, q1_i) * e(p2_i, q2_i) == 1 per item; affine int coords in,
    bool array out. Pads to the next power-of-two bucket."""
    import jax

    from ..ops import bls12_jax as K

    n = len(p1s)
    _, args = _pack_pairing_args(p1s, q1s, p2s, q2s)
    ok = K.pairing_check_batch(*args)
    return np.asarray(jax.device_get(ok))[:n]


class QueuedCheck:
    """One deferred signature check, normalized to the two-pairing form."""

    __slots__ = ("p1", "q1", "p2", "q2")

    def __init__(self, p1, q1, p2, q2):
        self.p1, self.q1, self.p2, self.q2 = p1, q1, p2, q2


def _decompress_inputs(pubkey: bytes, message: bytes, signature: bytes):
    """(pk_aff, H(m)_aff, sig_aff) or None if any input is invalid."""
    try:
        pk = g1_from_bytes(bytes(pubkey))
        sig = g2_from_bytes(bytes(signature))
    except ValueError:
        return None
    if pk is None or sig is None:  # point at infinity is never valid here
        return None
    hm = hash_to_curve_g2(bytes(message))
    return pk, hm, sig


def make_verify_check(pubkey, message, signature) -> QueuedCheck | None:
    """Verify(pk, m, sig) as a QueuedCheck (None = statically invalid)."""
    dec = _decompress_inputs(pubkey, message, signature)
    if dec is None:
        return None
    pk, hm, sig = dec
    return QueuedCheck(pk, hm, _NEG_G1, sig)


# Memoized committee-pubkey aggregation, keyed by sha256 of the
# concatenated compressed keys: only a 32-byte digest plus the affine
# result is retained per entry (keying an lru_cache on the pubkey tuple
# itself would pin ~45 KB of key objects per mainnet sync committee).
# The same committee aggregates on every re-verification of its
# attestations (gossip then block import; benchmark warm-up then measured
# run), and ~128 host point-adds per check otherwise dominate flush prep.
_AGG_CACHE: dict = {}
_AGG_CACHE_MAX = 1 << 12

# Device-validated pubkeys: compressed bytes -> affine pair, populated by
# the batched device subgroup check in _aggregate_pubkeys_device_impl.
# Kept separate from the g1_from_bytes lru_cache because an lru_cache can
# only be filled by the wrapped call — and that call is exactly the host
# 255-bit pt_mul this lane exists to avoid. Bounded FIFO; entries are the
# same ~0.5 KB as g1_from_bytes's.
_PK_VALIDATED: dict = {}
_PK_VALIDATED_MAX = 1 << 16


def _aggregate_pubkeys_affine(pubkeys_bytes: list):
    """Affine sum of compressed pubkeys (None for an infinity sum);
    raises ValueError on an invalid encoding (never cached)."""
    import hashlib

    key = hashlib.sha256(b"".join(pubkeys_bytes)).digest()
    # LRU, not FIFO: refresh a hit so a hot committee aggregate inserted
    # early outlives cold entries (re-insertion moves it to the dict's
    # end). pop(key, None) keeps this race-safe against a concurrent hit
    # or clear_caches() — a lost entry just recomputes below.
    hit = _AGG_CACHE.pop(key, None)
    if hit is not None:
        _AGG_CACHE[key] = hit
        return hit
    if len(pubkeys_bytes) >= DEVICE_AGGREGATE_MIN:
        marker = _aggregate_pubkeys_sched(pubkeys_bytes)
        if marker is not None:
            if marker[0] == "bad_encoding":
                raise ValueError(marker[1])
            if marker[0] in ("inf_member", "inf"):
                return None  # invalid/degenerate input: never cached
            agg = (marker[1], marker[2])
            if len(_AGG_CACHE) >= _AGG_CACHE_MAX:
                _AGG_CACHE.pop(next(iter(_AGG_CACHE)))
            _AGG_CACHE[key] = agg
            return agg
    acc = None
    for pk in pubkeys_bytes:
        aff = g1_from_bytes(pk)
        if aff is None:
            return None  # infinity pubkey: invalid input, don't cache
        pt = oracle.pt_from_affine(oracle.FP_FIELD, aff)
        acc = pt if acc is None else oracle.pt_add(oracle.FP_FIELD, acc, pt)
    agg = oracle.pt_to_affine(oracle.FP_FIELD, acc)
    if len(_AGG_CACHE) >= _AGG_CACHE_MAX:
        _AGG_CACHE.pop(next(iter(_AGG_CACHE)))
    _AGG_CACHE[key] = agg
    return agg


def _aggregate_pubkeys_sched(pubkeys_bytes: list):
    """Submit one committee aggregate to the sched "msm" work class and
    return its marker tuple, or None when the lane is unavailable (the
    class is not registered on the default scheduler — e.g. a test
    scheduler built from a trimmed class list). Nested submits are safe:
    the scheduler's lock is re-entrant, so this works from inside a BLS
    flush that is itself being served through sched."""
    from .. import sched as _sched

    sch = _sched.default_scheduler()
    if "msm" not in sch.classes:
        return None
    h = sch.submit(_sched.Request(
        work_class="msm", kind="aggregate", payload=tuple(pubkeys_bytes)))
    return h.result()


def _aggregate_pubkeys_device_impl(pubkeys_bytes: list):
    """Device committee aggregation — the "aggregate" kind behind the sched
    msm class. Returns a marker tuple instead of raising, so the scheduler
    seam can carry the outcome through its object-dtype result rows:

        ("point", x, y)        affine aggregate (ints mod p)
        ("inf",)               the sum is the identity
        ("inf_member",)        an infinity pubkey appeared (invalid input)
        ("bad_encoding", msg)  decompression / subgroup rejection

    Keys never seen before decompress WITHOUT the host 255-bit subgroup
    pt_mul (bls12_381.py:590) and are validated in ONE batched device
    ladder ([r]P == inf via g1_subgroup_check_device) — the firehose cold
    lane's dominant cost (one ~4 ms host check per member, ~2.7 s per
    488-member committee) collapses to a single bucketed kernel launch.
    The sum itself is the all-ones-scalar MSM degenerate case: a plain
    masked reduction tree (g1_aggregate_device), no windows needed."""
    from ..ops import bls12_jax as K

    reg = _obs_metrics.REGISTRY
    affs: list = []
    cold_idx: list = []
    try:
        for i, pk in enumerate(pubkeys_bytes):
            pk = bytes(pk)
            hit = _PK_VALIDATED.get(pk)
            if hit is not None:
                affs.append(hit)
                continue
            aff = oracle.g1_from_bytes(pk, subgroup_check=False)
            if aff is None:
                return ("inf_member",)
            affs.append(aff)
            cold_idx.append(i)
    except ValueError as e:
        return ("bad_encoding", str(e))
    if cold_idx:
        ok = K.g1_subgroup_check_device([affs[i] for i in cold_idx])
        if not bool(ok.all()):
            return ("bad_encoding", "G1 point not in r-subgroup")
        for i in cold_idx:
            if len(_PK_VALIDATED) >= _PK_VALIDATED_MAX:
                _PK_VALIDATED.pop(next(iter(_PK_VALIDATED)))
            _PK_VALIDATED[bytes(pubkeys_bytes[i])] = affs[i]
        reg.counter("bls_pubkey_subgroup_device_total").inc(len(cold_idx))
    total = K.g1_aggregate_device(affs)
    reg.counter("bls_pubkey_aggregate_device_total").inc()
    reg.counter("bls_pubkey_aggregate_device_keys_total").inc(len(affs))
    if total is None:
        return ("inf",)
    return ("point", total[0], total[1])


def make_fast_aggregate_check(pubkeys, message, signature) -> QueuedCheck | None:
    """FastAggregateVerify: aggregate the pubkeys on host, then one check."""
    if len(pubkeys) == 0:
        return None
    try:
        agg = _aggregate_pubkeys_affine([bytes(pk) for pk in pubkeys])
    except ValueError:
        return None
    if agg is None:
        return None
    try:
        sig = g2_from_bytes(bytes(signature))
    except ValueError:
        return None
    if sig is None:
        return None
    hm = hash_to_curve_g2(bytes(message))
    return QueuedCheck(agg, hm, _NEG_G1, sig)


def random_zbits(n: int):
    """(n, 64) bool device array of host-drawn nonzero 64-bit scalars — the
    randomness input of pairing_check_rlc (single shared packing helper)."""
    import secrets

    import jax.numpy as jnp
    import numpy as np

    zs = [secrets.randbelow(2**64 - 1) + 1 for _ in range(n)]
    return jnp.asarray(
        np.array([[(z >> i) & 1 for i in range(64)] for z in zs], dtype=bool))


def _pack_pairing_args(p1s, q1s, p2s, q2s):
    """Pad to the bucket and encode into pairing_check_* positional args."""
    from ..ops import bls12_jax as K

    n = len(p1s)
    b = _bucket(n)
    pad = b - n
    p1s = list(p1s) + [_G1] * pad
    q1s = list(q1s) + [_G2] * pad
    p2s = list(p2s) + [_NEG_G1] * pad
    q2s = list(q2s) + [_G2] * pad
    enc = K.F.ints_to_mont_batch

    def g1_coords(pts):
        return enc([p[0] for p in pts]), enc([p[1] for p in pts])

    def g2_coords(pts):
        x = (enc([p[0][0] for p in pts]), enc([p[0][1] for p in pts]))
        y = (enc([p[1][0] for p in pts]), enc([p[1][1] for p in pts]))
        return x, y

    px, py = g1_coords(p1s)
    qx, qy = g2_coords(q1s)
    p2x, p2y = g1_coords(p2s)
    q2x, q2y = g2_coords(q2s)
    return b, (qx, qy, px, py, q2x, q2y, p2x, p2y)


# Observability for the most recent randomized flush: which kernel path ran,
# the padded item/distinct counts, and the Miller-loop bill it implies. The
# source of truth is the metrics registry (record_flush below feeds gauges +
# per-path counters); LAST_FLUSH remains as a read-only Mapping VIEW over
# those series so existing consumers (benches/bls_verify_bench.py,
# tests/test_rlc_grouped.py) keep indexing it like the dict it used to be.

_FLUSH_PATHS = ("rlc", "rlc_grouped")


def record_flush(path: str, items: int, distinct: int,
                 miller_loops: int) -> None:
    """Publish one flush's routing decision to the metrics registry."""
    reg = _obs_metrics.REGISTRY
    reg.counter("bls_flush_total", path=path).inc()
    reg.counter("bls_flush_items_total", path=path).inc(items)
    reg.counter("bls_flush_miller_loops_total", path=path).inc(miller_loops)
    reg.gauge("bls_last_flush_items").set(int(items))
    reg.gauge("bls_last_flush_distinct").set(int(distinct))
    reg.gauge("bls_last_flush_miller_loops").set(int(miller_loops))
    for p in _FLUSH_PATHS:
        reg.gauge("bls_last_flush_path", path=p).set(1 if p == path else 0)
    _obs_trace.annotate(flush_path=path, flush_items=int(items),
                        flush_miller_loops=int(miller_loops))


class _LastFlushView(Mapping):
    """Dict-shaped read view of the last flush, backed by the registry.

    Empty before any flush (like the dict it replaces after .clear());
    supports the full Mapping protocol so `view["path"]`, `view.get(...)`
    and `dict(view)` behave exactly as before the migration."""

    def _data(self) -> dict:
        reg = _obs_metrics.REGISTRY
        path = None
        for p in _FLUSH_PATHS:
            if reg.gauge_value("bls_last_flush_path", path=p) == 1:
                path = p
        if path is None:
            return {}
        return {
            "path": path,
            "items": int(reg.gauge_value("bls_last_flush_items")),
            "distinct": int(reg.gauge_value("bls_last_flush_distinct")),
            "miller_loops": int(reg.gauge_value("bls_last_flush_miller_loops")),
        }

    def __getitem__(self, key):
        return self._data()[key]

    def __iter__(self):
        return iter(self._data())

    def __len__(self):
        return len(self._data())

    def __repr__(self):
        return f"LAST_FLUSH({self._data()!r})"


LAST_FLUSH = _LastFlushView()


def _pack_grouped_args(p1s, q1s, q2s):
    """Group checks by distinct q1 (the H(m) point) and pack the segmented
    kernel's arguments: (b_n, b_d, (qx, qy, px, py, q2x, q2y), seg_ids).

    q1 points come out of the hash_to_curve_g2 lru_cache, so equal messages
    share one tuple — but grouping keys on the VALUE (nested int tuples,
    hashable) so identity is an optimization, never a correctness input.

    Padding: distinct count pads to a power of two (one jit cache entry per
    (b_n, b_d) bucket pair, same stance as _bucket) and every pad group is
    seeded with at least one pad item — an empty segment would sum to
    infinity and fail the batch closed (see g1_segment_sum). Pad items are
    identities by construction: e(G1, Q)·e(−G1, Q) == 1 for ANY G2 point Q,
    so a pad item joining group g uses q1_g as its "signature". The item
    bucket is therefore computed over n + pad_groups, which guarantees
    pad_items >= pad_groups. The shape/assignment math lives in
    sched/bucketing.grouped_plan (shared with the scheduler's lanes); this
    function only supplies the BLS pad values."""
    from ..ops import bls12_jax as K

    plan = _bucketing.grouped_plan(q1s, _MIN_BATCH)
    b_n, b_d = plan.b_n, plan.b_d

    reps = [q1s[i] for i in plan.rep_index] + [_G2] * plan.pad_groups
    p1s = list(p1s) + [_G1] * plan.pad_items
    # sig := q1_g makes each pad check an identity for its group
    q2s = list(q2s) + [reps[g] for g in plan.pad_assignments]

    import jax.numpy as jnp
    import numpy as np

    enc = K.F.ints_to_mont_batch
    px, py = enc([p[0] for p in p1s]), enc([p[1] for p in p1s])
    qx = (enc([q[0][0] for q in reps]), enc([q[0][1] for q in reps]))
    qy = (enc([q[1][0] for q in reps]), enc([q[1][1] for q in reps]))
    q2x = (enc([s[0][0] for s in q2s]), enc([s[0][1] for s in q2s]))
    q2y = (enc([s[1][0] for s in q2s]), enc([s[1][1] for s in q2s]))
    seg_ids = jnp.asarray(np.array(plan.seg, dtype=np.int32))
    return b_n, b_d, (qx, qy, px, py, q2x, q2y), seg_ids


def _device_check_all(p1s, q1s, p2s, q2s) -> bool:
    """Single-bool randomized batch check (pairing_check_rlc) with host-drawn
    64-bit scalars; soundness error 2^-64 per flush.

    When messages repeat across the batch (attestation workloads: every
    committee of a slot signs the same root), the flush takes the segmented
    kernel path — D+1 Miller loops for D distinct messages instead of
    N+1. All-distinct batches keep the ungrouped kernel (the segment
    reduce would be pure overhead at D == N)."""
    import jax
    import numpy as np

    from ..ops import bls12_jax as K

    # every queued check's second pairing is e(−G1, sig) (QueuedCheck
    # construction above) — the fixed-base window path applies; the assert
    # pins the invariant so a future check kind with a different base fails
    # loudly instead of silently verifying the wrong equation
    assert all(p2 is _NEG_G1 for p2 in p2s), "RLC fast path requires p2 == -G1"
    n = len(p1s)
    with _obs_trace.span("bls.flush", checks=n):
        if len(set(q1s)) < n:
            with _obs_trace.span("bls.flush.pack", path="rlc_grouped"):
                b_n, b_d, args, seg_ids = _pack_grouped_args(p1s, q1s, q2s)
            with _obs_trace.span("bls.flush.ladder", path="rlc_grouped"):
                z = random_zbits(b_n)
            with _obs_trace.span("bls.flush.miller", path="rlc_grouped"):
                ok = K.pairing_check_rlc(*args, None, None, z,
                                         p2_is_neg_g1=True, seg_ids=seg_ids)
                result = bool(np.asarray(jax.device_get(ok)))
            record_flush("rlc_grouped", items=b_n, distinct=b_d,
                         miller_loops=b_d + 1)
        else:
            with _obs_trace.span("bls.flush.pack", path="rlc"):
                b, args = _pack_pairing_args(p1s, q1s, p2s, q2s)
            with _obs_trace.span("bls.flush.ladder", path="rlc"):
                z = random_zbits(b)
            with _obs_trace.span("bls.flush.miller", path="rlc"):
                ok = K.pairing_check_rlc(*args, z, p2_is_neg_g1=True)
                result = bool(np.asarray(jax.device_get(ok)))
            record_flush("rlc", items=b, distinct=b, miller_loops=b + 1)
    return result


def run_checks(checks) -> np.ndarray:
    """Execute a list of QueuedCheck | None on device; None -> False."""
    live = [(i, c) for i, c in enumerate(checks) if c is not None]
    out = np.zeros(len(checks), dtype=bool)
    if not live:
        return out
    cols = (
        [c.p1 for _, c in live],
        [c.q1 for _, c in live],
        [c.p2 for _, c in live],
        [c.q2 for _, c in live],
    )
    if len(live) >= RLC_MIN_BATCH and _device_check_all(*cols):
        for i, _ in live:
            out[i] = True
        return out
    # small batch, or the randomized check failed: per-item attribution
    res = _device_check(*cols)
    for (i, _), ok in zip(live, res):
        out[i] = bool(ok)
    return out


def bench_pairing_args(n: int, distinct: int = 8):
    """Device-ready args for `ops.bls12_jax.pairing_check_batch`: `n` valid
    (pubkey, H(m), signature) triples tiled from `distinct` host-signed ones.

    Single source of truth for the benchmark input packing (bench.py and
    benches/bls_verify_bench.py) so the positional pairing argument order
    lives in one place next to the shim's own packing above."""
    import jax
    import numpy as np

    from ..ops import bls12_jax as K
    from .bls_sig import Sign
    from .hash_to_curve import hash_to_curve_g2

    enc = K.F.ints_to_mont_batch
    pks, hms, sigs = [], [], []
    for i in range(distinct):
        sk = 1000 + i
        msg = b"bench message %d" % i
        sigs.append(g2_from_bytes(bytes(Sign(sk, msg))))
        pks.append(
            oracle.pt_to_affine(
                oracle.FP_FIELD, oracle.pt_mul(oracle.FP_FIELD, oracle.G1_GEN, sk)
            )
        )
        hms.append(hash_to_curve_g2(msg))

    def tile(arr):
        reps = (n + distinct - 1) // distinct
        return np.tile(arr, (reps,) + (1,) * (arr.ndim - 1))[:n]

    dev = jax.device_put
    return (
        (dev(tile(enc([h[0][0] for h in hms]))), dev(tile(enc([h[0][1] for h in hms])))),
        (dev(tile(enc([h[1][0] for h in hms]))), dev(tile(enc([h[1][1] for h in hms])))),
        dev(tile(enc([p[0] for p in pks]))),
        dev(tile(enc([p[1] for p in pks]))),
        (dev(tile(enc([s[0][0] for s in sigs]))), dev(tile(enc([s[0][1] for s in sigs])))),
        (dev(tile(enc([s[1][0] for s in sigs]))), dev(tile(enc([s[1][1] for s in sigs])))),
        dev(tile(enc([_NEG_G1[0]] * distinct))),
        dev(tile(enc([_NEG_G1[1]] * distinct))),
    )


def bench_grouped_pairing_args(n: int, distinct: int = 8):
    """Device-ready args for the SEGMENTED `pairing_check_rlc` fast path:
    the same `n` valid triples `bench_pairing_args` tiles (identical sks
    and messages), but packed through `_pack_grouped_args` — returns
    ((qx, qy, px, py, q2x, q2y), seg_ids) so benches and tests compare the
    grouped and ungrouped kernels on the SAME logical inputs."""
    from .bls_sig import Sign

    p1s, q1s, q2s = [], [], []
    for i in range(n):
        sk = 1000 + (i % distinct)
        msg = b"bench message %d" % (i % distinct)
        p1s.append(
            oracle.pt_to_affine(
                oracle.FP_FIELD, oracle.pt_mul(oracle.FP_FIELD, oracle.G1_GEN, sk)
            )
        )
        q1s.append(hash_to_curve_g2(msg))
        q2s.append(g2_from_bytes(bytes(Sign(sk, msg))))
    _, _, args, seg_ids = _pack_grouped_args(p1s, q1s, q2s)
    return args, seg_ids


DEVICE_AGGREGATE_MIN = 32  # below this, host point-adds beat a kernel launch


def aggregate_pubkeys_device(pubkeys) -> bytes:
    """Aggregate compressed G1 pubkeys on device, routed through the sched
    "msm" work class (shape-bucketed dispatch, bounded admission, breaker
    degradation to the host oracle) with batched device subgroup checks for
    cold keys and the g1_aggregate_device reduction tree underneath.

    Raises ValueError on any invalid/infinity input, mirroring the host
    oracle's AggregatePKs contract; an infinity SUM encodes as 0xc0."""
    from .bls12_381 import g1_to_bytes

    if len(pubkeys) == 0:
        raise ValueError("aggregate of empty pubkey list")
    pks = [bytes(pk) for pk in pubkeys]
    marker = _aggregate_pubkeys_sched(pks)
    if marker is None:  # msm lane unavailable: run the device impl inline
        marker = _aggregate_pubkeys_device_impl(pks)
    tag = marker[0]
    if tag == "bad_encoding":
        raise ValueError(marker[1])
    if tag == "inf_member":
        raise ValueError("infinity pubkey in aggregate")
    if tag == "inf":
        return g1_to_bytes(None)  # sum is infinity: canonical 0xc0 encoding
    return g1_to_bytes((marker[1], marker[2]))
