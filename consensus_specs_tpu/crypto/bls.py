"""BLS shim: the single boundary all spec code calls for BLS operations.

Reference parity: eth2spec/utils/bls.py — the switchable-backend module with
the global `bls_active` kill-switch (:6), backend selection (:17-30), the
`only_with_bls` decorator (:33-44) and the operation surface (:47-110).

Backends:
- "py"  : pure-Python oracle (crypto/bls_sig.py) — correctness reference
          (the reference's py_ecc role, utils/bls.py:25-30).
- "jax" : batched device pairing (crypto/bls_jax.py over ops/bls12_jax.py)
          for Verify/FastAggregateVerify — the milagro role (:17-22), built
          on the RNS/MXU field. Sign/aggregate/codec ops stay on the host
          oracle in either backend.

Deferred batching: `with deferred_verification():` queues every
verification (optimistically returning True) and flushes the whole set in
ONE device launch at exit, raising BLSVerificationError if any check fails
— the SURVEY.md §7 state_transition stance (collect triples, verify once,
AND-reduce). Works under either backend ("py" flushes through the oracle),
so the spec markdown's inline `assert bls.Verify(...)` lines stay untouched.

When `bls_active` is False every operation returns a stub success/zero value,
letting the spec-test matrix run fast without real crypto — the same contract
the reference's tests rely on (`--disable-bls`).
"""
from __future__ import annotations

from . import bls_sig as _py
# Surfaced so consumers can detect the current map_to_curve interop status
# (False until crypto/isogeny.py lands: signatures are internally consistent
# but not RFC-9380-interoperable; see crypto/hash_to_curve.py docstring).
from .hash_to_curve import MAP_TO_CURVE_RFC_COMPLIANT  # noqa: F401
from ..obs import trace as _obs_trace
from ..robustness import faults as _faults
from ..robustness import retry as _retry
from .. import sched as _sched

bls_active = True
_backend = "py"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
G2_POINT_AT_INFINITY = _py.G2_POINT_AT_INFINITY
STUB_COORDINATES = (0, 0)


def use_py():
    global _backend
    _backend = "py"


def use_jax():
    """Route Verify/FastAggregateVerify through the batched device pairing."""
    global _backend
    _backend = "jax"


class BLSVerificationError(AssertionError):
    """Raised at deferred-batch flush when one or more checks failed.

    Subclasses AssertionError so spec-level consumers (expect_assertion_error,
    fork-choice on_block try/except) treat a deferred failure exactly like an
    inline `assert bls.Verify(...)` failure."""


import threading as _threading


class _DeferralState(_threading.local):
    """Per-thread deferral state: the gossip driver's threaded mode runs
    concurrent drain_and_verify batches, and state_transition now enters the
    context unconditionally — a shared global queue would interleave checks
    across threads and misattribute failures."""

    def __init__(self):
        self.queue = None  # None = inline mode; list = queueing
        self.depth = 0  # reentrancy: only the outermost context flushes


_deferral = _DeferralState()
flush_count = 0  # batched flushes performed (test observability: one/block)
inline_check_count = 0  # un-batched verifications (should be ~0 in spec path)


class deferred_verification:
    """Context manager: queue all signature checks, verify once at exit.

    Reentrant: `state_transition` establishes this context by default, and an
    outer caller (fork choice replaying many blocks, the gossip driver) may
    hold its own — inner contexts then queue into the outer one and the single
    flush happens at the outermost exit. An inner body that raises truncates
    its own queued checks (the failed block's work is discarded wholesale)
    without poisoning the outer batch."""

    def __enter__(self):
        _deferral.depth += 1
        if _deferral.queue is None:
            _deferral.queue = []
        self._entry_len = len(_deferral.queue)
        return self

    def __exit__(self, exc_type, exc, tb):
        global flush_count
        _deferral.depth -= 1
        if exc_type is not None and _deferral.queue is not None:
            # drop checks queued by the failed body: the caller discards that
            # block's state, so its half-applied checks must not decide the
            # fate of sibling blocks in an outer batch
            del _deferral.queue[self._entry_len:]
        if _deferral.depth > 0:
            return False  # inner context: the outermost one flushes
        queue = _deferral.queue
        try:
            if exc_type is not None:
                return False  # propagate; skip verification of a failed body
            if queue:
                flush_count += 1
                results = _flush_retrying(queue)
                if not all(results):
                    bad = [i for i, ok in enumerate(results) if not ok]
                    raise BLSVerificationError(
                        f"deferred batch verification failed for checks {bad}"
                    )
            return False
        finally:
            # Structural reset: whatever escaped above — BLSVerificationError,
            # a device error the retries couldn't absorb — the NEXT
            # deferred_verification() on this thread must start from a clean
            # slate. Leaving the failed batch's queue attached would silently
            # append an unrelated block's checks onto checks the caller
            # already saw fail (queue poisoning).
            _deferral.queue = None
            _deferral.depth = 0


class inline_verification:
    """Context manager: bypass any active deferral for checks whose boolean
    steers control flow rather than feeding an assert. The one spec consumer
    is `process_deposit` — an invalid deposit signature skips the deposit
    (the funds are burned) instead of failing the block, so its check must
    resolve immediately; deferring it would turn a skippable deposit into a
    whole-block rejection at flush time."""

    def __enter__(self):
        self._saved = _deferral.queue
        _deferral.queue = None
        return self

    def __exit__(self, exc_type, exc, tb):
        _deferral.queue = self._saved
        return False


# Flush dispatch is side-effect-free on the queue (it only reads the
# ("kind", args) tuples), so re-dispatching the same queue after a transient
# device error is safe — there is no partially-consumed state to unwind.
FLUSH_RETRY_POLICY = _retry.RetryPolicy(
    max_attempts=3, base_delay=0.02, max_delay=0.2)


def _flush_retrying(queue):
    with _obs_trace.span("bls.deferred_flush", queued=len(queue)):
        return _retry.call_with_retry(
            lambda: _flush_deferred(queue), FLUSH_RETRY_POLICY)


def _flush_deferred(queue):
    """queue: list of ("kind", args) tuples -> list[bool]."""
    _faults.fire("bls.flush")
    if _backend == "jax":
        # The device flush is served by the unified verification scheduler
        # (sched/): one submit per queued check, then a class flush. The
        # scheduler owns the shape bucketing, the dispatch-seam retry +
        # breaker, and the per-class metrics; this shim keeps only the
        # queue semantics. sched is jax-free at module level (ADVICE r5
        # still holds): device kernels load inside the BLS work class's
        # execute body, so a pure-Python-oracle process can defer, flush,
        # and clear caches without jax ever being importable.
        sch = _sched.default_scheduler()
        handles = sch.submit_many([
            _sched.Request(work_class="bls", kind=kind, payload=args)
            for kind, args in queue])
        sch.flush("bls")
        return [bool(h.result()) for h in handles]
    dispatch = {
        "verify": _py.Verify,
        "fast_aggregate": _py.FastAggregateVerify,
        "aggregate_verify": _py.AggregateVerify,
    }
    return [dispatch[kind](*args) for kind, args in queue]


def _check(kind, args, py_fn):
    """Common path for the three verification ops: queue when deferring,
    else dispatch to the active backend."""
    global inline_check_count
    if _deferral.queue is not None:
        _deferral.queue.append((kind, args))
        return True
    inline_check_count += 1
    if _backend == "jax":
        return bool(_flush_retrying([(kind, args)])[0])
    return py_fn(*args)


def backend() -> str:
    return _backend


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped op (returning `alt_return`) when BLS is off."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        wrapper.__name__ = fn.__name__
        return wrapper
    return decorator


@only_with_bls(alt_return=True)
def Verify(pubkey, message, signature) -> bool:
    return _check("verify", (pubkey, message, signature), _py.Verify)


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature) -> bool:
    return _check(
        "aggregate_verify", (list(pubkeys), list(messages), signature),
        _py.AggregateVerify)


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature) -> bool:
    return _check(
        "fast_aggregate", (list(pubkeys), message, signature),
        _py.FastAggregateVerify)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures) -> bytes:
    return _py.Aggregate(signatures)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(privkey, message) -> bytes:
    # Memoized: signing is deterministic ([sk]·H(m)), so caching is
    # semantics-free; the vector-generator lane re-signs the same
    # (privkey, root) pairs constantly (cached genesis states, randao
    # reveals over the same epochs, selection proofs), and each pure-Python
    # G2 scalar mul costs ~10 ms. ~200 B/entry -> 2^16 cap < ~15 MB.
    # TEST-VECTOR INTENT ONLY: the cache pins raw private keys in process
    # memory for the process lifetime — fine for the deterministic test
    # keys 1..8192, unacceptable for real secrets. Call clear_sign_cache()
    # (or bls.clear_caches()) to drop them.
    return _sign_lru(int(privkey), bytes(message))


def clear_sign_cache() -> None:
    """Drop the Sign memo (pins privkeys; see Sign docstring)."""
    _sign_lru.cache_clear()


def clear_caches() -> None:
    """Drop every host-side crypto cache: the Sign memo plus the jax
    backend's committee-aggregate LRU and point-decode/hash-to-curve
    lru_caches (g1_from_bytes alone can hold ~0.5 GB at its default size).

    The jax-backend caches are cleared only if `bls_jax` has already been
    imported — importing it here would drag in jax (and initialize a
    backend) from a pure-host code path that never used it, just to clear
    caches that cannot have entries. Together with the deferred imports in
    _flush_deferred/AggregatePKs this makes the whole py-backend surface
    usable in a process where `bls_jax` cannot import at all (ADVICE r5;
    covered by test_bls.py's poisoned-module subprocess test)."""
    import sys

    clear_sign_cache()
    _py.clear_sig_point_cache()
    bls_jax = sys.modules.get(__package__ + ".bls_jax")
    if bls_jax is None:
        return
    bls_jax._AGG_CACHE.clear()
    bls_jax._PK_VALIDATED.clear()
    bls_jax.g1_from_bytes.cache_clear()
    bls_jax.g2_from_bytes.cache_clear()
    bls_jax.hash_to_curve_g2.cache_clear()


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=1 << 16)
def _sign_lru(privkey: int, message: bytes) -> bytes:
    return _py.Sign(privkey, message)


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(signature):
    return _py.signature_to_point(signature)


def AggregatePKs(pubkeys) -> bytes:
    """NOT behind the kill-switch: aggregate pubkeys are *state content*
    (SyncCommittee.aggregate_pubkey via eth_aggregate_pubkeys), not a
    verification — a stub here would bake fake bytes into states and make
    vectors generated with BLS on irreproducible by a BLS-off replay
    (bls_setting 0 means verification is optional, never that state
    contents change). Large aggregates route through the device G1
    reduction tree under the jax backend (512-member sync committees are
    one kernel launch instead of 511 host point-adds)."""
    if _backend == "jax":
        from . import bls_jax  # jax path only; see _flush_deferred

        if len(pubkeys) >= bls_jax.DEVICE_AGGREGATE_MIN:
            return bls_jax.aggregate_pubkeys_device(pubkeys)
    return _py.AggregatePKs(pubkeys)


@only_with_bls(alt_return=STUB_SIGNATURE)
def SkToPk(privkey) -> bytes:
    return _py.SkToPk(int(privkey))


def KeyValidate(pubkey) -> bool:
    return _py.KeyValidate(pubkey)
