"""BLS shim: the single boundary all spec code calls for BLS operations.

Reference parity: eth2spec/utils/bls.py — the switchable-backend module with
the global `bls_active` kill-switch (:6), backend selection (:17-30), the
`only_with_bls` decorator (:33-44) and the operation surface (:47-110).

Backends:
- "py"  : pure-Python oracle (crypto/bls_sig.py) — correctness reference
          (the reference's py_ecc role, utils/bls.py:25-30).
- "jax" : batched device pairing (crypto/bls_jax.py over ops/bls12_jax.py)
          for Verify/FastAggregateVerify — the milagro role (:17-22), built
          on the RNS/MXU field. Sign/aggregate/codec ops stay on the host
          oracle in either backend.

Deferred batching: `with deferred_verification():` queues every
verification (optimistically returning True) and flushes the whole set in
ONE device launch at exit, raising BLSVerificationError if any check fails
— the SURVEY.md §7 state_transition stance (collect triples, verify once,
AND-reduce). Works under either backend ("py" flushes through the oracle),
so the spec markdown's inline `assert bls.Verify(...)` lines stay untouched.

When `bls_active` is False every operation returns a stub success/zero value,
letting the spec-test matrix run fast without real crypto — the same contract
the reference's tests rely on (`--disable-bls`).
"""
from __future__ import annotations

from . import bls_sig as _py
# Surfaced so consumers can detect the current map_to_curve interop status
# (False until crypto/isogeny.py lands: signatures are internally consistent
# but not RFC-9380-interoperable; see crypto/hash_to_curve.py docstring).
from .hash_to_curve import MAP_TO_CURVE_RFC_COMPLIANT  # noqa: F401

bls_active = True
_backend = "py"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
G2_POINT_AT_INFINITY = _py.G2_POINT_AT_INFINITY
STUB_COORDINATES = (0, 0)


def use_py():
    global _backend
    _backend = "py"


def use_jax():
    """Route Verify/FastAggregateVerify through the batched device pairing."""
    global _backend
    _backend = "jax"


class BLSVerificationError(AssertionError):
    """Raised at deferred-batch flush when one or more checks failed.

    Subclasses AssertionError so spec-level consumers (expect_assertion_error,
    fork-choice on_block try/except) treat a deferred failure exactly like an
    inline `assert bls.Verify(...)` failure."""


_deferred_queue = None  # None = inline mode; list = queueing


class deferred_verification:
    """Context manager: queue all signature checks, verify once at exit."""

    def __enter__(self):
        global _deferred_queue
        if _deferred_queue is not None:  # not assert: -O must not skip this
            raise RuntimeError("deferred_verification cannot nest")
        _deferred_queue = []
        return self

    def __exit__(self, exc_type, exc, tb):
        global _deferred_queue
        queue, _deferred_queue = _deferred_queue, None
        if exc_type is not None:
            return False  # propagate; skip verification of a failed body
        if queue:
            results = _flush_deferred(queue)
            if not all(results):
                bad = [i for i, ok in enumerate(results) if not ok]
                raise BLSVerificationError(
                    f"deferred batch verification failed for checks {bad}"
                )
        return False


def _flush_deferred(queue):
    """queue: list of ("kind", args) tuples -> list[bool]."""
    from . import bls_jax

    if _backend == "jax":
        checks = []
        results = [None] * len(queue)
        for i, (kind, args) in enumerate(queue):
            if kind == "verify":
                checks.append(bls_jax.make_verify_check(*args))
            elif kind == "fast_aggregate":
                checks.append(bls_jax.make_fast_aggregate_check(*args))
            else:  # aggregate_verify: host fallback (distinct-message multi-pairing)
                checks.append(None)
                results[i] = _py.AggregateVerify(*args)
        dev = bls_jax.run_checks(checks)
        return [dev[i] if r is None else r for i, r in enumerate(results)]
    dispatch = {
        "verify": _py.Verify,
        "fast_aggregate": _py.FastAggregateVerify,
        "aggregate_verify": _py.AggregateVerify,
    }
    return [dispatch[kind](*args) for kind, args in queue]


def _check(kind, args, py_fn):
    """Common path for the three verification ops: queue when deferring,
    else dispatch to the active backend."""
    if _deferred_queue is not None:
        _deferred_queue.append((kind, args))
        return True
    if _backend == "jax":
        return bool(_flush_deferred([(kind, args)])[0])
    return py_fn(*args)


def backend() -> str:
    return _backend


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped op (returning `alt_return`) when BLS is off."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        wrapper.__name__ = fn.__name__
        return wrapper
    return decorator


@only_with_bls(alt_return=True)
def Verify(pubkey, message, signature) -> bool:
    return _check("verify", (pubkey, message, signature), _py.Verify)


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature) -> bool:
    return _check(
        "aggregate_verify", (list(pubkeys), list(messages), signature),
        _py.AggregateVerify)


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature) -> bool:
    return _check(
        "fast_aggregate", (list(pubkeys), message, signature),
        _py.FastAggregateVerify)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures) -> bytes:
    return _py.Aggregate(signatures)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(privkey, message) -> bytes:
    return _py.Sign(int(privkey), message)


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(signature):
    return _py.signature_to_point(signature)


def AggregatePKs(pubkeys) -> bytes:
    """NOT behind the kill-switch: aggregate pubkeys are *state content*
    (SyncCommittee.aggregate_pubkey via eth_aggregate_pubkeys), not a
    verification — a stub here would bake fake bytes into states and make
    vectors generated with BLS on irreproducible by a BLS-off replay
    (bls_setting 0 means verification is optional, never that state
    contents change). Large aggregates route through the device G1
    reduction tree under the jax backend (512-member sync committees are
    one kernel launch instead of 511 host point-adds)."""
    from . import bls_jax

    if _backend == "jax" and len(pubkeys) >= bls_jax.DEVICE_AGGREGATE_MIN:
        return bls_jax.aggregate_pubkeys_device(pubkeys)
    return _py.AggregatePKs(pubkeys)


@only_with_bls(alt_return=STUB_SIGNATURE)
def SkToPk(privkey) -> bytes:
    return _py.SkToPk(int(privkey))


def KeyValidate(pubkey) -> bool:
    return _py.KeyValidate(pubkey)
