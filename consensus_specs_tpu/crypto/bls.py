"""BLS shim: the single boundary all spec code calls for BLS operations.

Reference parity: eth2spec/utils/bls.py — the switchable-backend module with
the global `bls_active` kill-switch (:6), backend selection (:17-30), the
`only_with_bls` decorator (:33-44) and the operation surface (:47-110).

Backends:
- "py"  : pure-Python oracle (crypto/bls_sig.py) — correctness reference.
- "jax" : batched device kernels (ops/bls_jax.py) for bulk verification;
          falls back to "py" per-op until the kernel set is complete.

When `bls_active` is False every operation returns a stub success/zero value,
letting the spec-test matrix run fast without real crypto — the same contract
the reference's tests rely on (`--disable-bls`).
"""
from __future__ import annotations

from . import bls_sig as _py
# Surfaced so consumers can detect the current map_to_curve interop status
# (False until crypto/isogeny.py lands: signatures are internally consistent
# but not RFC-9380-interoperable; see crypto/hash_to_curve.py docstring).
from .hash_to_curve import MAP_TO_CURVE_RFC_COMPLIANT  # noqa: F401

bls_active = True
_backend = "py"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
G2_POINT_AT_INFINITY = _py.G2_POINT_AT_INFINITY
STUB_COORDINATES = (0, 0)


def use_py():
    global _backend
    _backend = "py"


def use_jax():
    raise NotImplementedError(
        "jax BLS backend not wired up yet (ops/bls_jax.py pending); "
        "the pure-Python backend is active"
    )


def backend() -> str:
    return _backend


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped op (returning `alt_return`) when BLS is off."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)
        wrapper.__name__ = fn.__name__
        return wrapper
    return decorator


@only_with_bls(alt_return=True)
def Verify(pubkey, message, signature) -> bool:
    return _py.Verify(pubkey, message, signature)


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature) -> bool:
    return _py.AggregateVerify(pubkeys, messages, signature)


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature) -> bool:
    return _py.FastAggregateVerify(pubkeys, message, signature)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures) -> bytes:
    return _py.Aggregate(signatures)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(privkey, message) -> bytes:
    return _py.Sign(int(privkey), message)


@only_with_bls(alt_return=STUB_COORDINATES)
def signature_to_G2(signature):
    return _py.signature_to_point(signature)


def AggregatePKs(pubkeys) -> bytes:
    """NOT behind the kill-switch: aggregate pubkeys are *state content*
    (SyncCommittee.aggregate_pubkey via eth_aggregate_pubkeys), not a
    verification — a stub here would bake fake bytes into states and make
    vectors generated with BLS on irreproducible by a BLS-off replay
    (bls_setting 0 means verification is optional, never that state
    contents change)."""
    return _py.AggregatePKs(pubkeys)


@only_with_bls(alt_return=STUB_SIGNATURE)
def SkToPk(privkey) -> bytes:
    return _py.SkToPk(int(privkey))


def KeyValidate(pubkey) -> bool:
    return _py.KeyValidate(pubkey)
