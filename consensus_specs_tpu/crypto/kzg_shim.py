"""KZG shim for the executable sharding spec.

The role `utils/bls.py` plays for signatures (reference utils/bls.py:6,33-44:
a single boundary with a `bls_active` kill-switch so the fast test matrix can
skip the expensive crypto), this module plays for the sharding spec's
polynomial-commitment checks (`process_shard_header`'s degree-bound pairing,
reference specs/sharding/beacon-chain.md:716-719). The compiled spec modules
see this module as `kzg` (compiler namespace), the same way they see the BLS
shim as `bls`.

The trusted setup (`G1_SETUP`/`G2_SETUP`, reference :172-173) is
externally-supplied ceremony data the spec treats as constants; here it is
process-global installable state (`use_setup`), with
`crypto/kzg.insecure_test_setup` as the test-time source. When `bls.bls_active`
is off (stub-crypto test mode) every check passes, mirroring the BLS
kill-switch contract.
"""
from __future__ import annotations

from . import bls, kzg
from .bls12_381 import g1_from_bytes, g1_to_bytes, pt_from_affine, pt_to_affine
from .kzg import FP_FIELD, KZGSetup

_setup: KZGSetup | None = None


def use_setup(setup: KZGSetup | None) -> None:
    """Install (or with None, clear) the process-global trusted setup."""
    global _setup
    _setup = setup


def get_setup() -> KZGSetup:
    assert _setup is not None, "no KZG setup installed (kzg_shim.use_setup)"
    return _setup


def identity_commitment() -> bytes:
    """Compressed `G1_SETUP[0]` — the required degree proof for zero-length
    blobs (reference :713-714)."""
    return g1_to_bytes(pt_to_affine(FP_FIELD, get_setup().g1[0]))


def is_identity_commitment(proof: bytes) -> bool:
    if not bls.bls_active:
        return True
    return bytes(proof) == identity_commitment()


def verify_degree_bound(commitment: bytes, degree_proof: bytes, points_count: int) -> bool:
    """e(degree_proof, G2_SETUP[0]) == e(commitment, G2_SETUP[-points_count])
    (reference :716-719) over compressed inputs; decompression failures are
    rejections (both fields arrive from the network inside a block body)."""
    if not bls.bls_active:
        return True
    if int(points_count) == 0:
        # Zero-length blob (reference :714-719): the pairing degenerates to
        # e(proof, G2[0]) == e(commitment, G2[-0]) == e(commitment, G2[0]),
        # i.e. commitment == degree_proof == G1_SETUP[0]. Check by equality —
        # kzg.verify_degree_proof rejects k == 0 as out of setup range.
        ident = identity_commitment()
        return bytes(commitment) == ident and bytes(degree_proof) == ident
    try:
        c = pt_from_affine(FP_FIELD, g1_from_bytes(bytes(commitment)))
        p = pt_from_affine(FP_FIELD, g1_from_bytes(bytes(degree_proof)))
    except ValueError:
        return False
    return kzg.verify_degree_proof(get_setup(), c, p, int(points_count))


def commit_to_data(points: list[int]) -> bytes:
    """Builder-side helper: commitment for a blob's scalar points (the data
    IS the evaluation form at the setup's domain in the real protocol; the
    test harness commits to the coefficient form directly)."""
    if not bls.bls_active:
        return b"\xc0" + b"\x00" * 47
    if len(points) == 0:
        # Zero-length blob: commitment == degree_proof == G1_SETUP[0]
        # (reference :714-719 — the degenerate pairing forces both).
        return identity_commitment()
    return kzg.commit_bytes(get_setup(), [p % kzg.MODULUS for p in points])


def prove_degree_bound_bytes(points: list[int], points_count: int) -> bytes:
    if not bls.bls_active:
        return b"\xc0" + b"\x00" * 47
    if points_count == 0:
        return identity_commitment()
    proof = kzg.prove_degree_bound(get_setup(), [p % kzg.MODULUS for p in points], points_count)
    return g1_to_bytes(pt_to_affine(FP_FIELD, proof))


# --- das spec surface (specs/das/das-core.md) -------------------------------


def check_multi_kzg_proof(commitment: bytes, proof: bytes, x: int, ys: list) -> bool:
    """One multiproof check: does `proof` complement evaluations `ys` on the
    coset x·H (H the len(ys)-element subgroup) to match `commitment`?
    (reference specs/das/das-core.md:131-137, left `...` there; executable
    here via crypto/kzg.verify_coset). Compressed inputs arrive from the
    network — decompression failures are rejections."""
    if not bls.bls_active:
        return True
    try:
        c = pt_from_affine(FP_FIELD, g1_from_bytes(bytes(commitment)))
        p = pt_from_affine(FP_FIELD, g1_from_bytes(bytes(proof)))
    except ValueError:
        return False
    return kzg.verify_coset(
        get_setup(), c, int(x) % kzg.MODULUS,
        [int(y) % kzg.MODULUS for y in ys], p,
    )


def construct_proofs_bytes(poly_coeffs: list, points_per_sample: int) -> list:
    """Multiproofs for every aligned coset of the extended polynomial,
    indexed by DOMAIN position p (the coset w_{n2}^p · H). The reference
    stubs this as FK20 (das-core.md:138-146); per-coset quotient proofs are
    functionally equivalent (FK20 batch proving is a planned kernel)."""
    n2 = len(poly_coeffs)
    sample_count = n2 // points_per_sample
    if not bls.bls_active:
        return [b"\xc0" + b"\x00" * 47] * sample_count
    coeffs = [int(c) % kzg.MODULUS for c in poly_coeffs]
    # extended-data polynomial: degree < n, top half must be zero
    assert all(c == 0 for c in coeffs[n2 // 2:]), "not an extension polynomial"
    coeffs = coeffs[: n2 // 2]
    from ..ops.fr_host import root_of_unity

    w = root_of_unity(n2)
    setup = get_setup()
    out = []
    for p in range(sample_count):
        proof, _ = kzg.prove_coset(setup, coeffs, pow(w, p, kzg.MODULUS), points_per_sample)
        out.append(g1_to_bytes(pt_to_affine(FP_FIELD, proof)))
    return out
