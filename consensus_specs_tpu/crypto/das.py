"""Data-availability sampling: erasure extension, recovery, sample checks.

Reference parity: specs/das/das-core.md — reverse-bit-order sample layout
(:66-77), `das_fft_extension` (:90-107), `recover_data` (:108-130),
`check_multi_kzg_proof` (:131-137), `sample_data` / `verify_sample` /
`reconstruct_extended_data` (:154-186). The reference marks recovery "TODO:
make this more beautiful" and points at research code; here the full pipeline
is implemented against the framework's Fr NTT kernels (ops/fr_jax.py) and the
KZG layer (crypto/kzg.py).

Model: a blob is n field elements, viewed as evaluations of a degree-<n
polynomial P on the even-indexed 2n-th roots of unity (= the n-th roots).
Extension doubles it to evaluations on ALL 2n-th roots; any n of the 2n
points recover P (Reed-Solomon rate 1/2, the spec's
DATA_AVAILABILITY_INVERSE_CODING_RATE = 2). Samples are
POINTS_PER_SAMPLE-sized cosets in reverse-bit-order layout so each sample is
contiguous AND forms a multiplicative coset — the property `verify_sample`'s
multi-KZG check relies on.

Device mapping: extension and the FFT steps of recovery are O(n log n)
butterfly chains — the make_ntt kernels; the zero-polynomial construction is
O(missing²) host work only at test scale (subproduct trees later).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.fr_host import R_MODULUS as MODULUS
from ..ops.fr_host import host_ntt, root_of_unity
from . import kzg


def _fr_jax():
    """Device NTT kernels, imported lazily: the `use_device=False` sampling
    and recovery path must stay usable in a jax-free process (PR-3
    deferred-import discipline, mirroring crypto/bls.py; the poisoned-module
    subprocess test in tests/test_deferred_crypto_path.py holds this)."""
    from ..ops import fr_jax

    return fr_jax

# --- reverse-bit-order layout (das-core.md:66-77) ---------------------------


def reverse_bit_order(n: int) -> list[int]:
    """Permutation mapping natural index -> reverse-bit-order position."""
    assert n & (n - 1) == 0
    bits = n.bit_length() - 1
    return [int(format(i, f"0{bits}b")[::-1], 2) if bits else 0 for i in range(n)]


def to_rbo(values: list[int]) -> list[int]:
    perm = reverse_bit_order(len(values))
    return [values[perm[i]] for i in range(len(values))]


def from_rbo(values: list[int]) -> list[int]:
    perm = reverse_bit_order(len(values))
    out = [0] * len(values)
    for i in range(len(values)):
        out[perm[i]] = values[i]
    return out


# --- extension (das-core.md:90-107) -----------------------------------------


def data_to_coeffs(data: list[int], use_device: bool = True) -> list[int]:
    """Coefficients of the degree-<n polynomial through the blob's evals
    (one inverse NTT; shared by extension and commitment so each runs once)."""
    n = len(data)
    if use_device:
        fr = _fr_jax()
        intt = fr.make_ntt(n, inverse=True)
        return fr.mont_batch_to_ints(intt(np.asarray(fr.ints_to_mont_batch(data))))
    return host_ntt(data, inverse=True)


def _extension_from_coeffs(coeffs: list[int], use_device: bool) -> list[int]:
    """Odd-root evaluations from coefficient form: zero-pad to 2n, NTT on the
    doubled domain, take odd positions (even positions reproduce the data —
    asserted in tests)."""
    n = len(coeffs)
    padded = coeffs + [0] * n
    if use_device:
        fr = _fr_jax()
        ntt2 = fr.make_ntt(2 * n)
        full = fr.mont_batch_to_ints(ntt2(np.asarray(fr.ints_to_mont_batch(padded))))
    else:
        full = host_ntt(padded)
    return full[1::2]


def das_fft_extension(data: list[int], use_device: bool = True) -> list[int]:
    """Given P's evaluations on the even 2n-th roots (w^0, w^2, ...), return
    its evaluations on the odd 2n-th roots (w^1, w^3, ...)."""
    return _extension_from_coeffs(data_to_coeffs(data, use_device), use_device)


def extend_data(data: list[int], use_device: bool = True) -> list[int]:
    """Interleave original (even positions) and extension (odd positions) to
    the full 2n-point evaluation vector in natural domain order."""
    odd = das_fft_extension(data, use_device)
    out = []
    for e, o in zip(data, odd):
        out.extend((e, o))
    return out


# --- recovery (das-core.md:108-130) -----------------------------------------


def _zero_poly(missing: list[int], n2: int) -> list[int]:
    """Coefficients of Z(x) = prod_{i in missing} (x - w^i) over the 2n domain."""
    w = root_of_unity(n2)
    coeffs = [1]
    for i in missing:
        root = pow(w, i, MODULUS)
        nxt = [0] * (len(coeffs) + 1)
        for j, c in enumerate(coeffs):
            nxt[j + 1] = (nxt[j + 1] + c) % MODULUS
            nxt[j] = (nxt[j] - c * root) % MODULUS
        coeffs = nxt
    return coeffs


def recover_data(samples: dict[int, int], n2: int, use_device: bool = True) -> list[int]:
    """Recover all n2 = 2n evaluations from any >= n of them.

    samples: {natural-domain index -> value}. Standard zero-poly technique:
    with Z vanishing on the missing set, (D·Z) is known everywhere (zero at
    missing points), so interpolate E = D·Z, then D = E/Z evaluated via a
    coset where Z never vanishes."""
    assert len(samples) >= n2 // 2, "not enough samples to recover"
    missing = [i for i in range(n2) if i not in samples]
    if not missing:
        return [samples[i] for i in range(n2)]

    def ntt(vals, inverse=False):
        if use_device:
            fr = _fr_jax()
            f = fr.make_ntt(len(vals), inverse=inverse)
            return fr.mont_batch_to_ints(f(np.asarray(fr.ints_to_mont_batch(vals))))
        return host_ntt(vals, inverse=inverse)

    z_coeffs = _zero_poly(missing, n2)
    z_coeffs_padded = z_coeffs + [0] * (n2 - len(z_coeffs))
    z_evals = ntt(z_coeffs_padded)
    # E(w^i) = D(w^i)·Z(w^i); zero wherever D is unknown (Z vanishes there)
    e_evals = [(samples.get(i, 0) * z_evals[i]) % MODULUS for i in range(n2)]
    e_coeffs = ntt(e_evals, inverse=True)
    # move to coset g·w^i (g any non-root): scale coeffs by g^k
    g = 7
    scale, gs = 1, []
    for _ in range(n2):
        gs.append(scale)
        scale = scale * g % MODULUS
    e_coset = ntt([c * s % MODULUS for c, s in zip(e_coeffs, gs)])
    z_coset = ntt([c * s % MODULUS for c, s in zip(z_coeffs_padded, gs)])
    d_coset = [e * pow(z, MODULUS - 2, MODULUS) % MODULUS for e, z in zip(e_coset, z_coset)]
    d_coeffs_scaled = ntt(d_coset, inverse=True)
    g_inv = pow(g, MODULUS - 2, MODULUS)
    scale, d_coeffs = 1, []
    for c in d_coeffs_scaled:
        d_coeffs.append(c * scale % MODULUS)
        scale = scale * g_inv % MODULUS
    # Rate-1/2 RS consistency: valid inputs interpolate to a degree-<n
    # polynomial; any corrupted/inconsistent sample generically leaks into
    # the top half of the coefficients. This is the real integrity check —
    # matching back the provided samples alone is NOT sufficient (the coset
    # quotient agrees with them by construction on most index sets).
    assert all(c == 0 for c in d_coeffs[n2 // 2 :]), "samples inconsistent (not a rate-1/2 codeword)"
    recovered = ntt(d_coeffs)
    for i, v in samples.items():
        assert recovered[i] == v % MODULUS, "recovery inconsistent with provided samples"
    return recovered


# --- sampling (das-core.md:131-186) -----------------------------------------


@dataclass(frozen=True)
class Sample:
    """One publishable sample: a contiguous run of POINTS_PER_SAMPLE values in
    reverse-bit-order layout (= one multiplicative coset) plus its KZG
    multiproof."""

    index: int
    values: tuple
    proof: object  # G1 point


def sample_cosets(n2: int, points_per_sample: int) -> list[tuple[int, list[int]]]:
    """(coset_shift, natural-domain indices) per sample. In reverse-bit-order
    layout, sample k covers rbo positions [k·m, (k+1)·m) whose natural indices
    form the coset w2n^rev(k)·H with H the (n2/m)-stride subgroup."""
    m = points_per_sample
    perm = reverse_bit_order(n2)
    inv = [0] * n2
    for i, p in enumerate(perm):
        inv[p] = i
    w = root_of_unity(n2)
    out = []
    for k in range(n2 // m):
        idxs = [inv[k * m + j] for j in range(m)]
        # all idxs share residue class structure: idxs = {base + t·(n2/m)}
        shift = pow(w, min(idxs), MODULUS)
        out.append((shift, idxs))
    return out


def sample_data(setup: kzg.KZGSetup, data: list[int], points_per_sample: int,
                use_device: bool = True) -> tuple[bytes, list[Sample]]:
    """Extend the blob, commit to it, and emit all samples with multiproofs
    (das-core.md `sample_data` :154-168)."""
    n = len(data)
    # one INTT serves both the extension and the commitment
    coeffs = data_to_coeffs(data, use_device)
    odd = _extension_from_coeffs(coeffs, use_device)
    full = []
    for e, o in zip(data, odd):
        full.extend((e, o))
    n2 = 2 * n
    commitment = kzg.commit(setup, coeffs)
    samples = []
    for k, (shift, idxs) in enumerate(sample_cosets(n2, points_per_sample)):
        # order values by ascending power within the coset so they line up
        # with the interpolation domain {shift·w_m^j}
        m = len(idxs)
        stride = n2 // m
        base = min(idxs)
        ordered = [full[(base + t * stride) % n2] for t in range(m)]
        proof, ys = kzg.prove_coset(setup, coeffs, shift, m)
        assert ys == ordered, "coset layout mismatch"
        samples.append(Sample(index=k, values=tuple(ordered), proof=proof))
    return commitment, samples


def verify_sample(setup: kzg.KZGSetup, commitment, sample: Sample, n2: int,
                  points_per_sample: int) -> bool:
    """`verify_sample` (das-core.md:169-176): one multi-KZG check per sample.

    Sample contents are untrusted network input: wrong index or wrong value
    count is a clean rejection (a short values tuple must not be allowed to
    verify against a smaller coset than the index claims)."""
    if len(sample.values) != points_per_sample:
        return False
    cosets = sample_cosets(n2, points_per_sample)
    if not 0 <= sample.index < len(cosets):
        return False
    shift, _ = cosets[sample.index]
    return kzg.verify_coset(setup, commitment, shift, list(sample.values), sample.proof)


def reconstruct_extended_data(samples: list[Sample], n2: int, points_per_sample: int,
                              use_device: bool = True) -> list[int]:
    """`reconstruct_extended_data` (das-core.md:177-186): scatter sample values
    back to natural-domain indices and run recovery."""
    cosets = sample_cosets(n2, points_per_sample)
    known: dict[int, int] = {}
    for s in samples:
        # untrusted input: reject bad indices/shapes instead of crashing or
        # (negative index) silently scattering to the wrong coset
        if not 0 <= s.index < len(cosets):
            raise ValueError(f"sample index {s.index} out of range")
        if len(s.values) != points_per_sample:
            raise ValueError(f"sample {s.index} has {len(s.values)} values, want {points_per_sample}")
        shift, idxs = cosets[s.index]
        stride = n2 // points_per_sample
        base = min(idxs)
        for t, v in enumerate(s.values):
            known[(base + t * stride) % n2] = v
    return recover_data(known, n2, use_device)
