"""BLS12-381: field towers, curve groups, optimal-ate pairing. Pure Python.

This is the framework's correctness oracle for BLS — the role py_ecc plays for
the reference (eth2spec/utils/bls.py backend "py_ecc"); the batched JAX kernels
(ops/bls_jax.py) are differential-tested against it. Built from the public
curve definition (y^2 = x^3 + 4 over Fp; sextic M-twist y^2 = x^3 + 4(u+1)
over Fp2; embedding degree 12).

Self-checking: every derived constant (cofactors, twist order, generators) is
validated at import time from the BLS parameter x = -0xd201000000010000, so a
corrupted constant fails fast instead of producing wrong signatures.

Representation choices:
- Fp: int mod P.
- Fp2 = Fp[u]/(u^2+1): tuple (a, b).
- Fp12 = Fp2[w]/(w^6 - xi), xi = 1+u: tuple of 6 Fp2 coefficients. The
  Fp6 tower view (v = w^2) is reconstructed only for inversion.
- Curve points: Jacobian (X, Y, Z) tuples; Z = zero => infinity.
"""
from __future__ import annotations

# --- parameters -----------------------------------------------------------

X_PARAM = -0xD201000000010000  # BLS parameter x (negative)
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Cross-validate P and R from the BLS12 family equations.
assert R == X_PARAM**4 - X_PARAM**2 + 1
assert (X_PARAM - 1) ** 2 % 3 == 0
assert P == (X_PARAM - 1) ** 2 // 3 * R + X_PARAM

B_G1 = 4  # E: y^2 = x^3 + 4

# --- Fp -------------------------------------------------------------------

def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> int | None:
    """p == 3 (mod 4): candidate a^((p+1)/4); validated."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a % P else None


# --- Fp2 = Fp[u]/(u^2+1) --------------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def f2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def f2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def f2_neg(x):
    return (-x[0] % P, -x[1] % P)


def f2_mul(x, y):
    a, b = x
    c, d = y
    ac = a * c
    bd = b * d
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def f2_sqr(x):
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def f2_muli(x, k: int):
    return (x[0] * k % P, x[1] * k % P)


def f2_conj(x):
    return (x[0], -x[1] % P)


def f2_inv(x):
    a, b = x
    norm_inv = fp_inv(a * a + b * b)
    return (a * norm_inv % P, -b * norm_inv % P)


def f2_pow(x, n: int):
    result = F2_ONE
    base = x
    while n > 0:
        if n & 1:
            result = f2_mul(result, base)
        base = f2_sqr(base)
        n >>= 1
    return result


def f2_sqrt(x):
    """Square root in Fp2 via the norm method; None if not a QR."""
    a, b = x
    if b == 0:
        s = fp_sqrt(a)
        if s is not None:
            return (s, 0)
        s = fp_sqrt(-a % P)
        return None if s is None else (0, s)
    n = fp_sqrt((a * a + b * b) % P)
    if n is None:
        return None
    inv2 = fp_inv(2)
    c2 = (a + n) * inv2 % P
    c = fp_sqrt(c2)
    if c is None:
        c2 = (a - n) * inv2 % P
        c = fp_sqrt(c2)
        if c is None:
            return None
    d = b * fp_inv(2 * c) % P
    cand = (c, d)
    return cand if f2_sqr(cand) == (a % P, b % P) else None


XI = (1, 1)  # xi = 1 + u, the twist / tower non-residue

# --- Fp12 = Fp2[w]/(w^6 - xi) ---------------------------------------------

F12_ONE = (F2_ONE, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)
F12_ZERO = (F2_ZERO,) * 6


def f12_add(x, y):
    return tuple(f2_add(a, b) for a, b in zip(x, y))


def f12_sub(x, y):
    return tuple(f2_sub(a, b) for a, b in zip(x, y))


def f12_neg(x):
    return tuple(f2_neg(a) for a in x)


def f12_mul(x, y):
    # schoolbook degree-6 poly mult over Fp2, reduce w^6 -> xi
    prod = [(0, 0)] * 11
    for i in range(6):
        xi_c = x[i]
        if xi_c == F2_ZERO:
            continue
        for j in range(6):
            if y[j] == F2_ZERO:
                continue
            prod[i + j] = f2_add(prod[i + j], f2_mul(xi_c, y[j]))
    out = list(prod[:6])
    for k in range(6, 11):
        if prod[k] != F2_ZERO:
            out[k - 6] = f2_add(out[k - 6], f2_mul(prod[k], XI))
    return tuple(out)


def f12_sqr(x):
    return f12_mul(x, x)


def f12_conj(x):
    """f^(p^6): negate odd w-coefficients."""
    return tuple(f2_neg(c) if i % 2 else c for i, c in enumerate(x))


# Fp6 helpers over v^3 = xi, elements (c0, c1, c2) of Fp2 — used for inversion.

def _f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul(XI, f2_sub(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), t1), t2)))
    c1 = f2_add(f2_sub(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), t0), t1), f2_mul(XI, t2))
    c2 = f2_add(f2_sub(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def _f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def _f6_inv(a):
    a0, a1, a2 = a
    t0 = f2_sub(f2_sqr(a0), f2_mul(XI, f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul(XI, f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    denom = f2_add(
        f2_mul(a0, t0),
        f2_mul(XI, f2_add(f2_mul(a2, t1), f2_mul(a1, t2))),
    )
    dinv = f2_inv(denom)
    return (f2_mul(t0, dinv), f2_mul(t1, dinv), f2_mul(t2, dinv))


def _f6_mul_by_v(a):
    """v * (c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2."""
    return (f2_mul(XI, a[2]), a[0], a[1])


def f12_inv(x):
    # tower view: x = a(v) + w*b(v), v = w^2
    a = (x[0], x[2], x[4])
    b = (x[1], x[3], x[5])
    # norm = a^2 - v * b^2 in Fp6
    norm = [f2_sub(p, q) for p, q in zip(_f6_mul(a, a), _f6_mul_by_v(_f6_mul(b, b)))]
    ninv = _f6_inv(tuple(norm))
    ra = _f6_mul(a, ninv)
    rb = _f6_neg(_f6_mul(b, ninv))
    return (ra[0], rb[0], ra[1], rb[1], ra[2], rb[2])


def f12_pow(x, n: int):
    if n < 0:
        x = f12_inv(x)
        n = -n
    result = F12_ONE
    base = x
    while n > 0:
        if n & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        n >>= 1
    return result


# Frobenius: f^p with f = sum c_i w^i  =>  sum conj(c_i) * g_i * w^i,
# g_i = xi^(i*(p-1)/6).
assert (P - 1) % 6 == 0
_FROB_GAMMA = [f2_pow(XI, i * (P - 1) // 6) for i in range(6)]


def f12_frobenius(x, power: int = 1):
    out = x
    for _ in range(power):
        out = tuple(f2_mul(f2_conj(c), _FROB_GAMMA[i]) for i, c in enumerate(out))
    return out


# --- generic Jacobian curve ops ------------------------------------------
# Parameterized by field function-table: (add, sub, mul, sqr, neg, inv, zero, one)

class _Field:
    __slots__ = ("add", "sub", "mul", "sqr", "neg", "inv", "zero", "one")

    def __init__(self, add, sub, mul, sqr, neg, inv, zero, one):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.neg, self.inv, self.zero, self.one = neg, inv, zero, one


FP_FIELD = _Field(
    lambda a, b: (a + b) % P, lambda a, b: (a - b) % P,
    lambda a, b: a * b % P, lambda a: a * a % P,
    lambda a: -a % P, fp_inv, 0, 1,
)
FP2_FIELD = _Field(f2_add, f2_sub, f2_mul, f2_sqr, f2_neg, f2_inv, F2_ZERO, F2_ONE)


def pt_is_inf(pt):
    return pt is None


def pt_double(F: _Field, pt):
    if pt is None:
        return None
    x, y, z = pt
    a = F.sqr(x)
    b = F.sqr(y)
    c = F.sqr(b)
    d = F.sub(F.sub(F.sqr(F.add(x, b)), a), c)
    d = F.add(d, d)
    e = F.add(F.add(a, a), a)
    f = F.sqr(e)
    x3 = F.sub(f, F.add(d, d))
    c8 = F.add(F.add(F.add(c, c), F.add(c, c)), F.add(F.add(c, c), F.add(c, c)))
    y3 = F.sub(F.mul(e, F.sub(d, x3)), c8)
    z3 = F.mul(F.add(y, y), z)
    return (x3, y3, z3)


def pt_add(F: _Field, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1 = F.sqr(z1)
    z2z2 = F.sqr(z2)
    u1 = F.mul(x1, z2z2)
    u2 = F.mul(x2, z1z1)
    s1 = F.mul(F.mul(y1, z2), z2z2)
    s2 = F.mul(F.mul(y2, z1), z1z1)
    if u1 == u2:
        if s1 != s2:
            return None
        return pt_double(F, p1)
    h = F.sub(u2, u1)
    i = F.sqr(F.add(h, h))
    j = F.mul(h, i)
    r = F.sub(s2, s1)
    r = F.add(r, r)
    v = F.mul(u1, i)
    x3 = F.sub(F.sub(F.sqr(r), j), F.add(v, v))
    s1j = F.mul(s1, j)
    y3 = F.sub(F.mul(r, F.sub(v, x3)), F.add(s1j, s1j))
    z3 = F.mul(F.mul(z1, z2), F.add(h, h))
    return (x3, y3, z3)


def pt_neg(F: _Field, pt):
    if pt is None:
        return None
    x, y, z = pt
    return (x, F.neg(y), z)


def pt_mul(F: _Field, pt, n: int):
    if n < 0:
        return pt_mul(F, pt_neg(F, pt), -n)
    result = None
    addend = pt
    while n > 0:
        if n & 1:
            result = pt_add(F, result, addend)
        addend = pt_double(F, addend)
        n >>= 1
    return result


def pt_to_affine(F: _Field, pt):
    if pt is None:
        return None
    x, y, z = pt
    zinv = F.inv(z)
    zinv2 = F.sqr(zinv)
    return (F.mul(x, zinv2), F.mul(y, F.mul(zinv, zinv2)))


def pt_from_affine(F: _Field, aff):
    if aff is None:
        return None
    x, y = aff
    return (x, y, F.one)


def pt_eq(F: _Field, p1, p2):
    if p1 is None or p2 is None:
        return p1 is None and p2 is None
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1, z2z2 = F.sqr(z1), F.sqr(z2)
    if F.mul(x1, z2z2) != F.mul(x2, z1z1):
        return False
    return F.mul(F.mul(y1, z2), z2z2) == F.mul(F.mul(y2, z1), z1z1)


def g1_on_curve(aff) -> bool:
    if aff is None:
        return True
    x, y = aff
    return y * y % P == (x * x * x + B_G1) % P


B_G2 = f2_muli(XI, 4)  # 4(1+u)


def g2_on_curve(aff) -> bool:
    if aff is None:
        return True
    x, y = aff
    return f2_sqr(y) == f2_add(f2_mul(f2_sqr(x), x), B_G2)


# --- generators and cofactors (validated) ---------------------------------

G1_GEN_AFF = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN_AFF = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

assert g1_on_curve(G1_GEN_AFF), "G1 generator not on curve"
assert g2_on_curve(G2_GEN_AFF), "G2 generator not on twist curve"

G1_GEN = pt_from_affine(FP_FIELD, G1_GEN_AFF)
G2_GEN = pt_from_affine(FP2_FIELD, G2_GEN_AFF)

assert pt_mul(FP_FIELD, G1_GEN, R) is None, "G1 generator order != r"
assert pt_mul(FP2_FIELD, G2_GEN, R) is None, "G2 generator order != r"

# G1 cofactor: |E(Fp)| = p + 1 - t, t = x + 1  =>  |E(Fp)| = p - x.
assert (P - X_PARAM) % R == 0
H1 = (P - X_PARAM) // R

# Twist order: |E'(Fp2)| is one of p^2 + 1 - (±t2 ± 3f)/2 with
# t2 = t^2 - 2p and f^2 = (4p^2 - t2^2)/3; pick the candidate divisible by r.
_t = X_PARAM + 1
_t2 = _t * _t - 2 * P


def _isqrt(n: int) -> int:
    import math
    return math.isqrt(n)


_f2 = (4 * P * P - _t2 * _t2) // 3
assert (4 * P * P - _t2 * _t2) % 3 == 0
_f = _isqrt(_f2)
assert _f * _f == _f2
_candidates = [
    P * P + 1 - (_t2 + 3 * _f) // 2,
    P * P + 1 - (_t2 - 3 * _f) // 2,
    P * P + 1 + (_t2 + 3 * _f) // 2,
    P * P + 1 + (_t2 - 3 * _f) // 2,
]
_twist_orders = [n for n in _candidates if n % R == 0 and pt_mul(FP2_FIELD, G2_GEN, n) is None]
assert _twist_orders, "no valid twist order found"
TWIST_ORDER = _twist_orders[0]
H2 = TWIST_ORDER // R

# --- untwist + pairing ----------------------------------------------------

FP12_FIELD = _Field(f12_add, f12_sub, f12_mul, f12_sqr, f12_neg, f12_inv, F12_ZERO, F12_ONE)

_XI_INV = f2_inv(XI)


def _f12_from_f2(c, pos: int = 0):
    coeffs = [F2_ZERO] * 6
    coeffs[pos] = c
    return tuple(coeffs)


def untwist(q_aff):
    """E'(Fp2) affine -> E(Fp12) affine: (x', y') -> (x' w^-2, y' w^-3);
    w^-2 = w^4/xi, w^-3 = w^3/xi."""
    if q_aff is None:
        return None
    x, y = q_aff
    return (
        _f12_from_f2(f2_mul(x, _XI_INV), 4),
        _f12_from_f2(f2_mul(y, _XI_INV), 3),
    )


def _embed_fp(a: int):
    return _f12_from_f2((a % P, 0), 0)


def _line(p1, p2, at):
    """Evaluate the line through p1, p2 (affine E(Fp12) points) at `at`.
    Returns the standard Miller line value (unnormalized)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = at
    if x1 != x2:
        m = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    elif y1 == y2:
        three_x1_sq = f12_mul(_embed_fp(3), f12_sqr(x1))
        m = f12_mul(three_x1_sq, f12_inv(f12_mul(_embed_fp(2), y1)))
    else:
        return f12_sub(xt, x1)  # vertical line
    return f12_sub(f12_mul(m, f12_sub(xt, x1)), f12_sub(yt, y1))


def _aff_add(F: _Field, p1, p2):
    return pt_to_affine(F, pt_add(F, pt_from_affine(F, p1), pt_from_affine(F, p2)))


def _aff_double(F: _Field, p1):
    return pt_to_affine(F, pt_double(F, pt_from_affine(F, p1)))


ATE_LOOP_COUNT = abs(X_PARAM)  # Miller loop runs over |x|; x < 0 handled by conjugation


def miller_loop(q_aff12, p_aff12):
    """f_{|x|,Q}(P) with Q, P affine points on E(Fp12); returns Fp12 element
    (before final exponentiation)."""
    if q_aff12 is None or p_aff12 is None:
        return F12_ONE
    f = F12_ONE
    t = q_aff12
    bits = bin(ATE_LOOP_COUNT)[3:]
    for bit in bits:
        f = f12_mul(f12_sqr(f), _line(t, t, p_aff12))
        t = _aff_double(FP12_FIELD, t)
        if bit == "1":
            f = f12_mul(f, _line(t, q_aff12, p_aff12))
            t = _aff_add(FP12_FIELD, t, q_aff12)
    # x < 0: f_{-n} = conj(f_n) up to final exponentiation
    return f12_conj(f)


# hard-part exponent of the final exponentiation, done by plain pow (safe,
# ~1500 bits); the easy part uses conj/inv/frobenius.
assert (P**4 - P**2 + 1) % R == 0
_HARD_EXP = (P**4 - P**2 + 1) // R


def final_exponentiation(f):
    # easy: f^((p^6 - 1)(p^2 + 1))
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frobenius(f, 2), f)
    # hard: f^((p^4 - p^2 + 1)/r)
    return f12_pow(f, _HARD_EXP)


def pairing(q_aff2, p_aff1, final_exp: bool = True):
    """e(P, Q) for P in G1 (affine Fp pair), Q in G2 (affine Fp2 pair)."""
    if q_aff2 is None or p_aff1 is None:
        return F12_ONE
    px, py = p_aff1
    p12 = (_embed_fp(px), _embed_fp(py))
    f = miller_loop(untwist(q_aff2), p12)
    return final_exponentiation(f) if final_exp else f


def multi_pairing(pairs) -> tuple:
    """prod e(P_i, Q_i): shares one final exponentiation across Miller loops."""
    f = F12_ONE
    for p_aff1, q_aff2 in pairs:
        if p_aff1 is None or q_aff2 is None:
            continue
        px, py = p_aff1
        p12 = (_embed_fp(px), _embed_fp(py))
        f = f12_mul(f, miller_loop(untwist(q_aff2), p12))
    return final_exponentiation(f)


# --- point (de)serialization: ZCash BLS12-381 format ----------------------

_COMP_FLAG = 0x80
_INF_FLAG = 0x40
_SIGN_FLAG = 0x20


def g1_to_bytes(aff) -> bytes:
    if aff is None:
        out = bytearray(48)
        out[0] = _COMP_FLAG | _INF_FLAG
        return bytes(out)
    x, y = aff
    flags = _COMP_FLAG | (_SIGN_FLAG if y > (P - 1) // 2 else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_from_bytes(data: bytes, subgroup_check: bool = True):
    """Decompress 48-byte G1 point; raises ValueError on invalid encoding."""
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMP_FLAG:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & _INF_FLAG:
        if any(data[1:]) or flags & _SIGN_FLAG or data[0] != (_COMP_FLAG | _INF_FLAG):
            raise ValueError("invalid G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x coordinate >= p")
    y = fp_sqrt((x * x * x + B_G1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if (y > (P - 1) // 2) != bool(flags & _SIGN_FLAG):
        y = P - y
    aff = (x, y)
    if subgroup_check and pt_mul(FP_FIELD, pt_from_affine(FP_FIELD, aff), R) is not None:
        raise ValueError("G1 point not in r-subgroup")
    return aff


def g2_to_bytes(aff) -> bytes:
    if aff is None:
        out = bytearray(96)
        out[0] = _COMP_FLAG | _INF_FLAG
        return bytes(out)
    (x0, x1), (y0, y1) = aff
    sign = y1 > (P - 1) // 2 if y1 != 0 else y0 > (P - 1) // 2
    flags = _COMP_FLAG | (_SIGN_FLAG if sign else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_from_bytes(data: bytes, subgroup_check: bool = True):
    """Decompress 96-byte G2 point; raises ValueError on invalid encoding."""
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMP_FLAG:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & _INF_FLAG:
        if any(data[1:]) or data[0] != (_COMP_FLAG | _INF_FLAG):
            raise ValueError("invalid G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x coordinate >= p")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), B_G2))
    if y is None:
        raise ValueError("G2 x not on twist curve")
    y0, y1 = y
    sign = y1 > (P - 1) // 2 if y1 != 0 else y0 > (P - 1) // 2
    if sign != bool(flags & _SIGN_FLAG):
        y = f2_neg(y)
    aff = (x, y)
    if subgroup_check and pt_mul(FP2_FIELD, pt_from_affine(FP2_FIELD, aff), R) is not None:
        raise ValueError("G2 point not in r-subgroup")
    return aff
