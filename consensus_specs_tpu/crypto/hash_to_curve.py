"""Hash-to-curve for BLS12-381 G2 (RFC 9380 structure).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fp2, m=2, L=64)
-> map_to_curve -> clear_cofactor (Budroni-Pintore endomorphism method).

map_to_curve status: the RFC suite BLS12381G2_XMD:SHA-256_SSWU_RO_ maps via
simplified SWU on a 3-isogenous curve E' (A'=240*I, B'=1012*(1+I), Z=-(2+I))
followed by the 3-isogeny to E. This module implements SSWU on E'; the isogeny
evaluation uses constants derived at import by isogeny.py (Velu). If
derivation is unavailable the module falls back to a deterministic
try-and-increment map — internally consistent (same message -> same G2 point,
uniform enough for tests) but NOT RFC-interoperable; the flag
MAP_TO_CURVE_RFC_COMPLIANT records which path is active.

The cofactor clearing uses psi (untwist-Frobenius-twist): h_eff action
[x^2-x-1]P + [x-1]psi(P) + psi^2(2P), the definition RFC 9380 G2 suites cite.
psi is validated at import against its characteristic equation.
"""
from __future__ import annotations

import hashlib

from .bls12_381 import (
    B_G2, F2_ONE, F2_ZERO, FP2_FIELD, P, X_PARAM, f2_add, f2_conj, f2_inv,
    f2_mul, f2_neg, f2_pow, f2_sqr, f2_sqrt, g2_on_curve, pt_add,
    pt_from_affine, pt_mul, pt_neg, pt_to_affine,
)

DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- expand_message_xmd (RFC 9380 section 5.3.1, H = SHA-256) --------------

_B_IN_BYTES = 32  # sha256 output
_R_IN_BYTES = 64  # sha256 block


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("expand_message_xmd: output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        mixed = bytes(a ^ c for a, c in zip(b0, prev))
        blocks.append(hashlib.sha256(mixed + bytes([i]) + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


# --- hash_to_field for Fp2 (m=2, L=64) -------------------------------------

_L = 64


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = DST_G2) -> list[tuple[int, int]]:
    uniform = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off:off + _L], "big") % P)
        out.append((coords[0], coords[1]))
    return out


# --- sgn0 for Fp2 (RFC 9380 section 4.1) -----------------------------------

def sgn0_fp2(x) -> int:
    a, b = x
    sign_0 = a % 2
    zero_0 = a == 0
    sign_1 = b % 2
    return sign_0 or (zero_0 and sign_1)


# --- SSWU on the 3-isogenous curve E': y^2 = x^3 + A'x + B' ----------------

A_ISO = (0, 240)          # 240 * I
B_ISO = (1012, 1012)      # 1012 * (1 + I)
Z_SSWU = (-2 % P, -1 % P)  # -(2 + I)


def _g_iso(x):
    return f2_add(f2_add(f2_mul(f2_sqr(x), x), f2_mul(A_ISO, x)), B_ISO)


def map_to_curve_sswu_iso(u) -> tuple:
    """Simplified SWU mapping u in Fp2 to a point on E' (the iso curve).
    RFC 9380 section 6.6.2 (straight-line version via sqrt, not sqrt_ratio —
    fine in a non-constant-time reference implementation)."""
    z = Z_SSWU
    zu2 = f2_mul(z, f2_sqr(u))
    tv1_denom = f2_add(f2_sqr(zu2), zu2)
    if tv1_denom == F2_ZERO:
        # exceptional case: x1 = B / (Z * A)
        x1 = f2_mul(B_ISO, f2_inv(f2_mul(z, A_ISO)))
    else:
        tv1 = f2_inv(tv1_denom)
        x1 = f2_mul(
            f2_mul(f2_neg(B_ISO), f2_inv(A_ISO)),
            f2_add(F2_ONE, tv1),
        )
    gx1 = _g_iso(x1)
    y1 = f2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = f2_mul(zu2, x1)
        gx2 = _g_iso(x2)
        y2 = f2_sqrt(gx2)
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square (impossible)"
        x, y = x2, y2
    if sgn0_fp2(u) != sgn0_fp2(y):
        y = f2_neg(y)
    return (x, y)


# --- isogeny E' -> E (derived) or fallback map -----------------------------

try:
    from .isogeny import ISO3_MAP  # (x', y') on E' -> (x, y) on E
    MAP_TO_CURVE_RFC_COMPLIANT = True
except ImportError:  # module not yet built — documented fallback path
    ISO3_MAP = None
    MAP_TO_CURVE_RFC_COMPLIANT = False


def _map_to_curve_try_inc(u) -> tuple:
    """Deterministic fallback: increment x from u until on-curve (NOT RFC
    interoperable; see module docstring)."""
    x = u
    while True:
        gx = f2_add(f2_mul(f2_sqr(x), x), B_G2)
        y = f2_sqrt(gx)
        if y is not None:
            if sgn0_fp2(u) != sgn0_fp2(y):
                y = f2_neg(y)
            return (x, y)
        x = f2_add(x, F2_ONE)


def map_to_curve_g2(u) -> tuple:
    if ISO3_MAP is not None:
        return ISO3_MAP(map_to_curve_sswu_iso(u))
    return _map_to_curve_try_inc(u)


# --- psi endomorphism + cofactor clearing ----------------------------------

from .bls12_381 import XI  # noqa: E402

assert (P - 1) % 3 == 0 and (P - 1) % 2 == 0
_PSI_CX = f2_inv(f2_pow(XI, (P - 1) // 3))
_PSI_CY = f2_inv(f2_pow(XI, (P - 1) // 2))


def psi(aff):
    """Twist endomorphism: twist . frobenius . untwist."""
    if aff is None:
        return None
    x, y = aff
    return (f2_mul(f2_conj(x), _PSI_CX), f2_mul(f2_conj(y), _PSI_CY))


def _validate_psi():
    # psi satisfies psi^2 - [t] psi + [p] = 0 on E'(Fp2), t = x + 1.
    probe = _map_to_curve_try_inc((5, 7))
    t = X_PARAM + 1
    p1 = pt_from_affine(FP2_FIELD, psi(psi(probe)))
    p2 = pt_mul(FP2_FIELD, pt_from_affine(FP2_FIELD, psi(probe)), abs(t))
    p2 = p2 if t >= 0 else pt_neg(FP2_FIELD, p2)
    p3 = pt_mul(FP2_FIELD, pt_from_affine(FP2_FIELD, probe), P)
    acc = pt_add(FP2_FIELD, p1, pt_neg(FP2_FIELD, p2))
    acc = pt_add(FP2_FIELD, acc, p3)
    assert acc is None, "psi endomorphism fails characteristic equation"


_validate_psi()


def clear_cofactor_g2(aff) -> tuple | None:
    """Budroni-Pintore: [x^2-x-1]P + [x-1]psi(P) + psi^2([2]P)."""
    if aff is None:
        return None
    F = FP2_FIELD
    p_j = pt_from_affine(F, aff)
    x = X_PARAM
    t1 = pt_mul(F, p_j, abs(x * x - x - 1))
    if x * x - x - 1 < 0:
        t1 = pt_neg(F, t1)
    psi_p = pt_from_affine(F, psi(aff))
    t2 = pt_mul(F, psi_p, abs(x - 1))
    if x - 1 < 0:
        t2 = pt_neg(F, t2)
    two_p = pt_to_affine(F, pt_mul(F, p_j, 2))
    t3 = pt_from_affine(F, psi(psi(two_p)))
    out = pt_add(F, pt_add(F, t1, t2), t3)
    return pt_to_affine(F, out)


# --- full hash_to_curve ----------------------------------------------------

def hash_to_curve_g2(msg: bytes, dst: bytes = DST_G2) -> tuple | None:
    """msg -> point in G2 (affine Fp2 pair). Follows hash_to_curve(RO):
    two field elements, two curve points, add, clear cofactor."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = map_to_curve_g2(u0)
    q1 = map_to_curve_g2(u1)
    F = FP2_FIELD
    q = pt_to_affine(F, pt_add(F, pt_from_affine(F, q0), pt_from_affine(F, q1)))
    out = clear_cofactor_g2(q)
    assert out is None or g2_on_curve(out)
    return out
