"""Proof-of-custody crypto: Legendre-symbol PRF and the custody-bit pipeline.

Reference parity: specs/custody_game/beacon-chain.md — `legendre_bit` (:263),
`get_custody_atoms` (:285), `get_custody_secrets` (:303),
`universal_hash_function` (:318), `compute_custody_bit` (:331), and the
period helpers `get_randao_epoch_for_custody_period` /
`get_custody_period_for_validator` (:340-360). Constants: CUSTODY_PRIME =
2^256 - 189, CUSTODY_SECRETS = 3, BYTES_PER_CUSTODY_ATOM = 32,
CUSTODY_PROBABILITY_EXPONENT = 10 (:69-72).

The custody bit says "I held this data": a validator derives secrets from its
period's RANDAO signature, hashes the data atoms through a polynomial
universal hash keyed by the secrets, and the bit is the AND of 10 Legendre
bits of consecutive shifts — a PRF an adversary without the signature cannot
compute. Legendre bits are Euler's criterion a^((q-1)/2) mod q (CUSTODY_PRIME
is prime, so the Jacobi iteration the reference uses and the modexp used here
agree); `legendre_bits_batch` evaluates many shifts at once and is the TPU
target shape (batched 256-bit modexp — each bit is one vmapped limb-exp).
"""
from __future__ import annotations

from .bls import signature_to_G2

CUSTODY_PRIME = 2**256 - 189
CUSTODY_SECRETS = 3
BYTES_PER_CUSTODY_ATOM = 32
CUSTODY_PROBABILITY_EXPONENT = 10

EPOCHS_PER_CUSTODY_PERIOD = 2**14
CUSTODY_PERIOD_TO_RANDAO_PADDING = 2**11
MAX_CHUNK_CHALLENGE_DELAY = 2**15


def legendre_bit(a: int, q: int) -> int:
    """Legendre symbol (a|q) normalized to a bit: QR -> 1, non-QR / 0 -> 0.

    q must be an odd prime (Euler's criterion); the reference computes the
    same value with a binary Jacobi iteration."""
    a %= q
    if a == 0:
        return 0
    return 1 if pow(a, (q - 1) // 2, q) == 1 else 0


def legendre_bits_batch(values: list[int], q: int = CUSTODY_PRIME) -> list[int]:
    """Batched PRF evaluation — the shape the TPU kernel takes over."""
    return [legendre_bit(v, q) for v in values]


def get_custody_atoms(data: bytes) -> list[bytes]:
    """Right-pad to a whole number of 32-byte atoms and split."""
    pad = (BYTES_PER_CUSTODY_ATOM - len(data) % BYTES_PER_CUSTODY_ATOM) % BYTES_PER_CUSTODY_ATOM
    padded = data + b"\x00" * pad
    return [padded[i : i + BYTES_PER_CUSTODY_ATOM] for i in range(0, len(padded), BYTES_PER_CUSTODY_ATOM)]


def get_custody_secrets(key: bytes) -> list[int]:
    """Secrets = 32-byte little-endian windows over the signature's G2 x-coord
    (two Fp coefficients, 48 bytes each, little-endian)."""
    x_coord = signature_to_G2(key)[0]
    if not isinstance(x_coord, (tuple, list)):
        # bls kill-switch stub path (bls.bls_active == False): the shim
        # returns scalar stub coordinates; keep the fast-test contract alive
        # with a deterministic zero-ish Fp2 coordinate.
        x_coord = (int(x_coord), 0)
    signature_bytes = b"".join(c.to_bytes(48, "little") for c in x_coord)
    return [
        int.from_bytes(signature_bytes[i : i + BYTES_PER_CUSTODY_ATOM], "little")
        for i in range(0, len(signature_bytes), 32)
    ]


def universal_hash_function(data_chunks: list[bytes], secrets: list[int]) -> int:
    """Polynomial universal hash over CUSTODY_PRIME with cycling secret keys,
    plus a length-binding term secrets[n % 3]^n."""
    n = len(data_chunks)
    acc = 0
    for i, atom in enumerate(data_chunks):
        key = secrets[i % CUSTODY_SECRETS]
        acc = (acc + pow(key, i, CUSTODY_PRIME) * int.from_bytes(atom, "little")) % CUSTODY_PRIME
    return (acc + pow(secrets[n % CUSTODY_SECRETS], n, CUSTODY_PRIME)) % CUSTODY_PRIME


def compute_custody_bit(key: bytes, data: bytes) -> int:
    """AND of CUSTODY_PROBABILITY_EXPONENT Legendre bits at consecutive
    shifts of the UHF digest."""
    atoms = get_custody_atoms(data)
    secrets = get_custody_secrets(key)
    uhf = universal_hash_function(atoms, secrets)
    bits = legendre_bits_batch([uhf + secrets[0] + i for i in range(CUSTODY_PROBABILITY_EXPONENT)])
    return 1 if all(bits) else 0


def get_randao_epoch_for_custody_period(period: int, validator_index: int) -> int:
    next_period_start = (period + 1) * EPOCHS_PER_CUSTODY_PERIOD - validator_index % EPOCHS_PER_CUSTODY_PERIOD
    return next_period_start + CUSTODY_PERIOD_TO_RANDAO_PADDING


def get_custody_period_for_validator(validator_index: int, epoch: int) -> int:
    return (epoch + validator_index % EPOCHS_PER_CUSTODY_PERIOD) // EPOCHS_PER_CUSTODY_PERIOD
