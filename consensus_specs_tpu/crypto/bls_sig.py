"""IETF BLS signatures over BLS12-381 (ciphersuite G2_XMD:SHA-256_SSWU_RO_POP_).

Scheme-level API in the byte domain (48-byte compressed pubkeys, 96-byte
compressed signatures) matching the surface the reference consumes from
py_ecc/milagro (eth2spec/utils/bls.py:47-110): Sign, Verify, Aggregate,
AggregateVerify, FastAggregateVerify, AggregatePKs, SkToPk, KeyValidate.

Invalid inputs (bad encodings, off-curve, wrong subgroup, infinity pubkeys)
make verification return False rather than raise — the behavior the
conformance BLS vectors demand.
"""
from __future__ import annotations

from functools import lru_cache

from . import bls12_381 as c
from .hash_to_curve import hash_to_curve_g2

G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95


def SkToPk(privkey: int) -> bytes:
    if not 0 < privkey < c.R:
        raise ValueError("privkey out of range")
    return c.g1_to_bytes(c.pt_to_affine(c.FP_FIELD, c.pt_mul(c.FP_FIELD, c.G1_GEN, privkey)))


def KeyValidate(pubkey: bytes) -> bool:
    try:
        pk = c.g1_from_bytes(bytes(pubkey))
    except ValueError:
        return False
    return pk is not None  # infinity pubkey is invalid


def Sign(privkey: int, message: bytes) -> bytes:
    if not 0 < privkey < c.R:
        raise ValueError("privkey out of range")
    h = hash_to_curve_g2(bytes(message))
    sig = c.pt_to_affine(c.FP2_FIELD, c.pt_mul(c.FP2_FIELD, c.pt_from_affine(c.FP2_FIELD, h), privkey))
    return c.g2_to_bytes(sig)


def signature_to_point(signature: bytes):
    return c.g2_from_bytes(bytes(signature))


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    try:
        pk = c.g1_from_bytes(bytes(pubkey))
        sig = c.g2_from_bytes(bytes(signature))
    except ValueError:
        return False
    if pk is None:  # infinity pubkey always invalid
        return False
    h = hash_to_curve_g2(bytes(message))
    # e(pk, H(m)) == e(G1, sig)  <=>  e(-G1, sig) * e(pk, H(m)) == 1
    neg_g1 = (c.G1_GEN_AFF[0], c.P - c.G1_GEN_AFF[1])
    return c.multi_pairing([(neg_g1, sig), (pk, h)]) == c.F12_ONE


@lru_cache(maxsize=1 << 16)
def _sig_point_memo(signature: bytes):
    """Decompressed, subgroup-checked G2 point for one compressed signature.

    Decompression pays an Fp2 sqrt plus a full scalar-mul subgroup check;
    a streaming aggregation workload (the attestation firehose) decodes
    the same committee signatures on every re-sighting, so the memo turns
    the dominant admission cost into a dict hit. Pure and deterministic
    (points are nested int tuples), bounded so an adversarial stream of
    unique garbage cannot grow it without bound; ValueErrors are not
    cached by lru_cache, so malformed bytes keep raising."""
    return c.g2_from_bytes(bytes(signature))


def clear_sig_point_cache() -> None:
    _sig_point_memo.cache_clear()


def Aggregate(signatures) -> bytes:
    if len(signatures) == 0:
        raise ValueError("Aggregate requires at least one signature")
    acc = None
    for s in signatures:
        pt = _sig_point_memo(bytes(s))
        acc = c.pt_add(c.FP2_FIELD, acc, c.pt_from_affine(c.FP2_FIELD, pt))
    return c.g2_to_bytes(c.pt_to_affine(c.FP2_FIELD, acc))


def AggregatePKs(pubkeys) -> bytes:
    if len(pubkeys) == 0:
        raise ValueError("AggregatePKs requires at least one pubkey")
    acc = None
    for p in pubkeys:
        pt = c.g1_from_bytes(bytes(p))
        if pt is None:
            raise ValueError("cannot aggregate infinity pubkey")
        acc = c.pt_add(c.FP_FIELD, acc, c.pt_from_affine(c.FP_FIELD, pt))
    return c.g1_to_bytes(c.pt_to_affine(c.FP_FIELD, acc))


def AggregateVerify(pubkeys, messages, signature: bytes) -> bool:
    if len(pubkeys) == 0 or len(pubkeys) != len(messages):
        return False
    try:
        sig = c.g2_from_bytes(bytes(signature))
        pks = [c.g1_from_bytes(bytes(p)) for p in pubkeys]
    except ValueError:
        return False
    if any(pk is None for pk in pks):
        return False
    pairs = [((c.G1_GEN_AFF[0], c.P - c.G1_GEN_AFF[1]), sig)]
    for pk, msg in zip(pks, messages):
        pairs.append((pk, hash_to_curve_g2(bytes(msg))))
    return c.multi_pairing(pairs) == c.F12_ONE


def FastAggregateVerify(pubkeys, message: bytes, signature: bytes) -> bool:
    if len(pubkeys) == 0:
        return False
    try:
        agg_pk = AggregatePKs(pubkeys)
    except ValueError:
        return False
    return Verify(agg_pk, message, signature)
