"""KZG10 polynomial commitments over BLS12-381 (sharding/DAS crypto layer).

Reference parity: the sharding spec's commitment machinery —
`DataCommitment`/degree-proof containers and the pairing checks in
`process_shard_header` (specs/sharding/beacon-chain.md:241-249,675-766:
`e(degree_proof, G2) == e(commitment, G2_SETUP[-points_count])`), the trusted
setup constants `G1_SETUP`/`G2_SETUP`/`ROOT_OF_UNITY` (:170-174), and the DAS
spec's `check_multi_kzg_proof` (specs/das/das-core.md:131-137). The reference
never ships executable KZG (its sharding fork is R&D-only and uncompiled);
here the full commit/open/verify path is implemented and tested.

Layering:
- polynomial arithmetic over Fr: host ints here; batch/FFT paths ride the
  ops/fr_jax.py NTT kernels (domains are the same 2-adic roots of unity);
- group/pairing ops: crypto/bls12_381.py pure-Python oracle. MSM commit on
  device is a later optimization target (Pippenger over ops/bls12_jax.py);
- the trusted setup here is an INSECURE deterministic test setup (the secret
  is derived from a fixed tag) — mainnet setups come from a ceremony and are
  loaded as data, exactly as the reference treats G1_SETUP/G2_SETUP as
  externally-supplied constants.
"""
from __future__ import annotations

from dataclasses import dataclass
from hashlib import sha256

# fr_host (not fr_jax): the polynomial-commitment host math must stay
# importable in jax-free processes (PR-3 deferred-import discipline, enforced
# by tpulint's import-layering pass — crypto/kzg_shim.py and crypto/das.py
# sit on this module's import chain).
from ..ops.fr_host import R_MODULUS, root_of_unity
from .bls12_381 import (
    F12_ONE,
    FP2_FIELD,
    FP_FIELD,
    G1_GEN,
    G2_GEN,
    g1_to_bytes,
    multi_pairing,
    pt_add,
    pt_mul,
    pt_neg,
    pt_to_affine,
)

MODULUS = R_MODULUS  # curve order; sharding spec's `MODULUS` (:107)


# --- polynomial helpers (host ints mod r) -----------------------------------


def eval_poly_at(coeffs: list[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % MODULUS
    return acc


def poly_quotient_linear(coeffs: list[int], z: int, y: int) -> list[int]:
    """(P(x) - y) / (x - z) by synthetic division; exact iff P(z) == y."""
    n = len(coeffs)
    q = [0] * (n - 1)
    carry = 0
    for i in range(n - 1, 0, -1):
        carry = (coeffs[i] + carry * z) % MODULUS
        q[i - 1] = carry
    remainder = (coeffs[0] + carry * z - y) % MODULUS
    assert remainder == 0, "point not on polynomial"
    return q


def interpolate_on_domain(values: list[int], shift: int = 1) -> list[int]:
    """Coefficients of the unique poly with P(shift·w^i) = values[i] over the
    n-th-root domain (n = len(values), power of two): inverse DFT + unshift."""
    n = len(values)
    w_inv = pow(root_of_unity(n), MODULUS - 2, MODULUS)
    n_inv = pow(n, MODULUS - 2, MODULUS)
    coeffs = []
    for i in range(n):
        acc = 0
        for j, v in enumerate(values):
            acc = (acc + v * pow(w_inv, i * j, MODULUS)) % MODULUS
        coeffs.append(acc * n_inv % MODULUS)
    if shift != 1:
        s_inv = pow(shift, MODULUS - 2, MODULUS)
        scale = 1
        for i in range(n):
            coeffs[i] = coeffs[i] * scale % MODULUS
            scale = scale * s_inv % MODULUS
    return coeffs


# --- trusted setup -----------------------------------------------------------


@dataclass(frozen=True)
class KZGSetup:
    """`G1_SETUP` / `G2_SETUP` of the sharding spec (:170-173): powers of a
    secret s on both curve sides; first entry is the generator."""

    g1: tuple  # tuple of Jacobian points, g1[i] = s^i * G1
    g2: tuple
    length: int

    @property
    def max_degree(self) -> int:
        return self.length - 1


def insecure_test_setup(n: int, tag: bytes = b"consensus-specs-tpu kzg test setup") -> KZGSetup:
    """Deterministic setup for tests; the 'secret' is public by construction."""
    s = int.from_bytes(sha256(tag).digest(), "little") % MODULUS
    g1, g2, acc = [], [], 1
    for _ in range(n):
        g1.append(pt_mul(FP_FIELD, G1_GEN, acc))
        g2.append(pt_mul(FP2_FIELD, G2_GEN, acc))
        acc = acc * s % MODULUS
    return KZGSetup(g1=tuple(g1), g2=tuple(g2), length=n)


# --- commit / prove / verify -------------------------------------------------


def _msm(field, points, scalars):
    """sum scalars[i]·points[i] over either group (host double-and-add; the
    device Pippenger kernel is the planned replacement)."""
    acc = None
    for pt, k in zip(points, scalars):
        k %= MODULUS
        if k == 0 or pt is None:
            continue
        term = pt_mul(field, pt, k)
        acc = term if acc is None else pt_add(field, acc, term)
    return acc


def _msm_g1(setup_points, scalars):
    return _msm(FP_FIELD, setup_points, scalars)


def commit(setup: KZGSetup, coeffs: list[int]):
    """C = P(s)·G1, computed as an MSM over the G1 setup. Returns Jacobian."""
    assert len(coeffs) <= setup.length, "polynomial exceeds setup degree"
    return _msm_g1(setup.g1, coeffs)


def commit_bytes(setup: KZGSetup, coeffs: list[int]) -> bytes:
    """Compressed 48-byte `BLSCommitment` (sharding spec :92)."""
    return g1_to_bytes(pt_to_affine(FP_FIELD, commit(setup, coeffs)))


def _pairings_equal(a1, a2, b1, b2) -> bool:
    """e(a1, a2) == e(b1, b2) via one multi-pairing: e(a1,a2)·e(-b1,b2) == 1."""
    nb1 = None if b1 is None else pt_neg(FP_FIELD, b1)
    aff = lambda F, p: None if p is None else pt_to_affine(F, p)
    res = multi_pairing(
        [
            (aff(FP_FIELD, a1), aff(FP2_FIELD, a2)),
            (aff(FP_FIELD, nb1), aff(FP2_FIELD, b2)),
        ]
    )
    return res == F12_ONE


def prove_degree_bound(setup: KZGSetup, coeffs: list[int], points_count: int):
    """Degree proof for `deg P < points_count`: commit to x^(M+1-k)·P(x)
    (sharding spec :716-719,766 — the shifted poly only fits in the setup if
    the bound holds)."""
    k = points_count
    assert 0 < k <= setup.max_degree + 1, "bound outside setup range"
    shift = setup.max_degree + 1 - k
    assert len(coeffs) <= k, "cannot prove a bound the polynomial violates"
    shifted = [0] * shift + list(coeffs)
    return commit(setup, shifted)


def verify_degree_proof(setup: KZGSetup, commitment, degree_proof, points_count: int) -> bool:
    """e(degree_proof, G2) == e(commitment, G2·s^(M+1-k)) (spec :716-719).

    An out-of-range bound claim is a rejection, never an index-wrap onto a
    different setup power (points_count is attacker-controlled input)."""
    k = points_count
    if not 0 < k <= setup.max_degree + 1:
        return False
    return _pairings_equal(
        degree_proof, setup.g2[0], commitment, setup.g2[setup.max_degree + 1 - k]
    )


def prove_at(setup: KZGSetup, coeffs: list[int], z: int):
    """Opening proof at z: commit to (P(x) - P(z)) / (x - z)."""
    y = eval_poly_at(coeffs, z)
    q = poly_quotient_linear(coeffs, z, y)
    return commit(setup, q), y


def verify_at(setup: KZGSetup, commitment, z: int, y: int, proof) -> bool:
    """e(proof, s·G2 - z·G2) == e(C - y·G1, G2)."""
    z_g2 = pt_mul(FP2_FIELD, G2_GEN, z % MODULUS)
    s_minus_z = pt_add(FP2_FIELD, setup.g2[1], pt_neg(FP2_FIELD, z_g2)) if z_g2 is not None else setup.g2[1]
    y_g1 = pt_mul(FP_FIELD, G1_GEN, y % MODULUS)
    c_minus_y = commitment if y_g1 is None else pt_add(FP_FIELD, commitment, pt_neg(FP_FIELD, y_g1))
    return _pairings_equal(proof, s_minus_z, c_minus_y, setup.g2[0])


def prove_coset(setup: KZGSetup, coeffs: list[int], coset_shift: int, m: int):
    """Multi-point proof over the coset {shift·w^i} of the m-th roots:
    commit to Q = (P - I) / Z with Z(x) = x^m - shift^m (the coset's
    vanishing poly) and I the degree-<m interpolant of P on the coset.
    This is the DAS spec's multi-proof shape (das-core.md:131-137)."""
    w = root_of_unity(m)
    ys = [eval_poly_at(coeffs, coset_shift * pow(w, i, MODULUS) % MODULUS) for i in range(m)]
    i_coeffs = interpolate_on_domain(ys, shift=coset_shift)
    # numerator N = P - I
    n_coeffs = list(coeffs)
    for i, c in enumerate(i_coeffs):
        n_coeffs[i] = (n_coeffs[i] - c) % MODULUS
    # divide by Z(x) = x^m - shift^m: long division, stride m
    zm = pow(coset_shift, m, MODULUS)
    q = [0] * max(len(n_coeffs) - m, 0)
    rem = list(n_coeffs)
    for i in range(len(n_coeffs) - 1, m - 1, -1):
        q[i - m] = rem[i]
        rem[i] = 0
        rem[i - m] = (rem[i - m] + q[i - m] * zm) % MODULUS
    assert all(r == 0 for r in rem), "coset values not on polynomial"
    return commit(setup, q) if q else None, ys


def verify_coset(setup: KZGSetup, commitment, coset_shift: int, ys: list[int], proof) -> bool:
    """e(proof, commit_G2(Z)) == e(C - commit_G1(I), G2)  — `check_multi_kzg_proof`.

    ys length is untrusted (it arrives inside a network sample): reject
    rather than crash when it is empty, not a power of two (no NTT domain),
    or beyond the setup (setup.g2[m] must exist)."""
    m = len(ys)
    if m == 0 or m & (m - 1) != 0 or m > setup.max_degree:
        return False
    zm = pow(coset_shift, m, MODULUS)
    # Z(x) = x^m - shift^m on the G2 side
    z_g2 = pt_add(
        FP2_FIELD, setup.g2[m], pt_neg(FP2_FIELD, pt_mul(FP2_FIELD, G2_GEN, zm))
    )
    i_coeffs = interpolate_on_domain(ys, shift=coset_shift)
    i_commit = _msm_g1(setup.g1, i_coeffs)
    c_minus_i = (
        commitment if i_commit is None else pt_add(FP_FIELD, commitment, pt_neg(FP_FIELD, i_commit))
    )
    return _pairings_equal(proof, z_g2, c_minus_i, setup.g2[0])
