# Build/test orchestration. Reference parity: the reference Makefile's
# test / citest / lint / generate_tests / pyspec / detect_generator_incomplete
# surface (Makefile:90-199), adapted to this repo's layout (no venv juggling:
# the environment is pre-baked; no markdown build step at test time: the spec
# compiler execs markdown on import).

PYTHON ?= python
TEST_VECTOR_DIR ?= ../consensus-spec-tests/tests
GENERATORS = bls ssz_generic ssz_static shuffling operations epoch_processing \
             sanity genesis finality rewards fork_choice forks transition \
             merkle random custody_sharding scenarios

.PHONY: test testall citest testfast chaos sched msm firehose scenarios proofs forkchoice frontdoor slo lint lint-fast pyspec generate_tests \
        clean_vectors detect_generator_incomplete bench bench_quick \
        bench-probe graft_check native replay random_codegen coverage \
        deposit_contract_json

# Default developer loop: full suite (minimal preset, BLS stubbed where the
# suite chooses; JAX pinned to the virtual 8-device CPU mesh by tests/conftest.py).
test:
	$(PYTHON) -m pytest tests/ -x -q -m "not slow"

# Everything, including the multi-minute compile-bound crypto tests the
# default lane defers (reference Makefile:98-100 keeps a fast-minimal
# default too; nothing is deleted — this lane runs it all).
testall:
	$(PYTHON) -m pytest tests/ -q

# CI profile: no -x, junit output, ALL tests.
citest:
	$(PYTHON) -m pytest tests/ -q --junitxml=test-results/junit.xml

# Quick sanity loop: skip every device-pairing test.
testfast:
	$(PYTHON) -m pytest tests/ -x -q -k "not pairing"

# Fault-tolerance lane: the robustness unit suite plus the seeded chaos
# convergence runs (faults at every device-boundary seam must leave the
# state root bit-identical to the fault-free oracle — see README "Fault
# tolerance"). Deterministic schedules only; the long randomized soak is
# marked `slow` and runs in testall/citest. Hard wall-clock bound so a
# retry/backoff regression hangs the lane loudly instead of silently.
# The run writes the canonical obs snapshot (every fault/retry/breaker
# counter the chaos schedules ticked) to test-results/ and validates it —
# CI uploads it as the chaos lane's observability artifact.
chaos:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_chaos.json OBS_SNAPSHOT_LANE=chaos \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_chaos_epoch.py tests/test_robustness.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_chaos.json

# Unified verification scheduler lane: admission/collapse/backpressure
# mechanics, device-vs-host lane agreement, and the compile-cache pin
# (one XLA compile per (class, bucket)) — see README "Verification
# scheduler". Writes + validates the lane's obs snapshot like chaos does;
# the scheduler's own counters/gauges/histograms are the artifact.
sched:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_sched.json OBS_SNAPSHOT_LANE=sched \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_sched.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_sched.json

# Pippenger MSM lane: the bucket-MSM kernel's cost pins (eval_shape loop
# counts, point-op budget), host-oracle equivalence on edge batches, the
# sched "msm" work class (compile-per-bucket pin, chaos corrupt faults,
# 2G2T self-check), and the cold-lane committee aggregation regression —
# see README "Pippenger MSM". Obs snapshot validated like the sibling
# lanes; the msm-class sched_* and bls_pubkey_*_device series are the
# artifact.
msm:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_msm.json OBS_SNAPSHOT_LANE=msm \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_msm.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_msm.json

# Attestation firehose lane: the streaming gossip->aggregate->flush
# service (ingest dedup, committee collapse, double-buffered flush,
# backpressure) plus the gossip driver's partial-drain seam it consumes —
# see README "Attestation firehose". Obs snapshot validated like the
# chaos/sched lanes; the firehose_* series are the artifact.
firehose:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_firehose.json OBS_SNAPSHOT_LANE=firehose \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_firehose.py tests/test_gossip_driver.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_firehose.json

# Scenario-engine lane: seeded long-horizon histories (reorg storms, fork
# ladders, equivocation waves, droughts) replayed through the oracle /
# chaos-engine / firehose lanes with bit-identical checkpoint assertions,
# plus the emit->replay->diff bidirectional conformance loop — see README
# "Scenario engine". The ≥2,000-slot soak is @slow (testall/citest only);
# this lane stays bounded for the inner loop. Obs snapshot validated like
# the chaos/sched/firehose lanes; the scenario_* series are the artifact.
scenarios:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_scenarios.json OBS_SNAPSHOT_LANE=scenarios \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_scenarios.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_scenarios.json

# Light-client read lane: device-batched Merkle multiproofs (ops +
# engine + the sched "multiproof" kind) pinned against the ssz host
# oracle, plus the dirty-column proof cache and its service — see README
# "Read path". Obs snapshot validated like the chaos/sched/firehose
# lanes; the proof_* series are the artifact.
proofs:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_proofs.json OBS_SNAPSHOT_LANE=proofs \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_proofs.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_proofs.json

# Fork-choice head lane: the device-resident LMD-GHOST tracker (ops +
# engine + the sched "forkchoice" kind + forkchoice/ service) pinned
# bit-identical against the spec's get_head across the three scenario
# lanes, chaos and breaker-open hard-down included — see README "Fork
# choice". Obs snapshot validated like the sibling lanes; the
# forkchoice_* series are the artifact.
forkchoice:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_forkchoice.json OBS_SNAPSHOT_LANE=forkchoice \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_forkchoice.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_forkchoice.json

# Front-door admission lane: the unified admission plane over the four
# service lanes (frontdoor/ + the scheduler's EDF seal-policy seam) —
# per-tenant quotas, the shed ladder, deadline sealing, and the three
# seeded traffic profiles replayed bit-identically under chaos — see
# README "Front door". Obs snapshot validated like the sibling lanes;
# the frontdoor_* series are the artifact.
frontdoor:
	mkdir -p test-results
	OBS_SNAPSHOT=test-results/obs_frontdoor.json OBS_SNAPSHOT_LANE=frontdoor \
	OBS_FLIGHT_DIR=test-results \
	timeout -k 10 600 $(PYTHON) -m pytest \
	    tests/test_frontdoor.py -q -m "not slow"
	$(PYTHON) tools/obs_dump.py check test-results/obs_frontdoor.json

# Declarative SLO gate (slo.json at the repo root): the bench trajectory
# and obs-snapshot invariants as machine-checked objectives — see README
# "Observability" and the SLO table in BASELINE.md. Evaluates the shipped
# BENCH_OBS.json plus whatever lane snapshots the sibling targets left in
# test-results/, against BENCH_LOCAL.json history; rc != 0 names the
# violated SLO. bench.py embeds the same verdict in every record it
# persists; this target is the standalone/CI entry point.
slo:
	$(PYTHON) tools/slo_check.py --bench BENCH_LOCAL.json \
	    BENCH_OBS.json $(wildcard test-results/obs_*.json)

# Compile-check every module and spec document (the exec-based analog of the
# reference's `make pyspec` build of eth2spec modules). With ARTIFACTS=1 the
# flattened per-(fork x preset) sources are ALSO written to build/specs/ and
# the emission is proven deterministic: each file is rendered twice and the
# two renders must be byte-identical (CI runs this same check).
pyspec:
	$(PYTHON) -m compileall -q consensus_specs_tpu generators tests bench.py __graft_entry__.py
	$(PYTHON) -c "from consensus_specs_tpu.compiler import get_spec; \
	    [get_spec(f, p) for f in ('phase0','altair','bellatrix') for p in ('minimal','mainnet')]; \
	    print('all fork x preset spec modules compile')"
ifeq ($(ARTIFACTS),1)
	$(PYTHON) -c "\
	from consensus_specs_tpu.compiler.spec_compiler import emit_spec_artifact, render_spec_source; \
	pairs = [(f, p) for f in ('phase0','altair','bellatrix') for p in ('minimal','mainnet')]; \
	paths = [emit_spec_artifact(f, p) for f, p in pairs]; \
	stale = [str(pth) for (f, p), pth in zip(pairs, paths) \
	         if pth.read_text() != render_spec_source(f, p)]; \
	assert not stale, f'non-deterministic emission: {stale}'; \
	print('spec artifacts (x2, byte-identical):'); \
	[print(' ', pth) for pth in paths]"
endif

# Static gate: compile-check + AST lint (unused imports, import shadowing,
# mutable defaults, tuple asserts, bare excepts) + tpulint (JAX hot-path
# invariants: jit purity, dtype pinning, donation aliasing, import layering,
# scatter bans, lock discipline, guarded fields, thread escapes — see
# BASELINE.md). The reference's flake8+mypy role (linter.ini) — those tools
# are not in this image. --max-seconds 30 is the runtime ratchet: the
# interprocedural fixpoints must stay a sub-minute gate as the tree grows
# (per-rule cost is visible via `tpulint --json` timings_s).
lint: pyspec
	$(PYTHON) tools/lint.py
	$(PYTHON) tools/typegate.py
	$(PYTHON) tools/tpulint.py consensus_specs_tpu --baseline tpulint_baseline.json --max-seconds 30
	$(PYTHON) tools/tpulint.py --self-test

# Inner-loop lint: full interprocedural analysis (the call graph needs every
# module), but only findings on files changed since $(SINCE) are reported —
# seconds of signal on the file you are editing, no baseline noise from the
# rest of the tree. `make lint-fast SINCE=origin/main` before pushing.
SINCE ?= HEAD
lint-fast:
	$(PYTHON) tools/tpulint.py consensus_specs_tpu --since $(SINCE)

# Regenerate the checked-in randomized test module (reference:
# tests/generators/random/generate.py workflow).
random_codegen:
	$(PYTHON) generators/random/generate.py

# Run every vector generator into TEST_VECTOR_DIR (reference: make generate_tests).
generate_tests: $(addprefix gen_,$(GENERATORS))

# Generation is a pure-host lane (never blocks on a TPU tunnel): pin the
# CPU backend and verify through the batched XLA pairing kernels — the
# reference generates with milagro instead of py_ecc for the same reason.
gen_%:
	CONSENSUS_TPU_GEN_BLS=jax JAX_PLATFORMS=cpu \
	$(PYTHON) generators/$*/main.py -o $(TEST_VECTOR_DIR)

clean_vectors:
	rm -rf $(TEST_VECTOR_DIR)

# Crash forensics: list INCOMPLETE sentinels left by a crashed generator run
# (reference Makefile:195-199).
detect_generator_incomplete:
	@find $(TEST_VECTOR_DIR) -name INCOMPLETE 2>/dev/null || true

# Replay a vector tree (ours or an external consensus-spec-tests corpus)
# against the compiled specs; non-zero exit on any mismatch.
replay:
	$(PYTHON) -m consensus_specs_tpu.conformance $(TEST_VECTOR_DIR)

# Native components (ctypes-loaded C++).
native:
	$(MAKE) -C consensus_specs_tpu/native

bench:
	$(PYTHON) bench.py

# Fast TPU provenance re-capture (VERDICT r3 item 5): small batches +
# fewer repeats, reusing the persistent XLA compile cache — appends a
# BENCH_LOCAL.json entry at the current sha whenever the tunnel is up.
# Target <5 min warm so every perf commit can re-prove itself on TPU.
bench_quick:
	BENCH_BLS_N=512 BENCH_E2E_RESIDENT_EPOCHS=6 BENCH_KZG_BLOBS=32 \
	BENCH_ATT_VALIDATORS=32768 BENCH_SR_VALIDATORS=262144 \
	BENCH_E2E_VALIDATORS=1048576 BENCH_PROOF_VALIDATORS=1048576 \
	BENCH_PROOF_QUERIES=2048 $(PYTHON) bench.py

# TPU-opportunistic bench loop: retry the probe until the tunnel answers,
# then run the bench_quick lane on the device; every attempt (success or
# probe failure) appends a provenance record to BENCH_LOCAL.json.
# Bounded by default so CI can run it without hanging on a dead tunnel;
# override e.g. `make bench-probe PROBE_ARGS="--max-tries 0 --interval 300"`.
PROBE_ARGS ?= --max-tries 3 --interval 30
bench-probe:
	$(PYTHON) tools/bench_probe.py $(PROBE_ARGS)

# Regenerate the checked-in deposit contract artifact from the in-repo
# assembler (consensus_specs_tpu/evm/deposit_contract_asm.py). The JSON is a
# conformance anchor: tests/test_deposit_contract_evm.py fails if it drifts.
deposit_contract_json:
	$(PYTHON) -m consensus_specs_tpu.evm.build
	$(PYTHON) -m consensus_specs_tpu.evm.build --check

# What the driver compile-checks: single-chip entry + 8-device CPU-mesh dry
# run. The axon sitecustomize imports jax at interpreter start (freezing
# jax_platforms), so env vars alone don't stick — force the CPU mesh the way
# tests/conftest.py does.
graft_check:
	$(PYTHON) -c "\
	from consensus_specs_tpu.utils.backend import force_cpu; force_cpu(8); \
	import __graft_entry__ as g; fn, args = g.entry(); fn(*args); \
	g.dryrun_multichip(8); print('graft entry ok')"

# Line coverage over consensus_specs_tpu via stdlib sys.monitoring
# (tools/coverage.py — the environment has no pytest-cov; reference
# gates with --cov, Makefile:100). COVERAGE_MIN gates the build.
COVERAGE_MIN ?= 85
coverage:
	$(PYTHON) tools/coverage.py --min $(COVERAGE_MIN) -- -m pytest tests/ -q -m "not slow"
