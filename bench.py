"""Headline benchmark — BOTH BASELINE.md north stars, one JSON line.

1. `bls_verify_throughput` (the headline metric/value): aggregate BLS
   signature verifications per second on one chip — batched
   e(pk_i, H(m_i))·e(-G1, sig_i) == 1 checks through the RNS pairing kernels
   (ops/bls12_jax.py over ops/fp_rns.py). Target >= 100k/s (BASELINE.json);
   `vs_baseline` is measured/target.
2. `extra.process_epoch_s` (+ `extra.epoch_validators` for the size it ran
   at): mainnet-preset altair `process_epoch` device wall-clock (target
   < 2 s at 1M validators; the `process_epoch_1m_s` alias is emitted only
   when the run really is >=1M). `extra.epoch_vs_baseline` = 2.0/measured,
   emitted only for unclamped accelerator runs — the cpu-debug lane
   carries NO `*_vs_baseline` ratios.

The reference publishes no numbers (BASELINE.json `published: {}`), so both
baselines are the BASELINE.json targets. Host prep (decompression,
hash-to-curve) is excluded from the BLS timed region: pubkeys live
decompressed in the registry and messages hash once per slot, so the pairing
is the marginal per-verification cost.

Prints exactly ONE JSON line on stdout (progress notes on stderr) — even on
failure. Scoreboard robustness (VERDICT r2 item 1): the accelerator backend is
probed in a SUBPROCESS with a hard timeout before the main process ever
touches it, because a broken TPU tunnel makes `jax.devices()` block for
minutes. On an unavailable/hung backend the script falls back to a
clearly-labeled small-shape CPU-debug run and emits
`{"error": "tpu_unavailable", ...}` alongside those numbers instead of a raw
traceback. Every successful measurement is also persisted to
BENCH_LOCAL.json (timestamp + git SHA) so perf evidence survives tunnel
outages. Crash-forensics stance modeled on the reference generator runtime
(gen_base/gen_runner.py error-log + INCOMPLETE sentinels).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_VALIDATORS = int(os.environ.get("BENCH_VALIDATORS", 1_048_576))
N_BLS = int(os.environ.get("BENCH_BLS_N", 2048))
BLS_TARGET = 100_000.0
EPOCH_TARGET_S = 2.0
BACKEND_PROBE_TIMEOUT_S = float(os.environ.get("BENCH_BACKEND_TIMEOUT_S", 120))
# small shapes for the cpu-debug fallback lane (tpu unavailable)
CPU_DEBUG_VALIDATORS = int(os.environ.get("BENCH_CPU_VALIDATORS", 65_536))
CPU_DEBUG_BLS = int(os.environ.get("BENCH_CPU_BLS_N", 128))


def probe_accelerator() -> str | None:
    """Return the accelerator platform name, or None if unavailable/hung.

    Runs `jax.devices()` in a child process under a hard timeout — the only
    safe way to ask "is the tunnel up" without risking a multi-minute block
    in the process that must emit the scoreboard line."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=BACKEND_PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        print(f"# backend probe timed out after {BACKEND_PROBE_TIMEOUT_S:.0f}s",
              file=sys.stderr)
        return None
    if res.returncode != 0:
        tail = (res.stderr or "").strip().splitlines()[-1:] or ["?"]
        print(f"# backend probe failed: {tail[0]}", file=sys.stderr)
        return None
    platform = res.stdout.strip()
    return platform or None


def force_cpu() -> None:
    """Pin this process to the host CPU backend before any backend init."""
    from consensus_specs_tpu.utils.backend import force_cpu as _force_cpu

    _force_cpu()


def bench_epoch() -> float:
    import jax

    from consensus_specs_tpu.compiler import get_spec
    from consensus_specs_tpu.engine.epoch import make_epoch_fn
    from consensus_specs_tpu.engine.state import EpochConfig
    from consensus_specs_tpu.engine.synthetic import synthetic_epoch_state

    cfg = EpochConfig.from_spec(get_spec("altair", "mainnet"))
    state = synthetic_epoch_state(cfg, n=N_VALIDATORS)
    fn = make_epoch_fn(cfg)

    t0 = time.time()
    out, _ = fn(state)
    jax.block_until_ready(out.balances)
    print(f"# epoch compile+first: {time.time() - t0:.1f}s", file=sys.stderr)

    times = []
    for _ in range(5):
        refreshed = jax.tree.map(lambda x: x.copy(), out)
        t0 = time.time()
        out2, _ = fn(refreshed)
        jax.block_until_ready(out2.balances)
        times.append(time.time() - t0)
        out = out2
    return sorted(times)[len(times) // 2]


def bench_bls() -> tuple[float, float, float, dict, dict]:
    """(per-item verifies/sec, RLC verifies/sec, compile_s, rlc stage
    breakdown, flush extras) at batch N_BLS. `flush extras` carries the
    grouped D+1-Miller-loop kernel comparison and the end-to-end
    deferred-flush lane (host prep included) from benches/bls_verify_bench —
    the e2e number is REQUIRED alongside the kernel-only figure (r5 VERDICT:
    kernel-only throughput without host-prep accounting is the evidence
    gap; tools/bench_probe.py refuses records missing it)."""
    import time as _time

    import jax
    import numpy as np

    from consensus_specs_tpu.crypto.bls_jax import bench_pairing_args
    from consensus_specs_tpu.ops import bls12_jax as K

    args = bench_pairing_args(N_BLS)
    t0 = _time.time()
    ok = K.pairing_check_batch(*args)
    ok.block_until_ready()
    compile_s = _time.time() - t0
    assert bool(np.asarray(ok).all()), "batched verification rejected valid signatures"
    print(f"# bls compile+first: {compile_s:.1f}s", file=sys.stderr)

    times = []
    for _ in range(3):
        t0 = _time.time()
        K.pairing_check_batch(*args).block_until_ready()
        times.append(_time.time() - t0)
    per_item = N_BLS / min(times)

    # randomized batch check (shared final exponentiation) — the deferred
    # flush's large-batch path
    from consensus_specs_tpu.crypto.bls_jax import random_zbits

    zbits = random_zbits(N_BLS)
    ok = K.pairing_check_rlc(*args, zbits, p2_is_neg_g1=True)
    ok.block_until_ready()
    assert bool(np.asarray(ok))
    rlc_times = []
    for _ in range(3):
        t0 = _time.time()
        K.pairing_check_rlc(*args, zbits, p2_is_neg_g1=True).block_until_ready()
        rlc_times.append(_time.time() - t0)

    stages = {}
    if os.environ.get("BENCH_BLS_STAGES", "1") != "0":
        from benches.bls_verify_bench import rlc_stage_breakdown

        stages = rlc_stage_breakdown(args, zbits)
        print(f"# rlc stage breakdown: {stages}", file=sys.stderr)

    flush_extra = {}
    if os.environ.get("BENCH_BLS_GROUPED", "1") != "0":
        from benches.bls_verify_bench import grouped_vs_ungrouped

        flush_extra.update(grouped_vs_ungrouped())
        print(f"# rlc grouped vs ungrouped: {flush_extra}", file=sys.stderr)
    if os.environ.get("BENCH_BLS_E2E", "1") != "0":
        from benches.bls_verify_bench import GROUPED_N, e2e_flush_lane

        e2e = e2e_flush_lane(min(N_BLS, GROUPED_N))
        print(f"# bls e2e flush lane: {e2e}", file=sys.stderr)
        flush_extra.update(e2e)
    return per_item, N_BLS / min(rlc_times), compile_s, stages, flush_extra


def run_benches() -> dict:
    import contextlib

    import jax

    from consensus_specs_tpu.obs import metrics as obs_metrics
    from consensus_specs_tpu.obs import recompile as obs_recompile
    from consensus_specs_tpu.obs import trace as obs_trace
    from consensus_specs_tpu.utils.profiling import timed, timings, trace

    # Observability ON for the bench run: spans over every instrumented seam
    # plus the per-kernel recompile tracker, all feeding the process
    # registry. The snapshot is persisted next to BENCH_LOCAL.json
    # (persist_local) and a compact digest rides in extra["obs"] — a bench
    # record that recompiled a kernel 14 times says so.
    tracer = obs_trace.Tracer(registry=obs_metrics.REGISTRY,
                              max_spans=65536).install()
    compile_tracker = obs_recompile.CompileTracker(
        registry=obs_metrics.REGISTRY).install()
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    ctx = trace(profile_dir) if profile_dir else contextlib.nullcontext()
    with ctx:
        with timed("bench_bls"):
            vps, rlc_vps, compile_s, rlc_stages, bls_flush = bench_bls()
        with timed("bench_epoch"):
            epoch_s = bench_epoch()
        with timed("bench_attestations"):
            import benches.attestation_bench as att_bench

            att = att_bench.run()
        with timed("bench_state_root"):
            import benches.state_root_bench as sr_bench

            sr = sr_bench.run(int(os.environ.get("BENCH_SR_VALIDATORS", N_VALIDATORS)))
        with timed("bench_epoch_e2e"):
            import benches.epoch_e2e_bench as e2e_bench

            e2e = e2e_bench.run(int(os.environ.get("BENCH_E2E_VALIDATORS", N_VALIDATORS)))
        with timed("bench_kzg"):
            import benches.kzg_bench as kzg_bench

            kzg_r = kzg_bench.run()
        with timed("bench_msm"):
            import benches.msm_bench as msm_bench

            msm_r = msm_bench.run()
        with timed("bench_sync_aggregate"):
            import benches.sync_aggregate_bench as sync_bench

            sync_r = sync_bench.run()
        with timed("bench_sched"):
            import benches.sched_bench as sched_bench

            sched_r = sched_bench.run()
        with timed("bench_firehose"):
            import benches.firehose_bench as firehose_bench

            fh_r = firehose_bench.run()
        with timed("bench_scenario"):
            import benches.scenario_bench as scenario_bench

            scen_r = scenario_bench.run()
        with timed("bench_proofs"):
            import benches.proof_bench as proof_bench

            proof_r = proof_bench.run()
        with timed("bench_forkchoice"):
            import benches.forkchoice_bench as forkchoice_bench

            fc_r = forkchoice_bench.run()
        with timed("bench_frontdoor"):
            import benches.frontdoor_bench as frontdoor_bench

            fd_r = frontdoor_bench.run()
    if profile_dir:
        print(f"# device trace written to {profile_dir}", file=sys.stderr)
    print(f"# stage timings: {timings()}", file=sys.stderr)
    tracer.uninstall()
    compile_tracker.uninstall()
    obs_digest = {
        "spans": len(tracer.finished) + tracer.dropped,
        "spans_dropped": tracer.dropped,
        "compile_total": compile_tracker.kernels(),
        "compile_distinct_shapes": {
            k: compile_tracker.distinct_shapes(k)
            for k in compile_tracker.kernels()},
        "flushes": obs_metrics.REGISTRY.counters_matching("bls_flush_total"),
    }
    print(f"# obs: {obs_digest}", file=sys.stderr)
    return {
        "metric": "bls_verify_throughput",
        "value": round(vps, 1),
        "unit": "verifications/sec/chip",
        "vs_baseline": round(vps / BLS_TARGET, 4),
        "extra": {
            "bls_batch": N_BLS,
            "bls_verify_throughput_rlc": round(rlc_vps, 1),
            "bls_compile_s": round(compile_s, 1),
            "bls_rlc_stage_s": rlc_stages,
            # grouped D+1 flush + end-to-end lane (host prep included):
            # bls_verify_throughput_e2e / rlc_distinct_messages / rlc_*
            **bls_flush,
            # keyed by the ACTUAL registry size measured — the 1M alias is
            # added only when the run really is 1M (VERDICT r4 weak #3)
            "process_epoch_s": round(epoch_s, 4),
            "epoch_validators": N_VALIDATORS,
            "epoch_vs_baseline": round(EPOCH_TARGET_S / epoch_s, 2),
            # cold = caches cleared (comparable with r1-r3 recordings);
            # warm = marginal re-verification rate with caches hot
            "attestations_per_sec": round(att["attestations_per_sec_cold"], 1),
            "attestation_epoch_s": round(att["cold_epoch_s"], 4),
            "attestations_per_sec_warm": round(att["attestations_per_sec_warm"], 1),
            "attestation_warm_epoch_s": round(att["warm_epoch_s"], 4),
            "attestations_per_epoch": att["attestations_per_epoch"],
            "attestation_validators": att["validators"],
            "attestation_committees_per_slot": att["committees_per_slot"],
            # BASELINE config 4 honest end-to-end — HEADLINE is the resident
            # pipeline's amortized per-epoch cost; the sequential lane (full
            # bridge round trip every epoch) rides along for the stage
            # breakdown, and write_back_bytes carries the measured dirty vs
            # full-materialize D2H accounting from the same run
            "epoch_e2e_s": e2e["e2e_epoch_s"],
            "epoch_e2e_sequential_s": e2e["sequential_epoch_s"],
            "epoch_e2e_stages_s": e2e["stages_s"],
            "epoch_e2e_write_back_bytes": e2e["write_back_bytes"],
            "epoch_e2e_validators": e2e["validators"],
            # steady-state device-resident loop (engine/resident.py): the
            # registry never leaves HBM; materialize + root amortized
            "epoch_resident_s": e2e["resident_epoch_s"],
            "epoch_resident_scan_s": e2e["resident_scan_epoch_s"],
            "epoch_resident_state_root_s": e2e["resident_state_root_s"],
            "epoch_resident_state_root_slot_s": e2e["resident_state_root_slot_s"],
            "epoch_resident_amortized_s": e2e["resident_amortized_epoch_s"],
            "epoch_resident_epochs": e2e["resident_epochs"],
            "epoch_resident_vs_baseline": round(
                EPOCH_TARGET_S / max(e2e["resident_amortized_epoch_s"], 1e-9), 2),
            # BASELINE config 5: batched KZG sample verification per block
            "kzg_blobs_per_s": kzg_r["blobs_per_s"],
            "kzg_batch_verify_s": kzg_r["batch_verify_s"],
            "kzg_blobs": kzg_r["blobs"],
            # Pippenger bucket-MSM kernel vs the per-item ladder it replaced
            # (same points/scalars, cross-checked before timing); the sweep
            # grid rides in msm_sweep
            "msm_items_per_s": msm_r["msm_items_per_s"],
            "msm_vs_ladder_speedup": msm_r["msm_vs_ladder_speedup"],
            "msm_n": msm_r["msm_n"],
            "msm_window": msm_r["msm_window"],
            "msm_nbits": msm_r["msm_nbits"],
            "msm_sweep": msm_r["msm_sweep"],
            # BASELINE config 3: per-block sync-aggregate obligation — one
            # 512-member FastAggregateVerify per block, flushed as a stream
            "sync_aggregate_blocks_per_s": sync_r["blocks_per_s_cold"],
            "sync_aggregate_blocks_per_s_warm": sync_r["blocks_per_s_warm"],
            "sync_aggregate_blocks": sync_r["blocks"],
            "sync_aggregate_committee_size": sync_r["committee_size"],
            # unified verification scheduler mixed lane: per-class items/s
            # through the shared dispatch seam, steady-state p99
            # submit->result latency, and the bucketing occupancy floor
            # (>= 0.75 by construction; a bucketing regression shows here)
            "sched_bls_items_per_s": sched_r["sched_bls_items_per_s"],
            "sched_kzg_items_per_s": sched_r["sched_kzg_items_per_s"],
            "sched_merkle_items_per_s": sched_r["sched_merkle_items_per_s"],
            "sched_p99_latency_s": sched_r["sched_p99_latency_s"],
            "sched_occupancy_min": sched_r["sched_occupancy_min"],
            "sched_compile_s": sched_r["sched_compile_s"],
            # attestation firehose soak: streaming gossip->aggregate->flush
            # throughput at 64 committees/slot sized for a 1M-validator
            # registry, p99 ingest->verified from the pipeline's own
            # histogram, and the committee-collapse ratio (atts per
            # device pairing check)
            "firehose_atts_per_s_cold": fh_r["firehose_atts_per_s_cold"],
            "firehose_atts_per_s_steady": fh_r["firehose_atts_per_s_steady"],
            "firehose_p99_ingest_to_verified_s":
                fh_r["firehose_p99_ingest_to_verified_s"],
            "firehose_collapse_ratio": fh_r["firehose_collapse_ratio"],
            "firehose_queue_depth_peak": fh_r["firehose_queue_depth_peak"],
            # scenario-engine SLO lane: chaos-enabled engine replay of a
            # seeded long-horizon history (storms/equivocations/fork
            # transition), plus the emit->diff double render — the
            # bidirectional conformance loop measured end to end
            "scenario_slots_per_s": scen_r["scenario_slots_per_s"],
            "scenario_reorg_depth_max": scen_r["scenario_reorg_depth_max"],
            "scenario_vectors_emitted": scen_r["scenario_vectors_emitted"],
            "scenario_vectors_diffed": scen_r["scenario_vectors_diffed"],
            "scenario_slots": scen_r["scenario_slots"],
            "scenario_faults_fired": scen_r["scenario_faults_fired"],
            # light-client read lane: batched device multiproofs + the
            # dirty-column proof cache serving thousands of branch queries
            # while the epoch+firehose write path runs; p99 from the
            # lane's own histogram and the cross-checked device-vs-host
            # speedup on identical inputs
            "proof_proofs_per_s_cold": proof_r["proof_proofs_per_s_cold"],
            "proof_proofs_per_s_warm": proof_r["proof_proofs_per_s_warm"],
            "proof_cache_hit_ratio": proof_r["proof_cache_hit_ratio"],
            "proof_p99_request_s": proof_r["proof_p99_request_s"],
            "proof_vs_host_speedup": proof_r["proof_vs_host_speedup"],
            "proof_queries": proof_r["proof_queries"],
            "proof_write_epochs": proof_r["proof_write_epochs"],
            # fork-choice head lane: reorg-storm soak over a contested
            # tree at registry scale, every verified batch folded through
            # the service's firehose seam; head lag (verified -> head
            # reflecting it) from the lane's own histogram, device batch
            # cross-checked bit-identical against the host oracle
            "forkchoice_heads_per_s": fc_r["forkchoice_heads_per_s"],
            "forkchoice_head_lag_p99_s": fc_r["forkchoice_head_lag_p99_s"],
            "forkchoice_head_flips": fc_r["forkchoice_head_flips"],
            "forkchoice_vs_host_speedup":
                fc_r["forkchoice_vs_host_speedup"],
            "forkchoice_blocks": fc_r["forkchoice_blocks"],
            "forkchoice_validators": fc_r["forkchoice_validators"],
            # front-door admission plane: the three seeded traffic
            # profiles replayed un-paced on the real clock; the
            # hostile-tenant lane's worst HONEST p99 (from the door's own
            # per-tenant histogram) is the SLO series, and the
            # attestation-shed count sums every round of every profile —
            # the writes-never-shed invariant, gated at zero
            "frontdoor_requests_per_s": fd_r["frontdoor_requests_per_s"],
            "frontdoor_hostile_honest_p99_s":
                fd_r["frontdoor_hostile_honest_p99_s"],
            "frontdoor_attestation_sheds":
                fd_r["frontdoor_attestation_sheds"],
            "frontdoor_mallory_quota_refusals":
                fd_r["frontdoor_mallory_quota_refusals"],
            "frontdoor_profiles": fd_r["frontdoor_profiles"],
            # per-slot state root at registry scale (incremental Merkle)
            "state_root_slot_s": sr["slot_root_s"],
            "state_root_block_s": sr["block_root_s"],
            "state_root_cold_s": sr["cold_root_s"],
            # trace/recompile digest; the full canonical snapshot is
            # BENCH_OBS.json (persist_local), validated by bench_probe
            "obs": obs_digest,
            "device": str(jax.devices()[0]),
        },
    }


def _git_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10, cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip()
    except Exception:
        return "unknown"


def persist_local(record: dict) -> None:
    """Append the measurement to BENCH_LOCAL.json so perf evidence survives a
    tunnel outage (VERDICT r2: no persisted bench provenance)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LOCAL.json")
    entry = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": _git_sha(),
        **record,
    }
    try:
        history = []
        if os.path.exists(path):
            with open(path) as f:
                history = json.load(f)
            if not isinstance(history, list):
                history = [history]
        history.append(entry)
        with open(path, "w") as f:
            json.dump(history, f, indent=1)
    except Exception as exc:  # never let provenance writing kill the bench
        print(f"# BENCH_LOCAL.json write failed: {exc}", file=sys.stderr)
    try:
        # The full canonical obs snapshot rides alongside the scoreboard
        # history: every counter/histogram the instrumented seams recorded
        # during this run, in the byte-stable exporter format.
        # tools/bench_probe.py FAILS (rc 3) when a successful bench leaves
        # this missing or non-canonical.
        from consensus_specs_tpu.obs import export as obs_export

        obs_export.write_snapshot(
            os.path.join(os.path.dirname(path), "BENCH_OBS.json"),
            meta={"lane": "bench", "git_sha": entry["git_sha"]})
    except Exception as exc:
        print(f"# BENCH_OBS.json write failed: {exc}", file=sys.stderr)


def main() -> None:
    global N_VALIDATORS, N_BLS
    record: dict
    from consensus_specs_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()
    platform = probe_accelerator()
    cpu_debug = platform is None or platform == "cpu"
    if cpu_debug:
        print("# accelerator unavailable — cpu-debug lane (small shapes)",
              file=sys.stderr)
        force_cpu()
        N_VALIDATORS = min(N_VALIDATORS, CPU_DEBUG_VALIDATORS)
        N_BLS = min(N_BLS, CPU_DEBUG_BLS)
        os.environ.setdefault("BENCH_ATT_VALIDATORS", "4096")
        # msm sweep: one grid cell (XLA compiles of the 255-bit programs
        # dominate on CPU; the items/s ratio is what's measured)
        os.environ.setdefault("BENCH_MSM_N", "64")
        # sync-aggregate stream: fewer blocks (host signing + the pairing
        # compile dominate on CPU; the per-block rate is what's measured)
        os.environ.setdefault("BENCH_SYNC_BLOCKS", "8")
        # proof read lane: smaller registry + query set (the epoch write
        # path stepping underneath is the expensive part on CPU; the
        # proofs/s and hit-ratio shape is what's measured)
        os.environ.setdefault("BENCH_PROOF_VALIDATORS", "65536")
        os.environ.setdefault("BENCH_PROOF_QUERIES", "1024")
        # fork-choice head lane: smaller registry + tree (the dense
        # O(blocks x validators) masked segment-sum is the accelerator
        # mapping; on CPU the heads/s and head-lag shape is what's
        # measured, not the device-vs-host ratio)
        os.environ.setdefault("BENCH_FC_VALIDATORS", "16384")
        os.environ.setdefault("BENCH_FC_BLOCKS", "256")
    try:
        record = run_benches()
        if N_VALIDATORS >= 1_048_576:
            record["extra"]["process_epoch_1m_s"] = record["extra"]["process_epoch_s"]
        if cpu_debug:
            # Honest debug scoreboard (VERDICT r4 weak #3): a clamped-shape
            # CPU run carries NO baseline ratios — the targets are defined
            # on TPU at full shapes, so any ratio computed here is noise
            # that reads as target-beaten.
            record["error"] = "tpu_unavailable"
            record["extra"]["mode"] = "cpu_debug_small_shapes"
            record["vs_baseline"] = 0.0
            for k in [k for k in record["extra"] if k.endswith("_vs_baseline")]:
                del record["extra"][k]
    except Exception as exc:  # scoreboard line must parse no matter what
        import traceback

        traceback.print_exc(file=sys.stderr)
        record = {
            "metric": "bls_verify_throughput",
            "value": 0.0,
            "unit": "verifications/sec/chip",
            "vs_baseline": 0.0,
            "error": f"{type(exc).__name__}: {exc}"[:500],
        }
    if "value" in record and record["value"] > 0:
        _gate_slos(record)
        # real measurements only (incl. labeled cpu-debug): crash records
        # with value 0 carry no perf evidence worth committing
        persist_local(record)
    print(json.dumps(record))


def _gate_slos(record: dict) -> None:
    """Evaluate slo.json against this run BEFORE persisting, so the record
    carries its own verdict (extra["slo"]) and a regression is visible in
    the history, not just in CI. Non-fatal by design: the scoreboard line
    must print no matter what, and `make slo` / tools/slo_check.py is the
    enforcing gate (rc != 0)."""
    root = os.path.dirname(os.path.abspath(__file__))
    spec_path = os.path.join(root, "slo.json")
    try:
        from consensus_specs_tpu.obs import export as obs_export
        from consensus_specs_tpu.obs import slo as obs_slo

        specs = obs_slo.load_spec_file(spec_path)
        snap = obs_export.snapshot_dict(meta={"lane": "bench"})
        history = []
        local = os.path.join(root, "BENCH_LOCAL.json")
        if os.path.exists(local):
            with open(local) as f:
                history = json.load(f)
            if not isinstance(history, list):
                history = [history]
        # run_benches() uninstalled its tracer, so disabled-mode overhead
        # is measurable in-process here
        results = obs_slo.evaluate(specs, [snap], history + [record])
        record.setdefault("extra", {})["slo"] = obs_slo.summarize(results)
        for r in results:
            if not r.ok:
                print(f"# SLO VIOLATION {r.name}: {r.detail}",
                      file=sys.stderr)
    except Exception as exc:
        print(f"# slo evaluation failed: {exc}", file=sys.stderr)


if __name__ == "__main__":
    main()
