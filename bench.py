"""Headline benchmark: mainnet-preset 1M-validator `process_epoch` wall-clock.

Target (BASELINE.md north star): < 2 s on a TPU chip for the full epoch
registry sweep (justification, inactivity, rewards/penalties, registry churn,
slashings, hysteresis, resets, historical-batch merkle). The reference
publishes no numbers (BASELINE.json `published: {}`), so `vs_baseline` is the
speedup against that 2 s target: 2.0 / measured.

Prints exactly one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

N = int(os.environ.get("BENCH_VALIDATORS", 1_048_576))
TARGET_S = 2.0


def main() -> None:
    import jax

    from consensus_specs_tpu.compiler import get_spec
    from consensus_specs_tpu.engine.epoch import make_epoch_fn
    from consensus_specs_tpu.engine.state import EpochConfig
    from consensus_specs_tpu.engine.synthetic import synthetic_epoch_state

    cfg = EpochConfig.from_spec(get_spec("altair", "mainnet"))
    state = synthetic_epoch_state(cfg, n=N)
    # donated buffers: keep a template to refresh inputs between timed runs
    fn = make_epoch_fn(cfg)

    t0 = time.time()
    out, _ = fn(state)
    jax.block_until_ready(out.balances)
    print(f"# compile+first: {time.time() - t0:.1f}s on {jax.devices()[0]}", file=sys.stderr)

    times = []
    for _ in range(5):
        refreshed = jax.tree.map(lambda x: x.copy(), out)
        t0 = time.time()
        out2, _ = fn(refreshed)
        jax.block_until_ready(out2.balances)
        times.append(time.time() - t0)
        out = out2
    med = sorted(times)[len(times) // 2]
    print(
        json.dumps(
            {
                "metric": f"mainnet_altair_process_epoch_{N}_validators",
                "value": round(med, 4),
                "unit": "s",
                "vs_baseline": round(TARGET_S / med, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
