"""Headline benchmark — BOTH BASELINE.md north stars, one JSON line.

1. `bls_verify_throughput` (the headline metric/value): aggregate BLS
   signature verifications per second on one chip — batched
   e(pk_i, H(m_i))·e(-G1, sig_i) == 1 checks through the RNS pairing kernels
   (ops/bls12_jax.py over ops/fp_rns.py). Target >= 100k/s (BASELINE.json);
   `vs_baseline` is measured/target.
2. `extra.process_epoch_1m_s`: mainnet-preset 1M-validator altair
   `process_epoch` device wall-clock (target < 2 s;
   `extra.epoch_vs_baseline` = 2.0/measured).

The reference publishes no numbers (BASELINE.json `published: {}`), so both
baselines are the BASELINE.json targets. Host prep (decompression,
hash-to-curve) is excluded from the BLS timed region: pubkeys live
decompressed in the registry and messages hash once per slot, so the pairing
is the marginal per-verification cost.

Prints exactly one JSON line on stdout (progress notes on stderr).
"""
from __future__ import annotations

import json
import os
import sys
import time

N_VALIDATORS = int(os.environ.get("BENCH_VALIDATORS", 1_048_576))
N_BLS = int(os.environ.get("BENCH_BLS_N", 2048))
BLS_TARGET = 100_000.0
EPOCH_TARGET_S = 2.0


def bench_epoch() -> float:
    import jax

    from consensus_specs_tpu.compiler import get_spec
    from consensus_specs_tpu.engine.epoch import make_epoch_fn
    from consensus_specs_tpu.engine.state import EpochConfig
    from consensus_specs_tpu.engine.synthetic import synthetic_epoch_state

    cfg = EpochConfig.from_spec(get_spec("altair", "mainnet"))
    state = synthetic_epoch_state(cfg, n=N_VALIDATORS)
    fn = make_epoch_fn(cfg)

    t0 = time.time()
    out, _ = fn(state)
    jax.block_until_ready(out.balances)
    print(f"# epoch compile+first: {time.time() - t0:.1f}s", file=sys.stderr)

    times = []
    for _ in range(5):
        refreshed = jax.tree.map(lambda x: x.copy(), out)
        t0 = time.time()
        out2, _ = fn(refreshed)
        jax.block_until_ready(out2.balances)
        times.append(time.time() - t0)
        out = out2
    return sorted(times)[len(times) // 2]


def bench_bls() -> tuple[float, float, float]:
    """(per-item verifies/sec, RLC verifies/sec, compile_s) at batch N_BLS."""
    import time as _time

    import jax
    import numpy as np

    from consensus_specs_tpu.crypto.bls_jax import bench_pairing_args
    from consensus_specs_tpu.ops import bls12_jax as K

    args = bench_pairing_args(N_BLS)
    t0 = _time.time()
    ok = K.pairing_check_batch(*args)
    ok.block_until_ready()
    compile_s = _time.time() - t0
    assert bool(np.asarray(ok).all()), "batched verification rejected valid signatures"
    print(f"# bls compile+first: {compile_s:.1f}s", file=sys.stderr)

    times = []
    for _ in range(3):
        t0 = _time.time()
        K.pairing_check_batch(*args).block_until_ready()
        times.append(_time.time() - t0)
    per_item = N_BLS / min(times)

    # randomized batch check (shared final exponentiation) — the deferred
    # flush's large-batch path
    from consensus_specs_tpu.crypto.bls_jax import random_zbits

    zbits = random_zbits(N_BLS)
    ok = K.pairing_check_rlc(*args, zbits)
    ok.block_until_ready()
    assert bool(np.asarray(ok))
    rlc_times = []
    for _ in range(3):
        t0 = _time.time()
        K.pairing_check_rlc(*args, zbits).block_until_ready()
        rlc_times.append(_time.time() - t0)
    return per_item, N_BLS / min(rlc_times), compile_s


def main() -> None:
    import contextlib

    import jax

    from consensus_specs_tpu.utils.profiling import timed, timings, trace

    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    ctx = trace(profile_dir) if profile_dir else contextlib.nullcontext()
    with ctx:
        with timed("bench_bls"):
            vps, rlc_vps, compile_s = bench_bls()
        with timed("bench_epoch"):
            epoch_s = bench_epoch()
        with timed("bench_attestations"):
            import benches.attestation_bench as att_bench

            att_per_s, att_epoch_s, att_count = att_bench.run()
    if profile_dir:
        print(f"# device trace written to {profile_dir}", file=sys.stderr)
    print(f"# stage timings: {timings()}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "bls_verify_throughput",
                "value": round(vps, 1),
                "unit": "verifications/sec/chip",
                "vs_baseline": round(vps / BLS_TARGET, 4),
                "extra": {
                    "bls_batch": N_BLS,
                    "bls_verify_throughput_rlc": round(rlc_vps, 1),
                    "bls_compile_s": round(compile_s, 1),
                    "process_epoch_1m_s": round(epoch_s, 4),
                    "epoch_vs_baseline": round(EPOCH_TARGET_S / epoch_s, 2),
                    "attestations_per_sec": round(att_per_s, 1),
                    "attestation_epoch_s": round(att_epoch_s, 4),
                    "attestations_per_epoch": att_count,
                    "attestation_validators": att_bench.default_validators(),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
