// SPDX-License-Identifier: CC0-1.0
pragma solidity 0.8.19;

// The beacon-chain deposit contract: an append-only incremental Merkle tree
// of DepositData hash-tree-roots, depth 32, with the deposit count mixed
// into the root (specs/phase0/deposit-contract.md). The ABI and the
// incremental-tree algorithm are pinned by the deployed mainnet contract
// and admit essentially one expression, so this file necessarily tracks
// that canonical artifact; the Python twin used by genesis tooling and the
// differential tests is consensus_specs_tpu/utils/deposit_tree.py.

interface IDepositContract {
    /// A deposit was accepted; fields are little-endian encoded as clients
    /// replay them into eth1 voting / genesis.
    event DepositEvent(
        bytes pubkey,
        bytes withdrawal_credentials,
        bytes amount,
        bytes signature,
        bytes index
    );

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) external payable;

    function get_deposit_root() external view returns (bytes32);

    function get_deposit_count() external view returns (bytes memory);
}

interface IERC165 {
    function supportsInterface(bytes4 interfaceId) external pure returns (bool);
}

contract DepositContract is IDepositContract, IERC165 {
    uint256 private constant DEPOSIT_CONTRACT_TREE_DEPTH = 32;
    // one slot must stay free so the count mix-in can never collide with a
    // full bottom layer
    uint256 private constant MAX_DEPOSIT_COUNT = 2 ** DEPOSIT_CONTRACT_TREE_DEPTH - 1;

    // branch[h]: the pending left-subtree root at height h (the right spine)
    bytes32[DEPOSIT_CONTRACT_TREE_DEPTH] private branch;
    uint256 private deposit_count;

    bytes32[DEPOSIT_CONTRACT_TREE_DEPTH] private zero_hashes;

    constructor() {
        // zero_hashes[0] defaults to 0x00...00; ladder up
        for (uint256 height = 0; height < DEPOSIT_CONTRACT_TREE_DEPTH - 1; height++)
            zero_hashes[height + 1] = sha256(
                abi.encodePacked(zero_hashes[height], zero_hashes[height])
            );
    }

    function get_deposit_root() external view override returns (bytes32) {
        bytes32 node;
        uint256 size = deposit_count;
        for (uint256 height = 0; height < DEPOSIT_CONTRACT_TREE_DEPTH; height++) {
            if ((size & 1) == 1)
                node = sha256(abi.encodePacked(branch[height], node));
            else
                node = sha256(abi.encodePacked(node, zero_hashes[height]));
            size /= 2;
        }
        return sha256(
            abi.encodePacked(node, to_little_endian_64(uint64(deposit_count)), bytes24(0))
        );
    }

    function get_deposit_count() external view override returns (bytes memory) {
        return to_little_endian_64(uint64(deposit_count));
    }

    function deposit(
        bytes calldata pubkey,
        bytes calldata withdrawal_credentials,
        bytes calldata signature,
        bytes32 deposit_data_root
    ) external payable override {
        require(pubkey.length == 48, "DepositContract: invalid pubkey length");
        require(
            withdrawal_credentials.length == 32,
            "DepositContract: invalid withdrawal_credentials length"
        );
        require(signature.length == 96, "DepositContract: invalid signature length");

        require(msg.value >= 1 ether, "DepositContract: deposit value too low");
        require(msg.value % 1 gwei == 0, "DepositContract: deposit value not multiple of gwei");
        uint256 deposit_amount = msg.value / 1 gwei;
        require(deposit_amount <= type(uint64).max, "DepositContract: deposit value too high");

        emit DepositEvent(
            pubkey,
            withdrawal_credentials,
            to_little_endian_64(uint64(deposit_amount)),
            signature,
            to_little_endian_64(uint64(deposit_count))
        );

        // hash_tree_root(DepositData) from scratch in EVM sha256:
        // leaves: pubkey (48 -> two 32B chunks), credentials, amount+pad,
        // signature (96 -> 3 chunks merkleized to depth 2)
        bytes32 pubkey_root = sha256(abi.encodePacked(pubkey, bytes16(0)));
        bytes32 signature_root = sha256(
            abi.encodePacked(
                sha256(abi.encodePacked(signature[:64])),
                sha256(abi.encodePacked(signature[64:], bytes32(0)))
            )
        );
        bytes32 node = sha256(
            abi.encodePacked(
                sha256(abi.encodePacked(pubkey_root, withdrawal_credentials)),
                sha256(
                    abi.encodePacked(
                        to_little_endian_64(uint64(deposit_amount)),
                        bytes24(0),
                        signature_root
                    )
                )
            )
        );
        require(
            node == deposit_data_root,
            "DepositContract: reconstructed DepositData does not match supplied deposit_data_root"
        );

        require(deposit_count < MAX_DEPOSIT_COUNT, "DepositContract: merkle tree full");
        deposit_count += 1;

        // incremental insert: merge left-subtree roots while the index bit
        // is 0; the first 1 bit's level stores the merged node
        uint256 size = deposit_count;
        for (uint256 height = 0; height < DEPOSIT_CONTRACT_TREE_DEPTH; height++) {
            if ((size & 1) == 1) {
                branch[height] = node;
                return;
            }
            node = sha256(abi.encodePacked(branch[height], node));
            size /= 2;
        }
        assert(false); // unreachable: deposit_count < 2^32 - 1
    }

    function supportsInterface(bytes4 interfaceId) external pure override returns (bool) {
        return
            interfaceId == type(IERC165).interfaceId ||
            interfaceId == type(IDepositContract).interfaceId;
    }

    function to_little_endian_64(uint64 value) internal pure returns (bytes memory ret) {
        ret = new bytes(8);
        bytes8 bytesValue = bytes8(value);
        ret[0] = bytesValue[7];
        ret[1] = bytesValue[6];
        ret[2] = bytesValue[5];
        ret[3] = bytesValue[4];
        ret[4] = bytesValue[3];
        ret[5] = bytesValue[2];
        ret[6] = bytesValue[1];
        ret[7] = bytesValue[0];
    }
}
