"""Static lint gate for the repo (reference parity: the flake8+mypy gate in
/root/reference/linter.ini + Makefile:133-136).

This image ships no flake8/mypy/ruff, so the gate is a focused AST linter
covering the defect classes that have actually bitten this codebase plus the
cheap universal ones:

  F401  unused import
  F811  redefinition of an imported/defined name by a def/class
  B006  mutable default argument
  B011  assert on a non-empty tuple (always true)
  E722  bare except
  E999  syntax error

Exit code 1 on any finding; `# noqa` on the offending line suppresses. Usage: python tools/lint.py [paths...]
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

DEFAULT_PATHS = [
    "consensus_specs_tpu",
    "generators",
    "tests",
    "benches",
    "tools",
    "bench.py",
    "__graft_entry__.py",
]

# names that modules legitimately import for re-export or side effects
REEXPORT_HINTS = ("__init__.py",)


class ImportTracker(ast.NodeVisitor):
    def __init__(self):
        self.imports: dict[str, ast.AST] = {}  # local name -> node
        self.used: set[str] = set()
        self.defs: dict[str, list[int]] = {}
        self.findings: list[tuple[int, str, str]] = []

    # --- collection ---------------------------------------------------------

    def visit_Import(self, node):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports[name] = node

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return  # compiler directives, not bindings to "use"
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports[name] = node

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    def _register_def(self, node):
        self.defs.setdefault(node.name, []).append(node.lineno)
        if node.name in self.imports:
            imp = self.imports[node.name]
            self.findings.append(
                (node.lineno, "F811",
                 f"'{node.name}' shadows import from line {imp.lineno}"))

    def visit_FunctionDef(self, node):
        self._register_def(node)
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._register_def(node)
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self._register_def(node)
        self.generic_visit(node)

    def _check_defaults(self, node):
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(
                    (default.lineno, "B006", "mutable default argument"))

    def visit_Assert(self, node):
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.findings.append(
                (node.lineno, "B011", "assert on a non-empty tuple is always true"))
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.findings.append((node.lineno, "E722", "bare except"))
        self.generic_visit(node)


def _noqa_suppresses(line: str, code: str) -> bool:
    """bare `# noqa` suppresses everything; `# noqa: X,Y` only those codes."""
    if "noqa" not in line:
        return False
    _, _, after = line.partition("noqa")
    after = after.strip()
    if not after.startswith(":"):
        return True
    codes = {c.strip().upper() for c in after[1:].split(",")}
    return code.upper() in codes


def lint_file(path: Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: E999 syntax error: {e.msg}"]
    tracker = ImportTracker()
    tracker.visit(tree)

    out = []
    # F401: imported but never used (skip __init__ re-export surfaces and
    # star-import collectors)
    has_star = any(
        isinstance(n, ast.ImportFrom) and any(a.name == "*" for a in n.names)
        for n in ast.walk(tree)
    )
    exported = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(n.value, (ast.List, ast.Tuple)):
                        exported = {
                            e.value for e in n.value.elts
                            if isinstance(e, ast.Constant)
                        }
    if path.name not in REEXPORT_HINTS and not has_star:
        for name, node in tracker.imports.items():
            if name in tracker.used or name in exported or name.startswith("_"):
                continue
            line = src.splitlines()[node.lineno - 1]
            if _noqa_suppresses(line, "F401"):
                continue
            out.append(f"{path}:{node.lineno}: F401 '{name}' imported but unused")
    for lineno, code, msg in tracker.findings:
        line = src.splitlines()[lineno - 1] if lineno <= len(src.splitlines()) else ""
        if _noqa_suppresses(line, code):
            continue
        out.append(f"{path}:{lineno}: {code} {msg}")
    return out


def main(argv) -> int:
    roots = argv[1:] or DEFAULT_PATHS
    files = []
    for r in roots:
        p = Path(r)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    findings = []
    for f in files:
        findings.extend(lint_file(f))
    for line in findings:
        print(line)
    print(f"lint: {len(files)} files, {len(findings)} findings")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
