"""TPU-opportunistic bench loop (`make bench-probe`).

The TPU tunnel in this image comes and goes; perf evidence is only worth
committing when it answers. This tool retries bench.probe_accelerator()
until a real accelerator shows up, then runs the bench_quick lane (small
batches, persistent XLA cache) in a child process — bench.py itself tags
the BENCH_LOCAL.json entry with the device. Every FAILED probe also
appends a probe-failure record, so "the tunnel was down at sha X / time Y"
is provenance too, not silence.

Bounded by default (--max-tries 3) so CI never hangs on a dead tunnel;
`--max-tries 0` retries forever for an operator babysitting a flaky link.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402  (needs REPO_ROOT on sys.path)
from consensus_specs_tpu.robustness import retry as rretry  # noqa: E402


class ProbeUnavailable(Exception):
    """No usable accelerator answered this probe attempt."""

    retryable = True  # robustness.retry classification marker

# bench_quick's shape overrides (Makefile bench_quick target) — one source
# of truth would be nicer, but make cannot export to a sibling target and
# the tool must work stand-alone; keep in sync with the Makefile.
BENCH_QUICK_ENV = {
    "BENCH_BLS_N": "512",
    "BENCH_E2E_RESIDENT_EPOCHS": "6",
    "BENCH_KZG_BLOBS": "32",
    "BENCH_ATT_VALIDATORS": "32768",
    "BENCH_SR_VALIDATORS": "262144",
    "BENCH_E2E_VALIDATORS": "1048576",
    "BENCH_MSM_N": "64",
    "BENCH_PROOF_VALIDATORS": "1048576",
    "BENCH_PROOF_QUERIES": "2048",
}


def run_bench_quick() -> int:
    env = dict(os.environ)
    env.update(BENCH_QUICK_ENV)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        env=env, cwd=REPO_ROOT,
    )
    if proc.returncode == 0:
        return check_e2e_lane()
    return proc.returncode


def check_e2e_lane() -> int:
    """Refuse a kernel-only BLS record: if the run just appended a
    bls_verify_throughput measurement WITHOUT the end-to-end flush lane
    (extra.bls_verify_throughput_e2e + extra.rlc_distinct_messages), fail
    loudly. A kernel number with no host-prep accounting is exactly the
    evidence gap the r5 VERDICT flagged — silently committing it would
    let the scoreboard regress to pre-e2e provenance."""
    path = os.path.join(REPO_ROOT, "BENCH_LOCAL.json")
    try:
        with open(path) as f:
            history = json.load(f)
    except Exception as exc:
        print(f"# bench-probe: cannot read BENCH_LOCAL.json ({exc})",
              file=sys.stderr)
        return 3
    last = (history[-1] if isinstance(history, list) and history else history) or {}
    if last.get("metric") != "bls_verify_throughput" or not last.get("value"):
        # crash record / probe record: bench.py already reported the failure
        return 0
    extra = last.get("extra") or {}
    missing = [k for k in ("bls_verify_throughput_e2e", "rlc_distinct_messages")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench emitted a kernel-only BLS number "
              f"without the e2e flush lane (missing {missing}); set "
              f"BENCH_BLS_E2E=1 or fix benches/bls_verify_bench.e2e_flush_lane",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: e2e lane present "
          f"(e2e={extra['bls_verify_throughput_e2e']}/s over "
          f"{extra['rlc_distinct_messages']} distinct messages)", file=sys.stderr)
    rc = check_sched_lane(extra)
    if rc:
        return rc
    rc = check_firehose_lane(extra)
    if rc:
        return rc
    rc = check_scenario_lane(extra)
    if rc:
        return rc
    rc = check_msm_lane(extra)
    if rc:
        return rc
    rc = check_proof_lane(extra)
    if rc:
        return rc
    rc = check_forkchoice_lane(extra)
    if rc:
        return rc
    rc = check_frontdoor_lane(extra)
    if rc:
        return rc
    return check_obs_snapshot()


def check_sched_lane(extra: dict) -> int:
    """Refuse a record without the unified-scheduler mixed lane: the
    occupancy floor (sched_occupancy_min) is the guard that the shared
    bucketing still packs batches instead of padding them away, and the
    per-class throughputs are the evidence that BLS/KZG/Merkle really run
    through one seam. A bench that silently dropped the lane would read
    as 'scheduler still fine' while measuring nothing."""
    missing = [k for k in ("sched_occupancy_min", "sched_bls_items_per_s",
                           "sched_kzg_items_per_s", "sched_merkle_items_per_s")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the unified "
              f"scheduler mixed lane (missing {missing}); fix "
              f"benches/sched_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: sched lane present "
          f"(occupancy_min={extra['sched_occupancy_min']})", file=sys.stderr)
    return 0


def check_firehose_lane(extra: dict) -> int:
    """Refuse a record without the attestation-firehose soak lane: the
    steady-state atts/s is the streaming path's headline (gossip ->
    committee collapse -> device flush at 64 committees/slot), the
    collapse ratio proves admission really merged same-committee
    aggregates into one pairing check each, and the p99 comes from the
    pipeline's own ingest->verified histogram. A bench that dropped the
    lane would keep reporting the slot-barrier number as if the firehose
    were still measured."""
    missing = [k for k in ("firehose_atts_per_s_steady",
                           "firehose_collapse_ratio",
                           "firehose_p99_ingest_to_verified_s")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"attestation firehose soak lane (missing {missing}); fix "
              f"benches/firehose_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: firehose lane present "
          f"(steady={extra['firehose_atts_per_s_steady']}/s, "
          f"collapse={extra['firehose_collapse_ratio']})", file=sys.stderr)
    return 0


def check_scenario_lane(extra: dict) -> int:
    """Refuse a record without the scenario-engine SLO lane: slots/s is
    the long-horizon replay headline, the reorg depth proves the storm
    machinery actually flipped heads, and the emitted/diffed vector
    counts are the bidirectional-conformance evidence (emit from the
    engine lane, diff byte-identical). A bench that dropped the lane
    would keep reporting per-epoch numbers as if multi-thousand-slot
    histories were still proven convergent."""
    missing = [k for k in ("scenario_slots_per_s", "scenario_reorg_depth_max",
                           "scenario_vectors_emitted",
                           "scenario_vectors_diffed")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"scenario-engine lane (missing {missing}); fix "
              f"benches/scenario_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: scenario lane present "
          f"(slots/s={extra['scenario_slots_per_s']}, "
          f"reorg_depth={extra['scenario_reorg_depth_max']}, "
          f"vectors={extra['scenario_vectors_emitted']})", file=sys.stderr)
    return 0


def check_msm_lane(extra: dict) -> int:
    """Refuse a record without the Pippenger MSM lane: the items/s number
    is the kernel headline for every Σ scalar_i·P_i consumer (KZG folds,
    committee aggregation), and the vs-ladder speedup is the evidence that
    the bucket decomposition actually beats the per-item ladder it
    replaced on the SAME inputs — a bench that dropped the lane would keep
    reporting kzg_blobs_per_s with no kernel-level attribution."""
    missing = [k for k in ("msm_items_per_s", "msm_vs_ladder_speedup",
                           "msm_n", "msm_window")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"Pippenger MSM lane (missing {missing}); fix "
              f"benches/msm_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: msm lane present "
          f"(items/s={extra['msm_items_per_s']}, "
          f"speedup={extra['msm_vs_ladder_speedup']}x at "
          f"n={extra['msm_n']} w={extra['msm_window']})", file=sys.stderr)
    return 0


def check_proof_lane(extra: dict) -> int:
    """Refuse a record without the light-client read lane: warm proofs/s
    is the serving headline (batched device multiproofs + dirty-column
    cache), the hit ratio proves the cache actually absorbed the clean
    columns across epoch advances, and the p99 comes from the lane's own
    request histogram under concurrent write-path load. A bench that
    dropped the lane would keep reporting write-path numbers as if the
    read half of the production story were still measured."""
    missing = [k for k in ("proof_proofs_per_s_warm",
                           "proof_cache_hit_ratio",
                           "proof_p99_request_s")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"light-client proof read lane (missing {missing}); fix "
              f"benches/proof_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: proof lane present "
          f"(warm={extra['proof_proofs_per_s_warm']}/s, "
          f"hit_ratio={extra['proof_cache_hit_ratio']}, "
          f"p99={extra['proof_p99_request_s']}s)", file=sys.stderr)
    return 0


def check_forkchoice_lane(extra: dict) -> int:
    """Refuse a record without the fork-choice head lane: heads/s is the
    write-side headline (every verified batch must produce a fresh head),
    the head-lag p99 is the SLO series (verified -> head reflecting it,
    from the lane's own histogram), and the flip count proves the soak
    actually stormed — a contested tree whose head never moves measures
    nothing. A bench that dropped the lane would keep reporting
    verification throughput with no evidence the chain can still pick a
    head at that rate."""
    missing = [k for k in ("forkchoice_heads_per_s",
                           "forkchoice_head_lag_p99_s",
                           "forkchoice_head_flips",
                           "forkchoice_vs_host_speedup")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"fork-choice head lane (missing {missing}); fix "
              f"benches/forkchoice_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    print(f"# bench-probe: forkchoice lane present "
          f"(heads={extra['forkchoice_heads_per_s']}/s, "
          f"lag_p99={extra['forkchoice_head_lag_p99_s']}s, "
          f"flips={extra['forkchoice_head_flips']})", file=sys.stderr)
    return 0


def check_frontdoor_lane(extra: dict) -> int:
    """Refuse a record without the front-door admission lane: the
    hostile-tenant honest p99 is the SLO series (a beacon API that melts
    for honest callers when one tenant floods it has no front door), the
    attestation-shed sum is the writes-never-shed invariant gated at
    zero, and the mallory refusal count proves the quota gate actually
    absorbed the hostile stream — a hostile lane where mallory was never
    refused measured a friendly one."""
    missing = [k for k in ("frontdoor_requests_per_s",
                           "frontdoor_hostile_honest_p99_s",
                           "frontdoor_attestation_sheds",
                           "frontdoor_mallory_quota_refusals")
               if k not in extra]
    if missing:
        print(f"# bench-probe: FATAL — bench record is missing the "
              f"front-door admission lane (missing {missing}); fix "
              f"benches/frontdoor_bench.run or its bench.py wiring",
              file=sys.stderr)
        return 3
    if extra["frontdoor_mallory_quota_refusals"] <= 0:
        print("# bench-probe: FATAL — the front-door hostile lane never "
              "quota-refused the hostile tenant; the lane measured "
              "friendly traffic", file=sys.stderr)
        return 3
    print(f"# bench-probe: frontdoor lane present "
          f"(honest_p99={extra['frontdoor_hostile_honest_p99_s']}s, "
          f"att_sheds={extra['frontdoor_attestation_sheds']}, "
          f"mallory_refusals={extra['frontdoor_mallory_quota_refusals']})",
          file=sys.stderr)
    return 0


def check_obs_snapshot() -> int:
    """A successful bench must leave the canonical obs snapshot next to
    BENCH_LOCAL.json (bench.persist_local writes it). Missing or
    non-canonical bytes fail LOUDLY: a bench record without its trace /
    recompile provenance is the same evidence gap as a kernel number
    without the e2e lane."""
    from consensus_specs_tpu.obs import export as obs_export

    path = os.path.join(REPO_ROOT, "BENCH_OBS.json")
    try:
        with open(path) as f:
            text = f.read()
    except OSError as exc:
        print(f"# bench-probe: FATAL — BENCH_OBS.json missing after a "
              f"successful bench ({exc})", file=sys.stderr)
        return 3
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        print(f"# bench-probe: FATAL — BENCH_OBS.json is not a canonical obs "
              f"snapshot: {reason}", file=sys.stderr)
        return 3
    print("# bench-probe: obs snapshot present and canonical", file=sys.stderr)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--interval", type=float, default=30.0,
                        help="seconds between probe attempts (default 30)")
    parser.add_argument("--max-tries", type=int, default=3,
                        help="probe attempts before giving up; 0 = forever "
                             "(default 3, so CI cannot hang)")
    parser.add_argument("--once", action="store_true",
                        help="single probe attempt (same as --max-tries 1)")
    parser.add_argument("--accept-cpu", action="store_true",
                        help="run the bench even if only the CPU backend "
                             "answers (bench.py tags it cpu_debug)")
    args = parser.parse_args(argv)
    max_tries = 1 if args.once else args.max_tries

    state = {"attempt": 0}

    def probe_once() -> str:
        state["attempt"] += 1
        attempt = state["attempt"]
        platform = bench.probe_accelerator()
        if platform and (platform != "cpu" or args.accept_cpu):
            return platform
        reason = "no backend" if platform is None else f"platform={platform}"
        print(f"# probe attempt {attempt}: {reason}", file=sys.stderr)
        bench.persist_local({
            "metric": "bench_probe",
            "value": 0.0,
            "unit": "probe",
            "error": f"probe_failed:{reason}",
            "extra": {"attempt": attempt, "max_tries": max_tries},
        })
        raise ProbeUnavailable(reason)

    # The shared retry helper replaces the hand-rolled while/sleep loop:
    # flat backoff (backoff=1.0, no jitter) keeps the historical fixed
    # --interval cadence, max_attempts=0 preserves "--max-tries 0 = forever".
    policy = rretry.RetryPolicy(
        max_attempts=max_tries, base_delay=args.interval, backoff=1.0,
        max_delay=args.interval, jitter=0.0)
    try:
        platform = rretry.call_with_retry(probe_once, policy)
    except ProbeUnavailable:
        print(f"# giving up after {state['attempt']} probe attempt(s)",
              file=sys.stderr)
        return 2
    print(f"# probe attempt {state['attempt']}: {platform} answered — "
          f"running bench_quick lane", file=sys.stderr)
    return run_bench_quick()


if __name__ == "__main__":
    raise SystemExit(main())
