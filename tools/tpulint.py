"""tpulint CLI: AST-based invariant checker for the JAX hot path.

Usage:
    python tools/tpulint.py [paths...]            # default: consensus_specs_tpu
        [--baseline tpulint_baseline.json]        # auto-loaded when present
        [--no-baseline]                           # report every finding as new
        [--write-baseline]                        # regenerate (shrink-only)
        [--allow-growth]                          # explicit override for growth
        [--rules id1,id2]                         # subset of passes
        [--since <git-ref>]                       # report changed files only
        [--sarif out.sarif]                       # SARIF 2.1.0 (PR annotations)
        [--max-seconds N]                         # fail if the run takes longer
        [--list-rules] [--json] [--self-test]

Exit codes: 0 clean (no findings beyond the baseline), 1 new findings (or
any finding with --no-baseline / on non-baselined paths), 2 usage errors.

--self-test replays the analyzer over its own fixture corpus
(tests/fixtures/tpulint): every `# tpulint-expect: <rule>` annotation must
be matched by a finding of that rule on that line and no fixture may produce
unexpected findings — the analyzer proves it still catches the seeded
historical bugs (the unpinned fori_loop bound; the module-level bls_jax
import in a py-branch module) before it is trusted to gate CI.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from consensus_specs_tpu.analysis import ALL_RULES, analyze_paths  # noqa: E402
from consensus_specs_tpu.analysis.baseline import (  # noqa: E402
    diff_against_baseline,
    load_baseline,
    write_baseline,
)

DEFAULT_PATHS = [str(REPO / "consensus_specs_tpu")]
DEFAULT_BASELINE = REPO / "tpulint_baseline.json"
FIXTURES = REPO / "tests" / "fixtures" / "tpulint"


def _canon(finding):
    """Repo-relative finding paths regardless of invocation cwd, so baseline
    diffs (and --write-baseline output) are stable whether tpulint runs from
    the repo root (make lint), CI, or anywhere else."""
    try:
        rel = Path(finding.path).resolve().relative_to(REPO)
    except ValueError:
        return finding
    return dataclasses.replace(finding, path=rel.as_posix())


def _changed_files(ref: str) -> set[str] | None:
    """Repo-relative paths of .py files changed since `ref` (plus untracked).

    The ANALYSIS always runs over the full package — interprocedural rules
    need every module to build the call graph — only the REPORT is filtered,
    so --since never changes what a finding means, just which ones print."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=REPO, capture_output=True, text=True, check=True)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO, capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError) as exc:
        detail = getattr(exc, "stderr", "") or str(exc)
        print(f"tpulint: --since {ref}: git failed: {detail.strip()}",
              file=sys.stderr)
        return None
    return {line.strip() for line in
            (diff.stdout + untracked.stdout).splitlines()
            if line.strip().endswith(".py")}


def _sarif_report(result, rules, new_set) -> dict:
    """SARIF 2.1.0 document over the SAME findings list as --json: one
    result per finding, `baselineState` distinguishing frozen-baseline
    findings (unchanged) from new ones so PR annotation surfaces can hide
    the former. Rule metadata comes from the live rule objects."""
    level = {"error": "error", "warning": "warning"}
    sarif_rules = [{
        "id": r.id,
        "shortDescription": {"text": r.doc},
        "defaultConfiguration": {"level": level.get(r.severity, "note")},
    } for r in rules]
    results = []
    for f in result.findings:
        message = f.message + (f"  (fix: {f.hint})" if f.hint else "")
        results.append({
            "ruleId": f.rule,
            "level": level.get(f.severity, "note"),
            "message": {"text": message},
            "baselineState": ("new" if (f.path, f.line, f.rule) in new_set
                              else "unchanged"),
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line},
                },
            }],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "rules": sarif_rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def _self_test() -> int:
    """Run every fixture root and compare against its inline expectations."""
    roots = sorted(p for p in FIXTURES.iterdir()
                   if p.name != "__pycache__" and (p.is_dir() or p.suffix == ".py"))
    if not roots:
        print(f"tpulint --self-test: no fixtures under {FIXTURES}", file=sys.stderr)
        return 2
    result = analyze_paths(roots)
    got = {(f.path, f.line, f.rule) for f in result.findings}
    expected = set()
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts:
                continue
            rel_root = root.as_posix()
            rel = rel_root if root.is_file() else \
                f"{rel_root}/{f.relative_to(root).as_posix()}"
            for i, line in enumerate(f.read_text().splitlines(), start=1):
                if "tpulint-expect:" not in line:
                    continue
                for rule in line.split("tpulint-expect:")[1].split("--")[0].split(","):
                    expected.add((rel, i, rule.strip()))
    missed = expected - got
    unexpected = got - expected
    for path, line, rule in sorted(missed):
        print(f"SELF-TEST MISS: expected {rule} at {path}:{line}")
    for path, line, rule in sorted(unexpected):
        print(f"SELF-TEST UNEXPECTED: {rule} at {path}:{line}")
    ok = not missed and not unexpected
    print(f"tpulint --self-test: {len(expected)} expectations over "
          f"{result.file_count} fixture files: {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpulint", add_help=True)
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--allow-growth", action="store_true")
    ap.add_argument("--rules", default=None)
    ap.add_argument("--since", default=None, metavar="REF")
    ap.add_argument("--sarif", default=None, metavar="FILE")
    ap.add_argument("--max-seconds", default=None, type=float,
                    metavar="N", dest="max_seconds")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:16s} [{rule.severity}] {rule.doc}")
        return 0
    if args.self_test:
        return _self_test()

    rules = ALL_RULES
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")}
        unknown = wanted - {r.id for r in ALL_RULES}
        if unknown:
            print(f"tpulint: unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = tuple(r for r in ALL_RULES if r.id in wanted)

    paths = args.paths or DEFAULT_PATHS
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"tpulint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    if args.since and args.write_baseline:
        print("tpulint: --since and --write-baseline are incompatible "
              "(the baseline must always describe a FULL run)", file=sys.stderr)
        return 2

    t_start = time.perf_counter()
    result = analyze_paths(paths, rules)
    elapsed = time.perf_counter() - t_start
    result.findings = [_canon(f) for f in result.findings]

    baseline_path = Path(args.baseline) if args.baseline else Path(DEFAULT_BASELINE)
    baseline = None
    if not args.no_baseline and baseline_path.exists():
        baseline = load_baseline(baseline_path)

    scope = ""
    if args.since:
        changed = _changed_files(args.since)
        if changed is None:
            return 2
        result.findings = [f for f in result.findings if f.path in changed]
        if baseline is not None:
            # Keep only baseline entries for changed files, else every frozen
            # finding on an UNtouched file would count as "fixed".
            baseline = dict(baseline)
            baseline["findings"] = [e for e in baseline.get("findings", [])
                                    if e["path"] in changed]
        scope = f", scope: {len(changed)} files changed since {args.since}"

    if args.write_baseline:
        old_budget = baseline["budget"] if baseline else len(result.findings)
        count = len(result.findings)
        if count > old_budget and not args.allow_growth:
            print(f"tpulint: refusing to grow the baseline "
                  f"({count} findings > budget {old_budget}); fix or suppress "
                  "the new findings, or pass --allow-growth with a review",
                  file=sys.stderr)
            return 1
        budget = min(old_budget, count) if not args.allow_growth else count
        write_baseline(result.findings, baseline_path, budget)
        print(f"tpulint: wrote {baseline_path} ({count} findings, "
              f"budget {budget})")
        return 0

    new, fixed = (diff_against_baseline(result.findings, baseline)
                  if baseline else (result.findings, 0))

    if args.sarif:
        new_set = {(f.path, f.line, f.rule) for f in new}
        doc = _sarif_report(result, rules, new_set)
        Path(args.sarif).write_text(json.dumps(doc, indent=1) + "\n")

    if args.as_json:
        report = {
            "files": result.file_count,
            "findings": [f.as_json() for f in result.findings],
            "new": [f.as_json() for f in new],
            "suppressed": result.suppressed,
            "fixed_vs_baseline": fixed,
            "elapsed_s": round(elapsed, 3),
            "timings_s": {k: round(v, 4)
                          for k, v in sorted(result.timings_s.items())},
        }
        if args.since:
            report["since"] = args.since
        print(json.dumps(report, indent=1))
    else:
        for f in new:
            print(f.format())
        label = "new findings vs baseline" if baseline else "findings"
        print(f"tpulint: {result.file_count} files, "
              f"{len(result.findings)} findings ({len(new)} {label}, "
              f"{result.suppressed} suppressed"
              + (f", {fixed} fixed vs baseline" if baseline else "")
              + scope + ")")
        if baseline and fixed:
            print("tpulint: baseline entries were fixed — ratchet down with "
                  f"`python tools/tpulint.py --write-baseline` ({baseline_path})")
    if args.max_seconds is not None and elapsed > args.max_seconds:
        print(f"tpulint: run took {elapsed:.1f}s > --max-seconds "
              f"{args.max_seconds:g} — the interprocedural fixpoints are "
              "outgrowing the lint budget; profile with --json timings_s",
              file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
