"""Declarative SLO gate CLI (`make slo`, CI).

Evaluates the repo-root slo.json (or `--spec FILE`) against:

  * obs snapshots given as positional args (default: BENCH_OBS.json) —
    each is validated as a canonical snapshot first, so a corrupted
    artifact fails the gate rather than silently passing "missing";
  * bench history from `--bench` (default: BENCH_LOCAL.json) — missing
    file is an empty history, the per-spec `missing` policy decides;
  * the disabled-tracer overhead, measured inline.

Exit codes: 0 all SLOs hold, 1 at least one violated (each printed to
stderr as `SLO VIOLATION <name>: <detail>`), 2 spec or snapshot
unreadable. Pass `-v` to print the full pass/fail table either way.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from consensus_specs_tpu.obs import export as obs_export  # noqa: E402
from consensus_specs_tpu.obs import slo as obs_slo  # noqa: E402


def _load_snapshot(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        raise ValueError(f"invalid snapshot: {reason}")
    return json.loads(text)


def _load_bench(path: str) -> list:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        history = json.load(f)
    if not isinstance(history, list):
        raise ValueError("bench history is not a JSON list")
    return history


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("snapshots", nargs="*",
                        default=[os.path.join(REPO_ROOT, "BENCH_OBS.json")],
                        help="obs snapshot paths (default: BENCH_OBS.json)")
    parser.add_argument("--spec",
                        default=os.path.join(REPO_ROOT, "slo.json"),
                        help="SLO spec file (default: repo-root slo.json)")
    parser.add_argument("--bench",
                        default=os.path.join(REPO_ROOT, "BENCH_LOCAL.json"),
                        help="bench history (default: BENCH_LOCAL.json)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print every SLO's verdict, not just violations")
    args = parser.parse_args(argv)

    try:
        specs = obs_slo.load_spec_file(args.spec)
    except (OSError, ValueError, TypeError, json.JSONDecodeError) as exc:
        print(f"slo-check: cannot load spec {args.spec}: {exc}",
              file=sys.stderr)
        return 2

    snapshots = []
    for path in args.snapshots:
        try:
            snapshots.append(_load_snapshot(path))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"slo-check: cannot load snapshot {path}: {exc}",
                  file=sys.stderr)
            return 2

    try:
        bench_records = _load_bench(args.bench)
    except (ValueError, json.JSONDecodeError) as exc:
        print(f"slo-check: cannot load bench history {args.bench}: {exc}",
              file=sys.stderr)
        return 2

    results = obs_slo.evaluate(specs, snapshots, bench_records)
    summary = obs_slo.summarize(results)

    for r in results:
        if not r.ok:
            print(f"SLO VIOLATION {r.name}: {r.detail}", file=sys.stderr)
        elif args.verbose:
            print(f"slo ok    {r.name}: {r.detail}")

    print(f"slo-check: {summary['pass']} pass, {summary['fail']} fail "
          f"({len(snapshots)} snapshot(s), {len(bench_records)} bench "
          f"record(s))")
    return 1 if summary["fail"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
