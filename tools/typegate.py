"""Spec type gate: static name/arity/annotation analysis of the executable
spec markdown.

Reference parity: the mypy-strict pass the reference runs over its GENERATED
eth2spec modules (/root/reference/linter.ini:5-14, Makefile:133-136 —
disallow_incomplete_defs etc.). This image ships no mypy, so the gate is
built from the stdlib: `symtable` resolves real scopes (comprehensions,
nested defs, class bodies) and `ast` checks call shapes. Three checks over
every fork's combined spec source:

  T001  undefined name: a global-scope load that resolves to nothing in the
        overlay namespace (markdown defs, table constants, preset/config
        keys, compiler runtime, builtins) — the class of typo that otherwise
        only explodes at runtime on a rarely-taken path
  T002  bad call arity / unknown keyword for calls to spec-defined functions
  T003  incomplete def: a spec function with unannotated parameters or
        return (strict-defs analog; the spec markdown's normative python is
        fully annotated by construction, so regressions are drift)

Usage: python tools/typegate.py [fork ...]   (default: all forks)
Exit 1 on any finding. `make typegate` wires it into the lint gate.
"""
from __future__ import annotations

import ast
import builtins
import symtable
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from consensus_specs_tpu.compiler.spec_compiler import (  # noqa: E402
    FORK_DOCS,
    FORK_ORDER,
    SPEC_DIR,
    _runtime_namespace,
    load_config,
    load_preset,
    parse_spec_markdown,
)

# names legitimately absent from the static namespace (injected at runtime
# or intentionally late-bound)
RUNTIME_INJECTED = {
    "config",  # frozen Config object, built per (fork, preset)
    "fork", "preset_name",  # module identity tags
}


def combined_source(fork: str) -> tuple[str, dict]:
    """All python blocks of the fork overlay concatenated (the exec order),
    plus the table-constant names."""
    parts, constants = [], {}
    forks = FORK_ORDER[: FORK_ORDER.index(fork) + 1]
    for f in forks:
        for doc_path in FORK_DOCS[f]:
            full = SPEC_DIR / doc_path
            if not full.exists():
                continue
            # same per-doc constant policy as build_spec (single-letter
            # names are real constants outside the p2p docs)
            doc = parse_spec_markdown(
                full.read_text(), allow_single_letter_constants="p2p" not in doc_path
            )
            constants.update(doc.constants)
            parts.extend(doc.python_blocks)
    return "\n\n".join(parts), constants


def known_global_names(fork: str, constants: dict, tree: ast.Module) -> set:
    names = set(dir(builtins)) | RUNTIME_INJECTED | set(constants)
    names |= set(_runtime_namespace().keys())
    names |= set(load_preset("minimal", FORK_ORDER[: FORK_ORDER.index(fork) + 1]))
    names |= set(load_config("minimal"))
    for node in tree.body:  # module-level defs/assignments across the overlay
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Tuple):
                    names.update(e.id for e in t.elts if isinstance(e, ast.Name))
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


def check_undefined_names(src: str, known: set, fork: str) -> list[str]:
    out = []
    table = symtable.symtable(src, f"<spec:{fork}>", "exec")

    def walk(t: symtable.SymbolTable):
        for sym in t.get_symbols():
            if not sym.is_referenced() or sym.get_name() in known:
                continue
            # a symbol is suspicious only when nothing binds it anywhere in
            # this scope (assignment, param, import) and it falls through to
            # the (already-checked) global namespace
            if sym.is_assigned() or sym.is_parameter() or sym.is_imported():
                continue
            if t.get_type() == "module":
                bound_here = False
            else:
                bound_here = sym.is_local()
            if not bound_here and sym.is_global():
                out.append(f"{fork}: T001 undefined name '{sym.get_name()}' "
                           f"(scope {t.get_name()})")
        for child in t.get_children():
            walk(child)

    walk(table)
    return out


def check_call_arity(tree: ast.Module, fork: str) -> list[str]:
    sigs: dict[str, ast.arguments] = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            sigs[node.name] = node.args  # overlay order: newest wins
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            continue
        args = sigs.get(node.func.id)
        if args is None or args.vararg or args.kwarg:
            continue
        pos_names = [a.arg for a in args.posonlyargs + args.args]
        n_required = len(pos_names) - len(args.defaults)
        n_pos = len(node.args)
        if any(isinstance(a, ast.Starred) for a in node.args):
            continue
        kw_names = {k.arg for k in node.keywords if k.arg is not None}
        if None in {k.arg for k in node.keywords}:
            continue  # **kwargs splat: not statically checkable
        allowed_kw = set(pos_names) | {a.arg for a in args.kwonlyargs}
        bad_kw = kw_names - allowed_kw
        # positional params satisfied: by position, or by keyword naming one
        pos_covered = n_pos + len(kw_names & set(pos_names))
        double_bound = kw_names & set(pos_names[:n_pos])
        if bad_kw:
            out.append(f"{fork}: T002 line {node.lineno}: call "
                       f"{node.func.id}(...) has unknown keyword(s) {sorted(bad_kw)}")
        elif double_bound:
            out.append(f"{fork}: T002 line {node.lineno}: call "
                       f"{node.func.id}(...) binds {sorted(double_bound)} both "
                       f"positionally and by keyword")
        elif n_pos > len(pos_names):
            out.append(f"{fork}: T002 line {node.lineno}: call "
                       f"{node.func.id}(...) passes {n_pos} positional args, "
                       f"max {len(pos_names)}")
        elif pos_covered < n_required:
            out.append(f"{fork}: T002 line {node.lineno}: call "
                       f"{node.func.id}(...) covers {pos_covered} of "
                       f"{n_required} required positional args")
    return out


def check_annotations(tree: ast.Module, fork: str) -> list[str]:
    out = []
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        missing = [a.arg for a in node.args.posonlyargs + node.args.args
                   + node.args.kwonlyargs
                   if a.annotation is None and a.arg not in ("self", "cls")]
        if missing:
            out.append(f"{fork}: T003 line {node.lineno}: def {node.name} has "
                       f"unannotated parameter(s) {missing}")
        if node.returns is None:
            out.append(f"{fork}: T003 line {node.lineno}: def {node.name} has "
                       f"no return annotation")
    return out


def run_gate(fork: str) -> list[str]:
    src, constants = combined_source(fork)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:  # the compiler would fail the same way
        return [f"{fork}: E999 spec source syntax error line {e.lineno}: {e.msg}"]
    known = known_global_names(fork, constants, tree)
    findings = check_undefined_names(src, known, fork)
    findings += check_call_arity(tree, fork)
    findings += check_annotations(tree, fork)
    return findings


def main(argv) -> int:
    forks = argv[1:] or FORK_ORDER
    findings = []
    for fork in forks:
        findings.extend(run_gate(fork))
    for f in findings:
        print(f)
    print(f"typegate: {len(forks)} forks, {len(findings)} findings")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
