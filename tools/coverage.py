"""Line-coverage measurement on stdlib sys.monitoring (PEP 669) — no
third-party coverage package exists in this environment, and the build
gates on measured coverage the way the reference gates on pytest-cov
(`/root/reference/Makefile:100` --cov=eth2spec).

Usage:
    python tools/coverage.py [--min PCT] [--report N] -- <python args...>
    e.g. python tools/coverage.py --min 60 -- -m pytest tests/ -q -m "not slow"

Mechanics: sys.monitoring LINE events record every executed (file, line)
for files under consensus_specs_tpu/ (the compiled-markdown spec modules
exec under synthetic filenames and are skipped — their conformance is
measured by the vector round-trip, not line counts). Executable lines per
file come from compiling the source and walking the code objects'
co_lines(), so docstrings/blank lines/comments are excluded exactly as
the interpreter sees them. Exit status is non-zero when total coverage
falls below --min.
"""
from __future__ import annotations

import argparse
import runpy
import sys
from collections import defaultdict
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "consensus_specs_tpu"

TOOL_ID = sys.monitoring.PROFILER_ID
_hits: dict[str, set[int]] = defaultdict(set)


def _want(path: str) -> bool:
    return path.startswith(str(PKG)) and path.endswith(".py")


def _on_line(code, line):
    # record the first hit, then DISABLE this exact (code, line) location:
    # line coverage only needs one observation, and disabling keeps the
    # monitoring overhead near-zero on hot loops
    f = code.co_filename
    if _want(f):
        _hits[f].add(line)
    return sys.monitoring.DISABLE


def executable_lines(path: Path) -> set[int]:
    """All line numbers the compiled module can execute."""
    try:
        top = compile(path.read_text(), str(path), "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [top]
    while stack:
        code = stack.pop()
        for _, _, line in code.co_lines():
            if line is not None:
                lines.add(line)
        for const in code.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def report(min_pct: float, worst_n: int) -> int:
    rows = []
    total_exec = total_hit = 0
    for path in sorted(PKG.rglob("*.py")):
        ex = executable_lines(path)
        if not ex:
            continue
        hit = _hits.get(str(path), set()) & ex
        total_exec += len(ex)
        total_hit += len(hit)
        rows.append((len(hit) / len(ex), str(path.relative_to(REPO)), len(hit), len(ex)))
    rows.sort()
    pct = 100.0 * total_hit / max(total_exec, 1)
    print(f"\ncoverage: {pct:.1f}% ({total_hit}/{total_exec} lines, "
          f"{len(rows)} files)", file=sys.stderr)
    if worst_n:
        print(f"least covered {worst_n}:", file=sys.stderr)
        for frac, name, hit, ex in rows[:worst_n]:
            print(f"  {100*frac:5.1f}%  {name} ({hit}/{ex})", file=sys.stderr)
    if pct < min_pct:
        print(f"coverage {pct:.1f}% below required {min_pct:.1f}%", file=sys.stderr)
        return 1
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--min", type=float, default=0.0,
                        help="fail when total coverage is below this percent")
    parser.add_argument("--report", type=int, default=15,
                        help="show the N least-covered files")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by python args (e.g. -- -m pytest tests/)")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("pass the python invocation after --")

    # running as `python tools/coverage.py` puts tools/ at sys.path[0];
    # the measured package must import from the repo root
    sys.path.insert(0, str(REPO))

    sys.monitoring.use_tool_id(TOOL_ID, "consensus-tpu-coverage")
    sys.monitoring.register_callback(
        TOOL_ID, sys.monitoring.events.LINE, _on_line)
    sys.monitoring.set_events(TOOL_ID, sys.monitoring.events.LINE)

    status = 0
    try:
        if cmd[0] == "-m":
            sys.argv = [cmd[1]] + cmd[2:]
            runpy.run_module(cmd[1], run_name="__main__", alter_sys=True)
        else:
            sys.argv = cmd
            runpy.run_path(cmd[0], run_name="__main__")
    except SystemExit as exc:
        # exc.code may be None (success), an int, or a message string
        status = (exc.code if isinstance(exc.code, int)
                  else (0 if exc.code is None else 1))
    finally:
        sys.monitoring.set_events(TOOL_ID, 0)
        sys.monitoring.free_tool_id(TOOL_ID)
    rc = report(args.min, args.report)
    return status or rc


if __name__ == "__main__":
    raise SystemExit(main())
