"""Observability snapshot tool (`make obs-dump`, CI artifact checks).

Four subcommands — three over the canonical JSON snapshot format
(consensus_specs_tpu/obs/export.py), one over the span-dump format
(consensus_specs_tpu/obs/timeline.py):

  check FILE   validate an on-disk snapshot: parseable, right version,
               canonical bytes, and Prometheus round-trip (the text
               exposition's value set must equal the JSON's). Exit 0 ok,
               1 invalid, 2 unreadable. CI runs this over every uploaded
               artifact; tools/bench_probe.py runs it over the snapshot
               persisted next to BENCH_LOCAL.json.
  prom FILE    render the snapshot as Prometheus text exposition (stdout),
               for scraping/diffing with standard tooling.
  table FILE   human-oriented summary: counters and gauges sorted by
               series key, histograms as count/sum/p50/p99. `--top N`
               flips to hot-spot mode: the N highest-value counters and
               gauges and the N fattest-p99 histograms, flat, hottest
               first.
  trace FILE   render a span dump (timeline.write_span_dump) as Chrome
               trace event JSON — load the output in Perfetto /
               chrome://tracing to see spans in per-thread lanes with
               flow arrows following each request across them. `-o OUT`
               writes to a file instead of stdout.

`FILE` may be `-` for stdin, so `... | obs_dump.py check -` works in a
pipeline.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from consensus_specs_tpu.obs import export as obs_export  # noqa: E402


def _read(path: str) -> str:
    if path == "-":
        return sys.stdin.read()
    with open(path) as f:
        return f.read()


def cmd_check(path: str) -> int:
    try:
        text = _read(path)
    except OSError as exc:
        print(f"obs-dump: cannot read {path}: {exc}", file=sys.stderr)
        return 2
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        print(f"obs-dump: INVALID snapshot {path}: {reason}", file=sys.stderr)
        return 1
    import json

    snap = json.loads(text)
    json_vals = obs_export.snapshot_value_set(snap)
    prom_vals = obs_export.prometheus_value_set(obs_export.prometheus_text(snap))
    if json_vals != prom_vals:
        only_j = sorted(set(json_vals) - set(prom_vals))[:5]
        only_p = sorted(set(prom_vals) - set(json_vals))[:5]
        diff = sorted(k for k in set(json_vals) & set(prom_vals)
                      if json_vals[k] != prom_vals[k])[:5]
        print(f"obs-dump: EXPORTER DISAGREEMENT {path}: "
              f"json-only={only_j} prom-only={only_p} differing={diff}",
              file=sys.stderr)
        return 1
    n = (len(snap.get("counters", {})) + len(snap.get("gauges", {}))
         + len(snap.get("histograms", {})))
    print(f"obs-dump: OK {path} ({n} series, version {snap['version']})")
    return 0


def _load(path: str) -> dict:
    text = _read(path)
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        raise SystemExit(f"obs-dump: INVALID snapshot {path}: {reason}")
    import json

    return json.loads(text)


def cmd_prom(path: str) -> int:
    sys.stdout.write(obs_export.prometheus_text(_load(path)))
    return 0


def _subsystem(series_key: str) -> str:
    """Grouping prefix of a series key: the first `_`-delimited token of
    the metric name (`sched_queue_depth{...}` -> `sched`). Series whose
    name has no underscore group under the whole name."""
    name = series_key.split("{", 1)[0]
    return name.split("_", 1)[0]


def cmd_table(path: str, top: int | None = None) -> int:
    """Human-oriented summary, grouped by subsystem prefix so the lanes a
    snapshot covers (sched_*, bls_*, gossip_*, fault_*, ...) read as
    blocks instead of one interleaved flat list. Within a group, rows
    keep canonical order: counters, then gauges, then histograms, each
    sorted by series key. With --top N the grouping drops: the N hottest
    counters/gauges (by value) and histograms (by p99) print flat,
    hottest first — what an operator scans during an incident."""
    snap = _load(path)
    if top is not None:
        return _table_top(snap, top)
    rows = []
    for key, v in sorted(snap.get("counters", {}).items()):
        rows.append((_subsystem(key), key, "counter", f"{v:g}"))
    for key, v in sorted(snap.get("gauges", {}).items()):
        rows.append((_subsystem(key), key, "gauge", f"{v:g}"))
    for key, h in sorted(snap.get("histograms", {}).items()):
        rows.append((_subsystem(key), key, "histogram",
                     f"count={h['count']} sum={h['sum']:.6g} "
                     f"p50={h['p50']:.6g} p99={h['p99']:.6g}"))
    if not rows:
        print("(empty snapshot)")
        return 0
    width = max(len(r[1]) for r in rows)
    by_group: dict = {}
    for group, key, kind, val in rows:
        by_group.setdefault(group, []).append((key, kind, val))
    for i, group in enumerate(sorted(by_group)):
        if i:
            print()
        print(f"[{group}]")
        for key, kind, val in by_group[group]:
            print(f"  {key:<{width}}  {kind:<9}  {val}")
    if "meta" in snap:
        print(f"\nmeta: {snap['meta']}")
    return 0


def _table_top(snap: dict, top: int) -> int:
    """Hot-spot view: counter/gauge rows ranked by value, histogram rows
    by p99 — series key ties break alphabetically so equal snapshots
    print identically."""
    scalars = ([(v, key, "counter") for key, v in
                snap.get("counters", {}).items()]
               + [(v, key, "gauge") for key, v in
                  snap.get("gauges", {}).items()])
    scalars.sort(key=lambda r: (-r[0], r[1]))
    hists = sorted(((h["p99"], key, h) for key, h in
                    snap.get("histograms", {}).items()),
                   key=lambda r: (-r[0], r[1]))
    if not scalars and not hists:
        print("(empty snapshot)")
        return 0
    rows = []
    for v, key, kind in scalars[:top]:
        rows.append((key, kind, f"{v:g}"))
    for p99, key, h in hists[:top]:
        rows.append((key, "histogram",
                     f"p99={p99:.6g} p50={h['p50']:.6g} "
                     f"count={h['count']} sum={h['sum']:.6g}"))
    width = max(len(r[0]) for r in rows)
    if scalars:
        print(f"[top {min(top, len(scalars))} counters/gauges by value]")
        for key, kind, val in rows[:len(scalars[:top])]:
            print(f"  {key:<{width}}  {kind:<9}  {val}")
    if hists:
        if scalars:
            print()
        print(f"[top {min(top, len(hists))} histograms by p99]")
        for key, kind, val in rows[len(scalars[:top]):]:
            print(f"  {key:<{width}}  {kind:<9}  {val}")
    return 0


def cmd_trace(path: str, output: str) -> int:
    """Span dump -> Chrome trace event JSON (Perfetto-loadable)."""
    from consensus_specs_tpu.obs import timeline as obs_timeline

    try:
        text = _read(path)
    except OSError as exc:
        print(f"obs-dump: cannot read {path}: {exc}", file=sys.stderr)
        return 2
    try:
        spans = obs_timeline.load_span_dump(text)
    except ValueError as exc:
        print(f"obs-dump: INVALID span dump {path}: {exc}", file=sys.stderr)
        return 1
    out = obs_export.canonical_json(obs_timeline.chrome_trace(spans))
    if output == "-":
        sys.stdout.write(out)
    else:
        with open(output, "w") as f:
            f.write(out)
        n = sum(1 for s in spans if s.get("t_start") is not None)
        print(f"obs-dump: wrote {output} ({n} spans)")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name, doc in (("check", "validate canonicality + exporter agreement"),
                      ("prom", "render Prometheus text exposition"),
                      ("table", "human-oriented summary"),
                      ("trace", "span dump -> Chrome/Perfetto trace JSON")):
        p = sub.add_parser(name, help=doc)
        p.add_argument("file", help="snapshot path, or - for stdin")
        if name == "table":
            p.add_argument("--top", type=int, default=None, metavar="N",
                           help="flat hot-spot view: top N counters/gauges "
                                "by value, histograms by p99")
        if name == "trace":
            p.add_argument("-o", "--output", default="-",
                           help="output path (default: stdout)")
    args = parser.parse_args(argv)
    if args.cmd == "check":
        return cmd_check(args.file)
    if args.cmd == "prom":
        return cmd_prom(args.file)
    if args.cmd == "table":
        return cmd_table(args.file, top=args.top)
    return cmd_trace(args.file, args.output)


if __name__ == "__main__":
    try:
        rc = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # downstream pager/head closed the pipe — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 0
    raise SystemExit(rc)
