"""Observability snapshot tool (`make obs-dump`, CI artifact checks).

Three subcommands over the canonical JSON snapshot format
(consensus_specs_tpu/obs/export.py):

  check FILE   validate an on-disk snapshot: parseable, right version,
               canonical bytes, and Prometheus round-trip (the text
               exposition's value set must equal the JSON's). Exit 0 ok,
               1 invalid, 2 unreadable. CI runs this over every uploaded
               artifact; tools/bench_probe.py runs it over the snapshot
               persisted next to BENCH_LOCAL.json.
  prom FILE    render the snapshot as Prometheus text exposition (stdout),
               for scraping/diffing with standard tooling.
  table FILE   human-oriented summary: counters and gauges sorted by
               series key, histograms as count/sum/p50/p99.

`FILE` may be `-` for stdin, so `... | obs_dump.py check -` works in a
pipeline.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from consensus_specs_tpu.obs import export as obs_export  # noqa: E402


def _read(path: str) -> str:
    if path == "-":
        return sys.stdin.read()
    with open(path) as f:
        return f.read()


def cmd_check(path: str) -> int:
    try:
        text = _read(path)
    except OSError as exc:
        print(f"obs-dump: cannot read {path}: {exc}", file=sys.stderr)
        return 2
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        print(f"obs-dump: INVALID snapshot {path}: {reason}", file=sys.stderr)
        return 1
    import json

    snap = json.loads(text)
    json_vals = obs_export.snapshot_value_set(snap)
    prom_vals = obs_export.prometheus_value_set(obs_export.prometheus_text(snap))
    if json_vals != prom_vals:
        only_j = sorted(set(json_vals) - set(prom_vals))[:5]
        only_p = sorted(set(prom_vals) - set(json_vals))[:5]
        diff = sorted(k for k in set(json_vals) & set(prom_vals)
                      if json_vals[k] != prom_vals[k])[:5]
        print(f"obs-dump: EXPORTER DISAGREEMENT {path}: "
              f"json-only={only_j} prom-only={only_p} differing={diff}",
              file=sys.stderr)
        return 1
    n = (len(snap.get("counters", {})) + len(snap.get("gauges", {}))
         + len(snap.get("histograms", {})))
    print(f"obs-dump: OK {path} ({n} series, version {snap['version']})")
    return 0


def _load(path: str) -> dict:
    text = _read(path)
    ok, reason = obs_export.validate_snapshot_text(text)
    if not ok:
        raise SystemExit(f"obs-dump: INVALID snapshot {path}: {reason}")
    import json

    return json.loads(text)


def cmd_prom(path: str) -> int:
    sys.stdout.write(obs_export.prometheus_text(_load(path)))
    return 0


def _subsystem(series_key: str) -> str:
    """Grouping prefix of a series key: the first `_`-delimited token of
    the metric name (`sched_queue_depth{...}` -> `sched`). Series whose
    name has no underscore group under the whole name."""
    name = series_key.split("{", 1)[0]
    return name.split("_", 1)[0]


def cmd_table(path: str) -> int:
    """Human-oriented summary, grouped by subsystem prefix so the lanes a
    snapshot covers (sched_*, bls_*, gossip_*, fault_*, ...) read as
    blocks instead of one interleaved flat list. Within a group, rows
    keep canonical order: counters, then gauges, then histograms, each
    sorted by series key."""
    snap = _load(path)
    rows = []
    for key, v in sorted(snap.get("counters", {}).items()):
        rows.append((_subsystem(key), key, "counter", f"{v:g}"))
    for key, v in sorted(snap.get("gauges", {}).items()):
        rows.append((_subsystem(key), key, "gauge", f"{v:g}"))
    for key, h in sorted(snap.get("histograms", {}).items()):
        rows.append((_subsystem(key), key, "histogram",
                     f"count={h['count']} sum={h['sum']:.6g} "
                     f"p50={h['p50']:.6g} p99={h['p99']:.6g}"))
    if not rows:
        print("(empty snapshot)")
        return 0
    width = max(len(r[1]) for r in rows)
    by_group: dict = {}
    for group, key, kind, val in rows:
        by_group.setdefault(group, []).append((key, kind, val))
    for i, group in enumerate(sorted(by_group)):
        if i:
            print()
        print(f"[{group}]")
        for key, kind, val in by_group[group]:
            print(f"  {key:<{width}}  {kind:<9}  {val}")
    if "meta" in snap:
        print(f"\nmeta: {snap['meta']}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name, doc in (("check", "validate canonicality + exporter agreement"),
                      ("prom", "render Prometheus text exposition"),
                      ("table", "human-oriented summary")):
        p = sub.add_parser(name, help=doc)
        p.add_argument("file", help="snapshot path, or - for stdin")
    args = parser.parse_args(argv)
    return {"check": cmd_check, "prom": cmd_prom,
            "table": cmd_table}[args.cmd](args.file)


if __name__ == "__main__":
    try:
        rc = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # downstream pager/head closed the pipe — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 0
    raise SystemExit(rc)
