"""Merkle single-proof vector generator.

Reference parity: tests/generators/merkle/main.py + tests/formats/merkle —
a BeaconState object plus (leaf, leaf_index, branch) proofs that clients
verify with is_valid_merkle_branch / calculate_merkle_root. Proofs are
built over the altair state for the light-client-critical gindices
(finalized_checkpoint.root = 105, next_sync_committee = 55,
current_sync_committee = 54).

Usage: python main.py -o <output_dir> [--preset-list minimal]
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.compiler import get_spec
from consensus_specs_tpu.gen import TestCase, TestProvider
from consensus_specs_tpu.gen.gen_runner import run_generator
from consensus_specs_tpu.ssz import serialize
from consensus_specs_tpu.ssz.gindex import get_generalized_index
from consensus_specs_tpu.ssz.proofs import build_proof, get_subtree_node_root
from consensus_specs_tpu.testlib.genesis import create_valid_beacon_state


def make_cases():
    spec = get_spec("altair", "minimal")
    state = create_valid_beacon_state(spec, num_validators=32)
    paths = {
        "finalized_root": ("finalized_checkpoint", "root"),
        "current_sync_committee": ("current_sync_committee",),
        "next_sync_committee": ("next_sync_committee",),
    }
    for name, path in paths.items():
        gindex = get_generalized_index(type(state), *path)

        def case_fn(state=state, gindex=gindex):
            branch = build_proof(state, gindex)
            leaf = get_subtree_node_root(state, gindex)
            return [
                ("object", "ssz", serialize(state)),
                (
                    "proof",
                    "data",
                    {
                        "leaf": "0x" + leaf.hex(),
                        "leaf_index": int(gindex),
                        "branch": ["0x" + b.hex() for b in branch],
                    },
                ),
            ]

        yield TestCase(
            fork_name="altair",
            preset_name="minimal",
            runner_name="merkle",
            handler_name="single_proof",
            suite_name="pyspec_tests",
            case_name=f"{name}_merkle_proof",
            case_fn=case_fn,
        )


if __name__ == "__main__":
    raise SystemExit(run_generator("merkle", [TestProvider(make_cases=make_cases)]))
