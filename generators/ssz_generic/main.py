"""ssz_generic vector generator: valid + invalid codec cases per type family.

Reference parity: tests/generators/ssz_generic (uints, booleans, bitvector,
bitlist, basic_vector, containers; valid cases carry serialized bytes +
value + root, invalid cases carry only the malformed serialization that
deserializers MUST reject).

Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.debug.encode import encode
from consensus_specs_tpu.gen import TestCase, TestProvider
from consensus_specs_tpu.gen.gen_runner import run_generator
from consensus_specs_tpu.ssz import hash_tree_root, serialize
from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)


class SingleFieldContainer(Container):
    a: uint64


class FixedContainer(Container):
    a: uint64
    b: uint32
    c: Vector[uint16, 3]


class VarContainer(Container):
    a: uint64
    items: List[uint16, 32]
    tail: uint8


def _valid(handler, name, value, typ=None):
    def case_fn():
        data = serialize(value)
        return [
            ("serialized", "ssz", data),
            ("value", "data", encode(value)),
            ("meta", "meta", {"root": "0x" + hash_tree_root(value).hex()}),
        ]

    return TestCase(
        fork_name="general",
        preset_name="general",
        runner_name="ssz_generic",
        handler_name=handler,
        suite_name="valid",
        case_name=name,
        case_fn=case_fn,
    )


def _invalid(handler, name, raw: bytes, typ):
    def case_fn():
        # sanity: the framework's own decoder must reject this input
        try:
            typ.decode_bytes(raw)
        except Exception:
            pass
        else:
            raise AssertionError(f"decoder accepted invalid case {name}")
        return [("serialized", "ssz", raw)]

    return TestCase(
        fork_name="general",
        preset_name="general",
        runner_name="ssz_generic",
        handler_name=handler,
        suite_name="invalid",
        case_name=name,
        case_fn=case_fn,
    )


def make_cases():
    # uints: bounds per width
    for typ, bits in ((uint8, 8), (uint16, 16), (uint32, 32), (uint64, 64), (uint128, 128), (uint256, 256)):
        hi = (1 << bits) - 1
        for label, v in (("zero", 0), ("one", 1), ("max", hi), ("mid", hi // 3)):
            yield _valid(f"uints", f"uint_{bits}_{label}", typ(v))
        yield _invalid("uints", f"uint_{bits}_short", b"\x01" * (bits // 8 - 1), typ)
        yield _invalid("uints", f"uint_{bits}_long", b"\x01" * (bits // 8 + 1), typ)

    # booleans: only 0x00/0x01 canonical
    yield _valid("boolean", "true", boolean(True))
    yield _valid("boolean", "false", boolean(False))
    yield _invalid("boolean", "byte_2", b"\x02", boolean)
    yield _invalid("boolean", "byte_ff", b"\xff", boolean)
    yield _invalid("boolean", "empty", b"", boolean)

    # bitvector
    for n in (1, 8, 9, 16, 31):
        bv = Bitvector[n](*([True, False] * n)[:n])
        yield _valid("bitvector", f"bitvec_{n}_alternating", bv)
    yield _invalid("bitvector", "bitvec_9_extra_byte", b"\x01\x01\x01", Bitvector[9])
    yield _invalid("bitvector", "bitvec_9_nonzero_padding", b"\x01\xfe", Bitvector[9])
    yield _invalid("bitvector", "bitvec_1_empty", b"", Bitvector[1])

    # bitlist: sentinel mechanics
    for limit, bits in ((8, []), (8, [True] * 8), (16, [True, False, True])):
        bl = Bitlist[limit](*bits)
        yield _valid("bitlist", f"bitlist_{limit}_len{len(bits)}", bl)
    yield _invalid("bitlist", "bitlist_8_no_sentinel_zero_byte", b"\x00", Bitlist[8])
    yield _invalid("bitlist", "bitlist_8_over_limit", b"\xff\xff\x01", Bitlist[8])
    yield _invalid("bitlist", "bitlist_8_empty", b"", Bitlist[8])

    # vectors of basics
    yield _valid("basic_vector", "vec_uint64_4", Vector[uint64, 4](1, 2, 3, (1 << 64) - 1))
    yield _valid("basic_vector", "vec_uint8_32", Vector[uint8, 32](*range(32)))
    yield _invalid("basic_vector", "vec_uint64_4_short", b"\x00" * 24, Vector[uint64, 4])
    yield _invalid("basic_vector", "vec_uint64_4_long", b"\x00" * 40, Vector[uint64, 4])

    # containers: fixed and variable layouts
    yield _valid("containers", "single_field", SingleFieldContainer(a=uint64(7)))
    yield _valid(
        "containers",
        "fixed_fields",
        FixedContainer(a=uint64(1), b=uint32(2), c=Vector[uint16, 3](3, 4, 5)),
    )
    yield _valid(
        "containers",
        "variable_empty_list",
        VarContainer(a=uint64(9), items=List[uint16, 32](), tail=uint8(1)),
    )
    yield _valid(
        "containers",
        "variable_full",
        VarContainer(a=uint64(9), items=List[uint16, 32](*range(32)), tail=uint8(250)),
    )
    # offset pathologies
    good = serialize(VarContainer(a=uint64(9), items=List[uint16, 32](1, 2), tail=uint8(3)))
    # offset points before the fixed region
    bad_offset = good[:8] + (0).to_bytes(4, "little") + good[12:]
    yield _invalid("containers", "var_offset_before_fixed_region", bad_offset, VarContainer)
    # offset beyond the buffer
    far_offset = good[:8] + (len(good) + 7).to_bytes(4, "little") + good[12:]
    yield _invalid("containers", "var_offset_past_end", far_offset, VarContainer)
    yield _invalid("containers", "truncated_fixed_part", good[:6], VarContainer)


if __name__ == "__main__":
    raise SystemExit(run_generator("ssz_generic", [TestProvider(make_cases=make_cases)]))
