"""Block-operation vector generator.

Reference parity: tests/generators/operations/main.py.
Usage: python main.py -o <output_dir> [--preset-list minimal]
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators

from consensus_specs_tpu.spec_tests import operations as ops
from consensus_specs_tpu.spec_tests import operations_extended as ops_ext
from consensus_specs_tpu.spec_tests import sync_aggregate

ALL_MODS = {
    "phase0": {"operations": [ops, ops_ext]},
    "altair": {"operations": [ops, ops_ext], "sync_aggregate": sync_aggregate},
    "bellatrix": {"operations": [ops, ops_ext], "sync_aggregate": sync_aggregate},
}

if __name__ == "__main__":
    run_state_test_generators("operations", ALL_MODS)
