"""Sanity (blocks/slots) vector generator.

Reference parity: tests/generators/sanity/main.py.
Usage: python main.py -o <output_dir> [--preset-list minimal]
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators

from consensus_specs_tpu.spec_tests import sanity_blocks

ALL_MODS = {
    "phase0": {"blocks": sanity_blocks},
    "altair": {"blocks": sanity_blocks},
    "bellatrix": {"blocks": sanity_blocks},
}

if __name__ == "__main__":
    run_state_test_generators("sanity", ALL_MODS)
