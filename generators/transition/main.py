"""Cross-fork transition vector generator.

Reference parity: tests/generators/transition/main.py.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import transition

ALL_MODS = {
    "phase0": {"core": transition},
    "altair": {"core": transition},
}

if __name__ == "__main__":
    run_state_test_generators("transition", ALL_MODS, presets=("minimal",))
