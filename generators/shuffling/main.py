"""Shuffling vector generator: full swap-or-not permutation maps.

Reference parity: tests/generators/shuffling/main.py + tests/formats/shuffling
— per seed and count, a mapping.yaml {seed, count, mapping} that clients
replay against their shuffle implementation. The mapping comes from the
batched device kernel (ops/shuffle.py), which the test suite has already
differentially validated against the scalar spec.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.compiler import get_spec
from consensus_specs_tpu.gen import TestCase, TestProvider
from consensus_specs_tpu.gen.gen_runner import run_generator
# The numpy twin, NOT the device kernel: the kernel compiles one XLA
# program per (count, rounds) shape, which across this generator's count
# sweep made vector generation compile-bound (VERDICT r3 weak #7). The
# twin is bit-identical (tests/test_shuffle.py) and compile-free.
from consensus_specs_tpu.ops.shuffle import compute_shuffled_indices_np


def make_cases():
    for preset in ("minimal", "mainnet"):
        spec = get_spec("phase0", preset)
        rounds = int(spec.SHUFFLE_ROUND_COUNT)
        for seed_i in range(16):
            seed = spec.hash(seed_i.to_bytes(4, "little"))
            for count in (1, 2, 3, 5, 8, 16, 21, 64, 256, 512, 1000):
                name = f"shuffle_0x{bytes(seed).hex()[:18]}_{count}"

                def case_fn(seed=seed, count=count, rounds=rounds):
                    mapping = compute_shuffled_indices_np(count, bytes(seed), rounds)
                    return [
                        (
                            "mapping",
                            "data",
                            {
                                "seed": "0x" + bytes(seed).hex(),
                                "count": count,
                                "mapping": [int(x) for x in mapping],
                            },
                        )
                    ]

                yield TestCase(
                    fork_name="phase0",
                    preset_name=preset,
                    runner_name="shuffling",
                    handler_name="core",
                    suite_name="shuffle",
                    case_name=name,
                    case_fn=case_fn,
                )


if __name__ == "__main__":
    raise SystemExit(run_generator("shuffling", [TestProvider(make_cases=make_cases)]))
