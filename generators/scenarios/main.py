"""Scenario-engine vector generator: seeded long-horizon histories
emitted from the TPU lane (the chaos-enabled engine replay supplies the
fork-choice checks payloads) into the reference
<preset>/<fork>/<runner>/<handler> tree — runners fork_choice/scenario
and sanity/blocks per segment.

Usage: python main.py -o <output_dir> [-f] [--seeds 1,2] [--epochs 8]
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.scenarios import (
    build_history,
    build_script,
    emit_history,
    engine_lane,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-o", "--output-dir", required=True)
    ap.add_argument("-f", "--force", action="store_true")
    ap.add_argument("--seeds", default="1,2",
                    help="comma-separated scenario seeds")
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--smoke", type=int, default=None, metavar="N",
                    help="stop after N generated cases (the default-lane "
                         "generator health probe)")
    args = ap.parse_args(argv)
    for seed in (int(s) for s in args.seeds.split(",") if s):
        script = build_script(seed, epochs=args.epochs)
        history = build_history(script)
        lane = engine_lane(history, fault_seed=seed)
        for rel in emit_history(history, Path(args.output_dir),
                                lane_result=lane, force=args.force,
                                smoke=args.smoke):
            print(f"  {rel}")
        if args.smoke is not None:
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
