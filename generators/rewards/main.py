"""Rewards vector generator (per-component Deltas).

Reference parity: tests/generators/rewards/main.py.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import rewards

ALL_MODS = {
    "phase0": {"basic": rewards},
    "altair": {"basic": rewards},
    "bellatrix": {"basic": rewards},
}

if __name__ == "__main__":
    run_state_test_generators("rewards", ALL_MODS)
