"""Epoch-processing vector generator.

Reference parity: tests/generators/epoch_processing/main.py — maps fork ->
dual-mode test modules and runs them through the generator runtime.
Usage: python main.py -o <output_dir> [--preset-list minimal]
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators

from consensus_specs_tpu.spec_tests import epoch_processing as ep

ALL_MODS = {
    "phase0": {"epoch_processing": ep},
    "altair": {"epoch_processing": ep},
    "bellatrix": {"epoch_processing": ep},
}

if __name__ == "__main__":
    run_state_test_generators("epoch_processing", ALL_MODS)
