"""Fork-upgrade vector generator (upgrade_to_<fork> pre/post states).

Reference parity: tests/generators/forks/main.py.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import forks

ALL_MODS = {
    "phase0": {"fork": forks},
    "altair": {"fork": forks},
}

if __name__ == "__main__":
    run_state_test_generators("forks", ALL_MODS, presets=("minimal",))
