"""BLS test-vector generator: hand-written cases incl. edge conditions.

Reference parity: tests/generators/bls/main.py (~550 LoC) — vectors for
Sign / Verify / Aggregate / AggregateVerify / FastAggregateVerify /
eth-extension behaviors, with the consensus-critical edge cases: the zero
privkey is invalid, the infinity pubkey/signature must be rejected by
Verify-family calls, empty aggregation input is an error,
eth_fast_aggregate_verify accepts (no pubkeys, infinity sig).

Format (tests/formats/bls): one data.yaml per case with {input, output}.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.crypto import bls_sig
from consensus_specs_tpu.crypto.bls12_381 import R as CURVE_ORDER
from consensus_specs_tpu.crypto.hash_to_curve import MAP_TO_CURVE_RFC_COMPLIANT
from consensus_specs_tpu.gen import TestCase, TestProvider
from consensus_specs_tpu.gen.gen_runner import run_generator

# Interop gate (VERDICT r1): vectors produced with a non-RFC-9380 map would
# look valid but be unusable by real clients — refuse to emit them silently.
if not MAP_TO_CURVE_RFC_COMPLIANT:  # not assert: must survive python -O
    raise SystemExit(
        "hash-to-curve is not RFC-9380 interoperable; BLS vectors would not "
        "be client-consumable (see crypto/hash_to_curve.py)"
    )

PRIVKEYS = [
    1,
    42,
    2**32 - 1,
    CURVE_ORDER - 1,
    int.from_bytes(b"\x12" * 32, "big") % CURVE_ORDER,
]
MESSAGES = [b"\x00" * 32, b"\xab" * 32, b"consensus-specs-tpu bls vectors!"]

Z1_PUBKEY = b"\xc0" + b"\x00" * 47  # infinity G1, compressed
Z2_SIGNATURE = b"\xc0" + b"\x00" * 95  # infinity G2, compressed


def hexify(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _case(handler, name, data):
    return TestCase(
        fork_name="general",
        preset_name="general",
        runner_name="bls",
        handler_name=handler,
        suite_name="bls",
        case_name=name,
        case_fn=lambda data=data: [("data", "data", data)],
    )


def sign_cases():
    for i, sk in enumerate(PRIVKEYS):
        for j, msg in enumerate(MESSAGES):
            sig = bls_sig.Sign(sk, msg)
            yield _case(
                "sign",
                f"sign_case_{i}_{j}",
                {
                    "input": {"privkey": hexify(sk.to_bytes(32, "big")), "message": hexify(msg)},
                    "output": hexify(sig),
                },
            )
    # the zero privkey is not a valid BLS secret: expect null output
    yield _case(
        "sign",
        "sign_case_zero_privkey",
        {"input": {"privkey": hexify(b"\x00" * 32), "message": hexify(MESSAGES[0])}, "output": None},
    )


def verify_cases():
    sk, msg = PRIVKEYS[1], MESSAGES[1]
    pk = bls_sig.SkToPk(sk)
    sig = bls_sig.Sign(sk, msg)
    good = {"pubkey": hexify(pk), "message": hexify(msg), "signature": hexify(sig)}
    yield _case("verify", "verify_valid", {"input": good, "output": True})
    yield _case(
        "verify",
        "verify_wrong_message",
        {"input": {**good, "message": hexify(MESSAGES[0])}, "output": False},
    )
    wrong_sig = bls_sig.Sign(PRIVKEYS[0], msg)
    yield _case(
        "verify",
        "verify_wrong_signer",
        {"input": {**good, "signature": hexify(wrong_sig)}, "output": False},
    )
    yield _case(
        "verify",
        "verify_tampered_signature",
        {"input": {**good, "signature": hexify(b"\xff" * 96)}, "output": False},
    )
    # infinity pubkey / infinity signature must both be rejected
    yield _case(
        "verify",
        "verify_infinity_pubkey",
        {
            "input": {"pubkey": hexify(Z1_PUBKEY), "message": hexify(msg), "signature": hexify(Z2_SIGNATURE)},
            "output": False,
        },
    )
    yield _case(
        "verify",
        "verify_infinity_signature",
        {"input": {**good, "signature": hexify(Z2_SIGNATURE)}, "output": False},
    )


def aggregate_cases():
    msg = MESSAGES[2]
    sigs = [bls_sig.Sign(sk, msg) for sk in PRIVKEYS[:3]]
    agg = bls_sig.Aggregate(sigs)
    yield _case(
        "aggregate",
        "aggregate_3_signatures",
        {"input": [hexify(s) for s in sigs], "output": hexify(agg)},
    )
    yield _case(
        "aggregate",
        "aggregate_single",
        {"input": [hexify(sigs[0])], "output": hexify(sigs[0])},
    )
    # empty input is an error (reference returns null output)
    yield _case("aggregate", "aggregate_empty", {"input": [], "output": None})
    yield _case(
        "aggregate",
        "aggregate_infinity",
        {"input": [hexify(Z2_SIGNATURE), hexify(Z2_SIGNATURE)], "output": hexify(Z2_SIGNATURE)},
    )


def aggregate_verify_cases():
    pairs = list(zip(PRIVKEYS[:3], MESSAGES))
    pks = [bls_sig.SkToPk(sk) for sk, _ in pairs]
    sig = bls_sig.Aggregate([bls_sig.Sign(sk, m) for sk, m in pairs])
    good = {
        "pubkeys": [hexify(pk) for pk in pks],
        "messages": [hexify(m) for _, m in pairs],
        "signature": hexify(sig),
    }
    yield _case("aggregate_verify", "aggregate_verify_valid", {"input": good, "output": True})
    shuffled = dict(good, messages=list(reversed(good["messages"])))
    yield _case("aggregate_verify", "aggregate_verify_wrong_order", {"input": shuffled, "output": False})
    yield _case(
        "aggregate_verify",
        "aggregate_verify_infinity_pubkey",
        {
            "input": {**good, "pubkeys": good["pubkeys"][:2] + [hexify(Z1_PUBKEY)]},
            "output": False,
        },
    )
    yield _case(
        "aggregate_verify",
        "aggregate_verify_empty",
        {"input": {"pubkeys": [], "messages": [], "signature": hexify(Z2_SIGNATURE)}, "output": False},
    )


def fast_aggregate_verify_cases():
    msg = MESSAGES[0]
    sks = PRIVKEYS[:4]
    pks = [bls_sig.SkToPk(sk) for sk in sks]
    sig = bls_sig.Aggregate([bls_sig.Sign(sk, msg) for sk in sks])
    good = {"pubkeys": [hexify(pk) for pk in pks], "message": hexify(msg), "signature": hexify(sig)}
    yield _case("fast_aggregate_verify", "fast_aggregate_verify_valid", {"input": good, "output": True})
    yield _case(
        "fast_aggregate_verify",
        "fast_aggregate_verify_extra_pubkey",
        {
            "input": {**good, "pubkeys": good["pubkeys"] + [hexify(bls_sig.SkToPk(PRIVKEYS[4]))]},
            "output": False,
        },
    )
    yield _case(
        "fast_aggregate_verify",
        "fast_aggregate_verify_empty_pubkeys",
        {"input": {**good, "pubkeys": []}, "output": False},
    )
    yield _case(
        "fast_aggregate_verify",
        "fast_aggregate_verify_infinity_signature",
        {"input": {**good, "signature": hexify(Z2_SIGNATURE)}, "output": False},
    )


def make_cases():
    yield from sign_cases()
    yield from verify_cases()
    yield from aggregate_cases()
    yield from aggregate_verify_cases()
    yield from fast_aggregate_verify_cases()


if __name__ == "__main__":
    raise SystemExit(run_generator("bls", [TestProvider(make_cases=make_cases)]))
