"""ssz_static vector generator: random roundtrips of every spec container.

Reference parity: tests/generators/ssz_static/main.py + tests/formats/
ssz_static — for each SSZ container in each compiled fork, emit randomized
instances as {roots.yaml (hash_tree_root), serialized.ssz_snappy,
value.yaml (debug encoding)} across the randomization modes of
debug/random_value.py.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from random import Random

from consensus_specs_tpu.compiler import get_spec
from consensus_specs_tpu.debug import RandomizationMode, encode, get_random_ssz_object
from consensus_specs_tpu.gen import TestCase, TestProvider
from consensus_specs_tpu.gen.gen_runner import run_generator
from consensus_specs_tpu.ssz import Container, hash_tree_root

MAX_BYTES_LENGTH = 1000
MAX_LIST_LENGTH = 10


def ssz_container_types(spec):
    out = {}
    for name, obj in vars(spec).items():
        if isinstance(obj, type) and issubclass(obj, Container) and obj is not Container:
            out[name] = obj
    return out


def make_cases():
    for preset in ("minimal",):
        for fork in ("phase0", "altair", "bellatrix"):
            spec = get_spec(fork, preset)
            for type_name, typ in sorted(ssz_container_types(spec).items()):
                for mode in RandomizationMode:
                    for chaos in (False, True) if mode == RandomizationMode.mode_random else (False,):
                        count = 3 if mode == RandomizationMode.mode_random else 1
                        for i in range(count):
                            seed = hash((fork, type_name, mode.value, chaos, i)) & 0xFFFFFFFF

                            def case_fn(typ=typ, mode=mode, chaos=chaos, seed=seed):
                                value = get_random_ssz_object(
                                    Random(seed), typ, MAX_BYTES_LENGTH, MAX_LIST_LENGTH, mode, chaos
                                )
                                return [
                                    ("roots", "data", {"root": "0x" + bytes(hash_tree_root(value)).hex()}),
                                    ("serialized", "ssz", value),
                                    ("value", "data", encode(value)),
                                ]

                            suffix = f"{mode.name}{'_chaos' if chaos else ''}_{i}"
                            yield TestCase(
                                fork_name=fork,
                                preset_name=preset,
                                runner_name="ssz_static",
                                handler_name=type_name,
                                suite_name="ssz_random",
                                case_name=f"case_{suffix}",
                                case_fn=case_fn,
                            )


if __name__ == "__main__":
    raise SystemExit(run_generator("ssz_static", [TestProvider(make_cases=make_cases)]))
