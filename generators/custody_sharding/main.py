"""Custody-game + sharding operation vector generator.

BEYOND reference parity: the reference disables sharding-era testgen
(tests/generators/operations/main.py:26-33 comments them out); this
framework compiles those forks, so their suites emit replayable vectors
like any other fork.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.crypto import kzg, kzg_shim
from consensus_specs_tpu.gen import run_state_test_generators

from consensus_specs_tpu.spec_tests import custody_game, sharding

# generator mode runs with LIVE crypto (the reference forces its fast
# backend for all vector generation): the sharding/custody pairing checks
# need the deterministic trusted setup installed
kzg_shim.use_setup(kzg.insecure_test_setup(16))

ALL_MODS = {
    "custody_game": {"custody": custody_game},
    "sharding": {"shard_ops": sharding},
}

if __name__ == "__main__":
    run_state_test_generators("custody_sharding", ALL_MODS, presets=("minimal",))
