"""Fork-choice vector generator (scripted store scenarios, steps.yaml).

Reference parity: tests/generators/fork_choice/main.py.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import fork_choice, merge_fork_choice

_HANDLERS = {
    "get_head": (fork_choice, "genesis_head"),
    "on_block": (fork_choice, "on_block"),
    "ex_ante": (fork_choice, "proposer_boost"),
    "on_attestation": (fork_choice, "on_attestation"),
    "chain": (fork_choice, "chain"),
}
ALL_MODS = {
    "phase0": _HANDLERS,
    "altair": _HANDLERS,
    # the merge-transition matrix only exists at the bellatrix fork
    "bellatrix": {**_HANDLERS, "on_merge_block": merge_fork_choice},
}

if __name__ == "__main__":
    run_state_test_generators("fork_choice", ALL_MODS, presets=("minimal",))
