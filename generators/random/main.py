"""Random-scenario vector generator (runs the CODEGEN'd test module).

Reference parity: tests/generators/random/main.py — replays the generated
random test matrix (see generate.py in this directory) as sanity-blocks
vectors.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import random_gen

ALL_MODS = {
    "phase0": {"random": random_gen},
    "altair": {"random": random_gen},
    "bellatrix": {"random": random_gen},
}

if __name__ == "__main__":
    run_state_test_generators("random", ALL_MODS, presets=("minimal",))
