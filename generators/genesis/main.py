"""Genesis vector generator (initialization + validity).

Reference parity: tests/generators/genesis/main.py.
Usage: python main.py -o <output_dir>
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))  # repo root

from consensus_specs_tpu.gen import run_state_test_generators
from consensus_specs_tpu.spec_tests import genesis

ALL_MODS = {
    "phase0": {
        "initialization": (genesis, "initialize_"),
        "validity": (genesis, "validity_"),
    },
    # altair/bellatrix genesis overrides: sync committees at genesis;
    # bellatrix adds the caller-selected merge status
    "altair": {
        "initialization": (genesis, "initialize_"),
    },
    "bellatrix": {
        "initialization": (genesis, "initialize_"),
    },
}

if __name__ == "__main__":
    run_state_test_generators("genesis", ALL_MODS, presets=("minimal",))
