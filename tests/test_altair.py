"""Altair: participation flags, sync committees, fork upgrade, light client.

Reference parity targets: test/altair/{block_processing,epoch_processing,
unittests/test_sync_protocol.py,transition}.
"""
import pytest

from consensus_specs_tpu.compiler import get_spec
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.testlib.attestations import next_epoch_with_attestations
from consensus_specs_tpu.testlib.block import apply_empty_block
from consensus_specs_tpu.testlib.genesis import create_valid_beacon_state
from consensus_specs_tpu.testlib.state import next_epoch, next_slots
from consensus_specs_tpu.testlib.sync_committee import build_sync_aggregate, get_committee_indices


@pytest.fixture(scope="module")
def spec():
    return get_spec("altair", "minimal")


@pytest.fixture(autouse=True)
def disable_bls():
    bls.bls_active = False
    yield
    bls.bls_active = True


@pytest.fixture()
def state(spec):
    return create_valid_beacon_state(spec, 64)


def test_altair_genesis_has_sync_committees(spec, state):
    assert len(state.current_sync_committee.pubkeys) == spec.SYNC_COMMITTEE_SIZE
    assert len(state.inactivity_scores) == 64
    assert len(state.current_epoch_participation) == 64


def test_empty_block_transition(spec, state):
    apply_empty_block(spec, state)
    assert state.slot == 1


def test_attestations_set_participation_flags(spec, state):
    next_epoch(spec, state)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=False)
    flagged = sum(1 for f in state.previous_epoch_participation if int(f) != 0)
    assert flagged > 0


def test_altair_finality(spec, state):
    next_epoch(spec, state)
    for _ in range(4):
        next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=False)
    assert state.finalized_checkpoint.epoch >= 2


def test_sync_committee_rotation(spec, state):
    old_next = state.next_sync_committee.copy()
    # Advance to the end of the sync committee period
    target_epoch = spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    while spec.get_current_epoch(state) < target_epoch:
        next_epoch(spec, state)
    assert state.current_sync_committee == old_next


def test_sync_aggregate_rewards(spec, state):
    next_slots(spec, state, 1)
    committee_indices = get_committee_indices(spec, state)
    balances_before = {int(i): int(state.balances[i]) for i in set(committee_indices)}
    aggregate = build_sync_aggregate(spec, state)
    spec.process_sync_aggregate(state, aggregate)
    # Full participation: every committee member earns a reward
    improved = sum(
        1 for i in set(committee_indices) if int(state.balances[i]) > balances_before[int(i)])
    assert improved == len(set(committee_indices))


def test_sync_aggregate_penalizes_absent(spec, state):
    next_slots(spec, state, 1)
    committee_indices = get_committee_indices(spec, state)
    proposer = spec.get_beacon_proposer_index(state)
    # Pick a member that is not the proposer (sampling is with replacement, so
    # mark ALL of its seats absent and assert the exact penalty).
    absent_member = next(ci for ci in committee_indices if ci != proposer)
    absent_seats = [i for i, ci in enumerate(committee_indices) if ci == absent_member]
    participation = [committee_indices[i] != absent_member
                    for i in range(int(spec.SYNC_COMMITTEE_SIZE))]
    balance_before = int(state.balances[absent_member])

    aggregate = build_sync_aggregate(spec, state, participation)
    spec.process_sync_aggregate(state, aggregate)

    total_active_increments = spec.get_total_active_balance(state) // spec.EFFECTIVE_BALANCE_INCREMENT
    total_base_rewards = spec.get_base_reward_per_increment(state) * total_active_increments
    max_participant_rewards = (total_base_rewards * spec.SYNC_REWARD_WEIGHT
                               // spec.WEIGHT_DENOMINATOR // spec.SLOTS_PER_EPOCH)
    participant_reward = int(max_participant_rewards // spec.SYNC_COMMITTEE_SIZE)
    expected = balance_before - participant_reward * len(absent_seats)
    assert int(state.balances[absent_member]) == expected


def test_inactivity_scores_accrue_for_idle(spec, state):
    # No attestations for several epochs during a leak
    for _ in range(7):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    assert all(int(s) > 0 for s in state.inactivity_scores)


def test_upgrade_to_altair(spec):
    phase0_spec = get_spec("phase0", "minimal")
    pre = create_valid_beacon_state(phase0_spec, 64)
    next_epoch(phase0_spec, pre)
    post = spec.upgrade_to_altair(pre)
    assert post.fork.current_version == spec.config.ALTAIR_FORK_VERSION
    assert post.fork.previous_version == pre.fork.current_version
    assert len(post.inactivity_scores) == 64
    assert len(post.current_sync_committee.pubkeys) == spec.SYNC_COMMITTEE_SIZE
    assert spec.hash_tree_root(post.validators) == phase0_spec.hash_tree_root(pre.validators)
    # The upgraded state continues to transition
    apply_empty_block(spec, post)
    assert post.slot == pre.slot + 1


def test_light_client_update_with_real_proof(spec, state):
    """The v1.1.8 store-based flow against a real state proof built by the
    SSZ proof machinery (signature check stubbed; branch checks are real)."""
    next_slots(spec, state, 1)
    from consensus_specs_tpu.ssz import build_proof

    store = spec.LightClientStore(
        finalized_header=spec.BeaconBlockHeader(),
        current_sync_committee=state.current_sync_committee,
        next_sync_committee=state.next_sync_committee,
        optimistic_header=spec.BeaconBlockHeader(),
    )

    # A header committing to the current state
    attested_header = spec.BeaconBlockHeader(
        slot=state.slot,
        proposer_index=spec.get_beacon_proposer_index(state),
        parent_root=spec.hash_tree_root(state.latest_block_header),
        state_root=spec.hash_tree_root(state),
        body_root=b"\x00" * 32,
    )
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        next_sync_committee=state.next_sync_committee,
        next_sync_committee_branch=[spec.Bytes32() for _ in range(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX))],
        finalized_header=spec.BeaconBlockHeader(),
        finality_branch=[spec.Bytes32() for _ in range(spec.floorlog2(spec.FINALIZED_ROOT_INDEX))],
        sync_committee_aggregate=spec.SyncAggregate(
            sync_committee_bits=[True] * int(spec.SYNC_COMMITTEE_SIZE),
            sync_committee_signature=b"\x11" * 96,
        ),
        fork_version=spec.config.GENESIS_FORK_VERSION,
    )
    current_slot = state.slot
    spec.validate_light_client_update(store, update, current_slot, state.genesis_validators_root)

    # process: supermajority but no finality proof -> optimistic header only
    spec.process_light_client_update(store, update, current_slot, state.genesis_validators_root)
    assert store.optimistic_header == attested_header
    assert store.finalized_header == spec.BeaconBlockHeader()
    assert store.best_valid_update == update

    # Next-period update requires a REAL merkle branch for next_sync_committee
    period_slots = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH)
    attested_next = attested_header.copy()
    attested_next.slot = spec.Slot(period_slots + 1)
    update_next = update.copy()
    update_next.attested_header = attested_next
    update_next.next_sync_committee_branch = build_proof(state, spec.NEXT_SYNC_COMMITTEE_INDEX)
    spec.validate_light_client_update(
        store, update_next, spec.Slot(period_slots + 1), state.genesis_validators_root)

    # Corrupt one branch node: must fail
    bad = update_next.copy()
    bad_branch = list(bad.next_sync_committee_branch)
    bad_branch[2] = spec.Bytes32(b"\x77" * 32)
    bad.next_sync_committee_branch = bad_branch
    with pytest.raises(AssertionError):
        spec.validate_light_client_update(
            store, bad, spec.Slot(period_slots + 1), state.genesis_validators_root)

    # Timeout forces the best valid update to apply
    spec.process_slot_for_light_client_store(
        store, spec.Slot(int(spec.UPDATE_TIMEOUT) + int(state.slot) + 1))
    assert store.finalized_header == attested_header


def test_sync_aggregate_real_bls(spec):
    bls.bls_active = True
    state = create_valid_beacon_state(spec, 64)
    next_slots(spec, state, 1)
    aggregate = build_sync_aggregate(spec, state)
    spec.process_sync_aggregate(state, aggregate)  # must not raise
    # Flipping one bit invalidates the signature
    bad_bits = list(aggregate.sync_committee_bits)
    bad_bits[0] = not bad_bits[0]
    bad = spec.SyncAggregate(
        sync_committee_bits=bad_bits,
        sync_committee_signature=aggregate.sync_committee_signature,
    )
    with pytest.raises(AssertionError):
        spec.process_sync_aggregate(state, bad)
