"""Unit coverage for the robustness package: fault-plan determinism, the
retry policy + classification, circuit-breaker transitions, and engine
checkpoint capture/restore with the integrity digest. The end-to-end chaos
convergence runs live in tests/test_chaos_epoch.py."""
import numpy as np
import pytest

from consensus_specs_tpu.robustness import breaker as rbreaker
from consensus_specs_tpu.robustness.breaker import CircuitBreaker
from consensus_specs_tpu.robustness.checkpoint import (
    CheckpointIntegrityError,
    EngineCheckpoint,
)
from consensus_specs_tpu.robustness.faults import (
    CorruptAuxError,
    FatalFault,
    FaultPlan,
    FaultSpec,
    TransientFault,
    corrupt_array,
    fire,
    mangle_bytes,
)
from consensus_specs_tpu.robustness.retry import (
    RetryPolicy,
    call_with_retry,
    is_device_failure,
    is_retryable,
)


# --- fault plans -------------------------------------------------------------


def test_fault_plan_at_calls_exact_schedule():
    plan = FaultPlan(seed=1, sites={
        "s": FaultSpec(kind="raise", at_calls=(2, 4), exc="transient"),
    })
    fired = []
    with plan.active():
        for i in range(1, 6):
            try:
                fire("s")
            except TransientFault:
                fired.append(i)
    assert fired == [2, 4]
    assert plan.calls("s") == 5
    assert plan.fires("s") == 2
    assert [e.call_index for e in plan.events] == [2, 4]


def test_fault_plan_rate_is_seed_deterministic():
    def run(seed):
        plan = FaultPlan(seed=seed, sites={
            "s": FaultSpec(kind="raise", rate=0.4, exc="transient"),
        })
        fired = []
        with plan.active():
            for i in range(1, 41):
                try:
                    fire("s")
                except TransientFault:
                    fired.append(i)
        return fired

    a, b, c = run(7), run(7), run(8)
    assert a == b  # same seed -> identical schedule
    assert a != c  # different seed -> (overwhelmingly) different schedule
    assert 0 < len(a) < 40


def test_fault_plan_site_streams_are_independent():
    """Extra traffic on one site must not shift another site's schedule —
    each site draws from its own (seed, site)-keyed stream."""
    def fired_on_b(calls_on_a):
        plan = FaultPlan(seed=3, sites={
            "a": FaultSpec(kind="raise", rate=0.5, exc="transient"),
            "b": FaultSpec(kind="raise", rate=0.5, exc="transient"),
        })
        out = []
        with plan.active():
            for _ in range(calls_on_a):
                try:
                    fire("a")
                except TransientFault:
                    pass
            for i in range(1, 21):
                try:
                    fire("b")
                except TransientFault:
                    out.append(i)
        return out

    assert fired_on_b(0) == fired_on_b(50)


def test_fault_plan_max_fires_caps_without_shifting_draws():
    """max_fires suppresses fires past the cap but still consumes the RNG
    draw, so the uncapped and capped schedules agree on every index below
    the cap AND on which indices would have drawn true."""
    def run(cap):
        plan = FaultPlan(seed=5, sites={
            "s": FaultSpec(kind="raise", rate=0.5, max_fires=cap,
                           exc="transient"),
        })
        fired = []
        with plan.active():
            for i in range(1, 31):
                try:
                    fire("s")
                except TransientFault:
                    fired.append(i)
        return fired

    unbounded = run(None)
    capped = run(2)
    assert capped == unbounded[:2]


def test_corrupt_and_mangle_kinds():
    plan = FaultPlan(seed=9, sites={
        "c": FaultSpec(kind="corrupt", at_calls=(1, 2), corruption="nan"),
        "t": FaultSpec(kind="corrupt", at_calls=(1,), corruption="truncate"),
        "m": FaultSpec(kind="mangle", at_calls=(1, 2), corruption="truncate"),
    })
    with plan.active():
        arr = np.arange(6, dtype=np.uint64)
        nan = corrupt_array("c", arr)
        assert nan.dtype == np.float64 and nan.shape == arr.shape
        assert np.isnan(nan).all()
        truncated = corrupt_array("t", np.arange(4))  # "t" call 1: truncate
        assert truncated.shape == (3,)
        nan2 = corrupt_array("c", np.arange(4))  # "c" call 2: nan again
        assert nan2.shape == (4,) and nan2.dtype == np.float64
        half = mangle_bytes("m", b"0123456789")
        assert half == b"01234"
        assert mangle_bytes("m", b"ok") != b"ok"  # second at_call
        # a site past its schedule passes data through untouched
        assert mangle_bytes("m", b"ok") == b"ok"
        assert corrupt_array("t", np.arange(4)).shape == (4,)


def test_uninstalled_plan_is_a_noop():
    fire("anything")  # no plan installed: must not raise
    a = np.arange(3)
    assert corrupt_array("anything", a) is a
    assert mangle_bytes("anything", b"x") == b"x"


# --- classification + retry --------------------------------------------------


def test_classification():
    class FakeXla(Exception):
        pass

    FakeXla.__name__ = "XlaRuntimeError"
    assert is_retryable(TransientFault("x"))
    assert is_retryable(CorruptAuxError("x"))
    assert is_retryable(TimeoutError())
    assert is_retryable(ConnectionResetError())
    assert is_retryable(FakeXla("device gone"))
    assert not is_retryable(FatalFault("x"))
    assert not is_retryable(AssertionError("host bug"))
    assert not is_retryable(ValueError("host bug"))
    # degradation eligibility: retryables plus injected fatals
    assert is_device_failure(FatalFault("x"))
    assert is_device_failure(FakeXla("x"))
    assert not is_device_failure(ValueError("x"))


def test_retry_policy_delay_growth_and_ceiling():
    from random import Random

    p = RetryPolicy(max_attempts=0, base_delay=0.1, backoff=2.0,
                    max_delay=0.35, jitter=0.0)
    rng = Random(0)
    delays = [p.delay(a, rng) for a in (1, 2, 3, 4)]
    assert delays == [0.1, 0.2, 0.35, 0.35]  # doubles, then clamps
    jittered = RetryPolicy(base_delay=0.1, jitter=0.5).delay(1, Random(0))
    assert 0.1 <= jittered <= 0.15


def test_call_with_retry_absorbs_then_succeeds():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientFault("not yet")
        return "done"

    retries = []
    out = call_with_retry(
        flaky,
        RetryPolicy(max_attempts=4, base_delay=0.01, backoff=2.0,
                    max_delay=1.0, jitter=0.0),
        sleep=slept.append,
        on_retry=lambda attempt, exc: retries.append(attempt))
    assert out == "done" and calls["n"] == 3
    assert slept == [0.01, 0.02]
    assert retries == [1, 2]


def test_call_with_retry_raises_fatal_immediately_and_exhausts_budget():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise FatalFault("hard crash")

    with pytest.raises(FatalFault):
        call_with_retry(fatal, RetryPolicy(max_attempts=5, base_delay=0.0))
    assert calls["n"] == 1  # fatal: no second attempt

    calls["n"] = 0

    def always_transient():
        calls["n"] += 1
        raise TransientFault("still down")

    with pytest.raises(TransientFault):
        call_with_retry(always_transient,
                        RetryPolicy(max_attempts=3, base_delay=0.0,
                                    max_delay=0.0))
    assert calls["n"] == 3  # full budget consumed, final error re-raised


# --- circuit breaker ---------------------------------------------------------


def test_breaker_opens_probes_and_rearms():
    brk = CircuitBreaker(failure_threshold=2, name="t")
    assert brk.on_attempt() == "closed"
    brk.record_failure()
    assert brk.state == rbreaker.CLOSED  # below threshold: still closed
    assert brk.on_attempt() == "closed"
    brk.record_failure()
    assert brk.state == rbreaker.OPEN
    # open -> the next attempt is a half-open probe
    assert brk.on_attempt() == "probe"
    brk.record_failure()  # probe failed: re-open immediately
    assert brk.state == rbreaker.OPEN
    assert brk.on_attempt() == "probe"
    brk.record_success()  # probe succeeded: re-armed
    assert brk.state == rbreaker.CLOSED
    assert brk.consecutive_failures == 0
    assert brk.degraded_epochs == 3
    assert [e["event"] for e in brk.events] == [
        "degraded_to_python", "degraded_to_python", "opened",
        "half_open_probe", "degraded_to_python", "opened",
        "half_open_probe", "rearmed",
    ]
    brk.reset()
    assert brk.state == rbreaker.CLOSED and brk.events == []


def test_breaker_event_ring_is_bounded_with_drop_counter():
    """Regression for the unbounded event log: a week-long degraded soak
    must not grow `events` past the ring size, dropped entries are counted
    (on the ring AND in the registry), and the full per-event history
    survives in counter form after the ring wraps."""
    from consensus_specs_tpu.obs import metrics as obs_metrics

    brk = CircuitBreaker(failure_threshold=2, name="ring-test",
                         event_ring_size=8)
    base = obs_metrics.REGISTRY.counter_value(
        "breaker_events_total", breaker="ring-test", event="degraded_to_python")
    for _ in range(50):
        brk.record_failure()  # every one logs degraded_to_python
    assert len(brk.events) == 8
    assert brk.events.dropped == 50 + 1 - 8  # +1: the "opened" transition
    assert obs_metrics.REGISTRY.counter_value(
        "breaker_events_dropped_total", breaker="ring-test") == brk.events.dropped
    # counters kept the whole history the ring forgot
    assert obs_metrics.REGISTRY.counter_value(
        "breaker_events_total", breaker="ring-test",
        event="degraded_to_python") - base == 50
    # the ring still behaves like the list the older tests compare against
    assert brk.events[-1]["event"] == "degraded_to_python"
    brk.reset()
    assert brk.events == [] and brk.events.dropped == 0


# --- checkpoints -------------------------------------------------------------


@pytest.fixture(scope="module")
def spec():
    from consensus_specs_tpu.compiler import get_spec

    return get_spec("altair", "minimal")


def _engine(spec, seed=31):
    from consensus_specs_tpu.engine.resident import ResidentEpochEngine
    from consensus_specs_tpu.testlib.state import prepared_epoch_state

    st = prepared_epoch_state(spec, start_epoch=6, seed=seed)
    return ResidentEpochEngine(spec, st)


def test_checkpoint_roundtrip_and_tamper(spec, tmp_path):
    from consensus_specs_tpu.crypto import bls

    was = bls.bls_active
    bls.bls_active = False
    try:
        eng = _engine(spec)
        eng.step_epoch()
        eng.step_epoch()
        ck = EngineCheckpoint.capture(eng)
        assert ck.digest and ck.meta["format"] == "engine-checkpoint-v1"
        ck.verify()

        # disk roundtrip preserves the digest and every array bit
        path = tmp_path / "engine.ckpt.npz"
        ck.save(path)
        loaded = EngineCheckpoint.load(path)
        assert loaded.digest == ck.digest
        assert loaded.compute_digest() == ck.compute_digest()

        # restore continues to the same root as the original engine
        eng2 = loaded.restore(spec)
        eng.step_epoch()
        eng2.step_epoch()
        assert eng2.state_root() == eng.state_root()

        # fork mismatch is refused
        from consensus_specs_tpu.compiler import get_spec

        with pytest.raises(CheckpointIntegrityError):
            loaded.restore(get_spec("bellatrix", "minimal"))

        # tampering with an array breaks the digest loudly
        ck.dev["balances"] = ck.dev["balances"] + 1
        with pytest.raises(CheckpointIntegrityError):
            ck.verify()
        loaded.digest = "0" * 64
        with pytest.raises(CheckpointIntegrityError):
            loaded.restore(spec)
    finally:
        bls.bls_active = was


# --- import hygiene ----------------------------------------------------------


def test_robustness_importable_without_jax():
    """tpulint enforces this statically; this is the runtime twin — the
    whole package (and its consumers' import of it) must work in a process
    where jax cannot be imported at all."""
    import subprocess
    import sys

    code = """
import sys


class _Block:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError(f"poisoned for test: {name}")
        return None


sys.meta_path.insert(0, _Block())

from consensus_specs_tpu import robustness
from consensus_specs_tpu.robustness.faults import FaultPlan, FaultSpec, fire
from consensus_specs_tpu.robustness.retry import call_with_retry, RetryPolicy
from consensus_specs_tpu.robustness.breaker import CircuitBreaker
from consensus_specs_tpu.robustness.checkpoint import EngineCheckpoint

# the "xla" exc kind falls back to TransientFault when jax is absent
plan = FaultPlan(seed=1, sites={"s": FaultSpec(kind="raise", at_calls=(1,),
                                               exc="xla")})
with plan.active():
    try:
        fire("s")
    except robustness.TransientFault:
        pass
    else:
        raise SystemExit("expected the no-jax fallback fault")
print("ROBUSTNESS-NO-JAX-OK")
"""
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300)
    assert res.returncode == 0, res.stderr
    assert "ROBUSTNESS-NO-JAX-OK" in res.stdout


# --- deadline-aware retry (the front-door admission budget) ------------------


def test_call_with_retry_deadline_stops_doomed_backoff():
    """Once the next backoff sleep would land past the deadline, the LAST
    error surfaces immediately instead of burning the budget on sleeps
    that cannot help."""
    from consensus_specs_tpu.obs import metrics as obs_metrics

    t = [0.0]
    slept = []
    calls = {"n": 0}

    def sleep(d):
        slept.append(d)
        t[0] += d

    def always_down():
        calls["n"] += 1
        raise TransientFault("device away")

    base = obs_metrics.REGISTRY.counter_value(
        "retries_deadline_exhausted_total", error="TransientFault")
    with pytest.raises(TransientFault):
        call_with_retry(
            always_down,
            RetryPolicy(max_attempts=10, base_delay=1.0, backoff=2.0,
                        max_delay=60.0, jitter=0.0),
            sleep=sleep, deadline=4.0, clock=lambda: t[0])
    # delays 1s, 2s are affordable (land at t=1, t=3); the third delay
    # (4s) would land at t=7 >= deadline 4 -> raise after 3 attempts
    assert slept == [1.0, 2.0] and calls["n"] == 3
    assert obs_metrics.REGISTRY.counter_value(
        "retries_deadline_exhausted_total",
        error="TransientFault") - base == 1


def test_call_with_retry_deadline_leaves_jitter_stream_untouched():
    """The backoff delay is computed BEFORE the deadline check, so adding
    a (generous) deadline must not shift a single jittered sleep — the
    chaos-replay bit-identity contract."""

    def run(deadline):
        calls = {"n": 0}
        slept = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise TransientFault("not yet")
            return "ok"

        out = call_with_retry(
            flaky,
            RetryPolicy(max_attempts=5, base_delay=0.1, backoff=2.0,
                        max_delay=1.0, jitter=0.5, seed=7),
            sleep=slept.append, deadline=deadline, clock=lambda: 0.0)
        assert out == "ok"
        return slept

    no_deadline = run(None)
    with_deadline = run(1e9)
    assert no_deadline == with_deadline and len(no_deadline) == 3


def test_call_with_retry_deadline_allows_fitting_attempts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientFault("x")
        return "done"

    assert call_with_retry(
        flaky, RetryPolicy(max_attempts=5, base_delay=0.0, max_delay=0.0,
                           jitter=0.0),
        sleep=lambda d: None, deadline=10.0, clock=lambda: 0.0) == "done"
    assert calls["n"] == 3


# --- breaker: the half-open probe is single under concurrency ----------------


def test_breaker_half_open_single_probe_under_concurrency():
    """Four threads race on_attempt() at the open->half_open boundary:
    every one gets probe mode (half-open means single-ATTEMPT, not
    single-caller), but the transition — and its half_open_probe event —
    happens exactly once per open, every round."""
    import threading

    from consensus_specs_tpu.obs import metrics as obs_metrics

    brk = CircuitBreaker(failure_threshold=1, name="probe-race")
    base = obs_metrics.REGISTRY.counter_value(
        "breaker_events_total", breaker="probe-race",
        event="half_open_probe")
    rounds = 20
    for _ in range(rounds):
        brk.record_failure()
        assert brk.state == rbreaker.OPEN
        barrier = threading.Barrier(4)
        modes = []
        lock = threading.Lock()

        def attempt():
            barrier.wait()  # maximize the race on the transition
            mode = brk.on_attempt()
            with lock:
                modes.append(mode)

        threads = [threading.Thread(target=attempt) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert modes == ["probe"] * 4
        probes = [e for e in brk.events if e["event"] == "half_open_probe"]
        assert len(probes) == 1  # the regression bar: never 0, never 2+
        brk.record_success()
        brk.events.clear()
    assert obs_metrics.REGISTRY.counter_value(
        "breaker_events_total", breaker="probe-race",
        event="half_open_probe") - base == rounds
