"""tpulint v3 cross-validation: static concurrency rules vs a live race.

The contract mirrors the recompile-risk precedent: the fixture corpus
under tests/fixtures/tpulint/concurrency/ must match its inline
expectations EXACTLY (both directions), the planted race in
firehose/planted.py must be flagged by guarded-field inference AND
reproduced — deterministically, via the fixture's `gate` interleaving
seam — by the barrier-synchronized stress harness below, and the
LockedStatsPlane control (same shape, one lock) must be BOTH statically
clean and dynamically loss-free under a seeded hammer loop. Finally the
shipped production planes themselves must come back clean: every real
finding the v3 bootstrap surfaced was fixed in-tree, not baselined.
"""
import importlib.util
import random
import sys
import threading
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "tpulint" / "concurrency"

sys.path.insert(0, str(REPO))

from consensus_specs_tpu.analysis import analyze_paths  # noqa: E402
from consensus_specs_tpu.analysis.runner import rule_by_id  # noqa: E402

CONCURRENCY_RULES = ("lock-order", "guarded-field", "thread-escape")


def _rules():
    return tuple(rule_by_id(r) for r in CONCURRENCY_RULES)


def _expected_annotations(path: Path) -> set:
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        if "tpulint-expect:" not in line:
            continue
        for rule in line.split("tpulint-expect:")[1].split("--")[0].split(","):
            out.add((path.name, i, rule.strip()))
    return out


# --- static: the corpus matches its annotations exactly ----------------------

def test_concurrency_fixture_matches_annotations():
    expected = set()
    for f in sorted(FIXTURES.rglob("*.py")):
        if "__pycache__" not in f.parts:
            expected |= _expected_annotations(f)
    result = analyze_paths([FIXTURES])
    got = {(Path(f.path).name, f.line, f.rule) for f in result.findings}
    assert got == expected, (
        f"missed={sorted(expected - got)} unexpected={sorted(got - expected)}")
    assert {r for _, _, r in expected} == set(CONCURRENCY_RULES)


def test_planted_race_flagged_statically():
    """Guarded-field must flag every unguarded `_hits`/`_drained` access in
    RacyStatsPlane, while the LockedStatsPlane control — the same shape plus
    one lock — contributes nothing."""
    result = analyze_paths([FIXTURES / "firehose" / "planted.py"], _rules())
    lines = (FIXTURES / "firehose" / "planted.py").read_text().splitlines()
    control_start = next(i for i, l in enumerate(lines, 1)
                         if "class LockedStatsPlane" in l)
    racy = [f for f in result.findings if f.rule == "guarded-field"]
    assert len(racy) == 5  # ingest read+write, drain scan+pop, drained +=
    assert all("RacyStatsPlane" in f.message for f in racy)
    assert all(f.line < control_start for f in result.findings)


def test_shipped_thread_shapes_stay_clean():
    """The two production thread shapes — double-buffered flusher hand-off
    and subscriber callbacks delivered post-lock — are negative cases; the
    rules must not regress into flagging them."""
    for name in ("flusher_ok.py", "callback_ok.py"):
        result = analyze_paths([FIXTURES / "firehose" / name], _rules())
        assert result.findings == [], [f.format() for f in result.findings]


def test_lock_order_cycle_and_self_deadlock():
    result = analyze_paths([FIXTURES / "sched"], _rules())
    by_file: dict = {}
    for f in result.findings:
        assert f.rule == "lock-order"
        by_file.setdefault(Path(f.path).name, []).append(f)
    # the same-module inversion: both halves of the cycle anchored
    assert len(by_file["order_pos.py"]) == 2
    # the cross-module chain: the cycle only exists through the callgraph
    assert len(by_file["chain_head.py"]) == 2
    assert all("cycle" in f.message for f in by_file["chain_head.py"])
    # non-reentrant self-acquisition is its own finding; the RLock twin is not
    reentry = by_file["reentry.py"]
    assert len(reentry) == 1 and "deadlocks" in reentry[0].message
    assert "NonReentrant" in reentry[0].message


def test_thread_escape_positive_and_negatives():
    pos = analyze_paths([FIXTURES / "forkchoice" / "escape_pos.py"], _rules())
    assert [f.rule for f in pos.findings] == ["thread-escape"]
    assert "MutableTally" in pos.findings[0].message
    neg = analyze_paths([FIXTURES / "forkchoice" / "escape_ok.py"], _rules())
    assert neg.findings == [], [f.format() for f in neg.findings]


def test_suppression_forms_absorbed():
    """The disable pragmas for the new rule ids must absorb (and count) the
    seeded findings, and must not go stale (they were used this run)."""
    result = analyze_paths([FIXTURES / "firehose" / "suppressed_ok.py"])
    assert result.findings == [], [f.format() for f in result.findings]
    assert result.suppressed == 2


def test_production_planes_clean():
    """The acceptance gate: zero unfixed concurrency findings in the shipped
    package — the StoreMirror RLock, the breaker lock, the registry read
    locks, and the firehose post-lock capture are all load-bearing here."""
    result = analyze_paths([REPO / "consensus_specs_tpu"], _rules())
    assert result.findings == [], [f.format() for f in result.findings]


# --- dynamic: the planted race loses real updates ----------------------------

def _load_planted():
    spec = importlib.util.spec_from_file_location(
        "_tpulint_planted_fixture", FIXTURES / "firehose" / "planted.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _join(*threads):
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "stress-harness thread wedged"


def test_planted_race_reproduced_deterministically():
    """Barrier-synchronized hammer loop: each round parks BOTH ingest
    threads inside the read→write-back window via the fixture's `gate`
    seam, so both read the same count and one increment is lost — every
    round, deterministically, not probabilistically. 2*ROUNDS ingests
    land as exactly ROUNDS."""
    mod = _load_planted()
    plane = mod.RacyStatsPlane()
    rendezvous = threading.Barrier(2)
    plane.gate = lambda: rendezvous.wait(timeout=10.0)
    rounds = 25
    for _ in range(rounds):
        t1 = threading.Thread(target=plane.ingest, args=("k",))
        t2 = threading.Thread(target=plane.ingest, args=("k",))
        t1.start()
        t2.start()
        _join(t1, t2)
    assert plane._hits["k"] == rounds  # half the updates lost to the race


def test_locked_control_conserves_updates():
    """The same hammer against LockedStatsPlane — with its flusher thread
    live and draining concurrently — must conserve every update: the lock
    is the only difference between this passing and the racy twin losing
    half its increments. Seeded keys keep the interleaving pressure
    reproducible run to run."""
    mod = _load_planted()
    plane = mod.LockedStatsPlane()
    plane.start()
    rng = random.Random(0xC0FFEE)
    keys = [f"k{rng.randrange(8)}" for _ in range(200)]
    n_threads = 4
    start_gate = threading.Barrier(n_threads)

    def hammer():
        start_gate.wait(timeout=10.0)
        for key in keys:
            plane.ingest(key)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    _join(*threads)
    plane.stop()
    plane.drain()  # fold any remainder into the drained total
    assert plane._drained == n_threads * len(keys)
