"""Native C++ hashtree engine vs hashlib oracle (differential)."""
import hashlib
import random

import pytest

from consensus_specs_tpu.native import hashtree

rng = random.Random(0x5A)


def test_native_available():
    # the toolchain is baked into the image; absence means a build break
    assert hashtree.available()


@pytest.mark.parametrize("n", [0, 1, 3, 55, 56, 63, 64, 65, 127, 128, 1000])
def test_sha256_matches_hashlib(n):
    data = bytes(rng.randrange(256) for _ in range(n))
    assert hashtree.sha256(data) == hashlib.sha256(data).digest()


@pytest.mark.parametrize("pairs", [1, 2, 7, 64])
def test_hash_pairs_matches_hashlib(pairs):
    level = bytes(rng.randrange(256) for _ in range(64 * pairs))
    got = hashtree.hash_pairs(level)
    want = b"".join(
        hashlib.sha256(level[64 * i : 64 * (i + 1)]).digest() for i in range(pairs)
    )
    assert got == want


@pytest.mark.parametrize("n,depth", [(0, 5), (1, 5), (2, 5), (5, 5), (32, 5), (9, 10)])
def test_merkle_root_matches_python(n, depth):
    leaves = bytes(rng.randrange(256) for _ in range(32 * n))
    assert hashtree.merkle_root(leaves, depth) == hashtree._py_merkle_root(leaves, n, depth)


def test_merkle_root_matches_ssz_merkleize():
    """Cross-check against the SSZ engine's chunk merkleization."""
    from consensus_specs_tpu.ssz.merkle import merkleize_chunks

    chunks = [bytes([i]) * 32 for i in range(7)]
    got = hashtree.merkle_root(b"".join(chunks), 3)
    assert got == merkleize_chunks(chunks, limit=8)


def test_merkle_root_rejects_overflow():
    with pytest.raises(ValueError):
        hashtree.merkle_root(b"\x00" * 32 * 3, 1)


def test_empty_tree_root_is_zero_ladder():
    import hashlib as h

    z = b"\x00" * 32
    for _ in range(4):
        z = h.sha256(z + z).digest()
    assert hashtree.merkle_root(b"", 4) == z
