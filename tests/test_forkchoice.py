"""Device-resident fork choice: the batched LMD-GHOST head kernel pinned
bit-identical against the spec-shaped host oracle and the compiled spec's
`get_head`, the "forkchoice" sched lane's retry/breaker/degrade seam, the
ForkChoiceService firehose subscription, and the three-lane scenario
replay with per-checkpoint device-head assertions.

Layers under test:
  * ops/forkchoice_jax.py + engine/fork_choice.ghost_head_batch — kernel
  * forkchoice/ — StoreMirror, reference.host_head, ForkChoiceService
  * sched/classes.py ForkChoiceWorkClass kind="head" — batching seam
  * firehose/pipeline.subscribe_verified — verified-batch consumer seam
  * scenarios/lanes.py head_check + scenarios/diff.diff_checkpoints
  * testlib/fork_choice.py pure helpers (the extracted spec semantics)
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.engine.fork_choice import ghost_head_batch
from consensus_specs_tpu.forkchoice import (
    ForkChoiceService,
    StoreMirror,
    host_head,
)
from consensus_specs_tpu.obs import metrics as obs_metrics
from consensus_specs_tpu.robustness.faults import FaultPlan, FaultSpec
from consensus_specs_tpu.robustness.retry import RetryPolicy
from consensus_specs_tpu.scenarios import (
    assert_converged,
    build_history,
    build_script,
    diff_checkpoints,
    engine_lane,
    firehose_lane,
    oracle_lane,
)
from consensus_specs_tpu.sched import ForkChoiceWorkClass, Request, Scheduler
from consensus_specs_tpu.testlib.fork_choice import (
    ancestor_at_slot,
    latest_message_updates,
)

FAST_RETRY = RetryPolicy(max_attempts=4, base_delay=0.0, backoff=1.0,
                         max_delay=0.0, jitter=0.0)
SEED, EPOCHS = 1, 4
GWEI_32 = 32_000_000_000


@pytest.fixture(scope="module")
def history():
    return build_history(build_script(SEED, epochs=EPOCHS))


# --- helpers -----------------------------------------------------------------


def _root(rng) -> bytes:
    return bytes(rng.randrange(256) for _ in range(32))


def _rand_mirror(seed, nb=16, nv=48) -> StoreMirror:
    """Seeded contested tree in a StoreMirror: random branching, mixed
    per-block FFG checkpoints, partial vote participation, sometimes a
    proposer boost, sometimes a non-genesis store justification."""
    rng = random.Random(seed)
    m = StoreMirror()
    anchor = _root(rng)
    anchor_ck = (0, anchor)
    m.add_block(anchor, anchor, 0, justified=anchor_ck, finalized=anchor_ck)
    roots, slots = [anchor], {anchor: 0}
    for _ in range(nb - 1):
        parent = roots[rng.randrange(len(roots))]
        root = _root(rng)
        slot = slots[parent] + rng.randrange(1, 3)
        jc = anchor_ck if rng.random() < 0.8 else (1, roots[0])
        fc = anchor_ck if rng.random() < 0.9 else (1, anchor)
        m.add_block(root, parent, slot, justified=jc, finalized=fc)
        roots.append(root)
        slots[root] = slot
    m.set_registry(np.full(nv, GWEI_32, dtype=np.int64))
    for v in range(nv):
        if rng.random() < 0.7:
            m.set_vote(v, roots[rng.randrange(len(roots))])
    if rng.random() < 0.5:
        m.set_checkpoints((0, anchor), (0, anchor))
    else:
        m.set_checkpoints((1, anchor), (0, anchor))
    if rng.random() < 0.5:
        m.set_boost(roots[rng.randrange(len(roots))], 2 * GWEI_32)
    return m


def _fresh_sched(**kw):
    kw.setdefault("retry_policy", FAST_RETRY)
    return Scheduler(classes=[ForkChoiceWorkClass()], **kw)


def _heads_via_sched(snaps, **kw):
    sch = _fresh_sched(**kw)
    handles = [sch.submit(Request(work_class="forkchoice", kind="head",
                                  payload=(s,))) for s in snaps]
    sch.drain()
    return [h.result() for h in handles], sch


# --- kernel vs host oracle ---------------------------------------------------


def test_kernel_matches_host_oracle_random_trees():
    """Batched device heads == spec-shaped host oracle across mixed
    (blocks, validators) buckets in one launch set."""
    snaps = []
    for seed in range(48):
        rng = random.Random(1000 + seed)
        snaps.append(_rand_mirror(seed, nb=rng.randrange(1, 34),
                                  nv=rng.randrange(1, 90)).snapshot())
    device = ghost_head_batch(snaps)
    for i, snap in enumerate(snaps):
        assert int(device[i]) == host_head(snap), f"tree {i}"


def _two_fork_mirror(weights=(3, 2), boost=None, tie=False):
    """anchor -> {a, b} with `weights` validators voting each side; fixed
    roots so tie-break assertions are deterministic."""
    m = StoreMirror()
    anchor = b"\x10" * 32
    a, b = b"\xaa" * 32, b"\x0b" * 32  # a > b bytes-wise
    ck = (0, anchor)
    m.add_block(anchor, anchor, 0, justified=ck, finalized=ck)
    m.add_block(a, anchor, 1, justified=ck, finalized=ck)
    m.add_block(b, anchor, 1, justified=ck, finalized=ck)
    nv = sum(weights)
    m.set_registry(np.full(max(nv, 1), GWEI_32, dtype=np.int64))
    v = 0
    for root, count in zip((a, b), weights):
        for _ in range(count):
            m.set_vote(v, root)
            v += 1
    m.set_checkpoints((0, anchor), (0, anchor))
    if boost is not None:
        m.set_boost(boost, 2 * GWEI_32)
    if tie:
        pass
    return m, anchor, a, b


def test_weighted_fork_boost_and_tiebreak_edges():
    # plain LMD majority
    m, _, a, b = _two_fork_mirror(weights=(3, 2))
    assert m.root_at(int(ghost_head_batch([m.snapshot()])[0])) == a
    # proposer boost flips the lighter side (1-vote gap < 2*GWEI_32 boost)
    m, _, a, b = _two_fork_mirror(weights=(3, 2), boost=b)
    assert m.root_at(int(ghost_head_batch([m.snapshot()])[0])) == b
    # exact tie: higher root bytes win (spec max(children, key=(w, root)))
    m, _, a, b = _two_fork_mirror(weights=(2, 2))
    assert a > b
    assert m.root_at(int(ghost_head_batch([m.snapshot()])[0])) == a
    # all-zero votes tie too
    m, _, a, b = _two_fork_mirror(weights=(0, 0))
    assert m.root_at(int(ghost_head_batch([m.snapshot()])[0])) == a
    for m, *_ in (_two_fork_mirror(weights=(3, 2)),
                  _two_fork_mirror(weights=(3, 2), boost=b),
                  _two_fork_mirror(weights=(2, 2))):
        snap = m.snapshot()
        assert int(ghost_head_batch([snap])[0]) == host_head(snap)


def test_ffg_filtering_prunes_disagreeing_leaves():
    """A heavier branch whose leaf states disagree with the store's
    justified checkpoint is filtered out (spec filter_block_tree); with
    no viable leaf at all the head stays the justified root."""
    m = StoreMirror()
    anchor = b"\x01" * 32
    good, bad = b"\x02" * 32, b"\x03" * 32
    just_ck = (1, anchor)
    m.add_block(anchor, anchor, 0, justified=just_ck, finalized=(0, anchor))
    # leaf agreeing with the store's justified view
    m.add_block(good, anchor, 1, justified=just_ck, finalized=(0, anchor))
    # heavier leaf with a stale justified checkpoint
    m.add_block(bad, anchor, 1, justified=(0, anchor), finalized=(0, anchor))
    m.set_registry(np.full(4, GWEI_32, dtype=np.int64))
    for v in range(4):
        m.set_vote(v, bad)
    m.set_checkpoints(just_ck, (0, anchor))
    snap = m.snapshot()
    assert m.root_at(host_head(snap)) == good
    assert int(ghost_head_batch([snap])[0]) == host_head(snap)
    # now make every leaf disagree: head falls back to the justified root
    m2 = StoreMirror()
    m2.add_block(anchor, anchor, 0, justified=just_ck, finalized=(0, anchor))
    m2.add_block(bad, anchor, 1, justified=(0, anchor), finalized=(0, anchor))
    m2.set_registry(np.full(2, GWEI_32, dtype=np.int64))
    m2.set_vote(0, bad)
    m2.set_checkpoints(just_ck, (0, anchor))
    snap2 = m2.snapshot()
    assert m2.root_at(host_head(snap2)) == anchor
    assert int(ghost_head_batch([snap2])[0]) == host_head(snap2)


# --- testlib pure helpers ----------------------------------------------------


class _Msg:
    def __init__(self, epoch):
        self.epoch = epoch


class _Blk:
    def __init__(self, slot, parent_root):
        self.slot = slot
        self.parent_root = parent_root


def test_latest_message_updates_filter():
    lm = {1: _Msg(3), 2: _Msg(5)}
    # unseen admitted, older/equal filtered, newer admitted
    assert latest_message_updates(lm, [0, 1, 2, 3], 4) == [0, 1, 3]
    assert latest_message_updates(lm, [1, 2], 3) == []
    assert latest_message_updates({}, [7], 0) == [7]


def test_ancestor_at_slot_walk():
    blocks = {"a": _Blk(0, "a"), "b": _Blk(2, "a"), "c": _Blk(5, "b")}
    assert ancestor_at_slot(blocks, "c", 5) == "c"
    assert ancestor_at_slot(blocks, "c", 4) == "b"
    assert ancestor_at_slot(blocks, "c", 2) == "b"
    assert ancestor_at_slot(blocks, "c", 1) == "a"
    # self-parented anchor terminates below its own slot
    assert ancestor_at_slot({"x": _Blk(9, "x")}, "x", 3) == "x"


# --- the sched lane ----------------------------------------------------------


def test_sched_forkchoice_device_degraded_agree():
    snaps = [_rand_mirror(s, nb=12 + s, nv=20 + s).snapshot()
             for s in range(5)]
    reqs = [Request(work_class="forkchoice", kind="head", payload=(s,))
            for s in snaps]
    cls = ForkChoiceWorkClass()
    oracle = [host_head(s) for s in snaps]
    assert [cls.to_result(r) for r in cls.execute(reqs)] == oracle
    assert [cls.to_result(r) for r in cls.execute_degraded(reqs)] == oracle
    heads, sch = _heads_via_sched(snaps)
    assert heads == oracle
    assert sch.breaker("forkchoice").state == "closed"


def test_forkchoice_compile_pinned_one_per_bucket():
    """One XLA compile per (blocks, validators) pow2 bucket, zero
    recompiles on replay, exactly one more on a new bucket."""
    from consensus_specs_tpu.obs.recompile import CompileTracker

    kernel = "_ghost_head_impl"
    tracker = CompileTracker(
        registry=obs_metrics.MetricsRegistry()).install()
    try:
        def run(seeds, nb, nv):
            snaps = [_rand_mirror(s, nb=nb, nv=nv).snapshot()
                     for s in seeds]
            heads = ghost_head_batch(snaps)
            for snap, head in zip(snaps, heads):
                assert int(head) == host_head(snap)

        # B=128 / V=128: out of reach of every other test in this file
        # (their trees stay under 64 blocks), so the pin is counted from
        # a cold bucket no matter the execution order.
        base = tracker.compiles(kernel)
        run(range(3), 70, 100)    # bucket (B=128, V=128), Q=4
        first = tracker.compiles(kernel) - base
        assert first == 1
        run(range(3, 6), 65, 90)  # same bucket, replay: zero recompiles
        assert tracker.compiles(kernel) - base == first
        run(range(3), 70, 150)    # new validator bucket (V=256): one more
        assert tracker.compiles(kernel) - base == first + 1
        assert tracker.distinct_shapes(kernel) == first + 1
    finally:
        tracker.uninstall()


def test_chaos_sched_forkchoice_converges_bit_identical():
    """Seeded raise + corrupt chaos at sched.dispatch: absorbed by retry
    from intact snapshots, heads bit-identical, breaker closed."""
    snaps = [_rand_mirror(100 + s, nb=10, nv=30).snapshot()
             for s in range(4)]
    oracle = [host_head(s) for s in snaps]
    heads, sch = _heads_via_sched(snaps)
    assert heads == oracle  # fault-free sanity
    schedules = (
        dict(kind="raise", at_calls=(1, 2), exc="transient"),
        dict(kind="raise", at_calls=(1,), exc="xla"),
        dict(kind="corrupt", at_calls=(1,), corruption="nan"),
        dict(kind="corrupt", at_calls=(1,), corruption="truncate"),
    )
    for kw in schedules:
        plan = FaultPlan(seed=17, sites={"sched.dispatch": FaultSpec(**kw)})
        with plan.active():
            heads, sch = _heads_via_sched(snaps)
        assert heads == oracle
        assert sch.breaker("forkchoice").state == "closed"
        assert plan.fired_sites() == {"sched.dispatch"}


def test_chaos_sched_forkchoice_hard_down_degrades_to_host():
    """A hard-down dispatch exhausts retries, opens the forkchoice
    breaker, and heads come from the host oracle — identical."""
    snaps = [_rand_mirror(200 + s, nb=14, nv=25).snapshot()
             for s in range(3)]
    oracle = [host_head(s) for s in snaps]
    plan = FaultPlan(seed=5, sites={
        "sched.dispatch": FaultSpec(kind="raise", rate=1.0,
                                    max_fires=FAST_RETRY.max_attempts,
                                    exc="transient"),
    })
    with plan.active():
        heads, sch = _heads_via_sched(snaps, failure_threshold=1)
    assert heads == oracle
    assert sch.breaker("forkchoice").state == "open"


# --- the service -------------------------------------------------------------


def test_service_direct_drive_votes_and_metrics():
    reg = obs_metrics.MetricsRegistry()
    service = ForkChoiceService(scheduler=_fresh_sched(registry=reg),
                                registry=reg)
    m = service.mirror
    anchor, a, b = b"\x20" * 32, b"\xbb" * 32, b"\x2b" * 32
    ck = (0, anchor)
    m.add_block(anchor, anchor, 0, justified=ck, finalized=ck)
    m.add_block(a, anchor, 1, justified=ck, finalized=ck)
    m.add_block(b, anchor, 1, justified=ck, finalized=ck)
    m.set_registry(np.full(4, GWEI_32, dtype=np.int64))
    m.set_checkpoints(ck, ck)
    assert service.apply_votes([0, 1, 2], 1, b) == [0, 1, 2]
    assert service.head() == b
    # an older-epoch vote for the other side must NOT move the messages
    assert service.apply_votes([0, 1, 2], 0, a) == []
    assert service.head() == b
    # a newer-epoch majority flips the head
    assert service.apply_votes([0, 1], 2, a) == [0, 1]
    # 2 votes a vs 1 vote b: a wins (and a > b bytes-wise anyway)
    assert service.head() == a
    assert reg.counter_value("forkchoice_heads_total") == 3


def test_service_subscribes_to_firehose_verified_batches():
    """The verified-batch consumer seam: each sealed flush triggers one
    head recompute and a head-lag observation per verified record; a
    subscriber fault is counted, not propagated."""
    import json

    from consensus_specs_tpu.firehose.ingest import (
        AttestationItem,
        ClassifyError,
    )
    from consensus_specs_tpu.firehose.pipeline import (
        AttestationFirehose,
        FirehoseConfig,
    )
    from consensus_specs_tpu.parallel.gossip_driver import message_id
    from consensus_specs_tpu.sched import BlsWorkClass

    class _StubBls(BlsWorkClass):
        def execute(self, requests):
            return np.asarray([True] * len(requests), dtype=bool)

        execute_degraded = execute

    def classify(raw):
        try:
            d = json.loads(raw)
            return AttestationItem(
                msg_id=message_id(bytes(raw)), key=(0, d["c"], b"r"),
                pubkeys=(b"\x01",), message=b"m", signature=b"\x02",
                ssz=bytes(raw))
        except Exception as exc:
            raise ClassifyError(str(exc)) from exc

    reg = obs_metrics.MetricsRegistry()
    hose = AttestationFirehose(
        classify,
        config=FirehoseConfig(batch_attestations=1, max_pending=16,
                              flush_deadline_s=0.0),
        scheduler=Scheduler(classes=[_StubBls()], max_depth=1 << 30,
                            registry=reg),
        registry=reg, threaded=False)

    service = ForkChoiceService(scheduler=_fresh_sched(registry=reg),
                                registry=reg)
    m = _rand_mirror(7, nb=10, nv=16)
    service.mirror = m
    expected = m.root_at(host_head(m.snapshot()))
    seen = []
    service.subscribe(hose)
    hose.subscribe_verified(lambda records: seen.append(len(records)))
    hose.subscribe_verified(lambda records: 1 / 0)  # faulty consumer

    for c in range(3):
        assert hose.offer(json.dumps({"c": c}).encode())
    hose.drain(timeout_s=30.0)
    assert seen and sum(seen) == 3
    assert service.head() == expected
    assert reg.counter_value("forkchoice_heads_total") >= 3
    lag = reg.histogram("forkchoice_head_lag_seconds")
    assert lag.count >= 3
    assert reg.counter_value("firehose_subscriber_errors_total") >= 3


# --- scenario replay: three lanes with the head check ------------------------


def _harddown_checker(spec, seg, *, registry=None):
    """device_head_checker variant whose lane opens its breaker on the
    first exhausted retry budget (failure_threshold=1)."""
    service = ForkChoiceService(
        scheduler=Scheduler(classes=[ForkChoiceWorkClass()],
                            retry_policy=FAST_RETRY, failure_threshold=1,
                            registry=registry),
        registry=registry)
    attached = []

    def check(store) -> bytes:
        if not attached:
            service.attach(spec, store)
            attached.append(True)
        return service.head()

    return check


def test_three_lanes_converge_with_device_head_checks(history):
    """Every epoch checkpoint of every lane carries a device_head equal
    to the reference get_head — and the three transcripts (including the
    device heads) stay bit-identical. The engine lane runs the "full"
    chaos profile, so sched.dispatch transients hit the head lane's own
    dispatch and must converge via retry."""
    o = oracle_lane(history, head_check=True)
    e = engine_lane(history, fault_seed=7, fault_profile="full",
                    head_check=True)
    f = firehose_lane(history, chaos=True, fault_seed=SEED, head_check=True)
    assert_converged([o, e, f])
    assert o.checkpoints, "history produced no checkpoints"
    for cp in o.checkpoints:
        assert cp["device_head"] == cp["checks"]["head"]["root"]


def test_head_check_hard_down_degrades_identically(history):
    """Permanent sched.dispatch failure: every head query degrades to the
    host oracle and the transcript (device_head included) still matches a
    fault-free device run bit-for-bit."""
    clean = oracle_lane(history, head_check=True)
    plan = FaultPlan(seed=9, sites={
        "sched.dispatch": FaultSpec(kind="raise", rate=1.0,
                                    max_fires=1 << 30, exc="transient"),
    })
    with plan.active():
        degraded = oracle_lane(history, head_check=_harddown_checker)
    assert plan.fires("sched.dispatch") > 0
    assert_converged([clean, degraded])


def test_diff_checkpoints_reports_head_divergence():
    cp = {"epoch": 3, "fork": "phase0", "head_state_root": "0xaa",
          "checks": {"head": {"slot": 24, "root": "0x01"}},
          "device_head": "0x01"}
    assert diff_checkpoints([cp], [cp]) == {
        "count": (1, 1), "mismatches": [], "head_divergence": []}
    # cross-transcript divergence
    other = {**cp, "checks": {"head": {"slot": 24, "root": "0x02"}},
             "device_head": "0x02"}
    d = diff_checkpoints([cp], [other])
    assert d["head_divergence"] and d["head_divergence"][0]["index"] == 0
    assert d["mismatches"]
    # intra-checkpoint divergence: device head contradicts its own lane
    wrong = {**cp, "device_head": "0x99"}
    d = diff_checkpoints([wrong], [wrong])
    assert d["head_divergence"][0]["heads"]["a.device"] == "0x99"
    assert d["mismatches"] == []


# --- the acceptance soak -----------------------------------------------------


@pytest.mark.slow
def test_soak_thousand_slot_heads_bit_identical_all_lanes():
    """Acceptance: a seeded ≥1,000-slot reorg-storm history where every
    epoch checkpoint's device head equals the reference get_head in all
    three lanes — with sched.dispatch chaos live in the engine lane
    (retry convergence) — and a hard-down replay serves identical heads
    from the host oracle with the breaker open."""
    script = build_script(2026, epochs=126)
    history = build_history(script)
    o = oracle_lane(history, head_check=True)
    e = engine_lane(history, fault_seed=2026, fault_profile="full",
                    head_check=True)
    f = firehose_lane(history, chaos=True, fault_seed=2026, head_check=True)
    assert_converged([o, e, f])
    assert o.slots >= 1000
    assert o.reorgs >= 1
    assert e.extra["faults_fired"]
    for cp in o.checkpoints:
        assert cp["device_head"] == cp["checks"]["head"]["root"]
    plan = FaultPlan(seed=2027, sites={
        "sched.dispatch": FaultSpec(kind="raise", rate=1.0,
                                    max_fires=1 << 30, exc="transient"),
    })
    with plan.active():
        harddown = oracle_lane(history, head_check=_harddown_checker)
    assert plan.fires("sched.dispatch") > 0
    assert_converged([o, harddown])
