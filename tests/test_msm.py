"""Pippenger bucket-MSM: kernel equivalence, cost pins, the sched "msm"
work class, and the device committee-aggregation lane (PR 11).

Layers under test, cheapest first:

1. **Cost pins (shape-only, no compile)** — at the acceptance shape
   (n=128, b=255, w=4) the Pippenger Horner combine runs 63 sequential
   fori_loop trips vs the per-item ladder's 127, and the batched point-op
   bill is 10235 vs 49024 — asserted via jax.eval_shape over the kernel's
   own digit decomposition, the same stance as test_rlc_grouped's D+1 pin.
2. **Oracle equivalence** — g1_msm_device bit-identical to the host
   Σ scalar_i·P_i (crypto/kzg.py:_msm) on random and edge batches: zero
   scalars, repeated points, the all-zero (identity) sum, 255-bit scalars.
   Pads are (generator, scalar 0) — infinity-adjacent in the sense that
   they gather the bucket-0 Jacobian zero in every window.
3. **Sched work class** — marker protocol, host-degrade agreement, one
   XLA compile per (class, bucket) via the PR-6 CompileTracker, chaos
   corrupt faults at sched.dispatch absorbed by validation+retry, and the
   2G2T-style self-check catching a corrupt-but-WELL-FORMED value that
   shape/dtype validation provably lets through.
4. **Cold-lane committee aggregation** — first sighting routes through
   the device path (batched subgroup checks + aggregate tree via the msm
   class), second sighting hits the committee cache; hostile members
   (infinity, non-subgroup) reject exactly as the host oracle does.

Compile budget note: every fast device case here reuses one of three
small programs ((8,64,4)/(8,255,4)/(8,8,4) msm buckets plus the
64-bucket aggregate/subgroup programs) — the persistent compile cache in
tests/.jax_cache makes reruns cheap.  The two tests whose *job* is to
trigger brand-new XLA compiles (the per-bucket compile counting at
nbits=12 and the randomized sweep) live in the slow tier; tier-1 keeps
the zero-recompile replay half of that pin.
"""
import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls12_381 as oracle
from consensus_specs_tpu.obs import metrics as obs_metrics
from consensus_specs_tpu.robustness.faults import FaultPlan, FaultSpec
from consensus_specs_tpu.robustness.retry import RetryPolicy
from consensus_specs_tpu.sched import (
    MsmWorkClass,
    Request,
    SchedSelfCheckError,
    Scheduler,
    reset_default_scheduler,
)

REG = obs_metrics.REGISTRY
FAST_RETRY = RetryPolicy(max_attempts=4, base_delay=0.0, backoff=1.0,
                         max_delay=0.0, jitter=0.0)


@pytest.fixture(autouse=True)
def _fresh_default_scheduler():
    reset_default_scheduler()
    yield
    reset_default_scheduler()


def _points(ks):
    """Affine [k]·G for each k (host oracle arithmetic)."""
    return [
        oracle.pt_to_affine(
            oracle.FP_FIELD, oracle.pt_mul(oracle.FP_FIELD, oracle.G1_GEN, k))
        for k in ks
    ]


def _host_msm(points_aff, scalars):
    from consensus_specs_tpu.crypto import kzg

    pts = [oracle.pt_from_affine(oracle.FP_FIELD, p) for p in points_aff]
    acc = kzg._msm(oracle.FP_FIELD, pts, scalars)
    return None if acc is None else oracle.pt_to_affine(oracle.FP_FIELD, acc)


# --- 1. cost pins (no compile) ----------------------------------------------


def test_msm_loop_count_pin_128x255():
    """Acceptance pin: at n=128 / b=255 the Pippenger combine's fori_loop
    trip count (63) is strictly below the per-item ladder's (127) —
    shape-only via eval_shape, like the grouped-RLC D+1 pin."""
    import jax
    import jax.numpy as jnp

    from consensus_specs_tpu.ops import bls12_jax as K

    bits = jnp.zeros((128, 255), dtype=bool)
    digits = jax.eval_shape(K.msm_window_digits, bits)
    assert digits.shape == (128, 64)  # 255 pads to 256 -> 64 4-bit windows
    assert K.msm_loop_count(digits) == 63
    assert K.g1_ladder_loop_count(bits) == 127
    assert K.msm_loop_count(digits) < K.g1_ladder_loop_count(bits)


def test_msm_point_op_budget_beats_ladder():
    """The batched point-op bill at the KZG shape: 10235 vs 49024 (the
    BASELINE.md stage table), and the gather-form advantage holds across
    the consumer shapes (64-bit KZG r-side, 488-member aggregation)."""
    from consensus_specs_tpu.ops import bls12_jax as K

    assert K.g1_msm_point_ops(128, 255, 4) == 10235
    assert K.g1_ladder_point_ops(128, 255) == 49024
    for n, b in ((128, 64), (128, 255), (512, 255), (64, 255)):
        assert K.g1_msm_point_ops(n, b, 4) < K.g1_ladder_point_ops(n, b)


def test_msm_window_digits_roundtrip():
    """Digits reassemble the scalar: Σ d_j·2^(w·j) == s, LSB-first."""
    import jax.numpy as jnp

    from consensus_specs_tpu.ops import bls12_jax as K

    scalars = [0, 1, 0xAB, 0x1234567, (1 << 64) - 1]
    bits = jnp.asarray(K._scalar_bits_lsb(scalars, 64))
    digits = np.asarray(K.msm_window_digits(bits, 4))
    assert digits.shape == (len(scalars), 16)
    for s, row in zip(scalars, digits):
        assert sum(int(d) << (4 * j) for j, d in enumerate(row)) == s


# --- 2. oracle equivalence ---------------------------------------------------


def test_msm_device_matches_host_oracle_64bit():
    """Random 64-bit batch with every edge in one bucket: zero scalar,
    scalar 1, repeated points, and pads past n=5 -> bucket 8."""
    from consensus_specs_tpu.ops import bls12_jax as K

    points = _points([2, 3, 3, 5, 9])  # index 1 == index 2: repeated point
    scalars = [0xDEADBEEFCAFE, 0, 1, 0xFFFFFFFFFFFFFFFF, 7]
    assert K.g1_msm_device(points, scalars, 64) == _host_msm(points, scalars)


def test_msm_device_matches_host_oracle_255bit():
    """Full-width scalars mod r — the KZG folded-side shape."""
    from consensus_specs_tpu.ops import bls12_jax as K

    points = _points([11, 13, 17, 19, 23, 29])
    scalars = [pow(7, i + 1, oracle.R) for i in range(6)]
    assert K.g1_msm_device(points, scalars, 255) == _host_msm(points, scalars)


def test_msm_device_zero_sum_is_none():
    """All-zero scalars (and a P + (-P) cancellation) produce the identity
    — returned as None, matching the host oracle."""
    from consensus_specs_tpu.ops import bls12_jax as K

    points = _points([2, 3, 4])
    assert K.g1_msm_device(points, [0, 0, 0], 64) is None
    p = _points([6])[0]
    neg = (p[0], (-p[1]) % oracle.P)
    assert K.g1_msm_device([p, neg], [5, 5], 64) is None


@pytest.mark.slow
def test_msm_device_randomized_sweep():
    """Wider randomized agreement: mixed windows, non-pow2 n, 255-bit
    scalars with zero/repeat riders — the grouped-vs-ungrouped style
    equivalence gate from ROADMAP item 1."""
    import random

    from consensus_specs_tpu.ops import bls12_jax as K

    rng = random.Random(1117)
    for n, window in ((12, 4), (20, 3)):
        ks = [rng.randrange(1, 1 << 20) for _ in range(n)]
        points = _points(ks)
        points[3] = points[0]  # repeated point
        scalars = [rng.randrange(oracle.R) for _ in range(n)]
        scalars[1] = 0
        scalars[n // 2] = scalars[0]
        assert K.g1_msm_device(points, scalars, 255, window) == \
            _host_msm(points, scalars)


# --- 3. the sched "msm" work class ------------------------------------------


def _msm_requests(nbits=8, tag=0):
    """Two small msm requests in the 8-bucket (scalars < 2^nbits)."""
    pts_a = _points([3 + tag, 5 + tag, 7 + tag])
    pts_b = _points([11 + tag, 13 + tag, 17 + tag, 19 + tag])
    return [
        Request(work_class="msm", kind="msm",
                payload=(tuple(pts_a), (5, 0, 200), nbits)),
        Request(work_class="msm", kind="msm",
                payload=(tuple(pts_b), (1, 255, 9, 128), nbits)),
    ]


def test_msm_class_matches_degraded_and_oracle():
    """Device markers == host-degrade markers == the host MSM oracle, for
    both kinds ("msm" + "aggregate") through one dispatch. The committee
    is 40 keys so the aggregate/subgroup programs land in the same
    64-bucket the cold-lane tests trace — no extra compile diversity."""
    from consensus_specs_tpu.crypto import bls_sig

    wc = MsmWorkClass()
    pks = tuple(bls_sig.SkToPk(900 + i) for i in range(40))
    reqs = _msm_requests() + [
        Request(work_class="msm", kind="aggregate", payload=pks)]
    dev = wc.execute(reqs)
    host = wc.execute_degraded(reqs)
    assert list(dev) == list(host)
    for r, row in zip(reqs[:2], dev):
        points, scalars, _ = r.payload
        want = _host_msm(list(points), list(scalars))
        assert row == ("point", want[0], want[1])


def test_msm_compile_replay_adds_zero():
    """Replaying an already-traced bucket must not re-trace: the cheap
    half of the one-compile-per-(class, bucket) pin, safe for tier-1
    because the (8-bucket, nbits=8) program is shared with the other
    sched tests in this process.  The fresh-compile counting half lives
    in test_msm_compile_pinned_one_per_bucket (@slow) — it exists to
    trigger brand-new XLA compiles, which is inherently expensive."""
    from consensus_specs_tpu.obs.recompile import CompileTracker

    kernel = "_g1_msm_program"
    tracker = CompileTracker(registry=obs_metrics.MetricsRegistry()).install()
    try:
        sch = Scheduler(classes=[MsmWorkClass()])

        def run(reqs):
            hs = [sch.submit(r) for r in reqs]
            sch.drain()
            return [h.result() for h in hs]

        run(_msm_requests(tag=0))
        after_first = tracker.compiles(kernel)
        run(_msm_requests(tag=30))  # same 8-bucket: cache hits, no trace
        assert tracker.compiles(kernel) == after_first
    finally:
        tracker.uninstall()


@pytest.mark.slow
def test_msm_compile_pinned_one_per_bucket():
    """Fixed bucket set => one XLA compile per (class, bucket): replaying
    the 8-bucket reuses the cached executable, only the 16-bucket adds a
    compile — the CompileTracker pin from the acceptance criteria. The
    tracker counts trace events (in-memory jit cache misses), so this test
    uses nbits=12 — a width no other test in this process traces."""
    from consensus_specs_tpu.obs.recompile import CompileTracker

    kernel = "_g1_msm_program"
    tracker = CompileTracker(registry=obs_metrics.MetricsRegistry()).install()
    try:
        sch = Scheduler(classes=[MsmWorkClass()])
        base = tracker.compiles(kernel)

        def run(reqs):
            hs = [sch.submit(r) for r in reqs]
            sch.drain()
            return [h.result() for h in hs]

        run(_msm_requests(nbits=12, tag=0))
        first = tracker.compiles(kernel) - base
        assert first >= 1
        run(_msm_requests(nbits=12, tag=30))  # same 8-bucket: cache hits
        assert tracker.compiles(kernel) - base == first
        big = Request(  # 12 items -> 16-bucket: exactly one new compile
            work_class="msm", kind="msm",
            payload=(tuple(_points(range(2, 14))), tuple(range(12)), 12))
        run([big])
        assert tracker.compiles(kernel) - base == first + 1
    finally:
        tracker.uninstall()


def test_chaos_msm_dispatch_corrupt_converges():
    """Corrupt faults at sched.dispatch (nan + truncate) on msm batches
    are caught by result validation and re-executed from intact host
    payloads — results bit-identical to the fault-free oracle, breaker
    closed throughout."""

    def run_all():
        sch = Scheduler(classes=[MsmWorkClass()], retry_policy=FAST_RETRY)
        hs = [sch.submit(r) for r in _msm_requests()]
        sch.drain()
        out = [h.result() for h in hs]
        assert sch.breaker("msm").state == "closed"
        return out

    want = run_all()
    for corruption in ("nan", "truncate"):
        plan = FaultPlan(seed=23, sites={"sched.dispatch": FaultSpec(
            kind="corrupt", at_calls=(1,), corruption=corruption)})
        with plan.active():
            assert run_all() == want
        assert plan.fired_sites() == {"sched.dispatch"}


def test_msm_self_check_catches_well_formed_corruption():
    """The 2G2T seam earns its keep exactly where shape/dtype validation
    is blind: a corrupted result row that is still a well-formed
    ("point", x, y) marker. With self_check ON the first dispatch raises
    the retryable SchedSelfCheckError BEFORE any handle resolves and the
    retry returns the true sum; with the flag OFF the same corruption
    resolves a handle with garbage — proving the check is load-bearing."""
    points, scalars, nbits = _msm_requests()[0].payload
    want = _host_msm(list(points), list(scalars))

    def corrupting(wc):
        real, state = wc.execute, {"calls": 0}

        def execute(requests):
            out = real(requests)
            state["calls"] += 1
            if state["calls"] == 1:
                tag, x, y = out[0]
                out[0] = (tag, x, (y + 1) % oracle.P)  # well-formed, wrong
            return out

        wc.execute = execute
        return state

    req = Request(work_class="msm", kind="msm",
                  payload=(points, scalars, nbits))
    wc = MsmWorkClass(self_check=True)
    state = corrupting(wc)
    sch = Scheduler(classes=[wc], retry_policy=FAST_RETRY)
    h = sch.submit(req)
    sch.drain()
    assert h.result() == ("point", want[0], want[1])
    assert state["calls"] == 2  # first attempt rejected by the self-check

    # the error itself is the retryable kind the dispatch loop absorbs
    bad = np.empty(1, dtype=object)
    bad[0] = ("point", want[0], (want[1] + 1) % oracle.P)
    with pytest.raises(SchedSelfCheckError):
        MsmWorkClass(self_check=True).verify_results([req], bad)

    # control: flag off, the same corruption escapes to the caller
    wc_off = MsmWorkClass(self_check=False)
    state = corrupting(wc_off)
    sch = Scheduler(classes=[wc_off], retry_policy=FAST_RETRY)
    h = sch.submit(req)
    sch.drain()
    assert h.result() == ("point", want[0], (want[1] + 1) % oracle.P)
    assert state["calls"] == 1


# --- 4. cold-lane committee aggregation -------------------------------------


def test_cold_committee_aggregation_routes_device_then_caches():
    """Firehose cold-lane regression: a first-sighting committee (caches
    cleared, 40 members >= DEVICE_AGGREGATE_MIN) aggregates through the
    device msm lane — one sched "aggregate" submit, one batched subgroup
    check covering every cold key — and matches the host oracle; the
    second sighting is served from the committee cache with zero new
    device work."""
    from consensus_specs_tpu.crypto import bls, bls_jax, bls_sig

    sks = [77001 + i for i in range(40)]
    pks = [bytes(bls_sig.SkToPk(sk)) for sk in sks]
    want = _points([sum(sks) % oracle.R])[0]  # Σ[sk]G == [Σsk]G

    bls.clear_caches()
    reset_default_scheduler()
    agg0 = REG.counter_value("bls_pubkey_aggregate_device_total")
    sub0 = REG.counter_value("bls_pubkey_subgroup_device_total")
    sched0 = REG.counter_value("sched_submitted_total", work_class="msm",
                               kind="aggregate")
    aff = bls_jax._aggregate_pubkeys_affine(pks)
    assert aff == want
    assert REG.counter_value("bls_pubkey_aggregate_device_total") - agg0 == 1
    assert REG.counter_value("bls_pubkey_subgroup_device_total") - sub0 == 40
    assert REG.counter_value("sched_submitted_total", work_class="msm",
                             kind="aggregate") - sched0 == 1

    # re-sighting: committee cache hit — no new dispatch, no new checks
    assert bls_jax._aggregate_pubkeys_affine(pks) == want
    assert REG.counter_value("bls_pubkey_aggregate_device_total") - agg0 == 1
    assert REG.counter_value("sched_submitted_total", work_class="msm",
                             kind="aggregate") - sched0 == 1

    # the flush-prep entry point rides the same lane
    msg = b"cold lane message"
    sig = bls_sig.Sign(sum(sks), msg)
    check = bls_jax.make_fast_aggregate_check(pks, msg, sig)
    assert check is not None and check.p1 == want


def test_cold_committee_hostile_members_reject_like_host():
    """Hostile first-sighting committees fail closed through the device
    lane: an infinity member and an on-curve-but-not-in-subgroup member
    ((0, 2) — only the DEVICE subgroup check can catch it post-decompress)
    both reject exactly as the host oracle contract demands."""
    from consensus_specs_tpu.crypto import bls, bls_jax, bls_sig

    bls.clear_caches()
    reset_default_scheduler()
    pks = [bytes(bls_sig.SkToPk(78001 + i)) for i in range(39)]
    assert bls_jax._aggregate_pubkeys_affine(
        pks + [oracle.g1_to_bytes(None)]) is None  # infinity member
    assert (0 * 0 * 0 + oracle.B_G1 - 2 * 2) % oracle.P == 0  # (0,2) on curve
    with pytest.raises(ValueError, match="subgroup"):
        bls_jax._aggregate_pubkeys_affine(
            [bytes(bls_sig.SkToPk(79001 + i)) for i in range(39)]
            + [oracle.g1_to_bytes((0, 2))])
    # aggregate_pubkeys_device mirrors AggregatePKs: infinity member raises
    with pytest.raises(ValueError, match="infinity"):
        bls_jax.aggregate_pubkeys_device(pks + [oracle.g1_to_bytes(None)])
