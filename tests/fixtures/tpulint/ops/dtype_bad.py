"""Seeded historical-bug replay (PR 1, CHANGES.md): fori_loop bounds left as
bare Python ints traced s64 under x64 mode against an s32 carry — the GSPMD
verifier failure on sharded programs. Plus the ambient-dtype constructor."""
import jax
import jax.numpy as jnp


def sha_rounds(state):
    def round_fn(i, st):
        return st + jnp.uint32(i)

    return jax.lax.fori_loop(0, 64, round_fn, state)  # tpulint-expect: dtype-pin


def widen(n):
    return jnp.zeros(n)  # tpulint-expect: dtype-pin


def window(n):
    return jnp.arange(n)  # tpulint-expect: dtype-pin


def horner_combine(acc, n_windows):
    """The MSM Horner-combine shape (PR 11) with the bad spelling: a
    runtime-derived upper bound left unpinned traces s64 under x64."""
    def body(i, a):
        return a + jnp.int32(i)

    return jax.lax.fori_loop(jnp.int32(0), n_windows - 1, body, acc)  # tpulint-expect: dtype-pin


def level_walk(gindices, siblings, depth):
    """The multiproof level-walk shape (PR 15) with the bad spelling: both
    bounds bare, so the induction var driving the dynamic_update_index
    traces s64 against the s32 gindex carry."""
    def step(i, carry):
        g, out = carry
        out = jax.lax.dynamic_update_index_in_dim(out, g, i, axis=1)
        return g >> jnp.int32(1), out

    return jax.lax.fori_loop(0, depth, step, (gindices, siblings))  # tpulint-expect: dtype-pin


def head_walk(parent, weight, filtered, head0, b):
    """The fork-choice head-walk shape (PR 17) with the bad spelling: the
    block-count bound left bare traces s64 under x64 against the s32 head
    carry the argmax refines."""
    def step(i, head):
        kids = (parent == head) & filtered
        m = kids & (weight == weight.max())
        return jax.lax.cond(m.any(), lambda: jnp.argmax(m).astype(jnp.int32),
                            lambda: head)

    return jax.lax.fori_loop(0, b, step, head0)  # tpulint-expect: dtype-pin
