"""Seeded historical-bug replay (PR 1, CHANGES.md): fori_loop bounds left as
bare Python ints traced s64 under x64 mode against an s32 carry — the GSPMD
verifier failure on sharded programs. Plus the ambient-dtype constructor."""
import jax
import jax.numpy as jnp


def sha_rounds(state):
    def round_fn(i, st):
        return st + jnp.uint32(i)

    return jax.lax.fori_loop(0, 64, round_fn, state)  # tpulint-expect: dtype-pin


def widen(n):
    return jnp.zeros(n)  # tpulint-expect: dtype-pin


def window(n):
    return jnp.arange(n)  # tpulint-expect: dtype-pin
