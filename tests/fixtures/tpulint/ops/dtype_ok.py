"""dtype-pin negative fixture: the sanctioned ops/sha256_jax.py spellings."""
import jax
import jax.numpy as jnp


def sha_rounds(state):
    def round_fn(i, st):
        return st + jnp.uint32(i)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(64), round_fn, state)


def widen(n):
    return jnp.zeros(n, dtype=jnp.uint32)


def widen_positional(n):
    return jnp.zeros(n, jnp.uint32)


def window(n):
    return jnp.arange(n, dtype=jnp.int32)


def inherit(x):
    return jnp.zeros_like(x)


def horner_combine(acc, n_windows):
    """The sanctioned MSM Horner-combine spelling
    (ops/bls12_jax.g1_msm_pippenger): both bounds pinned int32."""
    def body(i, a):
        return a + jnp.int32(i)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(n_windows - 1), body, acc)


def level_walk(gindices, siblings, depth):
    """The sanctioned multiproof level-walk spelling
    (ops/multiproof_jax._sibling_rows_impl): both bounds pinned int32."""
    def step(i, carry):
        g, out = carry
        out = jax.lax.dynamic_update_index_in_dim(out, g, i, axis=1)
        return g >> jnp.int32(1), out

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(depth), step,
                             (gindices, siblings))


def head_walk(parent, weight, filtered, head0, b):
    """The sanctioned fork-choice head-walk spelling
    (ops/forkchoice_jax._ghost_head_impl): both bounds pinned int32."""
    def step(i, head):
        kids = (parent == head) & filtered
        m = kids & (weight == weight.max())
        return jax.lax.cond(m.any(), lambda: jnp.argmax(m).astype(jnp.int32),
                            lambda: head)

    return jax.lax.fori_loop(jnp.int32(0), jnp.int32(b), step, head0)
