"""Suppression fixture: the violation is real but carries a one-line
justification, so the run stays clean (self-test fails on any unexpected
finding — including here, if suppression parsing regresses)."""
import jax.numpy as jnp


def trace_time_table(n):
    return jnp.zeros(n)  # tpulint: disable=dtype-pin -- trace-time table on a static size; ambient dtype fine


def blanket(n):
    return jnp.arange(n)  # tpulint: disable -- fixture: blanket suppression form
