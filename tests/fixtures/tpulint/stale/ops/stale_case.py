"""stale-suppression fixture: one live suppression, one stale, one typo'd."""
import jax.numpy as jnp


def table(n):
    return jnp.arange(n)  # tpulint: disable=dtype-pin -- trace-time ramp table, ambient dtype intended


def clean(n):
    return n + 1  # tpulint: disable=jit-purity -- leftover from a removed print  # tpulint-expect: stale-suppression


def typo(n):
    return n  # tpulint: disable=jit-puirty -- misspelled rule id  # tpulint-expect: stale-suppression
