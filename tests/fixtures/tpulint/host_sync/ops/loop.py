"""host-sync fixture: per-iteration device->host syncs in driver loops."""
import jax
import jax.numpy as jnp
import numpy as np


def _scale(x):
    return x * 2.0


kernel = jax.jit(_scale)


def hot_loop(batches):
    total = 0.0
    for b in batches:
        y = kernel(b)
        total += float(y)  # tpulint-expect: host-sync
    return total


def _sync(y):
    return y.block_until_ready()  # tpulint-expect: host-sync


def drain(batches):
    out = []
    for b in batches:
        out.append(_sync(kernel(b)))
    return out


def readout_once(batches):
    acc = jnp.zeros(8, dtype=jnp.float32)
    for b in batches:
        acc = acc + kernel(b)
    return float(acc)  # single sync AFTER the loop: the sanctioned pattern


def host_only(batches):
    out = []
    for b in batches:
        out.append(float(np.sum(b)))  # host value: no device sync
    return out
