"""seam-coverage negative fixture: the three sanctioned coverage shapes.

covered_direct  — seam lexically inside `with span(...)`;
_helper         — no span of its own, but every call site is covered
                  (the bridge._stage_write_back pattern);
covered_nested_attempt — seam inside a nested def while the span wraps the
                  dispatch in the same top-level function (the
                  resident._dispatch retry pattern).
"""
from seam_pkg.obs.trace import span
from seam_pkg.robustness.faults import corrupt_array, fire


def covered_direct(arr):
    with span("engine.step"):
        fire("engine.step")
    return arr


def _helper(arr):
    return corrupt_array("engine.helper", arr)


def covered_via_caller(arr):
    with span("engine.outer"):
        return _helper(arr)


def covered_nested_attempt(arr):
    def attempt():
        fire("engine.attempt")
        return arr

    with span("engine.attempt"):
        return attempt()
