"""seam-coverage positive fixture: naked and unlabelable seam call sites."""
from seam_pkg.obs.trace import span
from seam_pkg.robustness.faults import fire


def uncovered(arr):
    fire("engine.naked")  # tpulint-expect: seam-coverage
    return arr


def computed_label(site_name, arr):
    with span("engine.labeled"):
        fire(site_name)  # tpulint-expect: seam-coverage
    return arr
