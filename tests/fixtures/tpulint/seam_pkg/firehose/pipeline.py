"""seam-coverage fixtures for the ISSUE-13 context-propagation call shape.

The firehose ingest seam now mints a TraceContext and passes it to the
wrapping span (`span("firehose.ingest", ctx=ctx)`); the flush fan-in
passes the collapsed members' contexts as links. Both are ordinary
`with span(...)` scopes to the analyzer — the kwargs must not confuse
span detection — so `covered_ingest`/`covered_flush_fanin` stay clean,
while minting a context does NOT count as coverage by itself:
`uncovered_mint_only` propagates causality but never opens a span.
"""
from seam_pkg.obs.context import mint_trace
from seam_pkg.obs.trace import span
from seam_pkg.robustness.faults import fire


def covered_ingest(item):
    ctx = mint_trace()
    with span("firehose.ingest", ctx=ctx):
        fire("firehose.ingest")
    return item


def covered_flush_fanin(items):
    links = [mint_trace() for _ in items]
    with span("firehose.flush", batch=len(items), links=links):
        fire("firehose.flush")
    return items


def uncovered_mint_only(item):
    ctx = mint_trace()
    fire("firehose.ingest")  # tpulint-expect: seam-coverage
    return item, ctx
