"""seam-coverage fixtures for the sched.dispatch fan-in link shape.

The dispatch span carries links to every collapsed member's TraceContext
(`span("sched.dispatch", links=links)`); the seam inside it is covered.
Building the links list is propagation plumbing, not coverage: a seam
fired while assembling links outside any span is still naked.
"""
from seam_pkg.obs.context import mint_trace
from seam_pkg.obs.trace import span
from seam_pkg.robustness.faults import fire


def covered_dispatch(entries):
    links = [mint_trace() for _ in entries]
    with span("sched.dispatch", batch=len(entries), links=links):
        fire("sched.dispatch")
    return entries


def uncovered_link_assembly(entries):
    links = []
    for _ in entries:
        links.append(mint_trace())
        fire("sched.dispatch")  # tpulint-expect: seam-coverage
    return links
