"""Minimal span shim mirroring consensus_specs_tpu/obs/trace.py."""
from contextlib import contextmanager


@contextmanager
def span(name, **attrs):
    yield name
