"""Minimal trace-context shim mirroring consensus_specs_tpu/obs/context.py."""


class TraceContext:
    def __init__(self, trace_id, span_id, parent_span_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id


def mint_trace():
    return TraceContext("t0", "s0")
