"""Minimal counter registry mirroring consensus_specs_tpu/obs/metrics.py."""


class _Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n


class _Registry:
    def __init__(self):
        self._counters = {}

    def counter(self, name, **labels):
        key = (name, tuple(sorted(labels.items())))
        return self._counters.setdefault(key, _Counter())


REGISTRY = _Registry()
