"""Fault seams that tick the registry — the instrumented (correct) shape."""
from seam_pkg.obs import metrics as _metrics


def fire(site):
    _metrics.REGISTRY.counter("fault_fires_total", site=site).inc()
    return False


def corrupt_array(site, arr):
    fire(site)
    return arr
