"""jit-purity positive fixture: host effects reachable inside jit tracing."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky_kernel(x):
    print("tracing", x)  # tpulint-expect: jit-purity
    y = np.log(x)  # tpulint-expect: jit-purity
    return jnp.sum(y)


def _helper(x):
    return x.item()  # tpulint-expect: jit-purity


def wrapped(x):
    return _helper(x) + 1


fast_wrapped = jax.jit(wrapped)
