"""jit-purity negative fixture: host effects only outside jit reach, np dtype
constructors (the pinning pattern) exempt inside."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def clean_kernel(x):
    return jnp.sum(x * jnp.int32(2)) + jnp.int32(np.int32(1))


def host_report(x):
    print("result:", np.asarray(x))
    return np.asarray(x).tolist()


TABLE = np.arange(16)
