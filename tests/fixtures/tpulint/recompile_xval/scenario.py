"""recompile-risk fixture AND dynamic cross-validation scenario.

This module is read two ways: tpulint parses it (jax-free) and must flag
exactly the annotated lines; tests/test_tpulint_dataflow.py imports it under
obs/recompile.py's CompileTracker and asserts the static flags agree with
the observed compile counts — flagged kernels recompile when driven with
varying queue lengths, unflagged kernels compile exactly once.
"""
import jax
import jax.numpy as jnp

_MIN_BATCH = 8


def _scale(x):
    return x * 2.0


def _shift(x):
    return x + 1.0


def _square(x):
    return x * x


def _tail_sum(x, n):
    return jnp.sum(x[:n])


kernel_scale = jax.jit(_scale)
kernel_shift = jax.jit(_shift)
kernel_square = jax.jit(_square)
kernel_tail = jax.jit(_tail_sum, static_argnums=(1,))


def _bucket(n):
    b = _MIN_BATCH
    while b < n:
        b *= 2
    return b


def run_varying(queue):
    buf = jnp.zeros(len(queue))
    return kernel_scale(buf)  # tpulint-expect: recompile-risk


def run_bucketed(queue):
    buf = jnp.zeros(_bucket(len(queue)))
    return kernel_shift(buf)


def run_fixed():
    buf = jnp.zeros(16)
    return kernel_square(buf)


def run_static_runtime(x, queue):
    return kernel_tail(x, len(queue))  # tpulint-expect: recompile-risk
