"""no-scatter fixture (file named like the real reduction module so the
path-scoped rule applies): a dynamic-index scatter is flagged, the static
limb-surgery form is exempt."""
import jax.numpy as jnp


def segment_sum_scatter(acc, seg_ids, vals):
    return acc.at[seg_ids].add(vals)  # tpulint-expect: no-scatter


def segment_set_scatter(acc, idx, vals):
    return acc.at[idx].set(vals)  # tpulint-expect: no-scatter


def limb_surgery_ok(window, carry):
    window = window.at[..., 0].set(jnp.uint64(0))
    window = window.at[..., 1].add(carry)
    return window.at[2:4].set(jnp.uint64(1))
