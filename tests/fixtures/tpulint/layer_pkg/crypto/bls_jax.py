"""Fixture device backend: importing this module requires jax."""
import jax  # noqa  (the whole point: this module is jax-only)
