"""Transitive py-branch leak: das itself never says `import jax`, but its
module-level import chain reaches a jax-importing kernel module."""
from ..ops import fr_jax  # tpulint-expect: import-layering


def extend(data):
    return fr_jax.ntt(data)
