"""Seeded historical-bug replay (pre-PR-3 crypto/bls.py): a module-level
bls_jax import in the py-branch shim — a pure-Python-oracle process (no jax
importable) could not even import the module."""
from . import bls_jax  # noqa  tpulint-expect: import-layering


def backend():
    return "py"
