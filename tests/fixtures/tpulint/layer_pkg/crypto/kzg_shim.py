"""Negative case: the py-branch shim with properly deferred device imports
(the PR-3 discipline) stays clean."""


def _fr_jax():
    from ..ops import fr_jax  # deferred: only the device path pays

    return fr_jax


def commit(data, use_device=False):
    if use_device:
        return _fr_jax().ntt(data)
    return sum(data)
