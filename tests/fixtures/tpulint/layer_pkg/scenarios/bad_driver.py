"""Seeded failure shape: a scenario driver importing the device stack at
module level — the scenario engine is a pure host-side planner/replayer
(spec calls, sched submits, vector emission), so a module-level jax
import here would drag the device stack into every oracle-only replay."""
import jax  # noqa  tpulint-expect: import-layering


def replay(history):
    return jax.device_get(history)
