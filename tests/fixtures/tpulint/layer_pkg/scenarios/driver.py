"""Clean scenario driver: jax-free at module level, matching the
scenarios/ charter — the oracle lane never touches the device, and the
engine/firehose lanes reach it only through deferred imports inside the
lane bodies (bridge routing, sched work classes)."""

checkpoints = []


def replay(history, use_engine=False):
    for seg in history:
        if use_engine:
            import jax  # deferred: only the engine lane pays

            seg = jax.device_get(seg)
        checkpoints.append(seg)
    return list(checkpoints)
