"""Seeded failure shape: a proof-cache module importing the device stack
at module level — every jax-free consumer (tools, shims, the obs dump)
would drag jax in just by reading cached branches."""
import jax  # noqa  tpulint-expect: import-layering


def lookup(column, gindex):
    return jax.device_get((column, gindex))
