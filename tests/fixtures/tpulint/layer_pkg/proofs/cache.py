"""Clean proof-cache module: jax-free at module level, the device path
deferred into the serving body — the proofs/ charter (cache lookups and
dirty-column invalidation never touch the device stack; only a miss pays
for the multiproof kernel)."""

entries = {}


def lookup(column, gindex):
    return entries.get((column, gindex))


def prove(column, gindex, chunks, use_device=False):
    if use_device:
        import jax  # deferred: only the miss path pays

        return jax.device_get(chunks)
    return list(chunks)
