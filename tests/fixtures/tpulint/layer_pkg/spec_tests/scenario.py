"""Negative case: spec_tests/ is a sanctioned testlib consumer."""
from ..testlib import helpers


def scenario(x):
    return helpers.build(x)
