def run(x):
    return x
