"""Test-only leak: production orchestration importing testlib helpers."""
from ..testlib import helpers  # tpulint-expect: import-layering


def orchestrate(x):
    return helpers.build(x)
