def build(x):
    return x
