"""Clean scheduler module: jax-free at module level, device work deferred
into the executor body — the sched/scheduler.py charter (work classes load
jax inside execute(), so shims submit without importing the device stack)."""

pending = []


def submit(request):
    pending.append(request)
    return len(pending) - 1


def dispatch(batch, use_device=False):
    if use_device:
        import jax  # deferred: only the device path pays

        return jax.device_get(batch)
    return list(batch)
