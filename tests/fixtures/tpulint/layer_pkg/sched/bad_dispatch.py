"""Seeded failure shape: a scheduler module importing the device stack at
module level — every jax-free submitter (crypto/bls.py's deferral flush,
the KZG batch entry points) would drag jax in just by queueing work."""
import jax  # noqa  tpulint-expect: import-layering


def dispatch(batch):
    return jax.device_get(batch)
