"""Clean firehose stage: jax-free at module level, matching the
firehose/pipeline.py charter — device work happens only behind the
scheduler's work-class execute bodies, and any direct device touch is
deferred into the branch that needs it."""

queue = []


def offer(payload):
    queue.append(payload)
    return len(queue)


def flush(use_device=False):
    batch, queue[:] = list(queue), []
    if use_device:
        import jax  # deferred: only the device path pays

        return jax.device_get(batch)
    return batch
