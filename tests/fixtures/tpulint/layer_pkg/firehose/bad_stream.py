"""Seeded failure shape: a firehose stage importing the device stack at
module level — the streaming service is a pure host-side orchestrator
(submit/flush through sched/), so a module-level jax import here would
drag the device stack into every gossip consumer."""
import jax  # noqa  tpulint-expect: import-layering


def flush(batch):
    return jax.device_get(batch)
