"""Clean obs module: jax-free at module level, device hooks deferred into
install() — the sanctioned pattern for the observability layer (metrics and
tracing must be importable from every jax-free py-branch)."""

counts = {}


def install():
    import jax.monitoring  # deferred: only an installed tracker needs jax

    jax.monitoring.register_event_listener(lambda e: None)


def on_compile(kernel):
    counts[kernel] = counts.get(kernel, 0) + 1
