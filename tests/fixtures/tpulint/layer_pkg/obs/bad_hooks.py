"""Seeded failure shape: an obs module wiring its compile hooks at import
time — the module-level jax import poisons every jax-free consumer that
records a metric (crypto/bls.py, robustness/, the gossip driver)."""
import jax.monitoring  # noqa  tpulint-expect: import-layering


def install():
    jax.monitoring.register_event_listener(lambda e: None)
