"""Layer-order violation: a leaf kernel module importing the orchestration
layer above it."""
from ..engine import loop  # tpulint-expect: import-layering


def kernel(x):
    return loop.run(x)
