"""Fixture kernel module: module-level jax import (legitimate here — ops/ IS
the device layer)."""
import jax


def ntt(values):
    return jax.numpy.asarray(values)
