"""Clean robustness module: classification by type NAME (no jax import at
module level) and a deferred function-level import — the sanctioned pattern
for a jax-free branch that still needs to manufacture a device error."""


def is_retryable(exc):
    return any(t.__name__ == "XlaRuntimeError" for t in type(exc).__mro__)


def make_device_error(msg):
    from jax.errors import JaxRuntimeError  # deferred: jax-path only

    return JaxRuntimeError(msg)
