"""Seeded failure shape: a fault-injection module that imports jax at module
level to build its injected exception — poisons every jax-free consumer
(crypto/bls.py, the gossip driver) that threads a fault seam."""
import jax  # noqa  tpulint-expect: import-layering


def make_exc(msg):
    return jax.errors.JaxRuntimeError(msg)
