"""Seeded failure shape: an admission plane importing the device stack at
module level — every jax-free consumer (the traffic replay, the obs dump,
the SLO probe) would drag jax in just by asking whether a request may be
admitted."""
import jax  # noqa  tpulint-expect: import-layering


def admit(klass, payload):
    return jax.device_put(payload)
