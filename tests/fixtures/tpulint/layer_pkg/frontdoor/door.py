"""Clean admission-plane module: jax-free at module level, the frontdoor/
charter — the door decides admission, quotas, and shedding on the host
and only ever reaches the device through the fronted lanes' scheduler
submits; any direct device peek stays deferred behind the dispatch."""

queues = {"reads": [], "heads": []}


def admit(klass, payload):
    queues[klass].append(payload)
    return len(queues[klass])


def serve(snapshot, use_device=False):
    if use_device:
        from .. import ops  # deferred: only the dispatch path pays

        return ops.head(snapshot)
    return queues["heads"][-1] if queues["heads"] else None
