"""Seeded failure shape: a fork-choice service importing the device
stack at module level — every jax-free consumer (the scenario lanes, the
obs dump, the conformance runner) would drag jax in just by asking for
the current head."""
import jax  # noqa  tpulint-expect: import-layering


def head(snapshot):
    return jax.device_get(snapshot)
