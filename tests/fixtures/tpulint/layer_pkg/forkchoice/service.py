"""Clean fork-choice service module: jax-free at module level, the
device path deferred behind the sched work class — the forkchoice/
charter (mirror bookkeeping, vote filtering, and head queries never
touch the device stack directly; the kernel lives in ops/ and is reached
only through dispatch)."""

votes = {}


def apply_vote(index, root):
    votes[index] = root


def head(snapshot, use_device=False):
    if use_device:
        from .. import ops  # deferred: only the dispatch path pays

        return ops.head(snapshot)
    return max(votes, default=0)
