"""Negative case: the evm py-branch, pure Python end to end, stays clean."""


def encode(x):
    return bytes([x % 256])
