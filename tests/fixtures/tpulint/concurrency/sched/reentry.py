"""Self-acquisition: a non-reentrant Lock re-acquired through a callee
deadlocks instantly (positive); the same shape over an RLock is the
intended re-entry idiom (negative) — the StoreMirror pattern."""
import threading


class NonReentrant:
    def __init__(self):
        self._lock = threading.Lock()
        self.depth = 0

    def outer(self):
        with self._lock:
            self._inner()

    def _inner(self):
        with self._lock:  # tpulint-expect: lock-order
            self.depth += 1


class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()
        self.depth = 0

    def outer(self):
        with self._lock:
            self._inner()

    def _inner(self):
        with self._lock:
            self.depth += 1
