"""Positive: a two-lock ordering cycle inside one module — `admit` takes
ingest-then-flush, `reconcile` takes flush-then-ingest. Whichever thread
wins the first lock of each pair can deadlock the other."""
import threading

_ingest_lock = threading.Lock()
_flush_lock = threading.Lock()


def admit(batch):
    with _ingest_lock:
        with _flush_lock:  # tpulint-expect: lock-order
            return list(batch)


def reconcile(batch):
    with _flush_lock:
        with _ingest_lock:  # tpulint-expect: lock-order
            return list(batch)
