"""Cross-module half B: `resync` holds the head lock and calls back into
chain_queue.enqueue, which acquires the queue lock — closing the cycle
that chain_queue.flush opens in the other direction."""
import threading

_head_lock = threading.Lock()


def recompute(batch):
    with _head_lock:  # tpulint-expect: lock-order
        return len(batch)


def resync(batch):
    from . import chain_queue
    with _head_lock:  # tpulint-expect: lock-order
        return chain_queue.enqueue(batch)
