"""Cross-module half A of an ordering cycle: `flush` holds the queue
lock and calls into chain_head, which acquires the head lock — the
reverse chain lives in chain_head.resync. Neither module sees the whole
cycle; only the callgraph does (the firehose→sched flush shape)."""
import threading

from . import chain_head

_queue_lock = threading.Lock()


def flush(batch):
    # the queue->head edge this opens is anchored (and flagged) at the
    # acquire inside chain_head.recompute, where the cycle becomes visible
    with _queue_lock:
        return chain_head.recompute(batch)


def enqueue(batch):
    with _queue_lock:
        return list(batch)
