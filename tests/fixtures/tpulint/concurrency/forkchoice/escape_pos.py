"""Positive: a mutable, unlocked object handed to a thread target via
`args` — the owner keeps a reference and may mutate concurrently."""
import threading


class MutableTally:
    def __init__(self):
        self.counts: dict = {}

    def bump(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1


def _worker(tally):
    return tally


def spawn_worker():
    tally = MutableTally()
    threading.Thread(  # tpulint-expect: thread-escape
        target=_worker, args=(tally,), daemon=True).start()
    return tally
