"""Negatives for the escape audit: the StoreSnapshot pattern (frozen
dataclass handed off whole) and an internally-locked object whose every
mutating method guards itself."""
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class HeadSnapshot:
    slot: int
    root: bytes


class LockedTally:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts: dict = {}

    def bump(self, key):
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + 1


def _worker(payload):
    return payload


def publish(slot, root):
    snap = HeadSnapshot(slot=slot, root=root)
    threading.Thread(target=_worker, args=(snap,), daemon=True).start()
    return snap


def spawn_locked():
    tally = LockedTally()
    threading.Thread(target=_worker, args=(tally,), daemon=True).start()
    return tally
