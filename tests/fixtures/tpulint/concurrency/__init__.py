"""Fixture mini-package for the tpulint v3 concurrency rules.

Sub-packages reuse the production plane names (`firehose/`, `sched/`,
`forkchoice/`) so the path-scoped rules apply exactly as they do to the
shipped package. Positive cases carry inline expectation annotations;
the `_ok` modules encode the two shipped thread shapes (double-buffered
flusher hand-off, subscriber callbacks delivered post-lock) as negatives
so the rules stay precise.
"""
