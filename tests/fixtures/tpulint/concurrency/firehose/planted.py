"""The planted race: per-key hit counts mutated by `ingest` (caller
threads) and `drain` (the flusher thread) with no lock anywhere.

This is the cross-validation anchor for the guarded-field rule: the
static analysis must flag every unguarded access below, and the dynamic
stress harness (tests/test_tpulint_concurrency.py) must make the SAME
race lose real updates through the `gate` interleaving seam. The
`LockedStatsPlane` control is byte-for-byte the same shape plus one
lock — statically clean, dynamically loss-free — pinning both the rule
and the harness as race-sensitive rather than shape-sensitive.
"""
import threading


def _noop():
    return None


class RacyStatsPlane:
    """`gate` is an interleaving seam: the stress harness parks ingest
    threads between the read and the write-back to force the lost update
    deterministically; production-shaped code never replaces it."""

    def __init__(self):
        self.gate = _noop
        self._hits: dict = {}
        self._drained = 0
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(  # tpulint-expect: thread-escape
            target=self._flush_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join()

    def ingest(self, key):
        n = self._hits.get(key, 0)  # tpulint-expect: guarded-field
        self.gate()
        self._hits[key] = n + 1  # tpulint-expect: guarded-field

    def drain(self):
        total = 0
        for k in list(self._hits):  # tpulint-expect: guarded-field
            total += self._hits.pop(k, 0)  # tpulint-expect: guarded-field
        self._drained += total  # tpulint-expect: guarded-field
        return total

    def _flush_loop(self):
        while not self._stop:
            self.drain()


class LockedStatsPlane:
    """Control: identical shape, one lock over every access — clean."""

    def __init__(self):
        self.gate = _noop
        self._lock = threading.Lock()
        self._hits: dict = {}
        self._drained = 0
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join()

    def ingest(self, key):
        with self._lock:
            n = self._hits.get(key, 0)
            self.gate()
            self._hits[key] = n + 1

    def drain(self):
        with self._lock:
            total = 0
            for k in list(self._hits):
                total += self._hits.pop(k, 0)
            self._drained += total
            return total

    def _flush_loop(self):
        while not self._stop:
            self.drain()
