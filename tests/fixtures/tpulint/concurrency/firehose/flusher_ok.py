"""Negative: the double-buffered flusher hand-off, the firehose's shape.

The producer appends under the lock; the flusher swaps the whole buffer
out under the lock and walks the DETACHED batch outside it. Every access
to the shared list happens under `_lock`, so guarded-field inference
finds a dominating lock and stays quiet — the post-swap walk touches a
local the flusher exclusively owns.
"""
import threading


def _consume(item):
    return item


class DoubleBufferedFlusher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf: list = []
        self._flushed = 0
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join()

    def put(self, item):
        with self._lock:
            self._buf.append(item)

    def flushed(self) -> int:
        with self._lock:
            return self._flushed

    def _flush_loop(self):
        while not self._stop:
            with self._lock:
                batch, self._buf = self._buf, []
                self._flushed += len(batch)
            for item in batch:
                _consume(item)
