"""Suppression forms for the v3 rules: the same racy shapes as the
positive fixtures, absorbed by inline `tpulint: disable` comments (so
the suppression plumbing and the stale-suppression bookkeeping both see
the new rule ids in use)."""
import threading


class SuppressedPlane:
    def __init__(self):
        self._level = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(  # tpulint: disable=thread-escape -- fixture: suppression form for the escape audit
            target=self._spin, daemon=True)
        self._thread.start()

    def bump(self):
        self._level += 1  # tpulint: disable=guarded-field -- fixture: suppression form for the race rule

    def _spin(self):
        for _ in range(3):
            self.bump()
