"""Negative: subscriber callbacks delivered post-lock, the
`subscribe_verified` shape.

The batch and the subscriber list are both captured UNDER the lock; the
callbacks run OUTSIDE it on thread-local copies, so a subscriber may
re-enter the publisher without deadlocking and no shared field is
touched unguarded.
"""
import threading


class PostLockBroadcast:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs: list = []
        self._pending: list = []
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._deliver_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join()

    def subscribe(self, callback):
        with self._lock:
            self._subs.append(callback)

    def publish(self, item):
        with self._lock:
            self._pending.append(item)

    def _deliver_loop(self):
        while not self._stop:
            with self._lock:
                batch, self._pending = self._pending, []
                subs = list(self._subs)
            for callback in subs:
                for item in batch:
                    callback(item)
