"""seam-coverage counter fixture: seams that never tick the registry."""
_FIRED = []


def fire(site):  # tpulint-expect: seam-coverage
    _FIRED.append(site)
    return False
