"""donation-alias negative fixture: rebinding from the call's result (the
resident-engine pattern) and copies taken before donation are both clean."""
import jax
import numpy as np


def _step(cols, updates):
    return cols + updates


def epoch_loop(cols, updates):
    step = jax.jit(_step, donate_argnums=(0,))
    cols = step(cols, updates)
    return cols  # rebound from the call's result: owning, safe


def epoch_loop_with_copy(cols, updates):
    step = jax.jit(_step, donate_argnums=(0,))
    snapshot = np.asarray(cols)  # owning copy taken BEFORE donation
    cols = step(cols, updates)
    return cols, np.sum(snapshot)


def undonated(cols, updates):
    step = jax.jit(_step)
    out = step(cols, updates)
    return out, np.sum(cols)  # no donation: reads stay legal
