"""donation-alias positive fixture: reading a buffer already donated to jit
(the PR-1 incident shape: the memoized diff-base columns read after the
donating epoch dispatch)."""
import jax
import numpy as np


def _step(cols, updates):
    return cols + updates


def epoch_loop(cols, updates):
    step = jax.jit(_step, donate_argnums=(0,))
    new_cols = step(cols, updates)
    checksum = np.sum(cols)  # tpulint-expect: donation-alias
    return new_cols, checksum


def direct_call(cols, updates):
    out = jax.jit(_step, donate_argnums=(0,))(cols, updates)
    return out, cols.shape  # tpulint-expect: donation-alias
