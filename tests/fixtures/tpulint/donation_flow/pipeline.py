"""donation-flow fixture: the PR-5 post-donation-retry incident class.

Every flow here crosses a call boundary, which is precisely what the
same-scope donation-alias rule (PR 4) cannot see — the companion test
asserts donation-alias finds NOTHING in this file while donation-flow finds
each annotated line.
"""
import numpy as np

from donation_flow import kern
from donation_flow.retrylib import call_with_retry


def consume(cols, updates):
    return kern.step(cols, updates)


def epoch(cols, updates):
    out = consume(cols, updates)
    checksum = np.sum(cols)  # tpulint-expect: donation-flow
    return out, checksum


def epoch_rebound(cols, updates):
    cols = consume(cols, updates)
    return cols  # rebound from the call's result: owning, safe


def epoch_copied(cols, updates):
    snapshot = np.asarray(cols)  # owning copy BEFORE the donating call
    out = consume(cols, updates)
    return out, np.sum(snapshot)


def _do_epoch(cols, updates):
    return kern.step(cols, updates)


def dispatch_retry_lambda(cols, updates):
    return call_with_retry(lambda: kern.step(cols, updates))  # tpulint-expect: donation-flow


def dispatch_retry_ref(cols, updates):
    return call_with_retry(lambda: _do_epoch(cols, updates))  # tpulint-expect: donation-flow


def dispatch_retry_bare(fn_args):
    return call_with_retry(_do_epoch)  # tpulint-expect: donation-flow


def dispatch_retry_safe(updates):
    def attempt():
        fresh = np.zeros(8)
        return kern.step_clean(fresh, updates)

    return call_with_retry(attempt)
