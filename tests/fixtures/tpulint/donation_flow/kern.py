"""Module-level donating jit bindings — the cross-module donation source."""
import jax


def _step(cols, updates):
    return cols + updates


step = jax.jit(_step, donate_argnums=(0,))
step_clean = jax.jit(_step)
