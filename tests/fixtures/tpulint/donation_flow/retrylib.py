"""Minimal stand-in for robustness/retry.py's thunk-retry entry point."""


def call_with_retry(fn, attempts=2):
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except RuntimeError as e:  # pragma: no cover - fixture
            last = e
    raise last
