"""Unit tests for the minimal EVM harness (consensus_specs_tpu/evm/):
keccak-256 vectors, assembler round-trips, interpreter opcode semantics,
ABI encode/decode, and revert-reason decoding."""
import pytest

from consensus_specs_tpu.evm.abi import (
    ABIError,
    decode_abi,
    decode_revert_reason,
    encode_abi,
    encode_call,
    event_topic,
    function_selector,
)
from consensus_specs_tpu.evm.asm import Asm, AsmError
from consensus_specs_tpu.evm.interpreter import Code, EVM
from consensus_specs_tpu.evm.keccak import keccak256
from consensus_specs_tpu.evm.opcodes import BY_NAME, BY_VALUE


# -- keccak-256 --------------------------------------------------------------

KECCAK_VECTORS = [
    # Ethereum keccak-256 (0x01 padding), NOT NIST SHA3-256 (0x06 padding)
    (b"", "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"),
    (b"abc", "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"),
    (b"deposit(bytes,bytes,bytes,bytes32)",
     "228951186529ab0efc339ef5c94ccc3410bec3d3dbe1d4b869a6c6a2ba1de999"),
    (b"get_deposit_root()",
     "c5f2892f793909d60442da8894c2b8a8a4f96e729be0468feee3d23beba3c819"),
    (b"get_deposit_count()",
     "621fd130644659204038b345ef11da476ec8be3c04f005f988e95d80b3750dd3"),
    (b"supportsInterface(bytes4)",
     "01ffc9a7a5cef8baa21ed3c5c0d7e23accb804b619e9333b597f47a0d84076e2"),
    # one-past-rate block boundary (137 bytes forces a second permutation)
    (b"\xaa" * 137,
     "0f018f4a7d578f411e6f2a380295e8abff3ba307c4a497253af577d0fb3d7592"),
]


@pytest.mark.parametrize("data,digest", KECCAK_VECTORS,
                         ids=[f"len{len(d)}" for d, _ in KECCAK_VECTORS])
def test_keccak256_vectors(data, digest):
    assert keccak256(data).hex() == digest


def test_keccak256_incremental_lengths():
    # every padding branch around the 136-byte rate
    for n in (0, 1, 55, 56, 135, 136, 137, 271, 272, 273):
        out = keccak256(b"\x5c" * n)
        assert len(out) == 32
        # self-consistency: same input, same output
        assert out == keccak256(b"\x5c" * n)


# -- opcode table ------------------------------------------------------------

def test_opcode_table_bijective():
    assert len(BY_NAME) == len(BY_VALUE)
    for name, info in BY_NAME.items():
        assert BY_VALUE[info.value] is info
        assert info.name == name


# -- assembler ---------------------------------------------------------------

def test_asm_push_width_minimal():
    code = Asm().push(0).push(0xFF).push(0x100).assemble()
    assert code == bytes([0x60, 0x00, 0x60, 0xFF, 0x61, 0x01, 0x00])


def test_asm_label_jump_roundtrip():
    a = Asm()
    a.push_label("end").op("JUMP")
    a.op("INVALID")
    a.label("end")
    a.push(7).push(0).op("MSTORE").push(32).push(0).op("RETURN")
    result = EVM(Code(a.assemble())).execute()
    assert result.success
    assert int.from_bytes(result.output, "big") == 7


def test_asm_unknown_label():
    a = Asm()
    a.push_label("nowhere")
    with pytest.raises(AsmError):
        a.assemble()


# -- interpreter semantics ---------------------------------------------------

def run_ops(build, calldata=b"", value=0, storage=None):
    a = Asm()
    build(a)
    return EVM(Code(a.assemble()), storage=storage).execute(calldata, value)


def ret_top(a):
    """Store stack top at mem[0] and return the 32-byte word."""
    a.push(0).op("MSTORE").push(32).push(0).op("RETURN")


@pytest.mark.parametrize("op,a_val,b_val,expect", [
    ("ADD", 3, 4, 7),
    ("ADD", 2**256 - 1, 2, 1),                 # wraps mod 2**256
    ("SUB", 10, 3, 7),                          # first pop is minuend
    ("SUB", 3, 10, 2**256 - 7),
    ("MUL", 2**128, 2**128, 0),
    ("DIV", 7, 2, 3),
    ("DIV", 7, 0, 0),                           # EVM: div by zero is zero
    ("MOD", 7, 3, 1),
    ("MOD", 7, 0, 0),
    ("LT", 3, 4, 1),
    ("LT", 4, 3, 0),
    ("GT", 4, 3, 1),
    ("EQ", 5, 5, 1),
    ("AND", 0b1100, 0b1010, 0b1000),
    ("OR", 0b1100, 0b1010, 0b1110),
    ("XOR", 0b1100, 0b1010, 0b0110),
    ("SHL", 4, 1, 16),                          # first pop is shift amount
    ("SHR", 4, 32, 2),
    ("SHR", 300, 2**255, 0),                    # oversized shift drains
])
def test_binary_ops(op, a_val, b_val, expect):
    # push b first so a is on top (a is the FIRST pop = mu_s[0])
    res = run_ops(lambda asm: (asm.push(b_val), asm.push(a_val), asm.op(op),
                               ret_top(asm)))
    assert res.success, res.error
    assert int.from_bytes(res.output, "big") == expect


def test_iszero_not():
    res = run_ops(lambda a: (a.push(0), a.op("ISZERO"), ret_top(a)))
    assert int.from_bytes(res.output, "big") == 1
    res = run_ops(lambda a: (a.push(0), a.op("NOT"), ret_top(a)))
    assert int.from_bytes(res.output, "big") == 2**256 - 1


def test_memory_mstore8_msize():
    def build(a):
        a.push(0xAB).push(5).op("MSTORE8")   # one byte at offset 5
        a.op("MSIZE")                         # memory expanded to 32
        ret_top(a)
    res = run_ops(build)
    assert int.from_bytes(res.output, "big") == 32


def test_calldata_ops():
    def build(a):
        a.op("CALLDATASIZE")
        a.push(2).op("CALLDATALOAD")  # word at offset 2, zero-padded tail
        a.op("ADD")
        ret_top(a)
    res = run_ops(build, calldata=b"\x00\x00\xff" + b"\x00" * 31)
    # CALLDATASIZE=34; CALLDATALOAD(2) = 0xff000...0 as full word
    assert int.from_bytes(res.output, "big") == 34 + (0xFF << 248)


def test_storage_persistence_and_delete():
    storage = {}
    res = run_ops(lambda a: (a.push(42), a.push(9), a.op("SSTORE"), a.op("STOP")),
                  storage=storage)
    assert res.success and storage == {9: 42}
    run_ops(lambda a: (a.push(0), a.push(9), a.op("SSTORE"), a.op("STOP")),
            storage=storage)
    assert storage == {}  # zero-writes delete the key


def test_revert_and_error_string():
    # REVERT with an Error(string) payload built via the ABI helper
    payload = bytes.fromhex("08c379a0") + encode_abi(["string"], ["nope"])
    a = Asm()
    for i, byte in enumerate(payload):
        a.push(byte).push(i).op("MSTORE8")
    a.push(len(payload)).push(0).op("REVERT")
    res = EVM(Code(a.assemble())).execute()
    assert not res.success and res.reverted
    assert decode_revert_reason(res.output) == "nope"


def test_stack_underflow_is_exceptional():
    res = run_ops(lambda a: a.op("ADD"))
    assert not res.success and not res.reverted
    assert "underflow" in res.error


def test_bad_jump_is_exceptional():
    res = run_ops(lambda a: (a.push(3), a.op("JUMP"), a.op("STOP")))
    assert not res.success and "jump destination" in res.error


def test_invalid_opcode_is_exceptional():
    res = EVM(Code(b"\xfe")).execute()
    assert not res.success and not res.reverted


def test_step_limit():
    # infinite loop: JUMPDEST; PUSH 0; JUMP
    code = Code(bytes([0x5B, 0x60, 0x00, 0x56]))
    res = EVM(code, step_limit=1000).execute()
    assert not res.success and "step budget" in res.error


def test_sha256_precompile_staticcall():
    from hashlib import sha256
    def build(a):
        a.push(0xAB).push(31).op("MSTORE8")  # mem[31] = 0xAB
        # STATICCALL(gas, 0x02, in=0, insize=32, out=0x20, outsize=32)
        a.push(32).push(0x20).push(32).push(0).push(2).op("GAS").op("STATICCALL")
        a.op("POP")
        a.push(32).push(0x20).op("RETURN")
    res = run_ops(build)
    assert res.success
    assert res.output == sha256(b"\x00" * 31 + b"\xab").digest()


def test_log_capture():
    def build(a):
        a.push(0xDEAD).push(0).op("MSTORE")
        a.push(0x1234).push(32).push(0).op("LOG1")
        a.op("STOP")
    res = run_ops(build)
    assert res.success and len(res.logs) == 1
    assert res.logs[0].topics == [0x1234]
    assert int.from_bytes(res.logs[0].data, "big") == 0xDEAD


# -- ABI ---------------------------------------------------------------------

def test_selector_and_topic():
    assert function_selector("deposit(bytes,bytes,bytes,bytes32)").hex() == "22895118"
    assert function_selector("get_deposit_root()").hex() == "c5f2892f"
    assert function_selector("get_deposit_count()").hex() == "621fd130"
    assert function_selector("supportsInterface(bytes4)").hex() == "01ffc9a7"
    assert event_topic("DepositEvent(bytes,bytes,bytes,bytes,bytes)").hex() == (
        "649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5")


def test_abi_roundtrip_dynamic_bytes():
    types = ["bytes", "bytes", "bytes", "bytes32"]
    values = [b"\x01" * 48, b"\x02" * 32, b"\x03" * 96, b"\x04" * 32]
    blob = encode_abi(types, values)
    assert decode_abi(types, blob) == values
    # head is 4 words; dynamic tails are length-prefixed and 32-padded
    assert len(blob) == 32 * 4 + (32 + 64) + (32 + 32) + (32 + 96)


def test_abi_roundtrip_uints():
    types = ["uint256", "uint64", "bool", "bytes4"]
    values = [2**255 + 1, 2**64 - 1, True, b"\x85\x64\x09\x07"]
    assert decode_abi(types, encode_abi(types, values)) == values


def test_encode_call_prefixes_selector():
    blob = encode_call("supportsInterface(bytes4)", [b"\x01\xff\xc9\xa7"])
    assert blob[:4].hex() == "01ffc9a7" and len(blob) == 4 + 32


def test_decode_abi_bounds_checked():
    blob = encode_abi(["bytes"], [b"\xaa" * 40])
    with pytest.raises(ABIError):
        decode_abi(["bytes"], blob[:96])  # tail shorter than its length word
    with pytest.raises(ABIError):
        decode_abi(["uint256", "uint256"], b"\x00" * 32)  # truncated head


def test_decode_revert_reason_shapes():
    assert decode_revert_reason(b"") is None
    assert decode_revert_reason(b"\x00" * 3) is None
    err = bytes.fromhex("08c379a0") + encode_abi(["string"], ["boom"])
    assert decode_revert_reason(err) == "boom"
    panic = bytes.fromhex("4e487b71") + encode_abi(["uint256"], [0x11])
    assert decode_revert_reason(panic) == "Panic(0x11)"
