"""Bellatrix fork choice: merge-transition block validation in on_block.

Reference parity: test/bellatrix/fork_choice/test_on_merge_block.py and
specs/bellatrix/fork-choice.md (validate_merge_block, terminal-PoW checks,
TERMINAL_BLOCK_HASH override) — exercised through a mocked PoW chain
(testlib/pow_block.py).
"""
import pytest

from consensus_specs_tpu.compiler import build_spec, get_spec
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.testlib.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.testlib.fork_choice import get_genesis_forkchoice_store_and_block
from consensus_specs_tpu.testlib.genesis import create_valid_beacon_state
from consensus_specs_tpu.testlib.pow_block import pow_chain, prepare_terminal_pow_chain


@pytest.fixture(scope="module")
def spec():
    return get_spec("bellatrix", "minimal")


@pytest.fixture(autouse=True)
def disable_bls():
    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


def _merge_block_through_store(spec, terminal_hash):
    """Genesis (pre-merge) store + a signed transition block whose payload
    builds on `terminal_hash`."""
    state = create_valid_beacon_state(spec, 64)
    # rewind the state to a pre-merge execution header
    state.latest_execution_payload_header = spec.ExecutionPayloadHeader()
    assert not spec.is_merge_transition_complete(state)
    store, _ = get_genesis_forkchoice_store_and_block(spec, state)
    spec.on_tick(store, int(store.time) + int(spec.config.SECONDS_PER_SLOT))

    block = build_empty_block_for_next_slot(spec, state)
    payload = spec.ExecutionPayload()
    payload.parent_hash = spec.Hash32(terminal_hash)
    payload.random = spec.get_randao_mix(state, spec.get_current_epoch(state))
    payload.timestamp = spec.compute_timestamp_at_slot(state, block.slot)
    payload.block_hash = spec.Hash32(b"\xcc" * 32)
    payload.block_number = 1
    block.body.execution_payload = payload
    assert spec.is_merge_transition_block(state, block.body)
    # transition a scratch copy to fill state_root + sign (the store's
    # on_block will redo the real transition itself)
    signed = state_transition_and_sign_block(spec, state.copy(), block)
    return store, signed


def test_on_merge_block_valid_terminal_ancestry(spec):
    parent, terminal = prepare_terminal_pow_chain(spec)
    store, signed = _merge_block_through_store(spec, terminal.block_hash)
    with pow_chain(spec, [parent, terminal]):
        spec.on_block(store, signed)
    assert spec.hash_tree_root(signed.message) in store.blocks


def test_on_merge_block_unknown_pow_parent_rejected(spec):
    _, terminal = prepare_terminal_pow_chain(spec)
    store, signed = _merge_block_through_store(spec, terminal.block_hash)
    # terminal's own parent missing from the PoW chain view
    with pow_chain(spec, [terminal]):
        with pytest.raises(AssertionError):
            spec.on_block(store, signed)


def test_on_merge_block_pre_ttd_parent_rejected(spec):
    parent, terminal = prepare_terminal_pow_chain(spec)
    store, signed = _merge_block_through_store(spec, parent.block_hash)
    # payload builds on a PoW block that has NOT reached terminal difficulty
    grandparent = spec.PowBlock(
        block_hash=spec.Hash32(b"\x03" * 32),
        parent_hash=spec.Hash32(b"\x04" * 32),
        total_difficulty=spec.uint256(0),
    )
    parent = parent.copy()
    parent.parent_hash = grandparent.block_hash
    with pow_chain(spec, [grandparent, parent, terminal]):
        with pytest.raises(AssertionError):
            spec.on_block(store, signed)


def test_terminal_block_hash_override(spec):
    """With TERMINAL_BLOCK_HASH set, ancestry checks are replaced by an
    exact parent-hash + activation-epoch gate."""
    override = b"\x77" * 32
    ospec = build_spec(
        "bellatrix",
        "minimal",
        config_overrides={
            "TERMINAL_BLOCK_HASH": "0x" + override.hex(),
            "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0,
        },
    )
    store, signed = _merge_block_through_store(ospec, override)
    # no PoW chain mock needed: the override path never calls get_pow_block
    ospec.on_block(store, signed)
    assert ospec.hash_tree_root(signed.message) in store.blocks
    # wrong parent hash must be rejected
    store2, signed2 = _merge_block_through_store(ospec, b"\x78" * 32)
    with pytest.raises(AssertionError):
        ospec.on_block(store2, signed2)
